"""Sequence-parallel SERVING (round 5, VERDICT r04 #3): --mesh pp=N,sp=M
shards a long prompt's prefill across sp ranks with ring attention, gathers
the K/V into the decode cache, and decodes on the standard pass —
token-exact with the unsharded engine. The reference's prefill is a
full-sequence forward on one machine with O(seq^2) eager attention
(qwen3_server_module.py:67-89); SURVEY §7 names sequence sharding the
idiomatic TPU extension axis."""

import asyncio

import jax
import numpy as np
import pytest

from inferd_tpu.config import TINY, SamplingConfig
from inferd_tpu.core.generate import Engine, bucket_len
from inferd_tpu.models import qwen3
from inferd_tpu.parallel import mesh as meshlib
from inferd_tpu.parallel.infer import PipelinedEngine

GREEDY = SamplingConfig(temperature=0.0)



from conftest import requires_native_shard_map

pytestmark = requires_native_shard_map

@pytest.fixture(scope="module")
def target():
    return TINY, qwen3.init_params(TINY, jax.random.PRNGKey(0))


def _long_prompt(n, seed=0):
    rng = np.random.RandomState(seed)
    return [int(t) for t in rng.randint(3, TINY.vocab_size - 1, size=n)]


def _decode(eng, slot, first_logits, pos, steps):
    toks = [int(np.argmax(first_logits[0]))]
    while len(toks) < steps:
        lg = eng.step_slot(
            slot, np.asarray([[toks[-1]]], np.int32), 1, False, start_pos=pos
        )
        pos += 1
        toks.append(int(np.argmax(lg[0])))
    return toks


def test_pp2_sp2_long_prefill_token_exact(target, devices8):
    """70-token prompt (non-power-of-two, > one sp block) prefis sharded
    over sp; prefill logits match the solo engine bit-for-bit-ish and the
    decoded stream is token-exact."""
    cfg, params = target
    mesh = meshlib.make_mesh(meshlib.MeshPlan(pp=2, sp=2), devices8[:4])
    eng = PipelinedEngine(cfg, params, mesh, num_microbatches=2, batch=1,
                          max_len=128)
    assert eng.sp_active
    prompt = _long_prompt(70)
    solo = Engine(cfg, params, max_len=128, sampling_cfg=GREEDY)
    want = solo.generate(prompt, max_new_tokens=8)

    logits = eng.sp_prefill_slot(0, np.asarray([prompt], np.int32), len(prompt))
    # prefill logits equal the unsharded forward's last-token logits
    toks128 = np.zeros((1, bucket_len(len(prompt))), np.int32)
    toks128[0, : len(prompt)] = prompt
    ref_logits, _, _ = qwen3.forward(params, cfg, jax.numpy.asarray(toks128))
    np.testing.assert_allclose(
        np.asarray(logits[0]),
        np.asarray(ref_logits[0, len(prompt) - 1], np.float32),
        rtol=2e-4, atol=2e-4,
    )
    got = _decode(eng, 0, logits, len(prompt), 8)
    assert got == want


def test_pp2_sp2_tp2_composes(target, devices8):
    """sp composes with tp inside the same mesh (pp2 x sp2 x tp2 = 8
    virtual devices): still token-exact."""
    cfg, params = target
    mesh = meshlib.make_mesh(
        meshlib.MeshPlan(pp=2, sp=2, tp=2), devices8[:8]
    )
    eng = PipelinedEngine(cfg, params, mesh, num_microbatches=2, batch=1,
                          max_len=128)
    prompt = _long_prompt(40, seed=3)
    solo = Engine(cfg, params, max_len=128, sampling_cfg=GREEDY)
    want = solo.generate(prompt, max_new_tokens=6)
    logits = eng.sp_prefill_slot(0, np.asarray([prompt], np.int32), len(prompt))
    got = _decode(eng, 0, logits, len(prompt), 6)
    assert got == want


def test_sp_per_chip_memory_is_sharded(target, devices8):
    """MEASURED per-chip bytes: the prompt block each chip holds is S/sp,
    and the adopted KV cache holds L/pp layers per chip (replicated over
    sp) — the memory contract behind the sp win (each chip's prefill
    activations scale with its block, not the full sequence)."""
    cfg, params = target
    sp, pp = 2, 2
    mesh = meshlib.make_mesh(meshlib.MeshPlan(pp=pp, sp=sp), devices8[:4])
    eng = PipelinedEngine(cfg, params, mesh, num_microbatches=2, batch=1,
                          max_len=128)
    prompt = _long_prompt(64, seed=4)
    eng.sp_prefill_slot(0, np.asarray([prompt], np.int32), len(prompt))
    # KV cache: layer axis sharded over pp, replicated over sp
    shard = eng.caches.k.addressable_shards[0]
    assert shard.data.shape[0] == cfg.num_layers // pp
    total_bytes = eng.caches.k.size * eng.caches.k.dtype.itemsize
    per_chip = shard.data.size * shard.data.dtype.itemsize
    assert per_chip == total_bytes // pp  # sp replicates, pp shards
    # the sp-sharded prompt: each chip's block is S/sp tokens
    from jax.sharding import NamedSharding, PartitionSpec as P

    x = jax.device_put(
        np.zeros((1, 64), np.int32), NamedSharding(mesh, P(None, "sp"))
    )
    assert x.addressable_shards[0].data.shape == (1, 64 // sp)


def test_sp_with_quantized_params(target, devices8):
    """int8-quantized params serve through the sp prefill (the tp-path
    projections contract via qdot)."""
    from inferd_tpu.ops import quant

    cfg, params = target
    qparams = quant.apply_quant_mode(
        "int8", params, tie_word_embeddings=cfg.tie_word_embeddings
    )
    mesh = meshlib.make_mesh(meshlib.MeshPlan(pp=2, sp=2), devices8[:4])
    eng = PipelinedEngine(cfg, qparams, mesh, num_microbatches=2, batch=1,
                          max_len=128)
    prompt = _long_prompt(40, seed=5)
    want = Engine(cfg, qparams, max_len=128, sampling_cfg=GREEDY).generate(
        prompt, max_new_tokens=6
    )
    logits = eng.sp_prefill_slot(0, np.asarray([prompt], np.int32), len(prompt))
    got = _decode(eng, 0, logits, len(prompt), 6)
    assert got == want


@pytest.mark.asyncio
async def test_mesh_node_sp_serving_e2e(target, devices8):
    """A --mesh pp=2,sp=2 node serves a long-prompt generation through the
    stock SwarmClient, token-exact with the solo engine (the sp prefill
    rides /forward's first chunk transparently)."""
    from inferd_tpu.client.swarm_client import SwarmClient
    from inferd_tpu.control.dht import SwarmDHT
    from inferd_tpu.parallel.mesh import MeshPlan
    from inferd_tpu.parallel.stages import Manifest, split_and_save
    from inferd_tpu.runtime.node import Node, NodeInfo

    cfg, params = target
    base = 18950
    import tempfile

    with tempfile.TemporaryDirectory() as parts:
        split_and_save(params, cfg, Manifest.even_split("tiny", 1), parts)
        info = NodeInfo(
            name="spn0", host="127.0.0.1", port=base, stage=0,
            num_stages=1, model_name="tiny",
        )
        dht = SwarmDHT(
            info.node_id, base + 100, bootstrap=[], host="127.0.0.1",
            gossip_period_s=0.05, ttl_s=5.0,
        )
        node = Node(
            info, cfg, parts, dht, backend="qwen3", max_len=128,
            rebalance_period_s=600.0, mesh_plan=MeshPlan(pp=2, sp=2),
            mesh_slots=2,
        )
        await node.start()
        try:
            assert node.executor.engine.sp_active
            prompt = _long_prompt(70, seed=6)
            want = Engine(
                cfg, params, max_len=128, sampling_cfg=GREEDY
            ).generate(prompt, max_new_tokens=8)
            async with SwarmClient(
                [("127.0.0.1", base)], sampling=GREEDY
            ) as c:
                got = await c.generate_ids(prompt, max_new_tokens=8)
            assert got == want
        finally:
            await node.stop()
