# jaxlint: file-disable=J003 -- test code: loops here sync per-iteration to ASSERT on values; they are verification loops, not serving hot paths
"""Mesh-parallel correctness: ring attention, TP/EP layer parity vs the
single-device model, and the full pipelined train step (all five axes)."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from inferd_tpu.parallel import compat
from inferd_tpu.config import TINY, TINY_GEMMA2, TINY_GPT_OSS, TINY_MOE, TINY_QWEN2
from inferd_tpu.models import qwen3
from inferd_tpu.parallel import mesh as meshlib
from inferd_tpu.parallel.ring import ring_gqa_attention
from inferd_tpu.parallel.tp import sharded_forward_layers
from inferd_tpu.parallel.train import make_train_step



from conftest import requires_native_shard_map

pytestmark = requires_native_shard_map

def _mesh(dp=1, pp=1, sp=1, tp=1, ep=1):
    plan = meshlib.MeshPlan(dp=dp, pp=pp, sp=sp, tp=tp, ep=ep)
    return plan, meshlib.make_mesh(plan)


def test_ring_attention_matches_full():
    b, s, nq, nkv, d = 2, 16, 4, 2, 8
    key = jax.random.PRNGKey(0)
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (b, s, nq, d), jnp.float32)
    k = jax.random.normal(kk, (b, s, nkv, d), jnp.float32)
    v = jax.random.normal(kv, (b, s, nkv, d), jnp.float32)
    positions = jnp.broadcast_to(jnp.arange(s), (b, s))

    ref = qwen3.gqa_attention(q, k, v, positions, jnp.int32(s), kv_positions=positions)

    plan, mesh = _mesh(sp=4)

    def f(q, k, v, pos):
        return ring_gqa_attention(q, k, v, pos, pos, "sp")

    out = jax.jit(
        compat.shard_map(
            f,
            mesh=mesh,
            in_specs=(P(None, "sp"), P(None, "sp"), P(None, "sp"), P(None, "sp")),
            out_specs=P(None, "sp"),
            check_vma=False,
        )
    )(q, k, v, positions)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_ring_attention_window_softcap_scale_matches_full():
    """Ring attention with the Gemma-2 recipe (sliding window + logit
    softcap + query_pre_attn_scalar scale) == full-sequence gqa_attention —
    the round-2 sp-axis capability cliff (tp.py raised NotImplementedError
    for these configs), lifted."""
    b, s, nq, nkv, d = 2, 16, 4, 2, 8
    kq, kk, kv = jax.random.split(jax.random.PRNGKey(5), 3)
    q = jax.random.normal(kq, (b, s, nq, d), jnp.float32)
    k = jax.random.normal(kk, (b, s, nkv, d), jnp.float32)
    v = jax.random.normal(kv, (b, s, nkv, d), jnp.float32)
    positions = jnp.broadcast_to(jnp.arange(s), (b, s))
    scale, softcap, window = 1.0 / 5.6, 30.0, 6

    ref = qwen3.gqa_attention(
        q, k, v, positions, jnp.int32(s), kv_positions=positions,
        scale=scale, softcap=softcap, window=jnp.int32(window),
    )
    plan, mesh = _mesh(sp=4)

    def f(q, k, v, pos):
        return ring_gqa_attention(
            q, k, v, pos, pos, "sp",
            scale=scale, softcap=softcap, window=jnp.int32(window),
        )

    out = jax.jit(
        compat.shard_map(
            f, mesh=mesh,
            in_specs=(P(None, "sp"), P(None, "sp"), P(None, "sp"), P(None, "sp")),
            out_specs=P(None, "sp"),
            check_vma=False,
        )
    )(q, k, v, positions)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_ring_attention_sinks_matches_full():
    """GPT-OSS attention sinks join the ring's online softmax exactly once,
    at finalize — parity with the closed-form full-sequence path."""
    b, s, nq, nkv, d = 1, 16, 4, 2, 8
    kq, kk, kv, ks = jax.random.split(jax.random.PRNGKey(6), 4)
    q = jax.random.normal(kq, (b, s, nq, d), jnp.float32)
    k = jax.random.normal(kk, (b, s, nkv, d), jnp.float32)
    v = jax.random.normal(kv, (b, s, nkv, d), jnp.float32)
    # large positive sink on one head makes the denominator term decisive
    sinks = jax.random.normal(ks, (nq,), jnp.float32).at[1].set(4.0)
    positions = jnp.broadcast_to(jnp.arange(s), (b, s))

    ref = qwen3.gqa_attention(
        q, k, v, positions, jnp.int32(s), kv_positions=positions, sinks=sinks,
    )
    plan, mesh = _mesh(sp=4)

    def f(q, k, v, pos):
        return ring_gqa_attention(q, k, v, pos, pos, "sp", sinks=sinks)

    out = jax.jit(
        compat.shard_map(
            f, mesh=mesh,
            in_specs=(P(None, "sp"), P(None, "sp"), P(None, "sp"), P(None, "sp")),
            out_specs=P(None, "sp"),
            check_vma=False,
        )
    )(q, k, v, positions)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize(
    "cfg", [TINY, TINY_MOE, TINY_GEMMA2, TINY_GPT_OSS],
    ids=["dense", "moe", "gemma2", "gptoss"],
)
def test_sharded_layers_match_single_device(cfg):
    b, s = 2, 16
    key = jax.random.PRNGKey(1)
    layers = qwen3.init_layer_params(cfg, key)
    hidden = jax.random.normal(jax.random.PRNGKey(2), (b, s, cfg.hidden_size), jnp.float32)
    positions = jnp.broadcast_to(jnp.arange(s), (b, s))

    ref, _, _ = qwen3.forward_layers(layers, cfg, hidden, positions)

    plan, mesh = _mesh(sp=2, tp=2, ep=2 if cfg.is_moe else 1)
    lspecs = meshlib.layer_param_specs(cfg)

    def f(layers_local, h, pos):
        return sharded_forward_layers(layers_local, cfg, h, pos, "tp", "sp")

    out = jax.jit(
        compat.shard_map(
            f,
            mesh=mesh,
            in_specs=(lspecs, P(None, "sp", None), P(None, "sp")),
            out_specs=P(None, "sp", None),
            check_vma=False,
        )
    )(layers, hidden, positions)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-4, rtol=1e-4)


@pytest.mark.parametrize(
    "cfg,plan_kw",
    [
        (TINY, dict(dp=2, pp=2, tp=2)),
        (TINY_MOE, dict(pp=2, sp=2, tp=2)),
    ],
    ids=["dense-dp-pp-tp", "moe-pp-sp-tp"],
)
def test_train_step_loss_decreases(cfg, plan_kw):
    plan, mesh = _mesh(**plan_kw)
    meshlib.check_divisibility(cfg, plan)
    step = make_train_step(cfg, mesh, plan, learning_rate=5e-2)

    params = qwen3.init_params(cfg, jax.random.PRNGKey(0))
    mb, batch, seq = 2, 2 * plan.dp, 8 * plan.sp
    data = jax.random.randint(
        jax.random.PRNGKey(3), (mb, batch, seq + 1), 0, cfg.vocab_size, dtype=jnp.int32
    )
    tokens, targets = data[..., :-1], data[..., 1:]

    losses = []
    for _ in range(4):
        params, loss = step(params, tokens, targets)
        losses.append(float(loss))
    assert all(np.isfinite(losses)), losses
    assert losses[-1] < losses[0], losses


@pytest.mark.parametrize(
    "cfg,plan_kw",
    [
        (TINY, dict(dp=2)),
        (TINY, dict(pp=2)),
        (TINY, dict(sp=2)),
        (TINY, dict(tp=2)),
        (TINY_MOE, dict(ep=2)),
        (TINY_QWEN2, dict(tp=2)),
        (TINY, dict(dp=2, pp=2, tp=2)),
        (TINY_MOE, dict(pp=2, sp=2, ep=2)),
        # the round-2 sp-axis capability cliff, lifted: sliding windows,
        # softcaps, sinks, and non-head_dim scales train with sp > 1
        (TINY_GEMMA2, dict(sp=2, tp=2)),
        (TINY_GPT_OSS, dict(sp=2, ep=2)),
    ],
    ids=["dp2", "pp2", "sp2", "tp2", "ep2", "qwen2-tp2", "dense-8dev",
         "moe-8dev", "gemma2-sp2tp2", "gptoss-sp2ep2"],
)
def test_train_step_matches_single_device(cfg, plan_kw):
    """One train step on a multi-device plan must produce the SAME updated
    params as the single-device plan — catches gradient mis-scaling (e.g.
    effective lr silently growing with device count) and wrong grad sync."""
    params = qwen3.init_params(cfg, jax.random.PRNGKey(0))
    mb, batch, seq = 2, 4, 16
    data = jax.random.randint(
        jax.random.PRNGKey(5), (mb, batch, seq + 1), 0, cfg.vocab_size, dtype=jnp.int32
    )
    tokens, targets = data[..., :-1], data[..., 1:]

    plan1, mesh1 = _mesh()
    ref_params, ref_loss = make_train_step(cfg, mesh1, plan1, learning_rate=1e-2)(
        params, tokens, targets
    )

    plan, mesh = _mesh(**plan_kw)
    got_params, got_loss = make_train_step(cfg, mesh, plan, learning_rate=1e-2)(
        params, tokens, targets
    )

    np.testing.assert_allclose(float(got_loss), float(ref_loss), rtol=1e-5)
    flat_ref = jax.tree_util.tree_leaves_with_path(ref_params)
    flat_got = dict(jax.tree_util.tree_leaves_with_path(got_params))
    for path, ref_leaf in flat_ref:
        got_leaf = flat_got[path]
        np.testing.assert_allclose(
            np.asarray(got_leaf, np.float32),
            np.asarray(ref_leaf, np.float32),
            atol=2e-5,
            rtol=2e-5,
            err_msg=f"param {jax.tree_util.keystr(path)} diverged under plan {plan_kw}",
        )


def test_load_balance_loss_matches_hf():
    """tp.load_balance_loss == transformers' load_balancing_loss_func on the
    same router logits (the Switch-style aux the MoE training step adds)."""
    torch = pytest.importorskip("torch")
    from transformers.models.mixtral.modeling_mixtral import (
        load_balancing_loss_func,
    )

    from inferd_tpu.parallel.tp import load_balance_loss

    E, K, T = 8, 2, 64
    logits = np.random.RandomState(0).normal(size=(T, E)).astype(np.float32)
    want = float(
        load_balancing_loss_func((torch.from_numpy(logits),), E, K)
    )
    probs = jax.nn.softmax(jnp.asarray(logits), axis=-1)
    _, topi = jax.lax.top_k(probs, K)
    got = float(load_balance_loss(probs, topi, E))
    assert got == pytest.approx(want, rel=1e-5)


@pytest.mark.parametrize(
    "plan_kw",
    [dict(ep=2), dict(pp=2, ep=2), dict(pp=2, sp=2, tp=2), dict(dp=2, ep=2)],
    ids=["ep2", "pp2-ep2", "pp2-sp2-tp2", "dp2-ep2"],
)
def test_moe_aux_loss_matches_single_device(plan_kw):
    """The load-balancing aux term must be invariant to the mesh plan: same
    loss and same updated params as the 1-device plan (pins the 1/(ep*tp)
    per-rank scaling against the router's grad-sync psum, the GPipe
    bubble-tick masking, and the report-side psum)."""
    cfg = TINY_MOE
    params = qwen3.init_params(cfg, jax.random.PRNGKey(1))
    mb, batch, seq = 2, 4, 16
    data = jax.random.randint(
        jax.random.PRNGKey(6), (mb, batch, seq + 1), 0, cfg.vocab_size, dtype=jnp.int32
    )
    tokens, targets = data[..., :-1], data[..., 1:]
    kw = dict(learning_rate=1e-2, moe_aux_coef=0.01)

    plan1, mesh1 = _mesh()
    ref_params, ref_loss = make_train_step(cfg, mesh1, plan1, **kw)(
        params, tokens, targets
    )
    # the aux term must actually move the objective
    _, base_loss = make_train_step(cfg, mesh1, plan1, learning_rate=1e-2)(
        params, tokens, targets
    )
    assert float(ref_loss) != pytest.approx(float(base_loss), rel=1e-6)

    plan, mesh = _mesh(**plan_kw)
    got_params, got_loss = make_train_step(cfg, mesh, plan, **kw)(
        params, tokens, targets
    )
    np.testing.assert_allclose(float(got_loss), float(ref_loss), rtol=1e-5)
    flat_ref = jax.tree_util.tree_leaves_with_path(ref_params)
    flat_got = dict(jax.tree_util.tree_leaves_with_path(got_params))
    for path, ref_leaf in flat_ref:
        np.testing.assert_allclose(
            np.asarray(flat_got[path], np.float32),
            np.asarray(ref_leaf, np.float32),
            atol=2e-5, rtol=2e-5,
            err_msg=f"param {jax.tree_util.keystr(path)} diverged under {plan_kw}",
        )


def test_pipeline_forward_matches_single_device():
    """The GPipe schedule must compute exactly the plain stacked forward."""
    cfg = TINY
    plan, mesh = _mesh(pp=2)
    params = qwen3.init_params(cfg, jax.random.PRNGKey(0))
    mb, b, s = 3, 2, 8
    tokens = jax.random.randint(jax.random.PRNGKey(4), (mb, b, s), 0, cfg.vocab_size, dtype=jnp.int32)

    # reference: plain forward per microbatch
    ref = []
    for i in range(mb):
        logits, _, _ = qwen3.forward(params, cfg, tokens[i])
        ref.append(logits)
    ref = jnp.stack(ref)

    from inferd_tpu.parallel.train import _pipeline_forward, _unembed_local

    pspecs = meshlib.model_param_specs(cfg, layer_axis="pp")

    def f(p, toks):
        positions = jnp.broadcast_to(jnp.arange(s), (b, s))
        out = _pipeline_forward(p, cfg, toks, positions, None)
        out = jax.lax.psum(out, "pp")  # valid only on last rank; others zero
        return _unembed_local(p, cfg, out.reshape(mb * b, s, -1)).reshape(mb, b, s, -1)

    got = jax.jit(
        compat.shard_map(
            f, mesh=mesh, in_specs=(pspecs, P()), out_specs=P(), check_vma=False
        )
    )(params, tokens)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=1e-4, rtol=1e-4)


def test_grad_clip_and_schedule_match_single_device(devices8):
    """grad_clip_norm + warmup/cosine schedule over a sharded mesh must
    equal the same update computed on one device (the global-norm psum per
    shard axis has to reconstruct the exact full-tree norm)."""
    from inferd_tpu.parallel.train import init_train_state, make_train_step

    cfg = TINY
    key = jax.random.PRNGKey(0)
    params = qwen3.init_params(cfg, key)
    mb, b, s = 2, 2, 16
    toks = jax.random.randint(jax.random.PRNGKey(1), (mb, b, s), 0, cfg.vocab_size, jnp.int32)
    tgts = jax.random.randint(jax.random.PRNGKey(2), (mb, b, s), 0, cfg.vocab_size, jnp.int32)

    kw = dict(
        learning_rate=3e-2, optimizer="adam",
        grad_clip_norm=0.5, warmup_steps=3, decay_steps=10,
    )
    plan1 = meshlib.MeshPlan()
    mesh1 = meshlib.make_mesh(plan1, jax.devices()[:1])
    step1 = make_train_step(cfg, mesh1, plan1, **kw)
    st1 = step1.init_state(meshlib.shard_params(params, cfg, mesh1))
    plan8 = meshlib.MeshPlan(dp=2, pp=2, tp=2)
    mesh8 = meshlib.make_mesh(plan8, devices8)
    step8 = make_train_step(cfg, mesh8, plan8, **kw)
    st8 = step8.init_state(
        meshlib.shard_params(params, cfg, mesh8, layer_axis="pp")
    )

    for i in range(3):  # cross warmup into decay; clip engages on step 1
        st1, loss1 = step1(st1, toks, tgts)
        st8, loss8 = step8(st8, toks, tgts)
        np.testing.assert_allclose(float(loss1), float(loss8), rtol=2e-4, atol=2e-4)
    for a, b_ in zip(jax.tree.leaves(st1.params), jax.tree.leaves(st8.params)):
        np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b_, np.float32), rtol=3e-3, atol=3e-3
        )
