# jaxlint: file-disable=J003 -- test code: loops here sync per-iteration to ASSERT on values; they are verification loops, not serving hot paths
"""Pallas flash-attention kernel parity vs the XLA reference path.

Runs the kernel in the Pallas interpreter on the CPU mesh (conftest pins
JAX_PLATFORMS=cpu), asserting exactness properties the TPU kernel relies on.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from inferd_tpu.config import TINY
from inferd_tpu.models import qwen3
from inferd_tpu.models.qwen3 import gqa_attention
from inferd_tpu.ops.attention import flash_gqa


@pytest.fixture(scope="module")
def tiny_params():
    return qwen3.init_params(TINY, jax.random.PRNGKey(0))


def _rand_qkv(key, b, s, t, nq, nkv, d, dtype=jnp.float32):
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (b, s, nq, d), dtype)
    k = jax.random.normal(kk, (b, t, nkv, d), dtype)
    v = jax.random.normal(kv, (b, t, nkv, d), dtype)
    return q, k, v


@pytest.mark.parametrize(
    "b,s,t,nq,nkv,d,q_start,kv_len",
    [
        (1, 16, 16, 4, 2, 16, 0, 16),  # prefill from scratch
        (2, 8, 64, 4, 4, 32, 24, 32),  # chunk mid-sequence over a big buffer
        (1, 1, 64, 8, 2, 16, 40, 41),  # single-token decode step
        (2, 33, 70, 4, 2, 16, 0, 33),  # ragged (padded) shapes
    ],
)
def test_flash_matches_xla_cache_layout(b, s, t, nq, nkv, d, q_start, kv_len):
    q, k, v = _rand_qkv(jax.random.PRNGKey(0), b, s, t, nq, nkv, d)
    q_positions = q_start + jnp.broadcast_to(jnp.arange(s), (b, s))
    ref = gqa_attention(q, k, v, q_positions, jnp.int32(kv_len))
    got = flash_gqa(q, k, v, q_start=q_start, kv_len=kv_len, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_flash_matches_xla_no_cache_offset():
    # cache-free stage forward mid-sequence: slot j = position q_start + j
    b, s, nq, nkv, d = 2, 24, 4, 2, 16
    q, k, v = _rand_qkv(jax.random.PRNGKey(1), b, s, s, nq, nkv, d)
    pos = 100 + jnp.broadcast_to(jnp.arange(s), (b, s))
    ref = gqa_attention(q, k, v, pos, jnp.int32(s), kv_positions=pos)
    got = flash_gqa(q, k, v, q_start=pos[:, 0], kv_len=s, kv_start=pos[:, 0], interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("stream", [False, True], ids=["resident", "stream"])
@pytest.mark.parametrize(
    "b,s,t,nq,nkv,d,q_start,kv_len,window",
    [
        (1, 16, 16, 4, 2, 16, 0, 16, 8),   # prefill, window < seq
        (1, 1, 64, 8, 2, 16, 40, 41, 8),   # decode far past the window
        (2, 8, 64, 4, 4, 32, 24, 32, 100), # window wider than context = global
        (1, 1, 64, 8, 2, 16, 40, 41, 0),   # window 0 = global (gemma odd layers)
    ],
)
def test_flash_sliding_window_matches_xla(stream, b, s, t, nq, nkv, d, q_start, kv_len, window):
    """Kernel sliding-window masking + kv-block loop floor == XLA reference,
    with the window as a TRACED scalar (per-layer scan input) and softcap +
    non-default scale stacked on (the full Gemma-2 attention recipe)."""
    q, k, v = _rand_qkv(jax.random.PRNGKey(7), b, s, t, nq, nkv, d)
    q_positions = q_start + jnp.broadcast_to(jnp.arange(s), (b, s))
    scale, cap = 32.0 ** -0.5, 50.0
    ref = gqa_attention(
        q, k, v, q_positions, jnp.int32(kv_len),
        scale=scale, softcap=cap, window=jnp.int32(window),
    )

    @jax.jit
    def run(win):  # traced window, like the layer scan passes it
        return flash_gqa(
            q, k, v, q_start=q_start, kv_len=kv_len, interpret=True,
            stream=stream, scale=scale, softcap=cap, window=win,
        )

    got = run(jnp.int32(window))
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("stream", [False, True], ids=["resident", "stream"])
@pytest.mark.parametrize(
    "b,s,t,nq,nkv,d,q_start,kv_len,window",
    [
        (1, 16, 16, 4, 2, 16, 0, 16, 0),    # prefill, global layer
        (1, 1, 64, 8, 2, 16, 40, 41, 8),    # decode, sliding layer
        (2, 8, 64, 4, 4, 32, 24, 32, 0),    # multi-batch chunk
        (2, 33, 70, 4, 2, 16, 0, 33, 0),    # ragged/padded rows keep zeros
    ],
)
def test_flash_sinks_match_xla(stream, b, s, t, nq, nkv, d, q_start, kv_len, window):
    """Attention sinks fold into the kernels' online-softmax denominator at
    finalize; must equal the XLA closed form for every packed-tile layout —
    including bucket-padding rows (which must still emit zeros)."""
    q, k, v = _rand_qkv(jax.random.PRNGKey(11), b, s, t, nq, nkv, d)
    sinks = jax.random.normal(jax.random.PRNGKey(12), (nq,)) * 2.0
    q_positions = q_start + jnp.broadcast_to(jnp.arange(s), (b, s))
    win = jnp.int32(window)
    ref = gqa_attention(
        q, k, v, q_positions, jnp.int32(kv_len), window=win, sinks=sinks
    )
    got = flash_gqa(
        q, k, v, q_start=q_start, kv_len=kv_len, interpret=True,
        stream=stream, window=win, sinks=sinks,
    )
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_full_model_forward_with_flash_kernel_gpt_oss():
    """Whole tiny-gptoss forward (sinks + window + yarn + biases) with
    attn_impl=flash_interpret == the XLA path."""
    from inferd_tpu.config import TINY_GPT_OSS

    cfg_x = dataclasses.replace(TINY_GPT_OSS, attn_impl="xla")
    cfg_f = dataclasses.replace(TINY_GPT_OSS, attn_impl="flash_interpret")
    params = qwen3.init_params(cfg_x, jax.random.PRNGKey(13))
    # randomize sinks so they matter
    params["layers"]["sinks"] = jax.random.normal(
        jax.random.PRNGKey(14), params["layers"]["sinks"].shape
    )
    tokens = jax.random.randint(jax.random.PRNGKey(15), (1, 12), 0, cfg_x.vocab_size)
    ref, _, _ = qwen3.forward(params, cfg_x, tokens)
    got, _, _ = qwen3.forward(params, cfg_f, tokens)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("stream", [False, True], ids=["resident", "stream"])
def test_flash_window_with_kv_start_offset(stream):
    """window > 0 combined with kv_start > 0 — the configuration the
    windowed-read fast path produces (a window-covering KV slice whose
    slot 0 holds a mid-sequence absolute position). Pins the kernels'
    window-floor arithmetic (lo_slot subtracts kv_start)."""
    b, s, t, nq, nkv, d = 2, 1, 32, 4, 2, 16
    q, k, v = _rand_qkv(jax.random.PRNGKey(17), b, s, t, nq, nkv, d)
    kv_start, kv_len, q0, window = 100, 30, 129, 8
    pos = jnp.full((b, s), q0, jnp.int32)
    kvpos = kv_start + jnp.arange(t)
    ref = gqa_attention(
        q, k, v, pos, jnp.int32(kv_len), kv_positions=kvpos,
        window=jnp.int32(window),
    )
    got = flash_gqa(
        q, k, v, q_start=q0, kv_len=kv_len, kv_start=kv_start,
        interpret=True, stream=stream, window=jnp.int32(window),
    )
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_flash_softcap_only_matches_xla():
    """Softcap without a window (a Gemma global layer) on both kernels."""
    b, s, t, nq, nkv, d = 2, 8, 64, 4, 2, 16
    q, k, v = _rand_qkv(jax.random.PRNGKey(8), b, s, t, nq, nkv, d)
    pos = 24 + jnp.broadcast_to(jnp.arange(s), (b, s))
    ref = gqa_attention(q, k, v, pos, jnp.int32(32), softcap=30.0)
    for stream in (False, True):
        got = flash_gqa(
            q, k, v, q_start=24, kv_len=32, interpret=True,
            stream=stream, softcap=30.0,
        )
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_full_model_forward_with_flash_kernel_gemma():
    """Whole tiny-gemma2 forward with attn_impl=flash_interpret == XLA path:
    the per-layer window array reaches the kernel through the scan."""
    from inferd_tpu.config import TINY_GEMMA2

    cfg_x = dataclasses.replace(TINY_GEMMA2, attn_impl="xla")
    cfg_f = dataclasses.replace(TINY_GEMMA2, attn_impl="flash_interpret")
    params = qwen3.init_params(cfg_x, jax.random.PRNGKey(9))
    tokens = jax.random.randint(jax.random.PRNGKey(10), (1, 12), 0, cfg_x.vocab_size)
    ref, _, _ = qwen3.forward(params, cfg_x, tokens)
    got, _, _ = qwen3.forward(params, cfg_f, tokens)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=2e-4, atol=2e-4)


def test_flash_per_batch_lengths():
    b, s, t, nq, nkv, d = 3, 4, 32, 4, 2, 16
    q, k, v = _rand_qkv(jax.random.PRNGKey(2), b, s, t, nq, nkv, d)
    q_start = jnp.array([0, 8, 20], jnp.int32)
    kv_len = q_start + s
    pos = q_start[:, None] + jnp.arange(s)[None, :]
    ref = gqa_attention(q, k, v, pos, kv_len)
    got = flash_gqa(q, k, v, q_start=q_start, kv_len=kv_len, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_flash_bf16_close_to_f32_reference():
    b, s, nq, nkv, d = 1, 32, 4, 2, 32
    q, k, v = _rand_qkv(jax.random.PRNGKey(3), b, s, s, nq, nkv, d, jnp.bfloat16)
    pos = jnp.broadcast_to(jnp.arange(s), (b, s))
    ref = gqa_attention(q, k, v, pos, jnp.int32(s), kv_positions=pos)
    got = flash_gqa(q, k, v, q_start=0, kv_len=s, kv_start=0, interpret=True)
    assert got.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(ref, np.float32), rtol=0.1, atol=0.1
    )


def test_full_model_forward_with_flash_kernel(tiny_params):
    """End-to-end: whole tiny model with attn_impl=flash_interpret matches XLA."""
    cfg = TINY
    tokens = jax.random.randint(jax.random.PRNGKey(4), (1, 20), 0, cfg.vocab_size)
    ref_logits, _, _ = qwen3.forward(tiny_params, cfg, tokens)
    fcfg = dataclasses.replace(cfg, attn_impl="flash_interpret")
    got_logits, _, _ = qwen3.forward(tiny_params, fcfg, tokens)
    np.testing.assert_allclose(
        np.asarray(got_logits), np.asarray(ref_logits), rtol=1e-4, atol=1e-4
    )


def test_cached_decode_with_flash_kernel(tiny_params):
    """Prefill + cached decode through the kernel matches the XLA path."""
    from inferd_tpu.core.cache import KVCache

    cfg = dataclasses.replace(TINY, attn_impl="flash_interpret")
    tokens = jax.random.randint(jax.random.PRNGKey(5), (1, 12), 0, cfg.vocab_size)
    cache = KVCache.create(cfg, cfg.num_layers, 1, 32, ring=False)

    ref_logits, _, _ = qwen3.forward(tiny_params, TINY, tokens)

    # prefill first 11 tokens, then decode token 12 against the cache
    logits, nk, nv = qwen3.forward(
        tiny_params, cfg, tokens[:, :11],
        k_cache=cache.k, v_cache=cache.v, cache_write_pos=jnp.int32(0),
    )
    step_logits, _, _ = qwen3.forward(
        tiny_params, cfg, tokens[:, 11:12],
        k_cache=nk, v_cache=nv, cache_write_pos=jnp.int32(11),
    )
    np.testing.assert_allclose(
        np.asarray(step_logits[:, 0]), np.asarray(ref_logits[:, 11]), rtol=1e-4, atol=1e-4
    )


@pytest.mark.parametrize(
    "b,s,t,nq,nkv,d,q_start,kv_len",
    [
        (1, 16, 16, 4, 2, 16, 0, 16),   # prefill from scratch
        (2, 8, 640, 4, 4, 32, 24, 32),  # chunk over a much larger buffer
        (1, 1, 512, 8, 2, 16, 300, 301),  # decode step, multi-block stream
        (2, 33, 384, 4, 2, 16, 0, 33),  # ragged (padded) shapes
    ],
)
def test_flash_stream_matches_xla(b, s, t, nq, nkv, d, q_start, kv_len):
    """The streaming kernel (kv blocks on an inner grid axis, state in
    scratch — the no-VMEM-cap long-context path) must match XLA exactly."""
    q, k, v = _rand_qkv(jax.random.PRNGKey(1), b, s, t, nq, nkv, d)
    q_positions = q_start + jnp.broadcast_to(jnp.arange(s), (b, s))
    ref = gqa_attention(q, k, v, q_positions, jnp.int32(kv_len))
    got = flash_gqa(
        q, k, v, q_start=q_start, kv_len=kv_len, interpret=True,
        stream=True, block_k=128,
    )
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_flash_auto_selects_stream_past_vmem_budget():
    """Auto dispatch: buffers past the VMEM budget go to the streaming
    kernel rather than falling back to XLA (VERDICT r1 A6 — the ~8K cap)."""
    from inferd_tpu.ops import attention as att

    assert att._kv_fits_vmem(4096, 128, jnp.bfloat16)
    assert not att._kv_fits_vmem(16384, 128, jnp.bfloat16)  # past the old cap
    # a long-buffer call runs (interpret) and matches the reference — with
    # shapes that actually exceed the budget, so stream=None resolves to the
    # STREAMING kernel (d must match the budget assertion above, else auto
    # quietly picks the resident kernel and this test pins nothing)
    b, s, t, nq, nkv, d = 1, 1, 16384, 2, 2, 128
    assert not att._kv_fits_vmem(t, d, jnp.float32)
    q, k, v = _rand_qkv(jax.random.PRNGKey(2), b, s, t, nq, nkv, d)
    q_positions = jnp.full((b, s), 9000)
    ref = gqa_attention(q, k, v, q_positions, jnp.int32(9001))
    got = flash_gqa(q, k, v, q_start=9000, kv_len=9001, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("stream", [False, True], ids=["resident", "stream"])
@pytest.mark.parametrize(
    "b,s,t,nq,nkv,d,q_start,kv_len",
    [
        (1, 40, 64, 4, 2, 16, 20, 60),   # s_pad > block_q: multi-tile per head
        (2, 33, 96, 6, 2, 16, 0, 33),    # g=3 with per-batch rows, ragged s
    ],
)
def test_flash_packed_multitile_matches_xla(stream, b, s, t, nq, nkv, d, q_start, kv_len):
    """The s_pad >= block_q packing branch (long prefill: several tiles per
    query head, modulo position/frontier arithmetic) must match XLA — CI
    otherwise only exercises the small-S multi-head-per-tile branch."""
    q, k, v = _rand_qkv(jax.random.PRNGKey(11), b, s, t, nq, nkv, d)
    q_positions = q_start + jnp.broadcast_to(jnp.arange(s), (b, s))
    ref = gqa_attention(q, k, v, q_positions, jnp.int32(kv_len))
    got = flash_gqa(
        q, k, v, q_start=q_start, kv_len=kv_len, interpret=True,
        stream=stream, block_q=32, block_k=32,
    )
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("stream", [False, True], ids=["resident", "stream"])
def test_flash_consumes_fp8_kv_directly(stream):
    """The kernels upcast compressed (fp8) K/V in VMEM after the block
    fetch — results must match the upcast-then-XLA reference within fp8
    storage noise."""
    b, s, t, nq, nkv, d = 1, 8, 128, 4, 2, 16
    q, k, v = _rand_qkv(jax.random.PRNGKey(12), b, s, t, nq, nkv, d)
    k8 = k.astype(jnp.float8_e4m3fn)
    v8 = v.astype(jnp.float8_e4m3fn)
    q_positions = 100 + jnp.broadcast_to(jnp.arange(s), (b, s))
    ref = gqa_attention(
        q, k8.astype(q.dtype), v8.astype(q.dtype), q_positions, jnp.int32(108)
    )
    got = flash_gqa(
        q, k8, v8, q_start=100, kv_len=108, interpret=True, stream=stream
    )
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=2e-5, atol=2e-5)
