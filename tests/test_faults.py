"""Fault injection + failure recovery tests (SURVEY §5: the reference had
recovery *mechanisms* but no way to test them; here they're asserted):
chaos drop/delay, session-restart on node death, the flight-recorder
incident flow (peer.dead -> session.rescue journal sequence + the
postmortem CLI assembling it from the per-node JSONL artifacts), and the
on-demand jax.profiler endpoint."""

import asyncio
import glob
import os

import pytest

from inferd_tpu.client.swarm_client import SwarmClient
from inferd_tpu.config import TINY, SamplingConfig
from inferd_tpu.core.generate import Engine
from inferd_tpu.utils.chaos import Chaos, ChaosDrop

from test_node_e2e import BASE, _mk_node, _start_all, _stop_all, tiny_parts  # noqa: F401


def test_chaos_parse():
    c = Chaos.parse("drop=0.25,delay_ms=10,seed=3")
    assert c.drop == 0.25 and c.delay_ms == 10 and c.seed == 3
    assert Chaos.parse("") is None and Chaos.parse(None) is None
    with pytest.raises(ValueError):
        Chaos.parse("explode=1")


@pytest.mark.asyncio
async def test_chaos_drop_rate():
    c = Chaos(drop=0.5, seed=0)
    dropped = 0
    for _ in range(200):
        try:
            await c.before_forward()
        except ChaosDrop:
            dropped += 1
    assert 60 <= dropped <= 140  # ~50% of 200


@pytest.mark.asyncio
async def test_chaos_drop_surfaces_as_500():
    nodes = [_mk_node(70 + i, i, 2, bootstrap_idx=70) for i in range(2)]
    nodes[0].chaos = Chaos(drop=1.0)  # stage 0 drops everything
    await _start_all(nodes)
    try:
        async with SwarmClient([("127.0.0.1", BASE + 70)]) as c:
            with pytest.raises(RuntimeError, match="chaos drop"):
                await c._post(
                    "/forward", {"stage": 0, "session_id": "s", "payload": {}}
                )
        assert nodes[0].metrics.snapshot()["counters"]["chaos.dropped"] >= 1
    finally:
        await _stop_all(nodes)


@pytest.mark.asyncio
async def test_node_death_mid_generation_recovers(tiny_parts):  # noqa: F811
    """Kill the only stage-1 node mid-generation: its record TTLs out, the
    spare node adopts stage 1 (empty-stage recovery), and the client's
    session-restart retry completes the SAME tokens (greedy determinism)."""
    parts, params = tiny_parts
    # n0: stage 0.  n1: stage 1 (will die).  n2: spare replica on stage 0
    # that must migrate to stage 1 after the death.
    nodes = [
        _mk_node(80, 0, 2, backend="qwen3", parts=parts, bootstrap_idx=80),
        _mk_node(81, 1, 2, backend="qwen3", parts=parts, bootstrap_idx=80),
        _mk_node(82, 0, 2, backend="qwen3", parts=parts, bootstrap_idx=80),
    ]
    await _start_all(nodes)
    try:
        engine = Engine(TINY, params, max_len=64, sampling_cfg=SamplingConfig(temperature=0.0))
        prompt = [3, 7, 11, 19]
        expected = engine.generate(prompt, max_new_tokens=6)

        async with SwarmClient(
            [("127.0.0.1", BASE + 80)], sampling=SamplingConfig(temperature=0.0)
        ) as c:
            # healthy first pass
            assert await c.generate_ids(prompt, max_new_tokens=6) == expected

            # stage 1's only server hard-crashes: no tombstone gossip, no
            # graceful anything — peers must detect the death via record-TTL
            # expiry (1.5 s in these tests)
            n1 = nodes[1]
            await n1.crash()
            nodes.remove(n1)

            # generation must still complete: retries span the TTL window
            # (1.5 s in these tests) + adoption by a spare
            got = await c.generate_ids(
                prompt, max_new_tokens=6, session_retries=8, retry_delay_s=0.5
            )
            assert got == expected
            # someone now serves stage 1
            stage1 = nodes[0].dht.get_stage(1)
            assert stage1, "no node adopted the dead stage"
    finally:
        await _stop_all(nodes)


@pytest.fixture(scope="module")
def tiny_parts3(tmp_path_factory):
    """TINY split into THREE stages — the incident e2e needs a mid-chain
    stage with a replica pair so a kill forces a rescue, not an adoption."""
    from inferd_tpu.models import qwen3
    from inferd_tpu.parallel.stages import Manifest, split_and_save

    parts = tmp_path_factory.mktemp("parts3")
    params = qwen3.init_params(TINY, __import__("jax").random.PRNGKey(0))
    manifest = Manifest.even_split("tiny", 3)
    split_and_save(params, TINY, manifest, str(parts))
    return str(parts), params


@pytest.mark.asyncio
async def test_incident_journal_and_postmortem(tiny_parts3, tmp_path):
    """Kill the stage-1 replica HOLDING a session's KV mid-generation.

    Asserts the flight-recorder incident flow end to end: the upstream
    node journals `peer.dead` for the crashed hop, the surviving replica
    journals `session.rescue` (it saw a mid-session chunk without the KV
    while gossip still advertised the dead holder), both carry the
    request's trace_id, the generation still completes token-exact via
    the client's session restart — and `obs postmortem <trace_id>`
    assembles timeline + interleaved events + firing SLO rules entirely
    from the per-node JSONL artifacts (--trace-dir output)."""
    from inferd_tpu.obs import postmortem as pmlib
    from inferd_tpu.obs.__main__ import main as obs_main

    parts, params = tiny_parts3
    obs_dir = str(tmp_path / "obs")
    # n44: stage 0 (entry). n45+n46: stage-1 replica pair (one will die).
    # n47: stage 2.
    nodes = [
        _mk_node(44, 0, 3, backend="qwen3", parts=parts, bootstrap_idx=44),
        _mk_node(45, 1, 3, backend="qwen3", parts=parts, bootstrap_idx=44),
        _mk_node(46, 1, 3, backend="qwen3", parts=parts, bootstrap_idx=44),
        _mk_node(47, 2, 3, backend="qwen3", parts=parts, bootstrap_idx=44),
    ]
    for n in nodes:
        n.trace_dir = obs_dir
    await _start_all(nodes)
    live = list(nodes)
    stage1 = [nodes[1], nodes[2]]
    try:
        engine = Engine(
            TINY, params, max_len=64,
            sampling_cfg=SamplingConfig(temperature=0.0),
        )
        prompt = [3, 7, 11, 19]
        expected = engine.generate(prompt, max_new_tokens=24)

        async with SwarmClient(
            [("127.0.0.1", BASE + 44)],
            sampling=SamplingConfig(temperature=0.0),
        ) as c:
            tokens = []
            state = {}

            async def on_token(tok):
                # crash the KV holder BETWEEN steps (the hook is awaited
                # inside the client's token loop, so no request is
                # mid-flight at the victim): the next mid-session chunk
                # then fails at connection level (peer.dead), lands on
                # the survivor without its KV while gossip still
                # advertises the corpse (session.rescue), and 409s the
                # client into a session restart. A crash during an
                # in-flight step would surface as a 500 from the dying
                # handler instead and skip the rescue path entirely.
                tokens.append(tok)
                if len(tokens) == 3 and "victim" not in state:
                    victim = next(
                        (n for n in stage1 if len(n.executor.sessions) > 0),
                        None,
                    )
                    assert victim is not None, (
                        "no stage-1 replica held the session"
                    )
                    state["victim"] = victim
                    await victim.crash()

            got = await c.generate_ids(
                prompt, max_new_tokens=24, session_retries=10,
                retry_delay_s=0.4, on_token=on_token,
            )
            assert got == expected  # greedy determinism across the restart
            victim = state["victim"]
            live.remove(victim)
            survivor = next(n for n in stage1 if n is not victim)

            # the client's generate umbrella span carries the trace id
            roots = [
                s for s in c.tracer.spans()
                if s["name"] == "generate" and s.get("parent") is None
            ]
            assert roots, "client recorded no generate root span"
            tid = roots[0]["trace"]
            c.tracer.dump_jsonl(os.path.join(obs_dir, "client.spans.jsonl"))

        # ---- journal sequence: peer.dead -> session.rescue, same trace
        dead_evs = [
            ev for ev in nodes[0].journal.events()
            if ev["type"] == "peer.dead"
        ]
        assert dead_evs, "entry node journaled no peer.dead"
        assert any(ev.get("trace") == tid for ev in dead_evs)
        rescue_evs = [
            ev for ev in survivor.journal.events()
            if ev["type"] == "session.rescue"
        ]
        assert rescue_evs, "survivor journaled no session.rescue"
        assert any(ev.get("trace") == tid for ev in rescue_evs)
        assert min(ev["ts"] for ev in dead_evs) <= min(
            ev["ts"] for ev in rescue_evs
        ), "peer.dead must precede the rescue it caused"
        # the rescue relay's span joined the same trace on the survivor
        assert any(
            s.get("phase") == "rescue" and s["trace"] == tid
            for s in survivor.tracer.spans()
        )

        # ---- postmortem from the per-node JSONL artifacts alone
        await _stop_all(live)  # final flush writes spans/events/metrics
        live.clear()
        assert glob.glob(os.path.join(obs_dir, "*.events.jsonl"))
        assert glob.glob(os.path.join(obs_dir, "*.metrics.jsonl"))
        report = pmlib.build_report(tid, [obs_dir])
        assert report["timeline"]["stages"], "no per-stage timeline"
        ev_types = {ev["type"] for ev in report["events"]}
        assert {"peer.dead", "session.rescue"} <= ev_types
        kinds = {e["kind"] for e in report["entries"]}
        assert kinds == {"span", "event"}, "events not interleaved with spans"
        fired = {f["rule"] for f in report["firing"]}
        assert "event:peer.dead == 0" in fired, f"no firing SLO rule: {fired}"
        assert report["first_divergent_hop"] is not None
        # the CLI renders the same report from the same artifacts
        assert obs_main(["postmortem", tid, obs_dir]) == 0
    finally:
        await _stop_all(live)


@pytest.mark.asyncio
async def test_profile_endpoint_writes_trace(tmp_path):
    nodes = [_mk_node(95, 0, 1, bootstrap_idx=95)]
    nodes[0].enable_profiling = True  # endpoint is opt-in (ADVICE r1)
    nodes[0].profiler.base_dir = str(tmp_path)  # confine traces to tmp
    await _start_all(nodes)
    try:
        async with SwarmClient([("127.0.0.1", BASE + 95)]) as c:
            d = str(tmp_path / "trace")
            r = await c._post("/profile", {"action": "start", "name": "trace"})
            assert r["ok"] and r["dir"] == d
            # the endpoint is not a write-anywhere primitive
            r2 = await c._post("/profile", {"action": "stop"})
            with pytest.raises(RuntimeError, match="escapes profile dir"):
                await c._post("/profile", {"action": "start", "name": "../evil"})
            with pytest.raises(RuntimeError, match="escapes profile dir"):
                await c._post("/profile", {"action": "start", "name": "/tmp/evil"})
            r = await c._post("/profile", {"action": "start", "name": "trace"})
            # double start -> 409
            with pytest.raises(RuntimeError, match="already running"):
                await c._post("/profile", {"action": "start"})
            # some jax work to capture
            await c._post("/forward", {"stage": 0, "session_id": "p", "payload": {}})
            r = await c._post("/profile", {"action": "stop"})
            assert r["ok"]
            files = glob.glob(os.path.join(d, "**", "*"), recursive=True)
            assert files, "profiler wrote nothing"
            # stop without start -> 409
            with pytest.raises(RuntimeError, match="no profile"):
                await c._post("/profile", {"action": "stop"})
            # gate: with profiling disabled the endpoint refuses outright
            nodes[0].enable_profiling = False
            with pytest.raises(RuntimeError, match="profiling disabled"):
                await c._post("/profile", {"action": "start"})
    finally:
        await _stop_all(nodes)


def test_server_error_retryability():
    from inferd_tpu.client.base import ServerError

    assert ServerError("x", 500).retryable  # transient node trouble
    assert ServerError("x", 502).retryable  # dead next hop
    assert ServerError("x", 409, code="session_state").retryable  # KV lost
    assert not ServerError("x", 409, code="overflow").retryable
    assert not ServerError("x", 409, code="wrong_stage").retryable
    assert not ServerError("x", 400).retryable  # malformed request
