"""Fault injection + failure recovery tests (SURVEY §5: the reference had
recovery *mechanisms* but no way to test them; here they're asserted):
chaos drop/delay, session-restart on node death, the flight-recorder
incident flow (peer.dead -> session.rescue journal sequence + the
postmortem CLI assembling it from the per-node JSONL artifacts), and the
on-demand jax.profiler endpoint."""

import asyncio
import glob
import os

import pytest

from inferd_tpu.client.swarm_client import SwarmClient
from inferd_tpu.config import TINY, SamplingConfig
from inferd_tpu.core.generate import Engine
from inferd_tpu.utils.chaos import Chaos, ChaosDrop

from test_node_e2e import BASE, _mk_node, _start_all, _stop_all, tiny_parts  # noqa: F401


def test_chaos_parse():
    c = Chaos.parse("drop=0.25,delay_ms=10,seed=3")
    assert c.drop == 0.25 and c.delay_ms == 10 and c.seed == 3
    assert Chaos.parse("") is None and Chaos.parse(None) is None
    with pytest.raises(ValueError):
        Chaos.parse("explode=1")


@pytest.mark.asyncio
async def test_chaos_drop_rate():
    c = Chaos(drop=0.5, seed=0)
    dropped = 0
    for _ in range(200):
        try:
            await c.before_forward()
        except ChaosDrop:
            dropped += 1
    assert 60 <= dropped <= 140  # ~50% of 200


@pytest.mark.asyncio
async def test_chaos_drop_surfaces_as_500():
    nodes = [_mk_node(70 + i, i, 2, bootstrap_idx=70) for i in range(2)]
    nodes[0].chaos = Chaos(drop=1.0)  # stage 0 drops everything
    await _start_all(nodes)
    try:
        async with SwarmClient([("127.0.0.1", BASE + 70)]) as c:
            with pytest.raises(RuntimeError, match="chaos drop"):
                await c._post(
                    "/forward", {"stage": 0, "session_id": "s", "payload": {}}
                )
        assert nodes[0].metrics.snapshot()["counters"]["chaos.dropped"] >= 1
    finally:
        await _stop_all(nodes)


@pytest.mark.asyncio
async def test_node_death_mid_generation_recovers(tiny_parts):  # noqa: F811
    """Kill the only stage-1 node mid-generation: its record TTLs out, the
    spare node adopts stage 1 (empty-stage recovery), and the client's
    session-restart retry completes the SAME tokens (greedy determinism)."""
    parts, params = tiny_parts
    # n0: stage 0.  n1: stage 1 (will die).  n2: spare replica on stage 0
    # that must migrate to stage 1 after the death.
    nodes = [
        _mk_node(80, 0, 2, backend="qwen3", parts=parts, bootstrap_idx=80),
        _mk_node(81, 1, 2, backend="qwen3", parts=parts, bootstrap_idx=80),
        _mk_node(82, 0, 2, backend="qwen3", parts=parts, bootstrap_idx=80),
    ]
    await _start_all(nodes)
    try:
        engine = Engine(TINY, params, max_len=64, sampling_cfg=SamplingConfig(temperature=0.0))
        prompt = [3, 7, 11, 19]
        expected = engine.generate(prompt, max_new_tokens=6)

        async with SwarmClient(
            [("127.0.0.1", BASE + 80)], sampling=SamplingConfig(temperature=0.0)
        ) as c:
            # healthy first pass
            assert await c.generate_ids(prompt, max_new_tokens=6) == expected

            # stage 1's only server hard-crashes: no tombstone gossip, no
            # graceful anything — peers must detect the death via record-TTL
            # expiry (1.5 s in these tests)
            n1 = nodes[1]
            await n1.crash()
            nodes.remove(n1)

            # generation must still complete: retries span the TTL window
            # (1.5 s in these tests) + adoption by a spare
            got = await c.generate_ids(
                prompt, max_new_tokens=6, session_retries=8, retry_delay_s=0.5
            )
            assert got == expected
            # someone now serves stage 1
            stage1 = nodes[0].dht.get_stage(1)
            assert stage1, "no node adopted the dead stage"
    finally:
        await _stop_all(nodes)


@pytest.fixture(scope="module")
def tiny_parts3(tmp_path_factory):
    """TINY split into THREE stages — the incident e2e needs a mid-chain
    stage with a replica pair so a kill forces a rescue, not an adoption."""
    from inferd_tpu.models import qwen3
    from inferd_tpu.parallel.stages import Manifest, split_and_save

    parts = tmp_path_factory.mktemp("parts3")
    params = qwen3.init_params(TINY, __import__("jax").random.PRNGKey(0))
    manifest = Manifest.even_split("tiny", 3)
    split_and_save(params, TINY, manifest, str(parts))
    return str(parts), params


@pytest.mark.asyncio
async def test_incident_journal_and_postmortem(tiny_parts3, tmp_path):
    """Kill the stage-1 replica HOLDING a session's KV mid-generation.

    Asserts the flight-recorder incident flow end to end: the upstream
    node journals `peer.dead` for the crashed hop, the surviving replica
    journals `session.rescue` (it saw a mid-session chunk without the KV
    while gossip still advertised the dead holder), both carry the
    request's trace_id, the generation still completes token-exact via
    the client's session restart — and `obs postmortem <trace_id>`
    assembles timeline + interleaved events + firing SLO rules entirely
    from the per-node JSONL artifacts (--trace-dir output)."""
    from inferd_tpu.obs import postmortem as pmlib
    from inferd_tpu.obs.__main__ import main as obs_main

    parts, params = tiny_parts3
    obs_dir = str(tmp_path / "obs")
    # n44: stage 0 (entry). n45+n46: stage-1 replica pair (one will die).
    # n47: stage 2.
    nodes = [
        _mk_node(44, 0, 3, backend="qwen3", parts=parts, bootstrap_idx=44),
        _mk_node(45, 1, 3, backend="qwen3", parts=parts, bootstrap_idx=44),
        _mk_node(46, 1, 3, backend="qwen3", parts=parts, bootstrap_idx=44),
        _mk_node(47, 2, 3, backend="qwen3", parts=parts, bootstrap_idx=44),
    ]
    for n in nodes:
        n.trace_dir = obs_dir
    await _start_all(nodes)
    live = list(nodes)
    stage1 = [nodes[1], nodes[2]]
    try:
        engine = Engine(
            TINY, params, max_len=64,
            sampling_cfg=SamplingConfig(temperature=0.0),
        )
        prompt = [3, 7, 11, 19]
        expected = engine.generate(prompt, max_new_tokens=24)

        async with SwarmClient(
            [("127.0.0.1", BASE + 44)],
            sampling=SamplingConfig(temperature=0.0),
        ) as c:
            tokens = []
            state = {}

            async def on_token(tok):
                # crash the KV holder BETWEEN steps (the hook is awaited
                # inside the client's token loop, so no request is
                # mid-flight at the victim): the next mid-session chunk
                # then fails at connection level (peer.dead), lands on
                # the survivor without its KV while gossip still
                # advertises the corpse (session.rescue), and 409s the
                # client into a session restart. A crash during an
                # in-flight step would surface as a 500 from the dying
                # handler instead and skip the rescue path entirely.
                tokens.append(tok)
                if len(tokens) == 3 and "victim" not in state:
                    victim = next(
                        (n for n in stage1 if len(n.executor.sessions) > 0),
                        None,
                    )
                    assert victim is not None, (
                        "no stage-1 replica held the session"
                    )
                    state["victim"] = victim
                    await victim.crash()

            got = await c.generate_ids(
                prompt, max_new_tokens=24, session_retries=10,
                retry_delay_s=0.4, on_token=on_token,
            )
            assert got == expected  # greedy determinism across the restart
            victim = state["victim"]
            live.remove(victim)
            survivor = next(n for n in stage1 if n is not victim)

            # the client's generate umbrella span carries the trace id
            roots = [
                s for s in c.tracer.spans()
                if s["name"] == "generate" and s.get("parent") is None
            ]
            assert roots, "client recorded no generate root span"
            tid = roots[0]["trace"]
            c.tracer.dump_jsonl(os.path.join(obs_dir, "client.spans.jsonl"))

        # ---- journal sequence: peer.dead -> session.rescue, same trace
        dead_evs = [
            ev for ev in nodes[0].journal.events()
            if ev["type"] == "peer.dead"
        ]
        assert dead_evs, "entry node journaled no peer.dead"
        assert any(ev.get("trace") == tid for ev in dead_evs)
        rescue_evs = [
            ev for ev in survivor.journal.events()
            if ev["type"] == "session.rescue"
        ]
        assert rescue_evs, "survivor journaled no session.rescue"
        assert any(ev.get("trace") == tid for ev in rescue_evs)
        assert min(ev["ts"] for ev in dead_evs) <= min(
            ev["ts"] for ev in rescue_evs
        ), "peer.dead must precede the rescue it caused"
        # the rescue relay's span joined the same trace on the survivor
        assert any(
            s.get("phase") == "rescue" and s["trace"] == tid
            for s in survivor.tracer.spans()
        )

        # ---- postmortem from the per-node JSONL artifacts alone
        await _stop_all(live)  # final flush writes spans/events/metrics
        live.clear()
        assert glob.glob(os.path.join(obs_dir, "*.events.jsonl"))
        assert glob.glob(os.path.join(obs_dir, "*.metrics.jsonl"))
        report = pmlib.build_report(tid, [obs_dir])
        assert report["timeline"]["stages"], "no per-stage timeline"
        ev_types = {ev["type"] for ev in report["events"]}
        assert {"peer.dead", "session.rescue"} <= ev_types
        kinds = {e["kind"] for e in report["entries"]}
        assert kinds == {"span", "event"}, "events not interleaved with spans"
        fired = {f["rule"] for f in report["firing"]}
        assert "event:peer.dead == 0" in fired, f"no firing SLO rule: {fired}"
        assert report["first_divergent_hop"] is not None
        # the CLI renders the same report from the same artifacts
        assert obs_main(["postmortem", tid, obs_dir]) == 0
    finally:
        await _stop_all(live)


@pytest.mark.asyncio
async def test_profile_endpoint_writes_trace(tmp_path):
    nodes = [_mk_node(95, 0, 1, bootstrap_idx=95)]
    nodes[0].enable_profiling = True  # endpoint is opt-in (ADVICE r1)
    nodes[0].profiler.base_dir = str(tmp_path)  # confine traces to tmp
    await _start_all(nodes)
    try:
        async with SwarmClient([("127.0.0.1", BASE + 95)]) as c:
            d = str(tmp_path / "trace")
            r = await c._post("/profile", {"action": "start", "name": "trace"})
            assert r["ok"] and r["dir"] == d
            # the endpoint is not a write-anywhere primitive
            r2 = await c._post("/profile", {"action": "stop"})
            with pytest.raises(RuntimeError, match="escapes profile dir"):
                await c._post("/profile", {"action": "start", "name": "../evil"})
            with pytest.raises(RuntimeError, match="escapes profile dir"):
                await c._post("/profile", {"action": "start", "name": "/tmp/evil"})
            r = await c._post("/profile", {"action": "start", "name": "trace"})
            # double start -> 409
            with pytest.raises(RuntimeError, match="already running"):
                await c._post("/profile", {"action": "start"})
            # some jax work to capture
            await c._post("/forward", {"stage": 0, "session_id": "p", "payload": {}})
            r = await c._post("/profile", {"action": "stop"})
            assert r["ok"]
            files = glob.glob(os.path.join(d, "**", "*"), recursive=True)
            assert files, "profiler wrote nothing"
            # stop without start -> 409
            with pytest.raises(RuntimeError, match="no profile"):
                await c._post("/profile", {"action": "stop"})
            # gate: with profiling disabled the endpoint refuses outright
            nodes[0].enable_profiling = False
            with pytest.raises(RuntimeError, match="profiling disabled"):
                await c._post("/profile", {"action": "start"})
    finally:
        await _stop_all(nodes)


def test_server_error_retryability():
    from inferd_tpu.client.base import ServerError

    assert ServerError("x", 500).retryable  # transient node trouble
    assert ServerError("x", 502).retryable  # dead next hop
    assert ServerError("x", 409, code="session_state").retryable  # KV lost
    assert not ServerError("x", 409, code="overflow").retryable
    assert not ServerError("x", 409, code="wrong_stage").retryable
    assert not ServerError("x", 400).retryable  # malformed request
    # the overload plane's typed codes: an expired end-to-end deadline is
    # deterministic for the request (non-retryable); a shed is transient
    assert not ServerError("x", 408, code="deadline").retryable
    assert ServerError("x", 503, code="busy", retry_after=0.2).retryable
    assert ServerError("x", 503, code="draining").retryable


# ---------------------------------------------------------------------------
# PR 10 — overload containment: chaos extensions, backoff/budgets,
# deadlines, hedged relays, admission control, graceful drain
# ---------------------------------------------------------------------------


def test_chaos_parse_extended():
    c = Chaos.parse("jitter_ms=5:50,stall_p=0.3,drop_after=7,seed=9")
    assert c.jitter_ms == (5.0, 50.0)
    assert c.stall_p == 0.3 and c.drop_after == 7 and c.seed == 9
    # composes with the original keys
    c2 = Chaos.parse("drop=0.1,delay_ms=2,jitter_ms=0:1,stall_p=0.05")
    assert c2.drop == 0.1 and c2.delay_ms == 2 and c2.stall_p == 0.05
    with pytest.raises(ValueError, match="A:B"):
        Chaos.parse("jitter_ms=5")  # range syntax required
    with pytest.raises(ValueError, match="inverted"):
        Chaos.parse("jitter_ms=9:1")


@pytest.mark.asyncio
async def test_chaos_drop_after_healthy_then_sick():
    c = Chaos(drop_after=3, seed=0)
    for _ in range(3):  # healthy phase: first N forwards serve normally
        await c.before_forward()
    for _ in range(5):  # sick phase: everything drops
        with pytest.raises(ChaosDrop, match="drop_after"):
            await c.before_forward()


@pytest.mark.asyncio
async def test_chaos_stall_never_responds():
    """stall_p accepts the forward then never answers — the slow-loris
    that exercises deadline expiry and hedging (a drop answers instantly;
    only a stall makes the caller WAIT)."""
    c = Chaos(stall_p=1.0, seed=0)
    with pytest.raises(asyncio.TimeoutError):
        await asyncio.wait_for(c.before_forward(), timeout=0.1)
    # seeded composability: stall_p=0 never stalls, jitter still applies
    c2 = Chaos(jitter_ms=(0.0, 1.0), seed=1)
    await asyncio.wait_for(c2.before_forward(), timeout=1.0)


def test_backoff_full_jitter_deterministic():
    import random

    from inferd_tpu.utils.retry import backoff_delay

    rng = random.Random(42)
    sched = [backoff_delay(a, base_s=0.5, cap_s=4.0, rng=rng) for a in range(1, 6)]
    rng2 = random.Random(42)
    sched2 = [backoff_delay(a, base_s=0.5, cap_s=4.0, rng=rng2) for a in range(1, 6)]
    assert sched == sched2  # seeded => deterministic (the tests' contract)
    # full jitter: every delay inside [0, min(cap, base * 2^(n-1))]
    for i, d in enumerate(sched, start=1):
        assert 0.0 <= d <= min(4.0, 0.5 * 2 ** (i - 1))
    # the ceiling actually caps (attempt 5 would be 8.0 uncapped)
    assert all(d <= 4.0 for d in sched)


def test_retry_budget_token_bucket():
    from inferd_tpu.utils.retry import RatioBudget, RetryBudget

    t = [0.0]
    b = RetryBudget(rate_per_s=2.0, burst=3, clock=lambda: t[0])
    assert [b.try_acquire() for _ in range(4)] == [True, True, True, False]
    t[0] += 1.0  # refill 2 tokens
    assert b.try_acquire() and b.try_acquire() and not b.try_acquire()
    assert b.stats()["denied"] == 2
    # hedge ratio budget: <=5% of primaries + burst floor
    h = RatioBudget(ratio=0.05, burst=1)
    h.note(100)
    assert h.try_acquire()  # 1 <= 5 + 1
    for _ in range(5):
        h.try_acquire()
    assert not h.try_acquire()  # 7 > 0.05*100 + 1
    assert h.extra_frac() <= 0.06


class _FailingClient:
    """GenerationClient over a transport that always fails — the retry
    loop's unit harness (no HTTP, no nodes)."""

    def __init__(self, exc):
        from inferd_tpu.client.base import GenerationClient

        class C(GenerationClient):
            def __init__(inner):
                super().__init__()
                inner.steps = 0

            async def _step(inner, session_id, tokens, start_pos):
                inner.steps += 1
                raise exc

            async def _end_session(inner, session_id):
                pass

        self.client = C()


@pytest.mark.asyncio
async def test_retry_budget_exhaustion_surfaces_original_error():
    """When the per-process retry bucket is dry, generate_ids raises the
    ORIGINAL failure after the allowed retries — bounded amplification,
    and the operator sees what actually broke, not a budget error."""
    import random

    from inferd_tpu.client.base import ServerError
    from inferd_tpu.utils.retry import RetryBudget

    err = ServerError("boom: stage 1 down", 503)
    h = _FailingClient(err)
    budget = RetryBudget(rate_per_s=0.0, burst=2)  # exactly 2 retries, ever
    with pytest.raises(ServerError, match="boom"):
        await h.client.generate_ids(
            [1, 2, 3], max_new_tokens=2, session_retries=10,
            retry_delay_s=0.001, retry_budget=budget,
            retry_rng=random.Random(0),
        )
    # 1 initial attempt + the 2 budgeted retries; the other 8 never ran
    assert h.client.steps == 3
    assert budget.stats()["denied"] >= 1


@pytest.mark.asyncio
async def test_retry_honors_retry_after_hint():
    """A busy 503 carrying Retry-After paces the retry loop: the next
    attempt waits at least the hint, not just the jittered backoff."""
    import random
    import time as _time

    from inferd_tpu.client.base import ServerError

    err = ServerError("busy", 503, code="busy", retry_after=0.3)
    h = _FailingClient(err)
    t0 = _time.monotonic()
    with pytest.raises(ServerError):
        await h.client.generate_ids(
            [1], max_new_tokens=1, session_retries=1,
            retry_delay_s=0.001, retry_rng=random.Random(0),
        )
    assert _time.monotonic() - t0 >= 0.28  # waited the hint, not ~1 ms
    assert h.client.steps == 2


@pytest.mark.asyncio
async def test_client_deadline_stops_retries():
    """Once the end-to-end budget is spent, the retry loop stops with the
    typed non-retryable deadline error instead of burning attempts."""
    import random

    from inferd_tpu.client.base import ServerError

    h = _FailingClient(ServerError("transient", 500))
    with pytest.raises(ServerError) as ei:
        await h.client.generate_ids(
            [1], max_new_tokens=1, session_retries=5, retry_delay_s=0.2,
            deadline_s=0.0, retry_rng=random.Random(0),
        )
    assert ei.value.code == "deadline" and not ei.value.retryable
    assert h.client.steps <= 1  # no retry survived the dead budget


def test_wire_deadline_compat():
    """deadline_ms rides the envelope ONLY when a deadline is active
    (deadline-less traffic stays byte-identical), survives both wire
    generations and the coalesce/split round trip, and an absent key
    means 'no deadline' (what an old peer's envelopes look like)."""
    import numpy as np

    from inferd_tpu.client import base as clientbase
    from inferd_tpu.client.swarm_client import SwarmClient
    from inferd_tpu.runtime import wire
    from inferd_tpu.utils.retry import remaining_s

    env = SwarmClient([("127.0.0.1", 1)])._forward_env("s", [1, 2], 0)
    assert "deadline_ms" not in env  # no active deadline -> no new key
    tok = clientbase._DEADLINE_MS.set(1e15)
    try:
        env2 = SwarmClient([("127.0.0.1", 1)])._forward_env("s", [1, 2], 0)
    finally:
        clientbase._DEADLINE_MS.reset(tok)
    assert env2["deadline_ms"] == 1e15
    # both wire generations carry it (old peers DECODE legacy envelopes
    # and simply ignore the unknown key)
    for codec in (wire.pack, wire.pack_legacy):
        rt = wire.unpack(codec(env2))
        assert rt["deadline_ms"] == 1e15
    # coalesced multi envelopes: the per-session frames keep their own
    # deadline through split_forward (deadlines are per REQUEST)
    envs = []
    for i, dl in enumerate((1e15, None)):
        e = {
            "task_id": f"t{i}", "session_id": f"s{i}", "stage": 1,
            "payload": {
                "hidden": np.zeros((1, 1, 4), np.float32),
                "start_pos": 7, "real_len": 1,
            },
        }
        if dl is not None:
            e["deadline_ms"] = dl
        envs.append(e)
    split = wire.split_forward(wire.coalesce_forward(envs))
    assert split[0]["deadline_ms"] == 1e15
    assert "deadline_ms" not in split[1]
    # absent/garbage deadline == no deadline (fail open on old peers)
    assert remaining_s(None) is None
    assert remaining_s("not-a-number") is None


def test_ranked_nodes_draining_exclusion():
    from inferd_tpu.control.dstar import node_cost
    from inferd_tpu.control.path_finder import min_load_node, ranked_nodes

    stage_map = {
        "a": {"load": 0, "cap": 4, "host": "h", "port": 1},
        "b": {"load": 1, "cap": 4, "host": "h", "port": 2},
        "c": {"load": 0, "cap": 4, "host": "h", "port": 3, "draining": 1},
    }
    ranked = ranked_nodes(stage_map)
    # draining replica excluded outright; best-first among the rest
    assert [nid for nid, _ in ranked] == ["a", "b"]
    assert min_load_node(stage_map)[0] == "a"
    # availability beats drain: a stage with ONLY draining replicas
    # stays routable
    only_draining = {"c": dict(stage_map["c"])}
    assert min_load_node(only_draining)[0] == "c"
    # the planner's edge cost treats drain as exclusion-grade
    assert node_cost(stage_map["c"]) > node_cost(stage_map["b"]) + 1e5


@pytest.mark.asyncio
async def test_deadline_expired_entry_fast_fails(tiny_parts):  # noqa: F811
    """An envelope whose deadline is already spent fails with the typed
    non-retryable `deadline` 408 BEFORE any compute or relay: the
    downstream stage never sees the request (no dead work down the
    chain), and the decision lands in the journal."""
    import time as _time

    from inferd_tpu.client.base import ServerError

    nodes = [_mk_node(60 + i, i, 2, bootstrap_idx=60) for i in range(2)]
    await _start_all(nodes)
    try:
        async with SwarmClient([("127.0.0.1", BASE + 60)]) as c:
            with pytest.raises(ServerError) as ei:
                await c._post("/forward", {
                    "stage": 0, "session_id": "dl", "task_id": "t",
                    "payload": {"state": 0, "start_pos": 0, "real_len": 1},
                    "deadline_ms": (_time.time() - 5.0) * 1e3,  # spent
                })
        e = ei.value
        assert e.status == 408 and e.code == "deadline" and not e.retryable
        snap0 = nodes[0].metrics.snapshot()["counters"]
        snap1 = nodes[1].metrics.snapshot()["counters"]
        assert snap0.get("deadline.expired", 0) >= 1
        # the entry fast-failed: nothing was computed or relayed
        assert snap0.get("forward.requests", 0) == 0
        assert snap1.get("forward.requests", 0) == 0
        assert any(
            ev["type"] == "deadline.exceeded"
            for ev in nodes[0].journal.events()
        )
    finally:
        await _stop_all(nodes)


@pytest.mark.asyncio
async def test_deadline_expires_mid_chain_no_downstream_relay():
    """The budget dies DURING stage-0 work (chaos delay longer than the
    remaining deadline): the post-compute check fails the request with
    the typed 408 instead of relaying dead activations to stage 1."""
    import time as _time

    from inferd_tpu.client.base import ServerError

    nodes = [_mk_node(64 + i, i, 2, bootstrap_idx=64) for i in range(2)]
    nodes[0].chaos = Chaos(delay_ms=400)  # slower than the budget below
    await _start_all(nodes)
    try:
        async with SwarmClient([("127.0.0.1", BASE + 64)]) as c:
            with pytest.raises(ServerError) as ei:
                await c._post("/forward", {
                    "stage": 0, "session_id": "dm", "task_id": "t",
                    "payload": {"state": 0, "start_pos": 0, "real_len": 1},
                    "deadline_ms": (_time.time() + 0.15) * 1e3,
                })
        assert ei.value.status == 408 and ei.value.code == "deadline"
        # stage 0 DID compute (the budget died under it) ...
        assert nodes[0].metrics.snapshot()["counters"].get(
            "forward.requests", 0) >= 1
        # ... but nothing was relayed onward
        assert nodes[1].metrics.snapshot()["counters"].get(
            "forward.requests", 0) == 0
        evs = [
            ev for ev in nodes[0].journal.events()
            if ev["type"] == "deadline.exceeded"
        ]
        assert evs and evs[-1]["attrs"]["where"] == "post-compute"
    finally:
        await _stop_all(nodes)


@pytest.mark.asyncio
async def test_hedge_wins_when_primary_stalls():
    """Hedged relay end to end: the session's affinity replica slow-
    lorises (stall_p=1 — accepts, never answers), the hedge fires at the
    second-best ranked replica after hedge_delay_ms, the hedge's 200
    wins, the stalled primary is cancelled, and affinity repoints to the
    winner. hedge.fired/won counters + journal record it."""
    nodes = [_mk_node(67 + i, min(i, 1), 2, bootstrap_idx=67) for i in range(3)]
    # n1 and n2 are the stage-1 replica pair; n1 stalls forever
    nodes[1].chaos = Chaos(stall_p=1.0, seed=0)
    n0 = nodes[0]
    n0.hedge_mode = "any"  # counter backend is stateless: any replica works
    n0.hedge_delay_ms = 50.0
    await _start_all(nodes)
    try:
        import time as _time

        # pin the session's affinity to the stalled replica — the exact
        # "sick replica holds the session" shape hedging exists for
        n0._session_next[("hsess", 1)] = (nodes[1].info.node_id, _time.monotonic())
        env = {
            "task_id": "t", "session_id": "hsess", "stage": 1,
            "rescued": True,  # single bounce: the receiver serves locally
            "payload": {"state": 1, "start_pos": 5, "real_len": 1},
        }
        resp = await n0._relay(env, 1)
        assert resp.status == 200
        from inferd_tpu.runtime import wire as wirelib

        body = wirelib.unpack(bytes(resp.body))
        assert body["result_for_user"]["state"] == 2  # stage 1 computed
        counters = n0.metrics.snapshot()["counters"]
        assert counters.get("hedge.fired", 0) == 1
        assert counters.get("hedge.won", 0) == 1
        assert counters.get("hedge.cancelled", 0) == 0
        types = [ev["type"] for ev in n0.journal.events()]
        assert "hedge.fired" in types and "hedge.won" in types
        # affinity repointed to the winner for the session's next steps
        assert n0._session_next[("hsess", 1)][0] == nodes[2].info.node_id
        # extra-load ledger: 1 hedge against 1 primary, budget-tracked
        assert n0.hedge_budget.stats()["fired"] == 1
    finally:
        # the stalled handler sleeps ~forever: crash() skips the graceful
        # drain so teardown doesn't wait out aiohttp's shutdown timeout
        await nodes[1].crash()
        await _stop_all([nodes[0], nodes[2]])


@pytest.mark.asyncio
async def test_admission_shed_pool_watermark_and_retry_after():
    """Pool-aware admission (ROADMAP 2d): when the paged-KV block pool is
    under its reserve, NEW sessions shed with a typed 503 "busy" carrying
    a Retry-After hint — while mid-session chunks keep flowing (finishing
    them RELEASES capacity)."""
    from types import SimpleNamespace

    from inferd_tpu.client.base import ServerError

    nodes = [_mk_node(73, 0, 1, bootstrap_idx=73)]
    n0 = nodes[0]
    await _start_all(nodes)
    try:
        # duck-typed pool counters on the live executor: 2 free of 100
        # is under the 5% reserve
        n0.executor.pool = SimpleNamespace(num_blocks=100, blocks_free=2)
        async with SwarmClient([("127.0.0.1", BASE + 73)]) as c:
            with pytest.raises(ServerError) as ei:
                await c._post("/forward", {
                    "stage": 0, "session_id": "new", "task_id": "t",
                    "payload": {"state": 0, "start_pos": 0, "real_len": 1},
                })
            e = ei.value
            assert e.status == 503 and e.code == "busy"
            assert e.retry_after is not None and e.retry_after > 0
            assert e.retryable  # a shed is transient, not fatal
            # mid-session traffic is NOT shed (rescued skips the holder
            # bounce; the counter executor serves it)
            r = await c._post("/forward", {
                "stage": 0, "session_id": "old", "task_id": "t2",
                "rescued": True,
                "payload": {"state": 0, "start_pos": 3, "real_len": 1},
            })
            assert r["result_for_user"]["state"] == 1
        counters = n0.metrics.snapshot()["counters"]
        assert counters.get("admission.shed", 0) == 1
        assert any(
            ev["type"] == "admission.shed" and ev["attrs"]["code"] == "busy"
            for ev in n0.journal.events()
        )
    finally:
        await _stop_all(nodes)


@pytest.mark.asyncio
async def test_drain_hands_off_resident_session_token_exact(tiny_parts):  # noqa: F811
    """POST /drain mid-generation on the entry replica: residents hand
    off to the surviving stage-0 replica, the failed-over continuation
    rides the gossip session-location rescue, and the stream completes
    TOKEN-EXACT with no session restart. New sessions shed with the
    typed draining 503; gossip's draining flag excludes the node from
    ranked routing."""
    from inferd_tpu.client.base import ServerError
    from inferd_tpu.control.path_finder import ranked_nodes

    parts, params = tiny_parts
    # n0 + n1: stage-0 replica pair (n0 is the entry and will drain);
    # n2: stage 1
    nodes = [
        _mk_node(84, 0, 2, backend="qwen3", parts=parts, bootstrap_idx=84),
        _mk_node(85, 0, 2, backend="qwen3", parts=parts, bootstrap_idx=84),
        _mk_node(86, 1, 2, backend="qwen3", parts=parts, bootstrap_idx=84),
    ]
    await _start_all(nodes)
    try:
        engine = Engine(
            TINY, params, max_len=64,
            sampling_cfg=SamplingConfig(temperature=0.0),
        )
        prompt = [3, 7, 11, 19]
        expected = engine.generate(prompt, max_new_tokens=10)

        async with SwarmClient(
            [("127.0.0.1", BASE + 84)],
            sampling=SamplingConfig(temperature=0.0),
        ) as c:
            state = {}

            async def on_token(tok):
                if tok is None:
                    return  # restart marker: keep counting fresh tokens
                state.setdefault("toks", []).append(tok)
                if len(state["toks"]) == 3 and "drained" not in state:
                    # between steps (the hook is awaited inside the token
                    # loop): drain the entry while it holds the session
                    state["drained"] = await c._post(
                        "/drain", {"wait_s": 2.0}
                    )

            got = await c.generate_ids(
                prompt, max_new_tokens=10, session_retries=6,
                retry_delay_s=0.3, on_token=on_token,
            )
            assert got == expected  # token-exact across the drain
            drained = state["drained"]
            assert drained["ok"] and drained["draining"]
            assert drained["handed_off"] >= 1  # the resident session moved

            # new sessions shed at the draining entry with the typed 503
            with pytest.raises(ServerError) as ei:
                await c._post("/forward", {
                    "stage": 0, "session_id": "fresh", "task_id": "t",
                    "payload": {
                        "tokens": [[3]], "start_pos": 0, "real_len": 1,
                    },
                })
            assert ei.value.status == 503 and ei.value.code == "draining"
            assert ei.value.retry_after is not None

        # journal recorded the drain lifecycle
        types = [ev["type"] for ev in nodes[0].journal.events()]
        assert "node.draining" in types and "node.drained" in types
        # gossip carries the flag and ranked routing excludes the drainer
        stage0 = nodes[2].dht.get_stage(0)
        assert stage0[nodes[0].info.node_id].get("draining") == 1
        ranked = ranked_nodes(stage0)
        assert [nid for nid, _ in ranked] == [nodes[1].info.node_id]
    finally:
        await _stop_all(nodes)
