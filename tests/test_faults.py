"""Fault injection + failure recovery tests (SURVEY §5: the reference had
recovery *mechanisms* but no way to test them; here they're asserted):
chaos drop/delay, session-restart on node death, and the on-demand
jax.profiler endpoint."""

import asyncio
import glob
import os

import pytest

from inferd_tpu.client.swarm_client import SwarmClient
from inferd_tpu.config import TINY, SamplingConfig
from inferd_tpu.core.generate import Engine
from inferd_tpu.utils.chaos import Chaos, ChaosDrop

from test_node_e2e import BASE, _mk_node, _start_all, _stop_all, tiny_parts  # noqa: F401


def test_chaos_parse():
    c = Chaos.parse("drop=0.25,delay_ms=10,seed=3")
    assert c.drop == 0.25 and c.delay_ms == 10 and c.seed == 3
    assert Chaos.parse("") is None and Chaos.parse(None) is None
    with pytest.raises(ValueError):
        Chaos.parse("explode=1")


@pytest.mark.asyncio
async def test_chaos_drop_rate():
    c = Chaos(drop=0.5, seed=0)
    dropped = 0
    for _ in range(200):
        try:
            await c.before_forward()
        except ChaosDrop:
            dropped += 1
    assert 60 <= dropped <= 140  # ~50% of 200


@pytest.mark.asyncio
async def test_chaos_drop_surfaces_as_500():
    nodes = [_mk_node(70 + i, i, 2, bootstrap_idx=70) for i in range(2)]
    nodes[0].chaos = Chaos(drop=1.0)  # stage 0 drops everything
    await _start_all(nodes)
    try:
        async with SwarmClient([("127.0.0.1", BASE + 70)]) as c:
            with pytest.raises(RuntimeError, match="chaos drop"):
                await c._post(
                    "/forward", {"stage": 0, "session_id": "s", "payload": {}}
                )
        assert nodes[0].metrics.snapshot()["counters"]["chaos.dropped"] >= 1
    finally:
        await _stop_all(nodes)


@pytest.mark.asyncio
async def test_node_death_mid_generation_recovers(tiny_parts):  # noqa: F811
    """Kill the only stage-1 node mid-generation: its record TTLs out, the
    spare node adopts stage 1 (empty-stage recovery), and the client's
    session-restart retry completes the SAME tokens (greedy determinism)."""
    parts, params = tiny_parts
    # n0: stage 0.  n1: stage 1 (will die).  n2: spare replica on stage 0
    # that must migrate to stage 1 after the death.
    nodes = [
        _mk_node(80, 0, 2, backend="qwen3", parts=parts, bootstrap_idx=80),
        _mk_node(81, 1, 2, backend="qwen3", parts=parts, bootstrap_idx=80),
        _mk_node(82, 0, 2, backend="qwen3", parts=parts, bootstrap_idx=80),
    ]
    await _start_all(nodes)
    try:
        engine = Engine(TINY, params, max_len=64, sampling_cfg=SamplingConfig(temperature=0.0))
        prompt = [3, 7, 11, 19]
        expected = engine.generate(prompt, max_new_tokens=6)

        async with SwarmClient(
            [("127.0.0.1", BASE + 80)], sampling=SamplingConfig(temperature=0.0)
        ) as c:
            # healthy first pass
            assert await c.generate_ids(prompt, max_new_tokens=6) == expected

            # stage 1's only server hard-crashes: no tombstone gossip, no
            # graceful anything — peers must detect the death via record-TTL
            # expiry (1.5 s in these tests)
            n1 = nodes[1]
            await n1.crash()
            nodes.remove(n1)

            # generation must still complete: retries span the TTL window
            # (1.5 s in these tests) + adoption by a spare
            got = await c.generate_ids(
                prompt, max_new_tokens=6, session_retries=8, retry_delay_s=0.5
            )
            assert got == expected
            # someone now serves stage 1
            stage1 = nodes[0].dht.get_stage(1)
            assert stage1, "no node adopted the dead stage"
    finally:
        await _stop_all(nodes)


@pytest.mark.asyncio
async def test_profile_endpoint_writes_trace(tmp_path):
    nodes = [_mk_node(95, 0, 1, bootstrap_idx=95)]
    nodes[0].enable_profiling = True  # endpoint is opt-in (ADVICE r1)
    nodes[0].profiler.base_dir = str(tmp_path)  # confine traces to tmp
    await _start_all(nodes)
    try:
        async with SwarmClient([("127.0.0.1", BASE + 95)]) as c:
            d = str(tmp_path / "trace")
            r = await c._post("/profile", {"action": "start", "name": "trace"})
            assert r["ok"] and r["dir"] == d
            # the endpoint is not a write-anywhere primitive
            r2 = await c._post("/profile", {"action": "stop"})
            with pytest.raises(RuntimeError, match="escapes profile dir"):
                await c._post("/profile", {"action": "start", "name": "../evil"})
            with pytest.raises(RuntimeError, match="escapes profile dir"):
                await c._post("/profile", {"action": "start", "name": "/tmp/evil"})
            r = await c._post("/profile", {"action": "start", "name": "trace"})
            # double start -> 409
            with pytest.raises(RuntimeError, match="already running"):
                await c._post("/profile", {"action": "start"})
            # some jax work to capture
            await c._post("/forward", {"stage": 0, "session_id": "p", "payload": {}})
            r = await c._post("/profile", {"action": "stop"})
            assert r["ok"]
            files = glob.glob(os.path.join(d, "**", "*"), recursive=True)
            assert files, "profiler wrote nothing"
            # stop without start -> 409
            with pytest.raises(RuntimeError, match="no profile"):
                await c._post("/profile", {"action": "stop"})
            # gate: with profiling disabled the endpoint refuses outright
            nodes[0].enable_profiling = False
            with pytest.raises(RuntimeError, match="profiling disabled"):
                await c._post("/profile", {"action": "start"})
    finally:
        await _stop_all(nodes)


def test_server_error_retryability():
    from inferd_tpu.client.base import ServerError

    assert ServerError("x", 500).retryable  # transient node trouble
    assert ServerError("x", 502).retryable  # dead next hop
    assert ServerError("x", 409, code="session_state").retryable  # KV lost
    assert not ServerError("x", 409, code="overflow").retryable
    assert not ServerError("x", 409, code="wrong_stage").retryable
    assert not ServerError("x", 400).retryable  # malformed request
