"""The perf subsystem (inferd_tpu/perf/): roofline cost model, autotune
registry + dispatch integration, step-anatomy profiler, regression gate,
and the round-6 sampling fast path.

Hand-computed roofline expectations are derived INDEPENDENTLY here (byte
arithmetic written out per preset/mode, plus a ground-truth cross-check
against the actual init_params leaf bytes for the tiny preset) so a drift
in perf/roofline's accounting fails loudly instead of self-certifying.
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from inferd_tpu.config import PRESETS, SamplingConfig, get_config
from inferd_tpu.core import sampling as samplib
from inferd_tpu.perf import anatomy, autotune, gate as gatelib, roofline as rl
from inferd_tpu.perf.__main__ import main as perf_main

R05 = gatelib.DEFAULT_ARTIFACT


# ---------------------------------------------------------------------------
# roofline: hand-computed byte accounting
# ---------------------------------------------------------------------------


def _hand_linear(k, n, quant, dsize):
    """Independent re-derivation of stored linear bytes (duplicated on
    purpose — this is the change detector for the model's accounting)."""
    if quant == "none":
        return k * n * dsize
    if quant in ("int8", "w8a8", "int8-kernel"):
        return k * n + 4 * n
    assert quant == "int4"
    g = min(128, k)
    while k % g:
        g -= 1
    return (k // 2 * n if k % 2 == 0 else k * n) + 4 * (k // g) * n


def _hand_weight_bytes(cfg, quant):
    """Per-step weight read (attn + mlp + head + norms), dense configs."""
    h, d, L, i = cfg.hidden_size, cfg.head_dim, cfg.num_layers, cfg.intermediate_size
    qd, kvd = cfg.num_heads * d, cfg.num_kv_heads * d
    dsize = jnp.dtype(cfg.dtype).itemsize
    lin = sum(
        _hand_linear(k, n, quant, dsize)
        for k, n in [(h, qd), (h, kvd), (h, kvd), (qd, h),
                     (h, i), (h, i), (i, h)]
    ) * L
    norms = (L * (2 * h + (2 * d if cfg.qk_norm else 0)) + h) * dsize
    if cfg.attn_bias:
        norms += L * (qd + 2 * kvd) * dsize
    if cfg.tie_word_embeddings and quant == "none":
        head = h * cfg.vocab_size * dsize
    else:
        head = _hand_linear(h, cfg.vocab_size, quant, dsize)
    return lin + norms + head


@pytest.mark.parametrize("preset", ["qwen3-0.6b", "qwen3-8b", "qwen2-0.5b", "tiny"])
@pytest.mark.parametrize("quant", ["none", "int8", "int4"])
@pytest.mark.parametrize("kv_dtype", ["model", "float8_e4m3fn"])
def test_decode_step_cost_hand_computed(preset, quant, kv_dtype):
    cfg = get_config(preset)
    ctx = 1024
    c = rl.decode_step_cost(cfg, quant=quant, kv_dtype=kv_dtype, ctx=ctx)
    assert c.weight_bytes == _hand_weight_bytes(cfg, quant)
    kv_size = jnp.dtype(
        cfg.dtype if kv_dtype == "model" else kv_dtype
    ).itemsize
    kvd = cfg.num_kv_heads * cfg.head_dim
    assert c.kv_read_bytes == 2 * cfg.num_layers * ctx * kvd * kv_size
    assert c.kv_write_bytes == 2 * cfg.num_layers * kvd * kv_size
    assert c.embed_gather_bytes == cfg.hidden_size * jnp.dtype(cfg.dtype).itemsize
    # monotonicity: quantization and KV compression only shrink the step
    base = rl.decode_step_cost(cfg, ctx=ctx)
    assert c.read_bytes <= base.read_bytes


def test_quant_shrinks_bytes_strictly():
    cfg = get_config("qwen3-0.6b")
    none = rl.decode_step_cost(cfg).read_bytes
    i8 = rl.decode_step_cost(cfg, quant="int8").read_bytes
    i4 = rl.decode_step_cost(cfg, quant="int4").read_bytes
    assert i4 < i8 < none
    # fp8 KV halves the KV read at long context
    bf = rl.decode_step_cost(cfg, ctx=8192)
    f8 = rl.decode_step_cost(cfg, ctx=8192, kv_dtype="float8_e4m3fn")
    assert f8.kv_read_bytes * 2 == bf.kv_read_bytes


def test_tiny_bf16_read_matches_real_param_tree():
    """Ground truth: for a tied, unquantized model the per-step weight
    read equals the actual parameter tree's stored bytes (the embed table
    doubles as the unembed read), within the embed-gather rounding."""
    from inferd_tpu.models import qwen3

    cfg = get_config("tiny")
    params = qwen3.init_params(cfg, jax.random.PRNGKey(0))
    leaf_bytes = sum(x.size * x.dtype.itemsize for x in jax.tree.leaves(params))
    c = rl.decode_step_cost(cfg)
    assert c.weight_bytes == leaf_bytes


def test_moe_counts_active_experts_only():
    cfg = get_config("tiny-moe")
    c = rl.decode_step_cost(cfg)
    dsize = jnp.dtype(cfg.dtype).itemsize
    h, mi = cfg.hidden_size, cfg.moe_intermediate_size
    per_expert = 3 * h * mi * dsize
    router = h * cfg.num_experts * dsize
    assert c.mlp_weight_bytes == cfg.num_layers * (
        router + cfg.num_experts_per_tok * per_expert
    )


def test_roofline_reproduces_round5_decode_frac():
    """Acceptance: the analytic floor reproduces the committed round-5
    decode leg's hbm_roofline_frac 0.114 within +-10% (and the ctx8k /
    fp8-KV legs' recorded fracs too)."""
    cfg = get_config("qwen3-0.6b")
    chip = rl.get_chip("v5e")
    for kwargs, measured, recorded in [
        (dict(), 78.19, 0.114),
        (dict(ctx=8192), 35.17, 0.092),
        (dict(ctx=8192, kv_dtype="float8_e4m3fn"), 35.62, 0.072),
    ]:
        frac = rl.roofline_frac(measured, rl.decode_step_cost(cfg, **kwargs), chip)
        assert abs(frac - recorded) <= 0.10 * recorded, (kwargs, frac, recorded)


def test_report_cli_prints_table_and_rederivation(capsys):
    assert perf_main(["report", "--preset", "qwen3-0.6b"]) == 0
    out = capsys.readouterr().out
    assert "ceiling tok/s" in out and "int4" in out
    if os.path.exists(R05):
        import re

        m = re.search(r"decode: measured 78\.19 .* frac (0\.\d+)", out)
        assert m, out
        assert abs(float(m.group(1)) - 0.114) <= 0.0114


def test_chip_table_and_detect():
    assert rl.get_chip("v5e").hbm_gbps == 819.0
    with pytest.raises(KeyError):
        rl.get_chip("v99")
    assert rl.detect_chip().key == "cpu"  # tests run on CPU


# ---------------------------------------------------------------------------
# autotune registry
# ---------------------------------------------------------------------------


@pytest.fixture
def reg_path(tmp_path, monkeypatch):
    p = str(tmp_path / "autotune.json")
    monkeypatch.setenv("INFERD_AUTOTUNE", p)
    autotune.reset()
    yield p
    autotune.reset()


def test_registry_round_trip(reg_path):
    reg = autotune.get_registry()
    assert not reg.entries
    key = autotune.attn_key("v5e", 1, 1, 8192, 16, 8, 128, "bfloat16", False)
    reg.record(key, "xla", {"xla": 2656.0, "stream": 1780.0}, source="test")
    reg.record(autotune.int4_key("v5e"), "grouped", source="test")
    assert reg.save() == reg_path
    fresh = autotune.Registry.load(reg_path)
    assert fresh.winner(key, ("flash", "xla")) == "xla"
    assert fresh.winner(autotune.int4_key("v5e"), ("grouped", "dequant")) == "grouped"
    assert fresh.lookup(key)["rates"]["xla"] == 2656.0


def test_registry_corrupt_file_is_cold_not_fatal(reg_path, capsys):
    with open(reg_path, "w") as f:
        f.write("{not json at all")
    autotune.reset()
    reg = autotune.get_registry()
    assert reg.corrupt and not reg.entries
    assert autotune.attn_winner(get_config("tiny"), 8192) is None
    # save() rewrites the corrupt file whole and it loads clean after
    reg.record(autotune.int4_key("cpu"), "dequant")
    reg.save()
    assert not autotune.Registry.load(reg_path).corrupt


def test_registry_rejects_wrong_schema(reg_path):
    with open(reg_path, "w") as f:
        json.dump({"version": 999, "entries": {}}, f)
    autotune.reset()
    assert autotune.get_registry().corrupt


def test_registry_out_of_vocab_winner_treated_cold(reg_path):
    reg = autotune.get_registry()
    reg.record(autotune.int4_key("cpu"), "warp-drive")
    reg.save()
    autotune.reset()
    assert autotune.int4_winner("cpu") is None


def _frozen_flash_heuristic(cfg, kv_buf_len, compressed, q_len, batch, on_tpu):
    """The pre-registry `auto` rule, restated independently."""
    if compressed or not on_tpu:
        return False
    return 4 * batch * cfg.num_heads * q_len * kv_buf_len > 256 * 1024 * 1024


@pytest.mark.parametrize("on_tpu", [False, True])
def test_flash_enabled_cold_matches_frozen_heuristic(reg_path, monkeypatch, on_tpu):
    """Acceptance: with a COLD registry the `auto` dispatch is bit-for-bit
    the frozen heuristic, on every shape in a grid spanning both sides of
    the score budget."""
    from inferd_tpu.ops import attention as att

    monkeypatch.setattr(att, "is_tpu", lambda: on_tpu)
    cfg = get_config("qwen3-0.6b")  # attn_impl == "auto"
    for t in (2048, 8192, 65536, 1 << 20):
        for q_len in (1, 512, 4096):
            for compressed in (False, True):
                got = att.flash_enabled(
                    cfg, t, compressed_kv=compressed, q_len=q_len, batch=1
                )
                want = _frozen_flash_heuristic(
                    cfg, t, compressed, q_len, 1, on_tpu
                )
                assert got == want, (t, q_len, compressed, on_tpu)


def test_flash_enabled_consults_populated_registry(reg_path, monkeypatch):
    """A populated entry overrides the heuristic in BOTH directions —
    including the compressed-KV caution (the fp8-KV kernel enablement
    VERDICT r05 item 4 asks for) — and only for its own shape bucket."""
    from inferd_tpu.ops import attention as att

    cfg = get_config("qwen3-0.6b")
    reg = autotune.get_registry()
    # chip is "cpu" under tests; record a flash win at t=8192 decode,
    # compressed KV — the frozen rule would refuse both (cpu + compressed)
    reg.record(
        autotune.attn_key("cpu", 1, 1, 8192, cfg.num_heads, cfg.num_kv_heads,
                          cfg.head_dim, cfg.dtype, True),
        "flash",
    )
    # and an explicit xla win at a huge-prefill shape where the patched-TPU
    # heuristic would pick the kernel
    reg.record(
        autotune.attn_key("cpu", 1, 4096, 1 << 20, cfg.num_heads,
                          cfg.num_kv_heads, cfg.head_dim, cfg.dtype, False),
        "xla",
    )
    reg.save()
    autotune.reset()
    assert att.flash_enabled(cfg, 8192, compressed_kv=True, q_len=1, batch=1)
    monkeypatch.setattr(att, "is_tpu", lambda: True)
    assert not att.flash_enabled(
        cfg, 1 << 20, compressed_kv=False, q_len=4096, batch=1
    )
    # a different bucket stays on the heuristic (uncontaminated)
    assert not att.flash_enabled(cfg, 2048, compressed_kv=False, q_len=1, batch=1)
    # FORCE_FLASH and explicit impls still outrank the registry
    monkeypatch.setattr(att, "FORCE_FLASH", False)
    assert not att.flash_enabled(cfg, 8192, compressed_kv=True, q_len=1, batch=1)


def test_int4_mode_cold_and_populated(reg_path):
    from inferd_tpu.ops import quant

    assert quant.INT4_MODE == "auto"
    assert quant._int4_mode() == "grouped"  # cold CPU default, bit-for-bit
    reg = autotune.get_registry()
    reg.record(autotune.int4_key("cpu"), "dequant", source="test")
    reg.save()
    autotune.reset()
    assert quant._int4_mode() == "dequant"
    # explicit INT4_MODE still outranks the registry
    old = quant.INT4_MODE
    try:
        quant.INT4_MODE = "grouped"
        assert quant._int4_mode() == "grouped"
    finally:
        quant.INT4_MODE = old


def test_sweep_attn_populates_registry(reg_path, monkeypatch):
    """tools/sweep_attn --populate records winners the dispatch can read
    back (tiny shapes via a monkeypatched shape list, CPU interpreter)."""
    from inferd_tpu.tools import sweep_attn

    monkeypatch.setattr(
        sweep_attn, "shapes", lambda: iter([("decode", 1, 256, 3)])
    )
    monkeypatch.setattr("sys.argv", ["sweep_attn", "--populate"])
    sweep_attn.main()
    autotune.reset()
    reg = autotune.get_registry()
    assert any(k.startswith("attn|cpu|") for k in reg.entries), reg.entries
    (key,) = [k for k in reg.entries if k.startswith("attn|cpu|")]
    assert reg.entries[key]["winner"] in ("flash", "xla")
    assert reg.entries[key]["rates"]


# ---------------------------------------------------------------------------
# anatomy
# ---------------------------------------------------------------------------


def test_anatomy_phases_sum_to_whole_step():
    out = anatomy.profile_step(
        get_config("tiny"), ctx=64, pairs=2, short=3, long_=9
    )
    assert set(out["phases"]) == set(anatomy.PHASES)
    for name, p in out["phases"].items():
        if name == "dispatch":
            # host-loop overhead delta: clamped at 0 (can measure ~0 on a
            # fast local device), with the raw host-loop rate alongside
            assert p["ms"] >= 0 and p["hostloop_step_ms"] > 0
            continue
        assert p["ms"] > 0, name
        assert p["roofline_ms"] <= p["ms"] * 50  # sane attribution scale
    assert out["step_ms"] > 0
    # separately-jitted phases lose cross-phase fusion, so demand the sum
    # lands within a loose band of the fused step, not equality
    ratio = out["phase_sum_ms"] / out["step_ms"]
    assert 0.2 <= ratio <= 5.0, out
    assert out["unattributed_ms"] == pytest.approx(
        out["step_ms"] - out["phase_sum_ms"], abs=1e-6
    )


def test_anatomy_cli_emits_one_json_line(capsys):
    rc = perf_main([
        "anatomy", "--preset", "tiny", "--ctx", "32", "--pairs", "2",
        "--device", "cpu",
    ])
    assert rc == 0
    last = capsys.readouterr().out.strip().splitlines()[-1]
    obj = json.loads(last)
    assert obj["preset"] == "tiny" and "phases" in obj


# ---------------------------------------------------------------------------
# gate
# ---------------------------------------------------------------------------


def _battery_line(leg, result):
    return json.dumps({"leg": leg, "ts": "t", "argv": [], "rc": 0,
                       "result": result})


def _good_leg(**over):
    base = {
        "metric": "qwen3_0.6b_decode_tok_per_s_bs1",
        "value": 78.19, "unit": "tok/s", "e2e_tok_per_s": 60.0,
        "steady_timing_valid": True, "steady_spread_pt": 3.0,
        "timing_methodology": "interleaved-paired",
        "hbm_roofline_frac": 0.114, "device": "tpu",
    }
    base.update(over)
    return base


def test_gate_passes_committed_round5_artifacts():
    assert os.path.exists(R05), "committed round-5 battery artifact missing"
    findings, ok = gatelib.gate(R05)
    assert ok, [f.line() for f in findings]
    # the known round-5 inversion IS flagged — as an advisory warning
    assert any(
        f.check == "ordering" and f.leg == "decode" and f.severity == "warning"
        for f in findings
    )


def test_gate_fails_on_steady_e2e_inversion(tmp_path):
    """Acceptance: a new-methodology leg with steady < e2e (tok/s) fails."""
    art = tmp_path / "bad.jsonl"
    art.write_text(_battery_line(
        "decode", _good_leg(value=78.19, e2e_tok_per_s=119.07)
    ) + "\n")
    findings, ok = gatelib.gate(str(art))
    assert not ok
    assert any(f.check == "ordering" and f.severity == "error" for f in findings)
    # the same inversion WITHOUT the new-methodology marker is advisory
    legacy = dict(_good_leg(value=78.19, e2e_tok_per_s=119.07))
    legacy.pop("steady_spread_pt")
    legacy.pop("timing_methodology")
    art2 = tmp_path / "legacy.jsonl"
    art2.write_text(_battery_line("decode", legacy) + "\n")
    findings, ok = gatelib.gate(str(art2))
    assert ok
    assert any(f.check == "ordering" and f.severity == "warning" for f in findings)


def test_gate_swarm_agg_ordering(tmp_path):
    """swarm co-batching invariant: concurrent aggregate < serial baseline
    is an ERROR (the window/coalescing machinery regressed below
    one-session-at-a-time); >= serial passes. Also gates the committed
    round-6 swarm artifact."""
    leg = {
        "metric": "tiny_swarm_agg_tok_per_s", "value": 8.0,
        "unit": "tok/s", "serial_tok_per_s": 10.0, "sessions": 8,
    }
    art = tmp_path / "swarm.jsonl"
    art.write_text(_battery_line("swarm_agg", leg) + "\n")
    findings, ok = gatelib.gate(str(art))
    assert not ok
    assert any(
        f.check == "ordering" and f.severity == "error" and "serial" in f.message
        for f in findings
    )
    leg["value"] = 40.0
    art.write_text(_battery_line("swarm_agg", leg) + "\n")
    findings, ok = gatelib.gate(str(art))
    assert ok, [f.line() for f in findings]
    committed = os.path.join(
        os.path.dirname(R05), "BENCH_swarm_r06.json"
    )
    assert os.path.exists(committed), "committed swarm_agg artifact missing"
    findings, ok = gatelib.gate(committed)
    assert ok, [f.line() for f in findings]


def test_gate_fails_on_roofline_regression(tmp_path):
    prior = tmp_path / "prior.jsonl"
    cur = tmp_path / "cur.jsonl"
    prior.write_text(_battery_line("decode", _good_leg()) + "\n")
    cur.write_text(_battery_line(
        "decode", _good_leg(value=50.0, e2e_tok_per_s=40.0,
                            hbm_roofline_frac=0.073)
    ) + "\n")
    findings, ok = gatelib.gate(str(cur), str(prior))
    assert not ok
    assert any(f.check == "regression" for f in findings)
    # a <20% dip passes
    cur.write_text(_battery_line(
        "decode", _good_leg(value=70.0, e2e_tok_per_s=60.0,
                            hbm_roofline_frac=0.102)
    ) + "\n")
    findings, ok = gatelib.gate(str(cur), str(prior))
    assert ok, [f.line() for f in findings]


def test_gate_regression_not_fooled_by_accounting_change(tmp_path):
    """An r05-accounting prior (no methodology marker, frac 0.06) vs an
    r06 leg at the SAME measured tok/s (frac 0.039 under the new model)
    is NOT a regression — cross-generation pairs compare raw values."""
    prior_leg = {
        "metric": "qwen3_0.6b_decode_tok_per_s_bs1_int8",
        "value": 53.94, "unit": "tok/s", "e2e_tok_per_s": 50.0,
        "steady_timing_valid": True, "hbm_roofline_frac": 0.06,
        "device": "tpu",
    }
    cur_leg = _good_leg(
        metric="qwen3_0.6b_decode_tok_per_s_bs1_int8",
        value=53.94, e2e_tok_per_s=50.0, hbm_roofline_frac=0.039,
    )
    prior = tmp_path / "r05.jsonl"
    cur = tmp_path / "r06.jsonl"
    prior.write_text(_battery_line("decode_int8", prior_leg) + "\n")
    cur.write_text(_battery_line("decode_int8", cur_leg) + "\n")
    findings, ok = gatelib.gate(str(cur), str(prior))
    assert ok, [f.line() for f in findings]
    # but a real tok/s drop across generations still fails
    cur.write_text(_battery_line(
        "decode_int8",
        _good_leg(metric="qwen3_0.6b_decode_tok_per_s_bs1_int8",
                  value=40.0, e2e_tok_per_s=35.0, hbm_roofline_frac=0.029),
    ) + "\n")
    findings, ok = gatelib.gate(str(cur), str(prior))
    assert not ok
    assert any(f.check == "regression" for f in findings)


def test_gate_fails_on_impossible_fraction(tmp_path):
    art = tmp_path / "impossible.jsonl"
    art.write_text(_battery_line(
        "decode", _good_leg(value=5000.0, e2e_tok_per_s=4000.0,
                            hbm_roofline_frac=7.3)
    ) + "\n")
    findings, ok = gatelib.gate(str(art))
    assert not ok
    assert any(f.check == "physics" and f.severity == "error" for f in findings)


def test_gate_cli_exit_codes(tmp_path, capsys):
    art = tmp_path / "ok.jsonl"
    art.write_text(_battery_line("decode", _good_leg()) + "\n")
    assert perf_main(["check", "--artifact", str(art)]) == 0
    assert "PASS" in capsys.readouterr().out
    bad = tmp_path / "bad.jsonl"
    bad.write_text(_battery_line(
        "decode", _good_leg(value=78.19, e2e_tok_per_s=119.07)
    ) + "\n")
    assert perf_main(["check", "--artifact", str(bad), "--json"]) == 1
    obj = json.loads(capsys.readouterr().out)
    assert obj["ok"] is False and obj["findings"]


def test_gate_tolerates_truncated_artifact_line(tmp_path):
    """A battery killed mid-append leaves a truncated final line; the
    intact legs must still be checked (warning, not a crash)."""
    art = tmp_path / "truncated.jsonl"
    art.write_text(
        _battery_line("decode", _good_leg()) + "\n"
        + '{"leg": "decode_int8", "result": {"metr'
    )
    findings, ok = gatelib.gate(str(art))
    assert ok
    assert any(
        f.check == "artifact" and "unparseable" in f.message for f in findings
    )


def test_gate_uses_per_leg_roofline_chip(tmp_path):
    """A leg recorded against a faster chip must be re-derived against
    THAT chip — a correct v5p measurement above the v5e ceiling is not a
    physics error."""
    cfg = get_config("qwen3-0.6b")
    v5p_ceiling = rl.roofline(rl.decode_step_cost(cfg), rl.get_chip("v5p")).ceiling_tok_s
    value = round(v5p_ceiling * 0.5, 2)  # 50% of v5p > 100% of v5e
    leg = _good_leg(value=value, e2e_tok_per_s=value * 0.8,
                    hbm_roofline_frac=0.5, roofline_chip="v5p")
    art = tmp_path / "v5p.jsonl"
    art.write_text(_battery_line("decode", leg) + "\n")
    findings, ok = gatelib.gate(str(art))  # default --chip v5e
    assert ok, [f.line() for f in findings]
    # without the chip stamp the same leg IS flagged (legacy behavior)
    leg2 = dict(leg)
    leg2.pop("roofline_chip")
    art.write_text(_battery_line("decode", leg2) + "\n")
    findings, ok = gatelib.gate(str(art))
    assert not ok


def test_parse_decode_metric_variants():
    cfg, quant, kv, ctx = gatelib.parse_decode_metric(
        "qwen3_0.6b_decode_tok_per_s_bs1_ctx8192_kv-float8_e4m3fn"
    )
    assert cfg.name == "qwen3-0.6b" and kv == "float8_e4m3fn" and ctx == 8192
    assert quant == "none"
    cfg, quant, kv, ctx = gatelib.parse_decode_metric(
        "qwen3_8b_decode_tok_per_s_bs1_int8-kernel"
    )
    assert cfg.name == "qwen3-8b" and quant == "int8-kernel" and ctx == 0
    assert gatelib.parse_decode_metric("flash_gqa_decode_t8192_calls_per_s") is None
    assert gatelib.parse_decode_metric("nonexistent_decode_tok_per_s_bs1") is None


# ---------------------------------------------------------------------------
# sampling fast path (satellite: greedy / temperature-only skip the
# full-vocab warp chain; HF-parity regression)
# ---------------------------------------------------------------------------


def test_passthrough_predicate():
    V = 151936
    assert samplib.passthrough_filters(0, 1.0, 0.0, V)
    assert samplib.passthrough_filters(V, 1.0, 0.0, V)  # top_k >= vocab
    assert not samplib.passthrough_filters(20, 1.0, 0.0, V)
    assert not samplib.passthrough_filters(0, 0.95, 0.0, V)
    assert not samplib.passthrough_filters(0, 1.0, 0.1, V)


def test_temperature_only_sample_parity_with_full_chain():
    """The fast path must draw BIT-IDENTICAL tokens to the full warp
    chain (whose filters are all identity for this config)."""
    key = jax.random.PRNGKey(7)
    logits = jax.random.normal(jax.random.PRNGKey(1), (4, 512), jnp.float32)
    fast = samplib.sample(logits, key, temperature=0.8, top_k=0, top_p=1.0)
    slow = jax.random.categorical(
        key,
        samplib.min_p_filter(
            samplib.top_p_filter(
                samplib.top_k_filter(logits / jnp.float32(0.8), 0), 1.0
            ),
            0.0,
        ),
        axis=-1,
    )
    np.testing.assert_array_equal(np.asarray(fast), np.asarray(slow))


def test_greedy_sample_is_argmax():
    logits = jax.random.normal(jax.random.PRNGKey(2), (3, 257), jnp.float32)
    tok = samplib.sample(logits, jax.random.PRNGKey(0), temperature=0.0)
    np.testing.assert_array_equal(
        np.asarray(tok), np.asarray(jnp.argmax(logits, axis=-1))
    )


def test_warped_logits_greedy_is_point_mass():
    """temperature == 0 used to divide by zero (NaN softmax); it must be
    the argmax point mass — the distribution greedy `sample` draws from."""
    logits = jax.random.normal(jax.random.PRNGKey(3), (2, 64), jnp.float32)
    probs = samplib.warped_probs(logits, SamplingConfig(temperature=0.0))
    p = np.asarray(probs)
    assert np.isfinite(p).all()
    np.testing.assert_allclose(p.sum(-1), 1.0, rtol=1e-6)
    np.testing.assert_array_equal(
        p.argmax(-1), np.asarray(jnp.argmax(logits, -1))
    )
    assert (p.max(-1) > 0.999).all()


def test_warped_logits_temperature_only_is_scaled_identity():
    logits = jax.random.normal(jax.random.PRNGKey(4), (2, 64), jnp.float32)
    out = samplib.warped_logits(logits, 0.7, 0, 1.0, 0.0)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(logits / jnp.float32(0.7)), rtol=1e-6
    )


def test_sampled_path_unchanged_with_filters_active():
    """Regression guard: the non-passthrough path (top-k active) still
    matches the scatter-free candidate draw it had before this change."""
    key = jax.random.PRNGKey(9)
    logits = jax.random.normal(jax.random.PRNGKey(5), (4, 512), jnp.float32)
    got = samplib.sample(logits, key, temperature=0.6, top_k=20, top_p=0.95)
    scaled = logits / jnp.float32(0.6)
    vals, idx = jax.lax.top_k(scaled, 20)
    vals = samplib.min_p_filter(samplib.top_p_filter(vals, 0.95), 0.0)
    choice = jax.random.categorical(key, vals, axis=-1)
    want = jnp.take_along_axis(idx, choice[:, None], axis=-1)[:, 0]
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


# ---------------------------------------------------------------------------
# battery integration (CPU stand-ins for the round-6 hardware legs)
# ---------------------------------------------------------------------------


def test_battery_has_round6_legs():
    from inferd_tpu.tools.bench_battery import DEFAULT_LEGS, SMOKE_LEGS

    names = {n for n, _, _ in DEFAULT_LEGS}
    assert {"decode_8b_int8", "anatomy", "anatomy_ctx8k"} <= names
    tail = dict((n, t) for n, t, _ in DEFAULT_LEGS)["decode_8b_int8"]
    assert "--model" in tail and "qwen3-8b" in tail and "int8" in tail
    assert {"decode_tiny_int8", "anatomy_tiny"} <= {n for n, _, _ in SMOKE_LEGS}


@pytest.mark.slow
def test_battery_smoke_runs_int8_and_anatomy_legs(tmp_path):
    """Dryrun the two new battery legs end to end on CPU: the artifact
    lines must carry an int8 decode result and an anatomy phase table."""
    from inferd_tpu.tools.bench_battery import main

    out = tmp_path / "smoke.jsonl"
    rc = main(["--smoke", "--legs", "decode_tiny_int8,anatomy_tiny",
               "--out", str(out)])
    assert rc == 0
    lines = [json.loads(l) for l in out.read_text().splitlines()]
    by_leg = {l["leg"]: l for l in lines}
    dec = by_leg["decode_tiny_int8"]["result"]
    assert dec["metric"].endswith("_int8") and dec["quant"] == "int8"
    assert dec["timing_methodology"] == "interleaved-paired"
    ana = by_leg["anatomy_tiny"]["result"]
    assert set(ana["phases"]) == set(anatomy.PHASES)


# ---------------------------------------------------------------------------
# round 7: multi-step fused decode evidence (gate + anatomy + battery)
# ---------------------------------------------------------------------------

MULTISTEP_ARTIFACT = os.path.join(
    os.path.dirname(R05), "BENCH_multistep_cpu_r07.json"
)


def _multistep_leg(**over):
    base = {
        "metric": "tiny_decode_multistep_tok_per_s_bs1",
        "value": 1200.0, "unit": "tok/s",
        "per_k": {"1": 400.0, "4": 900.0, "8": 1200.0},
        "k_best": "8", "speedup_best_vs_k1": 3.0,
        "token_exact": True, "steady_timing_valid": True,
        "timing_methodology": "interleaved-paired", "device": "cpu",
    }
    base.update(over)
    return base


def test_gate_multistep_ordering(tmp_path):
    """decode_multistep's claim is CI-enforced: when every K>1 rate falls
    below K=1 (fused inner loop slower than per-token dispatch) the gate
    hard-errors; a single lagging K is advisory."""
    art = tmp_path / "ms.jsonl"
    art.write_text(_battery_line("decode_multistep", _multistep_leg(
        per_k={"1": 1000.0, "4": 500.0, "8": 700.0}
    )) + "\n")
    findings, ok = gatelib.gate(str(art))
    assert not ok
    assert any(
        f.check == "ordering" and f.severity == "error"
        and "K-step" in f.message
        for f in findings
    )
    # one K below base but the best K above: warning only
    art.write_text(_battery_line("decode_multistep", _multistep_leg(
        per_k={"1": 1000.0, "4": 500.0, "8": 1400.0}
    )) + "\n")
    findings, ok = gatelib.gate(str(art))
    assert ok, [f.line() for f in findings]
    assert any(
        f.check == "ordering" and f.severity == "warning" for f in findings
    )


def test_gate_multistep_token_exact_failure_is_hard(tmp_path):
    """A leg that measured token_exact=False is a CORRECTNESS regression,
    not an advisory hiccup: the gate hard-errors (run.sh step 0b2 is
    documented HARD and must not pass a divergent K-step stream). An
    errored leg WITHOUT a token-exactness verdict stays advisory."""
    art = tmp_path / "ms.jsonl"
    art.write_text(_battery_line("decode_multistep", _multistep_leg(
        token_exact=False,
        error="K>1 greedy stream diverged from the K=1 loop",
    )) + "\n")
    findings, ok = gatelib.gate(str(art))
    assert not ok
    assert any(
        f.check == "artifact" and f.severity == "error" for f in findings
    )
    # plain environmental error (no exactness verdict): advisory
    leg = _multistep_leg(error="no TPU on this box")
    del leg["token_exact"]
    art.write_text(_battery_line("decode_multistep", leg) + "\n")
    findings, ok = gatelib.gate(str(art))
    assert ok, [f.line() for f in findings]
    assert any(
        f.check == "artifact" and f.severity == "warning" for f in findings
    )


def test_gate_multistep_speedup_regression(tmp_path):
    """The committed K-speedup prior gates regressions on the
    DIMENSIONLESS ratio (machine-portable), not raw tok/s: a fresh
    artifact on a slower box with the same speedup passes; a collapsed
    speedup fails."""
    prior = tmp_path / "prior.jsonl"
    prior.write_text(_battery_line(
        "decode_multistep", _multistep_leg(speedup_best_vs_k1=3.0)
    ) + "\n")
    # slower box, same amortization ratio: PASS
    cur = tmp_path / "cur.jsonl"
    cur.write_text(_battery_line("decode_multistep", _multistep_leg(
        value=120.0, per_k={"1": 40.0, "4": 90.0, "8": 120.0},
        speedup_best_vs_k1=3.0,
    )) + "\n")
    findings, ok = gatelib.gate(str(cur), str(prior))
    assert ok, [f.line() for f in findings]
    # collapsed amortization: FAIL
    cur.write_text(_battery_line("decode_multistep", _multistep_leg(
        value=420.0, per_k={"1": 400.0, "4": 410.0, "8": 420.0},
        speedup_best_vs_k1=1.05,
    )) + "\n")
    findings, ok = gatelib.gate(str(cur), str(prior))
    assert not ok
    assert any(
        f.check == "regression" and "speedup_best_vs_k1" in f.message
        for f in findings
    )
    # a multistep pair missing the ratio on either side must SKIP the
    # regression compare, not fall back to raw tok/s (cross-host false
    # fail): slower box, no K=1 in the sweep -> no finding
    cur.write_text(_battery_line("decode_multistep", _multistep_leg(
        value=120.0, per_k={"4": 90.0, "8": 120.0},
        speedup_best_vs_k1=None,
    )) + "\n")
    findings, ok = gatelib.gate(str(cur), str(prior))
    assert ok, [f.line() for f in findings]
    assert not any(f.check == "regression" for f in findings)


def test_gate_passes_committed_multistep_artifact():
    """The committed CPU-proxy artifact (the raised prior this round's
    win is pinned to) must itself pass the gate, and must actually claim
    a >= 1.3x K-speedup (the round-7 acceptance bar)."""
    assert os.path.exists(MULTISTEP_ARTIFACT), "committed multistep artifact missing"
    findings, ok = gatelib.gate(MULTISTEP_ARTIFACT)
    assert ok, [f.line() for f in findings]
    legs = gatelib.load_artifact(MULTISTEP_ARTIFACT)
    res = dict(legs)["tiny_decode_multistep_tok_per_s_bs1"]
    assert res["token_exact"] is True
    assert res["speedup_best_vs_k1"] >= 1.3
    base = res["per_k"]["1"]
    assert any(
        v >= 1.3 * base for kk, v in res["per_k"].items() if kk != "1"
    )


def test_anatomy_dispatch_phase_subset():
    """--phases dispatch isolates the host-loop overhead phase: the fused
    step is still timed (it anchors the delta), device phases are
    skipped, and the dispatch entry carries the host-loop rate."""
    out = anatomy.profile_step(
        get_config("tiny"), ctx=32, pairs=2, short=3, long_=6,
        phases=("dispatch",),
    )
    assert set(out["phases"]) == {"dispatch"}
    d = out["phases"]["dispatch"]
    assert d["ms"] >= 0 and d["hostloop_step_ms"] > 0 and d["bytes"] == 0
    assert out["step_ms"] > 0
    # an incomplete device-phase set must not misreport the whole step as
    # unattributed residual: the reconciliation fields go null
    assert out["phase_sum_ms"] is None
    assert out["unattributed_ms"] is None
    with pytest.raises(ValueError, match="unknown anatomy phases"):
        anatomy.profile_step(get_config("tiny"), phases=("nope",))


def test_battery_has_round7_legs():
    from inferd_tpu.tools.bench_battery import DEFAULT_LEGS, SMOKE_LEGS

    names = {n for n, _, _ in DEFAULT_LEGS}
    assert {"decode_multistep", "anatomy_dispatch"} <= names
    smoke = dict((n, t) for n, t, _ in SMOKE_LEGS)
    assert "decode_multistep_tiny" in smoke
    assert "--config" in smoke["decode_multistep_tiny"]
    assert "decode-multistep" in smoke["decode_multistep_tiny"]
    assert "anatomy_dispatch_tiny" in smoke
    assert "dispatch" in smoke["anatomy_dispatch_tiny"]


# ---------------------------------------------------------------------------
# round 8: paged-KV mixed-workload gate (swarm-mixed ordering + ratio prior)
# ---------------------------------------------------------------------------

PAGED_ARTIFACT = os.path.join(
    os.path.dirname(R05), "BENCH_paged_cpu_r08.json"
)


def _mixed_leg(**over):
    base = {
        "metric": "tiny_swarm_mixed_tok_per_s",
        "value": 110.0, "unit": "tok/s",
        "vs_baseline": 1.6, "paged_vs_dense": 1.6,
        "dense_tok_per_s": 68.0, "sessions": 4, "waves": 2,
        "prefix_tokens": 192, "block_size": 16,
        "token_exact": True, "device": "cpu",
    }
    base.update(over)
    return base


def test_gate_swarm_mixed_ordering(tmp_path):
    """The paged-vs-dense ordering is CI-enforced: a paged aggregate
    below dense on the same cluster hard-errors (the block pool must WIN
    on the mixed-length shared-prefix workload it exists for)."""
    art = tmp_path / "mx.jsonl"
    art.write_text(_battery_line("swarm_mixed", _mixed_leg(
        value=60.0, dense_tok_per_s=68.0, paged_vs_dense=0.88,
        vs_baseline=0.88,
    )) + "\n")
    findings, ok = gatelib.gate(str(art))
    assert not ok
    assert any(
        f.check == "ordering" and f.severity == "error"
        and "dense" in f.message
        for f in findings
    )
    art.write_text(_battery_line("swarm_mixed", _mixed_leg()) + "\n")
    findings, ok = gatelib.gate(str(art))
    assert ok, [f.line() for f in findings]


def test_gate_swarm_mixed_token_exact_hard(tmp_path):
    """A divergent paged stream is a correctness regression, errored leg
    or not: token_exact=False hard-fails even when the leg 'succeeded'."""
    art = tmp_path / "mx.jsonl"
    art.write_text(_battery_line("swarm_mixed", _mixed_leg(
        token_exact=False,
    )) + "\n")
    findings, ok = gatelib.gate(str(art))
    assert not ok
    assert any(
        f.check == "artifact" and f.severity == "error"
        and "token_exact" in f.message
        for f in findings
    )


def test_gate_swarm_mixed_ratio_regression(tmp_path):
    """The committed prior regresses on the DIMENSIONLESS paged/dense
    ratio (machine-portable), never raw tok/s; a pair missing the ratio
    on either side SKIPS instead of false-failing cross-host."""
    prior = tmp_path / "prior.jsonl"
    prior.write_text(_battery_line("swarm_mixed", _mixed_leg()) + "\n")
    # slower box, same dedupe ratio: PASS
    cur = tmp_path / "cur.jsonl"
    cur.write_text(_battery_line("swarm_mixed", _mixed_leg(
        value=11.0, dense_tok_per_s=6.8,
    )) + "\n")
    findings, ok = gatelib.gate(str(cur), str(prior))
    assert ok, [f.line() for f in findings]
    # collapsed dedupe win: FAIL on the ratio
    cur.write_text(_battery_line("swarm_mixed", _mixed_leg(
        value=70.0, paged_vs_dense=1.02, vs_baseline=1.02,
    )) + "\n")
    findings, ok = gatelib.gate(str(cur), str(prior))
    assert not ok
    assert any(
        f.check == "regression" and "paged_vs_dense" in f.message
        for f in findings
    )
    # ratio missing on one side: SKIP (no regression finding)
    leg = _mixed_leg(value=11.0, dense_tok_per_s=6.8)
    del leg["paged_vs_dense"]
    cur.write_text(_battery_line("swarm_mixed", leg) + "\n")
    findings, ok = gatelib.gate(str(cur), str(prior))
    assert ok, [f.line() for f in findings]
    assert not any(f.check == "regression" for f in findings)


def test_gate_committed_paged_artifact():
    """The committed round-8 CPU-proxy artifact passes the gate, and
    passes as its own prior (run.sh step 0b3's shape)."""
    findings, ok = gatelib.gate(PAGED_ARTIFACT, PAGED_ARTIFACT)
    assert ok, [f.line() for f in findings]


def test_battery_has_round8_legs():
    from inferd_tpu.tools.bench_battery import DEFAULT_LEGS, SMOKE_LEGS

    names = {n for n, _, _ in DEFAULT_LEGS}
    assert "swarm_mixed" in names
    smoke = dict((n, t) for n, t, _ in SMOKE_LEGS)
    assert "swarm_mixed_tiny" in smoke
    assert "swarm-mixed" in smoke["swarm_mixed_tiny"]
    assert "--tiny" in smoke["swarm_mixed_tiny"]


# ---------------------------------------------------------------------------
# round 10: overload-containment gate (goodput floor + hung + hedge budget)
# ---------------------------------------------------------------------------

OVERLOAD_ARTIFACT = os.path.join(
    os.path.dirname(R05), "BENCH_overload_cpu_r10.json"
)


def _overload_leg(**over):
    base = {
        "metric": "tiny_overload_goodput_tok_per_s",
        "value": 200.0, "unit": "tok/s",
        "vs_baseline": 0.9, "goodput_ratio": 0.9,
        "fault_free_tok_per_s": 222.0, "hung_requests": 0,
        "hedge_extra_frac": 0.01, "deadline_s": 25.0,
        "token_exact": True, "device": "cpu",
    }
    base.update(over)
    return base


def test_gate_overload_invariants(tmp_path):
    """The overload leg's three HARD invariants: goodput >= 70% of
    fault-free, zero requests hung past their deadline, hedge extra
    load within the 5% budget."""
    art = tmp_path / "ov.jsonl"
    art.write_text(_battery_line("overload", _overload_leg()) + "\n")
    findings, ok = gatelib.gate(str(art))
    assert ok, [f.line() for f in findings]
    # burst exemption: a short leg whose hedges stayed within the
    # RatioBudget's burst floor may read above the cap as a FRACTION
    # without the budget having over-admitted — no error
    art.write_text(_battery_line("overload", _overload_leg(
        hedge_extra_frac=0.08, hedge_fired=2,
    )) + "\n")
    findings, ok = gatelib.gate(str(art))
    assert ok, [f.line() for f in findings]
    for bad, needle in (
        ({"goodput_ratio": 0.5, "vs_baseline": 0.5}, "goodput ratio"),
        ({"hung_requests": 2}, "past their deadline"),
        ({"hedge_extra_frac": 0.11}, "hedge extra load"),
        ({"hedge_extra_frac": 0.11, "hedge_fired": 9}, "hedge extra load"),
    ):
        art.write_text(
            _battery_line("overload", _overload_leg(**bad)) + "\n"
        )
        findings, ok = gatelib.gate(str(art))
        assert not ok, bad
        assert any(
            f.check == "ordering" and f.severity == "error"
            and needle in f.message
            for f in findings
        ), (bad, [f.line() for f in findings])


def test_gate_overload_ratio_regression(tmp_path):
    """Regression vs the prior gates on the DIMENSIONLESS goodput ratio
    (machine-portable); raw tok/s is never compared for this leg."""
    prior = tmp_path / "prior.jsonl"
    prior.write_text(_battery_line("overload", _overload_leg()) + "\n")
    # slower host, same containment quality: PASS
    cur = tmp_path / "cur.jsonl"
    cur.write_text(_battery_line("overload", _overload_leg(
        value=20.0, fault_free_tok_per_s=22.2,
    )) + "\n")
    findings, ok = gatelib.gate(str(cur), str(prior))
    assert ok, [f.line() for f in findings]
    # containment collapsed (ratio 0.9 -> 0.71, a >20% drop): FAIL
    cur.write_text(_battery_line("overload", _overload_leg(
        goodput_ratio=0.71, vs_baseline=0.71,
    )) + "\n")
    findings, ok = gatelib.gate(str(cur), str(prior))
    assert not ok
    assert any(
        f.check == "regression" and "goodput_ratio" in f.message
        for f in findings
    )
    # ratio missing on one side: SKIP, never raw tok/s
    leg = _overload_leg(value=20.0)
    del leg["goodput_ratio"]
    cur.write_text(_battery_line("overload", leg) + "\n")
    findings, ok = gatelib.gate(str(cur), str(prior))
    assert not any(f.check == "regression" for f in findings)


def test_gate_committed_overload_artifact():
    """The committed round-10 CPU-proxy artifact passes the gate, and
    passes as its own prior (run.sh step 0b4's shape)."""
    findings, ok = gatelib.gate(OVERLOAD_ARTIFACT, OVERLOAD_ARTIFACT)
    assert ok, [f.line() for f in findings]


def test_battery_has_round10_legs():
    from inferd_tpu.tools.bench_battery import DEFAULT_LEGS, SMOKE_LEGS

    names = {n for n, _, _ in DEFAULT_LEGS}
    assert "overload" in names
    smoke = dict((n, t) for n, t, _ in SMOKE_LEGS)
    assert "overload_tiny" in smoke
    assert "overload" in smoke["overload_tiny"]
    assert "--tiny" in smoke["overload_tiny"]
