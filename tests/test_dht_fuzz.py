"""Property-based convergence of the gossip store's merge (control/dht.py):
last-writer-wins on (version, ts) must be commutative, idempotent, and
order-independent — any two stores that saw the same record set in ANY
order and multiplicity hold identical state. This is the property that
makes the reference's read-modify-write races (SURVEY B6) impossible by
construction, so it gets pinned adversarially rather than by example."""

from hypothesis import given, settings
from hypothesis import strategies as st

from inferd_tpu.control.dht import Record, SwarmDHT

OWNERS = [f"10.0.0.{i}:7050" for i in range(1, 5)]


def mk_store():
    # no start(): _merge/_records are pure state machine surface
    return SwarmDHT("127.0.0.9:9", 0, bootstrap=[], host="127.0.0.1")


# Protocol invariant (dht.announce bumps _own_version on EVERY publish):
# an owner never issues two records with the same (version, ts) but
# different values — so the generator derives the value from the key.
# Ties with identical values (duplicated frames) are covered.
records = st.builds(
    lambda owner, version, ts: Record(
        owner=owner,
        value={
            "stage": version % 3,
            "load": version * 10 + int(ts),
            "host": owner.split(":")[0],
            "port": 7050,
        },
        version=version,
        ts=float(ts),
        addr=(owner.split(":")[0], 7050),
    ),
    st.sampled_from(OWNERS),
    st.integers(0, 5),
    st.integers(0, 3),
)


def state(store):
    return {
        o: (r.version, r.ts, r.value) for o, r in store._records.items()
    }


@settings(max_examples=150, deadline=None)
@given(st.lists(records, max_size=12), st.permutations(range(12)))
def test_merge_order_independent(recs, perm):
    a, b = mk_store(), mk_store()
    sender = ("10.0.0.1", 7050)
    for r in recs:
        a._merge([r.to_wire()], sender, sender_id=r.owner)
    order = [recs[i] for i in perm if i < len(recs)]
    for r in order:  # permuted order, same multiset
        b._merge([r.to_wire()], sender, sender_id=r.owner)
    assert state(a) == state(b)


@settings(max_examples=100, deadline=None)
@given(st.lists(records, max_size=10))
def test_merge_idempotent(recs):
    a = mk_store()
    sender = ("10.0.0.1", 7050)
    wires = [r.to_wire() for r in recs]
    a._merge(wires, sender)
    snap = state(a)
    a._merge(wires, sender)  # replay everything
    a._merge(list(reversed(wires)), sender)
    assert state(a) == snap


@settings(max_examples=100, deadline=None)
@given(st.lists(records, min_size=1, max_size=10))
def test_highest_version_wins(recs):
    a = mk_store()
    a._merge([r.to_wire() for r in recs], ("10.0.0.1", 7050))
    for owner in {r.owner for r in recs}:
        best = max(
            (r for r in recs if r.owner == owner), key=lambda r: (r.version, r.ts)
        )
        got = a._records[owner]
        assert (got.version, got.ts) == (best.version, best.ts)


def test_own_record_never_overwritten():
    a = mk_store()
    foreign = Record(
        owner=a.node_id, value={"stage": 9}, version=99, ts=9e9,
        addr=("1.2.3.4", 1),
    )
    a._merge([foreign.to_wire()], ("10.0.0.1", 7050))
    assert a.node_id not in a._records  # owner-writes-only held
