"""Property-based check of the D*-Lite router: after ANY sequence of edge
cost updates with compute() in between, the incrementally-replanned path
cost must equal a from-scratch Dijkstra on the final graph — incremental
replanning is the module's reason to exist (reference dstar/ was built for
it but only hand-checked one example).

Two layers:

  * a hypothesis fuzz over raw DStarLite edge updates (skipped cleanly
    where hypothesis isn't installed — some serving containers);
  * a seeded-random equivalence drive over the OPERATIONAL surface
    (SwarmChainPlanner): random gossip-delta / peer.dead / join / revive
    sequences must keep the planned chain cost-equal to a from-scratch
    Dijkstra after EVERY update, with joins spliced incrementally (no
    rebuilds while every stage stays live). Runs everywhere — no
    third-party dependency.
"""

import copy
import heapq
import math
import random

import pytest

from inferd_tpu.control.dstar import (
    DStarLite,
    Graph,
    SwarmChainPlanner,
    build_layered_graph,
    node_cost,
)
from inferd_tpu.control.path_finder import NoNodeForStage

try:
    from hypothesis import given, settings
    from hypothesis import strategies as st

    HAVE_HYPOTHESIS = True
except ModuleNotFoundError:  # pragma: no cover - env without hypothesis
    HAVE_HYPOTHESIS = False

N_LAYERS = 4
WIDTH = 3


def dijkstra_cost(g: Graph, start, goal) -> float:
    dist = {start: 0.0}
    pq = [(0.0, 0, start)]
    seq = 1
    seen = set()
    while pq:
        d, _, u = heapq.heappop(pq)
        if u in seen:
            continue
        seen.add(u)
        if u == goal:
            return d
        for v, c in g.succ(u):
            nd = d + c
            if nd < dist.get(v, float("inf")):
                dist[v] = nd
                heapq.heappush(pq, (nd, seq, v))
                seq += 1
    return float("inf")


def path_cost(g: Graph, path) -> float:
    if not path:
        return float("inf")
    total = 0.0
    for u, v in zip(path, path[1:]):
        total += g.cost(u, v)
    return total


def layered_edges():
    """All edges of a WIDTH x N_LAYERS layered DAG, start/goal terminal."""
    edges = []
    for i in range(WIDTH):
        edges.append(("start", f"n0_{i}"))
    for layer in range(N_LAYERS - 1):
        for i in range(WIDTH):
            for j in range(WIDTH):
                edges.append((f"n{layer}_{i}", f"n{layer + 1}_{j}"))
    for i in range(WIDTH):
        edges.append((f"n{N_LAYERS - 1}_{i}", "goal"))
    return edges

EDGES = layered_edges()

if HAVE_HYPOTHESIS:
    costs = st.lists(
        st.floats(min_value=0.1, max_value=50.0), min_size=len(EDGES),
        max_size=len(EDGES),
    )
    updates = st.lists(
        st.tuples(
            st.integers(0, len(EDGES) - 1),
            st.floats(min_value=0.1, max_value=200.0),
        ),
        max_size=10,
    )

    @settings(max_examples=80, deadline=None)
    @given(costs, updates)
    def test_incremental_equals_scratch_dijkstra(cs, ups):
        g = Graph()
        for (u, v), c in zip(EDGES, cs):
            g.add_edge(u, v, c)
        d = DStarLite(g, "start", "goal")
        d.compute()
        assert abs(path_cost(g, d.path()) - dijkstra_cost(g, "start", "goal")) < 1e-6

        # apply updates in batches of <=3, recomputing between batches (the
        # operational pattern: a few swarm load changes per routing tick)
        batch = []
        for idx, (ei, nc) in enumerate(ups):
            u, v = EDGES[ei]
            d.update_edge(u, v, nc)
            batch.append(None)
            if len(batch) == 3 or idx == len(ups) - 1:
                d.compute()
                batch.clear()
        if ups:
            d.compute()
            got = path_cost(g, d.path())
            want = dijkstra_cost(g, "start", "goal")
            assert abs(got - want) < 1e-6, (got, want)


# ---------------------------------------------------------------------------
# SwarmChainPlanner incremental-replan equivalence (no hypothesis needed)
# ---------------------------------------------------------------------------


def _optimal_chain_cost(snapshot, num_stages) -> float:
    """From-scratch Dijkstra over the same layered graph / node_cost the
    planner uses — the equivalence yardstick."""
    g = build_layered_graph(snapshot, 0, num_stages)
    return dijkstra_cost(g, ("start",), ("goal",))


def _planner_chain_cost(planner, snapshot) -> float:
    """Cost of the planner's chain, priced on OUR snapshot (the ground
    truth the planner was fed)."""
    try:
        chain = planner.chain()
    except NoNodeForStage:
        return float("inf")
    return sum(node_cost(snapshot[s][nid]) for s, nid, _ in chain)


def _assert_equiv(planner, snapshot, num_stages, ctx):
    want = _optimal_chain_cost(snapshot, num_stages)
    got = _planner_chain_cost(planner, snapshot)
    if math.isinf(want) or math.isinf(got):
        assert math.isinf(want) and math.isinf(got), (ctx, got, want)
    else:
        assert abs(got - want) < 1e-6, (ctx, got, want)


def test_planner_gossip_delta_and_peer_dead_equivalence():
    """Random gossip-delta / peer.dead / join / revive sequences: after
    EVERY update the incrementally-replanned chain must be cost-equal to
    a from-scratch Dijkstra on the same view; joins splice incrementally
    (zero rebuilds while every stage stays live); a peer.dead increment
    is equivalent to the node vanishing from the view."""
    rng = random.Random(0xD57A)
    for case in range(25):
        num_stages = rng.randint(2, 5)
        width = rng.randint(2, 4)
        next_id = [0]

        def mk_value():
            v = {"load": rng.randint(0, 12), "cap": rng.choice([1, 2, 4, 8])}
            if rng.random() < 0.5:
                v["svc_ms"] = round(rng.uniform(1.0, 400.0), 3)
            if rng.random() < 0.5:
                v["hop_p99_ms"] = round(rng.uniform(1.0, 2000.0), 3)
            if rng.random() < 0.1:
                v["outlier"] = 1
            return v

        def mk_node(s, snapshot):
            nid = f"s{s}x{next_id[0]}"
            next_id[0] += 1
            snapshot.setdefault(s, {})[nid] = mk_value()
            return nid

        snapshot = {}
        for s in range(num_stages):
            for _ in range(width):
                mk_node(s, snapshot)
        planner = SwarmChainPlanner(
            copy.deepcopy(snapshot), 0, num_stages
        )
        _assert_equiv(planner, snapshot, num_stages, (case, "build"))
        graveyard = []  # (stage, nid, value) for revivals

        for step in range(14):
            op = rng.choice(["drift", "drift", "dead", "join", "revive"])
            if op == "drift":
                s = rng.randrange(num_stages)
                if snapshot.get(s):
                    nid = rng.choice(sorted(snapshot[s]))
                    snapshot[s][nid] = mk_value()
            elif op == "dead":
                s = rng.randrange(num_stages)
                # keep one replica per stage so a chain keeps existing
                if len(snapshot.get(s, {})) > 1:
                    nid = rng.choice(sorted(snapshot[s]))
                    value = snapshot[s].pop(nid)
                    graveyard.append((s, nid, value))
                    if rng.random() < 0.5:
                        # the relay-observed death path: kill_node FIRST
                        # (incremental INF), then the gossip refresh —
                        # both must agree with the node gone
                        planner.kill_node(nid)
                        _assert_equiv(
                            planner, snapshot, num_stages,
                            (case, step, "kill_node"),
                        )
            elif op == "join":
                mk_node(rng.randrange(num_stages), snapshot)
            elif op == "revive" and graveyard:
                s, nid, value = graveyard.pop(rng.randrange(len(graveyard)))
                snapshot[s][nid] = value
            planner.refresh(copy.deepcopy(snapshot))
            _assert_equiv(planner, snapshot, num_stages, (case, step, op))

        # joins were spliced, never rebuilt: every stage stayed live
        assert planner.stats["builds"] == 1, planner.stats


def test_planner_replan_stays_incremental_under_drift():
    """On a wide fleet graph, the cumulative expansions of MANY drift
    replans stay far under what re-solving from scratch each time would
    cost — the vertex-expansion assertion behind the sim's replan_frac
    gate, pinned at unit level."""
    rng = random.Random(7)
    stages, width = 6, 10
    snapshot = {
        s: {
            f"s{s}x{i}": {"load": rng.randint(0, 8), "cap": 4}
            for i in range(width)
        }
        for s in range(stages)
    }
    planner = SwarmChainPlanner(copy.deepcopy(snapshot), 0, stages)
    build_exp = planner.stats["expansions_build"]
    replans = 40
    for _ in range(replans):
        s = rng.randrange(stages)
        nid = rng.choice(sorted(snapshot[s]))
        snapshot[s][nid] = {"load": rng.randint(0, 8), "cap": 4}
        planner.refresh(copy.deepcopy(snapshot))
        _assert_equiv(planner, snapshot, stages, "drift")
    assert planner.stats["builds"] == 1
    # mean expansions per replan << one full solve
    mean_replan = planner.stats["expansions_replan"] / max(
        1, planner.stats["computes"] - 1
    )
    assert mean_replan <= build_exp / 5, (mean_replan, build_exp)
