"""Property-based check of the D*-Lite router: after ANY sequence of edge
cost updates with compute() in between, the incrementally-replanned path
cost must equal a from-scratch Dijkstra on the final graph — incremental
replanning is the module's reason to exist (reference dstar/ was built for
it but only hand-checked one example)."""

import heapq

from hypothesis import given, settings
from hypothesis import strategies as st

from inferd_tpu.control.dstar import DStarLite, Graph

N_LAYERS = 4
WIDTH = 3


def dijkstra_cost(g: Graph, start, goal) -> float:
    dist = {start: 0.0}
    pq = [(0.0, start)]
    seen = set()
    while pq:
        d, u = heapq.heappop(pq)
        if u in seen:
            continue
        seen.add(u)
        if u == goal:
            return d
        for v, c in g.succ(u):
            nd = d + c
            if nd < dist.get(v, float("inf")):
                dist[v] = nd
                heapq.heappush(pq, (nd, v))
    return float("inf")


def path_cost(g: Graph, path) -> float:
    if not path:
        return float("inf")
    total = 0.0
    for u, v in zip(path, path[1:]):
        total += g.cost(u, v)
    return total


def layered_edges():
    """All edges of a WIDTH x N_LAYERS layered DAG, start/goal terminal."""
    edges = []
    for i in range(WIDTH):
        edges.append(("start", f"n0_{i}"))
    for layer in range(N_LAYERS - 1):
        for i in range(WIDTH):
            for j in range(WIDTH):
                edges.append((f"n{layer}_{i}", f"n{layer + 1}_{j}"))
    for i in range(WIDTH):
        edges.append((f"n{N_LAYERS - 1}_{i}", "goal"))
    return edges

EDGES = layered_edges()

costs = st.lists(
    st.floats(min_value=0.1, max_value=50.0), min_size=len(EDGES),
    max_size=len(EDGES),
)
updates = st.lists(
    st.tuples(
        st.integers(0, len(EDGES) - 1),
        st.floats(min_value=0.1, max_value=200.0),
    ),
    max_size=10,
)


@settings(max_examples=80, deadline=None)
@given(costs, updates)
def test_incremental_equals_scratch_dijkstra(cs, ups):
    g = Graph()
    for (u, v), c in zip(EDGES, cs):
        g.add_edge(u, v, c)
    d = DStarLite(g, "start", "goal")
    d.compute()
    assert abs(path_cost(g, d.path()) - dijkstra_cost(g, "start", "goal")) < 1e-6

    # apply updates in batches of <=3, recomputing between batches (the
    # operational pattern: a few swarm load changes per routing tick)
    batch = []
    for idx, (ei, nc) in enumerate(ups):
        u, v = EDGES[ei]
        d.update_edge(u, v, nc)
        batch.append(None)
        if len(batch) == 3 or idx == len(ups) - 1:
            d.compute()
            batch.clear()
    if ups:
        d.compute()
        got = path_cost(g, d.path())
        want = dijkstra_cost(g, "start", "goal")
        assert abs(got - want) < 1e-6, (got, want)
