# jaxlint: file-disable=J003 -- test code: loops here sync per-iteration to ASSERT on values; they are verification loops, not serving hot paths
"""Speculative decoding (core.speculative): the greedy-exactness guarantee,
full-acceptance fast path, rollback correctness across rounds, and EOS.
Added scope beyond the reference's one-token-per-pass decode
(client.py:244-266)."""

import dataclasses

import jax
import numpy as np
import pytest

from inferd_tpu.config import TINY, SamplingConfig
from inferd_tpu.core.generate import Engine
from inferd_tpu.core.speculative import SpeculativeEngine
from inferd_tpu.models import qwen3


@pytest.fixture(scope="module")
def target():
    params = qwen3.init_params(TINY, jax.random.PRNGKey(0))
    return TINY, params


@pytest.mark.parametrize("k", [1, 3, 4])
def test_greedy_exactness_with_unrelated_draft(target, k):
    """With an arbitrary (even adversarial) draft, output must EXACTLY match
    the target's own greedy decode — only speed may differ."""
    cfg, params = target
    draft_cfg = dataclasses.replace(TINY, name="tiny-draft", num_layers=2)
    draft_params = qwen3.init_params(draft_cfg, jax.random.PRNGKey(99))

    engine = Engine(cfg, params, max_len=128, sampling_cfg=SamplingConfig(temperature=0.0))
    prompt = [3, 17, 42, 9]
    want = engine.generate(prompt, max_new_tokens=24)

    spec = SpeculativeEngine(cfg, params, draft_cfg, draft_params, k=k, max_len=128)
    got, acc = spec.generate(prompt, max_new_tokens=24)
    assert got == want
    assert 0.0 <= acc <= 1.0


def test_full_acceptance_when_draft_is_target(target):
    """Draft == target accepts every draft (acceptance 1.0) and still emits
    the exact greedy stream."""
    cfg, params = target
    engine = Engine(cfg, params, max_len=128, sampling_cfg=SamplingConfig(temperature=0.0))
    prompt = [5, 11, 2]
    want = engine.generate(prompt, max_new_tokens=20)

    spec = SpeculativeEngine(cfg, params, cfg, params, k=4, max_len=128)
    got, acc = spec.generate(prompt, max_new_tokens=20)
    assert got == want
    assert acc == 1.0


@pytest.mark.parametrize("family", ["gemma2", "gptoss"])
def test_greedy_exactness_new_families(family):
    """Speculative self-drafting stays token-exact for the sliding-window
    families: the truncated draft's first-N layers keep the global layer
    indices (offset 0), so its window pattern matches the target's prefix,
    and the verify chunk walks the full recipe (sinks/softcaps included)."""
    from inferd_tpu.config import TINY_GEMMA2, TINY_GPT_OSS
    from inferd_tpu.core.speculative import self_draft

    cfg = TINY_GEMMA2 if family == "gemma2" else TINY_GPT_OSS
    params = qwen3.init_params(cfg, jax.random.PRNGKey(31))
    engine = Engine(cfg, params, max_len=128, sampling_cfg=SamplingConfig(temperature=0.0))
    prompt = [3, 17, 42, 9, 8, 1, 5, 12, 2]
    want = engine.generate(prompt, max_new_tokens=16)  # walks past window 8

    dcfg, dparams = self_draft(cfg, params, 2)
    spec = SpeculativeEngine(cfg, params, dcfg, dparams, k=3, max_len=128)
    got, acc = spec.generate(prompt, max_new_tokens=16)
    assert got == want
    assert 0.0 <= acc <= 1.0


def test_eos_stops_mid_chunk(target):
    """EOS inside an accepted run truncates the output exactly where the
    target's own greedy decode would stop."""
    cfg, params = target
    engine = Engine(cfg, params, max_len=128, sampling_cfg=SamplingConfig(temperature=0.0))
    prompt = [7, 1, 13]
    ref = engine.generate(prompt, max_new_tokens=30)
    # pick the 6th emitted token as a fake EOS so it lands mid-stream
    eos = ref[5]
    want = engine.generate(prompt, max_new_tokens=30, eos_token_id=eos)

    spec = SpeculativeEngine(cfg, params, cfg, params, k=4, max_len=128)
    got, _ = spec.generate(prompt, max_new_tokens=30, eos_token_id=eos)
    assert got == want


def test_vocab_mismatch_rejected(target):
    cfg, params = target
    bad = dataclasses.replace(TINY, vocab_size=128)
    with pytest.raises(ValueError, match="vocab"):
        SpeculativeEngine(cfg, params, bad, params, k=2)


def test_sampled_full_acceptance_when_draft_is_target(target):
    """With draft == target and temperature > 0, q == p at every position,
    so every draft is accepted (rate 1.0) and tokens flow."""
    cfg, params = target
    spec = SpeculativeEngine(
        cfg, params, cfg, params, k=4, max_len=128,
        sampling_cfg=SamplingConfig(temperature=0.8, top_k=10, top_p=0.95),
    )
    got, acc = spec.generate([5, 11, 2], max_new_tokens=20, seed=3)
    # q == p per token up to cross-program ulp noise (draft scan vs chunked
    # verify are different XLA programs), so near-total acceptance
    assert len(got) == 20 and acc >= 0.9


def test_sampled_distribution_matches_target(target):
    """The rejection scheme's output must be distributed exactly as
    target-only warped sampling, regardless of the (mismatched) draft:
    empirical first-emitted-token distribution over many seeds vs the
    target's warped probabilities, in total-variation distance."""
    import jax.numpy as jnp

    from inferd_tpu.core import sampling as samplib
    from inferd_tpu.core.cache import KVCache

    cfg, params = target
    draft_cfg = dataclasses.replace(TINY, name="tiny-draft2", num_layers=2)
    draft_params = qwen3.init_params(draft_cfg, jax.random.PRNGKey(77))
    sc = SamplingConfig(temperature=1.2, top_k=5, top_p=0.9)
    spec = SpeculativeEngine(
        cfg, params, draft_cfg, draft_params, k=3, max_len=64, sampling_cfg=sc
    )

    # fixed prefix: prompt + pending token x_n chosen greedily
    prompt = [3, 17, 42, 9]
    n = len(prompt)
    toks = jnp.asarray([prompt + [0] * (16 - n)], jnp.int32)

    # target's warped next-token distribution after [prompt, x_n]
    logits_p, _, _ = qwen3.forward(params, cfg, toks[:, :n])
    x_n = int(jnp.argmax(logits_p[0, n - 1]))
    logits_full, _, _ = qwen3.forward(
        params, cfg, jnp.asarray([prompt + [x_n] + [0] * (16 - n - 1)], jnp.int32)
    )
    want = np.asarray(
        jax.nn.softmax(
            samplib.warped_logits(
                logits_full[:, n], sc.temperature, sc.top_k, sc.top_p
            )
        )
    )[0]

    # one jitted prefill builds fresh cache buffers per trial (the spec step
    # donates its cache args, so each trial needs new buffers; jitting this
    # also avoids repeated eager scan dispatch, which segfaults XLA:CPU
    # under the pytest plugin environment)
    @jax.jit
    def prefill_caches(tp, dp, toks):
        tc = KVCache.create(cfg, cfg.num_layers, 1, 64, ring=False)
        dc = KVCache.create(draft_cfg, draft_cfg.num_layers, 1, 64, ring=False)
        _, tk, tv = qwen3.forward(tp, cfg, toks, None, tc.k, tc.v, jnp.int32(0))
        _, dk, dv = qwen3.forward(dp, draft_cfg, toks, None, dc.k, dc.v, jnp.int32(0))
        return tk, tv, dk, dv

    counts = np.zeros(cfg.vocab_size)
    trials = 600
    last = jnp.asarray([x_n], jnp.int32)
    for s in range(trials):
        tk, tv_, dk, dv = prefill_caches(params, draft_params, toks)
        tc = KVCache(k=tk, v=tv_, length=jnp.int32(n))
        dc = KVCache(k=dk, v=dv, length=jnp.int32(n))
        out_toks, n_new, _, _ = spec._spec_step_sampled(
            params, draft_params, last, tc, dc, jax.random.PRNGKey(10_000 + s)
        )
        counts[int(out_toks[0])] += 1
    emp = counts / trials
    tv = 0.5 * np.abs(emp - want).sum()
    assert tv < 0.10, f"TV distance {tv}"
