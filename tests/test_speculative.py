"""Speculative decoding (core.speculative): the greedy-exactness guarantee,
full-acceptance fast path, rollback correctness across rounds, and EOS.
Added scope beyond the reference's one-token-per-pass decode
(client.py:244-266)."""

import dataclasses

import jax
import numpy as np
import pytest

from inferd_tpu.config import TINY, SamplingConfig
from inferd_tpu.core.generate import Engine
from inferd_tpu.core.speculative import SpeculativeEngine
from inferd_tpu.models import qwen3


@pytest.fixture(scope="module")
def target():
    params = qwen3.init_params(TINY, jax.random.PRNGKey(0))
    return TINY, params


@pytest.mark.parametrize("k", [1, 3, 4])
def test_greedy_exactness_with_unrelated_draft(target, k):
    """With an arbitrary (even adversarial) draft, output must EXACTLY match
    the target's own greedy decode — only speed may differ."""
    cfg, params = target
    draft_cfg = dataclasses.replace(TINY, name="tiny-draft", num_layers=2)
    draft_params = qwen3.init_params(draft_cfg, jax.random.PRNGKey(99))

    engine = Engine(cfg, params, max_len=128, sampling_cfg=SamplingConfig(temperature=0.0))
    prompt = [3, 17, 42, 9]
    want = engine.generate(prompt, max_new_tokens=24)

    spec = SpeculativeEngine(cfg, params, draft_cfg, draft_params, k=k, max_len=128)
    got, acc = spec.generate(prompt, max_new_tokens=24)
    assert got == want
    assert 0.0 <= acc <= 1.0


def test_full_acceptance_when_draft_is_target(target):
    """Draft == target accepts every draft (acceptance 1.0) and still emits
    the exact greedy stream."""
    cfg, params = target
    engine = Engine(cfg, params, max_len=128, sampling_cfg=SamplingConfig(temperature=0.0))
    prompt = [5, 11, 2]
    want = engine.generate(prompt, max_new_tokens=20)

    spec = SpeculativeEngine(cfg, params, cfg, params, k=4, max_len=128)
    got, acc = spec.generate(prompt, max_new_tokens=20)
    assert got == want
    assert acc == 1.0


def test_eos_stops_mid_chunk(target):
    """EOS inside an accepted run truncates the output exactly where the
    target's own greedy decode would stop."""
    cfg, params = target
    engine = Engine(cfg, params, max_len=128, sampling_cfg=SamplingConfig(temperature=0.0))
    prompt = [7, 1, 13]
    ref = engine.generate(prompt, max_new_tokens=30)
    # pick the 6th emitted token as a fake EOS so it lands mid-stream
    eos = ref[5]
    want = engine.generate(prompt, max_new_tokens=30, eos_token_id=eos)

    spec = SpeculativeEngine(cfg, params, cfg, params, k=4, max_len=128)
    got, _ = spec.generate(prompt, max_new_tokens=30, eos_token_id=eos)
    assert got == want


def test_vocab_mismatch_rejected(target):
    cfg, params = target
    bad = dataclasses.replace(TINY, vocab_size=128)
    with pytest.raises(ValueError, match="vocab"):
        SpeculativeEngine(cfg, params, bad, params, k=2)
