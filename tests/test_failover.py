"""Crash-tolerant sessions (ISSUE 14): async standby KV replication,
bounded-RPO promotion, measured failover — plus the rescue give-up
journal, chaos crash_after, partial drain-handoff behavior, and the
kill-switch parity contract (replication off => gossip/wire//metrics
byte-identical to a build without the plane)."""

import asyncio
import time

import jax
import numpy as np
import pytest

from inferd_tpu.client.swarm_client import SwarmClient
from inferd_tpu.config import TINY, SamplingConfig
from inferd_tpu.control.dht import SwarmDHT
from inferd_tpu.core.generate import Engine
from inferd_tpu.models import qwen3
from inferd_tpu.parallel import stages as stagelib
from inferd_tpu.parallel.stages import Manifest, split_and_save
from inferd_tpu.runtime import repl as repllib
from inferd_tpu.runtime import wire
from inferd_tpu.runtime.node import Node, NodeInfo
from inferd_tpu.utils.chaos import Chaos, ChaosDrop

BASE = 19400  # distinct port block from test_chaos_soak (19300)


# --------------------------------------------------------------- fixtures


@pytest.fixture(scope="module")
def tiny_parts1(tmp_path_factory):
    """TINY as a single whole-model stage (the standby-replication e2e
    topology: a stage-0 replica PAIR serving the full model)."""
    parts = tmp_path_factory.mktemp("parts1")
    params = qwen3.init_params(TINY, jax.random.PRNGKey(0))
    manifest = Manifest.even_split("tiny", 1)
    split_and_save(params, TINY, manifest, str(parts))
    return str(parts), params


def _solo_executor(parts):
    from inferd_tpu.runtime.executor import Qwen3StageExecutor

    path = stagelib.stage_checkpoint_path(parts, 0)
    params, spec, _name = stagelib.load_stage_checkpoint(path)
    return Qwen3StageExecutor(TINY, spec, params, max_len=64)


def _batched_executor(parts, block_size=8):
    from inferd_tpu.runtime.batch_executor import BatchedExecutor

    path = stagelib.stage_checkpoint_path(parts, 0)
    params, _spec, _name = stagelib.load_stage_checkpoint(path)
    return BatchedExecutor(
        TINY, params, lanes=2, max_len=64, block_size=block_size,
    )


def _mk(idx, *, parts, bootstrap_idx=0, chaos=None, **node_kw):
    info = NodeInfo(
        name=f"f{idx}", host="127.0.0.1", port=BASE + idx,
        stage=0, num_stages=1, capacity=4, model_name="tiny",
    )
    dht = SwarmDHT(
        info.node_id, BASE + 100 + idx,
        bootstrap=(
            [("127.0.0.1", BASE + 100 + bootstrap_idx)]
            if idx != bootstrap_idx else []
        ),
        host="127.0.0.1", gossip_period_s=0.05, ttl_s=1.5,
    )
    return Node(
        info, TINY, parts, dht, backend="qwen3", max_len=64,
        rebalance_period_s=600.0, chaos=chaos, hop_timeout_s=2.0,
        **node_kw,
    )


async def _start_all(nodes):
    for n in nodes:
        await n.start()

    async def converged():
        for n in nodes:
            if not n.dht.get_stage(0):
                return False
        return True

    for _ in range(100):
        if await converged():
            return
        await asyncio.sleep(0.05)
    raise TimeoutError("swarm did not converge")


async def _stop_all(nodes):
    for n in nodes:
        try:
            await n.stop()
        except Exception:
            pass


def _drive(ex, sid, prompt, steps):
    """Greedy-generate on a raw executor via the process() surface;
    returns (tokens, final position)."""
    out = []
    r = ex.process(sid, {
        "tokens": [list(prompt)], "start_pos": 0, "real_len": len(prompt),
    })
    pos = len(prompt)
    tok = int(np.argmax(np.asarray(r["logits"])[0]))
    out.append(tok)
    for _ in range(steps - 1):
        r = ex.process(sid, {
            "tokens": [[tok]], "start_pos": pos, "real_len": 1,
        })
        pos += 1
        tok = int(np.argmax(np.asarray(r["logits"])[0]))
        out.append(tok)
    return out, pos


# ---------------------------------------------------- chaos crash_after


def test_chaos_crash_after_parse_and_compose():
    c = Chaos.parse("crash_after=3,drop=0.5,seed=2")
    assert c.crash_after == 3 and c.drop == 0.5 and c.seed == 2
    # still composes with the probabilistic keys and parses alone
    assert Chaos.parse("crash_after=7").crash_after == 7


@pytest.mark.asyncio
async def test_chaos_crash_after_fires_once_then_keeps_dropping():
    c = Chaos(crash_after=2)
    crashes = []
    c.on_crash = lambda: crashes.append(1)
    await c.before_forward()
    await c.before_forward()  # forward 2: still healthy
    assert crashes == []
    for _ in range(3):
        with pytest.raises(ChaosDrop, match="crash_after"):
            await c.before_forward()
    # the hook fired exactly once; every later forward still fails (the
    # node is "dead" — it must not come back healthy)
    assert crashes == [1]


# ------------------------------------------------- executor delta export


def test_solo_delta_export_accumulate_import_token_exact(tiny_parts1):
    parts, _params = tiny_parts1
    a = _solo_executor(parts)
    b = _solo_executor(parts)
    prompt = [3, 7, 11, 19, 5, 2]
    ref_ex = _solo_executor(parts)
    ref, _ = _drive(ref_ex, "ref", prompt, 8)

    store = repllib.StandbyStore()
    out_a, pos = _drive(a, "s", prompt, 4)
    assert a.session_lengths() == {"s": pos}
    # ship in two deltas: [0, F) then [F, pos)
    d1 = a.export_session_delta("s", 0)
    assert d1[repllib.START_KEY] == 0 and d1["length"] == pos
    ok, have = store.apply("s", 0, {"session_id": "s", "stage": 0, **d1})
    assert ok and have == pos
    # nothing new -> no delta
    assert a.export_session_delta("s", pos) is None
    out_a2, pos2 = [], pos
    tok = out_a[-1]
    for _ in range(2):
        r = a.process("s", {"tokens": [[tok]], "start_pos": pos2,
                            "real_len": 1})
        pos2 += 1
        tok = int(np.argmax(np.asarray(r["logits"])[0]))
        out_a2.append(tok)
    d2 = a.export_session_delta("s", pos)
    assert d2[repllib.START_KEY] == pos and d2["length"] == pos2
    ok, have = store.apply("s", 0, {"session_id": "s", "stage": 0, **d2})
    assert ok and have == pos2

    # promote on B: import the accumulated payload, continue decoding —
    # the continuation must be TOKEN-EXACT vs the uninterrupted run
    assert b.import_session("s", store.payload("s"))
    tail = []
    for _ in range(8 - 4 - 2):
        r = b.process("s", {"tokens": [[tok]], "start_pos": pos2,
                            "real_len": 1})
        pos2 += 1
        tok = int(np.argmax(np.asarray(r["logits"])[0]))
        tail.append(tok)
    assert out_a + out_a2 + tail == ref


def test_batched_paged_delta_block_aligned(tiny_parts1):
    parts, _params = tiny_parts1
    a = _batched_executor(parts, block_size=8)
    b = _batched_executor(parts, block_size=8)
    ref_ex = _batched_executor(parts, block_size=8)
    prompt = [3, 7, 11, 19, 5, 2, 13, 17, 23, 29]  # 10 tokens
    ref, _ = _drive(ref_ex, "ref", prompt, 12)

    store = repllib.StandbyStore()
    out_a, pos = _drive(a, "s", prompt, 3)  # KV length 12
    d1 = a.export_session_delta("s", 0)
    # paged: only IMMUTABLE FULL BLOCKS ship — the partial tail block
    # stays behind (bounded RPO, docs/SERVING.md)
    assert d1["length"] == (pos // 8) * 8 == 8
    assert np.asarray(d1["k"]).shape[2] == 8
    ok, have = store.apply("s", 0, {"session_id": "s", "stage": 0, **d1})
    assert ok and have == 8

    def advance(n, tok):
        nonlocal pos
        got = []
        for _ in range(n):
            r = a.process("s", {"tokens": [[tok]], "start_pos": pos,
                                "real_len": 1})
            pos += 1
            tok = int(np.argmax(np.asarray(r["logits"])[0]))
            got.append(tok)
        return got

    # advance past the next block boundary and ship the delta
    extra = advance(4, out_a[-1])  # KV length 16
    d2 = a.export_session_delta("s", 8)
    assert d2[repllib.START_KEY] == 8 and d2["length"] == 16
    ok, have = store.apply("s", 0, {"session_id": "s", "stage": 0, **d2})
    assert ok and have == 16
    # two more steps that never replicate (the crash window): the
    # standby's frontier stays one partial block behind
    tail = advance(2, extra[-1])  # KV length 18, frontier 16
    assert out_a + extra + tail == ref[:9]

    # promote on B: import the replicated prefix, re-prefill ONLY the
    # tokens past the frontier (known stream positions 16..17 — the
    # bounded re-prefill a resume-aware client sends), then continue
    assert b.import_session("s", store.payload("s"))
    known = list(prompt) + out_a + extra + tail  # token at index = position
    replay = known[16:pos]
    assert len(replay) == pos - 16 == 2  # << the 8-token prompt blocks
    p = 16
    r = None
    for t in replay:
        r = b.process("s", {"tokens": [[t]], "start_pos": p, "real_len": 1})
        p += 1
    tok_b = int(np.argmax(np.asarray(r["logits"])[0]))
    # the recomputed continuation matches the uninterrupted stream
    assert tok_b == ref[9]


def test_standby_store_gap_resync_and_sweep():
    store = repllib.StandbyStore(ttl_s=0.0)
    k = np.zeros((2, 1, 4, 1, 2), np.float32)
    base = {"k": k, "v": k, "length": 4, repllib.START_KEY: 0}
    ok, have = store.apply("s", 0, dict(base))
    assert ok and have == 4
    # a delta past the frontier declines and reports what it HAS
    gap = {"k": k, "v": k, "length": 12, repllib.START_KEY: 8}
    ok, have = store.apply("s", 0, dict(gap))
    assert not ok and have == 4
    # a mid-stream delta for an UNKNOWN session asks for a full re-sync
    ok, have = store.apply("s2", 0, dict(gap))
    assert not ok and have == 0
    # wrong stage declines
    ok, have = store.apply("s", 1, {
        "k": k, "v": k, "length": 8, repllib.START_KEY: 4,
    })
    assert not ok
    # start == 0 REPLACES (primary re-synced from scratch)
    ok, have = store.apply("s", 0, dict(base))
    assert ok and have == 4
    # TTL sweep drops idle shadows
    assert store.sweep() == 1 and len(store) == 0


def test_replicator_sticky_standby_and_frontier_reset():
    cands = [("b", {}), ("c", {"shed": 1})]
    r = repllib.SessionReplicator(lambda: list(cands))
    plan = r.plan({"s": 10})
    assert plan == [("s", "b", 0)]  # shedding candidate loses the pick
    r.record("s", "b", True, 10, 100)
    assert r.plan({"s": 10}) == []  # nothing new
    assert r.plan({"s": 14}) == [("s", "b", 10)]  # sticky standby
    assert r.lag_tokens({"s": 14}) == 4
    # standby death: forget it; the next pick re-ships from 0
    r.note_standby_dead("s")
    cands[:] = [("c", {"shed": 1})]
    assert r.plan({"s": 14}) == [("s", "c", 0)]  # last resort: shedding
    # a declined ship resets the frontier to what the peer reports
    r.record("s", "c", False, 6, 0)
    assert r.plan({"s": 14}) == [("s", "c", 6)]
    # residency loss prunes SILENTLY (the shadow may be the stream's
    # only surviving copy); an explicit end pops the drop-notice target
    r2 = repllib.SessionReplicator(lambda: [("b", {})])
    r2.record("x", "b", True, 4, 10)
    r2.prune([])
    assert r2.state == {} and r2.pop_standby("x") is None
    assert r.pop_standby("s") == "c"
    assert r.state == {}


# ------------------------------------------------------------- node e2e


@pytest.mark.asyncio
async def test_standby_promotion_e2e_token_exact(tiny_parts1):
    """Crash the KV holder mid-generation (chaos crash_after — the
    deterministic kill): the survivor PROMOTES its replicated shadow and
    the stream completes token-exact with NO client restart."""
    parts, params = tiny_parts1
    nodes = [
        _mk(0, parts=parts, standby_repl=True, repl_interval_s=0.05,
            chaos=Chaos(crash_after=5)),
        _mk(1, parts=parts, standby_repl=True, repl_interval_s=0.05),
    ]
    await _start_all(nodes)
    try:
        engine = Engine(TINY, params, max_len=64,
                        sampling_cfg=SamplingConfig(temperature=0.0))
        prompt = [3, 7, 11, 19]
        expected = engine.generate(prompt, max_new_tokens=8)

        restarts = []

        async def on_token(tok):
            if tok is None:
                restarts.append(1)
                return
            # pace the decode so the 50 ms replication tick ships the
            # frontier before the crash at forward 6 (prefill + 4 steps
            # serve, the 6th forward kills node 0)
            await asyncio.sleep(0.06)

        async with SwarmClient(
            [("127.0.0.1", BASE + 0), ("127.0.0.1", BASE + 1)],
            sampling=SamplingConfig(temperature=0.0),
        ) as c:
            got = await c.generate_ids(
                prompt, max_new_tokens=8, session_retries=4,
                retry_delay_s=0.2, on_token=on_token,
            )
        assert got == expected
        assert restarts == [], "promotion must continue, not restart"
        counters = nodes[1].metrics.snapshot()["counters"]
        assert counters.get("repl.promotions") == 1
        assert counters.get("repl.resumed_tokens", 0) >= len(prompt)
        types = [e["type"] for e in nodes[1].journal.events()]
        assert "standby.promote" in types
        # the promoted session advertised under `sess` on the survivor
        assert counters.get("repl.stale", 0) == 0
    finally:
        await _stop_all(nodes)


@pytest.mark.asyncio
async def test_ended_session_drops_shadow_promptly(tiny_parts1):
    """A finished session's shadow must not sit in the standby's RAM
    (or keep a stale `standby` advert) for the TTL: the primary's next
    replication tick sends a drop notice."""
    parts, _params = tiny_parts1
    nodes = [
        _mk(0, parts=parts, standby_repl=True, repl_interval_s=0.05),
        _mk(1, parts=parts, standby_repl=True, repl_interval_s=0.05),
    ]
    await _start_all(nodes)
    try:
        async with SwarmClient(
            [("127.0.0.1", BASE + 0), ("127.0.0.1", BASE + 1)],
            sampling=SamplingConfig(temperature=0.0),
        ) as c:

            async def on_token(tok):
                await asyncio.sleep(0.06)  # let the tick ship a shadow

            await c.generate_ids(
                [3, 7, 11, 19], max_new_tokens=6, on_token=on_token,
            )
        # the generation ended (the client sent /end_session): within a
        # few ticks every shadow it left behind is dropped fleet-wide
        for _ in range(40):
            if all(len(n.standby) == 0 for n in nodes):
                break
            await asyncio.sleep(0.05)
        assert all(len(n.standby) == 0 for n in nodes)
    finally:
        await _stop_all(nodes)


@pytest.mark.asyncio
async def test_stale_standby_degrades_to_restart_token_exact(tiny_parts1):
    """A corrupt shadow must NEVER produce a wrong token: promotion
    fails closed (standby.stale) and the client full-restarts — exactly
    the pre-replication path — still token-exact."""
    parts, params = tiny_parts1
    nodes = [
        _mk(0, parts=parts, standby_repl=True, repl_interval_s=0.05),
        _mk(1, parts=parts, standby_repl=True, repl_interval_s=0.05),
    ]
    await _start_all(nodes)
    try:
        engine = Engine(TINY, params, max_len=64,
                        sampling_cfg=SamplingConfig(temperature=0.0))
        prompt = [3, 7, 11, 19]
        expected = engine.generate(prompt, max_new_tokens=8)
        restarts = []
        state = {"n": 0, "killed": False}

        async def on_token(tok):
            if tok is None:
                restarts.append(1)
                return
            state["n"] += 1
            await asyncio.sleep(0.06)
            if state["n"] == 4 and not state["killed"]:
                state["killed"] = True
                # corrupt EVERY shadow the standby holds (truncated k:
                # the handoff validator rejects it at import), then
                # crash the holder abruptly
                sb = nodes[1].standby
                for sid in sb.ids():
                    sh = sb._shadows[sid]
                    if sh.ks:
                        # truncate the FIRST (prompt-sized) segment: the
                        # reassembled payload then covers fewer slots
                        # than its claimed length and the handoff
                        # validator must reject it at import
                        sh.ks[0] = sh.ks[0][:, :, :1]
                await nodes[0].crash()

        async with SwarmClient(
            [("127.0.0.1", BASE + 0), ("127.0.0.1", BASE + 1)],
            sampling=SamplingConfig(temperature=0.0),
        ) as c:
            got = await c.generate_ids(
                prompt, max_new_tokens=8, session_retries=6,
                retry_delay_s=0.2, on_token=on_token,
            )
        assert got == expected
        assert len(restarts) >= 1, "stale standby must degrade to restart"
        types = [e["type"] for e in nodes[1].journal.events()]
        assert "standby.stale" in types
        assert nodes[1].metrics.snapshot()["counters"].get(
            "repl.promotions", 0
        ) == 0
    finally:
        await _stop_all(nodes)


@pytest.mark.asyncio
async def test_kill_switch_parity_flag_off(tiny_parts1):
    """--standby-repl absent: gossip records carry no `standby` key, no
    repl.* series exist at /metrics or /stats, and /replicate_session
    answers 501 — byte-identical surfaces to a build without the plane."""
    import aiohttp

    from inferd_tpu.obs import export as obs_export

    parts, _params = tiny_parts1
    nodes = [_mk(0, parts=parts), _mk(1, parts=parts)]
    await _start_all(nodes)
    try:
        async with SwarmClient(
            [("127.0.0.1", BASE + 0)],
            sampling=SamplingConfig(temperature=0.0),
        ) as c:
            await c.generate_ids([3, 7, 11, 19], max_new_tokens=4)
        await asyncio.sleep(0.3)  # a few gossip + tick periods
        for n in nodes:
            rec = n.dht.get_stage(0).get(n.info.node_id, {})
            assert "standby" not in rec
            text = obs_export.prometheus_text(n.metrics)
            assert "repl_" not in text and "standby" not in text
            snap = n.metrics.snapshot()
            assert not any(
                k.startswith("repl.") for k in snap["counters"]
            )
            assert not any(k.startswith("repl.") for k in snap["gauges"])
        async with aiohttp.ClientSession() as s:
            async with s.post(
                f"http://127.0.0.1:{BASE}/replicate_session",
                data=wire.pack({"session_id": "x", "stage": 0,
                                "k": np.zeros((1, 1, 1, 1, 1)),
                                "v": np.zeros((1, 1, 1, 1, 1)),
                                "length": 1, "start": 0}),
            ) as r:
                assert r.status == 501
                body = wire.unpack(await r.read())
                assert body["code"] == "repl_off"
            async with s.get(f"http://127.0.0.1:{BASE}/stats") as r:
                assert "repl" not in await r.json()
    finally:
        await _stop_all(nodes)


@pytest.mark.asyncio
async def test_rescue_failed_event_and_bounce_flag(tiny_parts1):
    """The rescue give-up is journaled (session.rescue_failed with
    attempts + error) and --rescue-bounces caps the loop."""
    from inferd_tpu.client.base import ServerError

    parts, _params = tiny_parts1
    nodes = [_mk(0, parts=parts, rescue_bounces=2)]
    await _start_all(nodes)
    try:
        async with SwarmClient([("127.0.0.1", BASE + 0)]) as c:
            with pytest.raises(ServerError) as ei:
                await c._post("/forward", {
                    "stage": 0, "session_id": "ghost",
                    "payload": {"tokens": np.asarray([[5]], np.int32),
                                "start_pos": 9, "real_len": 1},
                })
            assert ei.value.status == 409
            assert ei.value.code == "session_state"
        evs = [
            e for e in nodes[0].journal.events()
            if e["type"] == "session.rescue_failed"
        ]
        assert len(evs) == 1
        assert evs[0]["attrs"]["attempts"] == 2
        assert "no holder" in evs[0]["attrs"]["error"]
    finally:
        await _stop_all(nodes)


# ------------------------------------------- partial drain-handoff (sat)


async def _seed_sessions(port, sids, prompt=(3, 7, 11, 19)):
    async with SwarmClient([("127.0.0.1", port)]) as c:
        for sid in sids:
            await c._post("/forward", {
                "stage": 0, "session_id": sid,
                "payload": {
                    "tokens": np.asarray([list(prompt)], np.int32),
                    "start_pos": 0, "real_len": len(prompt),
                },
            })


@pytest.mark.asyncio
async def test_partial_handoff_no_loss_no_double_adopt(tiny_parts1):
    """_handoff_sessions with one peer whose import always fails: every
    session is adopted EXACTLY ONCE (by the healthy peer) or stays
    cleanly resident — never lost, never double-adopted."""
    parts, _params = tiny_parts1
    nodes = [_mk(i, parts=parts) for i in range(3)]
    await _start_all(nodes)
    try:
        sids = ["h1", "h2", "h3"]
        await _seed_sessions(BASE + 0, sids)
        calls = {"n": 0}
        real_import = nodes[2].executor.import_session

        def broken_import(sid, payload):
            calls["n"] += 1
            raise RuntimeError("mid-handoff import explosion")

        nodes[2].executor.import_session = broken_import
        dropped = await asyncio.wait_for(nodes[0]._drain_handoff(), 15)
        held_1 = [s for s in sids if nodes[1]._holds_session(s)]
        held_2 = [s for s in sids if nodes[2]._holds_session(s)]
        held_0 = [s for s in sids if nodes[0]._holds_session(s)]
        assert held_2 == []  # the broken peer adopted nothing
        for s in sids:
            # exactly once somewhere, or still resident on the source
            assert (s in held_1) != (s in held_0), (held_0, held_1)
        assert dropped == len(held_1)
        nodes[2].executor.import_session = real_import
    finally:
        await _stop_all(nodes)


@pytest.mark.asyncio
async def test_partial_handoff_peer_death_no_hang(tiny_parts1):
    """A peer that accepts the TCP connection and never answers (died
    mid-handoff) must not hang the drain: the per-hop timeout bounds it
    and every session still lands exactly once on the live peer."""
    parts, _params = tiny_parts1
    nodes = [_mk(i, parts=parts) for i in range(2)]

    stalled = []

    async def black_hole(reader, writer):
        stalled.append(1)
        try:
            await asyncio.sleep(30)
        finally:
            writer.close()

    server = await asyncio.start_server(black_hole, "127.0.0.1", BASE + 50)
    await _start_all(nodes)
    try:
        sids = ["p1", "p2"]
        await _seed_sessions(BASE + 0, sids)
        real_get_stage = nodes[0].dht.get_stage

        def with_fake(stage):
            m = dict(real_get_stage(stage))
            # the stalled corpse sorts FIRST so every ship tries it
            # before the live peer
            m = {"000:fake": {"host": "127.0.0.1", "port": BASE + 50,
                             "stage": 0, "load": 0, "cap": 4}, **m}
            return m

        nodes[0].dht.get_stage = with_fake
        t0 = time.monotonic()
        dropped = await asyncio.wait_for(nodes[0]._drain_handoff(), 20)
        wall = time.monotonic() - t0
        nodes[0].dht.get_stage = real_get_stage
        assert stalled, "the dead peer was never even tried"
        # bounded: ~one hop timeout (2 s), never the 30 s stall
        assert wall < 15
        for s in sids:
            on_live = nodes[1]._holds_session(s)
            on_src = nodes[0]._holds_session(s)
            assert on_live != on_src, (s, on_live, on_src)
        assert dropped == sum(
            1 for s in sids if nodes[1]._holds_session(s)
        )
    finally:
        server.close()
        await _stop_all(nodes)


# ------------------------------------------------------------ perf gate


def _failover_leg(**over):
    base = {
        "metric": "tiny_failover_recovery_ms", "value": 700.0,
        "unit": "ms", "recovery_gain": 2.2, "recovery_off_ms": 1540.0,
        "re_prefilled_on": 4, "re_prefilled_off": 96,
        "re_prefill_cap": 32, "promotions": 1, "restarts_on": 0,
        "restarts_off": 1, "token_exact": True,
    }
    base.update(over)
    return [("failover", base)]


def test_gate_failover_invariants():
    from inferd_tpu.perf.gate import check_artifact

    assert not [
        f for f in check_artifact(_failover_leg()) if f.severity == "error"
    ]
    for bad in (
        {"recovery_gain": 0.9},          # promotion lost to restart
        {"promotions": 0},               # plane never exercised
        {"restarts_on": 1},              # fell back to a restart
        {"re_prefilled_on": 96},         # saved nothing
        {"re_prefilled_on": 40},         # past the lag bound (cap 32)
        {"token_exact": False},          # divergent stream
    ):
        errs = [
            f for f in check_artifact(_failover_leg(**bad))
            if f.severity == "error"
        ]
        assert errs, f"expected a hard error for {bad}"


def test_gate_failover_prior_regression():
    from inferd_tpu.perf.gate import check_artifact

    cur = _failover_leg(recovery_gain=1.5)
    prior = _failover_leg(recovery_gain=2.5)
    errs = [
        f for f in check_artifact(cur, prior)
        if f.severity == "error" and f.check == "regression"
    ]
    assert errs and "recovery_gain" in errs[0].message
    # a small drift passes
    ok = check_artifact(_failover_leg(recovery_gain=2.1), prior)
    assert not [
        f for f in ok if f.severity == "error" and f.check == "regression"
    ]
    # a prior missing the gain SKIPS (never falls through to raw ms,
    # which is lower-is-better and would invert)
    noprior = _failover_leg()
    del noprior[0][1]["recovery_gain"]
    out = check_artifact(_failover_leg(value=9000.0), noprior)
    assert not [
        f for f in out if f.severity == "error" and f.check == "regression"
    ]
