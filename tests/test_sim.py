"""Fleet simulator (inferd_tpu.sim): determinism, control-plane scenario
gates, committed-fixture replay.

The simulator drives the REAL control plane — SwarmDHT gossip over the
in-process transport, Balancer decisions, PathFinder's long-lived
D*-Lite planner, AutoScaler, retry budgets — on a virtual clock, so
these tests assert fleet-scale behaviors (adoption races, drain waves,
migration convergence, incremental replanning, budgeted retry storms)
in seconds of wall time with byte-identical replays.
"""

import json
import os

import pytest

from inferd_tpu.sim.scenario import (
    check_fixture,
    check_gates,
    fixture_paths,
    run_scenario,
)
from inferd_tpu.sim.scenarios import scenario

SIM_DATA = os.path.join(os.path.dirname(__file__), "data", "sim")


# ------------------------------------------------------------ determinism


def test_same_seed_byte_identical_trace_and_metrics():
    """The acceptance contract: same seed + same scenario => the FULL
    event trace is byte-identical and every metric matches exactly; a
    different seed diverges (the trace hash actually covers content)."""
    cfg = scenario("hysteresis")
    a = run_scenario(cfg, seed=11, capture_trace=True)
    b = run_scenario(cfg, seed=11, capture_trace=True)
    assert a["trace_lines"] == b["trace_lines"]  # byte-identical trace
    am, bm = dict(a), dict(b)
    am.pop("trace_lines"), bm.pop("trace_lines")
    assert json.dumps(am, sort_keys=True) == json.dumps(bm, sort_keys=True)
    c = run_scenario(cfg, seed=12)
    assert c["trace"]["hash"] != a["trace"]["hash"]


def test_traffic_scenario_deterministic_with_real_workload():
    """Determinism holds with sessions, retries, and churn in play, not
    just control ticks."""
    cfg = scenario("zonal_failure", {"duration_s": 40.0})
    a = run_scenario(cfg, seed=5)
    b = run_scenario(cfg, seed=5)
    assert a == b
    assert a["sessions"]["arrived"] > 0


# -------------------------------------------------- scenario-level gates


def test_hot_stage_skew_converges_without_oscillation():
    """The cost-aware balancer moves capacity into the starved stage and
    STOPS: bounded migrations, nobody ping-pongs, goodput and routing
    quality hold."""
    m = run_scenario(scenario("hot_stage_skew"), seed=3)
    failures = check_gates(m, [
        ["balance.migrations", ">=", 1],
        ["balance.migrations", "<=", 4],
        ["balance.max_migrations_per_node", "<=", 1],
        ["balance.migrate_dst.1", ">=", 1],
        ["goodput_ratio", ">=", 0.9],
        ["route_quality.cost_ratio_mean", "<=", 1.05],
        ["sessions.hung", "==", 0],
    ])
    assert not failures, failures


def test_zonal_failure_rescues_and_replans():
    """A zone dies mid-traffic: in-flight sessions rescue, the planner
    folds the deaths in (kills > 0, incremental), goodput survives, and
    every stage keeps its surviving replicas."""
    m = run_scenario(scenario("zonal_failure"), seed=3)
    failures = check_gates(m, [
        ["sessions.rescues", ">=", 1],
        ["sessions.hung", "==", 0],
        ["goodput_ratio", ">=", 0.85],
        ["fleet.replicas_final.0", "==", 4],
        ["fleet.replicas_final.1", "==", 4],
        ["fleet.replicas_final.2", "==", 4],
        ["route_quality.cost_ratio_mean", "<=", 1.1],
    ])
    assert not failures, failures


def test_autoscale_scales_up_then_down_and_joins_splice():
    """Sustained overload triggers scale-up (load + kvfree watermarks),
    the drained-off tail triggers scale-down, and every provisioned
    join is SPLICED into the planner incrementally (node_adds, no
    per-join rebuilds)."""
    m = run_scenario(scenario("autoscale_elastic"), seed=3)
    failures = check_gates(m, [
        ["autoscale.scale_up", ">=", 1],
        ["autoscale.scale_down", ">=", 1],
        ["planner.node_adds", ">=", 1],
        ["planner.builds", "<=", 2],
        ["goodput_ratio", ">=", 0.9],
        ["sessions.hung", "==", 0],
        ["fleet.replicas_final.0", ">=", 2],
        ["fleet.replicas_final.1", ">=", 2],
    ])
    assert not failures, failures


def test_mid_fleet_churn_replans_incrementally():
    """~100-node churn (the tier-1-sized stand-in for the slow 1000-node
    fixture): deaths arrive as peer.dead increments (kills), joins as
    splices (node_adds), and the mean replan touches a small fraction of
    what a from-scratch solve expands — the vertex-expansion assertion
    from the acceptance criteria."""
    cfg = scenario("churn_1000", {
        "replicas": 12,           # 8 stages x 12 = 96 nodes
        "warmup_s": 6.0,
        "gossip_period_s": 1.0,
        "ttl_s": 5.0,
        "anti_entropy_every": 2,
        "quality_sample_every": 2,
        "events": [
            {"t": 4.0, "op": "kill_random", "count": 8, "tag": "churn"},
            {"t": 6.0, "op": "join", "stage": 2, "count": 3},
            {"t": 7.0, "op": "join", "stage": 5, "count": 3},
        ],
    })
    m = run_scenario(cfg, seed=5)
    failures = check_gates(m, [
        ["planner.builds", "<=", 2],          # one per router, no rebuilds
        ["planner.kills", ">=", 1],           # peer.dead increments
        ["planner.node_adds", ">=", 6],       # joins spliced
        ["planner.replan_frac", "<=", 0.15],  # replans << from-scratch
        ["route_quality.cost_ratio_mean", "<=", 1.05],
        ["sessions.hung", "==", 0],
        ["goodput_ratio", ">=", 0.8],
    ])
    assert not failures, (failures, m["planner"])


def test_adopt_race_multi_donor_stages_exactly_one():
    """With 3+ stages, EVERY donor stage has a min-id replica — the
    adoption tie-break must be global (fleet-wide min donor), or one
    replica per donor stage piles into the hole concurrently."""
    cfg = scenario("adopt_race", {
        "stages": 3,
        "replicas": [25, 25, 1],
        "events": [{"t": 5.0, "op": "kill", "node": "s2r000"}],
    })
    m = run_scenario(cfg, seed=7)
    failures = check_gates(m, [
        ["balance.migrations", "==", 1],
        ["balance.migrate_dst.2", "==", 1],
        ["fleet.replicas_final.2", "==", 1],
    ])
    assert not failures, (failures, m["balance"])


def test_gossip_partition_heals_clean():
    m = run_scenario(scenario("gossip_partition"), seed=3)
    failures = check_gates(m, [
        ["sessions.hung", "==", 0],
        ["sessions.failed", "==", 0],
        ["goodput_ratio", ">=", 0.95],
    ])
    assert not failures, failures


# ------------------------------------------------- balancer policy unit


def test_projected_gain_ignores_unrelated_starved_stage():
    """The cost-aware migration gate must not collapse to `gain=inf`
    because some UNRELATED stage reads starved (all-draining): that
    would bypass oscillation protection exactly during a drain wave.
    Starved stages are adoption's business; the spread prices only the
    serving stages."""
    import asyncio

    from inferd_tpu.control.balance import Balancer, stage_loads

    class FakeDHT:
        node_id = "b0"

        def __init__(self, snap):
            self.snap = snap

        def get_all(self, n):
            return self.snap

    snap = {
        0: {"a0": {"load": 2, "cap": 4}},                      # 0.5
        1: {"b0": {"load": 1, "cap": 4}, "b1": {"load": 1, "cap": 4}},  # 0.25
        2: {"c0": {"load": 9, "cap": 4, "draining": 1}},       # starved: inf
    }
    loads = stage_loads(snap)
    assert loads[2] == float("inf")
    b = Balancer(FakeDHT(snap), 3, get_own_stage=lambda: 1,
                 change_stage=None)
    gain = b._projected_gain(snap, loads, 1, 0)
    # real projection: moving b0 just SWAPS the 0.5/0.25 ratios between
    # stages 0 and 1 — zero gain. Pre-fix, stage 2's inf poisoned the
    # spread and this read +inf, waving the move through the cost gate.
    assert gain == pytest.approx(0.0)
    # a genuinely starved TARGET still projects infinite gain
    assert b._projected_gain(snap, loads, 1, 2) == float("inf")
    # and the 0.125 gain loses to the default migration_cost: no move
    moved = []

    async def change(stage):
        moved.append(stage)

    b2 = Balancer(FakeDHT(snap), 3, get_own_stage=lambda: 1,
                  change_stage=change)
    assert asyncio.run(b2.rebalance_once()) is False
    assert moved == []


# ------------------------------------------------- autoscale policy unit


def _mk_autoscaler(now, **cfg_kw):
    from inferd_tpu.control.autoscale import AutoScaler, AutoscaleConfig

    return AutoScaler(
        2, AutoscaleConfig(**cfg_kw), clock=lambda: now[0]
    )


def test_autoscale_policy_triggers_and_dwell():
    """Pure policy: load over watermark scales up (proportional step),
    kvfree/burn each trigger alone, idle scales down but never under
    min_replicas, and the per-stage dwell suppresses flapping."""
    now = [0.0]
    a = _mk_autoscaler(now, cooldown_s=30.0, min_replicas=1)

    def snap(load0, kvfree=None, burn=None, n0=2, load1=1, n1=2):
        s0 = {
            f"a{i}": {
                "load": load0, "cap": 4,
                **({"kvfree": kvfree} if kvfree is not None else {}),
                **({"burn": burn} if burn is not None else {}),
            }
            for i in range(n0)
        }
        s1 = {f"b{i}": {"load": load1, "cap": 4} for i in range(n1)}
        return {0: s0, 1: s1}

    acts = a.decide(snap(load0=4))  # ratio 1.0 >= 0.75
    assert [x.kind for x in acts] == ["scale_up"] and acts[0].stage == 0
    # dwell: the same pressure doesn't refire inside the cooldown
    assert a.decide(snap(load0=4)) == []
    now[0] = 31.0
    assert [x.kind for x in a.decide(snap(load0=4))] == ["scale_up"]

    # kvfree watermark alone (load fine) scales up
    now[0] = 100.0
    acts = _mk_autoscaler(now).decide(snap(load0=1, kvfree=0.05))
    assert [x.kind for x in acts] == ["scale_up"]
    assert "kvfree" in acts[0].reason
    # burn alone scales up
    acts = _mk_autoscaler(now).decide(snap(load0=1, burn=20.0))
    assert [x.kind for x in acts] == ["scale_up"]
    assert "burn" in acts[0].reason

    # idle stage scales down, but never under min_replicas
    acts = _mk_autoscaler(now).decide(snap(load0=0, load1=0))
    assert {(x.kind, x.stage) for x in acts} == {
        ("scale_down", 0), ("scale_down", 1)
    }
    assert _mk_autoscaler(now, min_replicas=2).decide(
        snap(load0=0, load1=0)
    ) == []


def test_autoscale_repartition_advice():
    """Misplaced-capacity advice: hottest >= 2x coldest with spare
    replicas moves one, and only when no stage needed scaling."""
    now = [0.0]
    a = _mk_autoscaler(now)
    snap = {
        0: {f"a{i}": {"load": 2, "cap": 4} for i in range(2)},   # 0.50
        1: {f"b{i}": {"load": 1, "cap": 4} for i in range(2)},   # 0.25
    }
    acts = a.decide(snap)
    assert len(acts) == 1 and acts[0].kind == "repartition"
    assert acts[0].stage == 0 and acts[0].src_stage == 1
    assert "repartition 1->0" in acts[0].render()


def test_collector_autoscale_advisory_column():
    """tools/collector with an AutoScaler fills the per-stage advisory
    column (and the kvfree_min/burn_max aggregates) from gossip."""
    import asyncio

    import io

    from inferd_tpu.control.autoscale import AutoScaler
    from inferd_tpu.tools.collector import Collector

    swarm = {
        0: {
            "a0": {"load": 4, "cap": 4, "kvfree": 0.5, "burn": 0.0},
            "a1": {"load": 4, "cap": 4, "kvfree": 0.03, "burn": 2.5},
        },
        1: {"b0": {"load": 0, "cap": 4}},
    }

    async def source():
        return swarm

    out = io.StringIO()
    coll = Collector(source, out, autoscaler=AutoScaler(2))
    asyncio.run(coll.sample_once())
    text = out.getvalue()
    header, row0, row1 = text.strip().split("\r\n")[:3]
    assert "kvfree_min" in header and "burn_max" in header
    assert "0.03" in row0 and "2.5" in row0
    assert "scale_up stage 0" in row0
    assert coll.autoscale_actions >= 1
    # stage 1 idle but at min_replicas=1: no advice
    assert "scale" not in row1


# ------------------------------------------------------ fixture contract


def _fast_fixtures():
    if not os.path.isdir(SIM_DATA):
        return []
    return fixture_paths(SIM_DATA, include_slow=False)


def test_fixture_dir_has_fast_fixtures():
    """run.sh step 0g replays this directory; it must exist and carry
    fast fixtures (an empty dir would make the advisory step vacuous)."""
    assert _fast_fixtures(), f"no fast fixtures under {SIM_DATA}"


@pytest.mark.parametrize(
    "path", _fast_fixtures(), ids=lambda p: os.path.basename(p)
)
def test_committed_fixture_replays(path):
    """Every committed non-slow fixture replays byte-identically (expect
    block: trace hash + headline counts) and passes its gates."""
    ok, failures, _metrics = check_fixture(path)
    assert ok, failures


@pytest.mark.slow
def test_churn_1000_fixture_replays():
    """The 1000-node churn rehearsal (acceptance criteria): real
    Balancer/PathFinder/D*-Lite at fleet scale — routing within 5% of
    offline-optimal, incremental replans, bounded migrations, goodput
    vs the committed fixture, byte-identical trace."""
    path = os.path.join(SIM_DATA, "churn_1000.json")
    ok, failures, metrics = check_fixture(path)
    assert ok, (failures, metrics.get("planner"), metrics.get("sessions"))
