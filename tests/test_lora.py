"""LoRA adapter loading + merged-weight parity (ops.lora).

Golden reference is HF peft itself: a tiny Qwen3 base wrapped in a peft
LoraConfig with randomized A/B, saved with save_pretrained, loaded back
through our adapter loader, merged into the converted base params — logits
must match the live peft model. Added TPU-native scope (the reference has
no adapter story, SURVEY §2)."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from inferd_tpu.config import TINY, ModelConfig
from inferd_tpu.models import qwen3
from inferd_tpu.models.loader import params_from_hf_state_dict
from inferd_tpu.ops import lora as loralib


def _peft_setup(tmp_path):
    torch = pytest.importorskip("torch")
    peft = pytest.importorskip("peft")
    import transformers

    hf_cfg = transformers.Qwen3Config(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        head_dim=16, max_position_embeddings=512, rope_theta=1e6,
        tie_word_embeddings=True,
    )
    base = transformers.Qwen3ForCausalLM(hf_cfg)
    cfg = ModelConfig(
        name="tiny-lora-parity", vocab_size=256, hidden_size=64,
        intermediate_size=128, num_layers=2, num_heads=4, num_kv_heads=2,
        head_dim=16, max_position_embeddings=512, dtype="float32",
    )
    # convert base params BEFORE peft wraps the projections in LoraLayers
    # (which renames weights to ...base_layer.weight)
    base_params = params_from_hf_state_dict(cfg, base.state_dict())
    lcfg = peft.LoraConfig(
        r=4, lora_alpha=8,
        target_modules=["q_proj", "k_proj", "v_proj", "o_proj",
                        "gate_proj", "up_proj", "down_proj"],
        lora_dropout=0.0, bias="none",
    )
    model = peft.get_peft_model(base, lcfg)
    # lora_B inits to zero (identity adapter) — randomize so the merge is real
    with torch.no_grad():
        for name, p in model.named_parameters():
            if "lora_A" in name or "lora_B" in name:
                p.normal_(0.0, 0.05)
    model.eval()
    adir = str(tmp_path / "adapter")
    model.save_pretrained(adir)
    return torch, model, cfg, base_params, adir


def test_merged_lora_matches_peft(tmp_path):
    """save_pretrained -> load_adapter -> merge_adapter == live peft model."""
    torch, model, cfg, base_params, adir = _peft_setup(tmp_path)
    adapter = loralib.load_adapter(cfg, adir)
    merged = loralib.merge_adapter(base_params, adapter)

    tokens_np = np.array([[3, 17, 42, 99, 7, 250]], dtype=np.int64)
    with torch.no_grad():
        ref = model(torch.from_numpy(tokens_np)).logits.float().numpy()
    got, _, _ = qwen3.forward(merged, cfg, jnp.asarray(tokens_np))
    np.testing.assert_allclose(np.asarray(got), ref, rtol=2e-4, atol=2e-4)

    # and the merge is not a no-op
    plain, _, _ = qwen3.forward(base_params, cfg, jnp.asarray(tokens_np))
    assert not np.allclose(np.asarray(got), np.asarray(plain), atol=1e-3)


def test_stage_sliced_merge_matches_full(tmp_path):
    """Per-stage merge (slice_adapter over a checkpoint slice, the run_node
    --lora path) == merging the full model then slicing."""
    _, _, cfg, base_params, adir = _peft_setup(tmp_path)
    adapter = loralib.load_adapter(cfg, adir)
    full = loralib.merge_adapter(base_params, adapter)

    for start, end in ((0, 1), (1, 2)):
        stage_params = {
            "layers": qwen3.slice_layers(base_params["layers"], start, end)
        }
        got = loralib.merge_adapter(
            stage_params, loralib.slice_adapter(adapter, start, end)
        )
        want = qwen3.slice_layers(full["layers"], start, end)
        for name in want:
            np.testing.assert_allclose(
                np.asarray(got["layers"][name]), np.asarray(want[name]),
                rtol=1e-6, atol=1e-6, err_msg=f"stage [{start},{end}) {name}",
            )


def test_adapter_validation():
    cfg = dataclasses.replace(TINY, num_layers=2)
    with pytest.raises(ValueError, match="no LoRA parameters"):
        loralib.adapter_from_state_dict(cfg, {"not.a.lora.key": np.zeros(1)}, 8, 4)
    # gap in layer coverage
    sd = {
        "base_model.model.model.layers.0.self_attn.q_proj.lora_A.weight":
            np.zeros((4, 64), np.float32),
        "base_model.model.model.layers.0.self_attn.q_proj.lora_B.weight":
            np.zeros((64, 4), np.float32),
    }
    with pytest.raises(ValueError, match="misses layers"):
        loralib.adapter_from_state_dict(cfg, sd, 8, 4)


def _full_sd(num_layers, r=4, extra=None):
    sd = {}
    for i in range(num_layers):
        pre = f"base_model.model.model.layers.{i}.self_attn.q_proj"
        sd[f"{pre}.lora_A.weight"] = np.zeros((r, 64), np.float32)
        sd[f"{pre}.lora_B.weight"] = np.zeros((64, r), np.float32)
    if extra:
        sd.update(extra)
    return sd


def test_adapter_rejects_out_of_scope_targets():
    """lm_head / embedding adapters must error, not silently drop."""
    cfg = dataclasses.replace(TINY, num_layers=2)
    sd = _full_sd(2, extra={
        "base_model.model.lm_head.lora_A.weight": np.zeros((4, 64), np.float32),
    })
    with pytest.raises(ValueError, match="outside the supported"):
        loralib.adapter_from_state_dict(cfg, sd, 8, 4)


def test_adapter_rejects_layer_overrun():
    """An adapter for a DEEPER model than cfg must error, not truncate."""
    cfg = dataclasses.replace(TINY, num_layers=2)
    with pytest.raises(ValueError, match="only 2 layers"):
        loralib.adapter_from_state_dict(cfg, _full_sd(4), 8, 4)


def test_adapter_rejects_missing_half():
    """lora_A without its lora_B is a diagnostic error, not a KeyError."""
    cfg = dataclasses.replace(TINY, num_layers=2)
    sd = _full_sd(2)
    del sd["base_model.model.model.layers.1.self_attn.q_proj.lora_B.weight"]
    with pytest.raises(ValueError, match="layer 1 lora_B"):
        loralib.adapter_from_state_dict(cfg, sd, 8, 4)


def test_rslora_scale():
    """use_rslora=True merges with alpha/sqrt(r), not alpha/r."""
    cfg = dataclasses.replace(TINY, num_layers=2)
    plain = loralib.adapter_from_state_dict(cfg, _full_sd(2, r=4), 8, 4)
    rs = loralib.adapter_from_state_dict(cfg, _full_sd(2, r=4), 8, 4, rslora=True)
    assert plain["scale"] == pytest.approx(2.0)
    assert rs["scale"] == pytest.approx(4.0)


def test_rslora_scale_via_load_adapter(tmp_path):
    """adapter_config.json's use_rslora flag actually reaches the scale
    through the FULL load path (save_adapter -> load_adapter), not just
    the parser — scale = alpha/sqrt(r)."""
    layers = {
        "q_proj": (np.zeros((2, 64, 4), np.float32),
                   np.zeros((2, 4, 64), np.float32)),
    }
    cfg = dataclasses.replace(TINY, num_layers=2)
    p = loralib.save_adapter(str(tmp_path / "rs"), layers, alpha=8, r=4,
                             rslora=True)
    assert loralib.load_adapter(cfg, p)["scale"] == pytest.approx(4.0)
    p2 = loralib.save_adapter(str(tmp_path / "plain"), layers, alpha=8, r=4)
    assert loralib.load_adapter(cfg, p2)["scale"] == pytest.approx(2.0)


def test_rank_mismatch_error_identity():
    """A/B whose rank disagrees with the declared r raises the NAMED
    rank-mismatch error (with the target and both shapes), never a
    silent mis-scale or a downstream shape explosion."""
    cfg = dataclasses.replace(TINY, num_layers=2)
    with pytest.raises(ValueError, match=r"rank mismatch for 'q_proj'.*r=8"):
        loralib.adapter_from_state_dict(cfg, _full_sd(2, r=4), 8, 8)
