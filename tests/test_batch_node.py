"""Continuous-batching node serving (runtime/batch_executor.py): concurrent
SwarmClient generations against ONE batched node must each match solo-engine
output exactly, with decode steps actually coalescing; plus session eviction
and restart semantics."""

import asyncio

import jax
import pytest

from inferd_tpu.client.swarm_client import SwarmClient
from inferd_tpu.config import TINY, SamplingConfig
from inferd_tpu.control.dht import SwarmDHT
from inferd_tpu.core.generate import Engine
from inferd_tpu.models import qwen3
from inferd_tpu.parallel.stages import Manifest, split_and_save
from inferd_tpu.runtime.node import Node, NodeInfo

BASE = 18700  # distinct block from test_mesh_node (18600)


@pytest.fixture(scope="module")
def whole_parts(tmp_path_factory):
    parts = tmp_path_factory.mktemp("whole")
    params = qwen3.init_params(TINY, jax.random.PRNGKey(0))
    manifest = Manifest.even_split("tiny", 1)
    split_and_save(params, TINY, manifest, str(parts))
    return str(parts), params


def _mk_batched_node(idx, parts, lanes=4):
    info = NodeInfo(
        name=f"bn{idx}", host="127.0.0.1", port=BASE + idx,
        stage=0, num_stages=1, capacity=8, model_name="tiny",
    )
    dht = SwarmDHT(
        info.node_id, BASE + 100 + idx, bootstrap=[],
        host="127.0.0.1", gossip_period_s=0.05, ttl_s=5.0,
    )
    return Node(
        info, TINY, parts, dht, backend="qwen3", max_len=64,
        rebalance_period_s=600.0, batch_lanes=lanes,
    )


@pytest.mark.asyncio
async def test_concurrent_generations_match_solo(whole_parts):
    parts, params = whole_parts
    node = _mk_batched_node(0, parts)
    await node.start()
    try:
        prompts = [[3, 7, 11], [2, 5, 13, 17], [23, 29], [31, 37, 41, 43, 47]]
        sc = SamplingConfig(temperature=0.0)
        engine = Engine(TINY, params, max_len=64, sampling_cfg=sc)
        want = [engine.generate(p, max_new_tokens=8, seed=0) for p in prompts]

        async def one(p):
            async with SwarmClient([("127.0.0.1", BASE)], sampling=sc) as c:
                return await c.generate_ids(p, max_new_tokens=8)

        got = await asyncio.gather(*(one(p) for p in prompts))
        assert list(got) == want
    finally:
        await node.stop()


def test_decode_steps_actually_batch(whole_parts):
    """Decode steps of co-arriving sessions must coalesce into one device
    step. Driven directly (threads + barrier) so co-arrival is guaranteed
    rather than hoped for from HTTP timing."""
    import threading

    from inferd_tpu.runtime.batch_executor import BatchedExecutor

    parts, params = whole_parts
    ex = BatchedExecutor(TINY, params, lanes=4, max_len=64, window_ms=100.0)

    hwm = {"n": 0}

    class TrackingList(list):
        def append(self, item):
            super().append(item)
            hwm["n"] = max(hwm["n"], len(self))

    ex._batcher._pending = TrackingList(ex._batcher._pending)

    sessions = [f"s{i}" for i in range(3)]
    last = {}
    for i, s in enumerate(sessions):
        r = ex.process(s, {"tokens": [[3 + i, 7, 11]], "start_pos": 0, "real_len": 3})
        last[s] = int(r["logits"][0].argmax())

    barrier = threading.Barrier(len(sessions))
    results = {}

    def step(s):
        barrier.wait()
        results[s] = ex.process(
            s, {"tokens": [[last[s]]], "start_pos": 3, "real_len": 1}
        )

    threads = [threading.Thread(target=step, args=(s,)) for s in sessions]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert len(results) == 3
    assert hwm["n"] >= 2, "no decode step ever batched >1 session"
    # and the batched logits match a solo decode of the same session state
    for s in sessions:
        assert results[s]["logits"].shape == (1, TINY.vocab_size)


@pytest.mark.asyncio
async def test_lane_eviction_and_restart(whole_parts):
    """More sessions than lanes: LRU eviction frees lanes; an evicted
    session resuming mid-stream gets a clean session_state error and the
    client restarts transparently."""
    parts, params = whole_parts
    node = _mk_batched_node(2, parts, lanes=2)
    await node.start()
    try:
        sc = SamplingConfig(temperature=0.0)
        engine = Engine(TINY, params, max_len=64, sampling_cfg=sc)
        prompts = [[3, 7, 11], [2, 5, 13], [23, 29, 31], [37, 41, 43]]
        want = [engine.generate(p, max_new_tokens=6, seed=0) for p in prompts]

        async def one(p):
            async with SwarmClient([("127.0.0.1", BASE + 2)], sampling=sc) as c:
                # capacity backpressure (503 busy) retries the whole
                # generation; under full-suite load the in-flight ones
                # finish slowly, so give the retry loop more headroom than
                # the default 2 attempts
                return await c.generate_ids(p, max_new_tokens=6, session_retries=6)

        got = await asyncio.gather(*(one(p) for p in prompts))
        assert list(got) == want
    finally:
        await node.stop()


@pytest.mark.asyncio
async def test_quantized_batched_node_matches_quantized_engine(whole_parts):
    """--quant int8 + --batch-lanes compose: concurrent generations against
    a quantized batched node equal the solo engine on the SAME quantized
    params (greedy)."""
    from inferd_tpu.ops import quant

    parts, params = whole_parts
    info = NodeInfo(
        name="bq0", host="127.0.0.1", port=BASE + 40,
        stage=0, num_stages=1, capacity=8, model_name="tiny",
    )
    dht = SwarmDHT(
        info.node_id, BASE + 140, bootstrap=[],
        host="127.0.0.1", gossip_period_s=0.05, ttl_s=5.0,
    )
    node = Node(
        info, TINY, parts, dht, backend="qwen3", max_len=64,
        rebalance_period_s=600.0, batch_lanes=3, quant="int8",
    )
    await node.start()
    try:
        qparams = quant.quantize_params(
            params, tie_word_embeddings=TINY.tie_word_embeddings
        )
        sc = SamplingConfig(temperature=0.0)
        engine = Engine(TINY, qparams, max_len=64, sampling_cfg=sc)
        prompts = [[3, 7, 11], [2, 5, 13, 17], [23, 29]]
        want = [engine.generate(p, max_new_tokens=6, seed=0) for p in prompts]

        async def one(p):
            async with SwarmClient([("127.0.0.1", BASE + 40)], sampling=sc) as c:
                return await c.generate_ids(p, max_new_tokens=6)

        got = await asyncio.gather(*(one(p) for p in prompts))
        assert list(got) == want
    finally:
        await node.stop()


@pytest.mark.asyncio
async def test_int4_node_matches_int4_engine(whole_parts):
    """--quant int4 serves end to end: the node's group-wise int4 stage
    generates exactly what a solo engine over the SAME int4 params does
    (greedy) — the serving wiring (executor quantize hook, stage load,
    tied-head shadow) composes with the new format."""
    from inferd_tpu.ops import quant

    parts, params = whole_parts
    info = NodeInfo(
        name="i4", host="127.0.0.1", port=BASE + 41,
        stage=0, num_stages=1, capacity=8, model_name="tiny",
    )
    dht = SwarmDHT(
        info.node_id, BASE + 141, bootstrap=[],
        host="127.0.0.1", gossip_period_s=0.05, ttl_s=5.0,
    )
    node = Node(
        info, TINY, parts, dht, backend="qwen3", max_len=64,
        rebalance_period_s=600.0, quant="int4",
    )
    await node.start()
    try:
        qparams = quant.apply_quant_mode(
            "int4", params, tie_word_embeddings=TINY.tie_word_embeddings
        )
        sc = SamplingConfig(temperature=0.0)
        engine = Engine(TINY, qparams, max_len=64, sampling_cfg=sc)
        prompt = [3, 7, 11, 19]
        want = engine.generate(prompt, max_new_tokens=6)
        async with SwarmClient([("127.0.0.1", BASE + 41)], sampling=sc) as c:
            got = await c.generate_ids(prompt, max_new_tokens=6)
        assert got == want
    finally:
        await node.stop()


@pytest.mark.asyncio
async def test_chain_client_against_batched_node(whole_parts):
    """ChainClient (fixed hub-and-spoke, reference rpc_client.py topology)
    drives a 1-stage batched node identically to the swarm client."""
    from inferd_tpu.client.chain_client import ChainClient

    parts, params = whole_parts
    node = _mk_batched_node(5, parts)
    await node.start()
    try:
        sc = SamplingConfig(temperature=0.0)
        engine = Engine(TINY, params, max_len=64, sampling_cfg=sc)
        prompt = [3, 7, 11, 19]
        want = engine.generate(prompt, max_new_tokens=6, seed=0)
        async with ChainClient([("127.0.0.1", BASE + 5)], sampling=sc) as c:
            got = await c.generate_ids(prompt, max_new_tokens=6)
        assert got == want
    finally:
        await node.stop()


@pytest.mark.asyncio
async def test_batched_replica_graceful_death_failover(whole_parts):
    """Two --batch-lanes replicas: the serving one STOPS mid-generation,
    hands its lane KV to the survivor, and the client (failing over on the
    dead entry) completes token-exact with session_retries=0 — the
    zero-restart failover story on the continuous-batching path."""
    parts, params = whole_parts
    nodes = []
    for i in range(2):
        info = NodeInfo(
            name=f"gf{i}", host="127.0.0.1", port=BASE + 30 + i,
            stage=0, num_stages=1, capacity=8, model_name="tiny",
        )
        dht = SwarmDHT(
            info.node_id, BASE + 130 + i,
            bootstrap=[] if i == 0 else [("127.0.0.1", BASE + 130)],
            host="127.0.0.1", gossip_period_s=0.05, ttl_s=5.0,
        )
        nodes.append(Node(
            info, TINY, parts, dht, backend="qwen3", max_len=64,
            rebalance_period_s=600.0, batch_lanes=2,
        ))
    for n in nodes:
        await n.start()
    for _ in range(100):
        if all(len(n.dht.get_stage(0)) == 2 for n in nodes):
            break
        await asyncio.sleep(0.05)
    stopped = []
    try:
        engine = Engine(TINY, params, max_len=64,
                        sampling_cfg=SamplingConfig(temperature=0.0))
        prompt = [3, 7, 11, 19, 5]
        want = engine.generate(prompt, max_new_tokens=8)

        killed = {}

        async def kill_serving_entry():
            for _ in range(1200):
                for n in nodes:
                    if len(n.executor.sessions):
                        await n.stop()
                        stopped.append(n)
                        killed["node"] = n
                        return
                await asyncio.sleep(0.05)

        async with SwarmClient(
            [("127.0.0.1", BASE + 30), ("127.0.0.1", BASE + 31)],
            sampling=SamplingConfig(temperature=0.0), timeout_s=60.0,
        ) as c:
            task = asyncio.create_task(kill_serving_entry())
            got = await c.generate_ids(prompt, max_new_tokens=8,
                                       session_retries=0)
            await task
        assert killed.get("node") is not None
        assert got == want
        survivor = [n for n in nodes if n is not killed["node"]][0]
        m = survivor.metrics.snapshot()["counters"]
        assert m.get("sessions.imported", 0) >= 1
    finally:
        for n in nodes:
            if n not in stopped:
                await n.stop()
