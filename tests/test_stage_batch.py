"""Stage-level continuous batching: runtime/stage_batch (lane-slotted
multi-session stage executor), runtime/window's drain/gang continuous-
batching mode, and the node-level arrival window with coalesced relay.

The contract under test everywhere: co-batching decode steps of
concurrent sessions must NEVER change what any session decodes — every
path is asserted token-exact against the solo (batch-of-one) pipeline.
"""

import threading
import time

import numpy as np
import pytest

from inferd_tpu.runtime.window import WindowedBatcher

# ---------------------------------------------------------------------------
# WindowedBatcher: invalidate / drain / gang (no jax, no model)
# ---------------------------------------------------------------------------


def _direct_flush(run=None):
    """run_batch that serves entries in place (classic mode)."""
    seen = []

    def run_batch(entries):
        for e in entries:
            seen.append(e.payload)
            e.result = ("ok", e.payload)
        if run:
            run(entries)

    return run_batch, seen


def test_invalidate_fails_waiting_entry_fast():
    """A session torn down while its entry is still WAITING in the window
    fails fast with the teardown error and never reaches run_batch — the
    freed lane's next owner can never race a stale write."""
    run_batch, seen = _direct_flush()
    b = WindowedBatcher(0.05, run_batch, co_possible=lambda: True)

    results = {}

    def submit(tag):
        try:
            results[tag] = b.submit((tag, "payload"))
        except Exception as e:
            results[tag] = e

    t1 = threading.Thread(target=submit, args=("a",))
    t1.start()  # becomes the flusher, sleeps the 50 ms window
    time.sleep(0.01)
    t2 = threading.Thread(target=submit, args=("b",))
    t2.start()  # waiter
    time.sleep(0.01)
    err = ValueError("session b ended mid-request")
    t0 = time.monotonic()
    b.invalidate(lambda p: p[0] == "b", err)
    t1.join(timeout=5)
    t2.join(timeout=5)
    assert time.monotonic() - t0 < 2.0  # fail-fast, not wait_timeout_s
    assert results["b"] is err
    assert results["a"] == ("ok", ("a", "payload"))
    # the invalidated entry never executed
    assert ("b", "payload") not in seen


def test_invalidated_entry_skipped_even_when_flushers_own():
    """Invalidating the FLUSHER's own entry mid-window: the flusher must
    raise the teardown error, and run_batch must not see the entry."""
    run_batch, seen = _direct_flush()
    b = WindowedBatcher(0.05, run_batch, co_possible=lambda: True)
    got = {}

    def submit():
        try:
            got["r"] = b.submit(("a", 1))
        except Exception as e:
            got["r"] = e

    t = threading.Thread(target=submit)
    t.start()
    time.sleep(0.01)
    err = ValueError("session a ended mid-request")
    b.invalidate(lambda p: p[0] == "a", err)
    t.join(timeout=5)
    assert got["r"] is err and seen == []


def _drain_flush(b_ref, record):
    """swap_in_run-mode run_batch: drains the pending list itself and owns
    result + event delivery for every drained entry (the node contract)."""

    def run_batch(entries):
        assert entries == []  # swap_in_run always passes an empty list
        drained = b_ref[0].drain_pending()
        record.append([e.payload for e in drained])
        for e in drained:
            e.result = ("ok", e.payload)
            e.event.set()

    return run_batch


def test_swap_in_run_drain_serves_all_pending():
    record = []
    b_ref = [None]
    b = WindowedBatcher(
        0.03, _drain_flush(b_ref, record), co_possible=lambda: True,
        swap_in_run=True,
    )
    b_ref[0] = b
    results = {}

    def submit(tag):
        results[tag] = b.submit((tag,))

    ts = [threading.Thread(target=submit, args=(t,)) for t in "abc"]
    for t in ts:
        t.start()
        time.sleep(0.002)
    for t in ts:
        t.join(timeout=5)
    assert results == {t: ("ok", (t,)) for t in "abc"}
    # everything pending was folded into the drains; nothing was dropped
    assert sorted(p for batch in record for (p,) in batch) == ["a", "b", "c"]
    assert b.stats()["batched_tokens"] == 3


def test_swap_in_run_invalidate_still_fails_fast():
    """invalidate in drain mode: the entry leaves the pending list before
    any drain, and its submitter raises the teardown error."""
    record = []
    b_ref = [None]
    b = WindowedBatcher(
        0.05, _drain_flush(b_ref, record), co_possible=lambda: True,
        swap_in_run=True,
    )
    b_ref[0] = b
    got = {}

    def submit(tag):
        try:
            got[tag] = b.submit((tag,))
        except Exception as e:
            got[tag] = e

    t1 = threading.Thread(target=submit, args=("a",))
    t1.start()
    time.sleep(0.01)
    err = ValueError("session a ended mid-request")
    b.invalidate(lambda p: p[0] == "a", err)
    t1.join(timeout=5)
    assert got["a"] is err
    assert all(("a",) not in batch for batch in record)


def test_gang_wait_flushes_early_at_target():
    """With a gang target, the flusher must flush as soon as the target
    count is pending — well before the (deliberately long) window cap."""
    record = []
    b_ref = [None]
    b = WindowedBatcher(
        5.0, _drain_flush(b_ref, record), co_possible=lambda: True,
        swap_in_run=True, gang_target=lambda: 2,
    )
    b_ref[0] = b
    results = {}

    def submit(tag):
        results[tag] = b.submit((tag,))

    t0 = time.monotonic()
    t1 = threading.Thread(target=submit, args=("a",))
    t2 = threading.Thread(target=submit, args=("b",))
    t1.start()
    time.sleep(0.01)
    t2.start()
    t1.join(timeout=10)
    t2.join(timeout=10)
    assert time.monotonic() - t0 < 2.0  # gang met -> no 5 s window
    assert results == {"a": ("ok", ("a",)), "b": ("ok", ("b",))}
    assert record and len(record[0]) == 2  # ONE co-batch of both


# ---------------------------------------------------------------------------
# BatchedStageExecutor: co-batched parity with the solo stage pipeline
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def stage_setup():
    import jax

    from inferd_tpu.config import TINY
    from inferd_tpu.models import qwen3
    from inferd_tpu.parallel.stages import Manifest, extract_stage_params

    params = qwen3.init_params(TINY, jax.random.PRNGKey(0))
    manifest = Manifest.even_split("tiny", 2)
    specs = list(manifest.stage_specs())
    sp = [extract_stage_params(params, TINY, s) for s in specs]
    return TINY, params, specs, sp


def _solo_chain(cfg, specs, sp, prompt, steps):
    """Reference stream: batch-of-one stage executors, greedy."""
    from inferd_tpu.runtime.executor import Qwen3StageExecutor

    e0 = Qwen3StageExecutor(cfg, specs[0], sp[0], max_len=64)
    e1 = Qwen3StageExecutor(cfg, specs[1], sp[1], max_len=64)
    r0 = e0.process("r", {"tokens": [prompt], "start_pos": 0, "real_len": len(prompt)})
    r1 = e1.process("r", {"hidden": r0["hidden"], "start_pos": 0, "real_len": len(prompt)})
    out = [int(np.argmax(r1["logits"][0]))]
    pos = len(prompt)
    for _ in range(steps - 1):
        r0 = e0.process("r", {"tokens": [[out[-1]]], "start_pos": pos, "real_len": 1})
        r1 = e1.process("r", {"hidden": r0["hidden"], "start_pos": pos, "real_len": 1})
        out.append(int(np.argmax(r1["logits"][0])))
        pos += 1
    return out


def test_cobatch_matches_solo_mixed_positions(stage_setup):
    """Sessions at DIFFERENT positions co-batch into one device step per
    stage and each stream equals its solo run, token for token."""
    from inferd_tpu.runtime.stage_batch import BatchedStageExecutor

    cfg, _params, specs, sp = stage_setup
    b0 = BatchedStageExecutor(cfg, specs[0], sp[0], lanes=4, max_len=64)
    b1 = BatchedStageExecutor(cfg, specs[1], sp[1], lanes=4, max_len=64)
    prompts = {"x": [3, 7, 11, 19], "y": [5, 2], "z": [9, 9, 4]}
    steps = 5
    state = {}
    for sid, p in prompts.items():
        r0 = b0.process(sid, {"tokens": [p], "start_pos": 0, "real_len": len(p)})
        r1 = b1.process(sid, {"hidden": r0["hidden"], "start_pos": 0, "real_len": len(p)})
        state[sid] = {"pos": len(p), "out": [int(np.argmax(r1["logits"][0]))]}
    for _ in range(steps - 1):
        items0 = [
            (sid, {"tokens": [[state[sid]["out"][-1]]],
                   "start_pos": state[sid]["pos"], "real_len": 1})
            for sid in prompts
        ]
        outs0 = b0.process_batch(items0)
        assert not any(isinstance(o, Exception) for o in outs0)
        items1 = [
            (sid, {"hidden": o["hidden"], "start_pos": state[sid]["pos"],
                   "real_len": 1})
            for (sid, _), o in zip(items0, outs0)
        ]
        outs1 = b1.process_batch(items1)
        for (sid, _), o in zip(items1, outs1):
            state[sid]["out"].append(int(np.argmax(o["logits"][0])))
            state[sid]["pos"] += 1
    assert b0.stats()["batched_steps"] == steps - 1  # truly ONE step per round
    assert b0.stats()["mean_batch"] == 3.0
    for sid, p in prompts.items():
        assert state[sid]["out"] == _solo_chain(cfg, specs, sp, p, steps), sid


def test_per_item_rejection_does_not_fail_cobatch(stage_setup):
    """A stale/unknown session in the window 409s alone; its co-batch
    still decodes correctly (per-item errors, never batch-wide)."""
    from inferd_tpu.runtime.stage_batch import BatchedStageExecutor

    cfg, _params, specs, sp = stage_setup
    b0 = BatchedStageExecutor(cfg, specs[0], sp[0], lanes=4, max_len=64)
    p = [3, 7, 11, 19]
    b0.process("live", {"tokens": [p], "start_pos": 0, "real_len": len(p)})
    outs = b0.process_batch([
        ("live", {"tokens": [[1]], "start_pos": len(p), "real_len": 1}),
        ("ghost", {"tokens": [[1]], "start_pos": 9, "real_len": 1}),
    ])
    assert isinstance(outs[1], ValueError)  # unknown session -> 409 class
    assert not isinstance(outs[0], Exception)
    assert outs[0]["hidden"].shape[:2] == (1, 1)


def test_session_end_mid_window_fails_fast_and_lane_is_reusable(stage_setup):
    """The acceptance scenario: a session ends while its decode entry is
    still waiting in the window. The entry fails fast with the teardown
    error (never a stale write), and the freed lane serves a NEW session
    with a correct stream."""
    from inferd_tpu.runtime.stage_batch import BatchedStageExecutor

    cfg, _params, specs, sp = stage_setup
    ex = BatchedStageExecutor(cfg, specs[0], sp[0], lanes=2, max_len=64)

    # node-style wiring (runtime/node._attach_window)
    def run_batch(entries):
        assert entries == []
        drained = ex.window.drain_pending()
        outs = ex.process_batch([(e.payload[0], e.payload[1]) for e in drained])
        for e, o in zip(drained, outs):
            if isinstance(o, Exception):
                e.error = o
            else:
                e.result = o
            e.event.set()

    ex.window = WindowedBatcher(
        1.0, run_batch, co_possible=ex.co_possible, swap_in_run=True,
        gang_target=ex.gang_target,
    )
    ex.on_drop = lambda sid: ex.window.invalidate(
        lambda payload, _sid=sid: payload[0] == _sid,
        ValueError(f"session {sid} ended mid-request"),
    )

    prompt = [3, 7, 11, 19]
    ex.process("a", {"tokens": [prompt], "start_pos": 0, "real_len": len(prompt)})
    # a second live-but-idle session makes co_possible true AND keeps the
    # gang target at 2, so the flusher genuinely WAITS in the (1 s)
    # window — the interval where the teardown must catch the entry
    ex.process("b", {"tokens": [prompt], "start_pos": 0, "real_len": len(prompt)})
    got = {}

    def submit():
        try:
            got["r"] = ex.window.submit(
                ("a", {"tokens": [[1]], "start_pos": len(prompt), "real_len": 1})
            )
        except Exception as e:
            got["r"] = e

    t0 = time.monotonic()
    t = threading.Thread(target=submit)
    t.start()
    time.sleep(0.05)
    ex.end_session("a")  # -> on_drop -> invalidate pending entry
    t.join(timeout=10)
    assert time.monotonic() - t0 < 0.9  # failed FAST, not at the window cap
    assert isinstance(got["r"], ValueError)
    assert "ended mid-request" in str(got["r"])
    assert "a" not in ex
    # the freed lane serves a fresh session with the exact solo stream
    out = ex.process("c", {"tokens": [prompt], "start_pos": 0, "real_len": len(prompt)})
    step = ex.process("c", {"tokens": [[5]], "start_pos": len(prompt), "real_len": 1})
    assert out["hidden"].shape[1] == len(prompt)
    assert step["hidden"].shape[:2] == (1, 1)
    assert len(ex) == 2 and "c" in ex and "b" in ex


def test_replay_rollback_and_overflow(stage_setup):
    """Decode replay (client re-sent after a lost response) recomputes
    token-exactly; overflow past max_len raises BufferError."""
    from inferd_tpu.runtime.stage_batch import BatchedStageExecutor

    cfg, _params, specs, sp = stage_setup
    ex = BatchedStageExecutor(cfg, specs[0], sp[0], lanes=2, max_len=64)
    prompt = [3, 7, 11, 19]
    ex.process("s", {"tokens": [prompt], "start_pos": 0, "real_len": len(prompt)})
    r1 = ex.process("s", {"tokens": [[5]], "start_pos": len(prompt), "real_len": 1})
    # replay the same step (frontier rolled back, recomputed identically)
    r2 = ex.process("s", {"tokens": [[5]], "start_pos": len(prompt), "real_len": 1})
    np.testing.assert_array_equal(r1["hidden"], r2["hidden"])
    with pytest.raises(ValueError, match="out-of-order"):
        ex.process("s", {"tokens": [[5]], "start_pos": 50, "real_len": 1})
    with pytest.raises(BufferError):
        ex.process("s", {"tokens": [[0] * 60], "start_pos": len(prompt) + 1,
                         "real_len": 60})


# ---------------------------------------------------------------------------
# Multi-step fused decode on the stage-batch executor (single-stage swarm)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def single_stage_setup():
    import jax

    from inferd_tpu.config import TINY
    from inferd_tpu.models import qwen3
    from inferd_tpu.parallel.stages import StageSpec, extract_stage_params

    params = qwen3.init_params(TINY, jax.random.PRNGKey(0))
    spec = StageSpec(0, 1, 0, TINY.num_layers - 1)
    sp = extract_stage_params(params, TINY, spec)
    return TINY, spec, sp


_SAMP = {"temperature": 0.8, "top_k": 8, "top_p": 0.95}


def _solo_kstep(cfg, spec, sp, prompt, steps, seed):
    """Reference stream: the solo executor's K=1 on-device sampled loop."""
    from inferd_tpu.runtime.executor import Qwen3StageExecutor

    ex = Qwen3StageExecutor(cfg, spec, sp, max_len=64)
    r = ex.process("r", {"tokens": [prompt], "start_pos": 0,
                         "real_len": len(prompt)})
    out = [int(np.argmax(r["logits"][0]))]
    pos = len(prompt)
    key = None
    while len(out) < steps:
        pl = {"tokens": [[out[-1]]], "start_pos": pos, "decode_steps": 1,
              "sampling": _SAMP, "seed": seed}
        if key is not None:
            pl["key"] = key
        rr = ex.process("r", pl)
        out.extend(int(x) for x in rr["tokens"][0])
        pos += rr["real_len"]
        key = rr["key"]
    return out


def test_stage_batch_kstep_cobatch_token_exact(single_stage_setup):
    """Co-batched lanes decode K steps per window in ONE fused scan, and
    every session's sampled stream equals its solo K=1 run, token for
    token. Per-dispatch accounting counts K tokens per lane (satellite:
    truthful tok/s), and the group K is the MINIMUM of the window's
    budget-clamped requests."""
    from inferd_tpu.runtime.stage_batch import BatchedStageExecutor

    cfg, spec, sp = single_stage_setup
    prompts = {"x": [3, 7, 11, 19], "y": [5, 2], "z": [9, 9, 4]}
    steps, K = 9, 4
    refs = {
        sid: _solo_kstep(cfg, spec, sp, p, steps, i)
        for i, (sid, p) in enumerate(prompts.items())
    }
    bx = BatchedStageExecutor(cfg, spec, sp, lanes=4, max_len=64)
    state = {}
    for i, (sid, p) in enumerate(prompts.items()):
        r = bx.process(sid, {"tokens": [p], "start_pos": 0,
                             "real_len": len(p)})
        state[sid] = {"pos": len(p), "out": [int(np.argmax(r["logits"][0]))],
                      "key": None, "seed": i}
    rounds = 0
    while any(len(s["out"]) < steps for s in state.values()):
        items = []
        for sid, s in state.items():
            pl = {"tokens": [[s["out"][-1]]], "start_pos": s["pos"],
                  "real_len": 1,
                  "decode_steps": min(K, steps - len(s["out"])),
                  "sampling": _SAMP, "seed": s["seed"]}
            if s["key"] is not None:
                pl["key"] = s["key"]
            items.append((sid, pl))
        outs = bx.process_batch(items)
        rounds += 1
        for (sid, _), rr in zip(items, outs):
            assert not isinstance(rr, Exception), rr
            assert rr["real_len"] == len(rr["tokens"][0])
            s = state[sid]
            s["out"].extend(int(x) for x in rr["tokens"][0])
            s["pos"] += rr["real_len"]
            s["key"] = rr["key"]
    for sid in prompts:
        assert state[sid]["out"] == refs[sid], sid
    st = bx.stats()
    assert rounds == 2  # 8 decode tokens per lane at K=4
    assert st["batched_steps"] == rounds  # ONE fused dispatch per window
    assert st["batched_tokens"] == 3 * (steps - 1)  # token-true accounting


def test_stage_batch_kstep_replay_rollback_interaction(single_stage_setup):
    """The replay-rollback protocol survives K-step windows: after a
    window advanced a lane by K, a re-sent chunk starting inside that
    window rolls the frontier back and the re-decoded window is
    IDENTICAL (deterministic forward + same key), and a later chunk at
    the new frontier continues the stream exactly."""
    from inferd_tpu.runtime.stage_batch import BatchedStageExecutor

    cfg, spec, sp = single_stage_setup
    bx = BatchedStageExecutor(cfg, spec, sp, lanes=2, max_len=64)
    p = [3, 7, 11, 19]
    bx.process("s", {"tokens": [p], "start_pos": 0, "real_len": len(p)})
    pl = {"tokens": [[5]], "start_pos": 4, "real_len": 1, "decode_steps": 4,
          "sampling": _SAMP, "seed": 3}
    r1 = bx.process_batch([("s", pl)])[0]
    assert r1["real_len"] == 4
    # replay the SAME window (lost response): frontier rolls back 4 and
    # the recomputed tokens match bit for bit
    r2 = bx.process_batch([("s", pl)])[0]
    assert r2["tokens"] == r1["tokens"] and r2["key"] == r1["key"]
    # continue from the replayed frontier; mixed window with another lane
    bx.process("t", {"tokens": [p], "start_pos": 0, "real_len": len(p)})
    nxt = {"tokens": [[r2["tokens"][0][-1]]], "start_pos": 8, "real_len": 1,
           "decode_steps": 4, "sampling": _SAMP, "seed": 3, "key": r2["key"]}
    r3 = bx.process_batch([("s", nxt)])[0]
    assert not isinstance(r3, Exception) and r3["real_len"] == 4
    # out-of-order (past the frontier) still rejects
    bad = dict(nxt, start_pos=50)
    out = bx.process_batch([("s", bad)])[0]
    assert isinstance(out, ValueError)


def test_stage_batch_kstep_stop_token_and_budget(single_stage_setup):
    """Per-lane eos fires mid-window (only that lane truncates; co-lanes
    fill their K), and a lane near max_len clamps the whole group's K to
    its budget (falling back toward K=1 at the boundary)."""
    from inferd_tpu.runtime.stage_batch import BatchedStageExecutor

    cfg, spec, sp = single_stage_setup
    # budget: max_len 16; lane a at 4 (12 left), lane b at 2 (14 left)
    bx = BatchedStageExecutor(cfg, spec, sp, lanes=2, max_len=16)
    bx.process("a", {"tokens": [[3, 7, 11, 19]], "start_pos": 0, "real_len": 4})
    bx.process("b", {"tokens": [[5, 2]], "start_pos": 0, "real_len": 2})
    outs = bx.process_batch([
        ("a", {"tokens": [[1]], "start_pos": 4, "real_len": 1,
               "decode_steps": 50}),
        ("b", {"tokens": [[2]], "start_pos": 2, "real_len": 1,
               "decode_steps": 50}),
    ])
    assert outs[0]["decode_steps"] == 12 and outs[1]["decode_steps"] == 12

    # eos: find a token the reference stream emits mid-way, then rerun
    # with it as lane "e"'s stop token while lane "f" keeps decoding
    bx2 = BatchedStageExecutor(cfg, spec, sp, lanes=2, max_len=64)
    p = [3, 7, 11, 19]
    ref = _solo_kstep(cfg, spec, sp, p, 9, 5)
    eos = ref[4]
    cut = ref.index(eos) + 1
    bx2.process("e", {"tokens": [p], "start_pos": 0, "real_len": 4})
    bx2.process("f", {"tokens": [p], "start_pos": 0, "real_len": 4})
    outs = bx2.process_batch([
        ("e", {"tokens": [[ref[0]]], "start_pos": 4, "real_len": 1,
               "decode_steps": 8, "sampling": _SAMP, "seed": 5, "eos": eos}),
        ("f", {"tokens": [[ref[0]]], "start_pos": 4, "real_len": 1,
               "decode_steps": 8, "sampling": _SAMP, "seed": 5}),
    ])
    assert [ref[0]] + outs[0]["tokens"][0] == ref[:cut]  # stopped at eos
    assert outs[1]["real_len"] == 8  # co-lane unaffected by e's stop


def test_stage_batch_dispatch_failure_is_isolated(single_stage_setup):
    """Failure isolation is per DISPATCH in a mixed window: a raising
    K-step group must not fail the legacy step or the OTHER sampling
    group, and a raising legacy step must not fail the K-step groups.
    The failed lane's frontier never advances, so a plain retry
    recovers."""
    from inferd_tpu.runtime.stage_batch import BatchedStageExecutor

    cfg, spec, sp = single_stage_setup
    bx = BatchedStageExecutor(cfg, spec, sp, lanes=4, max_len=64)
    p = [3, 7, 11, 19]
    for sid in ("L", "g", "s"):
        bx.process(sid, {"tokens": [p], "start_pos": 0, "real_len": 4})

    real_k, real_legacy = bx._decode_k_all, bx._decode_all

    def boom_k(params, cache, toks, lengths, active, keys, eos, k, t, tk,
               tp, mp, ads=None):
        if t > 0:  # only the sampled group dies, before touching device
            raise RuntimeError("injected kstep group failure")
        return real_k(params, cache, toks, lengths, active, keys, eos, k,
                      t, tk, tp, mp, ads=ads)

    items = [
        ("L", {"tokens": [[1]], "start_pos": 4, "real_len": 1}),
        ("g", {"tokens": [[1]], "start_pos": 4, "real_len": 1,
               "decode_steps": 3}),
        ("s", {"tokens": [[1]], "start_pos": 4, "real_len": 1,
               "decode_steps": 3, "sampling": _SAMP, "seed": 2}),
    ]
    bx._decode_k_all = boom_k
    try:
        outs = bx.process_batch(items)
    finally:
        bx._decode_k_all = real_k
    assert "logits" in outs[0]  # legacy step survived
    assert len(outs[1]["tokens"][0]) == 3  # greedy group survived
    assert isinstance(outs[2], RuntimeError)  # only the sampled group died
    # the failed lane never advanced: the same request now succeeds
    r = bx.process_batch([items[2]])[0]
    assert not isinstance(r, Exception) and r["real_len"] == 3

    # converse: a dying legacy dispatch leaves the K-step group healthy
    def boom_legacy(*a, **kw):
        raise RuntimeError("injected legacy failure")

    items2 = [
        ("L", {"tokens": [[2]], "start_pos": 5, "real_len": 1}),
        ("g", {"tokens": [[outs[1]["tokens"][0][-1]]], "start_pos": 7,
               "real_len": 1, "decode_steps": 2}),
    ]
    bx._decode_all = boom_legacy
    try:
        outs2 = bx.process_batch(items2)
    finally:
        bx._decode_all = real_legacy
    assert isinstance(outs2[0], RuntimeError)
    assert len(outs2[1]["tokens"][0]) == 2


# ---------------------------------------------------------------------------
# Node e2e: 2-stage swarm, concurrent sessions, coalesced relay
# ---------------------------------------------------------------------------

BASE = 18700


def _mk_node(idx, stage, parts, bootstrap_idx, lanes=8, window_ms=10.0):
    from inferd_tpu.config import TINY
    from inferd_tpu.control.dht import SwarmDHT
    from inferd_tpu.runtime.node import Node, NodeInfo

    info = NodeInfo(
        name=f"n{idx}", host="127.0.0.1", port=BASE + idx, stage=stage,
        num_stages=2, capacity=16, model_name="tiny",
    )
    dht = SwarmDHT(
        info.node_id, BASE + 100 + idx,
        bootstrap=(
            [("127.0.0.1", BASE + 100 + bootstrap_idx)]
            if idx != bootstrap_idx else []
        ),
        host="127.0.0.1", gossip_period_s=0.05, ttl_s=1.5,
    )
    return Node(
        info, TINY, parts, dht, backend="qwen3", max_len=64,
        rebalance_period_s=600.0, stage_lanes=lanes, window_ms=window_ms,
    )


@pytest.fixture(scope="module")
def tiny_parts(tmp_path_factory):
    import jax

    from inferd_tpu.config import TINY
    from inferd_tpu.models import qwen3
    from inferd_tpu.parallel.stages import Manifest, split_and_save

    parts = tmp_path_factory.mktemp("parts")
    params = qwen3.init_params(TINY, jax.random.PRNGKey(0))
    split_and_save(params, TINY, Manifest.even_split("tiny", 2), str(parts))
    return str(parts), params


@pytest.mark.asyncio
async def test_swarm_cobatch_token_exact_e2e(tiny_parts):
    """The tentpole, end to end: 8 concurrent sessions (mixed prompt
    lengths -> mixed positions in every co-batch; mixed budgets -> some
    sessions END mid-window while others continue) through a 2-stage
    --stage-lanes swarm. Every stream must equal the single-process
    engine token for token, decode steps must actually co-batch, and
    same-hop co-batches must relay as coalesced multi envelopes."""
    import asyncio

    from inferd_tpu.client.swarm_client import SwarmClient
    from inferd_tpu.config import TINY, SamplingConfig
    from inferd_tpu.core.generate import Engine

    parts, params = tiny_parts
    nodes = [_mk_node(i, i, parts, 0) for i in range(2)]
    for n in nodes:
        await n.start()
    for _ in range(100):
        if all(all(n.dht.get_all(2)[s] for s in range(2)) for n in nodes):
            break
        await asyncio.sleep(0.05)
    try:
        engine = Engine(
            TINY, params, max_len=64,
            sampling_cfg=SamplingConfig(temperature=0.0),
        )
        # mixed lengths AND mixed budgets: session i ends after 3 + i % 5
        # tokens, so early finishers end mid-window for the others
        prompts = [
            [3, 7, 11, 19], [5, 2], [9, 9, 4], [1, 2, 3, 4, 5],
            [8, 8], [4, 4, 4], [17], [6, 5, 4, 3],
        ]
        budgets = [3 + i % 5 for i in range(len(prompts))]
        async with SwarmClient(
            [("127.0.0.1", BASE + 0)],
            sampling=SamplingConfig(temperature=0.0),
        ) as c:
            outs = await asyncio.gather(*(
                c.generate_ids(p, max_new_tokens=b)
                for p, b in zip(prompts, budgets)
            ))
        for p, b, got in zip(prompts, budgets, outs):
            assert got == engine.generate(p, max_new_tokens=b), p

        # decode steps actually co-batched on both stages
        for n in nodes:
            st = n.executor.stats()
            assert st["mode"] == "stage_batched"
            assert st["batched_steps"] >= 1
        assert nodes[0].executor.stats()["mean_batch"] > 1.0

        # the common-hop windows relayed as ONE coalesced envelope and the
        # downstream node decoded the multi form
        m0 = nodes[0].metrics.snapshot()["counters"]
        m1 = nodes[1].metrics.snapshot()["counters"]
        assert m0.get("hop.coalesced", 0) >= 1
        assert m1.get("forward.multi_envelopes", 0) == m0.get("hop.coalesced")
        assert m1.get("forward.multi_frames", 0) == m0.get(
            "hop.coalesced_sessions"
        )
        assert not m0.get("hop.coalesced_fallback")

        # observability: the co-batch histogram + gauge export at /metrics
        import aiohttp

        from inferd_tpu.obs.export import validate_exposition

        async with aiohttp.ClientSession() as s:
            async with s.get(f"http://127.0.0.1:{BASE}/metrics") as r:
                text = await r.text()
        assert r.status == 200
        validate_exposition(text)
        assert "inferd_window_cobatch_bucket" in text
        assert "inferd_window_mean_cobatch" in text

        # and the window phase landed in the span ring
        import json as jsonlib

        phases = {
            jsonlib.loads(line).get("phase")
            for line in nodes[0].tracer.jsonl_lines()
        }
        assert "window" in phases
    finally:
        for n in nodes:
            try:
                await n.stop()
            except Exception:
                pass


@pytest.mark.asyncio
async def test_swarm_chain_mode_cobatch_no_relay(tiny_parts):
    """Chain mode (relay=False, the client carries activations) through
    stage-lanes nodes: decode steps still co-batch per stage, responses
    return directly (no coalesced relay involved), streams stay exact."""
    import asyncio

    from inferd_tpu.client.chain_client import ChainClient
    from inferd_tpu.config import TINY, SamplingConfig
    from inferd_tpu.core.generate import Engine

    parts, params = tiny_parts
    nodes = [_mk_node(10 + i, i, parts, 10) for i in range(2)]
    for n in nodes:
        await n.start()
    for _ in range(100):
        if all(all(n.dht.get_all(2)[s] for s in range(2)) for n in nodes):
            break
        await asyncio.sleep(0.05)
    try:
        engine = Engine(
            TINY, params, max_len=64,
            sampling_cfg=SamplingConfig(temperature=0.0),
        )
        prompts = [[3, 7, 11, 19], [5, 2], [9, 9, 4]]
        async with ChainClient(
            [("127.0.0.1", BASE + 10), ("127.0.0.1", BASE + 11)],
            sampling=SamplingConfig(temperature=0.0),
        ) as c:
            outs = await asyncio.gather(*(
                c.generate_ids(p, max_new_tokens=4) for p in prompts
            ))
        for p, got in zip(prompts, outs):
            assert got == engine.generate(p, max_new_tokens=4), p
        assert nodes[0].metrics.snapshot()["counters"].get(
            "hop.coalesced", 0
        ) == 0  # chain mode: nothing to relay
    finally:
        for n in nodes:
            try:
                await n.stop()
            except Exception:
                pass
