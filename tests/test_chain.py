"""Chain-mode (hub-and-spoke) tests: the client drives each stage server
directly with `relay: false` — parity with the reference's gRPC slice
(/root/reference/models/qwen3/client/rpc_client.py:36-57) served by the
same unified node runtime as the swarm path."""

import pytest

from inferd_tpu.client.chain_client import ChainClient
from inferd_tpu.client.swarm_client import SwarmClient
from inferd_tpu.config import TINY, SamplingConfig
from inferd_tpu.core.generate import Engine

from test_node_e2e import BASE, _mk_node, _start_all, _stop_all, tiny_parts  # noqa: F401


@pytest.mark.asyncio
async def test_chain_counter_no_relay():
    """relay=false returns each stage's raw result instead of relaying; the
    client carries the payload between stages."""
    nodes = [_mk_node(30 + i, i, 3, bootstrap_idx=30) for i in range(3)]
    await _start_all(nodes)
    try:
        async with ChainClient(
            [("127.0.0.1", BASE + 30 + i) for i in range(3)]
        ) as c:
            payload = {}
            for stage in range(3):
                resp = await c._post(
                    ("127.0.0.1", BASE + 30 + stage),
                    "/forward",
                    {
                        "stage": stage,
                        "session_id": "chain1",
                        "relay": False,
                        "payload": payload,
                    },
                )
                # hub-and-spoke: the serving node answers for itself only
                assert resp["served_by"] == f"127.0.0.1:{BASE + 30 + stage}"
                payload = dict(resp["result"])
                payload.pop("result_for_user", None)
            assert payload["state"] == 3
            assert payload["trace"] == [0, 1, 2]
    finally:
        await _stop_all(nodes)


@pytest.mark.asyncio
async def test_chain_generation_matches_engine(tiny_parts):  # noqa: F811
    """Golden chain test: fixed 2-server chain == single-process engine,
    token for token (greedy), KV cached server-side per session."""
    parts, params = tiny_parts
    nodes = [
        _mk_node(40 + i, i, 2, backend="qwen3", parts=parts, bootstrap_idx=40)
        for i in range(2)
    ]
    await _start_all(nodes)
    try:
        engine = Engine(TINY, params, max_len=64, sampling_cfg=SamplingConfig(temperature=0.0))
        prompt = [3, 7, 11, 19]
        expected = engine.generate(prompt, max_new_tokens=6)
        async with ChainClient(
            [("127.0.0.1", BASE + 40), ("127.0.0.1", BASE + 41)],
            sampling=SamplingConfig(temperature=0.0),
        ) as c:
            got = await c.generate_ids(prompt, max_new_tokens=6)
        assert got == expected
        # sessions were ended on both servers by end_session
        for n in nodes:
            assert len(n.executor.sessions) == 0
    finally:
        await _stop_all(nodes)


@pytest.mark.asyncio
async def test_chain_end_session_is_local(tiny_parts):  # noqa: F811
    """relay=false end_session drops only the addressed server's cache."""
    parts, params = tiny_parts
    nodes = [
        _mk_node(50 + i, i, 2, backend="qwen3", parts=parts, bootstrap_idx=50)
        for i in range(2)
    ]
    await _start_all(nodes)
    try:
        async with ChainClient(
            [("127.0.0.1", BASE + 50), ("127.0.0.1", BASE + 51)],
            sampling=SamplingConfig(temperature=0.0),
        ) as c:
            await c._forward_through_chain("s-local", [1, 2, 3], 0)
            assert len(nodes[0].executor.sessions) == 1
            assert len(nodes[1].executor.sessions) == 1
            await c._post(
                ("127.0.0.1", BASE + 50),
                "/end_session",
                {"session_id": "s-local", "stage": 0, "relay": False},
            )
            assert len(nodes[0].executor.sessions) == 0
            assert len(nodes[1].executor.sessions) == 1  # untouched
    finally:
        await _stop_all(nodes)


@pytest.mark.asyncio
async def test_chain_wrong_stage_fails_loudly():
    """A relay=false request to a node serving a different stage must be
    rejected (409), not silently rerouted via the DHT — the chain client's
    fixed-topology contract."""
    nodes = [_mk_node(60 + i, i, 2, bootstrap_idx=60) for i in range(2)]
    await _start_all(nodes)
    try:
        async with SwarmClient([("127.0.0.1", BASE + 61)]) as c:  # node serving stage 1
            with pytest.raises(RuntimeError, match="wrong stage"):
                await c._post(
                    "/forward",
                    {"stage": 0, "session_id": "x", "relay": False, "payload": {}},
                )
    finally:
        await _stop_all(nodes)
