"""Weight-only int8 quantization (ops.quant): numerics, model integration,
engine generation, and pytree/stage mechanics.

The reference has no quantization subsystem (bf16 torch weights,
qwen3_server_module.py:212-217); this is TPU-first added scope targeting the
bs=1 decode bandwidth roofline.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from inferd_tpu.config import TINY, get_config
from inferd_tpu.core.generate import Engine
from inferd_tpu.models import qwen3
from inferd_tpu.ops import quant


def test_quantize_roundtrip_error_bounded():
    w = jax.random.normal(jax.random.PRNGKey(0), (64, 32), jnp.float32)
    qw = quant.quantize(w)
    assert qw.q.dtype == jnp.int8 and qw.scale.shape == (32,)
    deq = qw.dequantize(jnp.float32)
    # max error per column <= scale/2 (symmetric rounding)
    err = np.abs(np.asarray(deq - w))
    bound = np.asarray(qw.scale)[None, :] * 0.5 + 1e-6
    assert (err <= bound).all()


def test_qdot_matches_dequant_matmul():
    x = jax.random.normal(jax.random.PRNGKey(1), (4, 64), jnp.float32)
    w = jax.random.normal(jax.random.PRNGKey(2), (64, 32), jnp.float32)
    qw = quant.quantize(w)
    got = quant.qdot(x, qw)
    want = x @ qw.dequantize(jnp.float32)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5)


def test_qdot_int8_mode_close():
    x = jax.random.normal(jax.random.PRNGKey(1), (4, 64), jnp.float32)
    w = jax.random.normal(jax.random.PRNGKey(2), (64, 32), jnp.float32)
    qw = quant.quantize(w)
    old = quant.QDOT_MODE
    try:
        quant.QDOT_MODE = "int8"
        got = quant.qdot(x, qw)
    finally:
        quant.QDOT_MODE = old
    want = np.asarray(x @ w)
    # dynamic activation quant adds ~1/127-scale noise per operand, which
    # accumulates over the K=64 contraction — compare in matrix norm
    rel = np.linalg.norm(np.asarray(got) - want) / np.linalg.norm(want)
    assert rel < 0.02, rel


def test_qeinsum_stacked_experts():
    x = jax.random.normal(jax.random.PRNGKey(3), (6, 16), jnp.float32)
    w = jax.random.normal(jax.random.PRNGKey(4), (3, 16, 8), jnp.float32)  # [E,H,I]
    qw = quant.quantize(w)
    assert qw.scale.shape == (3, 8)
    got = quant.qeinsum("th,ehi->tei", x, qw)
    want = jnp.einsum("th,ehi->tei", x, qw.dequantize(jnp.float32))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("mode", ["dequant", "int8"])
def test_quantized_forward_close_to_fp(mode):
    cfg = TINY
    params = qwen3.init_params(cfg, jax.random.PRNGKey(0))
    qparams = quant.quantize_params(params, tie_word_embeddings=cfg.tie_word_embeddings)
    toks = jax.random.randint(jax.random.PRNGKey(5), (2, 12), 0, cfg.vocab_size, jnp.int32)
    ref_logits, _, _ = qwen3.forward(params, cfg, toks)
    old = quant.QDOT_MODE
    try:
        quant.QDOT_MODE = mode
        q_logits, _, _ = qwen3.forward(qparams, cfg, toks)
    finally:
        quant.QDOT_MODE = old
    ref = np.asarray(ref_logits, np.float32)
    got = np.asarray(q_logits, np.float32)
    # int8 weight noise perturbs logits but must keep them well correlated
    cos = (ref * got).sum() / (np.linalg.norm(ref) * np.linalg.norm(got) + 1e-9)
    assert cos > 0.99, f"cosine {cos} ({mode})"


@pytest.mark.parametrize("family", ["gemma2", "gptoss"])
def test_quantized_new_families_close_to_fp(family):
    """int8 weight-only quant composes with the new families: Gemma-2's
    sandwich norms pass through untouched, GPT-OSS's clamped-GLU experts
    consume QuantWeight through expert_ffn's qeinsum, and the sink/bias
    leaves stay bf16."""
    from inferd_tpu.config import TINY_GEMMA2, TINY_GPT_OSS

    cfg = TINY_GEMMA2 if family == "gemma2" else TINY_GPT_OSS
    params = qwen3.init_params(cfg, jax.random.PRNGKey(3))
    qparams = quant.quantize_params(params, tie_word_embeddings=cfg.tie_word_embeddings)
    toks = jax.random.randint(jax.random.PRNGKey(6), (2, 12), 0, cfg.vocab_size, jnp.int32)
    ref = np.asarray(qwen3.forward(params, cfg, toks)[0], np.float32)
    got = np.asarray(qwen3.forward(qparams, cfg, toks)[0], np.float32)
    cos = (ref * got).sum() / (np.linalg.norm(ref) * np.linalg.norm(got) + 1e-9)
    assert cos > 0.99, f"cosine {cos} ({family})"


def test_quantized_engine_generates():
    cfg = TINY
    params = qwen3.init_params(cfg, jax.random.PRNGKey(0))
    qparams = quant.quantize_params(params, tie_word_embeddings=cfg.tie_word_embeddings)
    eng = Engine(cfg, qparams, max_len=64)
    out = eng.generate([3, 5, 7], max_new_tokens=8, seed=0)
    assert len(out) == 8 and all(0 <= t < cfg.vocab_size for t in out)
    # scan path agrees with host loop on the same quantized params
    toks = jnp.asarray([[3, 5, 7] + [0] * 13], jnp.int32)
    scan_out = np.asarray(eng.generate_scan(toks, 3, 8, seed=0))[0]
    assert list(scan_out) == out


def test_quantized_stage_slicing_and_stacking():
    """QuantWeight must behave as a pytree leaf-pair under the stacked-layer
    mechanics: slice_layers cuts the layer axis of q and scale together."""
    cfg = TINY
    params = qwen3.init_params(cfg, jax.random.PRNGKey(0))
    qparams = quant.quantize_params(params, tie_word_embeddings=cfg.tie_word_embeddings)
    sliced = qwen3.slice_layers(qparams["layers"], 1, cfg.num_layers)
    qp = sliced["q_proj"]
    assert isinstance(qp, quant.QuantWeight)
    assert qp.q.shape[0] == cfg.num_layers - 1
    assert qp.scale.shape[0] == cfg.num_layers - 1


def test_quantized_bytes_shrink():
    cfg = get_config("tiny")
    params = qwen3.init_params(cfg, jax.random.PRNGKey(0))
    qparams = quant.quantize_params(params, tie_word_embeddings=cfg.tie_word_embeddings)
    assert quant.quantized_bytes(qparams) < quant.quantized_bytes(params)


@pytest.mark.asyncio
async def test_quantized_swarm_pipeline_matches_quantized_engine(tmp_path):
    """2-stage qwen3 swarm served with run_node-style quant=int8 produces
    exactly the tokens of a single-process engine on the SAME quantized
    params (greedy) — the distributed path adds no numeric drift."""
    import asyncio

    from inferd_tpu.client.swarm_client import SwarmClient
    from inferd_tpu.config import SamplingConfig
    from inferd_tpu.control.dht import SwarmDHT
    from inferd_tpu.parallel.stages import Manifest, split_and_save
    from inferd_tpu.runtime.node import Node, NodeInfo

    cfg = TINY
    base = 18470
    params = qwen3.init_params(cfg, jax.random.PRNGKey(0))
    manifest = Manifest.even_split("tiny", 2)
    split_and_save(params, cfg, manifest, str(tmp_path))

    nodes = []
    for i in range(2):
        info = NodeInfo(
            name=f"qn{i}", host="127.0.0.1", port=base + i,
            stage=i, num_stages=2, capacity=4, model_name="tiny",
        )
        dht = SwarmDHT(
            info.node_id, base + 100 + i,
            bootstrap=[] if i == 0 else [("127.0.0.1", base + 100)],
            host="127.0.0.1", gossip_period_s=0.05, ttl_s=1.5,
        )
        nodes.append(Node(
            info, cfg, str(tmp_path), dht, backend="qwen3", max_len=64,
            rebalance_period_s=600.0, quant="int8",
        ))
    for n in nodes:
        await n.start()
    try:
        for _ in range(100):
            maps = [n.dht.get_all(2) for n in nodes]
            if all(m[s] for m in maps for s in range(2)):
                break
            await asyncio.sleep(0.05)

        qparams = quant.quantize_params(params, tie_word_embeddings=cfg.tie_word_embeddings)
        engine = Engine(cfg, qparams, max_len=64, sampling_cfg=SamplingConfig(temperature=0.0))
        prompt = [3, 7, 11, 19]
        expected = engine.generate(prompt, max_new_tokens=6)
        async with SwarmClient(
            [("127.0.0.1", base)], sampling=SamplingConfig(temperature=0.0)
        ) as c:
            got = await c.generate_ids(prompt, max_new_tokens=6)
        assert got == expected
    finally:
        for n in nodes:
            try:
                await n.stop()
            except Exception:
                pass


def test_quantized_pipelined_engine_matches_single(monkeypatch):
    """Quantized params through the in-mesh pp pipeline (shard_params must
    split QuantWeight q/scale coherently) == quantized single-process
    engine, token for token."""
    from inferd_tpu.config import SamplingConfig
    from inferd_tpu.parallel import mesh as meshlib
    from inferd_tpu.parallel.infer import PipelinedEngine

    cfg = TINY
    devs = jax.devices()[:2]
    if len(devs) < 2:
        pytest.skip("needs 2 devices")
    params = qwen3.init_params(cfg, jax.random.PRNGKey(0))
    qparams = quant.quantize_params(params, tie_word_embeddings=cfg.tie_word_embeddings)
    mesh = meshlib.make_mesh(meshlib.MeshPlan(pp=2), devs)
    eng = PipelinedEngine(
        cfg, qparams, mesh, num_microbatches=2, batch=1, max_len=64,
        sampling_cfg=SamplingConfig(temperature=0.0),
    )
    prompts = [[3, 7, 11], [2, 5, 13, 17]]
    got = eng.generate(prompts, max_new_tokens=6)

    single = Engine(cfg, qparams, max_len=64, sampling_cfg=SamplingConfig(temperature=0.0))
    for p, g in zip(prompts, got):
        assert g == single.generate(p, max_new_tokens=6)


def test_qdot_kernel_mode_matches_dequant():
    """Pallas w8a16 kernel path (interpret off-TPU) == dequant matmul."""
    x = jax.random.normal(jax.random.PRNGKey(8), (3, 64), jnp.float32)
    w = jax.random.normal(jax.random.PRNGKey(9), (64, 200), jnp.float32)
    qw = quant.quantize(w)
    want = np.asarray(quant.qdot(x, qw))
    old = quant.QDOT_MODE
    try:
        quant.QDOT_MODE = "kernel"
        got = np.asarray(quant.qdot(x, qw))
    finally:
        quant.QDOT_MODE = old
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


def test_kernel_mode_forward_matches_dequant():
    """Whole-model forward in kernel mode == dequant mode (MoE-free tiny;
    expert einsums fall back to dequant inside kernel mode by design)."""
    cfg = TINY
    params = qwen3.init_params(cfg, jax.random.PRNGKey(0))
    qparams = quant.quantize_params(params, tie_word_embeddings=cfg.tie_word_embeddings)
    toks = jax.random.randint(jax.random.PRNGKey(10), (1, 9), 0, cfg.vocab_size, jnp.int32)
    ref, _, _ = qwen3.forward(qparams, cfg, toks)
    old = quant.QDOT_MODE
    try:
        quant.QDOT_MODE = "kernel"
        got, _, _ = qwen3.forward(qparams, cfg, toks)
    finally:
        quant.QDOT_MODE = old
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(ref), rtol=5e-4, atol=5e-4
    )


# ---------------------------------------------------------------------------
# int4 group-wise (w4a16): quarter the weight bytes of bf16. Scales vary
# ALONG the contraction axis, so the contraction applies each group's scale
# to its own partial sum (ops.quant.Int4Weight) — these pin that exactness,
# the accuracy bound, the full-model integration, and the flag surface.
# ---------------------------------------------------------------------------


def test_int4_roundtrip_error_bounded():
    w = jax.random.normal(jax.random.PRNGKey(0), (256, 32), jnp.float32)
    qw = quant.quantize_int4(w)
    # nibble-packed storage: int8 bytes, two K-values per byte, original
    # shape still reported by the duck-typed .shape
    assert qw.q.dtype == jnp.int8 and qw.packed
    assert qw.q.shape == (128, 32)
    assert qw.shape == (256, 32)
    assert qw.scale.shape == (2, 32)  # group=128 along K=256
    deq = np.asarray(qw.dequantize(jnp.float32))
    err = np.abs(deq - np.asarray(w))
    bound = np.repeat(np.asarray(qw.scale), 128, axis=0) * 0.5 + 1e-6
    assert (err <= bound).all()


def test_int4_qdot_matches_dequant_matmul():
    """The grouped contraction is EXACT vs the dequantized matmul (the
    scheme's correctness, independent of quantization noise)."""
    x = jax.random.normal(jax.random.PRNGKey(1), (4, 256), jnp.float32)
    w = jax.random.normal(jax.random.PRNGKey(2), (256, 32), jnp.float32)
    qw = quant.quantize_int4(w)
    got = np.asarray(quant.qdot(x, qw))
    want = np.asarray(x @ qw.dequantize(jnp.float32))
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


def test_int4_pack_unpack_roundtrip():
    """Nibble packing is lossless: unpacked() reproduces the exact int4
    values, including negatives in both nibble positions, and odd K falls
    back to unpacked storage."""
    w = jax.random.normal(jax.random.PRNGKey(9), (64, 16), jnp.float32)
    qw = quant.quantize_int4(w)
    assert qw.packed and qw.q.shape == (32, 16)
    ints = np.asarray(qw.unpacked())
    assert ints.dtype == np.int8
    assert ints.min() >= -7 and ints.max() <= 7
    # reconstruct independently from the packed bytes
    raw = np.asarray(qw.q).astype(np.int8)
    lo = (raw.astype(np.int8) << 4).astype(np.int8) >> 4
    hi = raw >> 4
    expect = np.stack([lo, hi], axis=1).reshape(64, 16)
    np.testing.assert_array_equal(ints, expect)

    qw_odd = quant.quantize_int4(jax.random.normal(
        jax.random.PRNGKey(10), (7, 8), jnp.float32))
    assert not qw_odd.packed and qw_odd.q.shape == (7, 8)
    np.testing.assert_array_equal(
        np.asarray(qw_odd.unpacked()), np.asarray(qw_odd.q))


def test_int4_dequant_mode_matches_grouped():
    """The two contraction schemes (INT4_MODE "grouped" vs "dequant") agree
    on qdot and on both MoE einsum shapes. "dequant" is the conservative
    TPU default — the round-5 window's int4 leg crashed staging jnp.int4
    weights and fell back to CPU (BENCH_tpu_r05.jsonl decode_int4), so no
    on-chip comparison exists yet; the next window re-measures both."""
    x = jax.random.normal(jax.random.PRNGKey(11), (3, 256), jnp.float32)
    w = jax.random.normal(jax.random.PRNGKey(12), (256, 32), jnp.float32)
    qw = quant.quantize_int4(w)
    e, h, i, t = 2, 256, 48, 5
    w_up = jax.random.normal(jax.random.PRNGKey(13), (e, h, i), jnp.float32)
    x_t = jax.random.normal(jax.random.PRNGKey(14), (t, h), jnp.float32)
    q_up = quant.quantize_int4(w_up)
    old = quant.INT4_MODE
    try:
        quant.INT4_MODE = "grouped"
        dot_g = np.asarray(quant.qdot(x, qw))
        ein_g = np.asarray(quant.qeinsum("th,ehi->tei", x_t, q_up))
        quant.INT4_MODE = "dequant"
        dot_d = np.asarray(quant.qdot(x, qw))
        ein_d = np.asarray(quant.qeinsum("th,ehi->tei", x_t, q_up))
    finally:
        quant.INT4_MODE = old
    np.testing.assert_allclose(dot_g, dot_d, rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(ein_g, ein_d, rtol=2e-5, atol=2e-5)


def test_int4_small_k_single_group():
    """K smaller than the group size collapses to one group (tiny test
    configs); oddball K still splits exactly via the largest divisor."""
    qw = quant.quantize_int4(jnp.ones((48, 8)), group=128)
    assert qw.scale.shape == (1, 8)
    qw2 = quant.quantize_int4(jnp.ones((96, 8)), group=64)
    assert qw2.scale.shape[0] in (2, 3)  # 48- or 32-sized groups divide 96
    assert 96 % (96 // qw2.scale.shape[0]) == 0


@pytest.mark.parametrize("family", ["tiny", "gemma2", "gptoss"])
def test_int4_forward_close_to_fp(family):
    from inferd_tpu.config import TINY_GEMMA2, TINY_GPT_OSS

    cfg = {"tiny": TINY, "gemma2": TINY_GEMMA2, "gptoss": TINY_GPT_OSS}[family]
    params = qwen3.init_params(cfg, jax.random.PRNGKey(3))
    qparams = quant.apply_quant_mode(
        "int4", params, tie_word_embeddings=cfg.tie_word_embeddings
    )
    toks = jax.random.randint(
        jax.random.PRNGKey(6), (2, 12), 0, cfg.vocab_size, jnp.int32
    )
    ref = np.asarray(qwen3.forward(params, cfg, toks)[0], np.float32)
    got = np.asarray(qwen3.forward(qparams, cfg, toks)[0], np.float32)
    cos = (ref * got).sum() / (np.linalg.norm(ref) * np.linalg.norm(got) + 1e-9)
    # int4's 15 levels on RANDOM-INIT weights (no outlier structure, and
    # tiny's K=64 collapses to one group) is the worst case — measured
    # 0.976 (tiny) / 0.94 (gemma2, whose logit softcap + scaled embedding
    # amplify relative noise at these widths); real checkpoints with
    # grouped outlier ranging do better. The bound guards implementation
    # breakage (a wrong scale axis or group mapping drops cosine to ~0,
    # and test_int4_engine_matches_dequant_engine pins exactness at 3e-7
    # vs explicitly dequantized weights), not quant quality.
    assert cos > {"tiny": 0.95, "gemma2": 0.90, "gptoss": 0.95}[family], (
        f"cosine {cos} ({family})"
    )


def test_int4_engine_matches_dequant_engine():
    """An int4 engine's greedy stream equals an engine over the EXPLICITLY
    dequantized weights — the contraction introduces no extra error beyond
    quantization itself."""
    cfg = TINY
    params = qwen3.init_params(cfg, jax.random.PRNGKey(0))
    qparams = quant.apply_quant_mode(
        "int4", params, tie_word_embeddings=cfg.tie_word_embeddings
    )
    deq = jax.tree.map(
        lambda a: a.dequantize(cfg.jnp_dtype)
        if isinstance(a, quant.Int4Weight) else a,
        qparams, is_leaf=lambda a: isinstance(a, quant.Int4Weight),
    )
    from inferd_tpu.config import SamplingConfig

    sc = SamplingConfig(temperature=0.0)
    e_q = Engine(cfg, qparams, max_len=64, sampling_cfg=sc)
    e_d = Engine(cfg, deq, max_len=64, sampling_cfg=sc)
    prompt = [3, 7, 11, 19, 5]
    assert e_q.generate(prompt, 8) == e_d.generate(prompt, 8)


def test_int4_stage_slicing_and_bytes():
    cfg = TINY
    params = qwen3.init_params(cfg, jax.random.PRNGKey(0))
    q8 = quant.quantize_params(params, tie_word_embeddings=cfg.tie_word_embeddings)
    q4 = quant.quantize_params(
        params, tie_word_embeddings=cfg.tie_word_embeddings,
        quantizer=quant.quantize_int4,
    )
    sliced = qwen3.slice_layers(q4["layers"], 1, cfg.num_layers)
    qp = sliced["q_proj"]
    assert isinstance(qp, quant.Int4Weight)
    assert qp.q.shape[0] == cfg.num_layers - 1
    assert qp.scale.shape[0] == cfg.num_layers - 1
    # packed int4 bytes undercut int8 which undercuts the fp tree
    assert (
        quant.quantized_bytes(q4)
        < quant.quantized_bytes(q8)
        < quant.quantized_bytes(params)
    )


# ---------------------------------------------------------------------------
# int4 MoE experts (round 5, VERDICT r04 #4): the expert einsums contract
# GROUP-WISE like the dense qdot path instead of dequantizing inline — the
# quarter-bytes win applies exactly where weight bytes dominate hardest.
# ---------------------------------------------------------------------------


def test_int4_grouped_einsum_exact_vs_dequant():
    """Both MoE expert einsum shapes: the grouped contraction is EXACT vs
    the dequantized einsum (scheme correctness, the dense qdot bar)."""
    e, h, i, t = 4, 256, 96, 6
    w_up = jax.random.normal(jax.random.PRNGKey(1), (e, h, i), jnp.float32)
    w_dn = jax.random.normal(jax.random.PRNGKey(2), (e, i, h), jnp.float32)
    x_t = jax.random.normal(jax.random.PRNGKey(3), (t, h), jnp.float32)
    x_tei = jax.random.normal(jax.random.PRNGKey(4), (t, e, i), jnp.float32)
    q_up, q_dn = quant.quantize_int4(w_up), quant.quantize_int4(w_dn)
    assert q_up.scale.shape == (e, 2, i)  # grouped along K=256

    got_up = np.asarray(quant.qeinsum("th,ehi->tei", x_t, q_up))
    want_up = np.asarray(
        jnp.einsum("th,ehi->tei", x_t, q_up.dequantize(jnp.float32))
    )
    np.testing.assert_allclose(got_up, want_up, rtol=3e-5, atol=3e-5)

    got_dn = np.asarray(quant.qeinsum("tei,eih->teh", x_tei, q_dn))
    want_dn = np.asarray(
        jnp.einsum("tei,eih->teh", x_tei, q_dn.dequantize(jnp.float32))
    )
    np.testing.assert_allclose(got_dn, want_dn, rtol=3e-5, atol=3e-5)


def test_int4_moe_engine_matches_dequant_engine():
    """tiny-moe int4 greedy stream == the explicitly-dequantized engine:
    the grouped expert contraction adds no error beyond quantization."""
    from inferd_tpu.config import TINY_MOE, SamplingConfig

    cfg = TINY_MOE
    params = qwen3.init_params(cfg, jax.random.PRNGKey(5))
    qparams = quant.apply_quant_mode(
        "int4", params, tie_word_embeddings=cfg.tie_word_embeddings
    )
    deq = jax.tree.map(
        lambda a: a.dequantize(cfg.jnp_dtype)
        if isinstance(a, quant.Int4Weight) else a,
        qparams, is_leaf=lambda a: isinstance(a, quant.Int4Weight),
    )
    sc = SamplingConfig(temperature=0.0)
    e_q = Engine(cfg, qparams, max_len=64, sampling_cfg=sc)
    e_d = Engine(cfg, deq, max_len=64, sampling_cfg=sc)
    prompt = [3, 7, 11, 19, 5]
    assert e_q.generate(prompt, 8) == e_d.generate(prompt, 8)


def test_int4_moe_forward_close_to_fp_and_bytes():
    """Accuracy cosine on tiny-moe + byte accounting: experts at ~1/4 of
    their bf16 bytes (the VERDICT r04 #4 'done' bar)."""
    from inferd_tpu.config import TINY_MOE

    cfg = TINY_MOE
    params = qwen3.init_params(cfg, jax.random.PRNGKey(6))
    qparams = quant.apply_quant_mode(
        "int4", params, tie_word_embeddings=cfg.tie_word_embeddings
    )
    toks = jax.random.randint(
        jax.random.PRNGKey(7), (2, 12), 0, cfg.vocab_size, jnp.int32
    )
    ref = np.asarray(qwen3.forward(params, cfg, toks)[0], np.float32)
    got = np.asarray(qwen3.forward(qparams, cfg, toks)[0], np.float32)
    cos = (ref * got).sum() / (np.linalg.norm(ref) * np.linalg.norm(got) + 1e-9)
    assert cos > 0.93, f"cosine {cos}"

    # expert byte accounting: int4 experts ~= 1/4 bf16 (+ scale overhead)
    for name in ("gate_proj", "up_proj", "down_proj"):
        qw = qparams["layers"][name]
        assert isinstance(qw, quant.Int4Weight)
        fp_bytes = params["layers"][name].size * 2  # bf16
        # packed int8 already stores two int4 values per byte
        q_bytes = qw.q.size + qw.scale.size * 4
        assert q_bytes < 0.35 * fp_bytes, (name, q_bytes, fp_bytes)


def test_int4_moe_composes_with_ep_mesh(devices8):
    """int4 expert weights serve through the ep mesh axis: a pp=2 x ep=2
    pipelined engine over int4-quantized tiny-moe params stays greedy-
    exact with the single-process int4 engine."""
    from inferd_tpu.config import TINY_MOE, SamplingConfig
    from inferd_tpu.parallel import mesh as meshlib
    from inferd_tpu.parallel.infer import PipelinedEngine

    cfg = TINY_MOE
    params = qwen3.init_params(cfg, jax.random.PRNGKey(8))
    qparams = quant.apply_quant_mode(
        "int4", params, tie_word_embeddings=cfg.tie_word_embeddings
    )
    sc = SamplingConfig(temperature=0.0)
    prompt = [3, 7, 11, 2]
    want = Engine(cfg, qparams, max_len=32, sampling_cfg=sc).generate(
        prompt, max_new_tokens=6
    )
    mesh = meshlib.make_mesh(meshlib.MeshPlan(pp=2, ep=2), devices8[:4])
    eng = PipelinedEngine(
        cfg, qparams, mesh, num_microbatches=2, batch=1, max_len=32,
        sampling_cfg=sc,
    )
    assert eng.generate([prompt], 6)[0] == want
