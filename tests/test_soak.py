"""Concurrency soak for the windowed-batching executors: many threads,
random mid-stream session abandonment, capacity churn — afterwards every
lane/slot must be back on the free list with no deferred-drain or
in-flight residue and no stuck thread. This is the regression net for the
flusher/eviction/end_session interleavings that single-scenario tests
can't enumerate."""

import random
import threading

import jax
import numpy as np
import pytest

from inferd_tpu.config import TINY
from inferd_tpu.models import qwen3
from inferd_tpu.parallel.mesh import MeshPlan
from inferd_tpu.runtime.batch_executor import BatchedExecutor, CapacityError
from inferd_tpu.runtime.mesh_executor import MeshExecutor


@pytest.fixture(scope="module")
def params():
    return qwen3.init_params(TINY, jax.random.PRNGKey(0))


def _soak(ex, n_workers: int, iters: int, free_count):
    errors, done = [], [0]

    def worker(wid):
        r = random.Random(wid)
        try:
            for it in range(iters):
                sid = f"w{wid}-{it}"
                try:
                    resp = ex.process(
                        sid,
                        {"tokens": [[3 + wid, 7, 11]], "start_pos": 0, "real_len": 3},
                    )
                except (CapacityError, BufferError, ValueError):
                    continue
                pos = 3
                tok = int(np.asarray(resp["logits"])[0].argmax())
                for _ in range(r.randint(1, 10)):
                    if r.random() < 0.1:
                        ex.end_session(sid)  # abandon mid-stream
                        break
                    try:
                        resp = ex.process(
                            sid, {"tokens": [[tok]], "start_pos": pos, "real_len": 1}
                        )
                    except (CapacityError, BufferError, ValueError):
                        break
                    pos += 1
                    tok = int(np.asarray(resp["logits"])[0].argmax())
                ex.end_session(sid)
            done[0] += 1
        except Exception as e:  # noqa: BLE001 — the assert below reports it
            errors.append((wid, repr(e)))

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(n_workers)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=240)
    stuck = [t for t in threads if t.is_alive()]
    assert not stuck, "threads never completed (deadlock/lost wakeup)"
    assert not errors, errors
    assert done[0] == n_workers
    assert free_count() == ex_lanes(ex), "lanes/slots leaked"
    assert not ex._dying and not ex._inflight


def ex_lanes(ex):
    return ex.engine.lanes if hasattr(ex.engine, "lanes") else ex.engine.mb


def test_batched_executor_soak(params):
    ex = BatchedExecutor(TINY, params, lanes=4, max_len=48, window_ms=2.0)
    _soak(ex, n_workers=8, iters=4, free_count=lambda: len(ex.engine.free))
    # (coalescing itself is pinned deterministically by the barrier tests in
    # test_batch_node/test_mesh_node — under CI scheduling, co-arrival here
    # is likely but not guaranteed, so no mean_batch assertion)


def test_mesh_executor_soak(params):
    ex = MeshExecutor(
        TINY, params, MeshPlan(pp=2), num_slots=4, max_len=48,
        devices=jax.devices()[:2], window_ms=2.0,
    )
    _soak(ex, n_workers=6, iters=3, free_count=lambda: len(ex.sessions._free))
