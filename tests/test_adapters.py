"""Multi-tenant LoRA serving (ISSUE 15): the batched unmerged apply, the
AdapterRegistry's hot-load/evict lifecycle, adapter-affinity routing, the
`ada` gossip field's mixed-version compat, and the kill-switch parity
contract (--adapters absent => byte-identical surfaces)."""

import asyncio
import dataclasses
import json
import os

import numpy as np
import pytest

from inferd_tpu.config import TINY
from inferd_tpu.core import prefix as prefixlib
from inferd_tpu.ops import lora as loralib
from inferd_tpu.runtime.adapters import (
    ADA_GOSSIP_MAX, AdapterAffinity, AdapterCapacityError, AdapterRegistry,
    combine_affinity, parse_adapter_dirs,
)

SIM_DATA = os.path.join(os.path.dirname(__file__), "data", "sim")

PROMPT = [3, 17, 42, 9, 5, 8, 2, 11]


def _mk_layers(cfg, seed, r=4, targets=None, scale_sd=0.25):
    g = np.random.default_rng(seed)
    h, q = cfg.hidden_size, cfg.q_dim
    kv, inter = cfg.kv_dim, cfg.intermediate_size
    dims = {
        "q_proj": (h, q), "k_proj": (h, kv), "v_proj": (h, kv),
        "o_proj": (q, h), "gate_proj": (h, inter), "up_proj": (h, inter),
        "down_proj": (inter, h),
    }
    if targets is not None:
        dims = {k: v for k, v in dims.items() if k in targets}
    return {
        name: (
            g.normal(0, scale_sd, (cfg.num_layers, din, r)).astype(np.float32),
            g.normal(0, scale_sd, (cfg.num_layers, r, dout)).astype(np.float32),
        )
        for name, (din, dout) in dims.items()
    }


@pytest.fixture(scope="module")
def catalog(tmp_path_factory):
    """Three synthetic peft tenant dirs (mixed ranks + target subsets)."""
    root = tmp_path_factory.mktemp("adapters")
    dirs = []
    specs = [
        ("ten0", 0, 4, None),
        ("ten1", 1, 2, ("q_proj", "gate_proj")),  # narrower rank + subset
        ("ten2", 2, 4, ("v_proj", "down_proj")),
    ]
    for name, seed, r, targets in specs:
        p = str(root / name)
        loralib.save_adapter(
            p, _mk_layers(TINY, 100 + seed, r=r, targets=targets),
            alpha=8, r=r,
        )
        dirs.append(p)
    return dirs


@pytest.fixture(scope="module")
def base_params():
    import jax

    from inferd_tpu.models import qwen3

    return qwen3.init_params(TINY, jax.random.PRNGKey(0))


def _greedy_stream(ex, sid, prompt, steps, adapter=None):
    payload = {"tokens": [prompt], "start_pos": 0, "real_len": len(prompt)}
    if adapter is not None:
        payload["adapter"] = adapter
    out = ex.process(sid, payload)
    toks = [int(np.argmax(out["logits"][0]))]
    pos = len(prompt)
    for _ in range(steps - 1):
        o = ex.process(sid, {
            "tokens": [[toks[-1]]], "start_pos": pos, "real_len": 1,
        })
        toks.append(int(np.argmax(o["logits"][0])))
        pos += 1
    return toks


def _merged_ref(base_params, adir, prompt, steps):
    from inferd_tpu.runtime.batch_executor import BatchedExecutor

    merged = loralib.merge_adapter(
        base_params, loralib.load_adapter(TINY, adir)
    )
    ex = BatchedExecutor(TINY, merged, lanes=1, max_len=64)
    return _greedy_stream(ex, "ref", prompt, steps)


# ---------------------------------------------------------------------------
# tentpole: batched unmerged apply == merged solo, per tenant, co-batched
# ---------------------------------------------------------------------------


def test_batched_executor_multi_adapter_token_exact(catalog, base_params):
    """Three sessions with THREE different adapters (mixed ranks/targets)
    plus a base-adapter session co-resident on one BatchedExecutor: every
    stream token-exact vs its merged (or base) solo reference."""
    from inferd_tpu.runtime.batch_executor import BatchedExecutor

    reg = AdapterRegistry(TINY, catalog)
    ex = BatchedExecutor(TINY, base_params, lanes=4, max_len=64,
                         adapters=reg)
    streams = {}
    for t, adir in enumerate(catalog):
        name = os.path.basename(adir)
        streams[name] = _greedy_stream(
            ex, f"s{t}", PROMPT, 6, adapter=name
        )
    streams["base"] = _greedy_stream(ex, "sb", PROMPT, 6)
    for t, adir in enumerate(catalog):
        name = os.path.basename(adir)
        assert streams[name] == _merged_ref(base_params, adir, PROMPT, 6), name
    from inferd_tpu.runtime.batch_executor import BatchedExecutor as BE

    base_ref = _greedy_stream(
        BE(TINY, base_params, lanes=1, max_len=64), "r", PROMPT, 6
    )
    assert streams["base"] == base_ref
    # the adapters discriminate (token-exactness would be vacuous if not)
    assert len({tuple(s) for s in streams.values()}) >= 2


def test_stage_executor_adapters_paged_token_exact(catalog, base_params):
    """The stage-batch executor flavor, over PAGED KV: the salted prefix
    chain keeps tenants' shared-prompt KV apart while the gathered apply
    stays token-exact vs merged references."""
    from inferd_tpu.parallel.stages import Manifest
    from inferd_tpu.runtime.stage_batch import BatchedStageExecutor

    spec = list(Manifest.even_split("tiny", 1).stage_specs())[0]
    reg = AdapterRegistry(TINY, catalog)
    ex = BatchedStageExecutor(
        TINY, spec, base_params, lanes=3, max_len=64, block_size=8,
        adapters=reg,
    )
    name0 = os.path.basename(catalog[0])
    name1 = os.path.basename(catalog[1])
    s0 = _greedy_stream(ex, "a0", PROMPT, 5, adapter=name0)
    s1 = _greedy_stream(ex, "a1", PROMPT, 5, adapter=name1)
    assert s0 == _merged_ref(base_params, catalog[0], PROMPT, 5)
    assert s1 == _merged_ref(base_params, catalog[1], PROMPT, 5)
    # same prompt, different adapters: the salted chains must never have
    # shared prefix blocks across the two tenants
    k0 = prefixlib.block_keys(PROMPT, 8, salt=name0)
    k1 = prefixlib.block_keys(PROMPT, 8, salt=name1)
    assert not set(k0) & set(k1)


def test_prefix_salt_kill_switch_and_scoping():
    """No salt => byte-identical chains (the kill-switch contract);
    salted chains differ from unsalted and from each other."""
    plain = prefixlib.block_keys(PROMPT, 4)
    assert plain == prefixlib.block_keys(PROMPT, 4, salt=None)
    assert plain == prefixlib.block_keys(PROMPT, 4, salt="")
    a = prefixlib.block_keys(PROMPT, 4, salt="ten0")
    b = prefixlib.block_keys(PROMPT, 4, salt="ten1")
    assert not set(plain) & set(a) and not set(a) & set(b)


# ---------------------------------------------------------------------------
# registry lifecycle: hot-load, refcounted eviction, pins, errors
# ---------------------------------------------------------------------------


def test_registry_refcount_lru_evict_and_events(catalog):
    reg = AdapterRegistry(TINY, catalog, slots=3)  # 2 non-base slots
    events = []
    reg.on_event = lambda e, **a: events.append((e, a))
    s0 = reg.acquire("ten0")
    s1 = reg.acquire("ten1")
    assert s0 != s1 and 0 not in (s0, s1)
    # both held: a third tenant cannot claim a slot
    with pytest.raises(AdapterCapacityError):
        reg.acquire("ten2")
    reg.release("ten0")
    s2 = reg.acquire("ten2")  # evicts idle ten0, reuses its slot
    assert s2 == s0
    names = [e for e, _ in events]
    assert names.count("adapter.load") == 3
    evicts = [a for e, a in events if e == "adapter.evict"]
    assert len(evicts) == 1 and evicts[0]["name"] == "ten0"
    assert evicts[0]["claimant"] == "ten2" and evicts[0]["idle_s"] >= 0
    st = reg.stats()
    assert st["loads"] == 3 and st["evictions"] == 1 and st["resident"] == 2
    assert reg.resident_names() == ["ten1", "ten2"]


def test_registry_pin_blocks_eviction_and_unknown_name(catalog):
    reg = AdapterRegistry(TINY, catalog, slots=2)  # ONE non-base slot
    reg.pin("ten0")
    with pytest.raises(AdapterCapacityError):
        reg.acquire("ten1")  # the only slot is pinned
    reg.unpin("ten0")
    reg.acquire("ten1")  # now evicts the unpinned idle ten0
    with pytest.raises(ValueError, match="unknown adapter"):
        reg.acquire("nope")


def test_registry_rejects_moe_and_sliding_window(catalog):
    moe = dataclasses.replace(
        TINY, num_experts=4, num_experts_per_tok=2, moe_intermediate_size=32
    )
    with pytest.raises(ValueError, match="MoE"):
        AdapterRegistry(moe, catalog)
    sw = dataclasses.replace(TINY, sliding_window=8)
    with pytest.raises(ValueError, match="sliding-window"):
        AdapterRegistry(sw, catalog)


def test_parse_adapter_dirs_collision():
    assert parse_adapter_dirs("/a/x,/b/y") == {"x": "/a/x", "y": "/b/y"}
    with pytest.raises(ValueError, match="collide"):
        parse_adapter_dirs("/a/x,/b/x")


def test_unknown_adapter_typed_and_slots_validation(catalog):
    """A name outside the catalog raises the TYPED UnknownAdapterError
    (the node maps it to a non-retryable 409 `unknown_adapter`, never the
    restart-loop `session_state`), and unservable --adapter-slots values
    raise loudly instead of silently substituting the default."""
    from inferd_tpu.runtime.adapters import UnknownAdapterError

    reg = AdapterRegistry(TINY, catalog)
    with pytest.raises(UnknownAdapterError, match="unknown adapter"):
        reg.acquire("nope")
    # must stay a ValueError so pre-existing broad handlers still catch
    assert issubclass(UnknownAdapterError, ValueError)
    for bad in (1, -3):
        with pytest.raises(ValueError, match="unservable"):
            AdapterRegistry(TINY, catalog, slots=bad)
    assert AdapterRegistry(TINY, catalog, slots=0).slots == len(catalog) + 1


def test_ads_all_base_window_routes_to_no_adapter_graph(catalog, base_params):
    """A dispatch whose lanes all ride slot 0 ships ads=None (the
    already-compiled no-adapter graph) even once pools are resident —
    base-only traffic must not pay zero-math adapter gathers forever."""
    from inferd_tpu.runtime.batch_executor import BatchedExecutor

    reg = AdapterRegistry(TINY, catalog)
    ex = BatchedExecutor(TINY, base_params, lanes=2, max_len=64,
                         adapters=reg)
    slot = reg.acquire("ten0")  # pools become resident
    try:
        assert ex._ads([0, 0]) is None
        mixed = ex._ads([0, slot])
        assert mixed is not None and "ids" in mixed
    finally:
        reg.release("ten0")


def test_executor_rejects_adapter_without_registry(base_params):
    from inferd_tpu.runtime.batch_executor import BatchedExecutor

    ex = BatchedExecutor(TINY, base_params, lanes=2, max_len=64)
    with pytest.raises(ValueError, match="no adapter registry"):
        ex.process("s", {
            "tokens": [PROMPT], "start_pos": 0, "real_len": len(PROMPT),
            "adapter": "ten0",
        })


def test_executor_capacity_error_releases_reference(catalog, base_params):
    """An admission that dies AFTER acquire must give the reference
    back — otherwise the slot can never be evicted again."""
    from inferd_tpu.runtime.batch_executor import BatchedExecutor

    reg = AdapterRegistry(TINY, catalog)
    ex = BatchedExecutor(TINY, base_params, lanes=2, max_len=16,
                         adapters=reg)
    with pytest.raises(BufferError):  # prompt exceeds max_len
        ex.process("s", {
            "tokens": [list(range(2, 40))], "start_pos": 0, "real_len": 38,
            "adapter": "ten0",
        })
    assert reg._refs == {}  # no leaked reference


# ---------------------------------------------------------------------------
# satellites: exclusive modes + slice bounds
# ---------------------------------------------------------------------------


def test_exclusive_modes_loud():
    loralib.check_exclusive_modes("", "")  # neither: fine
    loralib.check_exclusive_modes("/a", None)
    loralib.check_exclusive_modes(None, "/a,/b")
    with pytest.raises(ValueError, match="mutually exclusive"):
        loralib.check_exclusive_modes("/a", "/b,/c", owner="node0")


def test_slice_adapter_bounds_raise_with_stage_identity():
    ad = {
        "layers": {"q_proj": (np.zeros((2, 8, 4)), np.zeros((2, 4, 8)))},
        "scale": 2.0,
    }
    with pytest.raises(ValueError, match="stage 3.*no-op"):
        loralib.slice_adapter(ad, 1, 1, owner="node0 stage 3")
    with pytest.raises(ValueError, match="inverted|no-op"):
        loralib.slice_adapter(ad, 2, 1)
    with pytest.raises(ValueError, match="runs past the adapter's 2"):
        loralib.slice_adapter(ad, 0, 3, owner="node0 stage 1")
    ok = loralib.slice_adapter(ad, 0, 2)
    assert ok["layers"]["q_proj"][0].shape[0] == 2


# ---------------------------------------------------------------------------
# routing: AdapterAffinity through the real routers
# ---------------------------------------------------------------------------


def test_adapter_affinity_scoring_and_combination():
    aff = AdapterAffinity("ten0")
    assert aff.depth_frac({"ada": ["ten1", "ten0"]}) == 1.0
    assert aff.depth_frac({"ada": ["ten1"]}) == 0.0
    assert aff.depth_frac({}) == 0.0
    assert aff.depth_frac({"ada": "garbage"}) == 0.0
    combo = combine_affinity(AdapterAffinity("x"), AdapterAffinity("ten0"))
    assert combo.depth_frac({"ada": ["ten0"]}) == 1.0  # max composition
    assert combine_affinity(None, None) is None
    assert combine_affinity(aff, None) is aff


def test_routers_prefer_adapter_holder_but_health_dominates():
    from inferd_tpu.control.dstar import node_cost
    from inferd_tpu.control.path_finder import min_load_node, ranked_nodes

    aff = AdapterAffinity("ten0")
    stage = {
        "holder": {"load": 2, "cap": 8, "ada": ["ten0"]},
        "cold": {"load": 1, "cap": 8},
    }
    nid, _ = min_load_node(stage, affinity=aff)
    assert nid == "holder"  # bonus outweighs the small load gap
    # shedding holder: penalized, the cold healthy replica wins
    shed = {
        "holder": {"load": 2, "cap": 8, "ada": ["ten0"], "shed": 1},
        "cold": {"load": 1, "cap": 8},
    }
    assert min_load_node(shed, affinity=aff)[0] == "cold"
    # outlier holder: the penalty (4x the max bonus) dominates
    sick = {
        "holder": {"load": 0, "cap": 8, "ada": ["ten0"], "outlier": 1},
        "cold": {"load": 1, "cap": 8},
    }
    assert ranked_nodes(sick, affinity=aff)[0][0] == "cold"
    # draining holder: no bonus and excluded while others serve
    drain = {
        "holder": {"load": 0, "cap": 8, "ada": ["ten0"], "draining": 1},
        "cold": {"load": 1, "cap": 8},
    }
    assert min_load_node(drain, affinity=aff)[0] == "cold"
    # D*-Lite edge costs stay strictly positive under the discount
    assert node_cost({"load": 0, "cap": 8, "ada": ["ten0"]}, affinity=aff) > 0


# ---------------------------------------------------------------------------
# gossip: mixed-version `ada` compat + collector/dashboard surfaces
# ---------------------------------------------------------------------------


@pytest.mark.asyncio
async def test_mixed_version_gossip_ada_key():
    """The new `ada` key passes bit-true through peers that predate it,
    and old records gain nothing (the PR 7/13 test_dht pattern)."""
    from inferd_tpu.control.dht import SwarmDHT

    def mk(node_id, port, bootstrap=None):
        return SwarmDHT(node_id, port, bootstrap=bootstrap or [], ttl_s=5.0,
                        gossip_period_s=0.05, host="127.0.0.1")

    new = mk("new", 17361)
    old = mk("old", 17362, bootstrap=[("127.0.0.1", 17361)])
    obs = mk("obs", 17363, bootstrap=[("127.0.0.1", 17361)])
    await new.start(); await old.start(); await obs.start()
    try:
        new.announce({
            "stage": 0, "load": 1, "cap": 4, "ada": ["ten0", "ten1"],
        })
        old.announce({"stage": 0, "load": 0, "cap": 4})  # pre-adapter peer
        for _ in range(100):
            if len(obs.get_stage(0)) == 2:
                break
            await asyncio.sleep(0.05)
        stage = obs.get_stage(0)
        assert len(stage) == 2, "gossip did not converge"
        assert stage["new"]["ada"] == ["ten0", "ten1"]  # bit-true
        assert "ada" not in stage["old"]
        # an OBSERVER'S router scores the relayed residency directly
        aff = AdapterAffinity("ten1")
        assert aff.depth_frac(stage["new"]) == 1.0
        assert aff.depth_frac(stage["old"]) == 0.0
    finally:
        await new.stop(); await old.stop(); await obs.stop()


def test_collector_adapters_column_and_old_peer_blanks():
    from inferd_tpu.tools.collector import stage_rows

    swarm = {
        0: {
            "n0": {"load": 1, "cap": 4, "ada": ["ten1", "ten0"]},
            "n1": {"load": 1, "cap": 4, "ada": ["ten2"]},
            "old": {"load": 1, "cap": 4},  # pre-adapter peer
        },
        1: {"inner": {"load": 0, "cap": 4}},
    }
    rows = {r["stage"]: r for r in stage_rows(swarm, ts=1.0)}
    assert rows[0]["adapters"] == "ten0 ten1 ten2"  # sorted stage union
    assert rows[1]["adapters"] == ""  # registry-less stage: blank


def test_dashboard_ada_cell_blank_for_old_peers():
    from inferd_tpu.tools.dashboard import render_table

    swarm = {0: {
        "new": {"name": "n", "load": 0, "cap": 1, "ada": ["t0", "t1"]},
        "old": {"name": "o", "load": 0, "cap": 1},
    }}
    text = render_table(swarm, ts=0.0)
    assert "ada" in text.splitlines()[0]
    new_line = next(ln for ln in text.splitlines() if " new " in ln)
    old_line = next(ln for ln in text.splitlines() if " old " in ln)
    assert "  2 " in new_line
    assert "  - " in old_line


# ---------------------------------------------------------------------------
# kill-switch parity: --adapters absent => surfaces byte-identical
# ---------------------------------------------------------------------------


def test_kill_switch_no_registry_no_adapter_surfaces(base_params):
    from inferd_tpu.obs import devtel
    from inferd_tpu.runtime.batch_executor import BatchedExecutor

    ex = BatchedExecutor(TINY, base_params, lanes=2, max_len=64)
    assert "adapters" not in ex.stats()
    gauges, counters = devtel.adapter_series(ex)
    assert gauges == {} and counters == {}  # no adapter.* series at all


def test_kill_switch_client_envelope_byte_identical(monkeypatch):
    """adapter=None leaves the /forward envelope byte-identical to the
    pre-adapter wire format (the PR 13/14 parity contract)."""
    import uuid as uuidlib

    from inferd_tpu.client.swarm_client import SwarmClient
    from inferd_tpu.runtime import wire

    monkeypatch.setenv("INFERD_TRACE", "0")
    monkeypatch.setattr(uuidlib, "uuid4", lambda: uuidlib.UUID(int=9))
    plain = SwarmClient([("h", 1)])._forward_env("s", [1, 2], 0)
    manual = {
        "task_id": str(uuidlib.UUID(int=9)),
        "session_id": "s", "stage": 0,
        "payload": {
            "tokens": np.asarray([[1, 2]], dtype=np.int32),
            "start_pos": 0, "real_len": 2,
        },
    }
    assert wire.pack(plain) == wire.pack(manual)
    # a tenant client's FIRST chunk carries exactly one extra key
    env = SwarmClient([("h", 1)], adapter="ten0")._forward_env("s", [1, 2], 0)
    assert env["payload"]["adapter"] == "ten0"
    # ... and its decode steps stay byte-identical to the base wire
    step = SwarmClient([("h", 1)], adapter="ten0")._forward_env("s", [7], 5)
    assert "adapter" not in step["payload"]


def test_registry_gauges_present_with_registry(catalog, base_params):
    from inferd_tpu.obs import devtel
    from inferd_tpu.runtime.batch_executor import BatchedExecutor

    reg = AdapterRegistry(TINY, catalog)
    ex = BatchedExecutor(TINY, base_params, lanes=2, max_len=64,
                         adapters=reg)
    reg.acquire("ten0")
    gauges, counters = devtel.adapter_series(ex)
    assert gauges["adapter.resident"] == 1.0
    assert counters["adapter.loads"] == 1.0
    assert ex.stats()["adapters"]["resident"] == 1


# ---------------------------------------------------------------------------
# perf gate: the round-15 invariants
# ---------------------------------------------------------------------------


def _lt_leg(**kw):
    leg = {
        "metric": "tiny_lora_tenants_tok_per_s", "value": 400.0,
        "unit": "tok/s", "cobatch_vs_serial": 1.2,
        "serial_tok_per_s": 333.0, "token_exact": True,
        "distinct_streams": 4, "adapter_loads": 4,
    }
    leg.update(kw)
    return leg


def test_gate_lora_tenants_invariants():
    from inferd_tpu.perf import gate as gatelib

    ok = gatelib.check_artifact([("lt", _lt_leg())])
    assert not [f for f in ok if f.severity == "error"]
    bad = gatelib.check_artifact(
        [("lt", _lt_leg(value=300.0, serial_tok_per_s=333.0))]
    )
    assert any("strictly beat" in f.message for f in bad)
    bad = gatelib.check_artifact([("lt", _lt_leg(adapter_loads=0))])
    assert any("zero adapter hot-loads" in f.message for f in bad)
    bad = gatelib.check_artifact([("lt", _lt_leg(distinct_streams=1))])
    assert any("not discriminating" in f.message for f in bad)
    bad = gatelib.check_artifact([("lt", _lt_leg(token_exact=False))])
    assert any(f.severity == "error" and "token_exact" in f.message
               for f in bad)


def test_gate_lora_tenants_prior_regression_and_skip():
    from inferd_tpu.perf import gate as gatelib

    prior = [("lt", _lt_leg(cobatch_vs_serial=1.5))]
    fresh = [("lt", _lt_leg(cobatch_vs_serial=1.1))]  # 26.7% drop
    found = gatelib.check_artifact(fresh, prior)
    assert any(
        f.check == "regression" and "cobatch_vs_serial" in f.message
        for f in found
    )
    # missing ratio on either side SKIPS (no raw-tok/s fallback)
    legless = [("lt", {k: v for k, v in _lt_leg().items()
                       if k != "cobatch_vs_serial"})]
    assert not [
        f for f in gatelib.check_artifact(legless, prior)
        if f.check == "regression"
    ]


def test_committed_lora_artifact_passes_gate():
    from inferd_tpu.perf import gate as gatelib

    path = os.path.join(
        os.path.dirname(__file__), "..", "bench_artifacts",
        "BENCH_lora_cpu_r15.json",
    )
    findings, ok = gatelib.gate(path, prior_path=path)
    assert ok, [f.line() for f in findings]
    leg = dict(gatelib.load_artifact(path))["tiny_lora_tenants_tok_per_s"]
    assert leg["token_exact"] is True
    assert leg["cobatch_vs_serial"] > 1.0
    assert leg["tenants"] >= 4 and leg["adapter_loads"] >= leg["tenants"]


# ---------------------------------------------------------------------------
# sim: the committed adapter-affinity placement rehearsal
# ---------------------------------------------------------------------------


def test_adapter_affinity_fixtures_exist_and_diverge():
    with open(os.path.join(SIM_DATA, "adapter_affinity.json")) as f:
        on = json.load(f)
    with open(os.path.join(SIM_DATA, "adapter_affinity_off.json")) as f:
        off = json.load(f)
    gates_on = {tuple(g[:2]): g[2] for g in on["gates"]}
    gates_off = {tuple(g[:2]): g[2] for g in off["gates"]}
    # the committed pair IS the placement proof: the affinity-on
    # resident-hit floor sits strictly above the blind-baseline ceiling
    assert gates_on[("adapters.hit_frac", ">=")] > gates_off[
        ("adapters.hit_frac", "<=")
    ]
    # zero hung sessions in BOTH modes (a miss hot-loads, never wedges)
    assert gates_on[("sessions.hung", "==")] == 0
    assert gates_off[("sessions.hung", "==")] == 0


def test_resident_names_gossip_cap(catalog):
    reg = AdapterRegistry(TINY, catalog)
    for name in ("ten0", "ten1", "ten2"):
        reg.acquire(name)
    assert len(reg.resident_names()) <= ADA_GOSSIP_MAX
    assert reg.resident_names() == ["ten0", "ten1", "ten2"]


# ---------------------------------------------------------------------------
# review fixes: handoff rebinding, evict-after-read, target-union pools
# ---------------------------------------------------------------------------


def test_export_import_preserves_adapter_binding(catalog, base_params):
    """A tenant session handed off between replicas (drain migration /
    standby promotion) carries its adapter on the handoff payload and
    REBINDS it on the importer, continuing token-exact — and a
    registry-less importer DECLINES instead of silently resuming the
    stream on the base weights (the same corruption admission rejects
    loudly)."""
    from inferd_tpu.runtime.batch_executor import BatchedExecutor

    name = os.path.basename(catalog[0])
    ref = _merged_ref(base_params, catalog[0], PROMPT, 6)

    ex1 = BatchedExecutor(TINY, base_params, lanes=2, max_len=64,
                          adapters=AdapterRegistry(TINY, catalog))
    out = ex1.process("s", {"tokens": [PROMPT], "start_pos": 0,
                            "real_len": len(PROMPT), "adapter": name})
    toks = [int(np.argmax(out["logits"][0]))]
    pos = len(PROMPT)
    for _ in range(2):
        o = ex1.process("s", {"tokens": [[toks[-1]]], "start_pos": pos,
                              "real_len": 1})
        toks.append(int(np.argmax(o["logits"][0])))
        pos += 1
    exported = dict(ex1.export_sessions(only="s"))
    assert exported["s"]["adapter"] == name  # the binding rides the payload
    # base sessions' payloads gain no key (byte-identical to pre-adapter)
    ex1.process("b", {"tokens": [PROMPT], "start_pos": 0,
                      "real_len": len(PROMPT)})
    assert "adapter" not in dict(ex1.export_sessions(only="b"))["b"]

    bare = BatchedExecutor(TINY, base_params, lanes=2, max_len=64)
    assert bare.import_session("s", exported["s"]) is False

    ex2 = BatchedExecutor(TINY, base_params, lanes=2, max_len=64,
                          adapters=AdapterRegistry(TINY, catalog))
    assert ex2.import_session("s", exported["s"]) is True
    # the rebound adapter holds a live-session reference on the importer
    assert ex2.adapters.stats()["resident"] == 1
    for _ in range(3):
        o = ex2.process("s", {"tokens": [[toks[-1]]], "start_pos": pos,
                              "real_len": 1})
        toks.append(int(np.argmax(o["logits"][0])))
        pos += 1
    assert toks == ref  # the handed-off stream never left the tenant's weights


def test_unreadable_catalog_entry_never_evicts_residents(catalog, tmp_path):
    """A cataloged-but-unreadable adapter fails at the DISK READ, before
    any eviction decision — repeated admission retries for it must not
    churn-evict healthy residents one slot at a time."""
    import shutil

    ok = str(tmp_path / "ok")
    ghost = str(tmp_path / "ghost")
    shutil.copytree(catalog[0], ok)
    shutil.copytree(catalog[1], ghost)
    reg = AdapterRegistry(TINY, [ok, ghost], slots=2)  # ONE usable slot
    reg.acquire("ok")
    reg.release("ok")  # resident, idle -> LRU-evictable
    shutil.rmtree(ghost)  # becomes unreadable after startup
    for _ in range(3):
        with pytest.raises(Exception):
            reg.acquire("ghost")
    st = reg.stats()
    assert st["resident"] == 1 and st["evictions"] == 0
    assert reg.resident_names() == ["ok"]


def test_pools_cover_only_the_catalog_target_union(base_params, tmp_path):
    """An attention-only catalog allocates NO MLP pools (the
    intermediate_size-wide ones are the bulk of the memory) and pays no
    zero-math for them per dispatch — while staying token-exact vs the
    merged reference."""
    adir = str(tmp_path / "att")
    loralib.save_adapter(
        adir, _mk_layers(TINY, 7, targets=("q_proj", "v_proj")),
        alpha=8, r=4,
    )
    reg = AdapterRegistry(TINY, [adir])
    assert reg.targets == ("q_proj", "v_proj")
    reg.acquire("att")
    pools = reg.device_adapters()
    assert set(pools["a"]) == {"q_proj", "v_proj"}
    assert set(pools["b"]) == {"q_proj", "v_proj"}

    from inferd_tpu.runtime.batch_executor import BatchedExecutor

    ex = BatchedExecutor(TINY, base_params, lanes=2, max_len=64,
                         adapters=AdapterRegistry(TINY, [adir]))
    s = _greedy_stream(ex, "s", PROMPT, 4, adapter="att")
    assert s == _merged_ref(base_params, adir, PROMPT, 4)


def test_standby_store_carries_adapter_to_promotion():
    """Replication deltas stamped with the session's adapter re-emit it
    in the promotion payload (import_session rebinds or declines); base
    sessions' shadows gain no key."""
    from inferd_tpu.runtime.repl import StandbyStore

    st = StandbyStore()
    k = np.zeros((2, 1, 4, 2, 8), np.float32)
    ok, _ = st.apply("s", 0, {"k": k, "v": k, "length": 4, "start": 0,
                              "adapter": "ten0"})
    assert ok
    assert st.payload("s")["adapter"] == "ten0"
    ok, _ = st.apply("b", 0, {"k": k, "v": k, "length": 4, "start": 0})
    assert ok
    assert "adapter" not in st.payload("b")


def test_mesh_executor_declines_adapter_stamped_import():
    """The mesh executor has no registry (--adapters is lane-executor-
    only), so an adapter-stamped handoff/standby payload must DECLINE —
    adopting it would silently resume the tenant on the base weights.
    The guard fires before any executor state is touched."""
    from inferd_tpu.runtime.mesh_executor import MeshExecutor

    class _Stub:  # the guard must return before reading any attribute
        pass

    assert MeshExecutor.import_session(
        _Stub(), "s", {"adapter": "ten0"}
    ) is False


def test_standby_pick_requires_adapter_capable_peer():
    """A tenant session's shadow only goes to a peer gossiping the
    `ada` key (the capability marker, present even when empty): an
    old-release or registry-less standby would accumulate deltas it can
    never promote. A sticky shadow on a non-capable peer re-picks
    away; base sessions keep the plain best-ranked pick."""
    from inferd_tpu.runtime.repl import SessionReplicator

    cands = [("old", {"load": 0}), ("cap", {"load": 1, "ada": []})]
    rep = SessionReplicator(lambda: cands)
    assert rep.pick_standby("s", cands) == "old"  # base: best rank wins
    assert rep.pick_standby("s", cands, require_ada=True) == "cap"
    rep.state["t"] = ("old", 7)  # sticky shadow on a non-capable peer
    assert rep.pick_standby("t", cands) == "old"
    assert rep.pick_standby("t", cands, require_ada=True) == "cap"
    plans = {sid: nid for sid, nid, _f in rep.plan(
        {"base": 4, "ten": 4}, adapters={"ten": "ten0"}
    )}
    assert plans == {"base": "old", "ten": "cap"}


def test_registry_can_serve_gates_standby_acceptance(catalog):
    """The /replicate_session receiver's serviceability check: a
    registry-less executor (or one whose catalog lacks the name) can
    never promote the shadow, so it must decline the delta up front;
    base-session deltas are always welcome."""
    from inferd_tpu.runtime.adapters import registry_can_serve

    class _Ex:
        adapters = None

    ex = _Ex()
    assert registry_can_serve(ex, None)           # base: always
    assert not registry_can_serve(ex, "ten0")     # no registry
    ex.adapters = AdapterRegistry(TINY, catalog)
    assert registry_can_serve(ex, "ten0")
    assert not registry_can_serve(ex, "other_tenant")


def test_affinity_probe_salt_scopes_prefix_matching():
    """A tenant session's prefix probe must carry its adapter salt: the
    salted probe matches digests of salted chains (its own cached
    blocks) and NOT base-session digests for the same prompt — and vice
    versa (an unsalted probe scoring salted keys would bonus a replica
    whose blocks the session cannot map)."""
    ids = list(range(32))
    bs = 8
    base_keys = {prefixlib.digest_key(k)
                 for k in prefixlib.block_keys(ids, bs)}
    ten_keys = {prefixlib.digest_key(k)
                for k in prefixlib.block_keys(ids, bs, salt="ten0")}
    assert base_keys.isdisjoint(ten_keys)
    base_rec = {"pfx": {"bs": bs, "k": sorted(base_keys)}}
    ten_rec = {"pfx": {"bs": bs, "k": sorted(ten_keys)}}
    salted = prefixlib.AffinityProbe(ids, salt="ten0")
    unsalted = prefixlib.AffinityProbe(ids)
    assert salted.depth_frac(ten_rec) == 1.0
    assert salted.depth_frac(base_rec) == 0.0
    assert unsalted.depth_frac(base_rec) == 1.0
    assert unsalted.depth_frac(ten_rec) == 0.0
