"""Deploy generator tests (the reference's generate_docker_compose.py
semantics, SURVEY §2 'Deploy generator': per-node services, static IPs on
the bridge subnet, env injection — plus the shared-parts redesign)."""

import ipaddress
import os
import subprocess

import yaml

from inferd_tpu.parallel.stages import Manifest
from inferd_tpu.tools.deploy import SUBNET, generate_compose, generate_local_script

EXAMPLE = os.path.join(os.path.dirname(__file__), "..", "examples", "cluster.yaml")


def _manifest():
    return Manifest.from_yaml(EXAMPLE)


def test_compose_services_and_ips():
    compose = generate_compose(_manifest())
    services = compose["services"]
    assert set(services) == {"seed", "node0", "node1", "node2", "node3"}
    net = ipaddress.ip_network(SUBNET)
    ips = set()
    for name, svc in services.items():
        ip = ipaddress.ip_address(svc["networks"]["inferd"]["ipv4_address"])
        assert ip in net
        ips.add(ip)
    assert len(ips) == 5  # all static IPs distinct
    assert compose["networks"]["inferd"]["ipam"]["config"][0]["subnet"] == SUBNET


def test_compose_env_injection():
    compose = generate_compose(_manifest(), device="cpu")
    n2 = compose["services"]["node2"]
    env = n2["environment"]
    assert env["NODE_NAME"] == "node2"
    assert env["INITIAL_STAGE"] == "2"
    seed_ip = compose["services"]["seed"]["networks"]["inferd"]["ipv4_address"]
    assert env["BOOTSTRAP_NODES"] == f"{seed_ip}:7050"
    assert env["NODE_IP"] == n2["networks"]["inferd"]["ipv4_address"]


def test_compose_shared_parts_and_manifest_volumes():
    """Every node mounts the SAME read-only parts store (migration fix —
    unlike the reference's per-node PTH_DIR bake, SURVEY B2) AND this
    deployment's manifest over the image-baked default."""
    compose = generate_compose(
        _manifest(), parts_dir="/srv/parts", manifest_path="/srv/cluster.yaml"
    )
    vols = {
        name: svc["volumes"]
        for name, svc in compose["services"].items()
        if name != "seed"
    }
    expected = ["/srv/parts:/parts:ro", "/srv/cluster.yaml:/app/cluster.yaml:ro"]
    assert all(v == expected for v in vols.values())


def test_compose_tpu_mode_pins_one_chip_per_container():
    compose = generate_compose(_manifest(), device="tpu")
    for i, name in enumerate(["node0", "node1", "node2", "node3"]):
        svc = compose["services"][name]
        assert svc["privileged"] is True
        assert svc["environment"]["INFERD_DEVICE"] == "tpu"
        assert svc["environment"]["TPU_VISIBLE_DEVICES"] == str(i)


def test_compose_yaml_roundtrip(tmp_path):
    compose = generate_compose(_manifest())
    p = tmp_path / "compose.yaml"
    p.write_text(yaml.safe_dump(compose, sort_keys=False))
    assert yaml.safe_load(p.read_text())["services"]["node1"]["depends_on"] == ["seed"]


def test_local_script_shape(tmp_path):
    script = generate_local_script(_manifest(), device="tpu")
    assert script.startswith("#!/usr/bin/env bash")
    # seed first, then one line per node with distinct ports and chip pins
    assert "tools.seed --port 7050" in script
    for i, name in enumerate(["node0", "node1", "node2", "node3"]):
        assert f"--name {name}" in script
        assert f"--port {6050 + i}" in script
        assert f"TPU_VISIBLE_DEVICES={i} " in script
    # valid bash
    p = tmp_path / "launch.sh"
    p.write_text(script)
    subprocess.run(["bash", "-n", str(p)], check=True)


def test_quant_threading():
    """--quant reaches every node: compose env INFERD_QUANT, local --quant."""
    m = _manifest()
    compose = generate_compose(m, quant="int8")
    for name, svc in compose["services"].items():
        if name == "seed":
            continue
        assert svc["environment"]["INFERD_QUANT"] == "int8"
    script = generate_local_script(m, quant="w8a8")
    assert script.count("--quant w8a8") == len(m.nodes)


def test_mesh_threading():
    """--mesh reaches every node (1-stage manifest), skips TPU chip pinning
    (the node owns its whole slice), and rejects multi-stage manifests."""
    import pytest

    m1 = Manifest.even_split("tiny", 1)
    compose = generate_compose(m1, mesh="pp=4,tp=2", device="tpu")
    for name, svc in compose["services"].items():
        if name == "seed":
            continue
        assert svc["environment"]["INFERD_MESH"] == "pp=4,tp=2"
        assert "TPU_VISIBLE_DEVICES" not in svc["environment"]
        assert svc["privileged"] is True
    script = generate_local_script(m1, mesh="pp=2,ep=2", device="tpu")
    assert script.count("--mesh pp=2,ep=2") == len(m1.nodes)
    assert "TPU_VISIBLE_DEVICES" not in script

    with pytest.raises(ValueError, match="1-stage manifest"):
        generate_compose(_manifest(), mesh="pp=4")


def test_batch_lanes_threading():
    m1 = Manifest.even_split("tiny", 1)
    compose = generate_compose(m1, batch_lanes=8)
    for name, svc in compose["services"].items():
        if name == "seed":
            continue
        assert svc["environment"]["INFERD_BATCH_LANES"] == "8"
    script = generate_local_script(m1, batch_lanes=4)
    assert script.count("--batch-lanes 4") == len(m1.nodes)


def test_spec_draft_threading():
    m1 = Manifest.even_split("tiny", 1)
    compose = generate_compose(m1, spec_draft_layers=8)
    for name, svc in compose["services"].items():
        if name == "seed":
            continue
        assert svc["environment"]["INFERD_SPEC_DRAFT_LAYERS"] == "8"
    script = generate_local_script(m1, spec_draft_layers=8)
    assert script.count("--spec-draft-layers 8") == len(m1.nodes)
