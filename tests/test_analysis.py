"""jaxlint (inferd_tpu.analysis): per-rule fixtures, the repo self-scan
gate, and the runtime sanitizers.

Each rule gets one minimal positive and one negative fixture; J002, J003
and J006 additionally get regression fixtures reproducing the real
pre-fix bugs this PR fixed (the literal `default_backend() == "tpu"`
probe from ops/quant.py, the donated-cache-reuse shape, the
decode-loop host sync). The self-scan test is the CI gate: zero
non-baselined findings over inferd_tpu/ + tests/.
"""

import json
import os
import subprocess
import sys
from pathlib import Path

import pytest

from inferd_tpu.analysis import (
    Baseline,
    NanError,
    RetraceError,
    RetraceGuard,
    check_paths,
    check_source,
    nan_guard,
)
from inferd_tpu.analysis import retrace_guard as retrace_guard_cm

REPO = Path(__file__).resolve().parents[1]


def rules_of(src: str):
    return sorted({f.rule for f in check_source(src)})


def findings(src: str, rule: str):
    return [f for f in check_source(src) if f.rule == rule]


# --------------------------------------------------------------- J001


def test_j001_python_scalar_param_not_static():
    src = (
        "import jax\n"
        "@jax.jit\n"
        "def f(x, n: int):\n"
        "    return x * n\n"
    )
    assert [f.rule for f in findings(src, "J001")] == ["J001"]


def test_j001_mutable_default_and_mutated_global():
    src = (
        "import jax\n"
        "STATE = 0\n"
        "def bump():\n"
        "    global STATE\n"
        "    STATE += 1\n"
        "@jax.jit\n"
        "def f(x, buf=[]):\n"
        "    return x + STATE\n"
    )
    msgs = [f.message for f in findings(src, "J001")]
    assert any("mutable default" in m for m in msgs)
    assert any("global `STATE`" in m for m in msgs)


def test_j001_negative_pytree_carry_annotation():
    # a fixed-structure pytree carry is the idiomatic NON-static jit arg
    src = (
        "import jax\n"
        "from typing import Tuple\n"
        "@jax.jit\n"
        "def step(carry: Tuple, x: tuple):\n"
        "    return carry, x\n"
    )
    assert findings(src, "J001") == []


def test_j001_negative_static_argnames():
    src = (
        "import jax\n"
        "from functools import partial\n"
        "@partial(jax.jit, static_argnames=('n',))\n"
        "def f(x, n: int):\n"
        "    return x * n\n"
    )
    assert findings(src, "J001") == []


# --------------------------------------------------------------- J002


DONATING_STEP = (
    "import jax\n"
    "from functools import partial\n"
    "@partial(jax.jit, donate_argnames=('cache',))\n"
    "def step(tok, cache):\n"
    "    return tok, cache\n"
)


def test_j002_use_after_donate():
    src = DONATING_STEP + (
        "def run(tok, cache):\n"
        "    out, _ = step(tok, cache)\n"
        "    return cache.sum()\n"
    )
    out = findings(src, "J002")
    assert len(out) == 1 and "donated" in out[0].message


def test_j002_loop_never_rebinds():
    # the decode-loop shape: donating the cache every iteration without
    # ever rebinding it re-donates a consumed buffer
    src = DONATING_STEP + (
        "def run(tok, cache):\n"
        "    for _ in range(8):\n"
        "        out = step(tok, cache)\n"
        "    return out\n"
    )
    out = findings(src, "J002")
    assert len(out) == 1 and "loop" in out[0].message


def test_j002_negative_rebound():
    src = DONATING_STEP + (
        "def run(tok, cache):\n"
        "    out, cache = step(tok, cache)\n"
        "    return cache.sum()\n"
        "def run_loop(tok, cache):\n"
        "    for _ in range(8):\n"
        "        tok, cache = step(tok, cache)\n"
        "    return tok\n"
    )
    assert findings(src, "J002") == []


def test_j002_jit_call_form_with_argnums():
    src = (
        "import jax\n"
        "def _step(tok, cache):\n"
        "    return tok, cache\n"
        "step = jax.jit(_step, donate_argnums=(1,))\n"
        "def run(tok, cache):\n"
        "    out, _ = step(tok, cache)\n"
        "    return cache.sum()\n"
    )
    assert len(findings(src, "J002")) == 1


def test_j002_negative_def_inside_loop_is_separate_scope():
    # a callback *defined* per iteration never executes in the loop —
    # its donating call must not be attributed to the loop body
    src = DONATING_STEP + (
        "def run(toks, cache):\n"
        "    cbs = []\n"
        "    for tok in toks:\n"
        "        def cb():\n"
        "            return step(tok, cache)\n"
        "        cbs.append(cb)\n"
        "    return cbs\n"
    )
    assert findings(src, "J002") == []


def test_j002_negative_conditional_call_rebound_in_outer_loop_body():
    # call sits in a nested if, the rebind in the outer loop body: the
    # loop DOES rebind every iteration — must not flag
    src = DONATING_STEP + (
        "def run(toks, cache):\n"
        "    for tok in toks:\n"
        "        if tok > 0:\n"
        "            out = step(tok, cache)\n"
        "        tok2, cache = out\n"
        "    return out\n"
    )
    assert findings(src, "J002") == []


# --------------------------------------------------------------- J003


def test_j003_sync_in_decode_loop():
    # the real pre-fix bug class: per-token host reads in a decode loop
    src = (
        "import jax\n"
        "import jax.numpy as jnp\n"
        "import numpy as np\n"
        "def decode(step, tok):\n"
        "    out = []\n"
        "    while len(out) < 8:\n"
        "        tok = step(tok, jnp.int32(1))\n"
        "        out.append(int(tok[0]))\n"
        "        np.asarray(tok)\n"
        "        tok.block_until_ready()\n"
        "    return out\n"
    )
    msgs = [f.message for f in findings(src, "J003")]
    assert len(msgs) == 3
    assert any("int(tok[0])" in m for m in msgs)
    assert any("np.asarray" in m for m in msgs)
    assert any("block_until_ready" in m for m in msgs)


def test_j003_sync_in_while_condition():
    # the canonical decode shape with the per-token sync in the TEST
    src = (
        "import jax.numpy as jnp\n"
        "def decode(step, tok, done):\n"
        "    while int(tok[0]) != 2:\n"
        "        tok = step(tok, jnp.int32(1))\n"
        "    while not done.item():\n"
        "        done = step(tok, jnp.int32(0))\n"
        "    return tok\n"
    )
    assert len(findings(src, "J003")) == 2


def test_j003_negative_host_only_loop():
    # int(line[0]) in a loop that never touches jax: not a device sync
    src = (
        "import jax\n"
        "def count(lines):\n"
        "    total = 0\n"
        "    for line in lines:\n"
        "        total += int(line[0])\n"
        "    return total\n"
    )
    assert findings(src, "J003") == []


def test_j003_negative_sync_outside_loop():
    src = (
        "import jax.numpy as jnp\n"
        "import numpy as np\n"
        "def summarize(x):\n"
        "    y = jnp.sum(x)\n"
        "    return np.asarray(y)\n"
    )
    assert findings(src, "J003") == []


# --------------------------------------------------------------- J004


def test_j004_print_and_np_random_under_jit():
    src = (
        "import jax\n"
        "import numpy as np\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    print('tracing', x)\n"
        "    return x + np.random.rand()\n"
    )
    msgs = [f.message for f in findings(src, "J004")]
    assert any("print" in m for m in msgs)
    assert any("np.random.rand" in m for m in msgs)


def test_j004_append_in_scan_body():
    src = (
        "from jax import lax\n"
        "def outer(xs):\n"
        "    acc = []\n"
        "    def body(c, x):\n"
        "        acc.append(x)\n"
        "        return c, x\n"
        "    return lax.scan(body, 0.0, xs)\n"
    )
    out = findings(src, "J004")
    assert len(out) == 1 and "acc" in out[0].message


def test_j004_negative_jax_random_and_local_append():
    src = (
        "import jax\n"
        "@jax.jit\n"
        "def f(x, key):\n"
        "    parts = []\n"
        "    parts.append(jax.random.normal(key, x.shape))\n"
        "    return x + parts[0]\n"
    )
    assert findings(src, "J004") == []


# --------------------------------------------------------------- J005


def test_j005_blocking_sleep_and_dropped_coroutine():
    src = (
        "import time\n"
        "async def worker():\n"
        "    time.sleep(1)\n"
        "async def main():\n"
        "    worker()\n"
    )
    msgs = [f.message for f in findings(src, "J005")]
    assert any("time.sleep" in m for m in msgs)
    assert any("never awaited" in m for m in msgs)


def test_j005_negative_awaited_and_other_object():
    # `other.start()` must NOT match an unrelated `async def start`
    # elsewhere in the module (the Balancer-vs-Node false positive)
    src = (
        "import asyncio\n"
        "class Node:\n"
        "    async def start(self):\n"
        "        await asyncio.sleep(0)\n"
        "    async def boot(self, balancer):\n"
        "        await self.start()\n"
        "        balancer.start()\n"
    )
    assert findings(src, "J005") == []


def test_j005_self_method_dropped():
    src = (
        "import asyncio\n"
        "class Node:\n"
        "    async def start(self):\n"
        "        await asyncio.sleep(0)\n"
        "    async def boot(self):\n"
        "        self.start()\n"
    )
    assert len(findings(src, "J005")) == 1


# --------------------------------------------------------------- J006


def test_j006_regression_prefix_quant_pattern():
    # the EXACT pre-fix line from ops/quant.py:212 (ADVICE-r5 true
    # positive): behind the tunneled `axon` proxy this selects the
    # non-TPU scheme on a real TPU
    src = (
        "import jax\n"
        "INT4_MODE = 'auto'\n"
        "def _int4_mode():\n"
        "    if INT4_MODE != 'auto':\n"
        "        return INT4_MODE\n"
        "    return 'dequant' if jax.default_backend() == 'tpu' else 'grouped'\n"
    )
    out = findings(src, "J006")
    assert len(out) == 1 and out[0].line == 6


def test_j006_tainted_variable_and_interpret_kwarg():
    # the other two pre-fix shapes: quant.py:251's `!=` kwarg and the
    # assigned-then-compared variable
    src = (
        "import jax\n"
        "def pick(kernel):\n"
        "    backend = jax.default_backend()\n"
        "    if backend == 'tpu':\n"
        "        return kernel(interpret=jax.default_backend() != 'tpu')\n"
        "    return None\n"
    )
    assert len(findings(src, "J006")) == 2


def test_j006_taint_is_per_scope():
    # an unrelated variable sharing the name `backend` in ANOTHER
    # function must not inherit the taint
    src = (
        "import jax\n"
        "def probe():\n"
        "    backend = jax.default_backend()\n"
        "    return backend\n"
        "def send(backend: str):\n"
        "    return backend == 'grpc'\n"
    )
    assert findings(src, "J006") == []


def test_j006_negative_helper():
    src = (
        "from inferd_tpu.utils.platform import is_tpu\n"
        "def pick():\n"
        "    return 'dequant' if is_tpu() else 'grouped'\n"
    )
    assert findings(src, "J006") == []


# ------------------------------------------------- suppressions/baseline


def test_inline_suppression_requires_reason():
    base = (
        "import jax\n"
        "def pick():\n"
        "    return jax.default_backend() == 'tpu'{}\n"
    )
    with_reason = base.format("  # jaxlint: disable=J006 -- fixture")
    without = base.format("  # jaxlint: disable=J006")
    assert findings(with_reason, "J006") == []
    bad = findings(without, "J006")
    assert len(bad) == 1 and "missing a `-- reason`" in bad[0].note


def test_suppression_in_string_literal_is_ignored():
    # quoting the directive syntax (docs, fixtures) must not actually
    # suppress anything — only real COMMENT tokens count
    src = (
        "import jax\n"
        "DOC = '# jaxlint: file-disable=J006 -- just quoting the syntax'\n"
        "def pick():\n"
        "    return jax.default_backend() == 'tpu'\n"
    )
    assert len(findings(src, "J006")) == 1


def test_reasonless_directive_does_not_shadow_file_disable():
    src = (
        "# jaxlint: file-disable=J006 -- fixture-wide reason\n"
        "import jax\n"
        "def pick():\n"
        "    return jax.default_backend() == 'tpu'  # jaxlint: disable=J006\n"
    )
    assert findings(src, "J006") == []


def test_j003_negative_orelse_runs_once():
    # a for/while `else:` clause runs ONCE after the loop — not per
    # iteration
    src = (
        "import jax.numpy as jnp\n"
        "import numpy as np\n"
        "def reduce(xs, dev):\n"
        "    for x in xs:\n"
        "        dev = dev + jnp.float32(x)\n"
        "    else:\n"
        "        out = np.asarray(dev)\n"
        "    return out\n"
    )
    assert findings(src, "J003") == []


def test_j003_suppression_on_last_line_of_multiline_call():
    src = (
        "import jax.numpy as jnp\n"
        "import numpy as np\n"
        "def drain(step, t):\n"
        "    for _ in range(4):\n"
        "        t = step(t, jnp.int32(1))\n"
        "        v = np.asarray(\n"
        "            t)  # jaxlint: disable=J003 -- fixture: trailing the last line\n"
        "    return v\n"
    )
    assert findings(src, "J003") == []


def test_j003_negative_lambda_in_loop():
    # a callback *defined* in a loop doesn't sync per iteration
    src = (
        "import jax.numpy as jnp\n"
        "import numpy as np\n"
        "def register(handlers, state):\n"
        "    cbs = []\n"
        "    for h in handlers:\n"
        "        s = jnp.sum(state)\n"
        "        cbs.append(lambda: np.asarray(s))\n"
        "    return cbs\n"
    )
    assert findings(src, "J003") == []


def test_baseline_empty_reason_entry_is_not_stale(tmp_path):
    src = (
        "import jax\n"
        "def pick():\n"
        "    return jax.default_backend() == 'tpu'\n"
    )
    f = check_source(src, path="pkg/mod.py")
    path = tmp_path / "base.json"
    Baseline.write(str(path), f)  # empty reasons
    b = Baseline.load(str(path))
    assert len(b.filter(list(f))) == 1  # does not suppress...
    assert b.unused() == []  # ...but matches code that exists: not stale


def test_baseline_count_limits_duplicate_occurrences(tmp_path):
    # a NEW duplicate of a baselined line must resurface, not ride the
    # existing entry
    one = (
        "import jax\n"
        "def pick():\n"
        "    a = jax.default_backend() == 'tpu'\n"
        "    return a\n"
    )
    two = (  # the SAME line duplicated -> identical fingerprint
        "import jax\n"
        "def pick():\n"
        "    a = jax.default_backend() == 'tpu'\n"
        "    a = jax.default_backend() == 'tpu'\n"
        "    return a\n"
    )
    path = tmp_path / "base.json"
    Baseline.write(str(path), check_source(one, path="m.py"))
    data = json.loads(path.read_text())
    assert data["entries"][0]["count"] == 1
    data["entries"][0]["reason"] = "fixture"
    path.write_text(json.dumps(data))
    b = Baseline.load(str(path))
    assert b.filter(check_source(one, path="m.py")) == []  # covered
    # a Baseline instance accumulates hits for ONE scan; load fresh
    leaked = Baseline.load(str(path)).filter(check_source(two, path="m.py"))
    assert len(leaked) == 1 and "NEW duplicate" in leaked[0].note


def test_write_baseline_preserves_reasons(tmp_path):
    # regenerating the baseline must carry hand-written reasons over
    src = (
        "import jax\n"
        "def pick():\n"
        "    return jax.default_backend() == 'tpu'\n"
    )
    mod = tmp_path / "m.py"
    mod.write_text(src)
    base = tmp_path / "base.json"
    env = dict(os.environ, JAX_PLATFORMS="cpu")

    def run(*extra):
        return subprocess.run(
            [sys.executable, "-m", "inferd_tpu.analysis", "check",
             str(mod), *extra],
            capture_output=True, text=True, env=env, cwd=str(REPO),
        )

    run("--baseline", "none", "--write-baseline", str(base))
    data = json.loads(base.read_text())
    data["entries"][0]["reason"] = "hand-written justification"
    base.write_text(json.dumps(data))
    r = run("--baseline", "none", "--write-baseline", str(base))
    assert "1 with carried-over reasons" in r.stdout, r.stdout
    data = json.loads(base.read_text())
    assert data["entries"][0]["reason"] == "hand-written justification"
    # also across directories: entries re-key into the new file's frame
    sub = tmp_path / "sub"
    sub.mkdir()
    r = run("--baseline", str(base), "--write-baseline", str(sub / "b2.json"))
    assert "1 with carried-over reasons" in r.stdout, r.stdout
    data2 = json.loads((sub / "b2.json").read_text())
    assert data2["entries"][0]["reason"] == "hand-written justification"
    assert data2["entries"][0]["file"] == "../m.py"
    # a PARTIAL refresh (--rules subset) must keep out-of-scope entries
    # verbatim instead of silently deleting them and their reasons
    r = run("--baseline", str(base), "--rules", "J003",
            "--write-baseline", str(base))
    assert "1 out-of-scope entry kept" in r.stdout, r.stdout
    data3 = json.loads(base.read_text())
    assert len(data3["entries"]) == 1
    assert data3["entries"][0]["rule"] == "J006"
    assert data3["entries"][0]["reason"] == "hand-written justification"


def test_chip_probe_refuses_wrong_backend(monkeypatch):
    # once jax is initialized, the main() re-pin cannot switch backends;
    # the probe must refuse rather than time the wrong chip
    from inferd_tpu.tools import chip_probe

    monkeypatch.setattr(chip_probe, "is_cpu", lambda: False)
    monkeypatch.setattr(chip_probe, "is_tpu", lambda: True)
    assert chip_probe.main(["--device=cpu", "--small", "--skip-model"]) == 2


def test_chip_probe_tpu_request_on_cpu_gets_mismatch_message(
    capsys, monkeypatch
):
    # the honest diagnostic, not 'pass --device cpu to probe the host'.
    # main()'s force_platform mutates JAX_PLATFORMS + jax config; register
    # the env key with monkeypatch and restore the config so later tests'
    # subprocesses never inherit a "tpu" pin (which would dial the tunnel)
    import jax

    from inferd_tpu.tools import chip_probe

    jax.devices()  # initialize the cpu backend FIRST: otherwise main()'s
    # force_platform("tpu") pin would drive the first-ever backend init
    # at the tpu plugin (hang/dial on tunneled boxes)
    monkeypatch.setenv("JAX_PLATFORMS", os.environ.get("JAX_PLATFORMS", "cpu"))
    try:
        rc = chip_probe.main(["--device=tpu", "--small", "--skip-model"])
    finally:
        jax.config.update("jax_platforms", "cpu")
    assert rc == 2
    err = capsys.readouterr().err
    assert "--device=tpu requested but the resolved backend is cpu" in err


def test_baseline_roundtrip_and_empty_reason(tmp_path):
    src = (
        "import jax\n"
        "def pick():\n"
        "    return jax.default_backend() == 'tpu'\n"
    )
    f = check_source(src, path="pkg/mod.py")
    assert len(f) == 1
    path = tmp_path / "base.json"
    Baseline.write(str(path), f)
    b = Baseline.load(str(path))
    # empty reason does not suppress
    assert len(b.filter(list(f))) == 1
    data = json.loads(path.read_text())
    data["entries"][0]["reason"] = "fixture"
    path.write_text(json.dumps(data))
    b = Baseline.load(str(path))
    assert b.filter(list(f)) == []
    assert b.unused() == []


def test_self_scan_zero_unbaselined_findings():
    """The CI gate: the committed baseline covers everything, nothing
    else fires across the package, the test tree, and the root-level
    entry points (bench.py is where the J006 bug class actually lived)."""
    found = check_paths(
        [
            str(REPO / "inferd_tpu"),
            str(REPO / "tests"),
            str(REPO / "bench.py"),
            str(REPO / "__graft_entry__.py"),
        ],
        rel_to=str(REPO),
    )
    baseline = Baseline.load(str(REPO / "analysis-baseline.json"))
    remaining = baseline.filter(found)
    assert remaining == [], "\n".join(f.render() for f in remaining)


def test_cli_check_exit_codes(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text(
        "import jax\n"
        "def pick():\n"
        "    return jax.default_backend() == 'tpu'\n"
    )
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    r = subprocess.run(
        [sys.executable, "-m", "inferd_tpu.analysis", "check", str(bad),
         "--baseline", "none"],
        capture_output=True, text=True, env=env, cwd=str(REPO),
    )
    assert r.returncode == 1 and "J006" in r.stdout
    ok = tmp_path / "ok.py"
    ok.write_text("x = 1\n")
    r = subprocess.run(
        [sys.executable, "-m", "inferd_tpu.analysis", "check", str(ok),
         "--baseline", "none"],
        capture_output=True, text=True, env=env, cwd=str(REPO),
    )
    assert r.returncode == 0, r.stdout + r.stderr
    # a mistyped scan path must FAIL the gate, not silently scan nothing
    r = subprocess.run(
        [sys.executable, "-m", "inferd_tpu.analysis", "check",
         str(tmp_path / "no_such_dir"), "--baseline", "none"],
        capture_output=True, text=True, env=env, cwd=str(REPO),
    )
    assert r.returncode == 2 and "does not exist" in r.stderr
    # ...and so must an existing file that isn't Python (e.g. a typo'd
    # `bench.sh` for `bench.py`): scanning nothing must not pass
    r = subprocess.run(
        [sys.executable, "-m", "inferd_tpu.analysis", "check", "run.sh",
         "--baseline", "none"],
        capture_output=True, text=True, env=env, cwd=str(REPO),
    )
    assert r.returncode == 2 and "not a Python file" in r.stderr


def test_cli_gate_matches_baseline_from_any_cwd():
    # finding fingerprints are relative to the baseline file's directory,
    # so invoking the gate from a subdirectory still matches entries; and
    # entries for files OUTSIDE the scanned paths are not called stale
    env = dict(
        os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=str(REPO)
    )
    r = subprocess.run(
        [sys.executable, "-m", "inferd_tpu.analysis", "check",
         "../inferd_tpu/core/batch.py",
         "--baseline", "../analysis-baseline.json"],
        capture_output=True, text=True, env=env, cwd=str(REPO / "tests"),
    )
    assert r.returncode == 0, r.stdout + r.stderr
    assert "0 finding(s)" in r.stderr and "4 baselined" in r.stderr
    assert "stale" not in r.stderr


def test_cli_rules_subset_does_not_misreport_stale_baseline():
    # scanning with --rules J006 must not flag the J003 baseline entries
    # as stale (they never got a chance to match this run)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    r = subprocess.run(
        [sys.executable, "-m", "inferd_tpu.analysis", "check",
         "inferd_tpu/", "tests/", "bench.py", "__graft_entry__.py",
         "--baseline", "analysis-baseline.json", "--rules", "J006",
         "--warn-unused-baseline"],
        capture_output=True, text=True, env=env, cwd=str(REPO),
    )
    assert r.returncode == 0, r.stdout + r.stderr
    assert "stale" not in r.stderr


# ------------------------------------------------------ chip_probe fixes


def _reimport_chip_probe(monkeypatch, argv):
    import importlib

    import inferd_tpu.utils.platform as plat

    calls = []
    monkeypatch.setattr(plat, "force_platform", lambda d: calls.append(d))
    monkeypatch.setattr(sys, "argv", argv)
    sys.modules.pop("inferd_tpu.tools.chip_probe", None)
    importlib.import_module("inferd_tpu.tools.chip_probe")
    sys.modules.pop("inferd_tpu.tools.chip_probe", None)
    return calls


def test_chip_probe_preparse_handles_eq_form(monkeypatch):
    # regression: `--device=cpu` used to slip through the pre-parse and
    # silently no-op, leaving the backend unpinned before jax import
    calls = _reimport_chip_probe(
        monkeypatch, ["chip_probe", "--device=cpu", "--small"]
    )
    assert calls == ["cpu"]


def test_chip_probe_preparse_space_and_auto(monkeypatch):
    assert _reimport_chip_probe(
        monkeypatch, ["chip_probe", "--device", "cpu"]
    ) == ["cpu"]
    assert _reimport_chip_probe(
        monkeypatch, ["chip_probe", "--device=auto"]
    ) == [None]
    assert _reimport_chip_probe(monkeypatch, ["chip_probe"]) == []


def test_chip_probe_layers_step_kv_write_survives_dce():
    """regression for the layers_ms undercount: with the KV buffers
    returned-and-dropped, XLA DCE'd the cache write out of the scan; with
    them threaded through the carry, the compiled loop must keep the
    update (dynamic-update-slice) alive."""
    import jax
    import jax.numpy as jnp

    from inferd_tpu.config import get_config
    from inferd_tpu.core.cache import KVCache
    from inferd_tpu.models import qwen3

    cfg = get_config("tiny")
    params = qwen3.init_params(cfg, jax.random.PRNGKey(0))
    cache = KVCache.create(cfg, cfg.num_layers, 1, 64, ring=False)
    pos = jnp.full((1, 1), 3, jnp.int32)
    h0 = jnp.ones((1, 1, cfg.hidden_size), cfg.jnp_dtype)

    def fwd(h, k, v):
        return qwen3.forward_layers(
            params["layers"], cfg, h, pos, k, v, cache_write_pos=jnp.int32(3)
        )

    @jax.jit
    def dead(x):  # the pre-fix shape: KV returned and dropped
        def body(c, _):
            out, _, _ = fwd(c, cache.k, cache.v)
            return out, None

        return jax.lax.scan(body, x, None, length=2)[0]

    @jax.jit
    def live(x):  # the fixed shape: KV threaded through the carry
        def body(c, _):
            h, k, v = c
            return fwd(h, k, v), None

        return jax.lax.scan(body, x, None, length=2)[0]

    def dus_count(fn, arg):
        txt = fn.lower(arg).compile().as_text()
        return txt.count("dynamic-update-slice")

    n_live = dus_count(live, (h0, cache.k, cache.v))
    n_dead = dus_count(dead, h0)
    assert n_live > 0, "carried KV write was eliminated"
    assert n_live > n_dead, (
        f"expected the dropped-KV scan to lose cache writes to DCE "
        f"(live={n_live}, dead={n_dead})"
    )


# ------------------------------------------------------------ sanitizers


def test_retrace_guard_catches_shape_unstable_loop():
    import jax
    import jax.numpy as jnp

    @jax.jit
    def step(x):
        return x * 2

    step(jnp.ones((4,)))  # warm
    with pytest.raises(RetraceError, match="step"):
        with retrace_guard_cm() as g:
            g.register(step)
            for n in range(1, 4):  # deliberately shape-unstable
                step(jnp.ones((n,)))


def test_retrace_guard_stable_loop_passes():
    import jax
    import jax.numpy as jnp

    @jax.jit
    def step(x):
        return x + 1

    step(jnp.ones((4,)))
    with retrace_guard_cm() as g:
        g.register(step)
        for _ in range(5):
            step(jnp.ones((4,)))
    assert g.traces("step") == 0


def test_retrace_guard_instrument_path():
    import jax
    import jax.numpy as jnp

    g = RetraceGuard()  # default budget 0 RE-traces
    f = jax.jit(g.instrument(lambda x: x + 1, name="inc"))
    f(jnp.ones((2,)))  # initial compile is free, not a re-trace
    f(jnp.ones((2,)))  # same shape: no retrace
    assert g.traces("inc") == 0  # same convention as the register() path
    g.check()
    f(jnp.ones((3,)))  # retrace
    with pytest.raises(RetraceError, match="inc"):
        g.check()


def test_retrace_guard_fixture(retrace_guard):
    import jax
    import jax.numpy as jnp

    @jax.jit
    def step(x):
        return x - 1

    step(jnp.ones((2,)))
    retrace_guard.register(step)
    step(jnp.ones((2,)))  # fixture's teardown check must pass


def test_nan_guard():
    import jax.numpy as jnp

    @nan_guard
    def bad(x):
        return {"h": x, "lp": jnp.log(x - 1.0)}  # log(0) = -inf

    @nan_guard
    def good(x):
        return {"h": x * 2, "ids": jnp.ones((2,), jnp.int32)}

    good(jnp.ones((2,)))
    with pytest.raises(NanError, match="lp"):
        bad(jnp.ones((2,)))


# ------------------------------------------- J007 lock-order (project)


def test_j007_inversion_fires():
    # the seeded inversion fixture: mu (rank 2) held while taking the
    # device lock (rank 1) — the static half of the double catch (the
    # dynamic half is tests/test_lockwatch.py's live WatchedLock raise)
    src = (
        "class Exec:\n"
        "    def step(self):\n"
        "        with self._mu:\n"
        "            with self._dev_lock:\n"
        "                pass\n"
    )
    out = findings(src, "J007")
    assert len(out) == 1
    assert "'dev' while holding 'mu'" in out[0].message


def test_j007_canonical_order_passes():
    src = (
        "class Exec:\n"
        "    def step(self):\n"
        "        with self._dev_lock:\n"
        "            with self._mu:\n"
        "                pass\n"
    )
    assert findings(src, "J007") == []


def test_j007_blocking_acquire_edge_and_bounded_exemption():
    fires = (
        "class Exec:\n"
        "    def a(self):\n"
        "        with self._mu:\n"
        "            self._dev_lock.acquire()\n"
    )
    assert len(findings(fires, "J007")) == 1
    bounded = (
        "class Exec:\n"
        "    def a(self):\n"
        "        with self._mu:\n"
        "            if not self._dev_lock.acquire(blocking=False):\n"
        "                return\n"
        "    def b(self):\n"
        "        with self._mu:\n"
        "            self._dev_lock.acquire(timeout=0.1)\n"
    )
    assert findings(bounded, "J007") == []


def test_j007_reverse_nesting_names_the_deadlock_pair():
    src = (
        "class Exec:\n"
        "    def a(self):\n"
        "        with self._dev_lock:\n"
        "            with self._mu:\n"
        "                pass\n"
        "    def b(self):\n"
        "        with self._mu:\n"
        "            with self._dev_lock:\n"
        "                pass\n"
    )
    out = findings(src, "J007")
    assert len(out) == 1
    assert "reverse nesting exists" in out[0].message
    assert "deadlock" in out[0].message


def test_j007_class_qualified_lock_names():
    # StandbyStore._mu is 'repl' (rank 4) — under the device lock (rank
    # 1) that is canonical, NOT an inversion of the executor 'mu'
    ok = (
        "class StandbyStore:\n"
        "    def apply(self):\n"
        "        with self._dev_lock:\n"
        "            with self._mu:\n"
        "                pass\n"
    )
    assert findings(ok, "J007") == []
    # WindowedBatcher._mu is 'window' (rank 5): taking the device lock
    # under it contradicts the canonical order
    bad = (
        "class WindowedBatcher:\n"
        "    def flush(self):\n"
        "        with self._mu:\n"
        "            with self._dev_lock:\n"
        "                pass\n"
    )
    out = findings(bad, "J007")
    assert len(out) == 1 and "'window'" in out[0].message


def test_j007_multi_item_with_is_sequential():
    src = (
        "class Exec:\n"
        "    def a(self):\n"
        "        with self._mu, self._dev_lock:\n"
        "            pass\n"
    )
    assert len(findings(src, "J007")) == 1
    ok = (
        "class Exec:\n"
        "    def a(self):\n"
        "        with self._dev_lock, self._mu:\n"
        "            pass\n"
    )
    assert findings(ok, "J007") == []


# ---------------------------------------- J008 host work under dev lock


def test_j008_host_io_under_device_lock():
    src = (
        "import time\n"
        "class Exec:\n"
        "    def step(self):\n"
        "        with self._dev_lock:\n"
        "            time.sleep(0.01)\n"
        "            open('/tmp/x').read()\n"
    )
    out = findings(src, "J008")
    assert len(out) == 2
    assert any("time.sleep" in f.message for f in out)
    assert any("open" in f.message for f in out)


def test_j008_negative_boundary_fetch_and_outside():
    # np.asarray under the device lock is the DESIGNED boundary
    # transfer; host I/O outside the lock is fine
    src = (
        "import time\n"
        "import numpy as np\n"
        "class Exec:\n"
        "    def step(self):\n"
        "        with self._dev_lock:\n"
        "            out = np.asarray(self.logits)\n"
        "        time.sleep(0.01)\n"
        "        return out\n"
    )
    assert findings(src, "J008") == []


def test_j008_negative_other_lock():
    src = (
        "import time\n"
        "class Exec:\n"
        "    def step(self):\n"
        "        with self._mu:\n"
        "            time.sleep(0.01)\n"
    )
    assert findings(src, "J008") == []


# ------------------------------------------- J009 blocking in async def


def test_j009_sync_lock_in_async_handler():
    # the seeded blocking-async fixture: static half of the double
    # catch (the dynamic half is the LoopStallDetector live test)
    src = (
        "class Node:\n"
        "    async def handle(self, request):\n"
        "        with self._mu:\n"
        "            return self.sessions.copy()\n"
    )
    out = findings(src, "J009")
    assert len(out) == 1
    assert "sync `with` on threading lock 'mu'" in out[0].message


def test_j009_unbounded_acquire_and_inline_dispatch():
    src = (
        "class Node:\n"
        "    async def handle(self, request):\n"
        "        self._mu.acquire()\n"
        "        return self.executor.process(request)\n"
    )
    out = findings(src, "J009")
    assert len(out) == 2
    assert any("unbounded `.acquire()`" in f.message for f in out)
    assert any("dispatches jit work inline" in f.message for f in out)


def test_j009_negative_bounded_and_executor_hop():
    src = (
        "import asyncio\n"
        "class Node:\n"
        "    async def handle(self, request):\n"
        "        if not self._mu.acquire(blocking=False):\n"
        "            return None\n"
        "        self._mu.release()\n"
        "        loop = asyncio.get_running_loop()\n"
        "        return await loop.run_in_executor(\n"
        "            None, self.executor.process, request\n"
        "        )\n"
    )
    assert findings(src, "J009") == []


def test_j009_negative_sync_def_untouched():
    src = (
        "class Node:\n"
        "    def snapshot(self):\n"
        "        with self._mu:\n"
        "            return dict(self.sessions)\n"
    )
    assert findings(src, "J009") == []


# ------------------------------------------ J010 cross-thread registries


def test_j010_direct_metric_dict_write():
    src = (
        "def reset(m):\n"
        "    m.counters['c'] = 0.0\n"
        "    m.gauges['g'] += 1\n"
    )
    out = findings(src, "J010")
    assert len(out) == 2
    assert all("Metrics._lock" in f.message for f in out)


def test_j010_negative_inside_metrics_and_api():
    src = (
        "class Metrics:\n"
        "    def inc(self, name, by=1.0):\n"
        "        with self._lock:\n"
        "            self.counters[name] = self.counters.get(name, 0) + by\n"
        "def use(m):\n"
        "    m.inc('c')\n"
    )
    assert findings(src, "J010") == []


def test_j010_ring_buffer_mutation_outside_owner():
    src = (
        "class Sweeper:\n"
        "    def drop(self, journal):\n"
        "        journal._buf.clear()\n"
    )
    out = findings(src, "J010")
    assert len(out) == 1 and "_buf" in out[0].message
    owner = (
        "class EventJournal:\n"
        "    def emit(self, etype):\n"
        "        with self._lock:\n"
        "            self._buf.append(etype)\n"
    )
    assert findings(owner, "J010") == []


# --------------------------------------------- J011 stale disables


def test_j011_stale_disable_fires():
    src = "x = 1  # jaxlint: disable=J005 -- excused a sleep long gone\n"
    out = findings(src, "J011")
    assert len(out) == 1
    assert "suppresses nothing" in out[0].message


def test_j011_live_disable_passes():
    # the directive still suppresses a real J006 finding -> not stale
    src = (
        "import jax\n"
        "def pick():\n"
        "    return jax.default_backend() == 'tpu'  "
        "# jaxlint: disable=J006 -- fixture\n"
    )
    assert findings(src, "J006") == []
    assert findings(src, "J011") == []


def test_j011_audit_skips_inactive_rules():
    # a --rules run that never evaluated J005 cannot judge its disables
    src = "x = 1  # jaxlint: disable=J005 -- maybe still needed\n"
    from inferd_tpu.analysis.rules import ALL_RULES

    subset = [r for r in ALL_RULES if r.id in ("J006", "J011")]
    assert check_source(src, rules=subset) == []


# ------------------------------------------- parallel scan (--jobs)


def test_jobs_parallel_matches_serial():
    paths = [
        str(REPO / "inferd_tpu" / "analysis"),
        str(REPO / "inferd_tpu" / "utils"),
    ]
    serial = check_paths(paths, rel_to=str(REPO))
    parallel = check_paths(paths, rel_to=str(REPO), jobs=2)
    assert [f.fingerprint() for f in serial] == [
        f.fingerprint() for f in parallel
    ]


def test_step0_wall_time_budget():
    """run.sh step 0's scan must stay under its 30 s budget — the gate
    only stays HARD while it is cheap enough that nobody routes around
    it."""
    import time as _time

    t0 = _time.perf_counter()
    check_paths(
        [
            str(REPO / "inferd_tpu"),
            str(REPO / "tests"),
            str(REPO / "bench.py"),
            str(REPO / "__graft_entry__.py"),
        ],
        rel_to=str(REPO),
        jobs=os.cpu_count() or 1,
    )
    elapsed = _time.perf_counter() - t0
    assert elapsed < 30.0, f"step-0 scan took {elapsed:.1f}s (budget 30s)"


# --------------------------------------------- contracts drift lint


def _contracts_slice(tmp_path, code, doc, allow=None):
    (tmp_path / "inferd_tpu").mkdir(exist_ok=True)
    (tmp_path / "inferd_tpu" / "mod.py").write_text(code)
    (tmp_path / "docs").mkdir(exist_ok=True)
    (tmp_path / "docs" / "OBSERVABILITY.md").write_text(doc)
    if allow is not None:
        (tmp_path / "analysis-contracts.json").write_text(json.dumps(allow))
    from inferd_tpu.analysis.contracts import run_contracts

    return run_contracts(str(tmp_path))


CONTRACTS_DOC = (
    "# obs\n\n"
    "| event | emitted by | meaning |\n"
    "|-------|-----------|---------|\n"
    "| `thing.start` | mod | it began |\n"
    "| `thing.ghost` | mod | never actually emitted |\n\n"
    "| key | meaning |\n"
    "|-----|---------|\n"
    "| `load` | inflight count |\n\n"
    "The `requests` counter counts requests.\n"
)

CONTRACTS_CODE = (
    "class N:\n"
    "    def go(self):\n"
    "        self.journal.emit('thing.start', x=1)\n"
    "        self.journal.emit('thing.new')\n"
    "        self.metrics.inc('requests')\n"
    "        self.dht.announce({'load': 1, 'mystery': 2})\n"
)


def test_contracts_distinct_drift_codes(tmp_path):
    found, code, _allow = _contracts_slice(
        tmp_path, CONTRACTS_CODE, CONTRACTS_DOC
    )
    by_code = {f.code: f.name for f in found}
    # undocumented emitted event / dead doc row / ungated gossip key
    assert by_code.get("C001") == "thing.new"
    assert by_code.get("C002") == "thing.ghost"
    assert by_code.get("C003") == "mystery"
    assert "C005" not in by_code  # `requests` is doc-tokened
    assert code.events["thing.start"][0] == "mod.py"


def test_contracts_allowlist_needs_reason(tmp_path):
    reasoned = {
        "version": 1,
        "allow": [
            {"code": "C003", "name": "mystery", "reason": "rollout gap"},
            {"code": "C001", "name": "thing.new", "reason": "doc follows"},
            {"code": "C002", "name": "thing.ghost", "reason": "dynamic"},
            {"code": "C004", "name": "never_used", "reason": "stale entry"},
        ],
    }
    found, _code, allow = _contracts_slice(
        tmp_path, CONTRACTS_CODE, CONTRACTS_DOC, allow=reasoned
    )
    assert found == []
    # the C004 entry matched nothing: reported stale, not silently kept
    assert [e["name"] for e in allow.unused()] == ["never_used"]


def test_contracts_reasonless_allowlist_entry_never_suppresses(tmp_path):
    bare = {
        "version": 1,
        "allow": [{"code": "C003", "name": "mystery", "reason": "  "}],
    }
    found, _code, _allow = _contracts_slice(
        tmp_path, CONTRACTS_CODE, CONTRACTS_DOC, allow=bare
    )
    assert any(f.code == "C003" and f.name == "mystery" for f in found)


def test_contracts_metric_families_and_wildcards(tmp_path):
    code = (
        "class N:\n"
        "    def go(self):\n"
        "        self.metrics.observe('hop.wire_ms', 1.0)\n"
        "        self.metrics.set_gauge('repl.lag_tokens', 2.0)\n"
        "        self.metrics.inc('orphan.series')\n"
    )
    doc = (
        "# obs\n\n"
        "| event | emitted by | meaning |\n"
        "|-------|-----------|---------|\n\n"
        "| key | meaning |\n"
        "|-----|---------|\n\n"
        "* `inferd_hop_wire_ms` histogram\n"
        "* `inferd_repl_*` — the replication family\n"
    )
    found, _code, _allow = _contracts_slice(tmp_path, code, doc)
    names = {(f.code, f.name) for f in found}
    assert ("C005", "orphan.series") in names
    assert not any(n == "hop.wire_ms" for _c, n in names)
    assert not any(n == "repl.lag_tokens" for _c, n in names)


def test_contracts_repo_self_scan_clean():
    """The CI gate's second half: the real tree's emitted vocabulary
    matches docs/OBSERVABILITY.md (modulo the reasoned allowlist)."""
    from inferd_tpu.analysis.contracts import run_contracts

    found, _code, allow = run_contracts(str(REPO))
    assert found == [], "\n".join(f.render() for f in found)
    assert allow.unused() == [], allow.unused()


def test_contracts_cli_exit_codes(tmp_path):
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=str(REPO))
    (tmp_path / "inferd_tpu").mkdir()
    (tmp_path / "inferd_tpu" / "m.py").write_text(
        "def f(j):\n    j.emit('lonely.event')\n"
    )
    (tmp_path / "docs").mkdir()
    (tmp_path / "docs" / "OBSERVABILITY.md").write_text(
        "| event | meaning |\n|---|---|\n"
    )
    r = subprocess.run(
        [sys.executable, "-m", "inferd_tpu.analysis", "contracts",
         "--root", str(tmp_path)],
        capture_output=True, text=True, env=env, cwd=str(REPO),
    )
    assert r.returncode == 1 and "C001" in r.stdout
    (tmp_path / "docs" / "OBSERVABILITY.md").write_text(
        "| event | meaning |\n|---|---|\n| `lonely.event` | doc |\n"
    )
    r = subprocess.run(
        [sys.executable, "-m", "inferd_tpu.analysis", "contracts",
         "--root", str(tmp_path)],
        capture_output=True, text=True, env=env, cwd=str(REPO),
    )
    assert r.returncode == 0, r.stdout + r.stderr
    r = subprocess.run(
        [sys.executable, "-m", "inferd_tpu.analysis", "contracts",
         "--root", str(tmp_path / "nowhere")],
        capture_output=True, text=True, env=env, cwd=str(REPO),
    )
    assert r.returncode == 2
