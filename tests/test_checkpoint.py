# jaxlint: file-disable=J003 -- test code: loops here sync per-iteration to ASSERT on values; they are verification loops, not serving hot paths
"""Training checkpoint/resume tests: atomic roundtrip, retention GC, and —
the property that matters — a restored run continues BIT-IDENTICALLY to the
uninterrupted one on a sharded mesh."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from inferd_tpu.config import TINY
from inferd_tpu.models import qwen3
from inferd_tpu.parallel import checkpoint as ckpt
from inferd_tpu.parallel import mesh as meshlib
from inferd_tpu.parallel.train import make_train_step
from conftest import requires_native_shard_map


def test_roundtrip_and_meta(tmp_path):
    state = {"w": np.arange(6, dtype=np.float32).reshape(2, 3), "b": np.float32(1.5)}
    path = ckpt.save(str(tmp_path), state, step=7, meta={"lr": 0.1})
    assert os.path.basename(path) == "step_000000007.msgpack"
    got, meta = ckpt.restore(str(tmp_path))
    assert meta["step"] == 7 and meta["lr"] == 0.1
    np.testing.assert_array_equal(got["w"], state["w"])
    np.testing.assert_array_equal(got["b"], state["b"])


def test_latest_and_gc(tmp_path):
    d = str(tmp_path)
    assert ckpt.latest_step(d) is None
    for s in [1, 5, 3, 9, 12]:
        ckpt.save(d, {"x": np.zeros(1)}, step=s, keep=3)
    assert ckpt.latest_step(d) == 12
    kept = sorted(f for f in os.listdir(d) if f.endswith(".msgpack"))
    assert kept == ["step_000000005.msgpack", "step_000000009.msgpack", "step_000000012.msgpack"]
    # restore a specific retained step
    _, meta = ckpt.restore(d, step=9)
    assert meta["step"] == 9


def test_no_tmp_litter_on_success(tmp_path):
    ckpt.save(str(tmp_path), {"x": np.zeros(4)}, step=1)
    assert not [f for f in os.listdir(tmp_path) if f.endswith(".tmp")]


def test_restore_missing_raises(tmp_path):
    with pytest.raises(FileNotFoundError):
        ckpt.restore(str(tmp_path / "empty"))


@requires_native_shard_map
def test_sharded_resume_continues_identically(tmp_path, devices8):
    """Train 4 steps straight vs train 2 + checkpoint + restore-onto-mesh +
    train 2: final params must match exactly."""
    plan = meshlib.MeshPlan(dp=2, tp=2)
    mesh = meshlib.make_mesh(plan, devices8[:4])
    meshlib.check_divisibility(TINY, plan)
    step = make_train_step(TINY, mesh, plan, learning_rate=1e-2)

    params0 = qwen3.init_params(TINY, jax.random.PRNGKey(0))
    data = jax.random.randint(
        jax.random.PRNGKey(3), (2, 2 * plan.dp, 8 + 1), 0, TINY.vocab_size, dtype=jnp.int32
    )
    tokens, targets = data[..., :-1], data[..., 1:]

    # uninterrupted
    p = params0
    for _ in range(4):
        p, _ = step(p, tokens, targets)
    straight = jax.device_get(p)

    # interrupted at step 2
    p = params0
    for _ in range(2):
        p, _ = step(p, tokens, targets)
    ckpt.save(str(tmp_path), p, step=2)
    del p

    from jax.sharding import NamedSharding

    shardings = jax.tree.map(
        lambda spec: NamedSharding(mesh, spec),
        step.param_specs,
        is_leaf=lambda x: isinstance(x, jax.sharding.PartitionSpec),
    )
    restored, meta = ckpt.restore(str(tmp_path), shardings=shardings)
    assert meta["step"] == 2
    for _ in range(2):
        restored, _ = step(restored, tokens, targets)
    resumed = jax.device_get(restored)

    flat_a, _ = jax.tree.flatten(straight)
    flat_b, _ = jax.tree.flatten(resumed)
    for a, b in zip(flat_a, flat_b):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_meta_step_key_is_reserved(tmp_path):
    """A caller-supplied meta 'step' must not override the real step."""
    ckpt.save(str(tmp_path), {"x": np.zeros(1)}, step=5, meta={"step": 99, "lr": 0.1})
    _, meta = ckpt.restore(str(tmp_path))
    assert meta["step"] == 5 and meta["lr"] == 0.1


@requires_native_shard_map
def test_adam_resume_bit_identity(tmp_path, devices8):
    """Adam training: 4 steps straight vs 2 + snapshot(params+moments+count)
    + restore-with-target-onto-mesh + 2 — params AND moments must match
    bit for bit (VERDICT r1 item 7; ADVICE r1 restore-target fix)."""
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as PSpec

    from inferd_tpu.parallel.train import TrainState

    plan = meshlib.MeshPlan(dp=2, tp=2)
    mesh = meshlib.make_mesh(plan, devices8[:4])
    step = make_train_step(TINY, mesh, plan, learning_rate=1e-3, optimizer="adam")

    params0 = qwen3.init_params(TINY, jax.random.PRNGKey(0))
    data = jax.random.randint(
        jax.random.PRNGKey(3), (2, 2 * plan.dp, 8 + 1), 0, TINY.vocab_size, dtype=jnp.int32
    )
    tokens, targets = data[..., :-1], data[..., 1:]

    s = step.init_state(params0)
    for _ in range(4):
        s, _ = step(s, tokens, targets)
    straight = jax.device_get(s)

    s = step.init_state(params0)
    for _ in range(2):
        s, _ = step(s, tokens, targets)
    ckpt.save(str(tmp_path), s, step=2)
    del s

    shardings = jax.tree.map(
        lambda spec: NamedSharding(mesh, spec),
        step.state_specs(),
        is_leaf=lambda x: isinstance(x, PSpec),
    )
    restored, meta = ckpt.restore(
        str(tmp_path), target=step.init_state(params0), shardings=shardings
    )
    assert meta["step"] == 2
    assert isinstance(restored, TrainState) and int(restored.count) == 2
    for _ in range(2):
        restored, _ = step(restored, tokens, targets)
    resumed = jax.device_get(restored)

    for a, b in zip(jax.tree.leaves(straight), jax.tree.leaves(resumed)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@requires_native_shard_map
def test_adam_loss_decreases(devices8):
    plan = meshlib.MeshPlan(pp=2)
    mesh = meshlib.make_mesh(plan, devices8[:2])
    step = make_train_step(TINY, mesh, plan, learning_rate=3e-3, optimizer="adam")
    s = step.init_state(qwen3.init_params(TINY, jax.random.PRNGKey(0)))
    data = jax.random.randint(
        jax.random.PRNGKey(5), (2, 2, 8 + 1), 0, TINY.vocab_size, dtype=jnp.int32
    )
    tokens, targets = data[..., :-1], data[..., 1:]
    losses = []
    for _ in range(5):
        s, loss = step(s, tokens, targets)
        losses.append(float(loss))
    assert losses[-1] < losses[0] and all(np.isfinite(losses)), losses


@requires_native_shard_map
def test_adam_requires_state():
    import pytest as _pytest

    plan = meshlib.MeshPlan()
    mesh = meshlib.make_mesh(plan, jax.devices()[:1])
    step = make_train_step(TINY, mesh, plan, optimizer="adam")
    with _pytest.raises(TypeError, match="needs optimizer state"):
        step(qwen3.init_params(TINY, jax.random.PRNGKey(0)), None, None)
