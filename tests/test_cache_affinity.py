"""Memory-plane observability (ISSUE 13): prefix-cache telemetry export,
gossiped prefix digests, and the bounded cache-affinity routing bonus.

The contract under test, end to end: BlockPool counters flow into
/metrics + windowed series + fleet SLIs; each paged replica gossips a
size-bounded `pfx` digest of its hot prefix index; entry routers score
prompts against the digests and grant a BONUS that composes with — and
can never outweigh — the admission watermark, draining exclusion, and
outlier penalty (the acceptance pin: a shedding or draining digest
holder LOSES the ranked pick to a cache-cold healthy peer)."""

import asyncio
import json
import os

import numpy as np
import pytest

from inferd_tpu.config import PRESETS
from inferd_tpu.control import dstar as dstarlib
from inferd_tpu.control import path_finder as pflib
from inferd_tpu.core import prefix as prefixlib
from inferd_tpu.core.cache import BlockPool
from inferd_tpu.obs import canary as canarylib
from inferd_tpu.obs import devtel as devtellib
from inferd_tpu.obs import events as eventslib
from inferd_tpu.obs import export as obs_export
from inferd_tpu.obs import fleet as fleetlib
from inferd_tpu.obs import health as healthlib
from inferd_tpu.obs import tsdb as tsdblib
from inferd_tpu.utils.metrics import Metrics

TINY = PRESETS["tiny"]
FLEET_FIXTURE = os.path.join(os.path.dirname(__file__), "data", "fleet")
SIM_DATA = os.path.join(os.path.dirname(__file__), "data", "sim")

PROMPT = list(range(100))


def _digest_for(ids, bs=32):
    return prefixlib.make_digest(prefixlib.block_keys(ids, bs), bs)


# ---------------------------------------------------------------------------
# core.prefix: digest + probe
# ---------------------------------------------------------------------------


def test_digest_and_probe_depth():
    probe = prefixlib.AffinityProbe(PROMPT)
    full = _digest_for(PROMPT)
    assert full["bs"] == 32 and len(full["k"]) == len(PROMPT) // 32
    assert probe.depth_frac({"pfx": full}) == 1.0
    # a shallower holder scores a proportional fraction
    one = {"pfx": {"bs": 32, "k": full["k"][:1]}}
    assert probe.depth_frac(one) == pytest.approx(1 / 3)
    # chained keys: the DEEPEST match names the coverage even when
    # shallower keys are missing from the digest
    deep_only = {"pfx": {"bs": 32, "k": full["k"][-1:]}}
    assert probe.depth_frac(deep_only) == 1.0
    # a different prompt's digest never matches (chained identity)
    other = _digest_for([7] + PROMPT[1:])
    assert probe.depth_frac({"pfx": other}) == 0.0


def test_probe_rederives_per_block_size_and_tolerates_garbage():
    probe = prefixlib.AffinityProbe(PROMPT)
    d16 = _digest_for(PROMPT, bs=16)
    assert probe.depth_frac({"pfx": d16}) == 1.0  # re-keyed at bs=16
    # memoized per block size: the second call reuses the chain
    assert probe.keys_for(16) is probe.keys_for(16)
    for garbage in (
        {}, {"pfx": None}, {"pfx": []}, {"pfx": {"bs": 0, "k": ["x"]}},
        {"pfx": {"bs": "?", "k": ["x"]}}, {"pfx": {"bs": 16, "k": []}},
        {"pfx": {"bs": 16, "k": [1, 2]}}, {"pfx": {"bs": 16}},
    ):
        assert probe.depth_frac(garbage) == 0.0
    # prompts shorter than one block have no digestible identity
    assert prefixlib.AffinityProbe([1, 2]).depth_frac({"pfx": d16}) == 0.0


def test_make_digest_is_size_bounded():
    ids = list(range(32 * (prefixlib.DIGEST_MAX_KEYS + 40)))
    d = _digest_for(ids)
    assert len(d["k"]) == prefixlib.DIGEST_MAX_KEYS
    assert all(len(k) == 2 * prefixlib.DIGEST_KEY_BYTES for k in d["k"])


# ---------------------------------------------------------------------------
# BlockPool: digest selection + eviction ages
# ---------------------------------------------------------------------------


def _pool(**kw):
    kw.setdefault("lanes", 2)
    kw.setdefault("max_len", 64)
    kw.setdefault("block_size", 16)
    return BlockPool(TINY, TINY.num_layers, **kw)


def test_digest_keys_pinned_first_then_mru():
    pool = _pool(lanes=3, max_len=96, num_blocks=64)
    a = prefixlib.block_keys(list(range(32)), 16)
    b = prefixlib.block_keys(list(range(100, 132)), 16)
    c = prefixlib.block_keys(list(range(200, 232)), 16)
    for lane, keys in enumerate((a, b, c)):
        pool.ensure(lane, 32, owner=f"lane{lane}")
        pool.register_prefix(lane, keys)
    pool.pin(b)
    out = pool.digest_keys()
    assert out[:2] == b  # pinned entries lead
    assert set(out) == set(a + b + c)
    # MRU next: touch `a` (a hit), then cap the budget at 4 — the two
    # pinned keys plus the two most-recently-touched (a's)
    pool.release_lane(0)
    pool.map_prefix(0, a)
    capped = pool.digest_keys(limit=4)
    assert capped[:2] == b and set(capped[2:]) == set(a)


def test_eviction_age_hook_and_counters():
    clock = [100.0]
    pool = _pool(lanes=2, max_len=64, num_blocks=9, clock=lambda: clock[0])
    evicted = []
    pool.on_evict = lambda key, age_s: evicted.append((key, age_s))
    keys = prefixlib.block_keys(list(range(32)), 16)
    pool.ensure(0, 32, owner="s0")
    pool.register_prefix(0, keys)
    pool.release_lane(0)  # index alone holds the 2 blocks now
    clock[0] = 130.0
    # 8 usable blocks, 2 held by the index: a 7-block demand forces
    # evictions of the idle entries, stamped with their LRU age
    pool.ensure(0, 64, owner="s1")
    pool.ensure(1, 48, owner="s2")
    assert pool.prefix_evictions >= 1 and evicted
    assert all(age == pytest.approx(30.0) for _k, age in evicted)
    assert [k for k, _ in evicted] == keys[: len(evicted)]
    # a raising hook must never break allocation
    pool2 = _pool(lanes=2, max_len=64, num_blocks=9)
    pool2.on_evict = lambda *_a: (_ for _ in ()).throw(RuntimeError("x"))
    pool2.ensure(0, 32, owner="s0")
    pool2.register_prefix(0, prefixlib.block_keys(list(range(32)), 16))
    pool2.release_lane(0)
    pool2.ensure(0, 64, owner="s1")
    pool2.ensure(1, 48, owner="s2")  # would exhaust without eviction
    assert pool2.prefix_evictions >= 1


# ---------------------------------------------------------------------------
# routers: the bounded bonus and its composition contract
# ---------------------------------------------------------------------------


def _hot(**kw):
    return {"load": 1, "cap": 8, "pfx": _digest_for(PROMPT), **kw}


def _cold(**kw):
    return {"load": 1, "cap": 8, **kw}


def test_ranked_pick_prefers_digest_holder_at_equal_load():
    probe = prefixlib.AffinityProbe(PROMPT)
    nid, _ = pflib.min_load_node(
        {"cold": _cold(), "hot": _hot()}, affinity=probe
    )
    assert nid == "hot"
    # without a probe the ordering is the classic min-load (unchanged)
    ranked = pflib.ranked_nodes({"cold": _cold(), "hot": _hot()})
    assert [n for n, _ in ranked] == ["cold", "hot"]  # tie -> sort order


def test_bonus_is_bounded_by_half_a_capacity():
    """The bonus moves a pick only within CACHE_AFFINITY_BONUS load-ratio
    units: a full-depth holder more than 0.5 capacities busier loses."""
    probe = prefixlib.AffinityProbe(PROMPT)
    barely = _hot(load=4)  # +0.375 ratio vs cold: inside the bonus
    nid, _ = pflib.min_load_node(
        {"cold": _cold(load=1), "busy_hot": barely}, affinity=probe
    )
    assert nid == "busy_hot"
    over = _hot(load=6)  # +0.625 ratio: beyond the bonus
    nid, _ = pflib.min_load_node(
        {"cold": _cold(load=1), "busy_hot": over}, affinity=probe
    )
    assert nid == "cold"


def test_cache_hit_never_outweighs_overload():
    """ACCEPTANCE: an admission-shedding or draining replica loses the
    ranked pick to a cache-cold healthy peer, whatever its digest says."""
    probe = prefixlib.AffinityProbe(PROMPT)
    for unhealthy in (
        _hot(shed=1),                     # explicit watermark flag
        _hot(kvfree=0.01),                # old peer, kvfree floor
        _hot(draining=1),                 # drain = exclusion
        _hot(outlier=1),                  # outlier penalty >> bonus
    ):
        nid, _ = pflib.min_load_node(
            {"cold": _cold(), "sick_hot": unhealthy}, affinity=probe
        )
        assert nid == "cold", unhealthy
    # healthy kvfree above the floor still earns the bonus
    nid, _ = pflib.min_load_node(
        {"cold": _cold(), "hot": _hot(kvfree=0.5)}, affinity=probe
    )
    assert nid == "hot"


def test_node_cost_bonus_penalties_and_positivity():
    probe = prefixlib.AffinityProbe(PROMPT)
    base = dstarlib.node_cost(_cold())
    assert dstarlib.node_cost(_cold(), affinity=probe) == base
    bonus = base - dstarlib.node_cost(_hot(), affinity=probe)
    assert bonus == pytest.approx(canarylib.CACHE_AFFINITY_BONUS)
    # strict positivity survives the discount (D*-Lite admissibility)
    assert dstarlib.node_cost({"load": 0, "cap": 8, **_hot()},
                              affinity=probe) > 0
    # shed -> penalty instead of bonus; draining still exclusion-grade
    assert dstarlib.node_cost(_hot(shed=1), affinity=probe) == (
        pytest.approx(base + canarylib.ADMISSION_PENALTY)
    )
    assert dstarlib.node_cost(_hot(draining=1), affinity=probe) >= 1e6
    # no affinity argument -> byte-for-byte the PR 12 cost model
    assert dstarlib.node_cost(_hot(shed=1)) == base


class _StubDHT:
    def __init__(self, snapshot):
        self.snapshot = snapshot

    def get_all(self, _n):
        return {s: dict(m) for s, m in self.snapshot.items()}

    def get_stage(self, s):
        return dict(self.snapshot.get(s, {}))


def test_find_best_chain_affinity_rerank_entry_stage_only():
    probe = prefixlib.AffinityProbe(PROMPT)
    hot_inner = dict(_hot(), host="h3", port=4)  # inner stage holder: ignored
    snapshot = {
        0: {"a": dict(_cold(), host="h1", port=1),
            "b": dict(_hot(), host="h2", port=2)},
        1: {"c": dict(_cold(), host="h3", port=3), "d": hot_inner},
    }
    pf = pflib.PathFinder(_StubDHT(snapshot), 2)
    plain = pf.find_best_chain(0)
    assert plain[0][0] == "a"  # tie -> planner's pick, no probe
    routed = pf.find_best_chain(0, affinity=probe)
    assert routed[0][0] == "b"  # entry re-ranked to the digest holder
    # inner stage unaffected by the probe (token-keyed caches live at
    # the entry): same cost -> planner's original inner pick stands
    assert routed[1][0] == plain[1][0]
    # a shedding entry holder loses the re-rank too
    snapshot[0]["b"]["shed"] = 1
    pf2 = pflib.PathFinder(_StubDHT(snapshot), 2)
    assert pf2.find_best_chain(0, affinity=probe)[0][0] == "a"


# ---------------------------------------------------------------------------
# devtel: the PR-8 gap fix — counters/gauges actually exported
# ---------------------------------------------------------------------------


class _PagedStub:
    prefill_tokens = 40

    def block_stats(self):
        return {
            "blocks_free": 10, "blocks_used": 21, "cow_shared": 3,
            "cow_splits": 2, "prefix_entries": 6, "prefix_hit_tokens": 160,
            "prefix_evictions": 4, "pins_resident": 1,
        }


def test_devtel_exports_prefix_series(monkeypatch):
    m = Metrics()
    devtellib.refresh_gauges(m, _PagedStub())
    snap = m.snapshot()
    assert snap["gauges"]["kv.prefix_entries"] == 6.0
    assert snap["counters"]["kv.prefix_hit_tokens"] == 160.0
    assert snap["counters"]["kv.prefix_evictions"] == 4.0
    assert snap["counters"]["kv.cow_splits"] == 2.0
    assert snap["counters"]["kv.prefill_tokens"] == 40.0
    # the exposition stays valid with the new series
    assert obs_export.validate_exposition(obs_export.prometheus_text(m)) == []
    # kill switch: byte-identical /metrics (the PR 5 contract holds for
    # every new series)
    m2 = Metrics()
    monkeypatch.setenv("INFERD_EVENTS", "0")
    before = obs_export.prometheus_text(m2)
    devtellib.refresh_gauges(m2, _PagedStub())
    assert obs_export.prometheus_text(m2) == before


def test_devtel_dense_executor_contributes_nothing():
    m = Metrics()
    devtellib.refresh_gauges(m, object())
    snap = m.snapshot()
    assert not any(k.startswith("kv.prefix") for k in snap["gauges"])
    assert not any(k.startswith("kv.") for k in snap["counters"])


def test_set_counter_reset_rebaselines_in_tsdb():
    """An executor swap's younger pool reads as a Prometheus counter
    reset: the windowed tsdb re-baselines instead of freezing."""
    m = Metrics()
    clock = [1000.0]
    t = tsdblib.Tsdb(m, clock=lambda: clock[0])
    t.sample()
    clock[0] += 1
    m.set_counter("kv.prefix_hit_tokens", 100.0)
    t.sample()
    clock[0] += 1
    m.set_counter("kv.prefix_hit_tokens", 5.0)  # swap: younger pool
    t.sample()
    clock[0] += 1
    m.set_counter("kv.prefix_hit_tokens", 25.0)
    t.sample()
    total = tsdblib.trailing_sum(t.history(), "kv.prefix_hit_tokens", 60.0)
    assert total == pytest.approx(120.0)  # 100 + reset(0) + 20


# ---------------------------------------------------------------------------
# windowed series -> fleet SLIs -> committed fixture
# ---------------------------------------------------------------------------


def _paged_history(service="n0", stage=0, hit_per_tick=80.0,
                   prefill_per_tick=20.0, ticks=120):
    m = Metrics()
    clock = [1700000000.0]
    t = tsdblib.Tsdb(m, service=service,
                     meta={"stage": stage, "num_stages": 1},
                     clock=lambda: clock[0])
    t.sample()
    for i in range(ticks):
        clock[0] += 1.0
        m.set_counter("kv.prefix_hit_tokens", (i + 1) * hit_per_tick)
        m.set_counter("kv.prefill_tokens", (i + 1) * prefill_per_tick)
        m.inc("stage.tokens", 5)
        t.sample()
    return t.history()


def test_fleet_cache_slis_merge_sums_not_ratios():
    # node A: 80/20 per tick, node B: 0/100 — the fleet hit rate is the
    # ratio of merged sums (80/200 = 0.4), NOT the mean of per-node
    # ratios (0.4 vs (0.8 + 0.0)/2 = 0.4 ... distinguish with asymmetry)
    ha = _paged_history("a", hit_per_tick=80.0, prefill_per_tick=20.0)
    hb = _paged_history("b", hit_per_tick=0.0, prefill_per_tick=100.0)
    s = fleetlib.fleet_sample([ha, hb])
    assert s["fleet"]["cache_hit_frac"] == pytest.approx(80 / 200, abs=0.02)
    assert s["fleet"]["prefill_saved_per_s"] == pytest.approx(80.0, rel=0.1)
    # dense fleets resolve None, never zero
    dense = fleetlib.fleet_sample([_burnless_dense_history()])
    assert dense["fleet"]["cache_hit_frac"] is None
    assert dense["fleet"]["prefill_saved_per_s"] is None
    # the report renders the cache line
    assert "cache: prefill-saved/s" in fleetlib.format_report([s])


def _burnless_dense_history():
    m = Metrics()
    clock = [1700000000.0]
    t = tsdblib.Tsdb(m, service="dense", meta={"stage": 0, "num_stages": 1},
                     clock=lambda: clock[0])
    t.sample()
    clock[0] += 1
    m.inc("stage.tokens", 5)
    t.sample()
    return t.history()


def test_committed_fleet_fixture_resolves_cache_slis(capsys):
    """run.sh 0e coverage: the committed fixture now carries a paged
    replica history (node2) and `obs fleet --check` resolves the cache
    SLIs from it."""
    from inferd_tpu.obs.__main__ import main as obs_main

    assert obs_main(["fleet", "--check", FLEET_FIXTURE]) == 0
    out = capsys.readouterr().out
    assert "cache: prefill-saved/s" in out
    assert "hit-rate 80.0%" in out
    hs = [
        tsdblib.load_history_file(
            os.path.join(FLEET_FIXTURE, f"node{i}.history.json")
        )
        for i in range(3)
    ]
    s = fleetlib.fleet_sample(hs)
    assert s["fleet"]["cache_hit_frac"] == pytest.approx(0.8, abs=0.01)
    assert s["fleet"]["prefill_saved_per_s"] > 0


# ---------------------------------------------------------------------------
# health rules
# ---------------------------------------------------------------------------


def test_prefix_evict_thrash_rule_and_peer_cachehit():
    rule = next(
        r for r in healthlib.DEFAULT_RULES
        if r.signal == "event:prefix.evict/min"
    )
    now = 1000.0
    calm = [{"type": "prefix.evict", "ts": now - i} for i in range(30)]
    fired, val, _ = healthlib.evaluate_rule(rule, {}, events=calm, now=now)
    assert fired is False
    storm = [
        {"type": "prefix.evict", "ts": now - i * 0.1} for i in range(300)
    ]
    fired, val, _ = healthlib.evaluate_rule(rule, {}, events=storm, now=now)
    assert fired is True and val >= 240
    # the gossiped cachehit field is peer:-rule addressable; the worst
    # offender under a lower-bound rule is the SMALLEST value
    r = healthlib.Rule.parse("peer:cachehit > 0.1")
    fired, val, peer = healthlib.evaluate_rule(
        r, {}, peers={
            "a": {"cachehit": 0.9}, "b": {"cachehit": 0.05},
            "c": {"cachehit": 0.02}, "old": {},
        },
    )
    assert fired is True and peer == "c" and val == 0.02


# ---------------------------------------------------------------------------
# collector / dashboard: mixed-version rendering
# ---------------------------------------------------------------------------


def test_collector_cachehit_column_and_old_peer_blanks():
    from inferd_tpu.tools.collector import stage_rows

    swarm = {
        0: {
            "n0": {"load": 1, "cap": 4, "cachehit": 0.9,
                   "pfx": _digest_for(PROMPT)},
            "n1": {"load": 1, "cap": 4, "cachehit": 0.5},
            "old": {"load": 1, "cap": 4},  # pre-digest peer
        },
        1: {"inner": {"load": 0, "cap": 4}},
    }
    rows = {r["stage"]: r for r in stage_rows(swarm, ts=1.0)}
    assert rows[0]["cachehit"] == 70.0  # median of 0.9/0.5, as a %
    assert rows[1]["cachehit"] == ""    # no paged replica: blank


def test_dashboard_cache_cell_blank_for_old_peers():
    from inferd_tpu.tools.dashboard import render_table

    swarm = {0: {
        "new": {"name": "n", "load": 0, "cap": 1, "cachehit": 0.42},
        "old": {"name": "o", "load": 0, "cap": 1},
    }}
    text = render_table(swarm, ts=0.0)
    assert "cache%" in text
    new_line = next(ln for ln in text.splitlines() if " new " in ln)
    old_line = next(ln for ln in text.splitlines() if " old " in ln)
    assert "42%" in new_line
    assert "42%" not in old_line


# ---------------------------------------------------------------------------
# mixed-version gossip compat (the PR 7 test_dht pattern)
# ---------------------------------------------------------------------------


@pytest.mark.asyncio
async def test_mixed_version_gossip_digest_keys():
    """The new `pfx`/`shed`/`cachehit` keys pass bit-true through peers
    that predate them, and old records gain nothing."""
    from inferd_tpu.control.dht import SwarmDHT

    def mk(node_id, port, bootstrap=None):
        return SwarmDHT(node_id, port, bootstrap=bootstrap or [], ttl_s=5.0,
                        gossip_period_s=0.05, host="127.0.0.1")

    new = mk("new", 17351)
    old = mk("old", 17352, bootstrap=[("127.0.0.1", 17351)])
    obs = mk("obs", 17353, bootstrap=[("127.0.0.1", 17351)])
    await new.start(); await old.start(); await obs.start()
    try:
        digest = _digest_for(PROMPT)
        new.announce({
            "stage": 0, "load": 1, "cap": 4,
            "pfx": digest, "shed": 1, "cachehit": 0.73,
        })
        old.announce({"stage": 0, "load": 0, "cap": 4})  # pre-digest record
        for _ in range(100):
            if len(obs.get_stage(0)) == 2:
                break
            await asyncio.sleep(0.05)
        stage = obs.get_stage(0)
        assert len(stage) == 2, "gossip did not converge"
        assert stage["new"]["pfx"] == digest  # bit-true through the store
        assert stage["new"]["shed"] == 1
        assert stage["new"]["cachehit"] == 0.73
        for key in ("pfx", "shed", "cachehit"):
            assert key not in stage["old"]
        # an OBSERVER'S router scores the relayed digest directly
        probe = prefixlib.AffinityProbe(PROMPT)
        assert probe.depth_frac(stage["new"]) == 1.0
        assert probe.depth_frac(stage["old"]) == 0.0
    finally:
        await new.stop(); await old.stop(); await obs.stop()


# ---------------------------------------------------------------------------
# perf gate: the round-13 invariants
# ---------------------------------------------------------------------------


def _ca_leg(**kw):
    leg = {
        "metric": "tiny_cache_affinity_saved_tokens", "value": 1000,
        "unit": "tokens", "hit_frac_prior": 0.7,
        "saved_tokens_on": 1000, "saved_tokens_off": 100,
        "token_exact": True,
    }
    leg.update(kw)
    return leg


def test_gate_cache_affinity_ordering_invariant():
    from inferd_tpu.perf import gate as gatelib

    ok = gatelib.check_artifact([("ca", _ca_leg())])
    assert not [f for f in ok if f.severity == "error"]
    bad = gatelib.check_artifact(
        [("ca", _ca_leg(saved_tokens_on=90, value=90))]
    )
    assert any(
        f.severity == "error" and "prefill-tokens-avoided" in f.message
        for f in bad
    )


def test_gate_cache_affinity_prior_regression_and_skip():
    from inferd_tpu.perf import gate as gatelib

    prior = [("ca", _ca_leg(hit_frac_prior=0.7))]
    fresh = [("ca", _ca_leg(hit_frac_prior=0.5))]  # 28.6% drop
    found = gatelib.check_artifact(fresh, prior)
    assert any(
        f.check == "regression" and "hit_frac_prior" in f.message
        for f in found
    )
    # a pair missing the ratio on either side SKIPS (no raw-token
    # fallback — exactly the cross-host false-fail the ratio prevents)
    legless = [("ca", {k: v for k, v in _ca_leg().items()
                       if k != "hit_frac_prior"})]
    assert not gatelib.check_artifact(legless, prior)


def test_committed_cache_artifact_passes_gate():
    from inferd_tpu.perf import gate as gatelib

    path = os.path.join(
        os.path.dirname(__file__), "..", "bench_artifacts",
        "BENCH_cache_cpu_r13.json",
    )
    findings, ok = gatelib.gate(path, prior_path=path)
    assert ok, [f.line() for f in findings]
    legs = dict(gatelib.load_artifact(path))
    leg = legs["tiny_cache_affinity_saved_tokens"]
    # the committed evidence: strictly more prefill avoided with digest
    # routing on, token-exact both sides
    assert leg["saved_tokens_on"] > leg["saved_tokens_off"]
    assert leg["token_exact"] is True
    assert 0 < leg["hit_frac_prior"] <= 1


# ---------------------------------------------------------------------------
# executors: digest surface + tokens_saved + evict event
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def batch_exec():
    import jax

    from inferd_tpu.models import qwen3
    from inferd_tpu.runtime.batch_executor import BatchedExecutor

    params = qwen3.init_params(TINY, jax.random.PRNGKey(0))
    # 10 blocks (9 usable): tight enough that the third distinct prompt
    # family's registration must evict the first's idle index entries
    return BatchedExecutor(
        TINY, params, lanes=2, max_len=128, block_size=16, kv_blocks=10,
    )


def test_executor_digest_tokens_saved_and_evict_event(batch_exec):
    ex = batch_exec
    events = []
    ex.on_event = lambda etype, **attrs: events.append((etype, attrs))
    prompt = [list(range(2, 50))]
    r1 = ex.process("s1", {"tokens": prompt, "start_pos": 0, "real_len": 48})
    assert "tokens_saved" not in r1  # cold prefill: key omitted
    ex.end_session("s1")
    d = ex.prefix_digest()
    assert d is not None and d["bs"] == 16 and d["k"]
    probe = prefixlib.AffinityProbe(prompt[0])
    assert probe.depth_frac({"pfx": d}) > 0.5
    # a second session with the same prompt maps the cached prefix:
    # tokens_saved stamped, prefix.hit journaled
    r2 = ex.process("s2", {"tokens": prompt, "start_pos": 0, "real_len": 48})
    assert r2["tokens_saved"] == 32  # 2 full 16-token blocks (last
    # block covering the final token always computes)
    assert np.allclose(r1["logits"], r2["logits"], atol=2e-5)
    assert any(e == "prefix.hit" for e, _ in events)
    ex.end_session("s2")
    # crowd the pool until the index must evict: prefix.evict carries age
    big = [list(range(60, 120))]
    ex.process("s3", {"tokens": big, "start_pos": 0, "real_len": 60})
    ex.end_session("s3")
    big2 = [list(range(200, 260))]
    ex.process("s4", {"tokens": big2, "start_pos": 0, "real_len": 60})
    ex.end_session("s4")
    evicts = [a for e, a in events if e == "prefix.evict"]
    assert evicts and all("age_ms" in a and a["age_ms"] >= 0 for a in evicts)


def test_stage_executor_prefix_digest_inner_stage_is_none():
    """Inner pipeline stages never see tokens: their digest is None so
    the `pfx` key stays out of gossip (no token-keyed identity to
    advertise)."""
    import jax

    from inferd_tpu.models import qwen3
    from inferd_tpu.parallel.stages import Manifest, extract_stage_params
    from inferd_tpu.runtime.stage_batch import BatchedStageExecutor

    params = qwen3.init_params(TINY, jax.random.PRNGKey(0))
    manifest = Manifest.even_split("tiny", 2)
    spec = list(manifest.stage_specs())[1]  # the non-entry stage
    sp = extract_stage_params(params, TINY, spec)
    ex = BatchedStageExecutor(
        TINY, spec, sp, lanes=2, max_len=64, block_size=16,
    )
    assert ex.prefix_digest() is None


# ---------------------------------------------------------------------------
# sim: the 1000-node rehearsal (slow lane; fast fixtures ride the
# test_sim parametrization automatically)
# ---------------------------------------------------------------------------


def test_cache_affinity_fixtures_exist_and_diverge():
    with open(os.path.join(SIM_DATA, "cache_affinity.json")) as f:
        on = json.load(f)
    with open(os.path.join(SIM_DATA, "cache_affinity_off.json")) as f:
        off = json.load(f)
    gates_on = {tuple(g[:2]): g[2] for g in on["gates"]}
    gates_off = {tuple(g[:2]): g[2] for g in off["gates"]}
    # the committed pair IS the routing-prefers-holders proof: the on
    # floor sits strictly above the off ceiling
    assert gates_on[("cache.hit_frac", ">=")] > gates_off[
        ("cache.hit_frac", "<=")
    ]


@pytest.mark.slow
def test_cache_affinity_1000_fixture_replays():
    """ROADMAP 2c acceptance: digest-affinity routing rehearsed at 1000
    nodes — fleet hit rate well above chance placement, admission
    watermark never starved, byte-identical trace."""
    from inferd_tpu.sim.scenario import check_fixture

    path = os.path.join(SIM_DATA, "cache_affinity_1000.json")
    ok, failures, metrics = check_fixture(path)
    assert ok, (failures, metrics.get("cache"), metrics.get("sessions"))
