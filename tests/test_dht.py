"""Swarm store tests: gossip propagation on loopback UDP, owner-only write
merges (the B6 race fix), TTL expiry of dead nodes, tombstone withdrawal."""

import asyncio

import pytest

from inferd_tpu.control.dht import SwarmDHT


def _mk(node_id, port, bootstrap=None, ttl=5.0, period=0.05):
    return SwarmDHT(
        node_id, port, bootstrap=bootstrap or [], ttl_s=ttl,
        gossip_period_s=period, host="127.0.0.1",
    )


async def _wait_for(cond, timeout=5.0, interval=0.05):
    loop = asyncio.get_event_loop()
    deadline = loop.time() + timeout
    while loop.time() < deadline:
        if cond():
            return True
        await asyncio.sleep(interval)
    return False


@pytest.mark.asyncio
async def test_gossip_propagation_three_nodes():
    ports = [17101, 17102, 17103]
    a = _mk("a", ports[0])
    b = _mk("b", ports[1], bootstrap=[("127.0.0.1", ports[0])])
    c = _mk("c", ports[2], bootstrap=[("127.0.0.1", ports[0])])
    await a.start(); await b.start(); await c.start()
    try:
        a.announce({"stage": 0, "load": 0, "cap": 1})
        b.announce({"stage": 1, "load": 2, "cap": 1})
        c.announce({"stage": 1, "load": 0, "cap": 1})
        ok = await _wait_for(
            lambda: len(a.get_stage(1)) == 2
            and len(b.get_stage(0)) == 1
            and len(c.get_stage(0)) == 1
        )
        assert ok, "gossip did not converge"
        assert a.get_stage(1)["b"]["load"] == 2
        allmap = c.get_all(3)
        assert set(allmap.keys()) == {0, 1, 2} and allmap[2] == {}
    finally:
        await a.stop(); await b.stop(); await c.stop()


@pytest.mark.asyncio
async def test_owner_only_writes_no_clobber():
    """Concurrent announces from different nodes can never clobber each
    other (the reference's shared-record RMW race, SURVEY B6)."""
    a = _mk("a", 17111)
    b = _mk("b", 17112, bootstrap=[("127.0.0.1", 17111)])
    await a.start(); await b.start()
    try:
        for i in range(20):  # interleaved rapid announces
            a.announce({"stage": 0, "load": i, "cap": 1})
            b.announce({"stage": 0, "load": 100 + i, "cap": 1})
        ok = await _wait_for(
            lambda: a.get_stage(0).get("b", {}).get("load") == 119
            and b.get_stage(0).get("a", {}).get("load") == 19
        )
        assert ok
        assert set(a.get_stage(0)) == {"a", "b"}
    finally:
        await a.stop(); await b.stop()


@pytest.mark.asyncio
async def test_ttl_expires_dead_node():
    a = _mk("a", 17121, ttl=0.6)
    b = _mk("b", 17122, bootstrap=[("127.0.0.1", 17121)], ttl=0.6)
    await a.start(); await b.start()
    a.announce({"stage": 0, "load": 0, "cap": 1})
    b.announce({"stage": 1, "load": 0, "cap": 1})
    assert await _wait_for(lambda: len(a.get_stage(1)) == 1)
    await b.stop()  # b dies silently (no tombstone)
    try:
        assert await _wait_for(lambda: len(a.get_stage(1)) == 0, timeout=3.0)
    finally:
        await a.stop()


@pytest.mark.asyncio
async def test_withdraw_tombstone():
    a = _mk("a", 17131)
    b = _mk("b", 17132, bootstrap=[("127.0.0.1", 17131)])
    await a.start(); await b.start()
    a.announce({"stage": 0, "load": 0, "cap": 1})
    b.announce({"stage": 1, "load": 0, "cap": 1})
    assert await _wait_for(lambda: len(a.get_stage(1)) == 1)
    b.withdraw()
    try:
        assert await _wait_for(lambda: len(a.get_stage(1)) == 0, timeout=3.0)
    finally:
        await a.stop(); await b.stop()


@pytest.mark.asyncio
async def test_late_joiner_bootstrap_state():
    a = _mk("a", 17141)
    await a.start()
    a.announce({"stage": 0, "load": 3, "cap": 2})
    late = _mk("late", 17142, bootstrap=[("127.0.0.1", 17141)])
    await late.start()
    try:
        assert await _wait_for(lambda: late.get_stage(0).get("a", {}).get("load") == 3)
    finally:
        await a.stop(); await late.stop()


@pytest.mark.asyncio
async def test_bootstrap_retry_when_seed_starts_late():
    """A node whose initial HELLO is lost (seed not yet up) must keep
    retrying bootstrap and converge once the seed appears (the reference's
    Kademlia bootstrap retry, kademlia_client.py:25-37)."""
    base = 19450
    late = SwarmDHT(
        "late", base + 1, bootstrap=[("127.0.0.1", base)], host="127.0.0.1",
        gossip_period_s=0.05, ttl_s=5.0,
    )
    await late.start()  # hello goes nowhere: seed port not bound yet
    late.announce({"stage": 0, "load": 0, "cap": 1, "name": "late"})
    await asyncio.sleep(0.3)
    seed = SwarmDHT("seed", base, host="127.0.0.1", gossip_period_s=0.05, ttl_s=5.0)
    await seed.start()
    seed.announce({"stage": 1, "load": 0, "cap": 1, "name": "seed"})
    try:
        for _ in range(100):
            if late.get_stage(1) and seed.get_stage(0):
                break
            await asyncio.sleep(0.05)
        assert late.get_stage(1), "late node never learned the seed's record"
        assert seed.get_stage(0), "seed never learned the late node's record"
    finally:
        await late.stop()
        await seed.stop()


@pytest.mark.asyncio
async def test_mixed_version_gossip_windowed_and_outlier_keys():
    """PR 7 wire compat (mirroring the PR 4 multi-envelope pattern): a
    NEW node's record carries `outlier`, `svc_p99_ms`, and the windowed
    hop quantiles; an OLD peer must relay and store them untouched (the
    gossip store is schema-agnostic), and an old-style record LACKING
    them must coexist in the same stage map without defaults being
    invented for it."""
    new = _mk("new", 17151)
    old = _mk("old", 17152, bootstrap=[("127.0.0.1", 17151)])
    obs = _mk("obs", 17153, bootstrap=[("127.0.0.1", 17151)])
    await new.start(); await old.start(); await obs.start()
    try:
        new.announce({
            "stage": 0, "load": 1, "cap": 4,
            # PR 7 keys + a future key nobody knows yet
            "hop_p50_ms": 4.5, "hop_p99_ms": 22.0, "svc_p99_ms": 9.0,
            "outlier": 1, "sloth_factor_v9": {"nested": True},
        })
        old.announce({"stage": 0, "load": 0, "cap": 4})  # pre-PR record
        ok = await _wait_for(lambda: len(obs.get_stage(0)) == 2)
        assert ok, "gossip did not converge"
        stage = obs.get_stage(0)
        # the new keys arrive bit-true through the old-agnostic store
        assert stage["new"]["outlier"] == 1
        assert stage["new"]["svc_p99_ms"] == 9.0
        assert stage["new"]["hop_p99_ms"] == 22.0
        assert stage["new"]["sloth_factor_v9"] == {"nested": True}
        # the old record gained nothing it never announced
        for key in ("outlier", "svc_p99_ms", "hop_p50_ms", "hop_p99_ms"):
            assert key not in stage["old"]
    finally:
        await new.stop(); await old.stop(); await obs.stop()
