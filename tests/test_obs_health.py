"""Flight-recorder & fleet-health tests: the event journal (ring,
kill switch, trace capture, metrics mirroring), device telemetry
(graceful CPU fallback, KV occupancy, compile watch), the SLO rule
engine (parse/evaluate/verdict/event-rate/peer rules, the offline CLI
over the committed fixture), the postmortem assembly, the merge CLI's
negative-duration clamp, the trace epoch anchor, the extended perf-gate
overhead budget, and the /metrics byte-identity acceptance criterion
with events disabled."""

import json
import os
import time

import pytest

from inferd_tpu.obs import devtel, events, health, merge, postmortem, trace
from inferd_tpu.utils.metrics import Metrics

HEALTH_FIXTURE = os.path.join(os.path.dirname(__file__), "data", "health")


# -------------------------------------------------------------- journal


def test_journal_ring_cap_counts_and_stats():
    j = events.EventJournal("svc", cap=16)
    for i in range(40):
        j.emit("peer.dead", peer=f"n{i}")
    assert len(j) == 16
    st = j.stats()
    assert st["recorded"] == 40 and st["dropped"] == 24
    assert st["buffered"] == 16 and st["overhead_ms"] >= 0
    assert j.counts() == {"peer.dead": 16}
    # seq is a stable per-process ordinal (the JSONL dedup key)
    seqs = [ev["seq"] for ev in j.events()]
    assert seqs == sorted(seqs) and len(set(seqs)) == len(seqs)


def test_journal_mirrors_event_counters_into_metrics():
    m = Metrics()
    j = events.EventJournal("svc", metrics=m)
    j.emit("session.rescue", session="s")
    j.emit("session.rescue", session="s2")
    j.emit("kv.overflow")
    c = m.snapshot()["counters"]
    assert c["events.session.rescue"] == 2
    assert c["events.kv.overflow"] == 1


def test_journal_trace_capture_explicit_and_contextvar():
    rec = trace.SpanRecorder("svc")
    j = events.EventJournal("svc")
    with rec.span("root", "server") as ctx:
        ev = j.emit("lane.evict", session="s")  # from the contextvar
    assert ev["trace"] == ctx.trace_id
    other = trace.SpanContext("tid123", "sid456")
    ev2 = j.emit("peer.dead", trace=other)  # explicit wins
    assert ev2["trace"] == "tid123"
    ev3 = j.emit("node.start")  # no context in scope: no trace key
    assert "trace" not in ev3


def test_journal_kill_switch_records_nothing(monkeypatch):
    monkeypatch.setenv("INFERD_EVENTS", "0")
    m = Metrics()
    j = events.EventJournal("svc", metrics=m)
    assert j.emit("peer.dead") is None
    assert len(j) == 0
    assert m.snapshot()["counters"] == {}


def test_journal_flush_jsonl_high_water_and_load(tmp_path):
    j = events.EventJournal("svc")
    j.emit("node.start", stage=0)
    j.emit("peer.dead", peer="x")
    path = str(tmp_path / "svc.events.jsonl")
    assert j.flush_jsonl(path) == 2
    assert len(j) == 2  # non-draining
    assert j.flush_jsonl(path) == 0  # nothing new: no duplicates
    j.emit("node.stop")
    assert j.flush_jsonl(path) == 1
    # loader: dedupes, tolerates garbage, time-sorts
    with open(path, "a") as f:
        f.write("{truncated\n")
        f.write(json.dumps({"type": "bogus"}) + "\n")  # no ts
    loaded = events.load_events([str(tmp_path)])
    assert [ev["type"] for ev in loaded] == [
        "node.start", "peer.dead", "node.stop",
    ]
    # dump_jsonl appends the WHOLE ring regardless of the flush mark;
    # the loader dedups the resulting duplicates
    assert j.dump_jsonl(path) == 3
    assert len(events.load_events([path])) == 3


def test_load_events_keeps_both_runs_of_a_restarted_node(tmp_path):
    """A restarted node reuses its node_id and journal file; seq restarts
    at 0 — the per-process run nonce keeps the loader from dropping the
    second run's events as duplicates (the postmortem-critical half)."""
    path = str(tmp_path / "n0.events.jsonl")
    run1 = events.EventJournal("n0")
    run1.emit("node.start", stage=0)
    run1.emit("peer.dead", peer="x")
    run1.flush_jsonl(path)
    run2 = events.EventJournal("n0")  # fresh process: seq restarts at 0
    run2.emit("node.start", stage=0)
    run2.emit("session.rescue", session="s")
    run2.flush_jsonl(path)
    loaded = events.load_events([path])
    assert len(loaded) == 4
    assert [ev["type"] for ev in loaded].count("node.start") == 2


def test_journal_rate_per_min_windows():
    j = events.EventJournal("svc")
    now = trace.now()
    j.emit("node.start", ts=now - 3600.0)  # pins the journal's reach
    for dt in (0.5, 1.0, 2.0):
        j.emit("session.rescue", ts=now - dt)
    j.emit("session.rescue", ts=now - 1800.0)  # outside the window
    assert j.rate_per_min("session.rescue", window_s=60.0) == pytest.approx(
        3.0, rel=0.05
    )
    assert j.rate_per_min("peer.dead") == 0.0
    assert events.EventJournal("empty").rate_per_min("peer.dead") == 0.0
    # a young journal clamps the window to its reach (floored at 30 s):
    # a 20-rescue storm on a node alive ~5 s reads as a storm (40/min,
    # not 20/min diluted over a minute it hasn't lived)...
    young = events.EventJournal("young")
    for i in range(20):
        young.emit("session.rescue", ts=now - 0.25 * i)
    assert young.rate_per_min("session.rescue", window_s=60.0) == (
        pytest.approx(40.0)
    )
    # ...while a SINGLE benign early event amplifies at most 2x — one
    # kv.overflow seconds after start must not breach the <10 rule
    single = events.EventJournal("single")
    single.emit("kv.overflow", ts=now - 2.0)
    assert single.rate_per_min("kv.overflow", window_s=60.0) <= 2.0


# ------------------------------------------------------- epoch anchoring


def test_trace_now_is_anchored_and_monotonic():
    a = trace.now()
    b = trace.now()
    assert b >= a  # perf_counter deltas can't run backwards
    assert abs(trace.now() - time.time()) < 5.0  # still wall-clock epoch


def test_span_durations_non_negative():
    rec = trace.SpanRecorder("svc")
    with rec.span("s", "compute"):
        pass
    (s,) = rec.spans()
    assert s["t1"] >= s["t0"]


def test_merge_counts_and_clamps_negative_duration_spans(tmp_path):
    """A legacy recorder that stamped across an NTP step produced
    t1 < t0; merge must clamp (not skip, not corrupt stage sums)."""
    spans = [
        {"trace": "t1", "span": "r", "parent": None, "name": "generate",
         "phase": "client", "service": "c", "t0": 0.0, "t1": 1.0},
        {"trace": "t1", "span": "neg", "parent": "r", "name": "compute",
         "phase": "compute", "service": "c", "t0": 0.5, "t1": 0.2,
         "attrs": {"stage": 0}},
    ]
    p = tmp_path / "c.spans.jsonl"
    with open(p, "w") as f:
        for s in spans:
            f.write(json.dumps(s) + "\n")
    result = merge.merge_paths([str(p)])
    assert result["skipped_lines"] == 0
    assert result["clamped_spans"] == 1
    t = result["traces"][0]
    assert t["spans"] == 2
    # clamped to zero duration: the stage sum is not poisoned negative
    assert t["stages"]["0"]["compute_ms"] == 0.0


# --------------------------------------------------------------- devtel


def test_hbm_summary_graceful_on_cpu():
    # CPU backends report no memory_stats: None, never a crash
    assert devtel.hbm_summary() is None or isinstance(
        devtel.hbm_summary(), dict
    )


def test_kv_occupancy_resolution():
    class Pool:
        lengths = [10, 0, 30, 0]
        max_len = 40

    assert devtel.kv_occupancy(Pool()) == pytest.approx(40 / 160)

    class Custom:
        def kv_occupancy(self):
            return 0.5

    assert devtel.kv_occupancy(Custom()) == 0.5
    assert devtel.kv_occupancy(object()) is None


def test_refresh_gauges_disabled_is_noop(monkeypatch):
    monkeypatch.setenv("INFERD_EVENTS", "0")
    m = Metrics()

    class Pool:
        lengths = [10]
        max_len = 10

    devtel.refresh_gauges(m, Pool())
    assert m.snapshot()["gauges"] == {}


def test_compile_watch_detects_jit_compiles():
    jax = pytest.importorskip("jax")
    import jax.numpy as jnp

    m = Metrics()
    j = events.EventJournal("svc")
    watch = devtel.CompileWatch(m, j)
    step = watch.watch(jax.jit(lambda x: x * 2 + 1), "step")
    assert int(step(jnp.int32(3))) == 7  # first call: traces + compiles
    assert watch.compiles == 1
    assert int(step(jnp.int32(4))) == 9  # cached: no new compile
    assert watch.compiles == 1
    step(jnp.float32(1.5))  # new dtype: a real recompile
    assert watch.compiles == 2
    types = [ev["type"] for ev in j.events()]
    assert types.count("compile.begin") == 2
    assert types.count("compile.end") == 2
    ends = [ev for ev in j.events() if ev["type"] == "compile.end"]
    assert all(ev["attrs"]["elapsed_ms"] >= 0 for ev in ends)
    snap = m.snapshot()
    assert snap["counters"]["compile.events"] == 2
    assert snap["histograms"]["compile.ms"]["count"] == 2
    # non-jit callables pass through unwrapped
    plain = devtel.CompileWatch().watch(lambda x: x, "plain")
    assert plain(5) == 5


def test_instrument_executor_wraps_real_jits():
    """Regression: jax.jit products carry functools-style __wrapped__
    themselves, so the double-wrap guard must use its own sentinel — a
    guard on __wrapped__ silently skipped EVERY executor jit and left
    the compile watch dead on the production path."""
    jax = pytest.importorskip("jax")
    import jax.numpy as jnp

    class Ex:
        _run = staticmethod(jax.jit(lambda x: x + 1))

    ex = Ex()
    watch = devtel.CompileWatch(Metrics(), events.EventJournal("svc"))
    watch.instrument_executor(ex, label="Ex")
    assert getattr(ex._run, "_compile_watched", False), (
        "instrument_executor left the jitted attr unwrapped"
    )
    assert int(ex._run(jnp.int32(1))) == 2
    assert watch.compiles == 1
    before = ex._run
    watch.instrument_executor(ex, label="Ex")  # idempotent: no re-wrap
    assert ex._run is before

    class Engine:
        _decode_all = staticmethod(jax.jit(lambda x: x * 3))

    class BatchedEx:  # --batch-lanes shape: jits live on .engine
        engine = Engine()

    bex = BatchedEx()
    watch.instrument_executor(bex, label="BatchedEx")
    assert getattr(bex.engine._decode_all, "_compile_watched", False)
    assert int(bex.engine._decode_all(jnp.int32(2))) == 6
    assert watch.compiles == 2


# --------------------------------------------------------------- health


def test_rule_parse_and_errors():
    r = health.Rule.parse("hop.relay_ms.p99_ms < 250")
    assert (r.signal, r.op, r.threshold) == ("hop.relay_ms.p99_ms", "<", 250.0)
    assert r.expr == "hop.relay_ms.p99_ms < 250"
    with pytest.raises(ValueError, match="bad SLO rule"):
        health.Rule.parse("not a rule")
    with pytest.raises(ValueError, match="severity"):
        health.Rule.parse("x < 1", severity="catastrophic")


def test_evaluate_fires_skips_and_ranks_severity():
    snap = {
        "gauges": {"queue.depth": 20.0, "trace.dropped": 0.0},
        "histograms": {"hop.relay_ms": {"p99_ms": 3000.0}},
    }
    rules = [
        health.Rule.parse("queue.depth < 16"),                    # fires
        health.Rule.parse("trace.dropped == 0"),                  # ok
        health.Rule.parse("hop.relay_ms.p99_ms < 2000", "failing"),  # fires
        health.Rule.parse("hbm.frac < 0.95"),                     # skipped
    ]
    v = health.evaluate(rules, snap)
    assert v["status"] == "failing"
    assert v["evaluated"] == 3 and v["skipped"] == 1
    assert {f["rule"] for f in v["firing"]} == {
        "queue.depth < 16", "hop.relay_ms.p99_ms < 2000",
    }
    # degraded when only degraded-severity rules fire
    v2 = health.evaluate(rules[:2], snap)
    assert v2["status"] == "degraded"
    # ok when nothing fires
    assert health.evaluate(rules[1:2], snap)["status"] == "ok"


def test_evaluate_event_rules_count_and_rate():
    now = trace.now()
    evs = [
        {"ts": now - 1.0, "type": "session.rescue", "service": "n"},
        {"ts": now - 2.0, "type": "session.rescue", "service": "n"},
        {"ts": now - 3600.0, "type": "session.rescue", "service": "n"},
    ]
    count_rule = health.Rule.parse("event:session.rescue == 0")
    rate_rule = health.Rule.parse("event:session.rescue/min < 1")
    v = health.evaluate([count_rule, rate_rule], {}, events=evs, now=now)
    assert {f["rule"] for f in v["firing"]} == {
        "event:session.rescue == 0", "event:session.rescue/min < 1",
    }
    # the count rule sees ALL scoped events; the rate rule only the window
    by_rule = {f["rule"]: f["value"] for f in v["firing"]}
    assert by_rule["event:session.rescue == 0"] == 3.0
    assert by_rule["event:session.rescue/min < 1"] == pytest.approx(2.0)
    # no events provided at all -> event rules skip
    v2 = health.evaluate([count_rule], {})
    assert v2["evaluated"] == 0 and v2["skipped"] == 1
    # empty journal -> evaluates to zero, rule passes
    v3 = health.evaluate([count_rule], {}, events=[])
    assert v3["evaluated"] == 1 and v3["status"] == "ok"


def test_evaluate_peer_rules():
    rule = health.Rule.parse("peer:hop_p99_ms < 100")
    peers = {
        "10.0.0.2:6050": {"hop_p99_ms": 50.0},
        "10.0.0.3:6050": {"hop_p99_ms": 900.0},
    }
    v = health.evaluate([rule], {}, peers=peers)
    assert v["firing"][0]["peer"] == "10.0.0.3:6050"
    assert v["firing"][0]["value"] == 900.0
    # no peers (None), an EMPTY peer map (single-replica swarm), and
    # peers that don't carry the field all SKIP: no data is not passing
    assert health.evaluate([rule], {})["skipped"] == 1
    assert health.evaluate([rule], {}, peers={})["skipped"] == 1
    assert health.evaluate(
        [rule], {}, peers={"a": {"load": 1}}
    )["skipped"] == 1


def test_health_cli_check_over_committed_fixture(capsys):
    from inferd_tpu.obs.__main__ import main

    assert main(["health", "--check", HEALTH_FIXTURE]) == 0
    out = capsys.readouterr().out
    assert "obs health check: OK" in out


def test_health_cli_check_fails_on_breach(tmp_path):
    from inferd_tpu.obs.__main__ import main

    (tmp_path / "bad.stats.json").write_text(json.dumps({
        "gauges": {"hbm.frac": 0.99, "queue.depth": 0, "trace.dropped": 0},
    }))
    assert main(["health", "--check", str(tmp_path)]) == 1
    # custom rules file overrides the defaults
    (tmp_path / "rules.json").write_text(json.dumps(
        [{"rule": "hbm.frac < 1.5", "severity": "failing"}]
    ))
    assert main(["health", "--check", str(tmp_path)]) == 0


# ---------------------------------------------------- /metrics byte parity


def test_metrics_byte_identical_with_events_disabled(monkeypatch):
    """Acceptance: with events disabled, every emit site and gauge
    refresh is a no-op, so the Prometheus exposition is byte-identical
    to a registry the subsystem never touched."""
    from inferd_tpu.obs import export

    def drive(m):
        # the pre-PR instrumentation still runs either way
        m.inc("forward.requests")
        m.observe("stage.compute_ms", 5.0)
        m.set_gauge("inflight", 1)
        # this PR's surfaces: journal, compile watch, devtel gauges
        j = events.EventJournal("n0", metrics=m)
        j.emit("peer.dead", peer="x")
        j.emit("executor.warmup_failed", error="boom")
        watch = devtel.CompileWatch(m, j)
        watch.record("step", 12.0)
        devtel.refresh_gauges(m, None)
        if events.enabled():
            st = j.stats()
            m.set_gauge("events.count", st["recorded"])
            m.set_gauge("events.overhead_ms", st["overhead_ms"])
        return m

    monkeypatch.setenv("INFERD_EVENTS", "0")
    disabled = export.prometheus_text(drive(Metrics()))
    baseline = Metrics()
    baseline.inc("forward.requests")
    baseline.observe("stage.compute_ms", 5.0)
    baseline.set_gauge("inflight", 1)
    assert disabled == export.prometheus_text(baseline)
    monkeypatch.setenv("INFERD_EVENTS", "1")
    enabled_text = export.prometheus_text(drive(Metrics()))
    assert "inferd_events_peer_dead_total" in enabled_text
    assert "inferd_events_executor_warmup_failed_total" in enabled_text
    assert "inferd_compile_events_total" in enabled_text
    assert "inferd_events_overhead_ms" in enabled_text
    assert export.validate_exposition(enabled_text) == []


# ------------------------------------------------------- gate extension


def test_gate_budgets_event_journal_overhead():
    from inferd_tpu.perf.gate import check_span_overhead

    snap = {
        "gauges": {"trace.overhead_ms": 0.5, "events.overhead_ms": 5.0},
        "histograms": {"stage.compute_ms": {"count": 10, "mean_ms": 10.0}},
    }
    findings = check_span_overhead(snap)  # events at 5%, spans at 0.5%
    assert len(findings) == 1
    assert "event-journal" in findings[0].message
    snap["gauges"]["events.overhead_ms"] = 0.5
    assert check_span_overhead(snap) == []
    snap["gauges"]["trace.overhead_ms"] = 9.0
    assert "span-recording" in check_span_overhead(snap)[0].message


def test_measured_journal_overhead_inside_budget():
    """Acceptance: a realistic emit volume stays under the 1% budget
    against a plausible compute accumulation (1000 steps x 10 ms)."""
    from inferd_tpu.perf.gate import check_span_overhead

    m = Metrics()
    j = events.EventJournal("n0", metrics=m)
    for i in range(1000):
        j.emit("session.rescue", session=f"s{i % 7}", stage=1, holder="x")
    snap = {
        "gauges": {"events.overhead_ms": j.stats()["overhead_ms"]},
        "histograms": {"stage.compute_ms": {"count": 1000, "mean_ms": 10.0}},
    }
    assert check_span_overhead(snap) == [], (
        f"1000 events cost {j.stats()['overhead_ms']} ms"
    )


# ----------------------------------------------------------- postmortem


def _incident_artifacts(tmp_path):
    """Synthetic 2-node incident: client -> A relays to B; B's clock is
    skewed +2 s; a peer.dead on A mid-relay and a session.rescue on B,
    plus per-node metrics snapshots."""
    tid = "inc00000000000001"
    spans = {
        "client": [
            {"trace": tid, "span": "r", "parent": None, "name": "generate",
             "phase": "client", "service": "client", "t0": 100.0, "t1": 101.0},
            {"trace": tid, "span": "st", "parent": "r", "name": "step",
             "phase": "wire", "service": "client", "t0": 100.05, "t1": 100.95},
        ],
        "A": [
            {"trace": tid, "span": "af", "parent": "st", "name": "forward",
             "phase": "server", "service": "A", "t0": 100.1, "t1": 100.9,
             "attrs": {"stage": 0}},
            {"trace": tid, "span": "ac", "parent": "af", "name": "compute",
             "phase": "compute", "service": "A", "t0": 100.12, "t1": 100.3,
             "attrs": {"stage": 0}},
            {"trace": tid, "span": "ar", "parent": "af", "name": "relay",
             "phase": "relay", "service": "A", "t0": 100.32, "t1": 100.88,
             "attrs": {"stage": 1}},
        ],
        "B": [
            {"trace": tid, "span": "bf", "parent": "ar", "name": "forward",
             "phase": "server", "service": "B", "t0": 102.4, "t1": 102.85,
             "attrs": {"stage": 1}},
            {"trace": tid, "span": "br", "parent": "bf", "name": "relay",
             "phase": "rescue", "service": "B", "t0": 102.45, "t1": 102.8,
             "attrs": {"stage": 1}},
        ],
    }
    evs = {
        "A": [
            {"ts": 100.35, "type": "peer.dead", "service": "A",
             "trace": tid, "attrs": {"peer": "dead:1", "stage": 1}, "seq": 0},
        ],
        "B": [
            {"ts": 102.5, "type": "session.rescue", "service": "B",
             "trace": tid, "attrs": {"holder": "dead:1"}, "seq": 0},
            # fleet context WITHOUT the trace id, inside the window
            {"ts": 102.6, "type": "lane.evict", "service": "B",
             "attrs": {"session": "other"}, "seq": 1},
            # far outside the window and traceless: excluded
            {"ts": 500.0, "type": "node.stop", "service": "B", "seq": 2},
        ],
    }
    mets = {
        "A": {"ts": 100.5, "service": "A",
              "gauges": {"hbm.frac": 0.97, "trace.dropped": 0.0},
              "counters": {}, "histograms": {}},
        "B": {"ts": 102.6, "service": "B",
              "gauges": {"trace.dropped": 0.0}, "counters": {},
              "histograms": {}},
    }
    for svc in ("client", "A", "B"):
        with open(tmp_path / f"{svc}.spans.jsonl", "w") as f:
            for s in spans[svc]:
                f.write(json.dumps(s) + "\n")
        if svc in evs:
            with open(tmp_path / f"{svc}.events.jsonl", "w") as f:
                for ev in evs[svc]:
                    f.write(json.dumps(ev) + "\n")
        if svc in mets:
            with open(tmp_path / f"{svc}.metrics.jsonl", "w") as f:
                f.write(json.dumps(mets[svc]) + "\n")
    return tid


def test_postmortem_report_assembly(tmp_path):
    tid = _incident_artifacts(tmp_path)
    report = postmortem.build_report(tid, [str(tmp_path)])
    # merged per-stage timeline, skew-corrected (B ran +2 s fast)
    assert set(report["timeline"]["stages"]) == {"0", "1"}
    assert report["offsets"]["B"] == pytest.approx(-2.0, abs=0.1)
    # events: the trace's own + windowed fleet context, never the
    # out-of-window traceless one; B's event ts got B's clock correction
    types = {ev["type"] for ev in report["events"]}
    assert types == {"peer.dead", "session.rescue", "lane.evict"}
    rescue = next(
        ev for ev in report["events"] if ev["type"] == "session.rescue"
    )
    assert rescue["ts"] == pytest.approx(100.5, abs=0.1)
    # interleaved log is time-sorted and mixes spans with events
    ts = [e["t"] for e in report["entries"]]
    assert ts == sorted(ts)
    assert {e["kind"] for e in report["entries"]} == {"span", "event"}
    # SLO: peer.dead fires on A (count rule), hbm breach fires from A's
    # metrics snapshot
    fired = {(f["service"], f["rule"]) for f in report["firing"]}
    assert ("A", "event:peer.dead == 0") in fired
    assert ("A", "hbm.frac < 0.95") in fired
    # first divergent hop: A's relay overlaps the peer.dead event
    div = report["first_divergent_hop"]
    assert div["service"] == "A" and div["phase"] == "relay"
    assert "peer.dead" in div["reason"]
    # unknown trace raises (and the CLI turns it into exit 1)
    with pytest.raises(ValueError, match="no spans"):
        postmortem.build_report("nope", [str(tmp_path)])


def test_postmortem_cli(tmp_path, capsys):
    from inferd_tpu.obs.__main__ import main

    tid = _incident_artifacts(tmp_path)
    out = tmp_path / "report.json"
    assert main(["postmortem", tid, str(tmp_path), "--out", str(out)]) == 0
    text = capsys.readouterr().out
    assert "first divergent hop" in text
    assert "session.rescue" in text
    data = json.load(open(out))
    assert data["trace"] == tid and data["firing"]
    assert main(["postmortem", "missing", str(tmp_path)]) == 1


# ------------------------------------------------------- console columns


def test_dashboard_health_hbm_compile_columns():
    from inferd_tpu.tools.dashboard import render_table

    sample = {
        0: {
            "10.0.0.2:6050": {
                "name": "n0", "load": 1, "cap": 4, "model": "m",
                "hbm": 0.62, "compiles": 7, "health": "ok",
            },
            "10.0.0.3:6050": {
                "name": "n1", "load": 0, "cap": 4, "model": "m",
                "health": "failing",
            },
        },
    }
    text = render_table(sample, ts=0.0)
    assert "hbm%" in text and "compiles" in text and "health" in text
    assert "62%" in text and " 7 " in text
    assert "ok" in text and "failing" in text


def test_collector_hbm_and_health_fields():
    from inferd_tpu.tools.collector import FIELDS, stage_rows

    assert "hbm_frac" in FIELDS and "health" in FIELDS
    sample = {
        0: {
            "a": {"load": 1, "cap": 4, "hbm": 0.5, "health": "ok"},
            "b": {"load": 0, "cap": 4, "hbm": 0.9, "health": "degraded"},
        },
        1: {"c": {"load": 0, "cap": 4}},
    }
    rows = stage_rows(sample, ts=1.0)
    assert rows[0]["hbm_frac"] == pytest.approx(0.9)  # worst replica
    assert rows[0]["health"] == "degraded"  # worst replica's verdict
    assert rows[1]["hbm_frac"] == "" and rows[1]["health"] == ""
    assert set(rows[0]) == set(FIELDS)


def test_collector_unknown_health_never_displaces_failing():
    """Mixed-version gossip: an unrecognized verdict string ranks above
    ok/degraded (suspicious) but must NEVER outrank a real failing
    replica in the worst-replica column."""
    from inferd_tpu.tools.collector import stage_rows

    sample = {
        0: {
            "a": {"load": 0, "cap": 4, "health": "failing"},
            "b": {"load": 0, "cap": 4, "health": "unknown-verdict"},
        },
        1: {
            "c": {"load": 0, "cap": 4, "health": "ok"},
            "d": {"load": 0, "cap": 4, "health": "unknown-verdict"},
        },
    }
    rows = stage_rows(sample, ts=1.0)
    assert rows[0]["health"] == "failing"
    assert rows[1]["health"] == "unknown-verdict"


def test_default_rules_survive_event_kill_switch():
    """INFERD_EVENTS=0 makes the node pass events=None to evaluate
    (node._health_state): the event-rate rules must SKIP, but the
    metric-only DEFAULT_RULES keep evaluating — the journal kill switch
    sheds overhead without blinding the SLO engine."""
    snap = {
        "gauges": {"queue.depth": 20.0, "trace.dropped": 0.0},
        "histograms": {"hop.relay_ms": {"p99_ms": 100.0}},
    }
    v = health.evaluate(health.DEFAULT_RULES, snap, events=None)
    n_event = sum(
        1 for r in health.DEFAULT_RULES if r.signal.startswith("event:")
    )
    n_burn = sum(
        1 for r in health.DEFAULT_RULES if r.signal.startswith("burn:")
    )
    n_peer = sum(
        1 for r in health.DEFAULT_RULES if r.signal.startswith("peer:")
    )
    assert v["evaluated"] == 3  # queue.depth, trace.dropped, hop p99
    # every event rule (events=None), every burn rule (histories=None),
    # every peer rule (no peers passed), plus the absent hbm.frac and
    # perf.regression gauges
    assert v["skipped"] == n_event + n_burn + n_peer + 2
    assert {f["rule"] for f in v["firing"]} == {"queue.depth < 16"}
    assert v["status"] == "degraded"
