"""--compile-cache wiring (SURVEY §7 step 7; BASELINE config 4's timing
half): the flag must create the directory, point jax at it, and a jit run
must populate it; a second process sharing the directory warm-starts from
the cached executables.

Everything runs in SUBPROCESSES: the pytest process itself must never
enable the persistent cache — XLA:CPU AOT artifacts recorded by one
process can fail feature validation when reloaded by a sibling on the
same host and risk SIGILL (see the conftest note; that is also why the
serving flag is opt-in rather than default)."""

import os
import subprocess
import sys

import pytest

SCRIPT = r"""
import os, sys
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import jax
jax.config.update("jax_platforms", "cpu")
from inferd_tpu.utils.platform import enable_compile_cache
enable_compile_cache(sys.argv[1])
import jax.numpy as jnp
out = jax.jit(lambda x: (x * 3 + 1).sum())(jnp.arange(1017.0))
print("RESULT", float(out))
"""


def _run(cache_dir: str):
    return subprocess.run(
        [sys.executable, "-c", SCRIPT, cache_dir],
        capture_output=True, text=True, timeout=300,
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
    )


def test_compile_cache_populates_and_warm_starts(tmp_path):
    d = str(tmp_path / "cc")
    r1 = _run(d)
    assert r1.returncode == 0, r1.stderr[-800:]
    assert "RESULT" in r1.stdout
    entries = os.listdir(d)
    assert entries, "compilation cache dir empty after a jit run"

    # warm start: a SECOND process sharing the dir must produce the same
    # result from the cached executable. XLA:CPU's AOT loader is known to
    # reject same-host artifacts on feature-validation grounds in some
    # environments (conftest note) — that exact failure mode skips rather
    # than fails, anything else is a real bug.
    r2 = _run(d)
    if r2.returncode != 0:
        blob = (r2.stderr + r2.stdout)[-2000:]
        if "XLA:CPU" in blob or "Machine type" in blob or "cpu_aot" in blob:
            pytest.skip(f"XLA:CPU AOT reload rejected on this host: {blob[-200:]}")
        raise AssertionError(blob)
    assert r2.stdout.strip().split()[-1] == r1.stdout.strip().split()[-1]


def test_run_node_compile_cache_flag():
    from inferd_tpu.tools.run_node import build_parser

    args = build_parser().parse_args(
        ["--model", "tiny", "--compile-cache", "/tmp/ccache"]
    )
    assert args.compile_cache == "/tmp/ccache"


def test_compile_cache_hits_counted_across_processes(tmp_path):
    """The substrate-independent witness (VERDICT r04 #6): the SECOND
    process records persistent-cache HITS via jax.monitoring — an
    auditable number showing re-jit was avoided, not inferred from
    timing. Uses bench.py's _CC_SCRIPT (one definition — the same code
    the driver's artifact leg runs) on the tiny model. (Where XLA:CPU
    rejects the AOT reload, hits stay 0 and the test skips — anything
    else is a real bug. No timing assert: sub-second compiles on a
    timeshared 1-core host would flake; the hit count IS the proof.)"""
    import json as jsonlib

    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    import bench

    d = str(tmp_path / "cc")
    outs = []
    for _ in range(2):
        r = subprocess.run(
            [sys.executable, "-c", bench._CC_SCRIPT, d, "cpu", "tiny"],
            capture_output=True, text=True, timeout=300,
            env=dict(os.environ, JAX_PLATFORMS="cpu"),
            cwd=os.path.dirname(os.path.abspath(bench.__file__)),
        )
        assert r.returncode == 0, r.stderr[-800:]
        outs.append(jsonlib.loads(r.stdout.strip().splitlines()[-1]))
    cold, warm = outs
    assert cold["hits"] == 0
    if warm["hits"] == 0:
        pytest.skip("persistent-cache reload unavailable on this host")
    assert warm["hits"] >= 1
