"""--compile-cache wiring (SURVEY §7 step 7; BASELINE config 4's timing
half): the flag must create the directory, point jax at it, and a jit run
must populate it; a second process sharing the directory warm-starts from
the cached executables.

Everything runs in SUBPROCESSES: the pytest process itself must never
enable the persistent cache — XLA:CPU AOT artifacts recorded by one
process can fail feature validation when reloaded by a sibling on the
same host and risk SIGILL (see the conftest note; that is also why the
serving flag is opt-in rather than default)."""

import os
import subprocess
import sys

import pytest

SCRIPT = r"""
import os, sys
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import jax
jax.config.update("jax_platforms", "cpu")
from inferd_tpu.utils.platform import enable_compile_cache
enable_compile_cache(sys.argv[1])
import jax.numpy as jnp
out = jax.jit(lambda x: (x * 3 + 1).sum())(jnp.arange(1017.0))
print("RESULT", float(out))
"""


def _run(cache_dir: str):
    return subprocess.run(
        [sys.executable, "-c", SCRIPT, cache_dir],
        capture_output=True, text=True, timeout=300,
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
    )


def test_compile_cache_populates_and_warm_starts(tmp_path):
    d = str(tmp_path / "cc")
    r1 = _run(d)
    assert r1.returncode == 0, r1.stderr[-800:]
    assert "RESULT" in r1.stdout
    entries = os.listdir(d)
    assert entries, "compilation cache dir empty after a jit run"

    # warm start: a SECOND process sharing the dir must produce the same
    # result from the cached executable. XLA:CPU's AOT loader is known to
    # reject same-host artifacts on feature-validation grounds in some
    # environments (conftest note) — that exact failure mode skips rather
    # than fails, anything else is a real bug.
    r2 = _run(d)
    if r2.returncode != 0:
        blob = (r2.stderr + r2.stdout)[-2000:]
        if "XLA:CPU" in blob or "Machine type" in blob or "cpu_aot" in blob:
            pytest.skip(f"XLA:CPU AOT reload rejected on this host: {blob[-200:]}")
        raise AssertionError(blob)
    assert r2.stdout.strip().split()[-1] == r1.stdout.strip().split()[-1]


def test_run_node_compile_cache_flag():
    from inferd_tpu.tools.run_node import build_parser

    args = build_parser().parse_args(
        ["--model", "tiny", "--compile-cache", "/tmp/ccache"]
    )
    assert args.compile_cache == "/tmp/ccache"
