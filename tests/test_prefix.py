# jaxlint: file-disable=J003 -- test code: loops here sync per-iteration to ASSERT on values; they are verification loops, not serving hot paths
"""Prefix caching: engine pin_prefix, executor fork_session, and the
client-driven distributed session fork (swarm relay + chain hub-and-spoke).

The reference has no prefix reuse at all — every generation re-prefills its
full prompt (/root/reference/models/qwen3/client/client.py:217-236). Here a
pinned prefix's per-stage KV is forked stage-locally into each new session
(inner stages never see tokens, so the client — which does — drives it)."""

import asyncio

import jax
import numpy as np
import pytest

from inferd_tpu.client.chain_client import ChainClient
from inferd_tpu.client.swarm_client import SwarmClient
from inferd_tpu.config import TINY, SamplingConfig
from inferd_tpu.control.dht import SwarmDHT
from inferd_tpu.core.generate import Engine
from inferd_tpu.models import qwen3
from inferd_tpu.parallel.stages import (
    Manifest,
    StageSpec,
    extract_stage_params,
    split_and_save,
)
from inferd_tpu.runtime.executor import Qwen3StageExecutor
from inferd_tpu.runtime.node import Node, NodeInfo

BASE = 18800  # distinct port block from test_batch_node (18700)

PREFIX = [3, 7, 11, 19, 5, 2, 17, 13]
GREEDY = SamplingConfig(temperature=0.0)


@pytest.fixture(scope="module")
def tiny_params():
    return qwen3.init_params(TINY, jax.random.PRNGKey(0))


# ---------------------------------------------------------------- engine


def test_engine_pin_parity(tiny_params):
    """Pinned-prefix generation == cold generation, token for token."""
    cold = Engine(TINY, tiny_params, max_len=64, sampling_cfg=GREEDY)
    warm = Engine(TINY, tiny_params, max_len=64, sampling_cfg=GREEDY)
    warm.pin_prefix(PREFIX)
    for tail in ([4, 9], [8], [6, 1, 2, 12]):
        prompt = PREFIX + tail
        assert warm.generate(prompt, 5) == cold.generate(prompt, 5)


def test_engine_pin_exact_prompt(tiny_params):
    """Prompt == pinned prefix exactly: first token comes from the stored
    pin logits, no prefill at all."""
    cold = Engine(TINY, tiny_params, max_len=64, sampling_cfg=GREEDY)
    warm = Engine(TINY, tiny_params, max_len=64, sampling_cfg=GREEDY)
    warm.pin_prefix(PREFIX)
    assert warm.generate(PREFIX, 5) == cold.generate(PREFIX, 5)


def test_engine_pin_reusable_and_lru(tiny_params):
    """A pin survives repeated reuse (donation must never eat the snapshot)
    and the pin store is LRU-capped."""
    eng = Engine(TINY, tiny_params, max_len=64, sampling_cfg=GREEDY)
    eng.pin_prefix(PREFIX)
    first = eng.generate(PREFIX + [4], 4)
    for _ in range(2):
        assert eng.generate(PREFIX + [4], 4) == first
    eng.max_pins = 2
    for i in range(3):
        eng.pin_prefix([10 + i, 20 + i])
    assert len(eng._pins) == 2
    # evicted pin falls back to the cold path, still correct
    cold = Engine(TINY, tiny_params, max_len=64, sampling_cfg=GREEDY)
    assert eng.generate(PREFIX + [4], 4) == cold.generate(PREFIX + [4], 4)


def test_engine_non_matching_prompt_unaffected(tiny_params):
    cold = Engine(TINY, tiny_params, max_len=64, sampling_cfg=GREEDY)
    warm = Engine(TINY, tiny_params, max_len=64, sampling_cfg=GREEDY)
    warm.pin_prefix(PREFIX)
    prompt = [9, 9, 9]  # does not start with the pin
    assert warm.generate(prompt, 5) == cold.generate(prompt, 5)


# -------------------------------------------------------------- executor


def test_executor_fork_parity(tiny_params):
    """Fork at a prefix + prefill the tail == fresh full prefill."""
    cfg = TINY
    spec = StageSpec(0, 1, 0, cfg.num_layers - 1)
    ex = Qwen3StageExecutor(
        cfg, spec, extract_stage_params(tiny_params, cfg, spec), max_len=64
    )
    tail = [4, 9, 6]
    # parent: prefill the prefix (then decode a bit — fork must still take
    # only the first prefix_len slots)
    out_p = ex.process("parent", {"tokens": np.asarray([PREFIX]), "start_pos": 0})
    ex.process(
        "parent",
        {"tokens": np.asarray([[int(np.argmax(out_p["logits"][0]))]]),
         "start_pos": len(PREFIX)},
    )
    assert ex.fork_session("child", "parent", len(PREFIX))
    out_c = ex.process(
        "child",
        {"tokens": np.asarray([tail]), "start_pos": len(PREFIX),
         "real_len": len(tail)},
    )
    out_f = ex.process(
        "fresh", {"tokens": np.asarray([PREFIX + tail]), "start_pos": 0}
    )
    np.testing.assert_allclose(
        out_c["logits"], out_f["logits"], rtol=2e-5, atol=2e-5
    )


def test_executor_fork_misses(tiny_params):
    cfg = TINY
    spec = StageSpec(0, 1, 0, cfg.num_layers - 1)
    ex = Qwen3StageExecutor(
        cfg, spec, extract_stage_params(tiny_params, cfg, spec), max_len=64
    )
    assert not ex.fork_session("c", "nope", 4)  # unknown parent
    ex.process("p", {"tokens": np.asarray([[1, 2]]), "start_pos": 0})
    assert not ex.fork_session("c", "p", 5)  # parent shorter than prefix
    assert not ex.fork_session("c", "p", 0)  # degenerate


# ------------------------------------------- batched / mesh executors


def test_batched_executor_fork_parity(tiny_params):
    """Lane fork on the continuous-batching executor == fresh prefill."""
    from inferd_tpu.runtime.batch_executor import BatchedExecutor

    ex = BatchedExecutor(TINY, tiny_params, lanes=4, max_len=64)
    tail = [4, 9, 6]
    ex.process("parent", {"tokens": np.asarray([PREFIX]), "start_pos": 0})
    assert ex.fork_session("child", "parent", len(PREFIX))
    out_c = ex.process(
        "child",
        {"tokens": np.asarray([tail]), "start_pos": len(PREFIX),
         "real_len": len(tail)},
    )
    out_f = ex.process(
        "fresh", {"tokens": np.asarray([PREFIX + tail]), "start_pos": 0}
    )
    np.testing.assert_allclose(
        out_c["logits"], out_f["logits"], rtol=2e-5, atol=2e-5
    )
    # decode continues on the forked lane
    tok = int(np.argmax(out_c["logits"][0]))
    out_d = ex.process(
        "child",
        {"tokens": np.asarray([[tok]]), "start_pos": len(PREFIX) + len(tail)},
    )
    assert out_d["logits"].shape == out_f["logits"].shape
    assert not ex.fork_session("c2", "ghost", 3)


def test_batched_executor_fork_protects_parent(tiny_params):
    """With every lane taken, forking must not LRU-evict the parent to make
    room for its own child."""
    from inferd_tpu.runtime.batch_executor import BatchedExecutor

    ex = BatchedExecutor(TINY, tiny_params, lanes=2, max_len=64)
    ex.process("parent", {"tokens": np.asarray([PREFIX]), "start_pos": 0})
    ex.process("other", {"tokens": np.asarray([[1, 2]]), "start_pos": 0})
    assert ex.fork_session("child", "parent", len(PREFIX))  # evicts "other"
    assert "parent" in ex
    assert "child" in ex


def test_mesh_executor_fork_parity(tiny_params):
    """Slot fork on the in-mesh pipelined executor == fresh prefill (the
    copy is shard-local per pp rank)."""
    import jax

    from inferd_tpu.parallel.mesh import MeshPlan
    from inferd_tpu.runtime.mesh_executor import MeshExecutor

    ex = MeshExecutor(
        TINY, tiny_params, MeshPlan(pp=2), num_slots=4, max_len=64,
        devices=jax.devices()[:2],
    )
    tail = [4, 9, 6]
    ex.process("parent", {"tokens": np.asarray([PREFIX]), "start_pos": 0})
    assert ex.fork_session("child", "parent", len(PREFIX))
    out_c = ex.process(
        "child",
        {"tokens": np.asarray([tail]), "start_pos": len(PREFIX),
         "real_len": len(tail)},
    )
    out_f = ex.process(
        "fresh", {"tokens": np.asarray([PREFIX + tail]), "start_pos": 0}
    )
    np.testing.assert_allclose(
        out_c["logits"], out_f["logits"], rtol=2e-5, atol=2e-5
    )
    assert not ex.fork_session("c2", "ghost", 3)


# ------------------------------------------------------------------ swarm


def _mk_node(idx, stage, num_stages, *, parts, bootstrap_idx):
    info = NodeInfo(
        name=f"px{idx}", host="127.0.0.1", port=BASE + idx,
        stage=stage, num_stages=num_stages, capacity=4, model_name="tiny",
    )
    dht = SwarmDHT(
        info.node_id, BASE + 100 + idx,
        bootstrap=[("127.0.0.1", BASE + 100 + bootstrap_idx)]
        if idx != bootstrap_idx else [],
        host="127.0.0.1", gossip_period_s=0.05, ttl_s=1.5,
    )
    return Node(
        info, TINY, parts, dht, backend="qwen3", max_len=64,
        rebalance_period_s=600.0,
    )


async def _start_all(nodes):
    for n in nodes:
        await n.start()

    async def converged():
        for n in nodes:
            m = n.dht.get_all(n.info.num_stages)
            if any(not m[s] for s in range(n.info.num_stages)):
                return False
        return True

    for _ in range(100):
        if await converged():
            return
        await asyncio.sleep(0.05)
    raise TimeoutError("swarm did not converge")


@pytest.fixture(scope="module")
def tiny_parts(tmp_path_factory, tiny_params):
    parts = tmp_path_factory.mktemp("parts_prefix")
    split_and_save(tiny_params, TINY, Manifest.even_split("tiny", 2), str(parts))
    return str(parts)


@pytest.mark.asyncio
async def test_swarm_fork_e2e(tiny_parts, tiny_params):
    """Pinned client over a 2-stage swarm: token parity with the engine,
    forks actually taken on both stages, and prefix tokens prefilled once."""
    nodes = [
        _mk_node(i, i, 2, parts=tiny_parts, bootstrap_idx=0) for i in range(2)
    ]
    await _start_all(nodes)
    try:
        engine = Engine(TINY, tiny_params, max_len=64, sampling_cfg=GREEDY)
        tails = ([4, 9], [8, 6, 1])
        expected = [engine.generate(PREFIX + list(t), 5) for t in tails]
        async with SwarmClient(
            [("127.0.0.1", BASE + 0)], sampling=GREEDY, prefill_chunk=4
        ) as c:
            await c.pin_prefix(PREFIX)
            got = [await c.generate_ids(PREFIX + list(t), 5) for t in tails]
        assert got == expected
        for n in nodes:
            snap = n.metrics.snapshot()
            assert snap["counters"].get("fork.ok", 0) >= len(tails)
    finally:
        for n in nodes:
            await n.stop()


@pytest.mark.asyncio
async def test_swarm_fork_fallback_after_parent_eviction(tiny_parts, tiny_params):
    """Ending the pinned session behind the client's back: generation still
    succeeds via the full-prefill fallback and the stale pin is dropped."""
    nodes = [
        _mk_node(10 + i, i, 2, parts=tiny_parts, bootstrap_idx=10)
        for i in range(2)
    ]
    await _start_all(nodes)
    try:
        engine = Engine(TINY, tiny_params, max_len=64, sampling_cfg=GREEDY)
        prompt = PREFIX + [4, 9]
        expected = engine.generate(prompt, 5)
        async with SwarmClient(
            [("127.0.0.1", BASE + 10)], sampling=GREEDY
        ) as c:
            await c.pin_prefix(PREFIX)
            parent_sid, _ = c._pins[tuple(PREFIX)]
            await c._end_session(parent_sid)  # simulate server-side eviction
            got = await c.generate_ids(prompt, 5)
            assert got == expected
            assert tuple(PREFIX) not in c._pins
    finally:
        for n in nodes:
            await n.stop()


@pytest.mark.asyncio
async def test_server_side_generate(tiny_parts, tiny_params):
    """/generate: the node runs the token loop against itself — one round
    trip returns what the client-side loop returns, greedy and pinned."""
    nodes = [
        _mk_node(30 + i, i, 2, parts=tiny_parts, bootstrap_idx=30)
        for i in range(2)
    ]
    await _start_all(nodes)
    try:
        engine = Engine(TINY, tiny_params, max_len=64, sampling_cfg=GREEDY)
        prompt = PREFIX + [4, 9]
        expected = engine.generate(prompt, 5)
        async with SwarmClient(
            [("127.0.0.1", BASE + 30)], sampling=GREEDY, timeout_s=60.0
        ) as c:
            got = await c.generate_server_side(prompt, max_new_tokens=5)
            assert got == expected
            # pinned variant: the node pins the prefix and forks it
            got2 = await c.generate_server_side(
                prompt, max_new_tokens=5, pin_prefix_len=len(PREFIX)
            )
            assert got2 == expected
            got3 = await c.generate_server_side(
                prompt, max_new_tokens=5, pin_prefix_len=len(PREFIX)
            )
            assert got3 == expected
        # the second pinned call forked the node-held pin on both stages
        assert any(
            n.metrics.snapshot()["counters"].get("fork.ok", 0) >= 1 for n in nodes
        )
        # entering at the WRONG node still works (relay to stage 0)
        async with SwarmClient(
            [("127.0.0.1", BASE + 31)], sampling=GREEDY, timeout_s=60.0
        ) as c:
            got = await c.generate_server_side(prompt, max_new_tokens=5)
        assert got == expected
    finally:
        for n in nodes:
            await n.stop()


@pytest.mark.asyncio
async def test_server_side_generate_logprobs(tiny_parts, tiny_params):
    """/generate with logprobs=true returns per-token model log-
    probabilities that match re-scoring the emitted sequence with the
    single-process model (log-softmax of the raw logits at each step)."""
    import jax.numpy as jnp
    import numpy as np

    from inferd_tpu.models import qwen3

    nodes = [
        _mk_node(90 + i, i, 2, parts=tiny_parts, bootstrap_idx=90)
        for i in range(2)
    ]
    await _start_all(nodes)
    try:
        prompt = [3, 7, 11, 5]
        async with SwarmClient(
            [("127.0.0.1", BASE + 90)], sampling=GREEDY, timeout_s=60.0
        ) as c:
            lps: list = []
            tops: list = []
            ids = await c.generate_server_side(
                prompt, max_new_tokens=5, logprob_sink=lps,
                top_logprobs=3, top_sink=tops,
            )
        assert len(lps) == len(ids) == len(tops) == 5
        # engine parity: same greedy tokens, same logprobs, same top-3
        eng = Engine(TINY, tiny_params, max_len=64, sampling_cfg=GREEDY)
        elps: list = []
        etops: list = []
        eids = eng.generate(
            prompt, max_new_tokens=5, logprob_sink=elps, top_n=3,
            top_sink=etops,
        )
        assert ids == eids
        np.testing.assert_allclose(lps, elps, atol=1e-3, rtol=1e-4)
        for (ti, tl), (ei, el) in zip(tops, etops):
            assert list(ti) == list(ei)
            np.testing.assert_allclose(tl, el, atol=1e-3, rtol=1e-4)
        # re-score: full forward over prompt + emitted ids; the logprob of
        # ids[i] is log_softmax(logits at position len(prompt)-1+i)[ids[i]]
        toks = jnp.asarray([prompt + ids[:-1]], jnp.int32)
        logits, _, _ = qwen3.forward(tiny_params, TINY, toks)
        lsm = np.asarray(
            logits - jax.scipy.special.logsumexp(logits, axis=-1, keepdims=True)
        )
        for i, (t, lp) in enumerate(zip(ids, lps)):
            want = float(lsm[0, len(prompt) - 1 + i, t])
            assert abs(lp - want) < 1e-3, f"token {i}: {lp} vs {want}"
            assert lp <= 0.0
    finally:
        for n in nodes:
            await n.stop()


@pytest.mark.asyncio
async def test_server_side_generate_stream(tiny_parts, tiny_params):
    """Streaming /generate: tokens arrive one ndjson line at a time and
    match both the final ids and the engine."""
    nodes = [
        _mk_node(40 + i, i, 2, parts=tiny_parts, bootstrap_idx=40)
        for i in range(2)
    ]
    await _start_all(nodes)
    try:
        engine = Engine(TINY, tiny_params, max_len=64, sampling_cfg=GREEDY)
        prompt = PREFIX + [4, 9]
        expected = engine.generate(prompt, 5)
        streamed = []
        async with SwarmClient(
            [("127.0.0.1", BASE + 40)], sampling=GREEDY, timeout_s=60.0
        ) as c:
            got = await c.generate_server_side_stream(
                prompt, streamed.append, max_new_tokens=5
            )
        assert got == expected
        assert streamed == expected  # every token arrived incrementally
    finally:
        for n in nodes:
            await n.stop()


@pytest.mark.asyncio
async def test_server_side_generate_concurrent_sampling(tiny_parts, tiny_params):
    """Two concurrent /generate requests with DIFFERENT sampling configs:
    the node's shared self-client must not let one request's sampling bleed
    into the other (per-call sampling pass-through)."""
    nodes = [
        _mk_node(60 + i, i, 2, parts=tiny_parts, bootstrap_idx=60)
        for i in range(2)
    ]
    await _start_all(nodes)
    try:
        engine_g = Engine(TINY, tiny_params, max_len=64, sampling_cfg=GREEDY)
        hot = SamplingConfig(temperature=0.9, top_k=5, top_p=0.9)
        prompt = PREFIX + [4, 9]
        expected_greedy = engine_g.generate(prompt, 6)

        from inferd_tpu.client.base import sample_np

        async with SwarmClient(
            [("127.0.0.1", BASE + 60)], sampling=GREEDY, timeout_s=60.0
        ) as c:
            pairs = await asyncio.gather(
                c.generate_server_side(prompt, max_new_tokens=6, seed=0),
                c.generate_server_side(
                    prompt, max_new_tokens=6, seed=3, sampling=hot
                ),
                c.generate_server_side(prompt, max_new_tokens=6, seed=0),
            )
        greedy1, sampled, greedy2 = pairs
        assert greedy1 == expected_greedy == greedy2
        # the hot request sampled from ITS config: reproduce via the client
        # sampler over a locally-driven session would need logits; instead
        # assert determinism of the hot path itself (same seed -> same out)
        async with SwarmClient(
            [("127.0.0.1", BASE + 60)], sampling=GREEDY, timeout_s=60.0
        ) as c:
            sampled2 = await c.generate_server_side(
                prompt, max_new_tokens=6, seed=3, sampling=hot
            )
        assert sampled == sampled2
    finally:
        for n in nodes:
            await n.stop()


@pytest.mark.asyncio
async def test_speculative_server_side_generate(tiny_params):
    """--spec-draft-layers: greedy /generate takes the self-drafting
    propose/verify path and stays token-exact with the plain engine."""
    from inferd_tpu.parallel.stages import Manifest, split_and_save
    import tempfile

    work = tempfile.mkdtemp(prefix="prefix_spec_")
    split_and_save(tiny_params, TINY, Manifest.even_split("tiny", 1), work)
    info = NodeInfo(
        name="sp0", host="127.0.0.1", port=BASE + 70,
        stage=0, num_stages=1, capacity=4, model_name="tiny",
    )
    dht = SwarmDHT(
        info.node_id, BASE + 170, bootstrap=[], host="127.0.0.1",
        gossip_period_s=0.05, ttl_s=1.5,
    )
    node = Node(
        info, TINY, work, dht, backend="qwen3", max_len=64,
        rebalance_period_s=600.0, spec_draft_layers=2, spec_k=3,
    )
    await node.start()
    try:
        engine = Engine(TINY, tiny_params, max_len=64, sampling_cfg=GREEDY)
        prompt = [3, 7, 11, 19, 5]
        expected = engine.generate(prompt, 8)
        async with SwarmClient(
            [("127.0.0.1", BASE + 70)], sampling=GREEDY, timeout_s=60.0
        ) as c:
            resp = await c._post(
                "/generate",
                {"prompt_ids": prompt, "max_new_tokens": 8,
                 "sampling": {"temperature": 0.0}},
            )
        assert resp["speculative"] is True
        assert 0.0 <= resp["draft_acceptance"] <= 1.0
        assert [int(t) for t in resp["ids"]] == expected
        assert node.metrics.snapshot()["counters"].get("generate.speculative", 0) >= 1
        # logprobs + top-N ride the speculative path (the verify chunk's
        # TARGET logits) and match the plain engine exactly
        elps: list = []
        etops: list = []
        engine.generate(
            prompt, 8, logprob_sink=elps, top_n=3, top_sink=etops
        )
        async with SwarmClient(
            [("127.0.0.1", BASE + 70)], sampling=GREEDY, timeout_s=60.0
        ) as c:
            resp_lp = await c._post(
                "/generate",
                {"prompt_ids": prompt, "max_new_tokens": 8,
                 "logprobs": True, "top_logprobs": 3,
                 "sampling": {"temperature": 0.0}},
            )
        assert resp_lp["speculative"] is True
        np.testing.assert_allclose(resp_lp["logprobs"], elps, atol=1e-3, rtol=1e-4)
        for (ti, tl), (ei, el) in zip(resp_lp["top_logprobs"], etops):
            assert [int(x) for x in ti] == list(ei)
            np.testing.assert_allclose(tl, el, atol=1e-3, rtol=1e-4)
        # sampled requests take the rejection-sampled speculative engine
        # (one engine per sampling config, LRU-capped) — the response says
        # so and carries the acceptance rate; /stats accumulates the
        # production counters
        async with SwarmClient(
            [("127.0.0.1", BASE + 70)], sampling=GREEDY, timeout_s=120.0
        ) as c:
            resp2 = await c._post(
                "/generate",
                {"prompt_ids": prompt, "max_new_tokens": 4, "seed": 1,
                 "sampling": {"temperature": 0.8}},
            )
        assert resp2["speculative"] is True
        assert 0.0 <= resp2["spec_accept_rate"] <= 1.0
        assert len(resp2["ids"]) == 4
        snap = node.metrics.snapshot()["counters"]
        assert snap.get("spec.proposed", 0) > 0
        assert snap.get("spec.accepted", 0) <= snap.get("spec.proposed", 0)
        # sampled + logprobs falls back to the regular loop (the rejection
        # step has no per-token logprob trail)
        async with SwarmClient(
            [("127.0.0.1", BASE + 70)], sampling=GREEDY, timeout_s=120.0
        ) as c:
            resp3 = await c._post(
                "/generate",
                {"prompt_ids": prompt, "max_new_tokens": 4, "seed": 1,
                 "logprobs": True, "sampling": {"temperature": 0.8}},
            )
        assert "speculative" not in resp3
        assert len(resp3["logprobs"]) == len(resp3["ids"])
    finally:
        await node.stop()


@pytest.mark.asyncio
@pytest.mark.slow
async def test_speculative_sampled_distribution_over_http(tiny_params):
    """Sampled /generate through the speculative path is DISTRIBUTED as
    target-only warped sampling (the rejection scheme's guarantee, pinned
    end-to-end through the HTTP surface): the empirical first-token
    distribution over many seeds matches the target's warped probabilities
    in total variation, and a fixed seed is deterministic. (The rejection
    step's own exactness is pinned at the engine level by
    test_speculative.test_sampled_distribution_matches_target; this
    asserts the serving wiring — the per-request sampling config must
    reach the engine's warp.)"""
    from inferd_tpu.parallel.stages import Manifest, split_and_save
    from inferd_tpu.core import sampling as samplib
    import jax
    import jax.numpy as jnp
    import tempfile

    work = tempfile.mkdtemp(prefix="prefix_spec_tv_")
    split_and_save(tiny_params, TINY, Manifest.even_split("tiny", 1), work)
    info = NodeInfo(
        name="sptv0", host="127.0.0.1", port=BASE + 71,
        stage=0, num_stages=1, capacity=4, model_name="tiny",
    )
    dht = SwarmDHT(
        info.node_id, BASE + 171, bootstrap=[], host="127.0.0.1",
        gossip_period_s=0.05, ttl_s=1.5,
    )
    node = Node(
        info, TINY, work, dht, backend="qwen3", max_len=64,
        rebalance_period_s=600.0, spec_draft_layers=2, spec_k=3,
    )
    await node.start()
    try:
        prompt = [3, 17, 42, 9]
        temp, top_k, top_p = 1.2, 5, 0.9
        # the target's warped next-token distribution after the prompt
        logits, _, _ = qwen3.forward(
            tiny_params, TINY, jnp.asarray([prompt], jnp.int32)
        )
        want = np.asarray(
            jax.nn.softmax(
                samplib.warped_logits(
                    logits[:, len(prompt) - 1], temp, top_k, top_p
                )
            )
        )[0]

        counts = np.zeros(TINY.vocab_size)
        trials = 250
        async with SwarmClient(
            [("127.0.0.1", BASE + 71)], sampling=GREEDY, timeout_s=120.0
        ) as c:
            for seed in range(trials):
                r = await c._post(
                    "/generate",
                    {"prompt_ids": prompt, "max_new_tokens": 1, "seed": seed,
                     "sampling": {"temperature": temp, "top_k": top_k,
                                  "top_p": top_p}},
                )
                assert r["speculative"] is True
                counts[int(r["ids"][0])] += 1
            tv = 0.5 * np.abs(counts / trials - want).sum()
            assert tv < 0.12, f"TV distance {tv}"

            # fixed seed => identical stream (deterministic replay)
            a = await c._post(
                "/generate",
                {"prompt_ids": prompt, "max_new_tokens": 6, "seed": 7,
                 "sampling": {"temperature": temp, "top_k": top_k,
                              "top_p": top_p}},
            )
            b = await c._post(
                "/generate",
                {"prompt_ids": prompt, "max_new_tokens": 6, "seed": 7,
                 "sampling": {"temperature": temp, "top_k": top_k,
                              "top_p": top_p}},
            )
            assert a["ids"] == b["ids"] and len(a["ids"]) == 6
    finally:
        await node.stop()


@pytest.mark.asyncio
async def test_batched_node_fork_e2e(tiny_params):
    """Pinned client against a --batch-lanes node: the fork lands in a
    lane (BatchedEngine.fork_lane) and generations match the engine."""
    from inferd_tpu.parallel.stages import Manifest, split_and_save
    import tempfile

    work = tempfile.mkdtemp(prefix="prefix_batch_")
    split_and_save(tiny_params, TINY, Manifest.even_split("tiny", 1), work)
    info = NodeInfo(
        name="pb0", host="127.0.0.1", port=BASE + 50,
        stage=0, num_stages=1, capacity=4, model_name="tiny",
    )
    dht = SwarmDHT(
        info.node_id, BASE + 150, bootstrap=[], host="127.0.0.1",
        gossip_period_s=0.05, ttl_s=1.5,
    )
    node = Node(
        info, TINY, work, dht, backend="qwen3", max_len=64,
        rebalance_period_s=600.0, batch_lanes=3,
    )
    await node.start()
    try:
        engine = Engine(TINY, tiny_params, max_len=64, sampling_cfg=GREEDY)
        prompt = PREFIX + [4, 9]
        expected = engine.generate(prompt, 5)
        async with SwarmClient(
            [("127.0.0.1", BASE + 50)], sampling=GREEDY
        ) as c:
            await c.pin_prefix(PREFIX)
            got = [await c.generate_ids(prompt, 5) for _ in range(2)]
        assert got == [expected, expected]
        assert node.metrics.snapshot()["counters"].get("fork.ok", 0) >= 2
    finally:
        await node.stop()


@pytest.mark.asyncio
async def test_chain_fork_e2e(tiny_parts, tiny_params):
    """ChainClient (hub-and-spoke, relay=False) forks every stage directly."""
    nodes = [
        _mk_node(20 + i, i, 2, parts=tiny_parts, bootstrap_idx=20)
        for i in range(2)
    ]
    await _start_all(nodes)
    try:
        engine = Engine(TINY, tiny_params, max_len=64, sampling_cfg=GREEDY)
        prompt = PREFIX + [4, 9]
        expected = engine.generate(prompt, 5)
        async with ChainClient(
            [("127.0.0.1", BASE + 20), ("127.0.0.1", BASE + 21)], sampling=GREEDY
        ) as c:
            await c.pin_prefix(PREFIX)
            got = await c.generate_ids(prompt, 5)
        assert got == expected
        for n in nodes:
            snap = n.metrics.snapshot()
            assert snap["counters"].get("fork.ok", 0) >= 1
    finally:
        for n in nodes:
            await n.stop()
