"""Test harness: force JAX onto a virtual 8-device CPU platform so mesh /
sharding / collective tests run without TPU hardware (the driver separately
dry-runs the multi-chip path; see __graft_entry__.dryrun_multichip).

Note: this environment's sitecustomize registers an `axon` TPU-tunnel PJRT
plugin and sets jax_platforms="axon,cpu" — initializing it dials the TPU
relay and can block for minutes. Tests must never touch it, so we both set
the env vars (effective if jax isn't imported yet) and override the jax
config (effective even after the plugin hook ran).
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
# Tests assume the FROZEN `auto` dispatch heuristics (ops/attention,
# ops/quant). A committed bench_artifacts/autotune.json would silently
# flip them per-chip (that's its job in serving), so point the registry
# at a path that never exists; autotune tests override per-test.
os.environ.setdefault("INFERD_AUTOTUNE", os.devnull + ".absent-autotune.json")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# NOTE: do NOT enable the JAX persistent compilation cache here. It was
# tried (halves warm re-runs) and reverted: XLA:CPU AOT results recorded by
# one process can fail feature validation when reloaded by another on the
# same host ("Machine type used for XLA:CPU compilation doesn't match...",
# cpu_aot_loader.cc) and risk SIGILL mid-test — observed crashing a node
# subprocess in tests/test_batch_node.py.

import asyncio  # noqa: E402
import inspect  # noqa: E402

import pytest  # noqa: E402

from inferd_tpu.utils import lockwatch  # noqa: E402

# Suite-wide lock-order sanitizer (docs/ANALYSIS.md): every named lock
# the runtime constructs during tests becomes an order-checking proxy,
# and a blocking acquisition that contradicts lockwatch.LOCK_ORDER
# RAISES — an inversion anywhere in tier-1 is a test failure, not a
# latent production deadlock. Kill switch: INFERD_LOCKWATCH=0 (e.g. to
# bisect whether a failure is the sanitizer's). instrument() must run at
# import time, before any executor/node constructs its locks.
if os.environ.get("INFERD_LOCKWATCH", "").strip().lower() not in (
    "0", "off", "false", "no"
):
    lockwatch.instrument(strict=True)


def pytest_configure(config):
    config.addinivalue_line("markers", "asyncio: run test in an event loop")
    config.addinivalue_line(
        "markers", "slow: long-running e2e/soak test (minutes, not seconds)"
    )


def pytest_pyfunc_call(pyfuncitem):
    """Minimal async test support (pytest-asyncio isn't installed here).

    When lockwatch is on (suite default), each async test's loop also
    runs a LoopStallDetector: stalls are RECORDED (journal hook only, a
    stall never fails a test by itself — CI boxes under load would flake)
    so stall-detection tests and postmortems can read them."""
    fn = pyfuncitem.obj
    if inspect.iscoroutinefunction(fn):
        kwargs = {
            n: pyfuncitem.funcargs[n] for n in pyfuncitem._fixtureinfo.argnames
        }
        if lockwatch.watching():

            async def _with_stall_watch():
                det = lockwatch.LoopStallDetector().start()
                try:
                    await fn(**kwargs)
                finally:
                    det.stop()

            asyncio.run(_with_stall_watch())
        else:
            asyncio.run(fn(**kwargs))
        return True
    return None


# jax version-compat skip (see inferd_tpu/parallel/compat.py): on jax
# without the public jax.shard_map (< 0.6 — e.g. the 0.4.37 some serving
# containers pin), the parallel layer runs through the
# jax.experimental.shard_map fallback. The shard_map test cluster PASSES
# on the fallback but runs far slower (measured on this box:
# test_parallel + test_infer_pipeline alone take 461 s vs ~65 s of
# fail-fast before the shim, against tier-1's 870 s budget for the WHOLE
# suite), so by default it is skipped there to keep tier-1 inside its
# cap. The exact condition: `not compat.native_shard_map()` and
# INFERD_RUN_SHARDMAP_COMPAT unset — export INFERD_RUN_SHARDMAP_COMPAT=1
# to run the cluster on the fallback (e.g. in a nightly lane).
from inferd_tpu.parallel import compat as _compat  # noqa: E402

requires_native_shard_map = pytest.mark.skipif(
    not _compat.native_shard_map()
    and not os.environ.get("INFERD_RUN_SHARDMAP_COMPAT"),
    reason=(
        "jax.shard_map absent (old jax): the compat fallback passes these "
        "tests but multiplies their wall time past tier-1's 870 s cap; "
        "set INFERD_RUN_SHARDMAP_COMPAT=1 to run them anyway"
    ),
)


@pytest.fixture(scope="session")
def devices8():
    devs = jax.devices()
    assert len(devs) >= 8, f"expected 8 virtual CPU devices, got {len(devs)}"
    return devs[:8]


@pytest.fixture
def retrace_guard():
    """Hot-loop retrace sanitizer (inferd_tpu.analysis.sanitizers): register
    jitted step fns after warmup; the teardown check fails the test if any
    of them re-traced during the test body. See docs/ANALYSIS.md."""
    from inferd_tpu.analysis.sanitizers import RetraceGuard

    guard = RetraceGuard()
    yield guard
    guard.check()
