# jaxlint: file-disable=J003 -- test code: loops here sync per-iteration to ASSERT on values; they are verification loops, not serving hot paths
"""In-mesh pipelined inference tests: the microbatched pp decode must match
the single-process engine token for token, across pipeline depths and
microbatch counts (including MB > PP and MB < PP bubble regimes), with
greedy AND temperature sampling, ragged prompts, EOS stop, and slot refill
(more sequences than slots)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from inferd_tpu.config import TINY, TINY_GEMMA2, TINY_QWEN2, SamplingConfig
from inferd_tpu.core.generate import Engine
from inferd_tpu.models import qwen3
from inferd_tpu.parallel import mesh as meshlib
from inferd_tpu.parallel.infer import PipelinedEngine

GREEDY = SamplingConfig(temperature=0.0)



from conftest import requires_native_shard_map

pytestmark = requires_native_shard_map

def make_engine(cfg, pp, mb, devices8, batch=1, max_len=32, sampling=GREEDY):
    mesh = meshlib.make_mesh(meshlib.MeshPlan(pp=pp), devices8[:pp])
    params = qwen3.init_params(cfg, jax.random.PRNGKey(0))
    eng = PipelinedEngine(
        cfg, params, mesh, num_microbatches=mb, batch=batch,
        max_len=max_len, sampling_cfg=sampling,
    )
    return eng, params


@pytest.mark.parametrize(
    "cfg,pp,mb",
    [
        (TINY, 2, 1),   # minimal: bubble-dominated
        (TINY, 2, 3),   # MB > PP: interleaving exercised
        (TINY, 4, 2),   # MB < PP
        (TINY_QWEN2, 2, 2),
        # gemma2 at pp=4: one layer per rank, so every rank's TRACED
        # layer_offset picks a different point in the sliding/global
        # alternation; decode walks past the window of 8
        (TINY_GEMMA2, 4, 2),
    ],
    ids=["pp2-mb1", "pp2-mb3", "pp4-mb2", "qwen2-pp2-mb2", "gemma2-pp4-mb2"],
)
def test_pipelined_decode_matches_engine(cfg, pp, mb, devices8):
    eng, params = make_engine(cfg, pp, mb, devices8)
    batch, prompt_len, steps = 1, 5, 6
    prompts = jax.random.randint(
        jax.random.PRNGKey(1), (mb, batch, prompt_len), 0, cfg.vocab_size, dtype=jnp.int32
    )
    got = np.asarray(eng.generate_array(prompts, max_new_tokens=steps))

    single = Engine(cfg, params, max_len=32, sampling_cfg=GREEDY)
    for m in range(mb):
        expected = single.generate(list(np.asarray(prompts[m, 0])), max_new_tokens=steps)
        assert got[m, 0].tolist() == expected, f"microbatch {m}"


@pytest.mark.parametrize(
    "cfg,pp,tp,mb",
    [
        (TINY, 2, 2, 2),       # pp x tp serving
        (TINY, 1, 2, 2),       # tp-only (pp=1 pipeline degenerates cleanly)
        ("moe", 2, 2, 1),      # MoE: experts shard over tp, psum combine
    ],
    ids=["pp2-tp2", "tp-only", "moe-pp2-tp2"],
)
def test_tp_pipelined_decode_matches_engine(cfg, pp, tp, mb, devices8):
    """Tensor-parallel serving: the cached decoder blocks run on head/expert
    shards with Megatron psums (models/qwen3.decoder_layer tp_axis) and must
    match the single-process engine token for token."""
    from inferd_tpu.config import TINY_MOE

    cfg = TINY_MOE if cfg == "moe" else cfg
    mesh = meshlib.make_mesh(meshlib.MeshPlan(pp=pp, tp=tp), devices8[: pp * tp])
    params = qwen3.init_params(cfg, jax.random.PRNGKey(0))
    eng = PipelinedEngine(
        cfg, params, mesh, num_microbatches=mb, batch=1,
        max_len=32, sampling_cfg=GREEDY,
    )
    batch, prompt_len, steps = 1, 5, 6
    prompts = jax.random.randint(
        jax.random.PRNGKey(1), (mb, batch, prompt_len), 0, cfg.vocab_size, dtype=jnp.int32
    )
    got = np.asarray(eng.generate_array(prompts, max_new_tokens=steps))

    single = Engine(cfg, params, max_len=32, sampling_cfg=GREEDY)
    for m in range(mb):
        expected = single.generate(list(np.asarray(prompts[m, 0])), max_new_tokens=steps)
        assert got[m, 0].tolist() == expected, f"microbatch {m}"


@pytest.mark.parametrize(
    "pp,tp,ep",
    [(2, 1, 2), (1, 2, 2)],
    ids=["pp2-ep2", "tp2-ep2"],
)
def test_ep_pipelined_moe_decode_matches_engine(pp, tp, ep, devices8):
    """Expert-parallel serving (BASELINE config 5's axis): expert weights
    shard over the ep (x tp) mesh axes, attention/KV replicate over ep, and
    the combine psums — token parity with the single-process engine."""
    from inferd_tpu.config import TINY_MOE

    cfg = TINY_MOE
    mesh = meshlib.make_mesh(
        meshlib.MeshPlan(pp=pp, tp=tp, ep=ep), devices8[: pp * tp * ep]
    )
    params = qwen3.init_params(cfg, jax.random.PRNGKey(0))
    eng = PipelinedEngine(
        cfg, params, mesh, num_microbatches=1, batch=1,
        max_len=32, sampling_cfg=GREEDY,
    )
    prompt = [5, 2, 9, 13, 4]
    prompts = jnp.asarray([[prompt]], jnp.int32)
    got = np.asarray(eng.generate_array(prompts, max_new_tokens=6))

    single = Engine(cfg, params, max_len=32, sampling_cfg=GREEDY)
    assert got[0, 0].tolist() == single.generate(prompt, max_new_tokens=6)


def test_gpt_oss_pipelined_tp_ep_matches_engine(devices8):
    """GPT-OSS over a pp2 x tp2 x ep2 serving mesh: sinks shard with the
    q heads over tp, expert biases + clamped GLU shard over (ep, tp), the
    topk-then-softmax router replicates — token parity with the engine."""
    from inferd_tpu.config import TINY_GPT_OSS

    cfg = TINY_GPT_OSS
    mesh = meshlib.make_mesh(meshlib.MeshPlan(pp=2, tp=2, ep=2), devices8)
    params = qwen3.init_params(cfg, jax.random.PRNGKey(21))
    eng = PipelinedEngine(
        cfg, params, mesh, num_microbatches=1, batch=1,
        max_len=32, sampling_cfg=GREEDY,
    )
    prompt = [5, 2, 9, 13, 4, 7, 11, 3, 8]  # + 6 new > window of 8
    prompts = jnp.asarray([[prompt]], jnp.int32)
    got = np.asarray(eng.generate_array(prompts, max_new_tokens=6))

    single = Engine(cfg, params, max_len=32, sampling_cfg=GREEDY)
    assert got[0, 0].tolist() == single.generate(prompt, max_new_tokens=6)


def test_ep_rejects_dense(devices8):
    mesh = meshlib.make_mesh(meshlib.MeshPlan(pp=1, tp=1, ep=2), devices8[:2])
    params = qwen3.init_params(TINY, jax.random.PRNGKey(0))
    with pytest.raises(ValueError, match="dense has no experts"):
        PipelinedEngine(TINY, params, mesh, num_microbatches=1, max_len=32)


def test_tp_rejects_indivisible_heads(devices8):
    mesh = meshlib.make_mesh(meshlib.MeshPlan(pp=1, tp=4), devices8[:4])
    params = qwen3.init_params(TINY, jax.random.PRNGKey(0))
    with pytest.raises(ValueError, match="not divisible by tp"):
        PipelinedEngine(TINY, params, mesh, num_microbatches=1, max_len=32)


def test_tp_moe_quant_decode_matches_quant_engine(devices8):
    """--quant int8 composes with tp MoE serving: QuantWeight expert
    weights flow through moe_mlp_sharded's qeinsum path (a plain einsum
    cannot consume them) and match the quantized single-process engine."""
    from inferd_tpu.config import TINY_MOE
    from inferd_tpu.ops import quant

    cfg = TINY_MOE
    params = qwen3.init_params(cfg, jax.random.PRNGKey(0))
    qparams = quant.apply_quant_mode(
        "int8", params, tie_word_embeddings=cfg.tie_word_embeddings
    )
    mesh = meshlib.make_mesh(meshlib.MeshPlan(pp=2, tp=2), devices8[:4])
    eng = PipelinedEngine(
        cfg, qparams, mesh, num_microbatches=1, batch=1,
        max_len=32, sampling_cfg=GREEDY,
    )
    prompt = [5, 2, 9, 13]
    prompts = jnp.asarray([[prompt]], jnp.int32)
    got = np.asarray(eng.generate_array(prompts, max_new_tokens=5))

    single = Engine(cfg, qparams, max_len=32, sampling_cfg=GREEDY)
    assert got[0, 0].tolist() == single.generate(prompt, max_new_tokens=5)


def test_sampled_ragged_refill_matches_engine(devices8):
    """The round-2 'real engine' bar (VERDICT item 4): temperature>0, mixed
    prompt lengths, more sequences than slots (forces refill) — every
    sequence must match Engine.generate(prompt, seed=seed+i) exactly."""
    sampling = SamplingConfig(temperature=0.6, top_k=20, top_p=0.95)
    eng, params = make_engine(TINY, 2, 2, devices8, sampling=sampling)
    rng = np.random.RandomState(7)
    prompts = [list(rng.randint(0, TINY.vocab_size, size=n)) for n in (3, 7, 4, 5, 6)]
    steps, seed = 8, 11

    got = eng.generate(prompts, max_new_tokens=steps, seed=seed)

    single = Engine(TINY, params, max_len=32, sampling_cfg=sampling)
    for i, p in enumerate(prompts):
        expected = single.generate(p, max_new_tokens=steps, seed=seed + i)
        assert got[i] == expected, f"sequence {i}"


def test_eos_stop_matches_engine(devices8):
    eng, params = make_engine(TINY, 2, 2, devices8)
    rng = np.random.RandomState(3)
    prompts = [list(rng.randint(0, TINY.vocab_size, size=n)) for n in (4, 6, 5)]
    single = Engine(TINY, params, max_len=32, sampling_cfg=GREEDY)

    # pick an EOS that actually fires mid-generation for sequence 0
    ref = single.generate(prompts[0], max_new_tokens=8)
    eos = ref[3]

    got = eng.generate(prompts, max_new_tokens=8, eos_token_id=eos)
    for i, p in enumerate(prompts):
        expected = single.generate(p, max_new_tokens=8, eos_token_id=eos)
        assert got[i] == expected, f"sequence {i}"
    assert got[0][-1] == eos and len(got[0]) <= 8


def test_multi_lane_slots_group_equal_lengths(devices8):
    """batch>1: lanes of one slot share a cache length, so sequences are
    grouped by prompt length; odd-sized groups pad with a dummy lane."""
    eng, params = make_engine(TINY, 2, 2, devices8, batch=2)
    rng = np.random.RandomState(5)
    lens = [4, 4, 6, 6, 4]  # two full groups + one odd group
    prompts = [list(rng.randint(0, TINY.vocab_size, size=n)) for n in lens]

    got = eng.generate(prompts, max_new_tokens=5)

    single = Engine(TINY, params, max_len=32, sampling_cfg=GREEDY)
    for i, p in enumerate(prompts):
        expected = single.generate(p, max_new_tokens=5)
        assert got[i] == expected, f"sequence {i}"


def test_caches_persist_across_generate_calls(devices8):
    eng, params = make_engine(TINY, 2, 2, devices8)
    p = [list(range(1, 6))]
    first = eng.generate(p, max_new_tokens=4)
    again = eng.generate(p, max_new_tokens=4)
    assert first == again  # slot reuse must fully reset per-slot state


def test_pipelined_rejects_indivisible_layers(devices8):
    plan = meshlib.MeshPlan(pp=3)
    mesh = meshlib.make_mesh(plan, devices8[:3])
    params = qwen3.init_params(TINY, jax.random.PRNGKey(0))  # 4 layers, pp=3
    with pytest.raises(ValueError, match="not divisible"):
        PipelinedEngine(TINY, params, mesh, num_microbatches=1)


def test_generate_guards(devices8):
    eng, _ = make_engine(TINY, 2, 1, devices8, max_len=8)
    assert eng.generate([[1, 2, 3]], max_new_tokens=0) == [[]]
    with pytest.raises(BufferError, match="exceeds max_len"):
        eng.generate([[1, 2, 3, 4, 5]], max_new_tokens=4)  # 5 + 4 > 8
    with pytest.raises(ValueError, match="empty"):
        eng.generate([[]], max_new_tokens=2)


def test_elastic_reshard_carries_live_session(devices8):
    """Elastic reshard (BASELINE config 4's correctness half): a live
    session served on a pp=2 mesh is EXPORTED (layer axis reassembled
    across ranks), imported into a pp=4 engine — a genuinely different
    layer split — and keeps decoding token-exact vs the solo engine."""
    eng1, params = make_engine(TINY, pp=2, mb=2, devices8=devices8)
    want = Engine(TINY, params, max_len=32, sampling_cfg=GREEDY).generate(
        [3, 7, 11, 19, 5], max_new_tokens=6
    )
    prompt = [3, 7, 11, 19, 5]
    logits = eng1.step_slot(0, np.asarray([prompt]), len(prompt), reset=True)
    toks = [int(np.argmax(logits[0]))]
    pos = len(prompt)
    for _ in range(2):
        logits = eng1.step_slot(0, np.asarray([[toks[-1]]]), 1, False, start_pos=pos)
        pos += 1
        toks.append(int(np.argmax(logits[0])))
    k, v, ln, _, _ = eng1.export_slot(0)
    assert ln == pos

    mesh2 = meshlib.make_mesh(meshlib.MeshPlan(pp=4), devices8[:4])
    eng2 = PipelinedEngine(
        TINY, params, mesh2, num_microbatches=2, batch=1, max_len=32,
        sampling_cfg=GREEDY,
    )
    eng2.import_slot(1, k, v, ln)
    for _ in range(3):
        logits = eng2.step_slot(1, np.asarray([[toks[-1]]]), 1, False, start_pos=pos)
        pos += 1
        toks.append(int(np.argmax(logits[0])))
    assert toks == want

    # shape validation: wrong head count is refused
    with pytest.raises(ValueError, match="does not match"):
        eng2.import_slot(0, k[:, :, :, :1], v[:, :, :, :1], ln)
    with pytest.raises(BufferError):
        eng2.import_slot(0, k, v, 999)
