"""In-mesh pipelined inference tests: the microbatched pp decode must match
the single-process engine token for token, across pipeline depths and
microbatch counts (including MB > PP and MB < PP bubble regimes)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from inferd_tpu.config import TINY, TINY_QWEN2, SamplingConfig
from inferd_tpu.core.generate import Engine
from inferd_tpu.models import qwen3
from inferd_tpu.parallel import mesh as meshlib
from inferd_tpu.parallel.infer import PipelinedEngine


@pytest.mark.parametrize(
    "cfg,pp,mb",
    [
        (TINY, 2, 1),   # minimal: bubble-dominated
        (TINY, 2, 3),   # MB > PP: interleaving exercised
        (TINY, 4, 2),   # MB < PP
        (TINY_QWEN2, 2, 2),
    ],
    ids=["pp2-mb1", "pp2-mb3", "pp4-mb2", "qwen2-pp2-mb2"],
)
def test_pipelined_decode_matches_engine(cfg, pp, mb, devices8):
    plan = meshlib.MeshPlan(pp=pp)
    mesh = meshlib.make_mesh(plan, devices8[:pp])
    params = qwen3.init_params(cfg, jax.random.PRNGKey(0))

    batch, prompt_len, steps = 1, 5, 6
    prompts = jax.random.randint(
        jax.random.PRNGKey(1), (mb, batch, prompt_len), 0, cfg.vocab_size, dtype=jnp.int32
    )

    eng = PipelinedEngine(cfg, params, mesh, num_microbatches=mb, batch=batch, max_len=32)
    got = np.asarray(eng.generate(prompts, max_new_tokens=steps))  # [MB, B, steps]

    single = Engine(cfg, params, max_len=32, sampling_cfg=SamplingConfig(temperature=0.0))
    for m in range(mb):
        expected = single.generate(list(np.asarray(prompts[m, 0])), max_new_tokens=steps)
        assert got[m, 0].tolist() == expected, f"microbatch {m}"


def test_pipelined_rejects_indivisible_layers(devices8):
    plan = meshlib.MeshPlan(pp=3)
    mesh = meshlib.make_mesh(plan, devices8[:3])
    params = qwen3.init_params(TINY, jax.random.PRNGKey(0))  # 4 layers, pp=3
    with pytest.raises(ValueError, match="not divisible"):
        PipelinedEngine(TINY, params, mesh, num_microbatches=1)


def test_generate_guards(devices8):
    plan = meshlib.MeshPlan(pp=2)
    mesh = meshlib.make_mesh(plan, devices8[:2])
    params = qwen3.init_params(TINY, jax.random.PRNGKey(0))
    eng = PipelinedEngine(TINY, params, mesh, num_microbatches=1, max_len=8)
    prompts = jnp.ones((1, 1, 5), jnp.int32)
    assert eng.generate(prompts, max_new_tokens=0).shape == (1, 1, 0)
    with pytest.raises(BufferError, match="exceeds max_len"):
        eng.generate(prompts, max_new_tokens=4)  # 5 + 4 > 8
