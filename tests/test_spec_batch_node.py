"""Lane-batched speculative SERVING (runtime/node.py + batch_executor):
concurrent /generate requests on a --batch-lanes --spec-draft-layers node
must all speculate (no shedding to the regular loop), stay greedy-exact
with the solo engine, coalesce rounds, stream accepted runs, and coexist
with regular /forward sessions on the same lanes. Round-5 scope (VERDICT
r04 #1a/c)."""

import asyncio

import jax
import pytest

from inferd_tpu.client.swarm_client import SwarmClient
from inferd_tpu.config import TINY, SamplingConfig
from inferd_tpu.control.dht import SwarmDHT
from inferd_tpu.core.generate import Engine
from inferd_tpu.models import qwen3
from inferd_tpu.parallel.stages import Manifest, split_and_save
from inferd_tpu.runtime.node import Node, NodeInfo

BASE = 18750  # distinct block from test_batch_node (18700)


async def _start(node):
    """Start + wait for the spec warmup (it briefly holds a lane; tests
    that immediately saturate all lanes would otherwise race it)."""
    await node.start()
    t = getattr(node, "_spec_prebuild_task", None)
    if t is not None:
        await t
    return node


@pytest.fixture(scope="module")
def whole_parts(tmp_path_factory):
    parts = tmp_path_factory.mktemp("whole")
    params = qwen3.init_params(TINY, jax.random.PRNGKey(0))
    manifest = Manifest.even_split("tiny", 1)
    split_and_save(params, TINY, manifest, str(parts))
    return str(parts), params


def _mk_node(idx, parts, lanes=4, draft_layers=2, k=3):
    info = NodeInfo(
        name=f"sbn{idx}", host="127.0.0.1", port=BASE + idx,
        stage=0, num_stages=1, capacity=8, model_name="tiny",
    )
    dht = SwarmDHT(
        info.node_id, BASE + 100 + idx, bootstrap=[],
        host="127.0.0.1", gossip_period_s=0.05, ttl_s=5.0,
    )
    return Node(
        info, TINY, parts, dht, backend="qwen3", max_len=64,
        rebalance_period_s=600.0, batch_lanes=lanes,
        spec_draft_layers=draft_layers, spec_k=k,
    )


@pytest.mark.asyncio
async def test_concurrent_generate_all_speculative_greedy_exact(whole_parts):
    """Every one of 3 concurrent greedy /generate requests takes the lane
    fast path (speculative: true in each reply — the round-4 build would
    shed all but one to the regular loop) and each stream is token-exact
    with the solo engine."""
    parts, params = whole_parts
    node = _mk_node(0, parts)
    await _start(node)
    try:
        prompts = [[3, 7, 11], [2, 5, 13, 17], [23, 29]]
        sc = SamplingConfig(temperature=0.0)
        engine = Engine(TINY, params, max_len=64, sampling_cfg=sc)
        want = [engine.generate(p, max_new_tokens=10) for p in prompts]

        async def one(p):
            async with SwarmClient([("127.0.0.1", BASE)], sampling=sc) as c:
                return await c.generate_server_side(
                    p, max_new_tokens=10, return_payload=True
                )

        payloads = await asyncio.gather(*(one(p) for p in prompts))
        got = [p["ids"] for p in payloads]
        assert got == want
        assert all(p.get("speculative") for p in payloads), payloads
        st = node.executor.stats()
        assert st["spec_sessions"] == 0  # all closed
    finally:
        await node.stop()


@pytest.mark.asyncio
async def test_rounds_coalesce_across_sessions(whole_parts):
    """With a long window and simultaneous requests, at least one spec
    round must serve >1 session (the whole point of lane batching)."""
    parts, params = whole_parts
    node = _mk_node(1, parts)
    # widen the spec window BEFORE start: the warmup prebuild constructs
    # the greedy runner's batcher with whatever window is set then
    node.executor._spec_window_s = 0.2
    await _start(node)
    try:
        prompts = [[3, 7, 11], [2, 5, 13, 17], [23, 29], [5, 6]]
        sc = SamplingConfig(temperature=0.0)

        async def one(p):
            async with SwarmClient([("127.0.0.1", BASE + 1)], sampling=sc) as c:
                return await c.generate_server_side(p, max_new_tokens=10)

        await asyncio.gather(*(one(p) for p in prompts))
        st = node.executor.stats()
        assert st["spec_rounds"] > 0
        assert st["spec_round_sessions"] > st["spec_rounds"], st
    finally:
        await node.stop()


@pytest.mark.asyncio
async def test_streaming_speculative(whole_parts):
    """stream=true on a spec-enabled batched node emits accepted runs as
    ndjson {"t": ...} lines and finishes with speculative metadata; the
    streamed ids equal the solo greedy stream."""
    import json as jsonlib

    import aiohttp

    parts, params = whole_parts
    node = _mk_node(2, parts)
    await _start(node)
    try:
        from inferd_tpu.runtime import wire

        sc = SamplingConfig(temperature=0.0)
        engine = Engine(TINY, params, max_len=64, sampling_cfg=sc)
        prompt = [3, 7, 11]
        want = engine.generate(prompt, max_new_tokens=10)

        async with aiohttp.ClientSession() as http:
            async with http.post(
                f"http://127.0.0.1:{BASE + 2}/generate",
                data=wire.pack({
                    "prompt_ids": prompt, "max_new_tokens": 10,
                    "sampling": {"temperature": 0.0}, "stream": True,
                }),
            ) as r:
                assert r.status == 200
                lines = [
                    jsonlib.loads(l) for l in (await r.read()).splitlines()
                ]
        toks = [l["t"] for l in lines if "t" in l]
        done = lines[-1]
        assert done.get("done") and done["ids"] == want
        assert toks == want
        assert done.get("speculative") is True
    finally:
        await node.stop()


@pytest.mark.asyncio
async def test_spec_and_regular_sessions_interleave(whole_parts):
    """A regular client-side-sampling /forward session decoding WHILE spec
    generations run on sibling lanes keeps its exact stream (no KV
    corruption from verify-chunk garbage writes)."""
    parts, params = whole_parts
    node = _mk_node(3, parts)
    await _start(node)
    try:
        sc = SamplingConfig(temperature=0.0)
        engine = Engine(TINY, params, max_len=64, sampling_cfg=sc)
        reg_prompt = [9, 8, 7, 6]
        want_reg = engine.generate(reg_prompt, max_new_tokens=12)
        want_spec = engine.generate([3, 7, 11], max_new_tokens=12)

        async def regular():
            async with SwarmClient(
                [("127.0.0.1", BASE + 3)], sampling=sc
            ) as c:
                return await c.generate_ids(reg_prompt, max_new_tokens=12)

        async def spec():
            async with SwarmClient(
                [("127.0.0.1", BASE + 3)], sampling=sc
            ) as c:
                return await c.generate_server_side([3, 7, 11], max_new_tokens=12)

        got_reg, got_spec = await asyncio.gather(regular(), spec())
        assert got_reg == want_reg
        assert got_spec == want_spec
    finally:
        await node.stop()


@pytest.mark.asyncio
async def test_sampled_spec_serving_deterministic_per_seed(whole_parts):
    """Sampled lane speculation: tokens flow, the reply carries accept
    stats, and a repeated (prompt, seed) request on the same engine is
    deterministic (single in-flight request; the seed contract for
    CONCURRENT sampled requests is documented weaker)."""
    parts, params = whole_parts
    node = _mk_node(4, parts)
    await _start(node)
    try:
        sc = SamplingConfig(temperature=0.9, top_k=10, top_p=0.95)

        async def one():
            async with SwarmClient(
                [("127.0.0.1", BASE + 4)], sampling=sc
            ) as c:
                return await c.generate_server_side(
                    [3, 7, 11], max_new_tokens=12, seed=5,
                    return_payload=True,
                )

        p1 = await one()
        p2 = await one()
        assert p1["speculative"] and p2["speculative"]
        assert len(p1["ids"]) == 12
        assert p1["ids"] == p2["ids"]
        assert 0.0 <= p1["spec_accept_rate"] <= 1.0
    finally:
        await node.stop()


@pytest.mark.asyncio
async def test_capacity_cap_and_fallback(whole_parts):
    """A prompt+budget over the spec-capped capacity declines the fast
    path and the regular loop surfaces the ordinary overflow contract."""
    parts, params = whole_parts
    node = _mk_node(5, parts)
    await _start(node)
    try:
        # cap = 64 - (3+1) = 60; 50-token prompt + 20 new > 60 -> 409 from
        # the regular path (process() caps admissions at 60 too)
        from inferd_tpu.client.base import ServerError

        sc = SamplingConfig(temperature=0.0)
        async with SwarmClient([("127.0.0.1", BASE + 5)], sampling=sc) as c:
            with pytest.raises(ServerError):
                await c.generate_server_side(
                    list(range(1, 51)), max_new_tokens=20
                )
            # well within cap: serves speculatively
            p = await c.generate_server_side(
                [3, 7, 11], max_new_tokens=8, return_payload=True
            )
            assert p.get("speculative") is True
    finally:
        await node.stop()


@pytest.mark.asyncio
async def test_streaming_solo_spec_node(whole_parts):
    """SOLO (stage-executor) spec nodes stream too (round 5: the round-4
    build excluded stream=true from the fast path entirely): accepted
    runs arrive as {"t"} lines, the done line carries speculative
    metadata, and the ids equal the solo engine's greedy stream."""
    import json as jsonlib

    import aiohttp

    from inferd_tpu.runtime import wire

    parts, params = whole_parts
    # no batch_lanes: the stage executor hosts the whole 1-stage model
    info_port = BASE + 30
    from inferd_tpu.runtime.node import Node, NodeInfo

    info = NodeInfo(
        name="solo-spec", host="127.0.0.1", port=info_port,
        stage=0, num_stages=1, capacity=8, model_name="tiny",
    )
    dht = SwarmDHT(
        info.node_id, BASE + 130, bootstrap=[],
        host="127.0.0.1", gossip_period_s=0.05, ttl_s=5.0,
    )
    node = Node(
        info, TINY, parts, dht, backend="qwen3", max_len=64,
        rebalance_period_s=600.0, spec_draft_layers=2, spec_k=3,
    )
    await _start(node)
    try:
        assert not getattr(node.executor, "spec_enabled", lambda: False)()
        sc = SamplingConfig(temperature=0.0)
        engine = Engine(TINY, params, max_len=64, sampling_cfg=sc)
        prompt = [3, 7, 11]
        want = engine.generate(prompt, max_new_tokens=10)
        async with aiohttp.ClientSession() as http:
            async with http.post(
                f"http://127.0.0.1:{info_port}/generate",
                data=wire.pack({
                    "prompt_ids": prompt, "max_new_tokens": 10,
                    "sampling": {"temperature": 0.0}, "stream": True,
                }),
            ) as r:
                assert r.status == 200
                lines = [
                    jsonlib.loads(l) for l in (await r.read()).splitlines()
                ]
        toks = [l["t"] for l in lines if "t" in l]
        done = lines[-1]
        assert done.get("done") and done["ids"] == want
        assert toks == want
        assert done.get("speculative") is True
    finally:
        await node.stop()


@pytest.mark.asyncio
async def test_forward_overflow_at_spec_cap(whole_parts):
    """While speculation is enabled, a REGULAR /forward admission past
    max_len-(k+1) must 409 (the verify-chunk headroom contract applies to
    every lane, not just speculating ones)."""
    from inferd_tpu.client.base import ServerError

    parts, params = whole_parts
    node = _mk_node(6, parts)  # max_len=64, k=3 -> cap 60
    await _start(node)
    try:
        sc = SamplingConfig(temperature=0.0)
        async with SwarmClient([("127.0.0.1", BASE + 6)], sampling=sc) as c:
            with pytest.raises(ServerError) as ei:
                # 59-token prompt + 2 new: the second decode step would
                # write past cap=60
                await c.generate_ids(list(range(1, 60)), max_new_tokens=3)
            assert ei.value.status == 409
            # within cap: fine
            out = await c.generate_ids([3, 7, 11], max_new_tokens=4)
            assert len(out) == 4
    finally:
        await node.stop()


@pytest.mark.asyncio
async def test_pinned_prefix_composes_with_spec(whole_parts):
    """pin_prefix_len > 0 no longer excludes the speculative fast path:
    the spec session FORKS the shared pin (prefix KV reused, only the
    suffix prefills) and the stream stays greedy-exact. Covers both the
    suffix case and the prompt==prefix case (pin logits seed the first
    token)."""
    parts, params = whole_parts
    node = _mk_node(7, parts)
    await _start(node)
    try:
        sc = SamplingConfig(temperature=0.0)
        engine = Engine(TINY, params, max_len=64, sampling_cfg=sc)
        prefix = [3, 7, 11, 13]
        full = prefix + [2, 5]
        want_full = engine.generate(full, max_new_tokens=10)
        want_pfx = engine.generate(prefix, max_new_tokens=10)

        async with SwarmClient([("127.0.0.1", BASE + 7)], sampling=sc) as c:
            p1 = await c.generate_server_side(
                full, max_new_tokens=10, pin_prefix_len=len(prefix),
                return_payload=True,
            )
            # prompt == pinned prefix: first token comes from the pin's
            # stored logits, the rest from spec rounds
            p2 = await c.generate_server_side(
                prefix, max_new_tokens=10, pin_prefix_len=len(prefix),
                return_payload=True,
            )
        assert p1["ids"] == want_full
        assert p2["ids"] == want_pfx
        assert p1.get("speculative") and p2.get("speculative"), (p1, p2)
        snap = node.metrics.snapshot()
        assert snap["counters"]["generate.speculative_pinned"] == 2
    finally:
        await node.stop()


@pytest.mark.asyncio
async def test_greedy_logprobs_ride_the_lane_spec_path(whole_parts):
    """Greedy logprob/top-N requests take the lane fast path too (round 5:
    previously shed to the regular loop on batched nodes): the reply is
    speculative AND its logprob trail matches the regular loop's engine-
    computed values."""
    import math

    parts, params = whole_parts
    node = _mk_node(8, parts)
    await _start(node)
    try:
        sc = SamplingConfig(temperature=0.0)
        prompt = [3, 7, 11]
        # reference trail from the solo engine (the regular loop's source)
        engine = Engine(TINY, params, max_len=64, sampling_cfg=sc)
        want_lps = []
        want = engine.generate(
            prompt, max_new_tokens=10, logprob_sink=want_lps
        )
        async with SwarmClient([("127.0.0.1", BASE + 8)], sampling=sc) as c:
            lps = []
            tops = []
            p = await c.generate_server_side(
                prompt, max_new_tokens=10, logprob_sink=lps,
                top_logprobs=4, top_sink=tops, return_payload=True,
            )
        assert p["ids"] == want
        assert p.get("speculative") is True, p
        assert len(lps) == len(want) == len(tops)
        for a, b in zip(lps, want_lps):
            assert math.isfinite(a) and abs(a - b) < 1e-3, (a, b)
        for ti, tl in tops:
            assert len(ti) == 4 and len(tl) == 4
    finally:
        await node.stop()


@pytest.mark.asyncio
async def test_spec_serving_mixed_load_soak(whole_parts):
    """Concurrency soak over the round-5 serving surface: 12 requests —
    greedy spec, sampled spec, logprob spec, pinned spec, streamed spec,
    and regular client-side-sampling sessions — race on a 4-lane node.
    Every greedy reply must be EXACT vs the solo engine regardless of
    which path served it (CapacityError fallbacks to the regular loop are
    legal and equally exact); nothing may deadlock or leak sessions."""
    import json as jsonlib

    import aiohttp

    from inferd_tpu.runtime import wire

    parts, params = whole_parts
    node = _mk_node(9, parts)
    await _start(node)
    try:
        sc = SamplingConfig(temperature=0.0)
        engine = Engine(TINY, params, max_len=64, sampling_cfg=sc)
        prompts = [[3 + i, 7, 11 + i] for i in range(6)]
        want = {tuple(p): engine.generate(p, max_new_tokens=8)
                for p in prompts}
        prefix = [3, 7, 11, 13]
        want_pin = engine.generate(prefix + [9], max_new_tokens=8)

        entry = [("127.0.0.1", BASE + 9)]

        async def retry503(fn):
            # 503 = documented retryable backpressure (all lanes busy with
            # in-flight requests); a real client backs off and retries
            from inferd_tpu.client.base import ServerError

            for attempt in range(12):
                try:
                    return await fn()
                except ServerError as e:
                    # the client contract: retryable = transient
                    # backpressure (503) or a session whose lane was
                    # evicted under thrash (409 session_state) — restart
                    if not e.retryable:
                        raise
                    await asyncio.sleep(0.3 * (attempt + 1))
            raise AssertionError("backpressure never cleared")

        async def greedy_spec(p):
            async with SwarmClient(entry, sampling=sc) as c:
                out = await retry503(
                    lambda: c.generate_server_side(p, max_new_tokens=8)
                )
            assert out == want[tuple(p)], (p, out)

        async def lp_spec(p):
            async with SwarmClient(entry, sampling=sc) as c:
                lps = []
                out = await retry503(lambda: c.generate_server_side(
                    p, max_new_tokens=8, logprob_sink=lps
                ))
            assert out == want[tuple(p)]
            assert len(lps) == len(out)

        async def pinned_spec():
            async with SwarmClient(entry, sampling=sc) as c:
                out = await retry503(lambda: c.generate_server_side(
                    prefix + [9], max_new_tokens=8,
                    pin_prefix_len=len(prefix),
                ))
            assert out == want_pin

        async def sampled_spec(seed):
            s2 = SamplingConfig(temperature=0.9, top_k=10, top_p=0.95)
            async with SwarmClient(entry, sampling=s2) as c:
                out = await retry503(lambda: c.generate_server_side(
                    [5, 6, 7], max_new_tokens=8, seed=seed
                ))
            assert len(out) == 8

        async def streamed_spec(p):
            # same backpressure contract as the wire clients: a terminal
            # {"error": ...503...} line means retry the whole request
            for attempt in range(12):
                async with aiohttp.ClientSession() as http:
                    async with http.post(
                        f"http://127.0.0.1:{BASE + 9}/generate",
                        data=wire.pack({
                            "prompt_ids": p, "max_new_tokens": 8,
                            "sampling": {"temperature": 0.0}, "stream": True,
                        }),
                    ) as r:
                        lines = [jsonlib.loads(l)
                                 for l in (await r.read()).splitlines()]
                done = lines[-1]
                if done.get("done"):
                    break
                err = str(done.get("error", ""))
                # transient classes only: busy lanes (503) or a session
                # evicted under thrash (409 session_state)
                assert "503" in err or "409" in err, done
                await asyncio.sleep(0.3 * (attempt + 1))
            assert done.get("done") and done["ids"] == want[tuple(p)]

        async def regular(p):
            async with SwarmClient(entry, sampling=sc) as c:
                # under 12-sessions-on-4-lanes thrash a regular session can
                # be LRU-evicted repeatedly (each eviction is a correct,
                # retryable 409 session_state); give the restart loop room
                out = await c.generate_ids(
                    p, max_new_tokens=8, session_retries=10,
                    retry_delay_s=0.3,
                )
            assert out == want[tuple(p)]

        await asyncio.gather(
            greedy_spec(prompts[0]), greedy_spec(prompts[1]),
            lp_spec(prompts[2]), pinned_spec(),
            sampled_spec(1), sampled_spec(2),
            streamed_spec(prompts[3]), streamed_spec(prompts[4]),
            regular(prompts[5]), regular(prompts[0]),
            greedy_spec(prompts[2]), lp_spec(prompts[1]),
        )
        # nothing leaked: every spec session closed, lanes recycled
        st = node.executor.stats()
        assert st["spec_sessions"] == 0, st
    finally:
        await node.stop()
