"""In-process multi-node swarm tests over loopback (the reference's
test_rebalance.py sim idea, SURVEY §4, as a real asserted pytest suite):
counter-model pipeline traversal, distributed-vs-single-process golden
generation, wrong-node relay, admin reassign, and dead-stage adoption."""

import asyncio

import numpy as np
import pytest

from inferd_tpu.client.swarm_client import SwarmClient
from inferd_tpu.config import TINY, SamplingConfig, get_config
from inferd_tpu.control.dht import SwarmDHT
from inferd_tpu.core.generate import Engine
from inferd_tpu.models import qwen3
from inferd_tpu.parallel.stages import Manifest, split_and_save
from inferd_tpu.runtime import wire
from inferd_tpu.runtime.node import Node, NodeInfo

BASE = 18200


def _mk_node(
    idx, stage, num_stages, *, backend="counter", parts="", bootstrap_idx=0,
    rebalance_period_s=600.0, capacity=4, lora="",
):
    """Node with HTTP on BASE+idx, gossip UDP on BASE+100+idx."""
    info = NodeInfo(
        name=f"n{idx}", host="127.0.0.1", port=BASE + idx,
        stage=stage, num_stages=num_stages, capacity=capacity, model_name="tiny",
    )
    dht = SwarmDHT(
        info.node_id, BASE + 100 + idx,
        bootstrap=[("127.0.0.1", BASE + 100 + bootstrap_idx)] if idx != bootstrap_idx else [],
        host="127.0.0.1", gossip_period_s=0.05, ttl_s=1.5,
    )
    return Node(
        info, TINY, parts, dht, backend=backend, max_len=64,
        rebalance_period_s=rebalance_period_s, lora=lora or None,
    )


async def _start_all(nodes):
    for n in nodes:
        await n.start()
    # wait until every node sees every stage populated
    async def converged():
        for n in nodes:
            m = n.dht.get_all(n.info.num_stages)
            if any(not m[s] for s in range(n.info.num_stages)):
                return False
        return True

    for _ in range(100):
        if await converged():
            return
        await asyncio.sleep(0.05)
    raise TimeoutError("swarm did not converge")


async def _stop_all(nodes):
    for n in nodes:
        try:
            await n.stop()
        except Exception:
            pass


@pytest.mark.asyncio
async def test_counter_pipeline_three_stages():
    nodes = [_mk_node(i, i, 3) for i in range(3)]
    await _start_all(nodes)
    try:
        async with SwarmClient([("127.0.0.1", BASE + 0)]) as c:
            resp = await c._post(
                "/forward",
                {"stage": 0, "session_id": "s1", "payload": {}},
            )
        r = resp["result_for_user"]["result_for_user"]
        assert r["state"] == 3
        assert r["trace"] == [0, 1, 2]
    finally:
        await _stop_all(nodes)


@pytest.mark.asyncio
async def test_wrong_entry_node_relays():
    """A request sent to a non-stage-0 node must be relayed to stage 0 and
    still complete (reference node.py:139-141 behavior)."""
    nodes = [_mk_node(i, i, 3) for i in range(3)]
    await _start_all(nodes)
    try:
        async with SwarmClient([("127.0.0.1", BASE + 2)]) as c:  # entry = stage 2
            resp = await c._post("/forward", {"stage": 0, "session_id": "s2", "payload": {}})
        assert resp["result_for_user"]["result_for_user"]["trace"] == [0, 1, 2]
    finally:
        await _stop_all(nodes)


@pytest.fixture(scope="module")
def tiny_parts(tmp_path_factory):
    parts = tmp_path_factory.mktemp("parts")
    params = qwen3.init_params(TINY, __import__("jax").random.PRNGKey(0))
    manifest = Manifest.even_split("tiny", 2)
    split_and_save(params, TINY, manifest, str(parts))
    return str(parts), params


@pytest.mark.asyncio
async def test_distributed_generation_matches_engine(tiny_parts):
    """Golden distributed test: 2-stage qwen3 swarm over HTTP == single-
    process engine, token for token (greedy)."""
    parts, params = tiny_parts
    nodes = [
        _mk_node(10 + i, i, 2, backend="qwen3", parts=parts, bootstrap_idx=10)
        for i in range(2)
    ]
    await _start_all(nodes)
    try:
        engine = Engine(TINY, params, max_len=64, sampling_cfg=SamplingConfig(temperature=0.0))
        prompt = [3, 7, 11, 19]
        expected = engine.generate(prompt, max_new_tokens=6)
        async with SwarmClient(
            [("127.0.0.1", BASE + 10)], sampling=SamplingConfig(temperature=0.0)
        ) as c:
            got = await c.generate_ids(prompt, max_new_tokens=6)
        assert got == expected
    finally:
        await _stop_all(nodes)


def _write_tiny_adapter(tmp_path, r=4, alpha=8, seed=11, std=0.3):
    """Synthesize a peft-format adapter dir for TINY (no peft needed).

    std=0.3: the adapter-changes-the-output assert below compares GREEDY
    token streams, and TINY's random-init logits are near-degenerate (a
    single token dominates every step) — a 0.05-std adapter's logit
    perturbation is too small to flip any argmax, so base and merged
    engines emit identical streams and the assert fails spuriously
    (observed on this box). 0.3 flips the stream decisively while still
    exercising the exact same load/slice/merge path."""
    import json as _json

    from safetensors.numpy import save_file

    rng = np.random.RandomState(seed)
    dims = {
        "q_proj": (TINY.hidden_size, TINY.q_dim),
        "k_proj": (TINY.hidden_size, TINY.kv_dim),
        "v_proj": (TINY.hidden_size, TINY.kv_dim),
        "o_proj": (TINY.q_dim, TINY.hidden_size),
        "gate_proj": (TINY.hidden_size, TINY.intermediate_size),
        "up_proj": (TINY.hidden_size, TINY.intermediate_size),
        "down_proj": (TINY.intermediate_size, TINY.hidden_size),
    }
    sd = {}
    for i in range(TINY.num_layers):
        for name, (din, dout) in dims.items():
            mod = "self_attn" if name.endswith(("q_proj", "k_proj", "v_proj", "o_proj")) else "mlp"
            pre = f"base_model.model.model.layers.{i}.{mod}.{name}"
            sd[f"{pre}.lora_A.weight"] = rng.normal(0, std, (r, din)).astype(np.float32)
            sd[f"{pre}.lora_B.weight"] = rng.normal(0, std, (dout, r)).astype(np.float32)
    adir = tmp_path / "adapter"
    adir.mkdir()
    save_file(sd, str(adir / "adapter_model.safetensors"))
    (adir / "adapter_config.json").write_text(
        _json.dumps({"lora_alpha": alpha, "r": r})
    )
    return str(adir)


@pytest.mark.asyncio
async def test_lora_swarm_matches_merged_engine(tiny_parts, tmp_path):
    """run_node --lora e2e: a 2-stage swarm whose nodes merge a peft-format
    adapter into their stage slices must equal a single-process Engine over
    the fully merged params, token for token — pinning the per-stage
    slice_adapter offsets (spec.start_layer..end_layer+1)."""
    from inferd_tpu.ops import lora as loralib

    parts, params = tiny_parts
    adir = _write_tiny_adapter(tmp_path)
    nodes = [
        _mk_node(70 + i, i, 2, backend="qwen3", parts=parts,
                 bootstrap_idx=70, lora=adir)
        for i in range(2)
    ]
    await _start_all(nodes)
    try:
        merged = loralib.merge_adapter(
            params, loralib.load_adapter(TINY, adir)
        )
        engine = Engine(TINY, merged, max_len=64, sampling_cfg=SamplingConfig(temperature=0.0))
        prompt = [3, 7, 11, 19]
        expected = engine.generate(prompt, max_new_tokens=6)
        # the adapter must actually change the output vs the base weights
        base_engine = Engine(TINY, params, max_len=64, sampling_cfg=SamplingConfig(temperature=0.0))
        assert base_engine.generate(prompt, max_new_tokens=6) != expected
        async with SwarmClient(
            [("127.0.0.1", BASE + 70)], sampling=SamplingConfig(temperature=0.0)
        ) as c:
            got = await c.generate_ids(prompt, max_new_tokens=6)
        assert got == expected
    finally:
        await _stop_all(nodes)


@pytest.mark.asyncio
async def test_reassign_endpoint(tiny_parts):
    """Admin /reassign migrates a node to a new stage and it serves it
    (the reference's dead B1/B2 path, working)."""
    parts, params = tiny_parts
    nodes = [
        _mk_node(20 + i, i, 2, backend="qwen3", parts=parts, bootstrap_idx=20)
        for i in range(2)
    ]
    # extra replica on stage 0 that we'll move to stage 1
    extra = _mk_node(22, 0, 2, backend="qwen3", parts=parts, bootstrap_idx=20)
    nodes.append(extra)
    await _start_all(nodes)
    try:
        import aiohttp

        async with aiohttp.ClientSession() as s:
            async with s.post(
                f"http://127.0.0.1:{BASE + 22}/reassign", data=wire.pack({"stage": 1})
            ) as r:
                assert r.status == 200
        assert extra.info.stage == 1
        assert extra.executor.spec.is_last
        # reshard-latency observability (BASELINE config 4's timing half):
        # the reassign -> ready-to-serve interval is recorded, and the
        # eager warmup means it INCLUDES the new stage's decode compile
        hist = extra.metrics.snapshot()["histograms"]
        assert hist["reshard.ms_to_serving"]["count"] == 1
        assert hist["reshard.ms_to_serving"]["p50_ms"] > 0
        # swarm converges on the new membership
        for _ in range(100):
            if len(nodes[0].dht.get_stage(1)) == 2:
                break
            await asyncio.sleep(0.05)
        assert len(nodes[0].dht.get_stage(1)) == 2
        # and the moved node actually serves stage 1 traffic end to end
        async with SwarmClient(
            [("127.0.0.1", BASE + 20)], sampling=SamplingConfig(temperature=0.0)
        ) as c:
            out = await c.generate_ids([5, 6], max_new_tokens=3)
        assert len(out) == 3
    finally:
        await _stop_all(nodes)


@pytest.mark.asyncio
async def test_dead_stage_adoption():
    """Stage-0 node dies; a request entering via a stage-1 replica triggers
    adoption: one replica migrates to stage 0 and the request completes
    (reference path_finder.py:74-82 retry semantics, functioning)."""
    n0 = _mk_node(30, 0, 2, bootstrap_idx=30)
    n1a = _mk_node(31, 1, 2, bootstrap_idx=30)
    n1b = _mk_node(32, 1, 2, bootstrap_idx=30)
    nodes = [n0, n1a, n1b]
    await _start_all(nodes)
    try:
        await n0.stop()  # silent death; TTL (1.5 s) expires its record
        await asyncio.sleep(2.0)
        assert len(n1a.dht.get_stage(0)) == 0
        async with SwarmClient([("127.0.0.1", BASE + 31)], timeout_s=30.0) as c:
            resp = await c._post("/forward", {"stage": 0, "session_id": "s3", "payload": {}})
        r = resp["result_for_user"]["result_for_user"]
        assert r["state"] == 2
        assert r["trace"] == [0, 1]
        # exactly one replica adopted stage 0
        stages = sorted([n1a.info.stage, n1b.info.stage])
        assert stages == [0, 1]
    finally:
        await _stop_all(nodes[1:])


@pytest.mark.asyncio
async def test_reassign_hands_off_sessions(tiny_parts):
    """Live migration keeps sessions alive: when the replica holding a
    session's KV is reassigned to another stage, it ships the KV to the
    remaining replica of its old stage, and the client's in-flight
    generation continues WITHOUT a session restart (the reference's
    migration would orphan every session — SURVEY §7 hard parts)."""
    parts, params = tiny_parts
    n0 = _mk_node(60, 0, 2, backend="qwen3", parts=parts, bootstrap_idx=60)
    n1a = _mk_node(61, 1, 2, backend="qwen3", parts=parts, bootstrap_idx=60)
    n1b = _mk_node(62, 1, 2, backend="qwen3", parts=parts, bootstrap_idx=60)
    nodes = [n0, n1a, n1b]
    await _start_all(nodes)
    try:
        engine = Engine(TINY, params, max_len=64, sampling_cfg=SamplingConfig(temperature=0.0))
        prompt = [3, 7, 11, 19]
        expected = engine.generate(prompt, max_new_tokens=6)
        async with SwarmClient(
            [("127.0.0.1", BASE + 60)], sampling=SamplingConfig(temperature=0.0)
        ) as c:
            sid = "mig-session"
            logits = await c._step(sid, prompt, 0)
            toks = [int(np.argmax(logits))]
            pos = len(prompt)
            for _ in range(2):
                logits = await c._step(sid, [toks[-1]], pos)
                pos += 1
                toks.append(int(np.argmax(logits)))
            holder = n1a if len(n1a.executor.sessions) else n1b
            other = n1b if holder is n1a else n1a
            assert len(holder.executor.sessions) == 1

            import aiohttp

            async with aiohttp.ClientSession() as s:
                async with s.post(
                    f"http://127.0.0.1:{holder.info.port}/reassign",
                    data=wire.pack({"stage": 0}),
                ) as r:
                    assert r.status == 200
            # the handoff runs inside change_stage: the session must now
            # live on the remaining stage-1 replica
            assert sid in other.executor.sessions
            assert other.metrics.snapshot()["counters"].get("sessions.imported", 0) >= 1
            # wait until routing sees the holder gone from stage 1
            for _ in range(100):
                if len(n0.dht.get_stage(1)) == 1:
                    break
                await asyncio.sleep(0.05)
            # continue decoding — no session restart (a restart would need a
            # fresh prefill; _step would 409 on out-of-order otherwise)
            for _ in range(3):
                logits = await c._step(sid, [toks[-1]], pos)
                pos += 1
                toks.append(int(np.argmax(logits)))
            await c._end_session(sid)
        assert toks == expected
    finally:
        await _stop_all(nodes)


@pytest.mark.asyncio
async def test_reassign_without_replica_degrades_to_restart(tiny_parts):
    """Migration with NO remaining replica of the old stage: the handoff
    has nowhere to ship, the moved node re-adopts... no — the stage goes
    empty until adoption; a generation in flight restarts under a fresh
    session (the pre-handoff behavior) and still completes via the
    adoption path."""
    parts, params = tiny_parts
    n0a = _mk_node(70, 0, 2, backend="qwen3", parts=parts, bootstrap_idx=70)
    n0b = _mk_node(71, 0, 2, backend="qwen3", parts=parts, bootstrap_idx=70)
    n1 = _mk_node(72, 1, 2, backend="qwen3", parts=parts, bootstrap_idx=70)
    nodes = [n0a, n0b, n1]
    await _start_all(nodes)
    try:
        engine = Engine(TINY, params, max_len=64, sampling_cfg=SamplingConfig(temperature=0.0))
        prompt = [3, 7, 11, 19]
        expected = engine.generate(prompt, max_new_tokens=4)
        async with SwarmClient(
            [("127.0.0.1", BASE + 70), ("127.0.0.1", BASE + 71)],
            sampling=SamplingConfig(temperature=0.0), timeout_s=60.0,
        ) as c:
            # start a session, then migrate stage 1's ONLY node to stage 0:
            # its sessions have no adopter; subsequent chunks 5xx/409 and the
            # client restarts, completing once a replica adopts stage 1
            logits = await c._step("deg-session", prompt, 0)
            assert logits.shape[-1] == TINY.vocab_size
            import aiohttp

            async with aiohttp.ClientSession() as s:
                async with s.post(
                    f"http://127.0.0.1:{n1.info.port}/reassign",
                    data=wire.pack({"stage": 0}),
                ) as r:
                    assert r.status == 200
            got = await c.generate_ids(
                prompt, max_new_tokens=4, session_retries=4, retry_delay_s=0.5
            )
        assert got == expected
    finally:
        await _stop_all(nodes)


def test_session_export_import_fp8_kv(tiny_parts):
    """fp8-KV sessions survive the handoff wire trip: the codec can't carry
    float8, so export ships a same-shape uint8 byte view + dtype name and
    import views it back. Continuation on the importer matches the
    exporter's own continuation."""
    import dataclasses

    from inferd_tpu.parallel.stages import StageSpec, extract_stage_params
    from inferd_tpu.runtime.executor import Qwen3StageExecutor

    _, params = tiny_parts
    cfg = dataclasses.replace(TINY, kv_dtype="float8_e4m3fn")
    spec = StageSpec(0, 1, 0, cfg.num_layers - 1)
    sp = extract_stage_params(params, cfg, spec)
    ex1 = Qwen3StageExecutor(cfg, spec, sp, max_len=64)
    ex2 = Qwen3StageExecutor(cfg, spec, sp, max_len=64)

    prompt = [3, 7, 11, 19]
    out1 = ex1.process("s", {"tokens": np.asarray([prompt]), "start_pos": 0})
    exported = ex1.export_sessions()
    assert len(exported) == 1 and exported[0][1]["kv_dtype"] == "float8_e4m3fn"
    # emulate the transport: the payload must survive the wire codec
    payload = wire.unpack(wire.pack(exported[0][1]))
    assert ex2.import_session("s", payload)

    tok = int(np.argmax(out1["logits"][0]))
    step = {"tokens": np.asarray([[tok]]), "start_pos": len(prompt)}
    a = ex1.process("s", dict(step))
    b = ex2.process("s", dict(step))
    np.testing.assert_allclose(a["logits"], b["logits"], rtol=2e-5, atol=2e-5)


@pytest.mark.asyncio
async def test_session_affinity_sticky_across_load_changes():
    """Once a session lands on a replica, later chunks follow it even when
    the other replica becomes less loaded (KV cache lives there)."""
    n = _mk_node(40, 0, 2)
    n.dht._started = False  # offline: seed records directly
    rec_a = {"stage": 1, "load": 0, "cap": 1, "host": "127.0.0.1", "port": 1}
    rec_b = {"stage": 1, "load": 5, "cap": 1, "host": "127.0.0.1", "port": 2}

    class Seed:
        def __init__(self, recs):
            self.recs = recs

        def get_stage(self, stage):
            return self.recs

        def get_all(self, num):
            return {1: self.recs}

    n.dht.get_stage = Seed({"A": rec_a, "B": rec_b}).get_stage  # type: ignore
    n.path_finder.dht = n.dht

    nid1, _ = await n._pick_next("sess", 1)
    assert nid1 == "A"  # min load
    # A becomes heavily loaded; the session must still route to A
    rec_a["load"] = 100
    nid2, _ = await n._pick_next("sess", 1)
    assert nid2 == "A"
    # but a NEW session picks the now-lighter B
    nid3, _ = await n._pick_next("sess2", 1)
    assert nid3 == "B"
    # if A disappears, the affinity entry is dropped and re-picked
    n.dht.get_stage = Seed({"B": rec_b}).get_stage  # type: ignore
    nid4, _ = await n._pick_next("sess", 1)
    assert nid4 == "B"


def test_chunked_prefill_with_padded_growth_matches_full():
    """Chunked prefill whose padded writes cross the cache bucket boundary
    must equal a one-shot forward (regression: overflow check must use the
    padded length, not the real length)."""
    import jax

    from inferd_tpu.parallel.stages import StageSpec, extract_stage_params
    from inferd_tpu.runtime.executor import Qwen3StageExecutor

    cfg = TINY
    params = qwen3.init_params(cfg, __import__("jax").random.PRNGKey(1))
    spec = StageSpec(0, 1, 0, cfg.num_layers - 1)
    ex = Qwen3StageExecutor(
        cfg, spec, extract_stage_params(params, cfg, spec),
        max_len=64, initial_kv_len=16,
    )
    toks = np.asarray(
        __import__("jax").random.randint(
            __import__("jax").random.PRNGKey(2), (1, 21), 0, cfg.vocab_size
        )
    )
    # chunk 1: 14 real -> padded 16 fills the 16-slot bucket exactly;
    # chunk 2: 3 real at start 14 -> padded write of 4 would clamp without
    # the padded-length growth; chunk 3: 4 more.
    out1 = ex.process("s", {"tokens": toks[:, :14], "start_pos": 0})
    out2 = ex.process("s", {"tokens": toks[:, 14:17], "start_pos": 14})
    out3 = ex.process("s", {"tokens": toks[:, 17:21], "start_pos": 17})

    full_logits, _, _ = qwen3.forward(params, cfg, __import__("jax").numpy.asarray(toks))
    np.testing.assert_allclose(
        out3["logits"][0], np.asarray(full_logits[0, 20]), rtol=1e-4, atol=1e-4
    )


def test_balancer_decision_logic():
    """Pure decision test over a fake snapshot (no sockets)."""
    from inferd_tpu.control.balance import Balancer, stage_loads

    class FakeDHT:
        def __init__(self, snap):
            self.snap = snap

        def get_all(self, n):
            return self.snap

    snap = {
        0: {"a": {"load": 8, "cap": 1}},
        1: {"b": {"load": 0, "cap": 1}, "c": {"load": 0, "cap": 1}},
    }
    assert stage_loads(snap) == {0: 8.0, 1: 0.0}

    moved = []

    async def change(stage):
        moved.append(stage)

    b = Balancer(FakeDHT(snap), 2, get_own_stage=lambda: 1, change_stage=change)
    assert asyncio.run(b.rebalance_once()) is True
    assert moved == [0]

    # own stage is the only replica -> must not abandon it
    snap2 = {0: {"a": {"load": 8, "cap": 1}}, 1: {"b": {"load": 0, "cap": 1}}}
    b2 = Balancer(FakeDHT(snap2), 2, get_own_stage=lambda: 1, change_stage=change)
    assert asyncio.run(b2.rebalance_once()) is False

    # balanced -> no move
    snap3 = {
        0: {"a": {"load": 1, "cap": 1}},
        1: {"b": {"load": 1, "cap": 1}, "c": {"load": 1, "cap": 1}},
    }
    b3 = Balancer(FakeDHT(snap3), 2, get_own_stage=lambda: 1, change_stage=change)
    assert asyncio.run(b3.rebalance_once()) is False


@pytest.mark.asyncio
async def test_chunked_prefill_matches_single_shot(tiny_parts):
    """Client-side chunked prefill (prefill_chunk smaller than the prompt)
    must produce exactly the tokens of one-shot prefill — the stage
    executors consume sequential start_pos chunks into the same session
    cache."""
    parts, params = tiny_parts
    nodes = [
        _mk_node(50 + i, i, 2, backend="qwen3", parts=parts, bootstrap_idx=50)
        for i in range(2)
    ]
    await _start_all(nodes)
    try:
        prompt = [3, 7, 11, 19, 23, 29, 31, 37, 41, 2]
        async with SwarmClient(
            [("127.0.0.1", BASE + 50)], sampling=SamplingConfig(temperature=0.0)
        ) as c:
            whole = await c.generate_ids(prompt, max_new_tokens=6)
        async with SwarmClient(
            [("127.0.0.1", BASE + 50)], sampling=SamplingConfig(temperature=0.0),
            prefill_chunk=3,
        ) as c:
            chunked = await c.generate_ids(prompt, max_new_tokens=6)
        assert chunked == whole
    finally:
        await _stop_all(nodes)


@pytest.mark.asyncio
async def test_fp8_kv_swarm_matches_fp8_engine(tiny_parts):
    """Nodes serving with kv_dtype=float8_e4m3fn produce exactly the tokens
    of a single-process engine using the same compressed-cache config."""
    import dataclasses as _dc

    parts, params = tiny_parts
    cfg8 = _dc.replace(TINY, kv_dtype="float8_e4m3fn")
    nodes = []
    for i in range(2):
        info = NodeInfo(
            name=f"f{i}", host="127.0.0.1", port=BASE + 60 + i,
            stage=i, num_stages=2, capacity=4, model_name="tiny",
        )
        dht = SwarmDHT(
            info.node_id, BASE + 160 + i,
            bootstrap=[] if i == 0 else [("127.0.0.1", BASE + 160)],
            host="127.0.0.1", gossip_period_s=0.05, ttl_s=1.5,
        )
        nodes.append(Node(
            info, cfg8, parts, dht, backend="qwen3", max_len=64,
            rebalance_period_s=600.0,
        ))
    await _start_all(nodes)
    try:
        engine = Engine(cfg8, params, max_len=64, sampling_cfg=SamplingConfig(temperature=0.0))
        prompt = [3, 7, 11, 19]
        want = engine.generate(prompt, max_new_tokens=6)
        async with SwarmClient(
            [("127.0.0.1", BASE + 60)], sampling=SamplingConfig(temperature=0.0)
        ) as c:
            got = await c.generate_ids(prompt, max_new_tokens=6)
        assert got == want
    finally:
        await _stop_all(nodes)


@pytest.mark.asyncio
async def test_entry_failover_rescued_via_gossip_sessions(tiny_parts):
    """Swarm-shared session location: a mid-session chunk posted to a
    DIFFERENT same-stage entry (the client failed over; the new entry has
    no local affinity and no KV) is relayed to the replica ADVERTISING the
    session in its gossip record — the generation continues without a
    session restart (round-2 weak #7)."""
    parts, params = tiny_parts
    n0a = _mk_node(80, 0, 2, backend="qwen3", parts=parts, bootstrap_idx=80)
    n0b = _mk_node(81, 0, 2, backend="qwen3", parts=parts, bootstrap_idx=80)
    n1 = _mk_node(82, 1, 2, backend="qwen3", parts=parts, bootstrap_idx=80)
    nodes = [n0a, n0b, n1]
    await _start_all(nodes)
    try:
        engine = Engine(TINY, params, max_len=64, sampling_cfg=SamplingConfig(temperature=0.0))
        prompt = [3, 7, 11, 19]
        expected = engine.generate(prompt, max_new_tokens=6)
        sid = "failover-session"
        async with SwarmClient(
            [("127.0.0.1", BASE + 80)], sampling=SamplingConfig(temperature=0.0)
        ) as c_a:
            logits = await c_a._step(sid, prompt, 0)
            toks = [int(np.argmax(logits))]
            pos = len(prompt)
            for _ in range(2):
                logits = await c_a._step(sid, [toks[-1]], pos)
                pos += 1
                toks.append(int(np.argmax(logits)))
        assert sid in n0a.executor.sessions  # stage-0 KV lives on n0a
        # wait for n0a's session advert to reach n0b's gossip view
        from inferd_tpu.runtime.node import sess_hash

        for _ in range(100):
            v = n0b.dht.get_stage(0).get(n0a.info.node_id, {})
            if sess_hash(sid) in (v.get("sess") or ()):
                break
            await asyncio.sleep(0.05)
        else:
            raise TimeoutError("session advert never gossiped")
        # client fails over: remaining chunks enter via n0b
        async with SwarmClient(
            [("127.0.0.1", BASE + 81)], sampling=SamplingConfig(temperature=0.0)
        ) as c_b:
            for _ in range(3):
                logits = await c_b._step(sid, [toks[-1]], pos)
                pos += 1
                toks.append(int(np.argmax(logits)))
            await c_b._end_session(sid)
        assert toks == expected
        m = n0b.metrics.snapshot()["counters"]
        assert m.get("sessions.rescue_relay", 0) >= 1
    finally:
        await _stop_all(nodes)


@pytest.fixture(scope="module")
def tiny_parts3(tmp_path_factory):
    parts = tmp_path_factory.mktemp("parts3")
    params = qwen3.init_params(TINY, __import__("jax").random.PRNGKey(0))
    manifest = Manifest.even_split("tiny", 3)
    split_and_save(params, TINY, manifest, str(parts))
    return str(parts), params


@pytest.mark.asyncio
async def test_trace_merged_timeline_three_stage_swarm(tiny_parts3, tmp_path):
    """Distributed-tracing e2e (docs/OBSERVABILITY.md): a generation
    through a 3-stage swarm ENTERED AT THE WRONG NODE (the stage-1
    replica, forcing a relay-mismatch hop) yields ONE merged trace whose
    spans nest correctly across client + all three nodes, carry per-stage
    queue/compute/relay breakdowns, and account for >= 90% of the
    measured client wall time."""
    import time as _time

    from inferd_tpu.obs import merge as obs_merge

    parts, params = tiny_parts3
    nodes = [
        _mk_node(90 + i, i, 3, backend="qwen3", parts=parts, bootstrap_idx=90)
        for i in range(3)
    ]
    await _start_all(nodes)
    spans_dir = tmp_path / "spans"
    try:
        prompt = [3, 7, 11, 19]
        async with SwarmClient(
            [("127.0.0.1", BASE + 91)],  # stage-1 entry: every chunk
            # arrives at the wrong node and relays to stage 0 first
            sampling=SamplingConfig(temperature=0.0),
        ) as c:
            t0 = _time.perf_counter()
            out = await c.generate_ids(prompt, max_new_tokens=4)
            wall_ms = (_time.perf_counter() - t0) * 1e3
            assert len(out) == 4
            c.tracer.dump_jsonl(str(spans_dir / "client.spans.jsonl"))
        # dump BEFORE stopping: graceful-stop handoffs would add their own
        # traces to the ring
        for n in nodes:
            n.tracer.dump_jsonl(
                str(spans_dir / (n.info.node_id.replace(":", "_") + ".spans.jsonl"))
            )
    finally:
        await _stop_all(nodes)

    result = obs_merge.merge_paths([str(spans_dir)])
    assert result["skipped_lines"] == 0
    assert len(result["traces"]) == 1  # one generation == one trace
    t = result["traces"][0]
    assert t["root"]["name"] == "generate"
    assert t["root"]["service"] == "client"
    # every child nests inside its parent after skew correction
    assert t["nest_violations"] == []
    # client + all three stage nodes participated
    assert len(t["services"]) == 4
    # per-stage breakdown: compute on every stage, queue spans present
    assert set(t["stages"]) == {"0", "1", "2"}
    for row in t["stages"].values():
        assert row.get("compute_ms", 0) > 0
        assert row.get("queue_ms", 0) >= 0
    # the wrong-entry node recorded the mismatch relay hop(s)
    mismatch = [
        s for s in result["spans"]
        if s["service"] == nodes[1].info.node_id
        and s.get("phase") == "relay"
        and (s.get("attrs") or {}).get("mismatch")
    ]
    assert mismatch, "stage-1 entry must relay-mismatch to stage 0"
    # the merged timeline accounts for >= 90% of the measured wall time:
    # the root span covers the timed call and its direct children (step +
    # sample spans) cover the root
    assert t["wall_ms"] >= 0.9 * wall_ms
    assert t["coverage"] >= 0.9
    # token accounting: 4 sampled tokens, TTFT inside the wall
    assert t["tokens"] == 4
    assert t["ttft_ms"] is not None and 0 < t["ttft_ms"] <= t["wall_ms"]
    assert t["per_token_ms"] is not None and t["per_token_ms"] > 0


@pytest.mark.asyncio
async def test_trace_server_side_generate_joins_client_trace(
    tiny_parts, tmp_path
):
    """/generate tracing rides the X-Inferd-Trace header: a standalone
    client's server-side generation merges into ONE trace rooted at the
    CLIENT, with the node's self-driven token loop nested under the
    node's /generate umbrella — and the umbrella (phase `server`, not
    `sample`) must not inflate the token count."""
    from inferd_tpu.obs import merge as obs_merge

    parts, params = tiny_parts
    nodes = [
        _mk_node(98 + i, i, 2, backend="qwen3", parts=parts, bootstrap_idx=98)
        for i in range(2)
    ]
    await _start_all(nodes)
    spans_dir = tmp_path / "spans"
    try:
        async with SwarmClient(
            [("127.0.0.1", BASE + 98)], sampling=SamplingConfig(temperature=0.0)
        ) as c:
            ids = await c.generate_server_side([3, 7, 11, 19], max_new_tokens=3)
            assert len(ids) == 3
            c.tracer.dump_jsonl(str(spans_dir / "client.spans.jsonl"))
        for n in nodes:
            n.tracer.dump_jsonl(
                str(spans_dir / (n.info.node_id.replace(":", "_") + ".spans.jsonl"))
            )
    finally:
        await _stop_all(nodes)
    result = obs_merge.merge_paths([str(spans_dir)])
    assert len(result["traces"]) == 1
    t = result["traces"][0]
    assert t["root"]["service"] == "client"
    assert t["tokens"] == 3  # umbrella not counted as a sampled token
    assert t["nest_violations"] == []
    # the node-side /generate umbrella exists and is server-phase
    assert any(
        s["name"] == "generate" and s["phase"] == "server"
        for s in result["spans"]
    )


@pytest.mark.asyncio
async def test_metrics_endpoint_prometheus_and_spans():
    """/metrics serves parseable Prometheus text exposition including the
    new gauges, and /spans serves the live ring as ndjson."""
    import aiohttp

    from inferd_tpu.obs import export as obs_export

    nodes = [_mk_node(95, 0, 1)]
    await _start_all(nodes)
    try:
        async with SwarmClient([("127.0.0.1", BASE + 95)]) as c:
            await c._post(
                "/forward", {"stage": 0, "session_id": "m1", "payload": {}}
            )
        async with aiohttp.ClientSession() as s:
            async with s.get(f"http://127.0.0.1:{BASE + 95}/metrics") as r:
                assert r.status == 200
                assert "text/plain" in r.headers["Content-Type"]
                text = await r.text()
            async with s.get(f"http://127.0.0.1:{BASE + 95}/spans") as r:
                assert r.status == 200
                ndjson = await r.text()
        assert obs_export.validate_exposition(text) == []
        # counters, gauges (inflight/sessions/queue depth/span ring), and
        # histogram series all present
        assert "inferd_forward_requests_total" in text
        assert "inferd_inflight" in text
        assert "inferd_sessions" in text
        assert "inferd_queue_depth" in text
        assert "inferd_trace_overhead_ms" in text
        assert "inferd_stage_compute_ms_bucket" in text
        import json as _json

        spans = [
            _json.loads(ln) for ln in ndjson.splitlines() if ln.strip()
        ]
        assert any(sp["name"] == "forward" for sp in spans)
    finally:
        await _stop_all(nodes)


@pytest.mark.asyncio
async def test_tracing_disabled_leaves_envelope_and_behavior_intact(
    tiny_parts, monkeypatch
):
    """INFERD_TRACE=0: no spans recorded anywhere, no `trace` key on the
    wire, generation identical."""
    monkeypatch.setenv("INFERD_TRACE", "0")
    parts, params = tiny_parts
    nodes = [
        _mk_node(96 + i, i, 2, backend="qwen3", parts=parts, bootstrap_idx=96)
        for i in range(2)
    ]
    await _start_all(nodes)
    try:
        engine = Engine(TINY, params, max_len=64,
                        sampling_cfg=SamplingConfig(temperature=0.0))
        prompt = [3, 7, 11, 19]
        async with SwarmClient(
            [("127.0.0.1", BASE + 96)], sampling=SamplingConfig(temperature=0.0)
        ) as c:
            got = await c.generate_ids(prompt, max_new_tokens=4)
            assert got == engine.generate(prompt, max_new_tokens=4)
            assert c.tracer.spans() == []
        for n in nodes:
            assert n.tracer.spans() == []
    finally:
        await _stop_all(nodes)


@pytest.mark.asyncio
async def test_graceful_entry_death_hands_off_and_failover_continues(tiny_parts):
    """The entry node STOPS mid-generation: its graceful shutdown hands the
    session KV to the surviving same-stage replica, the client fails over
    to it, and the generation continues WITHOUT a session restart (the
    round-2 verdict's swarm-shared-affinity e2e)."""
    parts, params = tiny_parts
    n0a = _mk_node(85, 0, 2, backend="qwen3", parts=parts, bootstrap_idx=85)
    n0b = _mk_node(86, 0, 2, backend="qwen3", parts=parts, bootstrap_idx=85)
    n1 = _mk_node(87, 1, 2, backend="qwen3", parts=parts, bootstrap_idx=85)
    nodes = [n0a, n0b, n1]
    await _start_all(nodes)
    stopped = []
    try:
        engine = Engine(TINY, params, max_len=64, sampling_cfg=SamplingConfig(temperature=0.0))
        prompt = [3, 7, 11, 19]
        expected = engine.generate(prompt, max_new_tokens=6)
        sid = "dying-entry-session"
        async with SwarmClient(
            [("127.0.0.1", BASE + 85), ("127.0.0.1", BASE + 86)],
            sampling=SamplingConfig(temperature=0.0),
        ) as c:
            logits = await c._step(sid, prompt, 0)
            toks = [int(np.argmax(logits))]
            pos = len(prompt)
            for _ in range(2):
                logits = await c._step(sid, [toks[-1]], pos)
                pos += 1
                toks.append(int(np.argmax(logits)))
            assert sid in n0a.executor.sessions
            # the entry dies gracefully: handoff ships its stage-0 KV to n0b
            await n0a.stop()
            stopped.append(n0a)
            assert sid in n0b.executor.sessions
            assert n0b.metrics.snapshot()["counters"].get("sessions.imported", 0) >= 1
            # the client's entry failover lands on n0b, which now HOLDS the
            # session — generation continues, no restart possible (the raw
            # protocol would 409 on any out-of-order position)
            for _ in range(3):
                logits = await c._step(sid, [toks[-1]], pos)
                pos += 1
                toks.append(int(np.argmax(logits)))
            await c._end_session(sid)
        assert toks == expected
    finally:
        await _stop_all([n for n in nodes if n not in stopped])
