"""Native wire codec tests: the C++ extension and the pure-Python reference
implementation must produce BYTE-IDENTICAL frames and round-trip each
other's output (mixed swarms interoperate); plus adversarial-input and
performance sanity checks."""

import numpy as np
import pytest

from inferd_tpu import native
from inferd_tpu.native import pyimpl
from inferd_tpu.runtime import wire

NATIVE = native.codec

SAMPLES = [
    None,
    True,
    False,
    0,
    -(2**63),
    2**63 - 1,
    3.14159,
    "",
    "héllo wörld",
    b"\x00\xff raw",
    [],
    {},
    [1, [2, [3, None]], "x"],
    {"a": 1, "b": {"c": [True, 2.5]}},
    {"t": np.arange(24, dtype=np.int32).reshape(2, 3, 4)},
    {"scalar": np.float32(3.5)},
    {"empty": np.zeros((0, 4), dtype=np.float64)},
    {"bool_arr": np.array([True, False, True])},
]


def _py_pack(obj):
    return pyimpl.pack(obj, native.tensor_parts)


def _py_unpack(b):
    return pyimpl.unpack(b, native.tensor_build)


def _eq(a, b):
    if isinstance(a, np.ndarray) or isinstance(b, np.ndarray):
        return (
            np.asarray(a).dtype == np.asarray(b).dtype
            and np.asarray(a).shape == np.asarray(b).shape
            and np.array_equal(np.asarray(a), np.asarray(b))
        )
    if isinstance(a, dict):
        return set(a) == set(b) and all(_eq(a[k], b[k]) for k in a)
    if isinstance(a, list):
        return len(a) == len(b) and all(_eq(x, y) for x, y in zip(a, b))
    return a == b


@pytest.mark.parametrize("obj", SAMPLES, ids=range(len(SAMPLES)))
def test_python_impl_roundtrip(obj):
    assert _eq(_py_unpack(_py_pack(obj)), obj if not isinstance(obj, tuple) else list(obj))


@pytest.mark.skipif(NATIVE is None, reason="native codec not built")
@pytest.mark.parametrize("obj", SAMPLES, ids=range(len(SAMPLES)))
def test_native_matches_python_bytes(obj):
    """Byte-identical frames: the format has ONE canonical encoding."""
    assert NATIVE.pack(obj) == _py_pack(obj)


@pytest.mark.skipif(NATIVE is None, reason="native codec not built")
@pytest.mark.parametrize("obj", SAMPLES, ids=range(len(SAMPLES)))
def test_cross_impl_roundtrip(obj):
    assert _eq(NATIVE.unpack(_py_pack(obj)), obj)
    assert _eq(_py_unpack(NATIVE.pack(obj)), obj)


@pytest.mark.skipif(NATIVE is None, reason="native codec not built")
def test_native_bf16_roundtrip():
    import ml_dtypes

    a = np.arange(8, dtype=np.float32).astype(ml_dtypes.bfloat16).reshape(2, 4)
    out = NATIVE.unpack(NATIVE.pack({"x": a}))["x"]
    assert out.dtype == a.dtype
    np.testing.assert_array_equal(out.astype(np.float32), a.astype(np.float32))


def test_tuple_becomes_list():
    out = _py_unpack(_py_pack({"t": (1, 2, 3)}))
    assert out["t"] == [1, 2, 3]


def test_rejects_non_str_keys():
    with pytest.raises(TypeError):
        _py_pack({1: "x"})
    if NATIVE is not None:
        with pytest.raises(TypeError):
            NATIVE.pack({1: "x"})


def test_rejects_oversize_int():
    with pytest.raises(OverflowError):
        _py_pack(2**63)
    if NATIVE is not None:
        with pytest.raises(OverflowError):
            NATIVE.pack(2**63)


@pytest.mark.parametrize("impl", ["py", "native"])
def test_truncated_frames_rejected(impl):
    if impl == "native" and NATIVE is None:
        pytest.skip("native codec not built")
    unpack = _py_unpack if impl == "py" else NATIVE.unpack
    blob = _py_pack({"x": np.arange(16, dtype=np.float32), "s": "hello"})
    for cut in [1, 3, 4, 10, len(blob) // 2, len(blob) - 1]:
        with pytest.raises(ValueError):
            unpack(blob[:cut])
    with pytest.raises(ValueError):
        unpack(blob + b"extra")
    with pytest.raises(ValueError):
        unpack(b"XX\x01" + blob[3:])  # bad magic


def test_wire_pack_is_v1_and_legacy_decodes():
    """wire.pack emits v1; wire.unpack still reads legacy msgpack."""
    env = {"payload": {"x": np.arange(4, dtype=np.int64)}, "stage": 2}
    assert wire.pack(env)[:3] == pyimpl.MAGIC
    legacy = wire.pack_legacy(env)
    out = wire.unpack(legacy)
    np.testing.assert_array_equal(out["payload"]["x"], env["payload"]["x"])
    assert out["stage"] == 2


@pytest.mark.skipif(NATIVE is None, reason="native codec not built")
def test_native_faster_than_msgpack_on_tensors():
    """Perf sanity on a realistic activation envelope (not a strict bench —
    just catches the native path accidentally regressing to slower-than-
    legacy)."""
    import time

    hidden = np.random.randn(4, 512, 1024).astype(np.float32)  # 8 MB
    env = {"session_id": "s", "stage": 1, "payload": {"hidden": hidden, "start_pos": 0}}

    def timeit(fn, n=10):
        fn()  # warm
        t0 = time.perf_counter()
        for _ in range(n):
            fn()
        return (time.perf_counter() - t0) / n

    t_native = timeit(lambda: NATIVE.unpack(NATIVE.pack(env)))
    t_legacy = timeit(lambda: wire.unpack(wire.pack_legacy(env)))
    # allow generous slack for CI noise; typical speedup is >1.5x
    assert t_native < t_legacy * 1.2, (t_native, t_legacy)


def test_legacy_emission_knob(monkeypatch):
    """INFERD_WIRE=legacy makes pack emit msgpack (rolling-upgrade path).
    The knob is read PER CALL — no module reload needed, so mixed-version
    tests can flip emission mid-process."""
    monkeypatch.setenv("INFERD_WIRE", "legacy")
    blob = wire.pack({"x": np.arange(3, dtype=np.int32)})
    assert blob[:3] != pyimpl.MAGIC  # msgpack, not v1
    out = wire.unpack(blob)
    np.testing.assert_array_equal(out["x"], np.arange(3, dtype=np.int32))
    # back to v1 in the SAME process: the next pack emits native frames
    monkeypatch.setenv("INFERD_WIRE", "v1")
    blob2 = wire.pack({"x": np.arange(3, dtype=np.int32)})
    assert blob2[:3] == pyimpl.MAGIC
    np.testing.assert_array_equal(
        wire.unpack(blob2)["x"], np.arange(3, dtype=np.int32)
    )
