"""Regenerate tests/data/prof: deterministic fresh-vs-regressed
live-anatomy histories + the priors table the sentinel judges against.
Run from the repo root:  python tests/data/prof/generate.py
"""
import json, os

from inferd_tpu.obs import tsdb as tsdblib
from inferd_tpu.utils.metrics import Metrics

OUT = os.path.join(os.path.dirname(__file__), "..") if False else "tests/data/prof"
PRIOR_TOK_MS = 10.0

def build(service, stage, tok_ms, t0=1700000000.0):
    m = Metrics()
    clock = [t0]
    t = tsdblib.Tsdb(
        m, service=service,
        meta={"stage": stage, "num_stages": 2, "chip": "cpu",
              "preset": "tiny", "quant": "none"},
        clock=lambda: clock[0],
    )
    t.sample()
    # 10 minutes of steady decode: 5 tokens/s at tok_ms per token
    for _ in range(600):
        clock[0] += 1.0
        m.inc("stage.tokens", 5)
        for _ in range(5):
            m.observe("stage.compute_ms", tok_ms)
        m.inc("forward.requests", 5)
        # the live-anatomy gauges a prof-enabled node publishes
        m.set_gauge("anatomy.attention_ms", round(tok_ms * 0.5, 3))
        m.set_gauge("anatomy.attention_frac", 0.12)
        m.set_gauge("anatomy.mlp_ms", round(tok_ms * 0.3, 3))
        m.set_gauge("anatomy.mlp_frac", 0.2)
        m.set_gauge("roofline.frac", 0.15)
        m.set_gauge("roofline.live_frac", round(0.001 * 10.0 / tok_ms, 5))
        m.set_gauge("perf.regression",
                    1.0 if tok_ms > PRIOR_TOK_MS * 1.2 else 0.0)
        m.set_gauge("prof.overhead_ms", 4.0)
        t.sample()
    return t.history()

os.makedirs(OUT, exist_ok=True)
for name, stage, tok_ms in (
    ("fresh", 1, 10.0),      # matches the committed prior
    ("regressed", 1, 15.0),  # +50% per-token cost: the sentinel fires
):
    h = build(f"10.0.0.{1 if name == 'fresh' else 2}:6050", stage, tok_ms)
    assert tsdblib.validate_history(h) == []
    with open(os.path.join(OUT, f"{name}.history.json"), "w") as f:
        json.dump(h, f, separators=(",", ":"))
with open(os.path.join(OUT, "priors.json"), "w") as f:
    json.dump({"v": 1, "priors": {
        "cpu|tiny|none|s1": {"tok_ms": PRIOR_TOK_MS},
    }}, f, indent=1)
print("wrote", sorted(os.listdir(OUT)))
