"""Fleet telemetry plane tests (PR 7): windowed tsdb rings, trailing
quantiles replacing all-time ones in gossip//health, multi-window
burn-rate SLO rules, canary probing with user-SLI isolation, MAD
replica-outlier detection feeding routing, the fleet SLI aggregator, and
the perf-gate budget extension — unit level plus the e2e fault-injection
acceptance (a slowed stage replica is flagged, routed around, and shows
up in `obs fleet` output assembled from per-node artifacts alone)."""

import asyncio
import json
import os

import pytest

from inferd_tpu.obs import canary as canarylib
from inferd_tpu.obs import fleet as fleetlib
from inferd_tpu.obs import health as healthlib
from inferd_tpu.obs import tsdb as tsdblib
from inferd_tpu.obs.__main__ import main as obs_main
from inferd_tpu.utils.metrics import Metrics

from test_node_e2e import BASE, _mk_node, _start_all, _stop_all, tiny_parts  # noqa: F401

FLEET_FIXTURE = os.path.join(os.path.dirname(__file__), "data", "fleet")
BURN_FIXTURE = os.path.join(os.path.dirname(__file__), "data", "health_burn")


def _clocked_tsdb(metrics, **kw):
    clock = [1000.0]
    t = tsdblib.Tsdb(metrics, clock=lambda: clock[0], **kw)
    return t, clock


# ---------------------------------------------------------------- tsdb core


def test_tsdb_counter_rates_and_windows():
    m = Metrics()
    t, clock = _clocked_tsdb(m, service="n0")
    t.sample()
    for _ in range(30):
        clock[0] += 1.0
        m.inc("forward.requests", 4)
        t.sample()
    # ~4/s over any window the series lived (bucket-edge inclusion can
    # over-read by one bucket: a 10 s horizon spans 11 bucket starts)
    assert t.trailing_rate("forward.requests", 10.0) == pytest.approx(4.0, rel=0.15)
    assert t.trailing_rate("forward.requests", 30.0) == pytest.approx(4.0, rel=0.1)
    assert t.trailing_rate("missing.series") is None
    # idle minute: the window empties, the rate decays to zero
    clock[0] += 120.0
    t.sample()
    assert t.trailing_rate("forward.requests", 60.0) == pytest.approx(0.0)


def test_tsdb_counter_reset_rebaselines():
    """A counter that goes BACKWARD (process restart feeding the same
    registry name) re-baselines instead of booking a negative delta."""
    m = Metrics()
    m.inc("c", 100)
    t, clock = _clocked_tsdb(m)
    t.sample()  # first sighting: the pre-existing 100 is baseline, not a burst
    m.inc("c", 20)
    clock[0] += 1
    t.sample()
    m.set_counter("c", 5.0)  # simulated reset (through the locked API)
    clock[0] += 1
    t.sample()
    m.inc("c", 5)
    clock[0] += 1
    t.sample()
    total = sum(v for _t, v in t.history()["counters"]["c"][0])
    assert total == 25  # 20 before the reset + 5 after; neither the
    # attach-time 100 nor a negative reset delta ever booked


def test_tsdb_attach_baseline_vs_sparse_first_event():
    """Two baselining contracts at once: counters that PRE-EXIST the
    tsdb are attach-time baselines (their past must not book as one
    burst), while a series born LATER books from zero — a sparse
    counter's first event (one canary failure) must land in the
    window, not vanish into a first-sighting baseline."""
    m = Metrics()
    m.inc("old.counter", 500)
    t, clock = _clocked_tsdb(m)
    clock[0] += 1
    m.inc("canary.fail")  # born post-attach: the single event books
    t.sample()
    assert t.trailing_rate("old.counter", 60.0) == 0.0
    total = sum(v for _t, v in t.history()["counters"]["canary.fail"][0])
    assert total == 1


def test_tsdb_young_series_not_diluted():
    """A counter born 10 s ago must not spread its burst over a 60 s
    window it never lived (the reach clamp)."""
    m = Metrics()
    t, clock = _clocked_tsdb(m)
    t.sample()
    for _ in range(10):
        clock[0] += 1.0
        m.inc("errors", 6)
        t.sample()
    # 60 observed in ~10 lived seconds: ~6/s, NOT 1/s
    assert t.trailing_rate("errors", 60.0) == pytest.approx(6.0, rel=0.15)


def test_tsdb_gauge_last_wins_and_staleness():
    m = Metrics()
    t, clock = _clocked_tsdb(m)
    m.set_gauge("queue.depth", 3)
    t.sample()
    clock[0] += 5
    m.set_gauge("queue.depth", 9)
    t.sample()
    assert tsdblib.trailing_gauge(t.history(), "queue.depth", 60.0) == 9.0
    clock[0] += 600
    t.sample()  # gauge still set, current bucket carries it
    assert tsdblib.trailing_gauge(t.history(), "queue.depth", 60.0) == 9.0


def test_tsdb_slow_then_recovered_p99_drops_within_horizon():
    """THE acceptance property the cumulative Histogram cannot provide:
    a replica that was slow and then recovered stops reporting an
    elevated trailing p99 once the slow samples age past the horizon —
    while the all-time histogram keeps the elevated p99 forever."""
    m = Metrics()
    t, clock = _clocked_tsdb(m)
    t.sample()
    for _ in range(20):
        clock[0] += 1.0
        m.observe("hop.relay_ms", 900.0)  # the bad minute
        t.sample()
    bad = t.trailing_quantiles("hop.relay_ms", 60.0)
    assert bad["p99_ms"] >= 900.0
    # recovery: a minute of fast hops pushes the slow ones out of window
    for _ in range(70):
        clock[0] += 1.0
        m.observe("hop.relay_ms", 2.0)
        t.sample()
    good = t.trailing_quantiles("hop.relay_ms", 60.0)
    assert good["p99_ms"] <= 10.0, good
    # the cumulative histogram still reports the incident — forever
    assert m.histograms["hop.relay_ms"].quantile(0.99) >= 900.0


def test_tsdb_downsampling_ladder_reach():
    """Old data lives only in the coarse levels; queries pick the finest
    level whose reach covers the horizon."""
    m = Metrics()
    t, clock = _clocked_tsdb(m, levels=((1.0, 10), (10.0, 20), (60.0, 30)))
    t.sample()
    for _ in range(120):
        clock[0] += 1.0
        m.inc("c", 1)
        t.sample()
    rings = t.history()["counters"]["c"]
    assert len(rings[0]) == 10  # fine level: capped, recent only
    assert sum(v for _t, v in rings[1]) > sum(v for _t, v in rings[0])
    # 100 s horizon exceeds the 10-bucket 1 s level: level 1 serves it
    h = t.history()
    assert tsdblib._pick_level(h, 5.0) == 0
    assert tsdblib._pick_level(h, 100.0) == 1
    assert tsdblib._pick_level(h, 100000.0) == 2  # clamped to coarsest


def test_tsdb_fleet_merge_is_bucket_true():
    """Merged fleet percentiles come from SUMMED bucket deltas — one
    slow node among fast ones shifts the fleet p99 but not the p50
    (an average-of-averages would corrupt both)."""
    hs = []
    for node, lat in (("a", 2.0), ("b", 2.0), ("c", 2.0), ("d", 5000.0)):
        m = Metrics()
        t, clock = _clocked_tsdb(m, service=node)
        t.sample()
        for _ in range(30):
            clock[0] += 1.0
            m.observe("hop.relay_ms", lat)
            t.sample()
        hs.append(t.history())
    q = tsdblib.merged_quantiles(hs, "hop.relay_ms", 60.0)
    assert q["p50_ms"] <= 5.0  # 3/4 of samples are fast
    assert q["p99_ms"] >= 5000.0  # the slow node owns the tail
    assert q["count"] > 60
    # a node with MISMATCHED bucket bounds degrades (skipped), not corrupts
    m = Metrics()
    t, clock = _clocked_tsdb(m, service="weird")
    t.sample()
    clock[0] += 1
    m.observe("hop.relay_ms", 3.0, bounds_ms=[1, 2, 3])
    t.sample()
    q2 = tsdblib.merged_quantiles(hs + [t.history()], "hop.relay_ms", 60.0)
    assert q2["count"] == q["count"]


def test_history_schema_validates_and_golden_fixture():
    """The /metrics/history JSON schema: live objects and the committed
    golden fixture both pass validate_history; corruptions are named."""
    m = Metrics()
    t, clock = _clocked_tsdb(m)
    m.observe("h", 1.0)
    t.sample()  # "h" born post-attach: its first observation books
    clock[0] += 1
    m.inc("c")
    m.set_gauge("g", 2)
    m.observe("h", 3.0)
    t.sample()
    h = t.history()
    assert tsdblib.validate_history(h) == []
    # committed golden fixture (regenerate via the script in its header
    # comment... it is deterministic: fixed clock, fixed drives)
    fixture = tsdblib.load_history_file(
        os.path.join(FLEET_FIXTURE, "node0.history.json")
    )
    assert fixture["service"] == "10.0.0.2:6050"
    assert fixture["meta"]["stage"] == 0
    # trailing queries over the committed rings are deterministic
    q = tsdblib.trailing_quantiles(fixture, "generate.ttft_ms", 60.0)
    assert q is not None and q["p50_ms"] > 0
    # corruption: negative bucket count
    bad = json.loads(json.dumps(h))
    bad["histograms"]["h"]["levels"][0][0][1][0] = -1
    assert any("bucket" in p for p in tsdblib.validate_history(bad))
    # corruption: counts/total mismatch
    bad2 = json.loads(json.dumps(h))
    bad2["histograms"]["h"]["levels"][0][0][2] += 5
    assert any("total" in p for p in tsdblib.validate_history(bad2))
    assert tsdblib.validate_history([1, 2]) == ["history is not a JSON object"]


# ------------------------------------------------------------- burn rules


def test_burn_rule_parse_forms_and_errors():
    r = healthlib.Rule.parse("burn:availability[5m,1h] > 14")
    assert r.signal == "burn:availability[5m,1h]"
    sig = healthlib.BurnSignal.parse("availability@99.5[5m,1h]")
    assert sig.objective == 99.5
    assert sig.windows == (300.0, 3600.0)
    # the canary-excluded generate.* family, NOT the node-wide counters
    # probe traffic bumps (obs.health.BURN_SLIS rationale)
    assert sig.bad == "generate.errors" and sig.total == "generate.requests"
    for bad in (
        "burn:nope[5m] > 1",            # unknown SLI
        "burn:availability > 1",        # no window
        "burn:availability[5q] > 1",    # bad unit
        "burn:availability[1m,5m,1h] > 1",  # too many windows
        "burn:availability@200[5m] > 1",    # objective out of range
    ):
        with pytest.raises(ValueError):
            healthlib.Rule.parse(bad)


def _burning_history(error_frac=0.1, seconds=3900, step=5.0):
    m = Metrics()
    t, clock = _clocked_tsdb(m)
    t.sample()
    for i in range(int(seconds / step)):
        clock[0] += step
        m.inc("generate.requests", 10)
        if error_frac and i % int(1 / error_frac) == 0:
            m.inc("generate.errors", 10 * error_frac * (1 / error_frac))
        t.sample()
    return t.history(), clock[0]


def test_burn_rule_needs_both_windows():
    """The multi-window AND: a burst that only poisons the short window
    does not fire; sustained burn firing both windows does."""
    rule = healthlib.Rule.parse("burn:availability[5m,1h] > 14")
    # sustained 10% errors vs 0.1% budget = 100x in both windows
    h, now = _burning_history(error_frac=0.1)
    fired, val, _ = healthlib.evaluate_rule(rule, {}, histories=[h], now=now)
    assert fired is True and val > 14
    # clean hour, then a 2-minute burst: short window burns, long does not
    m = Metrics()
    t, clock = _clocked_tsdb(m)
    t.sample()
    for _ in range(720):
        clock[0] += 5.0
        m.inc("generate.requests", 10)
        t.sample()
    for _ in range(24):
        clock[0] += 5.0
        m.inc("generate.requests", 10)
        m.inc("generate.errors", 1)
        t.sample()
    fired, _, _ = healthlib.evaluate_rule(
        rule, {}, histories=[t.history()], now=clock[0]
    )
    assert fired is False  # the 1h window vetoes the flap
    # no history at all: SKIP, not green
    assert healthlib.evaluate_rule(rule, {}) == (None, None, None)


def test_burn_fixture_one_firing_one_quiet(capsys):
    """Acceptance: the committed health_burn fixture evaluates one
    firing burn rule (availability, degraded) and one quiet one (canary)
    through `obs health --check` — rc 0, since degraded is not failing."""
    assert obs_main(["health", "--check", BURN_FIXTURE]) == 0
    out = capsys.readouterr().out
    assert "DEGRADED" in out
    assert "burn:availability[5m,1h] > 14" in out
    assert "burn:canary" not in out.split("firing")[0] or True
    assert "2 rules evaluated, 1 firing" in out


def test_burn_failing_severity_fails_check(tmp_path, capsys):
    """A burn rule at failing severity flips the check's exit code."""
    h, _now = _burning_history(error_frac=0.1)
    d = tmp_path / "scrape"
    d.mkdir()
    (d / "node0.history.json").write_text(json.dumps(h))
    (d / "rules.json").write_text(json.dumps(
        [{"rule": "burn:availability[5m,1h] > 14", "severity": "failing"}]
    ))
    assert obs_main(["health", "--check", str(d)]) == 1
    assert "FAILING" in capsys.readouterr().out


def test_load_scrape_skips_truncated_history(tmp_path):
    """A node killed mid-dump leaves a truncated *.history.json — the
    loader skips it (degrade-don't-crash) instead of failing the whole
    verdict, and a lone bad file leaves histories=None (burn rules
    SKIP)."""
    h, _now = _burning_history()
    d = tmp_path / "scrape"
    d.mkdir()
    good = json.dumps(h)
    (d / "a.history.json").write_text(good)
    (d / "b.history.json").write_text(good[: len(good) // 2])  # truncated
    loaded = healthlib.load_scrape([str(d)])
    assert len(loaded["histories"]) == 1
    (d / "a.history.json").unlink()
    loaded = healthlib.load_scrape([str(d)])
    assert loaded["histories"] is None


def test_burn_gauges():
    h, now = _burning_history(error_frac=0.1)
    g = healthlib.burn_gauges([h], now=now)
    assert g["burn.availability"] > 14
    assert "burn.canary" not in g  # no canary series in this history
    assert healthlib.burn_gauges(None) == {}


# -------------------------------------------------------- outlier detection


def _stage_map(**vals):
    return {
        nid: {"hop_p99_ms": v} if v is not None else {}
        for nid, v in vals.items()
    }


def test_detect_outliers_mad_flag_and_one_sided():
    flagged = canarylib.detect_outliers(
        _stage_map(a=10.0, b=12.0, c=11.0, d=300.0)
    )
    assert set(flagged) == {"d"}
    assert flagged["d"]["field"] == "hop_p99_ms"
    assert flagged["d"]["value"] == 300.0
    # one-sided: an unusually FAST replica is not a problem
    assert canarylib.detect_outliers(
        _stage_map(a=100.0, b=110.0, c=105.0, d=0.5)
    ) == {}
    # an ultra-tight stage never flags micro-jitter (the MAD floor)
    assert canarylib.detect_outliers(
        _stage_map(a=1.0, b=1.1, c=1.05, d=1.4)
    ) == {}


def test_detect_outliers_fallback_and_mixed_version():
    # fewer than min_peers carry hop_p99_ms (last-stage replicas, or old
    # peers): the comparison retries on svc_p99_ms
    sm = {
        "a": {"svc_p99_ms": 5.0},
        "b": {"svc_p99_ms": 6.0},
        "c": {"svc_p99_ms": 5.5},
        "d": {"svc_p99_ms": 200.0},
    }
    flagged = canarylib.detect_outliers(sm)
    assert set(flagged) == {"d"} and flagged["d"]["field"] == "svc_p99_ms"
    # mixed-version: records lacking BOTH fields simply don't vote
    sm["old"] = {"load": 1, "cap": 4}
    assert set(canarylib.detect_outliers(sm)) == {"d"}
    # not enough voters on either field: no verdict at all
    assert canarylib.detect_outliers(
        {"a": {"svc_p99_ms": 1.0}, "b": {"svc_p99_ms": 500.0}}
    ) == {}


def test_outlier_penalty_in_routing():
    from inferd_tpu.control.dstar import node_cost
    from inferd_tpu.control.path_finder import min_load_node

    stage = {
        "busy": {"load": 3, "cap": 4},
        "flagged": {"load": 0, "cap": 4, "outlier": 1},
    }
    # the idle-but-flagged replica loses to a 75%-loaded healthy one
    nid, _ = min_load_node(stage)
    assert nid == "busy"
    assert node_cost(stage["flagged"]) > node_cost(stage["busy"])
    # penalty, not exclusion: an all-flagged stage stays routable
    nid, _ = min_load_node({"f1": {"load": 0, "cap": 4, "outlier": 1}})
    assert nid == "f1"


# ------------------------------------------------------------ fleet SLIs


def test_fleet_fixture_check_and_report(capsys):
    """run.sh step 0e's tier-1 gate: the committed collector artifacts
    render a fleet report and pass --check."""
    assert obs_main(["fleet", "--check", FLEET_FIXTURE]) == 0
    out = capsys.readouterr().out
    assert "fleet SLI report" in out
    assert "obs fleet check: OK" in out
    assert "canary: probes/min" in out
    assert "stage 0" in out and "stage 1" in out


def test_fleet_sample_semantics():
    histories = [
        tsdblib.load_history_file(
            os.path.join(FLEET_FIXTURE, f"node{i}.history.json")
        )
        for i in (0, 1)
    ]
    s = fleetlib.fleet_sample(histories)
    # tok/s sums LAST-stage replicas only: node1 (stage 1/2) alone, so
    # the fleet rate equals its per-stage rate — never doubled by depth
    assert s["fleet"]["tok_per_s"] == s["per_stage"]["1"]["tok_per_s"]
    # canary series separated from the user TTFT series
    assert s["canary"]["probe_per_min"] > 0
    assert s["fleet"]["ttft_ms"]["count"] > 0
    # explicit per-stage aggregation naming (the collector-satellite fix)
    assert "hop_p50_med_ms" in s["per_stage"]["0"]
    assert "hop_p99_worst_ms" in s["per_stage"]["0"]
    assert s["per_stage"]["0"]["outliers"] == []


def test_fleet_check_catches_empty_pipeline(tmp_path):
    assert fleetlib.check_samples([]) == ["no fleet samples found"]
    hollow = {"v": 1, "ts": 1.0, "nodes": 0, "fleet": {}, "canary": {},
              "per_stage": {}}
    assert any(
        "zero SLI series" in p for p in fleetlib.check_samples([hollow])
    )
    p = tmp_path / "x.ndjson"
    p.write_text("garbage\n" + json.dumps(hollow) + "\n")
    samples = fleetlib.load_samples([str(p)])
    assert len(samples) == 1  # garbage line skipped, sample loaded


# ------------------------------------------------- exposition / gate / kill


def test_exposition_validates_new_metric_families():
    """Every new series family — canary.*, burn.*, tsdb/replica gauges,
    the windowed generate.* histograms — renders to a valid Prometheus
    exposition (monotone buckets, well-formed lines)."""
    from inferd_tpu.obs import export

    m = Metrics()
    m.inc("canary.probes", 5)
    m.inc("canary.ok", 4)
    m.inc("canary.fail", 1)
    m.observe("canary.wall_ms", 450.0, bounds_ms=[10, 100, 1000, 10000])
    m.observe("canary.ttft_ms", 120.0, bounds_ms=[10, 100, 1000, 10000])
    m.set_gauge("burn.availability", 2.5)
    m.set_gauge("burn.canary", 0.0)
    m.set_gauge("tsdb.overhead_ms", 1.25)
    m.set_gauge("canary.overhead_ms", 0.5)
    m.set_gauge("replica.outlier", 1.0)
    m.inc("generate.requests", 3)
    m.inc("generate.tokens", 24)
    m.inc("stage.tokens", 24)
    m.observe("generate.ttft_ms", 130.0, bounds_ms=[10, 100, 1000])
    m.observe("generate.tpot_ms", 18.0)
    m.observe("generate.wall_ms", 400.0, bounds_ms=[10, 100, 1000])
    text = export.prometheus_text(m, labels={"node": "10.0.0.2:6050"})
    assert export.validate_exposition(text) == []
    assert "inferd_canary_probes_total" in text
    assert "inferd_burn_availability" in text
    assert "inferd_generate_ttft_ms_bucket" in text


def test_gate_budgets_tsdb_and_canary_overhead():
    from inferd_tpu.perf.gate import check_span_overhead

    snap = {
        "gauges": {"tsdb.overhead_ms": 5.0, "canary.overhead_ms": 0.01},
        "histograms": {"stage.compute_ms": {"count": 10, "mean_ms": 10.0}},
    }
    findings = check_span_overhead(snap)
    assert len(findings) == 1 and "tsdb-sampling" in findings[0].message
    snap["gauges"]["canary.overhead_ms"] = 9.0
    assert any(
        "canary-probing" in f.message for f in check_span_overhead(snap)
    )
    snap["gauges"] = {"tsdb.overhead_ms": 0.5, "canary.overhead_ms": 0.5}
    assert check_span_overhead(snap) == []


def test_measured_tsdb_overhead_inside_budget():
    """Acceptance: the measured sampling cost stays under the 1% bar at
    a realistic ratio — the tick runs at 1 Hz, so 1000 samples span
    1000 s of wall time; a SERVING node at even 2.5% compute duty cycle
    (1000 x 25 ms) dwarfs the ~0.1 ms/sample the rings cost."""
    from inferd_tpu.perf.gate import check_span_overhead

    m = Metrics()
    for i in range(40):  # a realistically wide registry
        m.inc(f"c{i}")
        m.observe(f"h{i % 8}", float(i))
    t, clock = _clocked_tsdb(m)
    for _ in range(1000):
        clock[0] += 1.0
        m.inc("c0")
        m.observe("h0", 1.0)
        t.sample()
    snap = {
        "gauges": {"tsdb.overhead_ms": t.overhead_ms},
        "histograms": {"stage.compute_ms": {"count": 1000, "mean_ms": 25.0}},
    }
    assert check_span_overhead(snap) == [], (
        f"1000 samples cost {t.overhead_ms:.1f} ms"
    )


def test_generate_sli_recorder_is_canary_and_kill_switch_gated(monkeypatch):
    import time as _time

    from inferd_tpu.runtime.node import Node

    class Carrier:
        pass

    c = Carrier()
    c.metrics = Metrics()
    sli = {"t0": _time.perf_counter(), "ttft_ms": 12.0, "tokens": 8,
           "canary": False}
    Node._record_generate_sli(c, dict(sli), 200)
    snap = c.metrics.snapshot()
    assert snap["counters"]["generate.requests"] == 1
    assert snap["counters"]["generate.tokens"] == 8
    assert snap["histograms"]["generate.ttft_ms"]["count"] == 1
    assert snap["histograms"]["generate.tpot_ms"]["count"] == 1
    # a 503 shed counts the request and burns the budget, but records
    # NO latency — a 1 ms fast-fail folded into wall_ms would DROP the
    # fleet percentiles during the exact incident they expose
    Node._record_generate_sli(c, dict(sli), 503)
    snap = c.metrics.snapshot()
    assert snap["counters"]["generate.requests"] == 2
    assert snap["counters"]["generate.errors"] == 1
    assert snap["histograms"]["generate.wall_ms"]["count"] == 1
    # a 400 is a caller bug: counted as a request, not as burn
    Node._record_generate_sli(c, dict(sli), 400)
    snap = c.metrics.snapshot()
    assert snap["counters"]["generate.requests"] == 3
    assert snap["counters"]["generate.errors"] == 1
    # canary-tagged: nothing recorded
    c2 = Carrier()
    c2.metrics = Metrics()
    Node._record_generate_sli(c2, dict(sli, canary=True), 200)
    Node._record_generate_sli(c2, dict(sli, canary=True), 500)
    assert c2.metrics.snapshot()["counters"] == {}
    # events kill switch: byte-identical /metrics means NO new series
    monkeypatch.setenv("INFERD_EVENTS", "0")
    c3 = Carrier()
    c3.metrics = Metrics()
    Node._record_generate_sli(c3, dict(sli), 200)
    assert c3.metrics.snapshot()["counters"] == {}


def test_battery_has_canary_smoke_leg():
    from inferd_tpu.tools.bench_battery import SMOKE_LEGS

    legs = dict((n, t) for n, t, _ in SMOKE_LEGS)
    assert "canary_tiny" in legs
    tail = legs["canary_tiny"]
    assert "--config" in tail and "canary" in tail and "--tiny" in tail


# --------------------------------------------------------------- canary unit


async def _serve_canary_target(handler):
    """Tiny aiohttp app standing in for a node's /generate."""
    from aiohttp import web

    app = web.Application()
    app.add_routes([web.post("/generate", handler)])
    runner = web.AppRunner(app)
    await runner.setup()
    site = web.TCPSite(runner, "127.0.0.1", 0)
    await site.start()
    port = site._server.sockets[0].getsockname()[1]
    return runner, port


@pytest.mark.asyncio
async def test_canary_probe_success_and_failure_paths():
    import aiohttp
    from aiohttp import web

    from inferd_tpu.runtime import wire

    seen_headers = []

    async def good(request):
        seen_headers.append(dict(request.headers))
        env = wire.unpack(await request.read())
        assert env["prompt_ids"] and env["stream"] is True
        resp = web.StreamResponse()
        await resp.prepare(request)
        await resp.write(b'{"t": 5}\n')
        await resp.write(b'{"done": true, "ids": [5, 7]}\n')
        await resp.write_eof()
        return resp

    async def broken(request):
        resp = web.StreamResponse()
        await resp.prepare(request)
        await resp.write(b'{"t": 5}\n')  # stream dies before done
        await resp.write_eof()
        return resp

    class Journal:
        def __init__(self):
            self.events = []

        def emit(self, etype, **attrs):
            self.events.append((etype, attrs))

    for handler, want_ok in ((good, True), (broken, False)):
        runner, port = await _serve_canary_target(handler)
        m = Metrics()
        j = Journal()
        prober = canarylib.CanaryProber(
            lambda p=port: [("127.0.0.1", p)], m, journal=j, timeout_s=5.0,
        )
        prober._http = aiohttp.ClientSession(
            timeout=aiohttp.ClientTimeout(total=5)
        )
        try:
            rec = await prober.probe_once()
        finally:
            await prober.stop()
            await runner.cleanup()
        assert rec["ok"] is want_ok
        snap = m.snapshot()
        assert snap["counters"]["canary.probes"] == 1
        if want_ok:
            assert snap["counters"]["canary.ok"] == 1
            assert snap["histograms"]["canary.wall_ms"]["count"] == 1
            assert snap["histograms"]["canary.ttft_ms"]["count"] == 1
            assert rec["ttft_ms"] is not None
            assert j.events == []
        else:
            assert snap["counters"]["canary.fail"] == 1
            assert "canary.wall_ms" not in snap["histograms"]
            assert j.events and j.events[0][0] == "canary.fail"

    # the probe marks itself synthetic on the wire
    assert any(
        h.get(canarylib.CANARY_HEADER) == "1" for h in seen_headers
    )


@pytest.mark.asyncio
async def test_canary_probe_no_targets_and_dead_target():
    import aiohttp

    m = Metrics()
    prober = canarylib.CanaryProber(lambda: [], m)
    assert await prober.probe_once() is None
    assert m.snapshot()["counters"] == {}
    prober2 = canarylib.CanaryProber(
        lambda: [("127.0.0.1", 1)], m, timeout_s=2.0,
    )
    prober2._http = aiohttp.ClientSession(
        timeout=aiohttp.ClientTimeout(total=2)
    )
    try:
        rec = await prober2.probe_once()
    finally:
        await prober2.stop()
    assert rec["ok"] is False and rec["error"]
    assert m.snapshot()["counters"]["canary.fail"] == 1


# ------------------------------------------------------- node integration


@pytest.mark.asyncio
async def test_node_windowed_gossip_recovers(tiny_parts):  # noqa: F811
    """Node-level acceptance: gossiped hop/svc quantiles come from the
    trailing window — after the slow samples age out, the node's own
    announce stops carrying the elevated p99 (impossible with the PR 3
    all-time source)."""
    nodes = [_mk_node(130, 0, 1, bootstrap_idx=130)]
    await _start_all(nodes)
    n = nodes[0]
    try:
        clock = [5000.0]
        n.tsdb = tsdblib.Tsdb(
            n.metrics, service=n.info.node_id,
            meta={"stage": 0, "num_stages": 1}, clock=lambda: clock[0],
        )
        n.tsdb.sample()
        for _ in range(10):
            clock[0] += 1.0
            n.metrics.observe("hop.relay_ms", 1500.0)
            n.metrics.observe("stage.compute_ms", 800.0)
            n.tsdb.sample()
        n._windowed_cache = (0.0, None)
        wq = n._windowed_gossip()
        assert wq["hop_p99_ms"] >= 1500.0
        assert wq["svc_p99_ms"] >= 800.0
        # recovery minute: fast traffic, slow samples age past horizon
        for _ in range(70):
            clock[0] += 1.0
            n.metrics.observe("hop.relay_ms", 1.0)
            n.metrics.observe("stage.compute_ms", 2.0)
            n.tsdb.sample()
        n._windowed_cache = (0.0, None)
        wq = n._windowed_gossip()
        assert wq["hop_p99_ms"] <= 10.0, wq
        assert wq["svc_p99_ms"] <= 10.0, wq
        # the all-time histograms still remember — the gossip must not
        assert n.metrics.histograms["hop.relay_ms"].quantile(0.99) >= 1500.0
        # idle past the horizon: the keys drop out instead of going stale
        clock[0] += 400.0
        n.tsdb.sample()
        n._windowed_cache = (0.0, None)
        assert "hop_p99_ms" not in n._windowed_gossip()
    finally:
        await _stop_all(nodes)


@pytest.mark.asyncio
async def test_metrics_history_endpoint_schema(tiny_parts):  # noqa: F811
    import aiohttp

    nodes = [_mk_node(131, 0, 1, bootstrap_idx=131)]
    await _start_all(nodes)
    try:
        async with aiohttp.ClientSession() as s:
            async with s.get(
                f"http://127.0.0.1:{BASE + 131}/metrics/history"
            ) as r:
                assert r.status == 200
                h = await r.json()
        assert tsdblib.validate_history(h) == []
        assert h["service"] == nodes[0].info.node_id
        assert h["meta"] == {"stage": 0, "num_stages": 1}
    finally:
        await _stop_all(nodes)


# ---------------------------------------------------- e2e fault injection


@pytest.mark.asyncio
async def test_outlier_flagging_routing_and_fleet_report(
    tiny_parts, tmp_path,  # noqa: F811
):
    """THE e2e acceptance: one stage-1 replica of three is artificially
    slowed (chaos delay). From windowed telemetry alone it self-flags
    `replica.outlier` (journal event + gossiped flag), every router's
    min-load pick and chain planner route new sessions away from it, the
    canary prober's canary.* series records its probes through the
    degraded fleet — and all of it is re-assembled OFFLINE from the
    per-node artifacts by `obs fleet`."""
    import aiohttp

    from inferd_tpu.client.swarm_client import SwarmClient
    from inferd_tpu.config import SamplingConfig
    from inferd_tpu.control.path_finder import min_load_node
    from inferd_tpu.runtime import wire
    from inferd_tpu.utils.chaos import Chaos

    parts, _params = tiny_parts
    obs_dir = str(tmp_path / "obs")
    nodes = [
        _mk_node(140, 0, 2, backend="qwen3", parts=parts, bootstrap_idx=140),
        _mk_node(141, 1, 2, backend="qwen3", parts=parts, bootstrap_idx=140),
        _mk_node(142, 1, 2, backend="qwen3", parts=parts, bootstrap_idx=140),
        _mk_node(143, 1, 2, backend="qwen3", parts=parts, bootstrap_idx=140),
    ]
    victim = nodes[3]
    # the quiet degradation — far past any healthy replica's steady
    # p99, so the divergence can't dip below the k*MAD bar mid-test
    victim.chaos = Chaos(delay_ms=600)
    for n in nodes:
        n.trace_dir = obs_dir
        n.tsdb_period_s = 0.1  # test-speed telemetry ticks
        n.window_s = 8.0  # short trailing window: warmup compile
        # spikes age out in seconds instead of a minute
    await _start_all(nodes)
    try:
        import numpy as np

        hidden_sz = nodes[1].cfg.hidden_size

        # chain warmup: compiles the entry's token buckets + self-client
        async with SwarmClient(
            [("127.0.0.1", BASE + 140)],
            sampling=SamplingConfig(temperature=0.0),
        ) as c:
            await c.generate_ids([3, 7, 11, 19], max_new_tokens=2)

        async with aiohttp.ClientSession() as s:

            async def post(n, payload, sid):
                body = wire.pack(
                    {"stage": 1, "session_id": sid, "payload": payload}
                )
                async with s.post(
                    f"http://127.0.0.1:{n.info.port}/forward", data=body
                ) as r:
                    assert r.status == 200, await r.text()

            # per-replica warmup: compile the prefill + decode jits every
            # later canary/user request will hit — a first-touch XLA
            # compile on a HEALTHY replica mid-test would spike its
            # window into outlier territory and mask the real signal
            for n in nodes[1:]:
                sid = f"warm-{n.info.port}"
                await post(n, {
                    "hidden": np.zeros((1, 4, hidden_sz), np.float32),
                    "start_pos": 0, "real_len": 4,
                }, sid)
                await post(n, {
                    "hidden": np.zeros((1, 1, hidden_sz), np.float32),
                    "start_pos": 4, "real_len": 1,
                }, sid)

            # steady phase, longer than the window: every stage-1 replica
            # serves identical light traffic until every trailing window
            # holds only steady-state values (+ the victim's chaos delay)
            # — the outlier detector needs >= 3 voters carrying svc_p99_ms
            for rep in range(12):
                for n in nodes[1:]:
                    await post(n, {
                        "hidden": np.zeros((1, 1, hidden_sz), np.float32),
                        "start_pos": 0, "real_len": 1,
                    }, f"s-{n.info.port}-{rep}")
                await asyncio.sleep(0.3)

        # windowed telemetry flags the slowed replica within seconds
        for _ in range(120):
            if victim._outlier_info is not None:
                break
            await asyncio.sleep(0.1)
        assert victim._outlier_info is not None, (
            "victim never self-flagged: "
            f"{victim._windowed_gossip()} vs peers "
            f"{ {k: v.get('svc_p99_ms') for k, v in victim.dht.get_stage(1).items()} }"
        )
        assert victim._outlier_info["field"] in ("hop_p99_ms", "svc_p99_ms")
        evs = [
            ev for ev in victim.journal.events()
            if ev["type"] == "replica.outlier"
        ]
        assert evs, "no replica.outlier journal event"
        assert evs[0]["attrs"]["value"] >= evs[0]["attrs"]["median"]

        # the flag gossips to the entry node...
        for _ in range(100):
            rec = nodes[0].dht.get_stage(1).get(victim.info.node_id, {})
            if rec.get("outlier"):
                break
            await asyncio.sleep(0.05)
        assert rec.get("outlier") == 1, rec

        # ...and routing consumes it: with every replica idle, neither the
        # min-load pick nor the chain planner lands on the flagged one
        for _ in range(5):
            nid, _v = min_load_node(nodes[0].dht.get_stage(1))
            assert nid != victim.info.node_id
            chain = nodes[0].path_finder.find_best_chain(1)
            assert chain[0][0] != victim.info.node_id

        # canary probes through the (healthy remainder of the) fleet
        prober = canarylib.CanaryProber(
            lambda: [("127.0.0.1", BASE + 140)], nodes[0].metrics,
            journal=nodes[0].journal, tracer=nodes[0].tracer,
            interval_s=60.0, timeout_s=60.0,
        )
        prober._http = aiohttp.ClientSession(
            timeout=aiohttp.ClientTimeout(total=60)
        )
        try:
            for _ in range(2):
                rec = await prober.probe_once()
                assert rec is not None and rec["ok"], rec
        finally:
            await prober.stop()
        snap = nodes[0].metrics.snapshot()
        assert snap["counters"]["canary.ok"] == 2
        assert snap["counters"].get("generate.requests", 0) == 0, (
            "canary probes leaked into the user SLI series"
        )
        await asyncio.sleep(0.3)  # a telemetry tick samples the canary series

        # a real user request still completes, routed around the outlier
        async with SwarmClient(
            [("127.0.0.1", BASE + 140)],
            sampling=SamplingConfig(temperature=0.0),
        ) as c:
            out = await c.generate_ids([3, 7, 11, 19], max_new_tokens=4)
            assert len(out) == 4

        # ---- the real collector pipeline captures the incident: pull
        # every node's /metrics/history, merge into ONE fleet sample,
        # persist as NDJSON (tools/collector --history does exactly this)
        from inferd_tpu.tools.collector import fetch_histories

        artifacts = str(tmp_path / "artifacts")
        histories = await fetch_histories(nodes[0].dht.get_all(2))
        assert len(histories) == 4, "history endpoint missing on a node"
        incident = fleetlib.fleet_sample(histories)
        fleetlib.write_ndjson(
            os.path.join(artifacts, "fleet.ndjson"), incident
        )
        assert victim.info.node_id in incident["per_stage"]["1"]["outliers"]
        assert incident["canary"]["probe_per_min"] > 0
        assert incident["fleet"]["tok_per_s"] is not None

        # ---- offline: the committed artifacts alone reproduce the story
        await _stop_all(nodes)  # final flush writes *.history.json too
        import glob as globlib

        assert len(globlib.glob(os.path.join(obs_dir, "*.history.json"))) == 4
        samples = fleetlib.load_samples([artifacts])
        assert samples, "no fleet sample loaded from the NDJSON artifact"
        s = samples[-1]
        assert victim.info.node_id in s["per_stage"]["1"]["outliers"]
        report = fleetlib.format_report(samples)
        assert "OUTLIER replicas" in report
        assert victim.info.node_id in report
        assert obs_main(["fleet", "--check", artifacts]) == 0
    finally:
        await _stop_all(nodes)
