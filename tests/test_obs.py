"""inferd_tpu/obs tests: span recorder + context propagation, Prometheus
exposition, Chrome export, span-merge/skew-correction properties over
shuffled/duplicated/partially-missing JSONL, the merge CLI --check smoke
over the committed fixture, wire trace-key compatibility, the perf-gate
span-overhead check, and the satellite fixes (Profiler.stop wedge,
Histogram.summary lock consistency, dashboard/collector hop columns)."""

import json
import os
import random

import numpy as np
import pytest

from inferd_tpu.client.swarm_client import SwarmClient
from inferd_tpu.obs import export, merge, trace
from inferd_tpu.runtime import wire
from inferd_tpu.utils.metrics import Metrics

FIXTURE_DIR = os.path.join(os.path.dirname(__file__), "data", "spans")


# ------------------------------------------------------------- recorder


def test_span_recorder_ring_cap_and_stats():
    rec = trace.SpanRecorder("svc", cap=16)
    for i in range(40):
        rec.record_span("s", "compute", float(i), float(i) + 0.5)
    assert len(rec) == 16
    st = rec.stats()
    assert st["recorded"] == 40
    assert st["dropped"] == 24
    assert st["buffered"] == 16
    assert st["overhead_ms"] >= 0


def test_span_context_nesting_and_propagation_surfaces():
    rec = trace.SpanRecorder("svc")
    assert trace.current() is None
    with rec.span("root", "client") as root_ctx:
        assert trace.current() == root_ctx
        assert trace.wire_ctx() == {"id": root_ctx.trace_id, "span": root_ctx.span_id}
        hdr = trace.header_ctx()
        assert hdr == {trace.TRACE_HEADER: root_ctx.to_header()}
        with rec.span("child", "wire") as child_ctx:
            assert child_ctx.trace_id == root_ctx.trace_id
        # context restored after the child block
        assert trace.current() == root_ctx
    assert trace.current() is None
    spans = {s["name"]: s for s in rec.spans()}
    assert spans["child"]["parent"] == root_ctx.span_id
    assert spans["root"]["parent"] is None
    assert spans["child"]["t0"] >= spans["root"]["t0"]
    assert spans["child"]["t1"] <= spans["root"]["t1"]
    # header/wire round trips
    assert trace.SpanContext.from_header(root_ctx.to_header()) == root_ctx
    assert trace.SpanContext.from_wire(root_ctx.to_wire()) == root_ctx
    assert trace.SpanContext.from_wire({"bogus": 1}) is None
    assert trace.SpanContext.from_header(None) is None


def test_recorder_disabled_records_nothing(monkeypatch):
    monkeypatch.setenv("INFERD_TRACE", "0")
    rec = trace.SpanRecorder("svc")
    assert rec.record_span("s", "compute", 0.0, 1.0) is None
    with rec.span("root", "client") as ctx:
        assert ctx is None
        assert trace.wire_ctx() is None
        assert trace.header_ctx() is None
    assert len(rec) == 0


def test_recorder_dump_jsonl_drains_and_appends(tmp_path):
    rec = trace.SpanRecorder("svc")
    rec.record_span("a", "compute", 0.0, 1.0)
    path = str(tmp_path / "svc.spans.jsonl")
    assert rec.dump_jsonl(path) == 1
    assert len(rec) == 0
    rec.record_span("b", "compute", 1.0, 2.0)
    assert rec.dump_jsonl(path) == 1
    names = [json.loads(ln)["name"] for ln in open(path)]
    assert names == ["a", "b"]


def test_recorder_flush_jsonl_keeps_ring_live(tmp_path):
    """The periodic exporter must NOT drain the ring: /spans and the
    gossiped hop quantiles read the live buffer between flushes, while
    the JSONL file receives every span exactly once."""
    rec = trace.SpanRecorder("svc")
    rec.record_span("a", "relay", 0.0, 1.0)
    rec.record_span("b", "relay", 1.0, 2.0)
    path = str(tmp_path / "svc.spans.jsonl")
    assert rec.flush_jsonl(path) == 2
    assert len(rec) == 2  # ring intact
    assert rec.phase_quantiles(("relay",)) is not None
    assert rec.flush_jsonl(path) == 0  # nothing new: no duplicates
    rec.record_span("c", "relay", 2.0, 3.0)
    assert rec.flush_jsonl(path) == 1  # only the new span appends
    names = [json.loads(ln)["name"] for ln in open(path)]
    assert names == ["a", "b", "c"]


def test_phase_quantiles():
    rec = trace.SpanRecorder("svc")
    for ms in (10, 20, 30, 40, 100):
        rec.record_span("relay", "relay", 0.0, ms / 1e3)
    rec.record_span("other", "compute", 0.0, 9.0)  # not a hop phase
    q = rec.phase_quantiles(("relay", "rescue"), (0.5, 0.99))
    assert q["p50_ms"] == pytest.approx(30.0, abs=0.1)
    assert q["p99_ms"] == pytest.approx(100.0, abs=0.1)
    assert trace.SpanRecorder("x").phase_quantiles() is None


# ------------------------------------------------- wire envelope compat


def test_disabled_tracing_envelope_byte_identical(monkeypatch):
    """Acceptance: tracing disabled-by-config leaves the /forward envelope
    byte-identical to the untraced format."""
    import uuid as uuidlib

    monkeypatch.setenv("INFERD_TRACE", "0")
    monkeypatch.setattr(uuidlib, "uuid4", lambda: uuidlib.UUID(int=7))
    env = SwarmClient([("127.0.0.1", 1)])._forward_env("sess", [1, 2, 3], 5)
    assert set(env) == {"task_id", "session_id", "stage", "payload"}
    manual = {
        "task_id": str(uuidlib.UUID(int=7)),
        "session_id": "sess",
        "stage": 0,
        "payload": {
            "tokens": np.asarray([[1, 2, 3]], dtype=np.int32),
            "start_pos": 5,
            "real_len": 3,
        },
    }
    assert wire.pack(env) == wire.pack(manual)
    # enabled, inside a step span: the ONLY delta is the trace key
    monkeypatch.setenv("INFERD_TRACE", "1")
    rec = trace.SpanRecorder("client")
    with rec.span("step", "wire") as ctx:
        env2 = SwarmClient([("127.0.0.1", 1)])._forward_env("sess", [1, 2, 3], 5)
    assert set(env2) == set(env) | {"trace"}
    assert env2["trace"] == {"id": ctx.trace_id, "span": ctx.span_id}
    # enabled but NO active context: still no trace key
    assert "trace" not in SwarmClient([("127.0.0.1", 1)])._forward_env("sess", [1], 0)


def test_wire_trace_key_round_trips_both_generations(monkeypatch):
    """v1 nodes round-trip envelopes carrying `trace`; legacy decoders
    tolerate (ignore) it — toggled per call via INFERD_WIRE, no reimport."""
    env = {
        "task_id": "t",
        "session_id": "s",
        "stage": 1,
        "payload": {
            "tokens": np.asarray([[1, 2]], dtype=np.int32),
            "start_pos": 0,
            "real_len": 2,
        },
        "trace": {"id": "abc123", "span": "def456"},
    }
    for mode in ("v1", "legacy", "v1"):
        monkeypatch.setenv("INFERD_WIRE", mode)
        out = wire.unpack(wire.pack(env))
        assert out["trace"] == {"id": "abc123", "span": "def456"}
        assert out["session_id"] == "s" and out["stage"] == 1
        np.testing.assert_array_equal(
            out["payload"]["tokens"], env["payload"]["tokens"]
        )
    # a legacy (msgpack-only) decoder sees the trace key as a plain dict
    # and the rest of the envelope intact — unknown keys are ignored by
    # every handler, so mixed-version swarms interoperate
    legacy_blob = wire.pack_legacy(env)
    out = wire.unpack(legacy_blob)
    assert out["trace"]["id"] == "abc123"


# ------------------------------------------------------------ prometheus


def test_prometheus_exposition_valid_and_complete():
    m = Metrics()
    m.inc("forward.requests", 3)
    m.inc("hop.bytes_total", 1024)
    m.set_gauge("kv.bytes", 12345)
    m.set_gauge("inflight", 2)
    m.observe("stage.compute_ms", 7.0)
    m.observe("stage.compute_ms", 0.05)
    m.observe("stage.compute_ms", 99999.0)  # lands in +Inf bucket
    text = export.prometheus_text(m, labels={"node": "1.2.3.4:6050"})
    assert export.validate_exposition(text) == []
    assert 'inferd_forward_requests_total{node="1.2.3.4:6050"} 3' in text
    assert 'inferd_kv_bytes{node="1.2.3.4:6050"} 12345' in text
    assert "# TYPE inferd_inflight gauge" in text
    assert "# TYPE inferd_stage_compute_ms histogram" in text
    assert 'le="+Inf"} 3' in text
    assert 'inferd_stage_compute_ms_count{node="1.2.3.4:6050"} 3' in text


def test_prometheus_name_sanitization_and_validator_catches_garbage():
    m = Metrics()
    m.inc("weird-name.with/slash")
    text = export.prometheus_text(m)
    assert "inferd_weird_name_with_slash_total 1" in text
    assert export.validate_exposition(text) == []
    assert export.validate_exposition("not a metric line!\n") != []
    assert export.validate_exposition("x_bucket 2\nx_bucket 1\n") != []


def test_chrome_trace_events():
    spans = [
        {"trace": "t1", "span": "a", "parent": None, "name": "root",
         "phase": "client", "service": "client", "t0": 10.0, "t1": 10.5},
        {"trace": "t1", "span": "b", "parent": "a", "name": "step",
         "phase": "wire", "service": "nodeA", "t0": 10.1, "t1": 10.4,
         "attrs": {"stage": 0}},
    ]
    out = export.chrome_trace(spans, offsets={"nodeA": -1.0})
    evs = out["traceEvents"]
    assert len(evs) == 2
    root, step = evs
    assert root["ph"] == "X" and root["pid"] == "client"
    assert root["ts"] == pytest.approx(10.0 * 1e6)
    assert root["dur"] == pytest.approx(0.5 * 1e6)
    assert step["ts"] == pytest.approx(9.1 * 1e6)  # offset applied
    assert step["args"]["parent"] == "a" and step["args"]["stage"] == 0


# ------------------------------------------------------- merge properties


def _mk_skewed_trace(skew_b=5.0, trace_id="t1"):
    """client -> nodeA -> nodeB synthetic trace; nodeB's clock is ahead by
    `skew_b` seconds. Returns {service: [span, ...]} in TRUE time + skew."""
    out = {"client": [], "A": [], "B": []}

    def add(svc, sid, parent, name, phase, t0, t1, skew=0.0, **attrs):
        s = {"trace": trace_id, "span": sid, "parent": parent, "name": name,
             "phase": phase, "service": svc,
             "t0": t0 + skew, "t1": t1 + skew}
        if attrs:
            s["attrs"] = attrs
        out[svc].append(s)

    add("client", "root", None, "generate", "client", 0.0, 1.0)
    add("client", "step", "root", "step", "wire", 0.05, 0.95)
    add("client", "samp", "root", "sample", "sample", 0.96, 0.97)
    add("A", "af", "step", "forward", "server", 0.10, 0.90, stage=0)
    add("A", "aq", "af", "queue", "queue", 0.11, 0.12, stage=0)
    add("A", "ac", "af", "compute", "compute", 0.12, 0.50, stage=0)
    add("A", "ar", "af", "relay", "relay", 0.52, 0.88, stage=1)
    add("B", "bf", "ar", "forward", "server", 0.55, 0.85, skew=skew_b, stage=1)
    add("B", "bq", "bf", "queue", "queue", 0.56, 0.57, skew=skew_b, stage=1)
    add("B", "bc", "bf", "compute", "compute", 0.57, 0.84, skew=skew_b, stage=1)
    return out


def _write_files(tmp_path, by_svc, shuffle=True, dup=0, seed=0):
    rng = random.Random(seed)
    paths = []
    for svc, spans in by_svc.items():
        spans = list(spans)
        if shuffle:
            rng.shuffle(spans)
        spans += [spans[i % len(spans)] for i in range(dup)]
        p = tmp_path / f"{svc}.spans.jsonl"
        with open(p, "w") as f:
            for s in spans:
                f.write(json.dumps(s) + "\n")
        paths.append(str(p))
    return paths


def test_merge_corrects_clock_skew_on_shuffled_duplicated_input(tmp_path):
    by_svc = _mk_skewed_trace(skew_b=5.0)
    paths = _write_files(tmp_path, by_svc, shuffle=True, dup=3)
    result = merge.merge_paths(paths)
    assert result["skipped_lines"] == 0
    # B's clock ran 5 s ahead; the hop send/recv anchors (A's relay span
    # bracketing B's forward span) pin the correction
    assert result["offsets"]["client"] == 0.0
    assert result["offsets"]["B"] == pytest.approx(-5.0, abs=0.05)
    assert len(result["traces"]) == 1
    t = result["traces"][0]
    assert t["nest_violations"] == []
    assert t["spans"] == 10  # duplicates deduped
    assert t["services"] == ["A", "B", "client"]
    assert t["wall_ms"] == pytest.approx(1000.0, abs=1.0)
    assert t["tokens"] == 1
    assert t["ttft_ms"] == pytest.approx(970.0, abs=1.0)
    assert t["stages"]["0"]["compute_ms"] == pytest.approx(380.0, abs=1.0)
    assert t["stages"]["1"]["compute_ms"] == pytest.approx(270.0, abs=1.0)
    # skew-corrected spans nest: B's forward lies inside A's relay
    by_id = {s["span"]: s for s in result["spans"]}
    assert by_id["bf"]["t0"] >= by_id["ar"]["t0"]
    assert by_id["bf"]["t1"] <= by_id["ar"]["t1"]


def test_merge_tolerates_missing_spans_and_bad_lines(tmp_path):
    by_svc = _mk_skewed_trace(skew_b=2.0)
    # drop nodeB's forward span (the cross-node parent): its children
    # become orphans, the trace still merges
    by_svc["B"] = [s for s in by_svc["B"] if s["span"] != "bf"]
    paths = _write_files(tmp_path, by_svc, shuffle=True)
    with open(tmp_path / "garbage.spans.jsonl", "w") as f:
        f.write("{truncated\n")
        f.write(json.dumps({"trace": "t1", "span": "x"}) + "\n")  # no times
        f.write("\n")
    result = merge.merge_paths([str(tmp_path)])
    assert result["skipped_lines"] == 2
    assert len(result["traces"]) == 1
    t = result["traces"][0]
    assert t["root"]["name"] == "generate"
    # orphans (parent missing) are never nesting violations
    assert t["nest_violations"] == []
    assert t["spans"] == 9


def test_merge_multiple_traces_sorted(tmp_path):
    a = _mk_skewed_trace(skew_b=0.0, trace_id="t-early")
    b = _mk_skewed_trace(skew_b=0.0, trace_id="t-late")
    for spans in b.values():
        for s in spans:
            s["t0"] += 100.0
            s["t1"] += 100.0
    both = {svc: a[svc] + b[svc] for svc in a}
    result = merge.merge_paths(_write_files(tmp_path, both))
    assert [t["trace"] for t in result["traces"]] == ["t-early", "t-late"]
    assert all(t["nest_violations"] == [] for t in result["traces"])


def test_merge_cli_check_over_committed_fixture(tmp_path):
    from inferd_tpu.obs.__main__ import main

    out = tmp_path / "traces.json"
    chrome = tmp_path / "chrome.json"
    rc = main([
        "merge", "--check", "--out", str(out), "--chrome", str(chrome),
        FIXTURE_DIR,
    ])
    assert rc == 0
    data = json.load(open(out))
    assert len(data["traces"]) == 1
    t = data["traces"][0]
    assert t["nest_violations"] == []
    assert set(t["stages"]) == {"0", "1", "2"}
    # the fixture's node clocks are skewed +2.5 s / -1.25 s; the merge
    # recovered the corrections from the hop anchors alone
    assert data["offsets"]["10.0.0.11:6050"] == pytest.approx(-2.5, abs=0.05)
    assert data["offsets"]["10.0.0.13:6050"] == pytest.approx(1.25, abs=0.05)
    ev = json.load(open(chrome))
    assert len(ev["traceEvents"]) == t["spans"]


def test_merge_cli_check_fails_on_garbage(tmp_path):
    from inferd_tpu.obs.__main__ import main

    p = tmp_path / "bad.spans.jsonl"
    p.write_text("{nope\n")
    assert main(["merge", "--check", str(p)]) == 1


# ---------------------------------------------------------- gate overhead


def test_gate_span_overhead_check():
    from inferd_tpu.perf.gate import check_span_overhead

    snap = {
        "gauges": {"trace.overhead_ms": 5.0},
        "histograms": {"stage.compute_ms": {"count": 10, "mean_ms": 10.0}},
    }
    findings = check_span_overhead(snap)  # 5 ms on 100 ms compute: 5%
    assert len(findings) == 1
    assert findings[0].severity == "warning"
    assert findings[0].check == "overhead"
    snap["gauges"]["trace.overhead_ms"] = 0.5  # 0.5% — inside budget
    assert check_span_overhead(snap) == []
    assert check_span_overhead({}) == []
    # counters fallback (older snapshot shape)
    assert check_span_overhead({
        "counters": {"trace.overhead_ms": 50.0},
        "histograms": {"stage.compute_ms": {"count": 10, "mean_ms": 10.0}},
    })[0].severity == "warning"


def test_perf_check_cli_stats_flag(tmp_path, capsys):
    from inferd_tpu.perf.__main__ import main
    from inferd_tpu.perf.gate import DEFAULT_ARTIFACT

    p = tmp_path / "stats.json"
    p.write_text(json.dumps({
        "gauges": {"trace.overhead_ms": 50.0},
        "histograms": {"stage.compute_ms": {"count": 10, "mean_ms": 10.0}},
    }))
    rc = main(["check", "--artifact", DEFAULT_ARTIFACT, "--stats", str(p)])
    assert rc == 0  # overhead findings are warning-severity only
    assert "span-recording overhead" in capsys.readouterr().out


# ---------------------------------------------------------- satellite fixes


def test_profiler_stop_unwedges_after_failure(monkeypatch, tmp_path):
    """A raising jax.profiler.stop_trace must not leave the profiler
    stuck 'running' forever (the /profile endpoint would 409 every
    subsequent start with no recovery short of a restart)."""
    import jax

    from inferd_tpu.utils.profiling import Profiler

    monkeypatch.setattr(jax.profiler, "start_trace", lambda d: None)

    def boom():
        raise RuntimeError("trace finalization failed")

    monkeypatch.setattr(jax.profiler, "stop_trace", boom)
    p = Profiler(base_dir=str(tmp_path))
    p.start("x")
    with pytest.raises(RuntimeError, match="finalization failed"):
        p.stop()
    assert p.active_dir is None  # cleared despite the failure
    # fully recovered: start works again (no "already running" 409)...
    monkeypatch.setattr(jax.profiler, "stop_trace", lambda: None)
    d = p.start("y")
    # ...and a clean stop returns the new dir
    assert p.stop() == d
    # a second stop correctly reports nothing running
    with pytest.raises(RuntimeError, match="no profile running"):
        p.stop()


def test_histogram_summary_single_lock_snapshot(monkeypatch):
    """summary() must compute every quantile from ONE locked snapshot: a
    concurrent observe between per-quantile lock acquisitions could yield
    quantiles disagreeing with the summary's own count."""
    from inferd_tpu.utils import metrics as mlib

    h = mlib.Histogram()
    for v in (1.0, 2.0, 3.0, 500.0):
        h.observe(v)

    def poisoned(self, q):
        raise AssertionError("summary() must not re-lock via quantile()")

    monkeypatch.setattr(mlib.Histogram, "quantile", poisoned)
    s = h.summary()
    assert s["count"] == 4
    assert s["mean_ms"] == pytest.approx(126.5)
    assert s["p50_ms"] == 2.5
    assert s["p99_ms"] == 1000.0


def test_metrics_gauges_in_snapshot():
    m = Metrics()
    m.set_gauge("inflight", 3)
    m.set_gauge("inflight", 1)  # last write wins
    snap = m.snapshot()
    assert snap["gauges"] == {"inflight": 1.0}
    counters, gauges, hists = m.export_state()
    assert gauges == {"inflight": 1.0}
    assert counters == {} and hists == {}


# ----------------------------------------------------- console tool columns


def test_dashboard_hop_latency_column():
    from inferd_tpu.tools.dashboard import render_table

    sample = {
        0: {
            "10.0.0.2:6050": {
                "name": "n0", "load": 1, "cap": 4, "model": "m",
                "hop_p50_ms": 12.0, "hop_p99_ms": 80.0,
            },
            "10.0.0.3:6050": {"name": "n1", "load": 0, "cap": 4, "model": "m"},
        },
    }
    text = render_table(sample, ts=0.0)
    # PR 7: separate columns with independent fallbacks (the single
    # merged "p50/p99" cell blanked both when either side was missing)
    assert "hop p50" in text and "hop p99" in text
    row = next(ln.split() for ln in text.splitlines() if "10.0.0.2" in ln)
    assert row[4] == "12" and row[5] == "80"  # windowed quantiles rendered
    row = next(ln.split() for ln in text.splitlines() if "10.0.0.3" in ln)
    assert row[4] == "-" and row[5] == "-"  # no-data cells


def test_collector_hop_latency_fields():
    from inferd_tpu.tools.collector import FIELDS, stage_rows

    assert "hop_p50_ms" in FIELDS and "hop_p99_ms" in FIELDS
    sample = {
        0: {
            "a": {"load": 1, "cap": 4, "hop_p50_ms": 10.0, "hop_p99_ms": 50.0},
            "b": {"load": 0, "cap": 4, "hop_p50_ms": 20.0, "hop_p99_ms": 90.0},
        },
        1: {"c": {"load": 0, "cap": 4}},
    }
    rows = stage_rows(sample, ts=1.0)
    assert rows[0]["hop_p50_ms"] == pytest.approx(15.0)  # median of replicas
    assert rows[0]["hop_p99_ms"] == pytest.approx(90.0)  # worst replica
    assert rows[1]["hop_p50_ms"] == "" and rows[1]["hop_p99_ms"] == ""
    assert set(rows[0]) == set(FIELDS)
