"""Disaggregated prefill->decode (round 5, VERDICT r04 #5): a session
prefills on one replica, its KV hands off to a decode replica via
/export_session, and decoding continues there TOKEN-EXACT with zero
restarts. The reference pins a session's KV to one server forever
(qwen3_server_module.py:220); here placement is a per-phase choice."""

import asyncio

import jax
import pytest

from inferd_tpu.client.swarm_client import SwarmClient
from inferd_tpu.config import TINY, SamplingConfig
from inferd_tpu.control.dht import SwarmDHT
from inferd_tpu.core.generate import Engine
from inferd_tpu.models import qwen3
from inferd_tpu.parallel.stages import Manifest, split_and_save
from inferd_tpu.runtime.node import Node, NodeInfo

BASE = 18900
GREEDY = SamplingConfig(temperature=0.0)


@pytest.fixture(scope="module")
def whole_parts(tmp_path_factory):
    parts = tmp_path_factory.mktemp("disagg_parts")
    params = qwen3.init_params(TINY, jax.random.PRNGKey(0))
    split_and_save(params, TINY, Manifest.even_split("tiny", 1), str(parts))
    return str(parts), params


def _mk_node(idx, parts, batch_lanes=0):
    info = NodeInfo(
        name=f"dg{idx}", host="127.0.0.1", port=BASE + idx,
        stage=0, num_stages=1, capacity=8, model_name="tiny",
    )
    dht = SwarmDHT(
        info.node_id, BASE + 100 + idx, bootstrap=(
            [] if idx == 0 else [("127.0.0.1", BASE + 100)]
        ),
        host="127.0.0.1", gossip_period_s=0.05, ttl_s=5.0,
    )
    return Node(
        info, TINY, parts, dht, backend="qwen3", max_len=64,
        rebalance_period_s=600.0, batch_lanes=batch_lanes,
    )


@pytest.mark.asyncio
async def test_prefill_on_a_decode_on_b_token_exact(whole_parts):
    """Prefill on replica A, decode on replica B: the stream equals a
    single-replica greedy run token for token (zero restarts — the
    disaggregated client has no restart path, so exactness IS the proof),
    and A's /stats carries the handoff telemetry."""
    parts, params = whole_parts
    a = _mk_node(0, parts)
    b = _mk_node(1, parts)
    await a.start()
    await b.start()
    try:
        prompt = [3, 7, 11, 2, 5, 13]
        want = Engine(TINY, params, max_len=64, sampling_cfg=GREEDY).generate(
            prompt, max_new_tokens=12
        )
        async with SwarmClient([("127.0.0.1", BASE)], sampling=GREEDY) as c:
            got = await c.generate_ids_disaggregated(
                prompt, ("127.0.0.1", BASE + 1), max_new_tokens=12
            )
        assert got == want
        snap = a.metrics.snapshot()
        assert snap["counters"]["handoff.bytes"] > 0
        assert snap["counters"]["sessions.handed_off"] == 1
        assert snap["histograms"]["handoff.ms"]["count"] == 1
        # A no longer holds the session; B adopted it (then ended it)
        assert b.metrics.snapshot()["counters"]["sessions.imported"] == 1
    finally:
        await a.stop()
        await b.stop()


@pytest.mark.asyncio
async def test_disagg_across_executor_types(whole_parts):
    """Prefill on a stage-executor replica, decode on a CONTINUOUS-
    BATCHING replica: the shared handoff codec re-homes the session across
    executor types mid-stream, token-exact."""
    parts, params = whole_parts
    a = _mk_node(2, parts)
    b = _mk_node(3, parts, batch_lanes=4)
    await a.start()
    await b.start()
    try:
        prompt = [9, 8, 7, 6, 5]
        want = Engine(TINY, params, max_len=64, sampling_cfg=GREEDY).generate(
            prompt, max_new_tokens=10
        )
        async with SwarmClient(
            [("127.0.0.1", BASE + 2)], sampling=GREEDY
        ) as c:
            got = await c.generate_ids_disaggregated(
                prompt, ("127.0.0.1", BASE + 3), max_new_tokens=10
            )
        assert got == want
    finally:
        await a.stop()
        await b.stop()


@pytest.mark.asyncio
async def test_export_unknown_session_404(whole_parts):
    parts, _ = whole_parts
    a = _mk_node(4, parts)
    await a.start()
    try:
        from inferd_tpu.client.base import ServerError

        async with SwarmClient(
            [("127.0.0.1", BASE + 4)], sampling=GREEDY
        ) as c:
            with pytest.raises(ServerError) as ei:
                await c._post(
                    "/export_session",
                    {"session_id": "nope", "target_host": "127.0.0.1",
                     "target_port": BASE + 4},
                )
            assert ei.value.status == 404
    finally:
        await a.stop()


@pytest.mark.asyncio
async def test_disagg_between_mesh_replicas(whole_parts, devices8):
    """Prefill on one --mesh pp=2 replica, decode on another: the slot KV
    exports across the pp split (layer axis reassembled), re-homes, and
    the stream stays token-exact."""
    from inferd_tpu.parallel.mesh import MeshPlan

    parts, params = whole_parts

    def mk_mesh(idx):
        info = NodeInfo(
            name=f"dgm{idx}", host="127.0.0.1", port=BASE + 10 + idx,
            stage=0, num_stages=1, capacity=8, model_name="tiny",
        )
        dht = SwarmDHT(
            info.node_id, BASE + 110 + idx, bootstrap=(
                [] if idx == 0 else [("127.0.0.1", BASE + 110)]
            ),
            host="127.0.0.1", gossip_period_s=0.05, ttl_s=5.0,
        )
        return Node(
            info, TINY, parts, dht, backend="qwen3", max_len=64,
            rebalance_period_s=600.0, mesh_plan=MeshPlan(pp=2),
            mesh_slots=2,
        )

    a, b = mk_mesh(0), mk_mesh(1)
    await a.start()
    await b.start()
    try:
        prompt = [3, 7, 11, 2, 5]
        want = Engine(TINY, params, max_len=64, sampling_cfg=GREEDY).generate(
            prompt, max_new_tokens=10
        )
        async with SwarmClient(
            [("127.0.0.1", BASE + 10)], sampling=GREEDY
        ) as c:
            got = await c.generate_ids_disaggregated(
                prompt, ("127.0.0.1", BASE + 11), max_new_tokens=10
            )
        assert got == want
        assert a.metrics.snapshot()["counters"]["sessions.handed_off"] == 1
    finally:
        await a.stop()
        await b.stop()
