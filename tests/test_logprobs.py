"""Per-token logprobs + top-N alternatives from EVERY engine and the
serving surface (the round-2 gap: only the client loop reported logprobs,
and the speculative path bypassed them).

Logprob = log-softmax of the RAW logits (the model's distribution — the
standard serving-API meaning), so under greedy decoding every engine must
report the SAME values for the same tokens: solo Engine (device-side jit),
BatchedEngine (lanes + fused chunks), PipelinedEngine (pp mesh),
SpeculativeEngine (from the verify chunk's TARGET logits), and the node's
/generate (client-side from shipped logits; speculative fast path when
armed). That cross-engine equality is the test."""

import asyncio

import jax
import numpy as np
import pytest

from inferd_tpu.config import TINY, SamplingConfig
from inferd_tpu.core.batch import BatchedEngine
from inferd_tpu.core.generate import Engine
from inferd_tpu.core.speculative import SpeculativeEngine, self_draft
from inferd_tpu.models import qwen3

GREEDY = SamplingConfig(temperature=0.0)
PROMPT = [3, 7, 11, 19, 5, 2]
STEPS = 8
TOPN = 4


@pytest.fixture(scope="module")
def tiny_params():
    return qwen3.init_params(TINY, jax.random.PRNGKey(0))


@pytest.fixture(scope="module")
def reference(tiny_params):
    """Solo engine greedy run with logprobs: every other engine must match."""
    eng = Engine(TINY, tiny_params, max_len=64, sampling_cfg=GREEDY)
    lps, tops = [], []
    ids = eng.generate(
        PROMPT, max_new_tokens=STEPS, logprob_sink=lps, top_n=TOPN,
        top_sink=tops,
    )
    assert len(ids) == len(lps) == len(tops) == STEPS
    return ids, lps, tops


def _assert_match(reference, ids, lps, tops, atol=5e-4):
    ref_ids, ref_lps, ref_tops = reference
    assert ids == ref_ids
    np.testing.assert_allclose(lps, ref_lps, atol=atol, rtol=1e-4)
    for (ti, tl), (ri, rl) in zip(tops, ref_tops):
        assert list(ti)[: len(ri)] == list(ri)
        np.testing.assert_allclose(list(tl)[: len(rl)], rl, atol=atol, rtol=1e-4)


def test_engine_logprobs_match_rescoring(tiny_params, reference):
    """The reference values themselves are honest: re-score the emitted
    sequence with a plain forward and compare log-softmax directly."""
    import jax.numpy as jnp

    ids, lps, tops = reference
    seq = PROMPT + ids
    logits, _, _ = qwen3.forward(params=tiny_params, cfg=TINY, tokens=jnp.asarray([seq], jnp.int32))
    lf = np.asarray(logits[0], np.float64)
    for i, t in enumerate(ids):
        row = lf[len(PROMPT) - 1 + i]
        row = row - row.max()
        want = row[t] - np.log(np.exp(row).sum())
        assert abs(lps[i] - want) < 5e-4, (i, lps[i], want)
        order = np.argsort(-row, kind="stable")[:TOPN]
        assert tops[i][0] == list(order)


def test_engine_tokens_identical_with_and_without_sinks(tiny_params, reference):
    eng = Engine(TINY, tiny_params, max_len=64, sampling_cfg=GREEDY)
    assert eng.generate(PROMPT, max_new_tokens=STEPS) == reference[0]


def test_batched_engine_logprobs(tiny_params, reference):
    for chunk in (1, 4):
        eng = BatchedEngine(TINY, tiny_params, lanes=2, max_len=64,
                            sampling_cfg=GREEDY)
        lp_lists, top_lists = [], []
        outs = eng.generate_all(
            [PROMPT, [9, 4, 1]], STEPS, chunk=chunk,
            logprob_sink=lp_lists, top_n=TOPN, top_sink=top_lists,
        )
        assert len(lp_lists) == len(top_lists) == 2
        assert [len(l) for l in lp_lists] == [len(o) for o in outs]
        _assert_match(reference, outs[0], lp_lists[0], top_lists[0])


def test_pipelined_engine_logprobs(tiny_params, reference):
    from inferd_tpu.parallel import mesh as meshlib
    from inferd_tpu.parallel.infer import PipelinedEngine

    mesh = meshlib.make_mesh(meshlib.MeshPlan(pp=2), jax.devices()[:2])
    eng = PipelinedEngine(TINY, tiny_params, mesh, num_microbatches=2,
                          batch=1, max_len=64, sampling_cfg=GREEDY)
    lp_lists, top_lists = [], []
    outs = eng.generate(
        [PROMPT], STEPS, logprob_sink=lp_lists, top_n=TOPN, top_sink=top_lists,
    )
    _assert_match(reference, outs[0], lp_lists[0], top_lists[0])


def test_speculative_engine_logprobs(tiny_params, reference):
    """The verify chunk's TARGET logits carry the logprobs — identical to
    the plain engine's, regardless of what the draft proposed."""
    dcfg, dparams = self_draft(TINY, tiny_params, 2)
    eng = SpeculativeEngine(TINY, tiny_params, dcfg, dparams, k=3,
                            max_len=64, top_n=TOPN)
    lps, tops = [], []
    ids, _acc = eng.generate(
        PROMPT, STEPS, logprob_sink=lps, top_sink=tops,
    )
    assert len(ids) == len(lps) == len(tops)
    _assert_match(reference, ids, lps, tops)
    with pytest.raises(ValueError, match="greedy-only"):
        SpeculativeEngine(
            TINY, tiny_params, dcfg, dparams, k=3, max_len=64,
            sampling_cfg=SamplingConfig(temperature=0.5),
        ).generate(PROMPT, 4, logprob_sink=[])


def test_sampled_logprobs_are_model_probs(tiny_params):
    """Sampled decoding reports the MODEL's logprob of whatever was drawn
    (not the warped distribution) — and tokens don't change with sinks."""
    s = SamplingConfig(temperature=0.9, top_k=10)
    eng = Engine(TINY, tiny_params, max_len=64, sampling_cfg=s)
    a = eng.generate(PROMPT, max_new_tokens=6, seed=11)
    lps = []
    b = eng.generate(PROMPT, max_new_tokens=6, seed=11, logprob_sink=lps)
    assert a == b and len(lps) == 6 and all(x < 0 for x in lps)
