"""Round-19 Pallas decode kernels: interpret-mode parity vs the XLA
siblings they replace, and token-exact end-to-end streams with the
kernels forced on.

Three kernels, one correctness bar each:
  * paged decode-attention (`ops.attention.paged_decode_gqa`) vs
    gather_block_kv + decode_gqa — same online-softmax math, no dense
    gather; scratch block 0 masked, sinks/softcap/window folded.
  * dequant-fused int4 GEMV (`ops.qmatmul.w4a16_matvec`) vs the qdot
    dequant / grouped XLA paths — the "dequant" scheme is BIT-exact by
    construction (identical op sequence), "grouped" matches the XLA
    grouped contraction to accumulation-order rounding.
  * fused LoRA lane-delta (`ops.lora.fused_lane_delta`) vs
    gather_lanes + lane_delta — bit-exact (same two f32 contractions,
    the gather just never materializes).

conftest pins INFERD_AUTOTUNE to an absent path, so with the FORCE
hooks left at None every dispatch below is registry-cold: the kernels
stay OFF and serving is byte-identical to the pre-kernel tree — that
cold-fallback identity is asserted here too.
"""

import dataclasses

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from inferd_tpu.config import PRESETS
from inferd_tpu.models import qwen3
from inferd_tpu.ops import attention as att
from inferd_tpu.ops import lora as lora_ops
from inferd_tpu.ops import quant

TINY = PRESETS["tiny"]


@pytest.fixture(scope="module")
def tiny_params():
    return qwen3.init_params(TINY, jax.random.PRNGKey(0))


@pytest.fixture
def paged_forced():
    old = att.FORCE_PAGED_KERNEL
    att.FORCE_PAGED_KERNEL = True
    yield
    att.FORCE_PAGED_KERNEL = old


@pytest.fixture
def all_kernels_forced():
    olds = (att.FORCE_PAGED_KERNEL, quant.FORCE_QUANT_KERNEL,
            lora_ops.FORCE_LORA_KERNEL)
    att.FORCE_PAGED_KERNEL = True
    quant.FORCE_QUANT_KERNEL = True
    lora_ops.FORCE_LORA_KERNEL = True
    yield
    (att.FORCE_PAGED_KERNEL, quant.FORCE_QUANT_KERNEL,
     lora_ops.FORCE_LORA_KERNEL) = olds


# ---------------------------------------------------------------------------
# paged decode-attention kernel
# ---------------------------------------------------------------------------


def _paged_case(rng, b=2, nkv=2, g=2, d=16, bs=8, mb=4, pool_dtype=None):
    """Shuffled-chain paged pools + the equivalent dense view."""
    t = mb * bs
    nb = 1 + b * mb  # block 0 = scratch
    pool_k = rng.randn(nb, bs, nkv, d).astype(np.float32)
    pool_v = rng.randn(nb, bs, nkv, d).astype(np.float32)
    # deliberately shuffled, non-contiguous chains over blocks 1..nb-1
    perm = rng.permutation(nb - 1) + 1
    table = perm[: b * mb].reshape(b, mb).astype(np.int32)
    kd = pool_k[table].reshape(b, t, nkv, d)
    vd = pool_v[table].reshape(b, t, nkv, d)
    q = rng.randn(b, 1, nkv * g, d).astype(np.float32)
    if pool_dtype is not None:
        pool_k = np.asarray(jnp.asarray(pool_k, pool_dtype))
        kd = np.asarray(jnp.asarray(kd, pool_dtype))
        pool_v = np.asarray(jnp.asarray(pool_v, pool_dtype))
        vd = np.asarray(jnp.asarray(vd, pool_dtype))
    return pool_k, pool_v, table, kd, vd, q


@pytest.mark.parametrize("pool_dtype", [
    jnp.float32, jnp.bfloat16, jnp.float8_e4m3fn,
])
def test_paged_kernel_shuffled_chain_parity(paged_forced, pool_dtype):
    """Kernel == XLA gather path over shuffled chains and ragged per-lane
    valid lengths, for full-width AND compressed-KV pools (the upcast
    stays dequant-fused inside the kernel)."""
    rng = np.random.RandomState(0)
    pool_k, pool_v, table, kd, vd, q = _paged_case(
        rng, pool_dtype=pool_dtype)
    qpos = jnp.asarray([[21], [30]], jnp.int32)
    valid = jnp.asarray([22, 31], jnp.int32)
    args = (jnp.asarray(q), jnp.asarray(pool_k), jnp.asarray(pool_v),
            qpos, valid)
    kern = att.decode_gqa(*args, block_table=jnp.asarray(table))
    att.FORCE_PAGED_KERNEL = False
    xla = att.decode_gqa(*args, block_table=jnp.asarray(table))
    dense = att.decode_gqa(jnp.asarray(q), jnp.asarray(kd),
                           jnp.asarray(vd), qpos, valid)
    assert jnp.array_equal(xla, dense)  # gather path is exact by layout
    tol = 2e-6 if pool_dtype == jnp.float32 else 2e-2
    np.testing.assert_allclose(np.asarray(kern, np.float32),
                               np.asarray(xla, np.float32),
                               rtol=tol, atol=tol)


def test_paged_kernel_scratch_and_garbage_blocks_masked(paged_forced):
    """Block 0 (scratch) and never-chained pool blocks hold garbage — the
    frozen-lane / unallocated-block state a live co-batched pool is
    always in — and must not leak into any lane's output. Finite garbage
    for the XLA parity check (the XLA gather's 0-weight x NaN would
    poison ITS output, not the kernel's), then NaN garbage to prove the
    kernel truly never reads those slots."""
    rng = np.random.RandomState(1)
    pool_k, pool_v, table, kd, vd, q = _paged_case(rng, b=2, mb=4)
    # lane 1's chain only covers 2 blocks of history; its tail table
    # entries point AT scratch (the executor stamps unallocated = 0)
    table = table.copy()
    table[1, 2:] = 0
    garbage = [0] + [blk for blk in range(pool_k.shape[0])
                     if blk not in set(table.flatten().tolist())]
    qpos = jnp.asarray([[21], [13]], jnp.int32)
    valid = jnp.asarray([22, 14], jnp.int32)  # lane 1 inside 2 blocks

    def run(fill, forced):
        pk, pv = pool_k.copy(), pool_v.copy()
        for blk in garbage:
            pk[blk] = fill
            pv[blk] = fill
        att.FORCE_PAGED_KERNEL = forced
        return att.decode_gqa(
            jnp.asarray(q), jnp.asarray(pk), jnp.asarray(pv), qpos,
            valid, block_table=jnp.asarray(table))

    kern = run(1e6, True)
    xla = run(1e6, False)
    assert np.isfinite(np.asarray(xla)).all()
    np.testing.assert_allclose(np.asarray(kern), np.asarray(xla),
                               rtol=2e-6, atol=2e-6)
    kern_nan = run(np.nan, True)
    assert np.isfinite(np.asarray(kern_nan)).all()
    assert jnp.array_equal(kern_nan, kern)


@pytest.mark.parametrize("softcap,window,with_sinks", [
    (30.0, None, False),   # gemma-2 logit softcap
    (0.0, 16, False),      # sliding window shorter than the chain
    (0.0, None, True),     # gpt-oss attention sinks
    (30.0, 16, True),      # all three folded together
])
def test_paged_kernel_sinks_softcap_window(paged_forced, softcap, window,
                                           with_sinks):
    rng = np.random.RandomState(2)
    pool_k, pool_v, table, kd, vd, q = _paged_case(rng)
    nq = q.shape[2]
    sinks = (jnp.asarray(rng.randn(nq), jnp.float32)
             if with_sinks else None)
    w = jnp.int32(window) if window else None
    qpos = jnp.asarray([[25], [28]], jnp.int32)
    valid = jnp.asarray([26, 29], jnp.int32)
    kw = dict(softcap=softcap, window=w, sinks=sinks,
              block_table=jnp.asarray(table))
    kern = att.decode_gqa(jnp.asarray(q), jnp.asarray(pool_k),
                          jnp.asarray(pool_v), qpos, valid, **kw)
    att.FORCE_PAGED_KERNEL = False
    xla = att.decode_gqa(jnp.asarray(q), jnp.asarray(pool_k),
                         jnp.asarray(pool_v), qpos, valid, **kw)
    np.testing.assert_allclose(np.asarray(kern), np.asarray(xla),
                               rtol=2e-6, atol=2e-6)


def test_paged_dispatch_cold_registry_stays_xla():
    """FORCE hooks at None + cold registry (conftest pins the autotune
    path absent): every enable gate reports off and the block-table
    dispatch is byte-identical to the explicit gather + decode_gqa
    composition — registry-less hosts keep the pre-kernel bytes."""
    assert att.FORCE_PAGED_KERNEL is None
    assert not att.paged_kernel_enabled()
    assert not quant._quant_kernel_enabled()
    assert not lora_ops.fused_delta_enabled()
    rng = np.random.RandomState(3)
    pool_k, pool_v, table, kd, vd, q = _paged_case(rng)
    qpos = jnp.asarray([[21], [30]], jnp.int32)
    valid = jnp.asarray([22, 31], jnp.int32)
    via_dispatch = att.decode_gqa(
        jnp.asarray(q), jnp.asarray(pool_k), jnp.asarray(pool_v),
        qpos, valid, block_table=jnp.asarray(table))
    kg, vg = att.gather_block_kv(jnp.asarray(pool_k), jnp.asarray(pool_v),
                                 jnp.asarray(table))
    explicit = att.decode_gqa(jnp.asarray(q), kg, vg, qpos, valid)
    assert jnp.array_equal(via_dispatch, explicit)


# ---------------------------------------------------------------------------
# dequant-fused int4/int8 decode GEMV
# ---------------------------------------------------------------------------


def _int4_case(rng, m, k, n, x_dtype, group=32):
    x = jnp.asarray(rng.randn(m, k), x_dtype)
    w = quant.quantize_int4(
        jnp.asarray(rng.randn(k, n), jnp.float32), group=group)
    return x, w


@pytest.mark.parametrize("x_dtype", [jnp.float32, jnp.bfloat16])
@pytest.mark.parametrize("m,k,n", [(1, 64, 96), (4, 64, 96), (3, 33, 96)])
def test_w4a16_dequant_scheme_bitexact(x_dtype, m, k, n):
    """The "dequant" scheme runs the same unpack -> scale -> cast -> dot
    sequence as `x @ w.dequantize(x.dtype)` — bit-exact, packed (even K)
    and unpacked (odd K) alike."""
    from inferd_tpu.ops.qmatmul import w4a16_matvec

    rng = np.random.RandomState(4)
    x, w = _int4_case(rng, m, k, n, x_dtype)
    got = w4a16_matvec(x, w, scheme="dequant", interpret=True)
    ref = x @ w.dequantize(x.dtype)
    assert got.dtype == ref.dtype
    assert jnp.array_equal(got, ref)


@pytest.mark.parametrize("x_dtype,tol", [
    (jnp.float32, 1e-5), (jnp.bfloat16, 2e-2),
])
def test_w4a16_grouped_scheme_allclose(x_dtype, tol):
    """The "grouped" scheme keeps per-group partials in f32 where the XLA
    sibling rounds them through x.dtype — parity to accumulation-order
    rounding, not bits."""
    from inferd_tpu.ops.qmatmul import w4a16_matvec

    rng = np.random.RandomState(5)
    x, w = _int4_case(rng, 2, 64, 96, x_dtype)
    got = w4a16_matvec(x, w, scheme="grouped", interpret=True)
    # the XLA grouped contraction qdot runs when the kernel is off
    g = w.scale.shape[-2]
    k = w.shape[0]
    xg = x.reshape(2, g, k // g)
    qg = w.unpacked().reshape(g, k // g, w.shape[1]).astype(x.dtype)
    y = jnp.einsum("bgk,gkn->bgn", xg, qg)
    ref = (y.astype(jnp.float32) * w.scale).sum(axis=-2).astype(x.dtype)
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(ref, np.float32),
        rtol=tol, atol=tol)


def test_qdot_int4_kernel_routing_and_prefill_fallthrough():
    """With the kernel forced on, decode-shaped qdot routes through
    w4a16_matvec (identical bits under the dequant scheme) while
    prefill-shaped calls (rows > MAX_KERNEL_ROWS) fall through to the
    XLA path untouched."""
    from inferd_tpu.ops.qmatmul import MAX_KERNEL_ROWS

    rng = np.random.RandomState(6)
    x_dec, w = _int4_case(rng, 2, 64, 96, jnp.float32)
    x_pre = jnp.asarray(
        rng.randn(MAX_KERNEL_ROWS + 1, 64), jnp.float32)
    olds = quant.FORCE_QUANT_KERNEL, quant.INT4_MODE
    try:
        quant.INT4_MODE = "dequant"
        quant.FORCE_QUANT_KERNEL = False
        ref_dec = quant.qdot(x_dec, w)
        ref_pre = quant.qdot(x_pre, w)
        quant.FORCE_QUANT_KERNEL = True
        got_dec = quant.qdot(x_dec, w)
        got_pre = quant.qdot(x_pre, w)
    finally:
        quant.FORCE_QUANT_KERNEL, quant.INT4_MODE = olds
    assert jnp.array_equal(got_dec, ref_dec)   # kernel == dequant, bitwise
    assert jnp.array_equal(got_pre, ref_pre)   # fell through: same path


@pytest.mark.parametrize("x_dtype,tol", [
    (jnp.float32, 1e-5), (jnp.bfloat16, 2e-2),
])
def test_qdot_int8_dequant_mode_kernel_routing(x_dtype, tol):
    """QDOT_MODE="dequant" + registry-says-kernel routes int8 decode
    matvecs through w8a16_matmul; parity to the dequant XLA path is
    rounding-bounded (the kernel keeps the f32 scale-accumulate)."""
    rng = np.random.RandomState(7)
    x = jnp.asarray(rng.randn(2, 64), x_dtype)
    w = quant.quantize(jnp.asarray(rng.randn(64, 96), jnp.float32))
    old = quant.FORCE_QUANT_KERNEL
    try:
        quant.FORCE_QUANT_KERNEL = False
        ref = quant.qdot(x, w)
        quant.FORCE_QUANT_KERNEL = True
        got = quant.qdot(x, w)
    finally:
        quant.FORCE_QUANT_KERNEL = old
    assert got.dtype == ref.dtype
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(ref, np.float32),
        rtol=tol, atol=tol)


# ---------------------------------------------------------------------------
# fused LoRA lane-delta kernel
# ---------------------------------------------------------------------------


def _lora_pools(rng, slots=4, n_layers=3, d_in=32, r=4, d_out=48):
    """Stacked pools with slot 0 = zero base and MIXED effective ranks
    (narrow tenants zero-pad their tail rank columns, exactly how the
    registry stacks a rank-2 adapter into a rank-4 pool)."""
    a = rng.randn(slots, n_layers, d_in, r).astype(np.float32) * 0.3
    b = rng.randn(slots, n_layers, r, d_out).astype(np.float32) * 0.3
    a[0] = 0.0
    b[0] = 0.0
    a[2, :, :, 2:] = 0.0  # slot 2: effective rank 2
    b[2, :, 2:, :] = 0.0
    scale = np.asarray([0.0, 2.0, 0.5, 1.25], np.float32)[:slots]
    return jnp.asarray(a), jnp.asarray(b), jnp.asarray(scale)


def test_fused_lane_delta_bitexact_mixed_ranks():
    """Kernel == gather_lanes + lane_delta, every layer, bit for bit —
    mixed-rank slots, slot-0 base lanes included."""
    rng = np.random.RandomState(8)
    a, b, scale = _lora_pools(rng)
    ids = jnp.asarray([2, 0, 1, 3], jnp.int32)  # incl. base lane
    x = jnp.asarray(rng.randn(4, 1, 32), jnp.float32)
    for layer in range(a.shape[1]):
        got = lora_ops.fused_lane_delta(
            x, a, b, scale, ids, jnp.int32(layer), interpret=True)
        ref = lora_ops.lane_delta(
            x, a[ids, layer], b[ids, layer], scale[ids])
        assert jnp.array_equal(got, ref), f"layer {layer}"
    # slot-0 lanes are an exact zero delta
    got0 = lora_ops.fused_lane_delta(
        x, a, b, scale, jnp.zeros(4, jnp.int32), jnp.int32(0),
        interpret=True)
    assert jnp.array_equal(got0, jnp.zeros_like(got0))


def test_apply_lane_delta_pools_form_matches_gather_form():
    """apply_lane_delta's fused pools form == its gather (layers) form at
    a projection, bit for bit; a target absent from the pools passes y
    through untouched."""
    rng = np.random.RandomState(9)
    a, b, scale = _lora_pools(rng)
    ids = jnp.asarray([1, 2, 0, 3], jnp.int32)
    x = jnp.asarray(rng.randn(4, 1, 32), jnp.float32)
    y = jnp.asarray(rng.randn(4, 1, 48), jnp.float32)
    adapters = {"a": {"q_proj": a}, "b": {"q_proj": b},
                "scale": scale, "ids": ids}
    old = lora_ops.FORCE_LORA_KERNEL
    try:
        lora_ops.FORCE_LORA_KERNEL = True
        fused = lora_ops.apply_lane_delta(
            y, x, "q_proj", {"pools": adapters, "layer": jnp.int32(1)})
        missing = lora_ops.apply_lane_delta(
            y, x, "up_proj", {"pools": adapters, "layer": jnp.int32(1)})
    finally:
        lora_ops.FORCE_LORA_KERNEL = old
    gathered = lora_ops.apply_lane_delta(
        y, x, "q_proj",
        {"layers": {"q_proj": (a[ids, 1], b[ids, 1])},
         "scale": scale[ids]})
    assert jnp.array_equal(fused, gathered)
    assert jnp.array_equal(missing, y)


# ---------------------------------------------------------------------------
# end-to-end: decode_k and both batched executors, kernels forced on
# ---------------------------------------------------------------------------


def _greedy_stream(ex, sid, prompt, steps, adapter=None):
    payload = {"tokens": [prompt], "start_pos": 0, "real_len": len(prompt)}
    if adapter is not None:
        payload["adapter"] = adapter
    out = ex.process(sid, payload)
    toks = [int(np.argmax(out["logits"][0]))]
    pos = len(prompt)
    for _ in range(steps - 1):
        o = ex.process(sid, {
            "tokens": [[toks[-1]]], "start_pos": pos, "real_len": 1,
        })
        toks.append(int(np.argmax(o["logits"][0])))
        pos += 1
    return toks


def test_decode_k_paged_token_exact_kernel_forced(all_kernels_forced,
                                                  tiny_params):
    """The fused K-step loop over a paged cache with the attention kernel
    forced on emits the same tokens as the dense cache with it off."""
    from inferd_tpu.core.cache import BlockPool, KVCache

    def run(forced):
        att.FORCE_PAGED_KERNEL = forced
        pool = BlockPool(TINY, TINY.num_layers, lanes=2, max_len=96,
                         block_size=16)
        serve = qwen3.make_decode_k_serve(TINY)
        toks = np.array([list(range(3, 19)), list(range(4, 20))], np.int32)
        b, n = toks.shape
        dense = KVCache.create(TINY, TINY.num_layers, b,
                               pool.max_blocks * pool.block_size, ring=False)
        for lane in range(b):
            pool.ensure(lane, n + 6, owner=f"lane {lane}")
        paged = dataclasses.replace(pool.cache, table=pool.device_table())
        pos = jnp.broadcast_to(jnp.arange(n), (b, n))
        cache = paged if forced else dense
        logits, cache = qwen3.forward_cached(
            tiny_params, TINY, jnp.asarray(toks), pos, cache,
            jnp.int32(0), real_end=jnp.int32(n))
        tok = jnp.argmax(logits[:, n - 1], -1).astype(jnp.int32)
        lens = jnp.full((b,), n, jnp.int32)
        act = jnp.ones((b,), bool)
        keys = jnp.zeros((b, 2), jnp.uint32)
        eos = jnp.asarray([-1, -1], jnp.int32)
        _, seq, n_new, _ = serve(tiny_params, cache, tok, lens, act, keys,
                                 eos, 6, 0.0, 0, 1.0, 0.0)
        return np.asarray(seq), np.asarray(n_new)

    seq_k, n_k = run(True)
    seq_x, n_x = run(False)
    assert np.array_equal(seq_k, seq_x)
    assert np.array_equal(n_k, n_x)


def test_stage_executor_paged_cobatch_token_exact_kernels_forced(
        all_kernels_forced, tiny_params):
    """BatchedStageExecutor over a paged pool, staggered admissions (so
    co-batched steps see frozen lanes whose blocks hold stale garbage),
    every stream token-exact with the kernels forced on vs off."""
    from inferd_tpu.parallel.stages import Manifest, extract_stage_params
    from inferd_tpu.runtime.stage_batch import BatchedStageExecutor

    spec = list(Manifest.even_split("tiny", 1).stage_specs())[0]
    sp = extract_stage_params(tiny_params, TINY, spec)
    p_a = [3, 17, 42, 9, 5, 8, 2, 11]
    p_b = [6, 1, 33, 27]

    def run(forced):
        att.FORCE_PAGED_KERNEL = forced
        ex = BatchedStageExecutor(TINY, spec, sp, lanes=4, max_len=64,
                                  block_size=8)
        # stagger: A decodes alone first (B's future lane frozen), then
        # B joins the co-batch window
        a1 = _greedy_stream(ex, "a", p_a, 4)
        b1 = _greedy_stream(ex, "b", p_b, 6)
        a2 = _greedy_stream(ex, "a2", p_a, 4)
        return a1, b1, a2

    assert run(True) == run(False)


def test_batched_executor_lora_tenants_token_exact_kernels_forced(
        all_kernels_forced, tiny_params, tmp_path):
    """BatchedExecutor with two mixed-rank tenants + a base lane: every
    stream token-exact with the fused LoRA kernel forced on vs off."""
    from inferd_tpu.runtime.adapters import AdapterRegistry
    from inferd_tpu.runtime.batch_executor import BatchedExecutor

    g = np.random.default_rng(10)
    dirs = []
    for name, r, targets in (("t0", 4, ("q_proj", "down_proj")),
                             ("t1", 2, ("gate_proj",))):
        dims = {"q_proj": (TINY.hidden_size, TINY.q_dim),
                "down_proj": (TINY.intermediate_size, TINY.hidden_size),
                "gate_proj": (TINY.hidden_size, TINY.intermediate_size)}
        layers = {
            t: (g.normal(0, 0.25, (TINY.num_layers, dims[t][0], r))
                 .astype(np.float32),
                g.normal(0, 0.25, (TINY.num_layers, r, dims[t][1]))
                 .astype(np.float32))
            for t in targets
        }
        p = str(tmp_path / name)
        lora_ops.save_adapter(p, layers, alpha=8, r=r)
        dirs.append(p)
    prompt = [3, 17, 42, 9, 5, 8, 2, 11]

    def run(forced):
        lora_ops.FORCE_LORA_KERNEL = forced
        ex = BatchedExecutor(TINY, tiny_params, lanes=4, max_len=64,
                             adapters=AdapterRegistry(TINY, dirs))
        return (_greedy_stream(ex, "s0", prompt, 5, adapter="t0"),
                _greedy_stream(ex, "s1", prompt, 5, adapter="t1"),
                _greedy_stream(ex, "sb", prompt, 5))

    assert run(True) == run(False)


def test_quantized_executor_stream_token_exact_kernel_forced(tiny_params):
    """An int4-quantized single-stage executor decodes the same greedy
    stream with the dequant GEMV kernel forced on vs off."""
    from inferd_tpu.parallel.stages import StageSpec, extract_stage_params
    from inferd_tpu.runtime.executor import Qwen3StageExecutor

    qparams = quant.apply_quant_mode(
        "int4", tiny_params, tie_word_embeddings=TINY.tie_word_embeddings)
    spec = StageSpec(0, 1, 0, TINY.num_layers - 1)
    sp = extract_stage_params(qparams, TINY, spec)
    prompt = [3, 17, 42, 9, 5, 8, 2, 11]

    def run(forced):
        olds = quant.FORCE_QUANT_KERNEL, quant.INT4_MODE
        quant.FORCE_QUANT_KERNEL = forced
        quant.INT4_MODE = "dequant"
        try:
            ex = Qwen3StageExecutor(TINY, spec, sp, max_len=64,
                                    initial_kv_len=64)
            return _greedy_stream(ex, "q", prompt, 5)
        finally:
            quant.FORCE_QUANT_KERNEL, quant.INT4_MODE = olds

    assert run(True) == run(False)
