"""Paged KV block pool: token-exactness vs the dense path, CoW shared-
prefix caching, chunked prefill, and the block-pool allocator itself
(core/cache.BlockPool, ops/attention block-table path, both batched
executors' --paged-kv mode). The correctness bar everywhere is the dense
layout: same tokens, same logits, bit for bit."""

import threading
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from inferd_tpu.config import PRESETS
from inferd_tpu.core import prefix as prefixlib
from inferd_tpu.core.cache import BlockPool, KVCache, PagedKVCache, grow
from inferd_tpu.models import qwen3

TINY = PRESETS["tiny"]


@pytest.fixture(scope="module")
def tiny_params():
    return qwen3.init_params(TINY, jax.random.PRNGKey(0))


@pytest.fixture(scope="module")
def whole_stage(tiny_params):
    from inferd_tpu.parallel.stages import Manifest, extract_stage_params

    manifest = Manifest.even_split("tiny", 1)
    spec = list(manifest.stage_specs())[0]
    return spec, extract_stage_params(tiny_params, TINY, spec)


# ---------------------------------------------------------------------------
# block_keys: the shared-prefix identity
# ---------------------------------------------------------------------------


def test_block_keys_chain_identity():
    a = prefixlib.block_keys(list(range(40)), 16)
    b = prefixlib.block_keys(list(range(40)) + [99], 16)
    assert len(a) == 2 and len(b) == 2
    assert a == b  # same full blocks -> same keys (tail token is partial)
    c = prefixlib.block_keys([7] + list(range(1, 40)), 16)
    # first block differs -> EVERY key differs (chained, not per-block)
    assert c[0] != a[0] and c[1] != a[1]
    d = prefixlib.block_keys(list(range(16)) , 16)
    assert d == a[:1]


def test_block_keys_partial_blocks_get_no_key():
    assert prefixlib.block_keys([1, 2, 3], 16) == []
    assert len(prefixlib.block_keys(list(range(16)), 16)) == 1


# ---------------------------------------------------------------------------
# BlockPool allocator
# ---------------------------------------------------------------------------


def test_block_pool_alloc_release_refcount():
    pool = BlockPool(TINY, TINY.num_layers, lanes=2, max_len=64,
                     block_size=16)
    pool.ensure(0, 40, owner="session a, lane 0")
    assert pool.lane_blocks[0] == 3 and pool.blocks_used == 3
    pool.release_lane(0)
    assert pool.blocks_used == 0 and pool.lane_blocks[0] == 0
    # exhaustion carries the owner identity in the BufferError
    small = BlockPool(TINY, TINY.num_layers, lanes=2, max_len=64,
                      block_size=16, num_blocks=3)  # scratch + 2
    small.ensure(0, 32, owner="session a, lane 0")
    with pytest.raises(BufferError, match="session b, lane 1"):
        small.ensure(1, 32, owner="session b, lane 1")


def test_block_pool_prefix_index_map_register_evict():
    pool = BlockPool(TINY, TINY.num_layers, lanes=2, max_len=64,
                     block_size=16, num_blocks=5)
    keys = prefixlib.block_keys(list(range(32)), 16)
    pool.ensure(0, 32, owner="a")
    assert pool.register_prefix(0, keys) == 2
    pool.release_lane(0)
    # blocks survive teardown through the index's own references
    assert pool.blocks_used == 2
    cov = pool.map_prefix(1, keys)
    assert cov == 32 and pool.lane_shared[1] == 2
    assert pool.cow_shared == 2
    pool.release_lane(1)
    # unpinned entries evict LRU when space is needed
    pool.ensure(0, 64, owner="a")  # needs all 4 usable blocks
    assert pool.prefix_evictions == 2 and pool.blocks_used == 4


def test_block_pool_pinned_entries_never_evicted():
    pool = BlockPool(TINY, TINY.num_layers, lanes=1, max_len=64,
                     block_size=16, num_blocks=4)
    keys = prefixlib.block_keys(list(range(16)), 16)
    pool.ensure(0, 16, owner="a")
    pool.register_prefix(0, keys)
    assert pool.pin(keys) == 1 and pool.pins_resident == 1
    pool.release_lane(0)
    with pytest.raises(BufferError):
        pool.ensure(0, 64, owner="session x, lane 0")  # pin holds 1 of 3
    pool.unpin(keys)
    pool.ensure(0, 48, owner="a")  # now evictable
    assert pool.pins_resident == 0


def test_block_pool_cow_split_queues_copy_and_holds_src():
    pool = BlockPool(TINY, TINY.num_layers, lanes=2, max_len=64,
                     block_size=16)
    keys = prefixlib.block_keys(list(range(32)), 16)
    pool.ensure(0, 32, owner="a")
    pool.register_prefix(0, keys)
    pool.release_lane(0)
    pool.map_prefix(1, keys)
    pool.make_writable(1, 20, owner="b")  # split block 1 only
    assert pool.cow_splits == 1 and pool.lane_shared[1] == 1
    src_before = pool.blocks_used
    # a release between queue and drain must NOT recycle the copy source
    pairs = pool.drain_copies()
    assert len(pairs) == 1
    assert pool.blocks_used <= src_before


def test_block_pool_rejects_sliding_window_models():
    with pytest.raises(ValueError, match="uniform-layout"):
        BlockPool(PRESETS["tiny-gemma2"], 4, lanes=1, max_len=64,
                  block_size=16)


# ---------------------------------------------------------------------------
# ops-level: block-table attention path vs dense
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("pool_dtype", [jnp.float32, jnp.bfloat16])
def test_decode_gqa_block_table_exact(pool_dtype):
    """decode_gqa through a (shuffled) block table equals decode_gqa over
    the equivalent dense buffer — including compressed-KV storage (the
    gather preserves the narrow dtype; the upcast stays downstream)."""
    from inferd_tpu.ops import attention as ops

    rng = np.random.RandomState(0)
    b, nkv, g, d, bs, mb = 2, 2, 2, 8, 4, 4
    t = mb * bs
    nb = 1 + b * mb
    pool_k = rng.randn(nb, bs, nkv, d).astype(np.float32)
    pool_v = rng.randn(nb, bs, nkv, d).astype(np.float32)
    # deliberately non-contiguous chains
    table = np.array([[3, 1, 7, 5], [2, 8, 4, 6]], np.int32)
    kd = pool_k[table].reshape(b, t, nkv, d)
    vd = pool_v[table].reshape(b, t, nkv, d)
    q = jnp.asarray(rng.randn(b, 1, nkv * g, d), jnp.float32)
    qpos = jnp.asarray([[9], [11]], jnp.int32)
    valid = jnp.asarray([10, 12], jnp.int32)
    dense = ops.decode_gqa(
        q, jnp.asarray(kd, pool_dtype), jnp.asarray(vd, pool_dtype),
        qpos, valid,
    )
    paged = ops.decode_gqa(
        q, jnp.asarray(pool_k, pool_dtype), jnp.asarray(pool_v, pool_dtype),
        qpos, valid, block_table=jnp.asarray(table),
    )
    assert jnp.array_equal(dense, paged)


def test_gqa_attention_block_table_prefill_exact(tiny_params):
    """The S>1 path (prefill chunks) gathers through the table too."""
    rng = np.random.RandomState(1)
    b, s, nkv, g, d, bs, mb = 1, 5, 2, 2, 8, 4, 3
    nb = 1 + b * mb
    pool_k = rng.randn(nb, bs, nkv, d).astype(np.float32)
    pool_v = rng.randn(nb, bs, nkv, d).astype(np.float32)
    table = np.array([[2, 3, 1]], np.int32)
    kd = pool_k[table].reshape(b, mb * bs, nkv, d)
    vd = pool_v[table].reshape(b, mb * bs, nkv, d)
    q = jnp.asarray(rng.randn(b, s, nkv * g, d), jnp.float32)
    qpos = jnp.asarray([[4, 5, 6, 7, 8]], jnp.int32)
    dense = qwen3.gqa_attention(q, jnp.asarray(kd), jnp.asarray(vd), qpos,
                                jnp.int32(9))
    paged = qwen3.gqa_attention(
        q, jnp.asarray(pool_k), jnp.asarray(pool_v), qpos, jnp.int32(9),
        block_table=jnp.asarray(table),
    )
    assert jnp.array_equal(dense, paged)


# ---------------------------------------------------------------------------
# model-level: paged forward_cached / decode_k vs dense
# ---------------------------------------------------------------------------


def _prefill_both(params, pool, toks):
    import dataclasses

    b, n = toks.shape
    dense = KVCache.create(TINY, TINY.num_layers, b, pool.max_blocks *
                           pool.block_size, ring=False)
    for lane in range(b):
        pool.ensure(lane, n, owner=f"lane {lane}")
    paged = dataclasses.replace(pool.cache, table=pool.device_table())
    pos = jnp.broadcast_to(jnp.arange(n), (b, n))
    ld, dc = qwen3.forward_cached(params, TINY, jnp.asarray(toks), pos,
                                  dense, jnp.int32(0), real_end=jnp.int32(n))
    lp, pc = qwen3.forward_cached(params, TINY, jnp.asarray(toks), pos,
                                  paged, jnp.int32(0), real_end=jnp.int32(n))
    assert jnp.array_equal(ld, lp)
    return ld, dc, pc


def test_forward_cached_paged_parity_prefill_decode(tiny_params):
    import dataclasses

    pool = BlockPool(TINY, TINY.num_layers, lanes=2, max_len=96,
                     block_size=16)
    toks = np.array([[3, 7, 11, 19, 23, 5, 2, 9, 14, 6],
                     [4, 8, 12, 20, 24, 6, 3, 10, 15, 7]], np.int32)
    n = toks.shape[1]
    logits, dc, pc = _prefill_both(tiny_params, pool, toks)
    lens = np.full((2,), n, np.int32)
    cur = n  # host-side frontier (python int: no per-step device read)
    tok = jnp.argmax(logits[:, n - 1], -1).astype(jnp.int32)
    for _ in range(4):
        for lane in range(2):
            pool.ensure(lane, cur + 1, owner=f"lane {lane}")
        cur += 1
        pc = dataclasses.replace(pc, table=pool.device_table())
        ld, dc = qwen3.forward_cached(
            tiny_params, TINY, tok[:, None], jnp.asarray(lens)[:, None],
            dc, jnp.asarray(lens), real_end=jnp.asarray(lens) + 1,
        )
        lp, pc = qwen3.forward_cached(
            tiny_params, TINY, tok[:, None], jnp.asarray(lens)[:, None],
            pc, jnp.asarray(lens), real_end=jnp.asarray(lens) + 1,
            write_mask=jnp.ones((2,), bool),
        )
        assert jnp.array_equal(ld, lp)
        tok = jnp.argmax(ld[:, 0], -1).astype(jnp.int32)
        lens += 1


def test_decode_k_paged_parity_with_eos(tiny_params):
    """The K-step fused inner loop over a paged cache: same tokens, same
    early-eos n_new as the dense cache."""
    import dataclasses

    pool = BlockPool(TINY, TINY.num_layers, lanes=2, max_len=96,
                     block_size=16)
    serve = qwen3.make_decode_k_serve(TINY)
    toks = np.array([list(range(3, 23)), list(range(4, 24))], np.int32)
    n = toks.shape[1]
    logits, dc, pc = _prefill_both(tiny_params, pool, toks)
    tok = jnp.argmax(logits[:, n - 1], -1).astype(jnp.int32)
    lens = jnp.full((2,), n, jnp.int32)
    act = jnp.ones((2,), bool)
    keys = jnp.zeros((2, 2), jnp.uint32)
    # eos = whatever greedy emits first for row 0 -> row 0 stops after 1
    eos = jnp.asarray([int(tok[0]) if True else -1, -1], jnp.int32)
    K = 6
    for lane in range(2):
        pool.ensure(lane, n + K, owner=f"lane {lane}")
    pc = dataclasses.replace(pc, table=pool.device_table())
    dc2, seq_d, n_d, _ = serve(tiny_params, dc, tok, lens, act, keys, eos,
                               K, 0.0, 0, 1.0, 0.0)
    pc2, seq_p, n_p, _ = serve(tiny_params, pc, tok, lens, act, keys, eos,
                               K, 0.0, 0, 1.0, 0.0)
    assert jnp.array_equal(seq_d, seq_p)
    assert jnp.array_equal(n_d, n_p)


# ---------------------------------------------------------------------------
# executor parity: both batched executors, dense vs paged
# ---------------------------------------------------------------------------


def _mk_stage(whole_stage, **kw):
    from inferd_tpu.runtime.stage_batch import BatchedStageExecutor

    spec, sp = whole_stage
    return BatchedStageExecutor(TINY, spec, sp, lanes=4, max_len=128, **kw)


def _mk_batch(tiny_params, **kw):
    from inferd_tpu.runtime.batch_executor import BatchedExecutor

    return BatchedExecutor(TINY, tiny_params, lanes=4, max_len=128, **kw)


def _drive(ex, sid, prompt, steps, kstep=None, sampling=None, eos=None,
           seed=0):
    """Greedy (or K-step payload) stream through an executor's process()
    surface; returns the emitted ids."""
    r = ex.process(sid, {"tokens": [prompt], "start_pos": 0,
                         "real_len": len(prompt)})
    out = [int(np.argmax(r["logits"][0]))]
    pos = len(prompt)
    while len(out) < steps + 1:
        payload = {"tokens": [[out[-1]]], "start_pos": pos, "real_len": 1}
        if kstep:
            payload["decode_steps"] = min(kstep, steps + 1 - len(out))
            payload["seed"] = seed
            if sampling:
                payload["sampling"] = sampling
            if eos is not None:
                payload["eos"] = eos
            r = ex.process(sid, payload)
            toks = r["tokens"][0]
            out.extend(int(t) for t in toks)
            pos += r["real_len"]
            if eos is not None and out and out[-1] == eos:
                break
            if r["real_len"] == 0:
                break
        else:
            r = ex.process(sid, payload)
            out.append(int(np.argmax(r["logits"][0])))
            pos += 1
    return out


@pytest.mark.parametrize("flavor", ["stage", "batch"])
def test_executor_paged_parity_greedy(flavor, whole_stage, tiny_params):
    mk = (lambda **kw: _mk_stage(whole_stage, **kw)) if flavor == "stage" \
        else (lambda **kw: _mk_batch(tiny_params, **kw))
    dense, paged = mk(), mk(block_size=16, prefill_chunk=8)
    prompt = list(range(3, 3 + 20))
    a = _drive(dense, "s", prompt, 6)
    b = _drive(paged, "s", prompt, 6)
    assert a == b


@pytest.mark.parametrize("flavor", ["stage", "batch"])
def test_executor_paged_parity_kstep_sampled_and_eos(flavor, whole_stage,
                                                     tiny_params):
    """K-step fused decode with on-device SAMPLING and an eos stop:
    paged == dense, token for token, committed-length for committed-
    length."""
    mk = (lambda **kw: _mk_stage(whole_stage, **kw)) if flavor == "stage" \
        else (lambda **kw: _mk_batch(tiny_params, **kw))
    dense, paged = mk(), mk(block_size=16)
    prompt = list(range(3, 3 + 20))
    sampling = {"temperature": 0.8, "top_k": 5}
    a = _drive(dense, "s", prompt, 8, kstep=4, sampling=sampling, seed=11)
    b = _drive(paged, "s", prompt, 8, kstep=4, sampling=sampling, seed=11)
    assert a == b
    # eos mid-window: stop after the first emitted token repeats
    eos = a[0]
    c = _drive(dense, "e", prompt, 8, kstep=4, eos=eos)
    d = _drive(paged, "e", prompt, 8, kstep=4, eos=eos)
    assert c == d


@pytest.mark.parametrize("flavor", ["stage", "batch"])
def test_executor_paged_replay_rollback_parity(flavor, whole_stage,
                                               tiny_params):
    """A replayed decode step (client re-sent after a lost response)
    rolls the paged frontier back and recomputes the same token."""
    mk = (lambda **kw: _mk_stage(whole_stage, **kw)) if flavor == "stage" \
        else (lambda **kw: _mk_batch(tiny_params, **kw))
    paged = mk(block_size=16)
    prompt = list(range(3, 3 + 20))
    out = _drive(paged, "s", prompt, 5)
    # replay the step that produced out[3]: frontier rolls back
    pos = len(prompt) + 2
    r = paged.process("s", {"tokens": [[out[2]]], "start_pos": pos,
                            "real_len": 1})
    assert int(np.argmax(r["logits"][0])) == out[3]


def test_shared_prefix_skips_prefill_compute(whole_stage):
    """THE acceptance assertion: a session admitted against a pinned
    shared prefix performs zero prefill compute for the shared region —
    the prefill-token counter moves only by the unshared remainder."""
    ex = _mk_stage(whole_stage, block_size=16)
    prefix = list(range(3, 3 + 64))
    assert ex.pin_prefix(prefix) == 64
    prompt = prefix + [99, 98, 97]
    before = ex.stats()["prefill_tokens"]
    hits0 = ex.stats()["paged"]["prefix_hit_tokens"]
    out = _drive(ex, "s", prompt, 4)
    moved = ex.stats()["prefill_tokens"] - before
    assert moved == len(prompt) - 64  # zero FLOPs for the pinned region
    assert ex.stats()["paged"]["prefix_hit_tokens"] - hits0 == 64
    # and the stream equals a dense run of the same prompt
    dense = _mk_stage(whole_stage)
    assert out == _drive(dense, "s", prompt, 4)


def test_cow_divergence_does_not_corrupt_sharers(whole_stage):
    """Two sessions share pinned-prefix blocks; one REWRITES inside the
    shared region (divergent replay). CoW must split its blocks so the
    other session's stream stays exact."""
    ex = _mk_stage(whole_stage, block_size=16)
    dense = _mk_stage(whole_stage)
    prefix = list(range(3, 3 + 32))
    ex.pin_prefix(prefix)
    prompt = prefix + [77, 76]
    a1 = _drive(ex, "a", prompt, 2)
    _b1 = _drive(ex, "b", prompt, 2)
    # session b diverges: replay a prefill chunk INSIDE the shared region
    # with different tokens
    alt = [50, 51, 52, 53]
    pos = 8
    rb = ex.process("b", {"tokens": [alt], "start_pos": pos, "real_len": 4})
    assert ex.stats()["paged"]["cow_splits"] >= 1
    # session a (and the pin) keep decoding the ORIGINAL stream
    ra = ex.process("a", {"tokens": [[a1[-1]]],
                          "start_pos": len(prompt) + 2, "real_len": 1})
    _drive(dense, "a", prompt, 2)
    rd = dense.process("a", {"tokens": [[a1[-1]]],
                             "start_pos": len(prompt) + 2, "real_len": 1})
    assert np.array_equal(ra["logits"], rd["logits"])
    # and b's rewritten stream equals a dense executor given the same
    # divergent history
    dense_b = _mk_stage(whole_stage)
    dense_b.process("b", {"tokens": [prompt], "start_pos": 0,
                          "real_len": len(prompt)})
    rdb = dense_b.process("b", {"tokens": [alt], "start_pos": pos,
                                "real_len": 4})
    assert np.array_equal(rb["logits"], rdb["logits"])


def test_cow_protects_registered_blocks_from_rollback(whole_stage):
    """Review regression: a lane that PUBLISHED its own blocks into the
    prefix index (register_prefix — lane_shared stays 0) must still CoW-
    split them on a divergent rollback rewrite; an in-place rewrite would
    corrupt the index for every future session."""
    ex = _mk_stage(whole_stage, block_size=16)
    dense = _mk_stage(whole_stage)
    prompt = list(range(3, 3 + 34))
    a = _drive(ex, "a", prompt, 2)  # registers blocks 0-1 on a COLD index
    assert _drive(dense, "a", prompt, 2) == a
    # divergent replay INSIDE the registered region (not a mapped prefix:
    # lane_shared is 0 for the registering lane)
    alt = [60, 61, 62, 63]
    ex.process("a", {"tokens": [alt], "start_pos": 18, "real_len": 4})
    assert ex.stats()["paged"]["cow_splits"] >= 1
    ex.end_session("a")
    # a NEW session with the ORIGINAL prompt maps the indexed blocks —
    # they must still hold the ORIGINAL KV
    b = _drive(ex, "b", prompt, 2)
    assert b == a


def test_cow_protects_fork_parent_blocks_from_rollback(tiny_params):
    """Review regression sibling: a fork PARENT's blocks are shared with
    the child (refcount) without the parent's lane_shared moving — a
    parent rollback rewrite must split, not scribble on the child."""
    ex = _mk_batch(tiny_params, block_size=16)
    dense = _mk_batch(tiny_params)
    prompt = list(range(3, 3 + 20))
    a = _drive(ex, "parent", prompt, 3)
    assert _drive(dense, "parent", prompt, 3) == a
    assert ex.fork_session("child", "parent", 16)
    assert dense.fork_session("child", "parent", 16)
    # parent diverges INSIDE the forked region
    alt = [70, 71, 72]
    ex.process("parent", {"tokens": [alt], "start_pos": 8, "real_len": 3})
    dense.process("parent", {"tokens": [alt], "start_pos": 8, "real_len": 3})
    # the child continues from the ORIGINAL prefix, unaffected
    tail = prompt[16:] + [88]
    rp = ex.process("child", {"tokens": [tail], "start_pos": 16,
                              "real_len": len(tail)})
    rd = dense.process("child", {"tokens": [tail], "start_pos": 16,
                                 "real_len": len(tail)})
    assert np.array_equal(rp["logits"], rd["logits"])


def test_export_after_fork_before_dispatch(tiny_params):
    """Review regression: exporting a session whose CoW copies are still
    QUEUED (forked, no dispatch yet) must apply them first — otherwise
    the snapshot ships uninitialized blocks."""
    src = _mk_batch(tiny_params, block_size=16)
    dense = _mk_batch(tiny_params)
    prompt = list(range(3, 3 + 20))
    a = _drive(src, "parent", prompt, 3)
    assert _drive(dense, "parent", prompt, 3) == a
    assert src.fork_session("child", "parent", 18)  # partial tail queued
    exp = dict(src.export_sessions(only="child"))  # NO dispatch ran
    dst = _mk_batch(tiny_params, block_size=16)
    assert dst.import_session("child", exp["child"])
    assert dense.fork_session("child", "parent", 18)
    tail = prompt[18:] + [88]
    r1 = dst.process("child", {"tokens": [tail], "start_pos": 18,
                               "real_len": len(tail)})
    r2 = dense.process("child", {"tokens": [tail], "start_pos": 18,
                                 "real_len": len(tail)})
    assert np.array_equal(r1["logits"], r2["logits"])


def test_paged_cobatch_mixed_lanes_parity(whole_stage):
    """Co-batched decode windows over paged lanes at mixed positions:
    every stream equals its dense co-batched sibling."""
    dense = _mk_stage(whole_stage)
    paged = _mk_stage(whole_stage, block_size=16)
    prompts = {"x": list(range(3, 3 + 18)), "y": [5, 2, 8],
               "z": list(range(9, 9 + 33))}
    state_d, state_p = {}, {}
    for ex, state in ((dense, state_d), (paged, state_p)):
        for sid, p in prompts.items():
            r = ex.process(sid, {"tokens": [p], "start_pos": 0,
                                 "real_len": len(p)})
            state[sid] = {"pos": len(p),
                          "out": [int(np.argmax(r["logits"][0]))]}
    for _ in range(4):
        for ex, state in ((dense, state_d), (paged, state_p)):
            items = [
                (sid, {"tokens": [[state[sid]["out"][-1]]],
                       "start_pos": state[sid]["pos"], "real_len": 1})
                for sid in prompts
            ]
            outs = ex.process_batch(items)
            for (sid, _), o in zip(items, outs):
                assert not isinstance(o, Exception), o
                state[sid]["out"].append(int(np.argmax(o["logits"][0])))
                state[sid]["pos"] += 1
    for sid in prompts:
        assert state_d[sid]["out"] == state_p[sid]["out"], sid


def test_paged_pool_exhaustion_is_per_item_and_carries_identity(whole_stage):
    """A lane that cannot extend its chain fails ALONE (per-item), with
    the session/lane identity in the error; its co-batch survives."""
    ex = _mk_stage(whole_stage, block_size=16, kv_blocks=5)  # tight pool
    a = list(range(3, 3 + 30))  # 2 blocks + partial
    b = list(range(40, 40 + 30))
    ex.process("a", {"tokens": [a], "start_pos": 0, "real_len": len(a)})
    ex.process("b", {"tokens": [b], "start_pos": 0, "real_len": len(b)})
    # both at 30 positions = 2 blocks each: the 4-block pool is full.
    # a's 2-step request still fits its tail block; b's 8-step request
    # needs a third block and must fail ALONE
    outs = ex.process_batch([
        ("a", {"tokens": [[1]], "start_pos": 30, "real_len": 1,
               "decode_steps": 2}),
        ("b", {"tokens": [[1]], "start_pos": 30, "real_len": 1,
               "decode_steps": 8}),
    ])
    errs = [o for o in outs if isinstance(o, Exception)]
    oks = [o for o in outs if not isinstance(o, Exception)]
    assert len(errs) == 1 and len(oks) == 1
    assert "block pool exhausted" in str(errs[0])
    assert "lane" in str(errs[0]) and "session" in str(errs[0])


def test_paged_fork_and_export_import_roundtrip(tiny_params):
    """fork_session maps blocks CoW-style; export/import speak the dense
    handoff schema so paged and dense replicas interchange sessions."""
    src = _mk_batch(tiny_params, block_size=16)
    dense = _mk_batch(tiny_params)
    prompt = list(range(3, 3 + 20))
    a = _drive(src, "parent", prompt, 3)
    b = _drive(dense, "parent", prompt, 3)
    assert a == b
    assert src.fork_session("child", "parent", 18)
    assert dense.fork_session("child", "parent", 18)
    tail = prompt[18:] + [88]
    rp = src.process("child", {"tokens": [tail], "start_pos": 18,
                               "real_len": len(tail)})
    rd = dense.process("child", {"tokens": [tail], "start_pos": 18,
                                 "real_len": len(tail)})
    assert np.array_equal(rp["logits"], rd["logits"])
    # export from paged, import into a FRESH paged executor, keep decoding
    exp = dict(src.export_sessions(only="parent"))
    dst = _mk_batch(tiny_params, block_size=16)
    assert dst.import_session("parent", exp["parent"])
    pos = len(prompt) + 3
    r1 = dst.process("parent", {"tokens": [[a[-1]]], "start_pos": pos,
                                "real_len": 1})
    r2 = dense.process("parent", {"tokens": [[b[-1]]], "start_pos": pos,
                                  "real_len": 1})
    assert np.array_equal(r1["logits"], r2["logits"])


def test_paged_rejects_spec_and_library_loop(tiny_params):
    ex = _mk_batch(tiny_params, block_size=16)
    with pytest.raises(ValueError, match="paged"):
        ex.enable_spec(2, 4)
    with pytest.raises(RuntimeError, match="dense-only"):
        ex.engine.admit([1, 2, 3])


def test_block_pool_gauges_surface(whole_stage):
    from inferd_tpu.obs import devtel

    ex = _mk_stage(whole_stage, block_size=16)
    ex.pin_prefix(list(range(3, 3 + 32)))
    g = devtel.block_pool_gauges(ex)
    assert g["pins.resident"] == 2.0  # 32 tokens / 16-token blocks
    assert g["kv.blocks_used"] >= 2.0
    assert g["kv.blocks_free"] > 0.0
    dense = _mk_stage(whole_stage)
    assert devtel.block_pool_gauges(dense) == {}


# ---------------------------------------------------------------------------
# chunked prefill: a long admission must not stall co-batched decoders
# ---------------------------------------------------------------------------


def test_chunked_prefill_interleaves_with_decode_windows(whole_stage):
    """Mixed prefill+decode load (the WindowedBatcher satellite): a long
    chunked prefill runs WHILE 8 lanes keep decoding through the node-
    style window — decode steps complete during the prefill (no head-of-
    line blocking) and the window.stall hook never fires."""
    from inferd_tpu.runtime.window import WindowedBatcher

    from inferd_tpu.runtime.stage_batch import BatchedStageExecutor

    spec, sp = whole_stage
    ex = BatchedStageExecutor(TINY, spec, sp, lanes=6, max_len=384,
                              block_size=16, prefill_chunk=8)
    stalls = []
    ex.on_event = lambda etype, **attrs: stalls.append(etype) if \
        etype == "window.stall" else None

    def run_batch(entries):
        assert entries == []
        drained = ex.window.drain_pending()
        outs = ex.process_batch([(e.payload[0], e.payload[1])
                                 for e in drained])
        for e, o in zip(drained, outs):
            if isinstance(o, Exception):
                e.error = o
            else:
                e.result = o
            e.event.set()

    # window budget: the configured bound a decode lane may wait
    window_s = 0.005
    ex.window = WindowedBatcher(
        window_s, run_batch, co_possible=ex.co_possible, swap_in_run=True,
        wait_timeout_s=30.0,
    )
    ex.window.on_event = ex.on_event

    # warm the chunked-prefill jit (bucket-8 chunks) so the measured
    # prefill is dispatch-paced, not one long compile
    ex.process("warm", {"tokens": [list(range(5, 5 + 24))], "start_pos": 0,
                        "real_len": 24})
    ex.end_session("warm")

    n_dec = 3
    prompts = {f"d{i}": [3 + i, 7, 11, 19] for i in range(n_dec)}
    state = {}
    for sid, p in prompts.items():
        r = ex.process(sid, {"tokens": [p], "start_pos": 0,
                             "real_len": len(p)})
        state[sid] = {"pos": len(p), "tok": int(np.argmax(r["logits"][0]))}

    done_ts = {sid: [] for sid in prompts}

    def one_step(sid):
        st = state[sid]
        r = ex.window.submit((sid, {
            "tokens": [[st["tok"]]], "start_pos": st["pos"],
            "real_len": 1,
        }))
        st["tok"] = int(np.argmax(r["logits"][0]))
        st["pos"] += 1
        done_ts[sid].append(time.monotonic())

    # warm the co-batched decode dispatch OUTSIDE the measured window
    warm_threads = [threading.Thread(target=one_step, args=(sid,))
                    for sid in prompts]
    for t in warm_threads:
        t.start()
    for t in warm_threads:
        t.join(timeout=60)
    for sid in done_ts:
        done_ts[sid].clear()

    long_prompt = list(range(5, 5 + 240))  # 30 chunks of 8
    span = {}

    def prefill():
        span["t0"] = time.monotonic()
        ex.process("long", {"tokens": [long_prompt], "start_pos": 0,
                            "real_len": len(long_prompt)})
        span["t1"] = time.monotonic()

    def decoder(sid):
        for _ in range(30):
            one_step(sid)

    tds = [threading.Thread(target=decoder, args=(sid,)) for sid in prompts]
    for t in tds:
        t.start()
    # let the decode cadence establish, then admit the long prompt
    time.sleep(0.03)
    tp = threading.Thread(target=prefill)
    tp.start()
    tp.join(timeout=60)
    for t in tds:
        t.join(timeout=60)
    assert stalls == []  # the window.stall hook stayed silent
    # decode steps really interleaved INTO the prefill window
    during = [
        ts for sid in prompts for ts in done_ts[sid]
        if span["t0"] <= ts <= span["t1"]
    ]
    assert during, "no decode step completed while the prefill ran"
    # and the long session is correct: its next decode matches a dense run
    dense = BatchedStageExecutor(TINY, spec, sp, lanes=2, max_len=384)
    r1 = ex.process("long", {"tokens": [[1]],
                             "start_pos": len(long_prompt), "real_len": 1})
    dense.process("long", {"tokens": [long_prompt], "start_pos": 0,
                           "real_len": len(long_prompt)})
    r2 = dense.process("long", {"tokens": [[1]],
                                "start_pos": len(long_prompt),
                                "real_len": 1})
    # chunked prefill is TOKEN-exact, not bit-exact, vs a one-dispatch
    # prefill (XLA reduces a [1, 8, H] chunk program differently than a
    # [1, 256, H] one): same argmax, logits within float tolerance
    assert np.argmax(r1["logits"][0]) == np.argmax(r2["logits"][0])
    assert np.allclose(r1["logits"], r2["logits"], atol=1e-4)


# ---------------------------------------------------------------------------
# cache.grow(): grow-then-decode token exactness (satellite)
# ---------------------------------------------------------------------------


def _decode_tokens(cfg, params, cache, logits, n, steps):
    toks = [int(np.argmax(np.asarray(logits)[0, n - 1]))]
    lens = n
    for _ in range(steps):
        l, cache = qwen3.forward_cached(
            params, cfg, jnp.asarray([[toks[-1]]], jnp.int32),
            jnp.asarray([[lens]], jnp.int32), cache, jnp.int32(lens),
            real_end=jnp.int32(lens + 1),
        )
        toks.append(int(np.argmax(np.asarray(l)[0, 0])))  # jaxlint: disable=J003 -- per-token decode loop: one boundary sync per emitted token is the pattern under test
        lens += 1
    return toks, cache


@pytest.mark.parametrize("preset", ["tiny", "tiny-gemma2"])
def test_grow_then_decode_token_exact(preset):
    """grow() to a larger bucket mid-stream changes NOTHING about the
    decoded tokens — uniform AND sliding-window (ring) layouts."""
    cfg = PRESETS[preset]
    params = qwen3.init_params(cfg, jax.random.PRNGKey(1))
    prompt = np.asarray([list(range(3, 3 + 12))], np.int32)
    n = prompt.shape[1]
    pos = jnp.broadcast_to(jnp.arange(n), (1, n))

    small = KVCache.create(cfg, cfg.num_layers, 1, 32)
    big = KVCache.create(cfg, cfg.num_layers, 1, 64)
    ls, cs = qwen3.forward_cached(params, cfg, jnp.asarray(prompt), pos,
                                  small, jnp.int32(0), real_end=jnp.int32(n))
    lb, cb = qwen3.forward_cached(params, cfg, jnp.asarray(prompt), pos,
                                  big, jnp.int32(0), real_end=jnp.int32(n))
    toks_small, cs = _decode_tokens(cfg, params, cs, ls, n, 8)
    # grow mid-stream, decode past the old 32-slot bucket
    cs = grow(cs, 64)
    assert cs.max_len == 64
    toks_big, cb = _decode_tokens(cfg, params, cb, lb, n, 8)
    assert toks_small == toks_big
    # continue decoding in the grown cache vs the always-big cache
    lens = n + 8
    tok = toks_big[-1]
    for _ in range(16):
        l1, cs = qwen3.forward_cached(
            params, cfg, jnp.asarray([[tok]], jnp.int32),
            jnp.asarray([[lens]], jnp.int32), cs, jnp.int32(lens),
            real_end=jnp.int32(lens + 1),
        )
        l2, cb = qwen3.forward_cached(
            params, cfg, jnp.asarray([[tok]], jnp.int32),
            jnp.asarray([[lens]], jnp.int32), cb, jnp.int32(lens),
            real_end=jnp.int32(lens + 1),
        )
        t1 = int(np.argmax(np.asarray(l1)[0, 0]))  # jaxlint: disable=J003 -- per-token parity loop: the grown-vs-big comparison IS per step
        t2 = int(np.argmax(np.asarray(l2)[0, 0]))  # jaxlint: disable=J003 -- same per-step comparison
        assert t1 == t2
        tok = t1
        lens += 1


def test_grow_is_noop_at_or_below_current_size():
    cache = KVCache.create(TINY, TINY.num_layers, 1, 32)
    assert grow(cache, 32) is cache
    assert grow(cache, 16) is cache


def test_ensure_room_carries_owner_identity():
    cache = KVCache.create(TINY, TINY.num_layers, 1, 16)
    with pytest.raises(BufferError, match="session s7, lane 3"):
        cache.ensure_room(32, owner="session s7, lane 3")
    cache.ensure_room(8)  # fits: no raise


# ---------------------------------------------------------------------------
# Engine.max_pins satellite
# ---------------------------------------------------------------------------


def test_engine_max_pins_parameter_and_gauge(tiny_params):
    from inferd_tpu.core.generate import Engine

    eng = Engine(TINY, tiny_params, max_len=64, max_pins=2)
    assert eng.pins_resident == 0
    eng.pin_prefix([3, 7])
    eng.pin_prefix([4, 8])
    assert eng.pins_resident == 2
    eng.pin_prefix([5, 9])  # LRU caps at max_pins
    assert eng.pins_resident == 2
    assert eng._longest_pin([3, 7, 1]) is None  # [3,7] was LRU-evicted
    assert eng._longest_pin([5, 9, 1]) == (5, 9)
    with pytest.raises(ValueError):
        Engine(TINY, tiny_params, max_len=64, max_pins=0)
