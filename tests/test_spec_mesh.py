# jaxlint: file-disable=J003 -- test code: loops here sync per-iteration to ASSERT on values; they are verification loops, not serving hot paths
"""In-mesh speculative decoding (parallel.infer.MeshSpecRunner): the draft
layers replicate on every rank and the verify chunk rides the ppermute
pipeline — one SPMD program per round. Greedy parity vs the solo engine on
pp and pp x tp virtual meshes; sampled rounds flow. Round-5 scope (VERDICT
r04 #1b)."""

import jax
import numpy as np
import pytest

from inferd_tpu.config import TINY, SamplingConfig
from inferd_tpu.core.generate import Engine, bucket_len
from inferd_tpu.models import qwen3
from inferd_tpu.parallel import mesh as meshlib
from inferd_tpu.parallel.infer import MeshSpecRunner, PipelinedEngine



from conftest import requires_native_shard_map

pytestmark = requires_native_shard_map

@pytest.fixture(scope="module")
def target():
    return TINY, qwen3.init_params(TINY, jax.random.PRNGKey(0))


def _drive(eng, runner, prompts, max_new, seed=0):
    """Lockstep driver over slots (the serving driver lives in the mesh
    executor; this mirrors core.spec_batch.generate_lanes)."""
    MB, K = eng.mb, runner.k
    sampled = runner.sampling.temperature > 0.0
    dlens = [0] * MB
    outs, tlens, chains = {}, {}, {}
    for slot, p in enumerate(prompts):
        n = len(p)
        logits = eng.step_slot(slot, np.asarray([p], np.int32), n, reset=True)
        b = min(bucket_len(n), eng.max_len)
        padded = np.zeros((1, b), np.int32)
        padded[0, :n] = p
        runner.draft_prefill(padded, slot, 0, n)
        dlens[slot] = n
        tlens[slot] = n
        key = jax.random.PRNGKey(seed + slot)
        key, sub = jax.random.split(key)
        if sampled:
            outs[slot] = [runner.first_token(logits[0], sub)]
        else:
            outs[slot] = [int(np.argmax(logits[0]))]
        chains[slot] = key
    live = set(outs)
    while live:
        for s in list(live):
            if len(outs[s]) >= max_new or tlens[s] + K + 1 > eng.max_len:
                live.discard(s)
        if not live:
            break
        active = np.zeros(MB, bool)
        last = np.zeros(MB, np.int32)
        catch = np.zeros(MB, np.int32)
        cm = np.zeros(MB, bool)
        keys = np.zeros((MB, 2), np.uint32)
        for s in live:
            active[s] = True
            last[s] = outs[s][-1]
            if dlens[s] < tlens[s]:
                catch[s] = outs[s][-2]
                cm[s] = True
            if sampled:
                chains[s], sub = jax.random.split(chains[s])
                keys[s] = np.asarray(sub)
        toks, n_new = runner.run_round(
            last, catch, cm, np.asarray(dlens, np.int32), active,
            keys if sampled else None,
        )
        for s in live:
            n = int(n_new[s])
            old = tlens[s]
            tlens[s] = old + n
            dlens[s] = old + min(n, K)
            for t in toks[s][:n].tolist():
                outs[s].append(int(t))
                if len(outs[s]) >= max_new:
                    break
    return [outs[s][:max_new] for s in range(len(prompts))]


def test_pp2_greedy_parity(target, devices8):
    cfg, params = target
    mesh = meshlib.make_mesh(meshlib.MeshPlan(pp=2), devices8[:2])
    eng = PipelinedEngine(cfg, params, mesh, num_microbatches=4, batch=1,
                          max_len=64)
    eng.enable_spec(2, 3, params)
    runner = MeshSpecRunner(eng)
    solo = Engine(cfg, params, max_len=64,
                  sampling_cfg=SamplingConfig(temperature=0.0))
    prompts = [[3, 7, 11], [2, 5, 13, 17]]
    want = [solo.generate(p, max_new_tokens=12) for p in prompts]
    got = _drive(eng, runner, prompts, 12)
    assert got == want


def test_pp2_tp2_greedy_parity(target, devices8):
    """Speculation composes with tensor parallelism inside the same SPMD
    program: draft replicated over pp x tp, verify sharded both ways."""
    cfg, params = target
    mesh = meshlib.make_mesh(meshlib.MeshPlan(pp=2, tp=2), devices8[:4])
    eng = PipelinedEngine(cfg, params, mesh, num_microbatches=2, batch=1,
                          max_len=64)
    eng.enable_spec(2, 3, params)
    runner = MeshSpecRunner(eng)
    solo = Engine(cfg, params, max_len=64,
                  sampling_cfg=SamplingConfig(temperature=0.0))
    prompts = [[3, 7, 11]]
    want = [solo.generate(p, max_new_tokens=10) for p in prompts]
    got = _drive(eng, runner, prompts, 10)
    assert got == want


def test_pp2_sampled_rounds_flow(target, devices8):
    """Sampled rejection rounds on the mesh: tokens flow and full
    acceptance holds when draft == target layers would — here just check
    length/liveness and determinism per seed."""
    cfg, params = target
    mesh = meshlib.make_mesh(meshlib.MeshPlan(pp=2), devices8[:2])
    eng = PipelinedEngine(cfg, params, mesh, num_microbatches=2, batch=1,
                          max_len=64)
    eng.enable_spec(2, 3, params)
    sc = SamplingConfig(temperature=0.9, top_k=10, top_p=0.95)
    runner = MeshSpecRunner(eng, sc)
    got1 = _drive(eng, runner, [[3, 7, 11]], 10, seed=5)
    got2 = _drive(eng, runner, [[3, 7, 11]], 10, seed=5)
    assert len(got1[0]) == 10
    assert got1 == got2


def test_ring_kv_mesh_spec_exactness(devices8):
    """Speculation composes with the ring-KV mesh layout: a Gemma-2-style
    sliding-window model on pp=2 (split ring caches) speculates
    token-exact — the verify chunk's rollback stays inside the ring
    margin and the draft's own sliding layers ring too."""
    from inferd_tpu.config import TINY_GEMMA2

    cfg = TINY_GEMMA2
    params = qwen3.init_params(cfg, jax.random.PRNGKey(31))
    mesh = meshlib.make_mesh(meshlib.MeshPlan(pp=2), devices8[:2])
    eng = PipelinedEngine(cfg, params, mesh, num_microbatches=2, batch=1,
                          max_len=64)
    assert eng.ring_active  # the split ring layout engages for gemma2 pp=2
    eng.enable_spec(2, 3, params)
    runner = MeshSpecRunner(eng)
    solo = Engine(cfg, params, max_len=64,
                  sampling_cfg=SamplingConfig(temperature=0.0))
    prompt = [3, 17, 42, 9, 8, 1, 5, 12, 2]  # walks past window 8
    want = [solo.generate(prompt, max_new_tokens=12)]
    got = _drive(eng, runner, [prompt], 12)
    assert got == want
