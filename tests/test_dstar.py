"""D*-Lite tests: optimal chain extraction, incremental re-planning after
cost changes (the property the algorithm exists for — reference
dstar/test.py exercised exactly this), and the swarm adapter."""

import pytest

from inferd_tpu.control.dstar import (
    DStarLite,
    Graph,
    build_layered_graph,
    best_chain_over_swarm,
)
from inferd_tpu.control.path_finder import NoNodeForStage


def _grid_graph():
    g = Graph()
    # two parallel routes start->a->goal (cost 2) and start->b->goal (cost 5)
    g.add_edge("start", "a", 1.0)
    g.add_edge("a", "goal", 1.0)
    g.add_edge("start", "b", 2.0)
    g.add_edge("b", "goal", 3.0)
    return g


def test_shortest_path_basic():
    g = _grid_graph()
    d = DStarLite(g, "start", "goal")
    d.compute()
    assert d.path() == ["start", "a", "goal"]


def test_incremental_replan_after_cost_change():
    g = _grid_graph()
    d = DStarLite(g, "start", "goal")
    d.compute()
    assert d.path() == ["start", "a", "goal"]
    # route via a becomes expensive -> replan must switch to b
    d.update_edge("a", "goal", 100.0)
    d.compute()
    assert d.path() == ["start", "b", "goal"]
    # and back
    d.update_edge("a", "goal", 0.5)
    d.compute()
    assert d.path() == ["start", "a", "goal"]


def test_unreachable_goal():
    g = Graph()
    g.add_edge("start", "a", 1.0)  # no edge to goal
    g.add_edge("goal", "z", 1.0)
    d = DStarLite(g, "start", "goal")
    d.compute()
    assert d.path() == []


def test_advance_start():
    g = _grid_graph()
    d = DStarLite(g, "start", "goal")
    d.compute()
    d.advance_start("a")
    d.compute()
    assert d.path() == ["a", "goal"]


def _snapshot():
    return {
        0: {"n0": {"load": 0, "cap": 1, "host": "h", "port": 1}},
        1: {
            "n1a": {"load": 5, "cap": 1, "host": "h", "port": 2},
            "n1b": {"load": 0, "cap": 1, "host": "h", "port": 3},
        },
        2: {"n2": {"load": 1, "cap": 4, "host": "h", "port": 4}},
    }


def test_best_chain_over_swarm_picks_min_load():
    chain = best_chain_over_swarm(_snapshot(), 0, 3)
    assert [c[0] for c in chain] == ["n0", "n1b", "n2"]


def test_best_chain_raises_on_empty_stage():
    snap = _snapshot()
    snap[1] = {}
    with pytest.raises(NoNodeForStage):
        best_chain_over_swarm(snap, 0, 3)


def test_layered_graph_shape():
    g = build_layered_graph(_snapshot(), 0, 3)
    # start -> 1 node -> 2 nodes -> 1 node -> goal
    assert len(list(g.succ(("start",)))) == 1
    assert len(list(g.succ(("s", 0, "n0")))) == 2
