"""Combined chaos soak (VERDICT r03 item 7): every round-3 capability at
once, adversarially. A replicated-stage swarm serves sustained mixed load —
relay-path SwarmClients, a D*-Lite RoutedChainClient, streamed server-side
generations, prefix forks — while a chaos loop gracefully kills and
restarts stage-0 replicas and the balancer keeps migrating. The soak's
invariants are the whole system's contract:

  * ZERO parity violations: every completed generation is token-exact with
    the single-process engine (greedy determinism end to end, through
    relays, rescues, handoffs, and forks);
  * bounded restarts: session restarts happen only when a death beats the
    handoff (the retry loop reports each via on_token(None)); the budget is
    proportional to the number of kills, never to the number of requests;
  * chaos actually fired, and the swarm still completed a healthy volume.

This is the asserted, adversarial descendant of the reference's eyeball
rebalance sim (/root/reference/test_rebalance.py — CSV plotting, no
assertions)."""

import asyncio
import time

import jax
import pytest

from inferd_tpu.client.routed_client import RoutedChainClient
from inferd_tpu.client.swarm_client import SwarmClient
from inferd_tpu.config import TINY, SamplingConfig
from inferd_tpu.control.dht import SwarmDHT
from inferd_tpu.core.generate import Engine
from inferd_tpu.models import qwen3
from inferd_tpu.parallel.stages import Manifest, split_and_save
from inferd_tpu.runtime.node import Node, NodeInfo

BASE = 19300
GREEDY = SamplingConfig(temperature=0.0)
PROMPTS = [
    [3, 7, 11, 19, 5],
    [2, 9, 4, 31],
    [13, 1, 8, 40, 6, 22],
    [5, 5, 27],
]
NEW_TOKENS = 5


@pytest.fixture(scope="module")
def soak_parts(tmp_path_factory):
    params = qwen3.init_params(TINY, jax.random.PRNGKey(0))
    parts = tmp_path_factory.mktemp("chaos_soak_parts")
    split_and_save(params, TINY, Manifest.even_split("tiny", 2), str(parts))
    return str(parts), params


def _mk_node(idx, stage, *, parts, rebalance_period_s=600.0):
    info = NodeInfo(
        name=f"s{idx}", host="127.0.0.1", port=BASE + idx,
        stage=stage, num_stages=2, capacity=4, model_name="tiny",
    )
    # gossip: longer TTL + period than the microtests — five nodes, five
    # load generators, and pytest share ONE core here, and a starved event
    # loop must not expire LIVE nodes' records mid-soak. The graceful soak
    # learns of kills via withdraw + handoff; the ungraceful soak relies
    # on TTL death, so ttl_s must stay comfortably under its 6 s crash
    # cadence + 2 s respawn gap — retune BOTH tests together.
    dht = SwarmDHT(
        info.node_id, BASE + 100 + idx,
        bootstrap=[("127.0.0.1", BASE + 100)] if idx else [],
        host="127.0.0.1", gossip_period_s=0.2, ttl_s=5.0,
    )
    return Node(
        info, TINY, parts, dht, backend="qwen3", max_len=64,
        rebalance_period_s=rebalance_period_s,
    )


async def _bring_up_swarm(parts):
    """Shared 5-node soak layout: 0/1/2 serve stage 0 (replicated — the
    chaos loops only ever target 0/1), 3/4 stage 1, node 0 is the gossip
    seed, a 2 s balancer keeps migration live. Returns (nodes dict,
    entry addr — node 2, never a chaos victim) after DHT convergence."""
    nodes = {
        i: _mk_node(i, 0 if i < 3 else 1, parts=parts,
                    rebalance_period_s=2.0)
        for i in range(5)
    }
    for n in nodes.values():
        await n.start()
    for _ in range(200):
        m = nodes[2].dht.get_all(2)
        if m[0] and m[1]:
            break
        await asyncio.sleep(0.05)
    else:
        raise TimeoutError("swarm never converged")
    return nodes, ("127.0.0.1", BASE + 2)


@pytest.mark.asyncio
@pytest.mark.slow
async def test_chaos_soak_mixed_load(soak_parts):
    parts, params = soak_parts
    engine = Engine(TINY, params, max_len=64, sampling_cfg=GREEDY)
    expected = {
        tuple(p): engine.generate(p, max_new_tokens=NEW_TOKENS) for p in PROMPTS
    }
    nodes, entry = await _bring_up_swarm(parts)

    stop = time.monotonic() + 45.0  # soak window (CPU-sized)
    failures: list = []
    restarts = [0]
    kills = [0]
    done_counts = {"relay": 0, "routed": 0, "stream": 0, "fork": 0}

    def check(kind, prompt, got):
        want = expected[tuple(prompt)]
        if [int(t) for t in got] != want:
            failures.append((kind, prompt, got, want))

    def note_restart(t):
        if t is None:
            restarts[0] += 1

    async def relay_load(i):
        async with SwarmClient([entry], sampling=GREEDY, timeout_s=60.0) as c:
            k = 0
            while time.monotonic() < stop:
                p = PROMPTS[(i + k) % len(PROMPTS)]
                k += 1
                try:
                    got = await c.generate_ids(
                        p, max_new_tokens=NEW_TOKENS, on_token=note_restart
                    )
                except Exception as e:
                    failures.append(("relay-error", p, repr(e), None))
                    await asyncio.sleep(0.3)
                    continue
                check("relay", p, got)
                done_counts["relay"] += 1

    async def routed_load():
        obs = SwarmDHT(
            "soak-observer", BASE + 99,
            bootstrap=[("127.0.0.1", BASE + 100)],
            host="127.0.0.1", gossip_period_s=0.2, ttl_s=5.0,
        )
        await obs.start()
        try:
            async with RoutedChainClient(obs, 2, sampling=GREEDY) as c:
                k = 0
                while time.monotonic() < stop:
                    p = PROMPTS[k % len(PROMPTS)]
                    k += 1
                    try:
                        got = await c.generate_ids(
                            p, max_new_tokens=NEW_TOKENS, on_token=note_restart
                        )
                    except Exception as e:
                        failures.append(("routed-error", p, repr(e), None))
                        await asyncio.sleep(0.3)
                        continue
                    check("routed", p, got)
                    done_counts["routed"] += 1
        finally:
            await obs.stop()

    async def stream_load():
        async with SwarmClient([entry], sampling=GREEDY, timeout_s=60.0) as c:
            k = 0
            while time.monotonic() < stop:
                p = PROMPTS[k % len(PROMPTS)]
                k += 1
                streamed: list = []
                try:
                    got = await c.generate_server_side_stream(
                        p, streamed.append, max_new_tokens=NEW_TOKENS
                    )
                except Exception as e:
                    failures.append(("stream-error", p, repr(e), None))
                    await asyncio.sleep(0.5)
                    continue
                check("stream", p, got)
                # a None marks a mid-stream session restart: the stream
                # re-emits from the start after it, so only the segment
                # after the LAST restart must equal the final ids
                seg = streamed
                while None in seg:
                    seg = seg[seg.index(None) + 1:]
                    restarts[0] += 1
                if [int(t) for t in seg] != [int(t) for t in got]:
                    failures.append(("stream-increments", p, streamed, got))
                done_counts["stream"] += 1

    async def fork_load():
        # pinned shared prefix: generations fork the node-held prefix KV
        prefix = PROMPTS[0][:3]
        async with SwarmClient([entry], sampling=GREEDY, timeout_s=60.0) as c:
            while time.monotonic() < stop:
                try:
                    await c.pin_prefix(prefix)
                    got = await c.generate_ids(
                        PROMPTS[0], max_new_tokens=NEW_TOKENS,
                        on_token=note_restart,
                    )
                except Exception as e:
                    failures.append(("fork-error", PROMPTS[0], repr(e), None))
                    await asyncio.sleep(0.5)
                    continue
                check("fork", PROMPTS[0], got)
                done_counts["fork"] += 1
                await asyncio.sleep(0.2)

    async def chaos_loop():
        """Gracefully kill a stage-0 replica (shutdown handoff fires), then
        bring a fresh node up on the same slot; repeat while the soak
        runs."""
        while time.monotonic() < stop:
            await asyncio.sleep(8.0)
            if time.monotonic() >= stop:
                return
            victim_idx = kills[0] % 2  # alternate nodes 0 and 1 — never 2
            kills[0] += 1
            await nodes[victim_idx].stop()
            await asyncio.sleep(2.0)
            if time.monotonic() >= stop:
                return
            fresh = _mk_node(victim_idx, 0, parts=parts,
                             rebalance_period_s=2.0)
            await fresh.start()
            nodes[victim_idx] = fresh

    try:
        await asyncio.gather(
            relay_load(0), relay_load(1), routed_load(), stream_load(),
            fork_load(), chaos_loop(),
        )
    finally:
        for n in nodes.values():
            try:
                await n.stop()
            except Exception:
                pass

    total = sum(done_counts.values())
    # the soak must have actually soaked. The floor is deliberately modest:
    # five load generators + five nodes timeshare ONE CPU core here, and
    # the throughput varies ~2x with scheduler weather — the floor guards
    # against a wedged swarm (zero/near-zero completions), not a slow one;
    # parity and boundedness below are the real invariants.
    assert total >= 10, (done_counts, failures[:5])
    assert kills[0] >= 2, kills  # chaos actually fired
    # THE invariant: zero parity violations — whatever completed is exact
    parity = [f for f in failures if f[0] in ("relay", "routed", "stream",
                                              "fork", "stream-increments")]
    assert not parity, parity[:5]
    # transient errors only in proportion to kills (each kill can fail a
    # few in-flight requests across the five load generators)
    errors = [f for f in failures if f[0].endswith("-error")]
    assert len(errors) <= 5 * max(kills[0], 1), (len(errors), errors[:5])
    # bounded restarts: proportional to kills, never to request volume.
    # A single kill can interrupt every load generator's in-flight
    # generation at once, and the retry loop emits one marker per ATTEMPT
    # (a generation that retries into the still-dying window counts
    # several times) — so the per-kill budget is generators x a few
    # attempts. The volume guard is the real invariant: healthy
    # generations never restart, so restarts must stay a small fraction
    # of completions no matter how many complete.
    assert restarts[0] <= 10 * kills[0] + 4, (restarts[0], kills[0], total)
    assert restarts[0] <= max(10, total // 4), (restarts[0], total)


@pytest.mark.asyncio
@pytest.mark.slow
async def test_chaos_soak_ungraceful_crashes(soak_parts):
    """The harsher flavor: replicas die via crash() — no DHT withdraw, no
    session handoff, the swarm only learns via record TTL — and fresh
    nodes take their place. Completed generations must STILL be
    token-exact (TTL death + re-pick + the retry loop's session restarts
    absorb everything). An exploratory 5-minute run of this shape
    completed 9,785 generations across 37 crashes with zero errors and
    zero parity violations; this is its CI-sized regression net."""
    parts, params = soak_parts
    engine = Engine(TINY, params, max_len=64, sampling_cfg=GREEDY)
    expected = {
        tuple(p): engine.generate(p, max_new_tokens=NEW_TOKENS) for p in PROMPTS
    }
    # a crashed seed's replacement re-binds its port, so later restarts
    # can still bootstrap
    nodes, entry = await _bring_up_swarm(parts)

    stop = time.monotonic() + 30.0
    stats = {"done": 0, "err": 0, "crashes": 0}
    parity: list = []

    async def load(i):
        async with SwarmClient([entry], sampling=GREEDY, timeout_s=60.0) as c:
            k = 0
            while time.monotonic() < stop:
                p = PROMPTS[(i + k) % len(PROMPTS)]
                k += 1
                try:
                    got = await c.generate_ids(p, max_new_tokens=NEW_TOKENS)
                except Exception:
                    stats["err"] += 1
                    await asyncio.sleep(0.3)
                    continue
                if [int(t) for t in got] != expected[tuple(p)]:
                    parity.append((p, got))
                else:
                    stats["done"] += 1

    async def chaos():
        n = 0
        while time.monotonic() < stop:
            await asyncio.sleep(6.0)
            if time.monotonic() >= stop:
                return
            v = n % 2
            n += 1
            stats["crashes"] += 1
            await nodes[v].crash()  # UNGRACEFUL
            await asyncio.sleep(2.0)
            if time.monotonic() >= stop:
                return
            fresh = _mk_node(v, 0, parts=parts, rebalance_period_s=2.0)
            await fresh.start()
            nodes[v] = fresh

    try:
        await asyncio.gather(load(0), load(1), chaos())
    finally:
        for n in nodes.values():
            try:
                await n.stop()
            except Exception:
                pass

    assert not parity, parity[:5]
    assert stats["crashes"] >= 2, stats
    assert stats["done"] >= 10, stats
    # errors are allowed (a crash can eat an in-flight request faster than
    # the client retries) but must stay proportional to crashes
    assert stats["err"] <= 5 * stats["crashes"] + 5, stats
