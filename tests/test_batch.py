"""Continuous batching (core.batch.BatchedEngine): per-lane token parity
with solo Engine runs (greedy and sampled PRNG-chain parity), ragged lane
fills, lane refill from the queue, and EOS/capacity handling."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from inferd_tpu.config import TINY, SamplingConfig
from inferd_tpu.core.batch import BatchedEngine
from inferd_tpu.core.generate import Engine
from inferd_tpu.models import qwen3


@pytest.fixture(scope="module")
def setup():
    params = qwen3.init_params(TINY, jax.random.PRNGKey(0))
    return TINY, params


PROMPTS = [
    [3, 7, 11],
    [2, 5, 13, 17, 19],
    [23, 29],
    [31, 37, 41, 43, 47, 53, 59],
    [61, 67, 71, 3],
]


@pytest.mark.parametrize("temperature", [0.0, 0.9], ids=["greedy", "sampled"])
def test_lanes_match_solo_engine(setup, temperature):
    """Every sequence from the batched engine must equal a solo Engine run
    with the same per-sequence seed — ragged prompts decode together but
    never numerically interact."""
    cfg, params = setup
    sc = SamplingConfig(temperature=temperature, top_k=8, top_p=0.9)
    eng = BatchedEngine(cfg, params, lanes=3, max_len=64, sampling_cfg=sc)
    got = eng.generate_all(PROMPTS, max_new_tokens=10, seed=5)

    solo = Engine(cfg, params, max_len=64, sampling_cfg=sc)
    for i, p in enumerate(PROMPTS):
        want = solo.generate(p, max_new_tokens=10, seed=5 + i)
        assert got[i] == want, f"lane for prompt {i} diverged"


def test_refill_more_prompts_than_lanes(setup):
    """Queue longer than lanes: freed lanes must refill until drained."""
    cfg, params = setup
    sc = SamplingConfig(temperature=0.0)
    eng = BatchedEngine(cfg, params, lanes=2, max_len=64, sampling_cfg=sc)
    got = eng.generate_all(PROMPTS, max_new_tokens=6, seed=0)
    assert len(got) == len(PROMPTS)
    assert all(len(g) == 6 for g in got)
    assert len(eng.free) == 2  # all lanes returned


def test_eos_frees_lane(setup):
    cfg, params = setup
    sc = SamplingConfig(temperature=0.0)
    solo = Engine(cfg, params, max_len=64, sampling_cfg=sc)
    ref = solo.generate(PROMPTS[0], max_new_tokens=12, seed=0)
    eos = ref[4]
    want = solo.generate(PROMPTS[0], max_new_tokens=12, eos_token_id=eos, seed=0)

    eng = BatchedEngine(cfg, params, lanes=2, max_len=64, sampling_cfg=sc)
    got = eng.generate_all([PROMPTS[0]], max_new_tokens=12, eos_token_id=eos, seed=0)
    assert got[0] == want
    assert len(eng.free) == 2


@pytest.mark.parametrize("temperature", [0.0, 0.9], ids=["greedy", "sampled"])
def test_chunked_decode_matches_per_step(setup, temperature):
    """chunk>1 fuses decode steps into one scan dispatch; tokens must be
    bit-identical to the per-step path (and hence to solo Engine runs) —
    including lanes that finish mid-chunk and per-lane PRNG chains that
    continue across chunk boundaries."""
    cfg, params = setup
    sc = SamplingConfig(temperature=temperature, top_k=8, top_p=0.9)
    eng = BatchedEngine(cfg, params, lanes=3, max_len=64, sampling_cfg=sc)
    got = eng.generate_all(PROMPTS, max_new_tokens=10, seed=5, chunk=4)
    assert len(eng.free) == 3

    solo = Engine(cfg, params, max_len=64, sampling_cfg=sc)
    for i, p in enumerate(PROMPTS):
        want = solo.generate(p, max_new_tokens=10, seed=5 + i)
        assert got[i] == want, f"chunked lane for prompt {i} diverged"


def test_chunked_eos_mid_chunk(setup):
    """A lane hitting EOS inside a fused chunk truncates there and frees."""
    cfg, params = setup
    sc = SamplingConfig(temperature=0.0)
    solo = Engine(cfg, params, max_len=64, sampling_cfg=sc)
    ref = solo.generate(PROMPTS[0], max_new_tokens=12, seed=0)
    eos = ref[4]
    want = solo.generate(PROMPTS[0], max_new_tokens=12, eos_token_id=eos, seed=0)

    eng = BatchedEngine(cfg, params, lanes=2, max_len=64, sampling_cfg=sc)
    got = eng.generate_all(
        PROMPTS, max_new_tokens=12, eos_token_id=eos, seed=0, chunk=8
    )
    assert got[0] == want
    assert len(eng.free) == 2
    # every other lane matches its solo run with the same EOS
    for i, p in enumerate(PROMPTS[1:], start=1):
        assert got[i] == solo.generate(p, max_new_tokens=12, eos_token_id=eos, seed=i)


def test_chunked_max_len_boundary(setup):
    """Chunks cap at KV headroom; lanes at the cache cap release exactly
    where the per-step path releases them."""
    cfg, params = setup
    sc = SamplingConfig(temperature=0.0)
    eng1 = BatchedEngine(cfg, params, lanes=2, max_len=16, sampling_cfg=sc)
    want = eng1.generate_all(PROMPTS, max_new_tokens=40, seed=0)
    eng2 = BatchedEngine(cfg, params, lanes=2, max_len=16, sampling_cfg=sc)
    got = eng2.generate_all(PROMPTS, max_new_tokens=40, seed=0, chunk=8)
    assert got == want
    assert len(eng2.free) == 2


def test_lanes_match_solo_engine_sliding_window():
    """Continuous batching on a sliding-window model (tiny-gptoss): lanes at
    RAGGED fill levels exercise the per-row [B] branch of the windowed KV
    read (_windowed_slice vmapped slices) — every lane must still equal its
    solo engine run past the window."""
    from inferd_tpu.config import TINY_GPT_OSS

    cfg = TINY_GPT_OSS
    params = qwen3.init_params(cfg, jax.random.PRNGKey(23))
    sc = SamplingConfig(temperature=0.0)
    eng = BatchedEngine(cfg, params, lanes=3, max_len=64, sampling_cfg=sc)
    got = eng.generate_all(PROMPTS, max_new_tokens=12, seed=7)  # past window 8

    solo = Engine(cfg, params, max_len=64, sampling_cfg=sc)
    for i, p in enumerate(PROMPTS):
        want = solo.generate(p, max_new_tokens=12, seed=7 + i)
        assert got[i] == want, f"lane for prompt {i} diverged"


def test_admit_capacity_guard(setup):
    cfg, params = setup
    eng = BatchedEngine(cfg, params, lanes=1, max_len=64)
    eng.admit([1, 2, 3])
    with pytest.raises(RuntimeError, match="free lanes"):
        eng.admit([4, 5])
    with pytest.raises(BufferError):
        BatchedEngine(cfg, params, lanes=1, max_len=8).admit(list(range(8)))
