"""D*-Lite chain routing WIRED into serving (the reference's signature gap:
its dstar/ module was never imported by routing — path_finder.py:22,36 TODO,
client.py:131-138 dead stub). Covered here:

  * SwarmChainPlanner unit behavior: incremental replans (update_edge +
    bounded compute, proven by expansion counts on a wide graph), node
    death as an INF cost update, rebuild only on genuinely new nodes,
    agent advance restricting replans to the remaining stages;
  * node-side wiring: a new session entering the swarm gets a planned
    whole-chain route that relays follow (route.planned / route.followed
    metrics), falling back to per-hop picks when planning fails;
  * client-side wiring (RoutedChainClient): a mid-first-pass load spike on
    the replica planned for a LATER stage replans the remaining hops
    incrementally and the pass lands on the better replica — token-exact
    vs the single-process engine; an empty stage raises NoNodeForStage.
"""

import asyncio

import numpy as np
import pytest

from inferd_tpu.client.routed_client import RoutedChainClient
from inferd_tpu.client.swarm_client import SwarmClient
from inferd_tpu.config import TINY, SamplingConfig
from inferd_tpu.control.dht import SwarmDHT
from inferd_tpu.control.dstar import START, SwarmChainPlanner, node_cost
from inferd_tpu.control.path_finder import NoNodeForStage
from inferd_tpu.core.generate import Engine
from inferd_tpu.models import qwen3
from inferd_tpu.parallel.stages import Manifest, split_and_save
from inferd_tpu.runtime.node import Node, NodeInfo

BASE = 19000  # distinct port block from test_prefix (18800)

GREEDY = SamplingConfig(temperature=0.0)


# ----------------------------------------------------------------- planner


def _snap(loads):
    """{stage: {node_id: value}} from {stage: {node_id: load}}."""
    return {
        s: {nid: {"load": load, "cap": 4} for nid, load in m.items()}
        for s, m in loads.items()
    }


def test_node_cost_svc_ms_term():
    base = node_cost({"load": 2, "cap": 4})
    assert base == 1.0 + 0.5
    # 100 ms of announced service time weighs like one extra hop
    assert node_cost({"load": 2, "cap": 4, "svc_ms": 100.0}) == pytest.approx(base + 1.0)
    # nodes that don't announce svc_ms stay comparable (no term)
    assert node_cost({"load": 0, "cap": 1}) == 1.0


def test_planner_initial_chain_and_stats():
    p = SwarmChainPlanner(
        _snap({0: {"a0": 0}, 1: {"b0": 0, "b1": 2}, 2: {"c0": 1, "c1": 0}}), 0, 3
    )
    assert [n for _, n, _ in p.chain()] == ["a0", "b0", "c1"]
    assert p.stats["builds"] == 1 and p.stats["expansions_build"] > 0


def test_planner_incremental_replan_cheaper_than_build():
    """On a wide graph, a single-node cost change replans with FAR fewer
    expansions than the initial solve — the incremental property that is
    D*-Lite's entire reason to exist over re-running Dijkstra."""
    stages, width = 6, 8
    loads = {s: {f"n{s}_{i}": (i % 3) for i in range(width)} for s in range(stages)}
    p = SwarmChainPlanner(_snap(loads), 0, stages)
    chain0 = [n for _, n, _ in p.chain()]
    build_exp = p.stats["expansions_build"]
    # spike the load on the planned stage-3 replica
    loads[3][chain0[3]] = 50
    assert p.refresh(_snap(loads))
    chain1 = [n for _, n, _ in p.chain()]
    assert chain1[3] != chain0[3]
    assert p.stats["builds"] == 1  # no rebuild: pure cost update
    assert p.stats["expansions_replan"] < build_exp / 2, p.stats


def test_planner_death_and_flap_are_cost_updates():
    loads = {0: {"a0": 0}, 1: {"b0": 0, "b1": 1}}
    p = SwarmChainPlanner(_snap(loads), 0, 2)
    assert [n for _, n, _ in p.chain()] == ["a0", "b0"]
    # b0 TTLs out -> INF edges -> survivor routes; no rebuild
    p.refresh(_snap({0: {"a0": 0}, 1: {"b1": 1}}))
    assert [n for _, n, _ in p.chain()] == ["a0", "b1"]
    assert p.stats["builds"] == 1
    # b0 flaps back -> cost update again, still no rebuild
    p.refresh(_snap(loads))
    assert [n for _, n, _ in p.chain()] == ["a0", "b0"]
    assert p.stats["builds"] == 1
    # a genuinely NEW node on a live stage is SPLICED in incrementally
    # (join = D*-Lite increment, not a rebuild) and is immediately
    # routable when it wins on cost
    loads[1]["b9"] = -5  # cheapest stage-1 replica by far
    p.refresh(_snap(loads))
    assert p.stats["builds"] == 1 and p.stats["node_adds"] == 1
    assert [n for _, n, _ in p.chain()] == ["a0", "b9"]


def test_planner_kill_node_is_incremental_and_empty_stage_rebuilds():
    """kill_node folds an observed peer death into the plan without a
    refresh (the runtime's peer.dead hook); a node resurrecting a stage
    that was EMPTY at build time is the one topology change that still
    rebuilds (the layered graph never reached GOAL through it)."""
    loads = {0: {"a0": 0}, 1: {"b0": 0, "b1": 1}, 2: {"c0": 0}}
    p = SwarmChainPlanner(_snap(loads), 0, 3)
    assert [n for _, n, _ in p.chain()] == ["a0", "b0", "c0"]
    build_exp = p.stats["expansions_build"]
    assert p.kill_node("b0") is True
    assert [n for _, n, _ in p.chain()] == ["a0", "b1", "c0"]
    assert p.stats["builds"] == 1 and p.stats["kills"] == 1
    assert p.stats["expansions_replan"] < max(2, build_exp)
    # killing something unknown (or already dead) is a no-op
    assert p.kill_node("b0") is False
    assert p.kill_node("zz") is False
    # empty-at-build stage: no chain; a join there rebuilds and routes
    p2 = SwarmChainPlanner(_snap({0: {"a0": 0}, 1: {}}), 0, 2)
    with pytest.raises(NoNodeForStage):
        p2.chain()
    p2.refresh(_snap({0: {"a0": 0}, 1: {"b0": 0}}))
    assert p2.stats["builds"] == 2
    assert [n for _, n, _ in p2.chain()] == ["a0", "b0"]


def test_node_cost_hop_p99_term():
    """The gossiped trailing-window relay p99 is a live edge-weight term:
    HOP_P99_NORM_MS milliseconds of tail latency weigh like one extra
    hop, and records without the key stay comparable (no term)."""
    from inferd_tpu.control.dstar import HOP_P99_NORM_MS

    base = node_cost({"load": 2, "cap": 4})
    assert node_cost(
        {"load": 2, "cap": 4, "hop_p99_ms": HOP_P99_NORM_MS}
    ) == pytest.approx(base + 1.0)
    # composes with (does not replace) the svc_ms EWMA term
    assert node_cost(
        {"load": 2, "cap": 4, "svc_ms": 100.0, "hop_p99_ms": 2 * HOP_P99_NORM_MS}
    ) == pytest.approx(base + 3.0)


def test_planner_advance_limits_replans_to_remaining_stages():
    loads = {0: {"a0": 0, "a1": 1}, 1: {"b0": 0, "b1": 1}, 2: {"c0": 0, "c1": 1}}
    p = SwarmChainPlanner(_snap(loads), 0, 3)
    p.advance(0, "a0")
    assert [s for s, _, _ in p.chain()] == [1, 2]
    # a committed-stage cost change is ignored entirely
    loads[0]["a0"] = 99
    assert not p.refresh(_snap(loads))
    # a remaining-stage spike replans
    loads[1]["b0"] = 99
    assert p.refresh(_snap(loads))
    assert [n for _, n, _ in p.chain()] == ["b1", "c0"]


def test_planner_empty_stage_raises():
    p = SwarmChainPlanner(_snap({0: {"a0": 0}, 1: {"b0": 0}}), 0, 2)
    p.refresh(_snap({0: {"a0": 0}, 1: {}}))
    with pytest.raises(NoNodeForStage):
        p.chain()
    with pytest.raises(NoNodeForStage):
        SwarmChainPlanner(_snap({0: {}, 1: {"b0": 0}}), 0, 2).chain()


# ------------------------------------------------------------- swarm e2e


@pytest.fixture(scope="module")
def tiny_params():
    import jax

    return qwen3.init_params(TINY, jax.random.PRNGKey(0))


@pytest.fixture(scope="module")
def tiny_parts(tmp_path_factory, tiny_params):
    parts = tmp_path_factory.mktemp("parts_router")
    split_and_save(tiny_params, TINY, Manifest.even_split("tiny", 2), str(parts))
    return str(parts)


def _mk_node(idx, stage, num_stages, *, parts, capacity=4):
    info = NodeInfo(
        name=f"r{idx}", host="127.0.0.1", port=BASE + idx,
        stage=stage, num_stages=num_stages, capacity=capacity,
        model_name="tiny",
    )
    dht = SwarmDHT(
        info.node_id, BASE + 100 + idx,
        bootstrap=[("127.0.0.1", BASE + 100)] if idx else [],
        host="127.0.0.1", gossip_period_s=0.05, ttl_s=1.5,
    )
    return Node(
        info, TINY, parts, dht, backend="qwen3", max_len=64,
        rebalance_period_s=600.0,
    )


async def _start_all(nodes):
    for n in nodes:
        await n.start()

    async def converged():
        for n in nodes:
            m = n.dht.get_all(n.info.num_stages)
            if any(not m[s] for s in range(n.info.num_stages)):
                return False
        return True

    for _ in range(100):
        if await converged():
            return
        await asyncio.sleep(0.05)
    raise TimeoutError("swarm did not converge")


PROMPT = [3, 7, 11, 19, 5]


@pytest.mark.asyncio
async def test_relay_follows_planned_route(tiny_params, tiny_parts):
    """A new session entering the swarm gets a D*-Lite whole-chain route;
    the relay follows it to the LOW-cost stage-1 replica (not round-robin,
    not accidental) and the tokens match the single-process engine."""
    # engine reference FIRST: its jit compile blocks the shared event loop
    # for seconds, which would stall every in-process gossip loop and TTL
    # out the records mid-test
    engine = Engine(TINY, tiny_params, max_len=64, sampling_cfg=GREEDY)
    want = engine.generate(PROMPT, max_new_tokens=6)
    nodes = [
        _mk_node(0, 0, 2, parts=tiny_parts),
        _mk_node(1, 1, 2, parts=tiny_parts),
        _mk_node(2, 1, 2, parts=tiny_parts),
    ]
    try:
        await _start_all(nodes)
        # skew the stage-1 replicas: make nodes[1] expensive so the planner
        # must choose nodes[2] (min-load would too — the point here is that
        # the route is PLANNED once and followed, metrics prove the path)
        nodes[1]._svc_ewma = 500.0
        nodes[1].announce()
        for _ in range(40):
            v = nodes[0].dht.get_stage(1).get(nodes[1].info.node_id, {})
            if v.get("svc_ms"):
                break
            await asyncio.sleep(0.05)
        async with SwarmClient(
            [("127.0.0.1", BASE)], sampling=GREEDY, prefill_chunk=4
        ) as c:
            got = await c.generate_ids(PROMPT, max_new_tokens=6)
        assert got == want
        m = nodes[0].metrics.snapshot()
        assert m["counters"].get("route.planned", 0) >= 1
        assert m["counters"].get("route.followed", 0) >= 1
        stats = nodes[0].path_finder.planner.stats
        assert stats["builds"] >= 1
        # the cheap replica served every relayed chunk; the expensive one
        # stayed idle — the planned route, not round-robin, carried traffic
        m1 = nodes[1].metrics.snapshot()["counters"]
        m2 = nodes[2].metrics.snapshot()["counters"]
        assert m2.get("forward.requests", 0) > 0
        assert m1.get("forward.requests", 0) == 0
    finally:
        for n in nodes:
            await n.stop()


@pytest.mark.asyncio
async def test_entry_plan_failure_falls_back_to_per_hop(tiny_parts):
    """With no stage-1 replica in view, planning fails (route.plan_failed)
    and the request degrades to the existing per-hop pick path (which
    surfaces 503 after its own retries) — never an unhandled error."""
    node = _mk_node(0, 0, 2, parts=tiny_parts)
    try:
        await node.start()
        assert node._plan_route(1) is None
        assert node.metrics.snapshot()["counters"].get("route.plan_failed") == 1
    finally:
        await node.stop()


# ------------------------------------------------------- routed client e2e


@pytest.mark.asyncio
async def test_routed_client_mid_pass_spike_replans(tiny_params, tiny_parts):
    """The verdict's e2e: while the first pass sits between stage 0 and
    stage 1, a load spike hits the replica the planner chose for stage 1;
    the client replans INCREMENTALLY (no rebuild, bounded expansions) and
    the pass lands on the other replica — token-exact vs the engine."""
    # engine reference FIRST (see test_relay_follows_planned_route: the jit
    # compile must not stall the in-process gossip loops mid-test)
    engine = Engine(TINY, tiny_params, max_len=64, sampling_cfg=GREEDY)
    want = engine.generate(PROMPT, max_new_tokens=5)
    nodes = [
        _mk_node(0, 0, 2, parts=tiny_parts),
        _mk_node(1, 1, 2, parts=tiny_parts),
        _mk_node(2, 1, 2, parts=tiny_parts),
    ]
    spiked_id = nodes[1].info.node_id
    try:
        await _start_all(nodes)
        # make nodes[1] the initial stage-1 choice (cheaper than nodes[2])
        nodes[2]._svc_ewma = 50.0
        nodes[2].announce()

        obs = SwarmDHT(
            "router-client", BASE + 99,
            bootstrap=[("127.0.0.1", BASE + 100)],
            host="127.0.0.1", gossip_period_s=0.05, ttl_s=1.5,
        )
        await obs.start()
        for _ in range(100):
            snap = obs.get_all(2)
            if all(snap[s] for s in range(2)) and (
                snap[1].get(nodes[2].info.node_id, {}).get("svc_ms")
            ):
                break
            await asyncio.sleep(0.05)
        else:
            raise TimeoutError("observer never converged")

        stats_seen = {}

        async def spike(session_id, completed_stage):
            if completed_stage != 0 or stats_seen.get("spiked"):
                return
            stats_seen["spiked"] = True
            # the planned stage-1 replica becomes very expensive while the
            # pass is in flight between stage 0 and stage 1
            nodes[1]._svc_ewma = 5000.0
            nodes[1].announce()
            for _ in range(100):
                v = obs.get_stage(1).get(spiked_id, {})
                if v.get("svc_ms", 0) > 1000:
                    return
                await asyncio.sleep(0.05)
            raise TimeoutError("spike never reached the observer view")

        async with RoutedChainClient(
            obs, 2, sampling=GREEDY, prefill_chunk=4
        ) as c:
            c.hop_hook = spike

            # capture planner stats before the client freezes the plan
            orig_step = c._step

            async def step_and_snap(session_id, tokens, start_pos):
                out = await orig_step(session_id, tokens, start_pos)
                st = c.planner_stats(session_id)
                if st is not None:
                    stats_seen["stats"] = st
                plan = c._plans.get(session_id)
                if plan is not None and plan.committed:
                    stats_seen["chain"] = [nid for nid, _ in plan.chain]
                return out

            c._step = step_and_snap
            got = await c.generate_ids(PROMPT, max_new_tokens=5)

        assert got == want
        assert stats_seen["spiked"]
        # the pass landed on the OTHER replica for stage 1
        assert stats_seen["chain"][1] == nodes[2].info.node_id
        st = stats_seen["stats"]
        assert st["builds"] == 1, st  # replans were incremental, no rebuild
        assert st["cost_updates"] >= 1, st
        assert st["expansions_replan"] > 0, st
        await obs.stop()
    finally:
        for n in nodes:
            await n.stop()


@pytest.mark.asyncio
async def test_routed_client_empty_stage_raises(tiny_parts):
    """Planner's stage view empty -> retryable 503 (code no_chain): the
    generation gets its session retries (a gossip blip heals), and a
    PERSISTENTLY empty stage surfaces the error cleanly after them."""
    from inferd_tpu.client.base import ServerError

    node = _mk_node(0, 0, 2, parts=tiny_parts)  # no stage-1 node at all
    try:
        await node.start()
        obs = SwarmDHT(
            "router-client-2", BASE + 98,
            bootstrap=[("127.0.0.1", BASE + 100)],
            host="127.0.0.1", gossip_period_s=0.05, ttl_s=1.5,
        )
        await obs.start()
        for _ in range(100):
            if obs.get_all(2)[0]:
                break
            await asyncio.sleep(0.05)
        async with RoutedChainClient(obs, 2, sampling=GREEDY) as c:
            with pytest.raises(ServerError) as ei:
                await c.generate_ids(
                    PROMPT, max_new_tokens=3,
                    session_retries=1, retry_delay_s=0.05,
                )
            assert ei.value.code == "no_chain" and ei.value.retryable
        await obs.stop()
    finally:
        await node.stop()


@pytest.mark.asyncio
async def test_routed_client_mid_session_failover_via_gossip(
    tiny_params, tiny_parts
):
    """VERDICT r03 item 5: a COMMITTED-chain replica dies mid-decode. The
    routed client consults the gossip session-location adverts it already
    merges (the `sess` hashes — the same records the swarm relay's rescue
    uses), repairs the chain to the replica holding the handed-off KV, and
    completes token-exact with ZERO session restarts (we drive _step
    directly, so a restart would be impossible — any unrescued failure
    raises instead)."""
    from inferd_tpu.control.dht import sess_hash

    engine = Engine(TINY, tiny_params, max_len=64, sampling_cfg=GREEDY)
    want = engine.generate(PROMPT, max_new_tokens=6)
    nodes = [
        _mk_node(0, 0, 2, parts=tiny_parts),
        _mk_node(1, 0, 2, parts=tiny_parts),
        _mk_node(2, 1, 2, parts=tiny_parts),
    ]
    obs = None
    try:
        await _start_all(nodes)
        obs = SwarmDHT(
            "router-failover-client", BASE + 98,
            bootstrap=[("127.0.0.1", BASE + 100)],
            host="127.0.0.1", gossip_period_s=0.05, ttl_s=1.5,
        )
        await obs.start()
        for _ in range(100):
            snap = obs.get_all(2)
            if all(snap[s] for s in range(2)):
                break
            await asyncio.sleep(0.05)
        else:
            raise TimeoutError("observer never converged")

        async with RoutedChainClient(obs, 2, sampling=GREEDY) as c:
            sid = "routed-failover"
            logits = await c._step(sid, PROMPT, 0)
            toks = [int(np.argmax(logits))]
            pos = len(PROMPT)
            for _ in range(2):
                logits = await c._step(sid, [toks[-1]], pos)
                pos += 1
                toks.append(int(np.argmax(logits)))
            plan = c._plans[sid]
            assert plan.committed
            victim_id = plan.chain[0][0]
            victim = next(n for n in nodes[:2] if n.info.node_id == victim_id)
            survivor = next(n for n in nodes[:2] if n is not victim)
            # graceful death: drains + hands the session KV to the survivor
            await victim.stop()
            assert sid in survivor.executor.sessions
            # the survivor's session advert must reach the CLIENT's view
            for _ in range(100):
                v = obs.get_stage(0).get(survivor.info.node_id, {})
                if sess_hash(sid) in (v.get("sess") or ()):
                    break
                await asyncio.sleep(0.05)
            else:
                raise TimeoutError("session advert never reached the client")

            for _ in range(3):  # hop to the dead node -> rescued, repaired
                logits = await c._step(sid, [toks[-1]], pos)
                pos += 1
                toks.append(int(np.argmax(logits)))
            assert c._plans[sid].chain[0][0] == survivor.info.node_id
            await c._end_session(sid)
        assert toks == want
        nodes.remove(victim)
        if obs is not None:
            await obs.stop()
            obs = None
    finally:
        for n in nodes:
            await n.stop()
        if obs is not None:
            await obs.stop()
