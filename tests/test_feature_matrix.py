"""Feature-composition matrix: every engine must produce its reference
output under every storage/compute variant — quantized weights (dequant /
w8a8 / Pallas kernel) x compressed KV (fp8) x engines (plain, batched,
speculative). Features that each pass alone but corrupt state when
composed are a classic integration failure mode; this pins the grid."""

import dataclasses

import jax
import pytest

from inferd_tpu.config import TINY, SamplingConfig
from inferd_tpu.core.batch import BatchedEngine
from inferd_tpu.core.generate import Engine
from inferd_tpu.core.speculative import SpeculativeEngine
from inferd_tpu.models import qwen3
from inferd_tpu.ops import quant
from conftest import requires_native_shard_map

VARIANTS = [
    ("bf16", "none", "model"),
    ("int8", "int8", "model"),
    ("w8a8", "w8a8", "model"),
    ("kernel", "int8-kernel", "model"),
    ("fp8kv", "none", "float8_e4m3fn"),
    ("int8+fp8kv", "int8", "float8_e4m3fn"),
]

GREEDY = SamplingConfig(temperature=0.0)
PROMPTS = [[3, 7, 11], [2, 5, 13, 17]]


@pytest.fixture(scope="module")
def base_params():
    return qwen3.init_params(TINY, jax.random.PRNGKey(0))


def _setup(base_params, quant_flag, kv_dtype):
    cfg = TINY if kv_dtype == "model" else dataclasses.replace(TINY, kv_dtype=kv_dtype)
    params = quant.apply_quant_mode(
        quant_flag, base_params, tie_word_embeddings=cfg.tie_word_embeddings
    )
    return cfg, params


@pytest.mark.parametrize("name,quant_flag,kv_dtype", VARIANTS,
                         ids=[v[0] for v in VARIANTS])
def test_engines_agree_under_variant(base_params, name, quant_flag, kv_dtype):
    cfg, params = _setup(base_params, quant_flag, kv_dtype)
    try:
        solo = Engine(cfg, params, max_len=64, sampling_cfg=GREEDY)
        want = [solo.generate(p, max_new_tokens=6, seed=0) for p in PROMPTS]

        batched = BatchedEngine(cfg, params, lanes=2, max_len=64, sampling_cfg=GREEDY)
        got_b = batched.generate_all(PROMPTS, max_new_tokens=6, seed=0)
        assert got_b == want, f"batched diverged under {name}"

        spec = SpeculativeEngine(cfg, params, cfg, params, k=3, max_len=64)
        got_s, _ = spec.generate(PROMPTS[0], max_new_tokens=6)
        assert got_s == want[0], f"speculative diverged under {name}"
    finally:
        quant.QDOT_MODE = "dequant"  # module default for other tests


@pytest.mark.parametrize("name,quant_flag,kv_dtype", [
    ("int8", "int8", "model"),
    ("fp8kv", "none", "float8_e4m3fn"),
    ("int8+fp8kv", "int8", "float8_e4m3fn"),
], ids=["int8", "fp8kv", "int8+fp8kv"])
@requires_native_shard_map
def test_pipelined_engine_agrees_under_variant(base_params, name, quant_flag, kv_dtype):
    """The in-mesh pp pipeline under the same variants: sharded QuantWeight
    placement + compressed sharded caches must not perturb tokens."""
    from inferd_tpu.parallel import mesh as meshlib
    from inferd_tpu.parallel.infer import PipelinedEngine

    devs = jax.devices()
    if len(devs) < 2:
        pytest.skip("needs 2 devices")
    cfg, params = _setup(base_params, quant_flag, kv_dtype)
    try:
        solo = Engine(cfg, params, max_len=64, sampling_cfg=GREEDY)
        want = [solo.generate(p, max_new_tokens=6, seed=0) for p in PROMPTS]

        mesh = meshlib.make_mesh(meshlib.MeshPlan(pp=2), devs[:2])
        eng = PipelinedEngine(
            cfg, params, mesh, num_microbatches=2, batch=1, max_len=64,
            sampling_cfg=GREEDY,
        )
        got = eng.generate(PROMPTS, max_new_tokens=6)
        assert got == want, f"pipelined diverged under {name}"
    finally:
        quant.QDOT_MODE = "dequant"


@requires_native_shard_map
def test_pipelined_pp_tp_maximal_composition(base_params):
    """The maximal serving stack in one program: pp x tp mesh x int8
    weights x fp8 KV. Sharded QuantWeight leaves (q + scale specs), a
    tp-sharded compressed cache, Megatron psums, and ppermute hops must
    compose to the exact tokens of the solo engine under the same
    quant/kv variant."""
    from inferd_tpu.parallel import mesh as meshlib
    from inferd_tpu.parallel.infer import PipelinedEngine

    devs = jax.devices()
    if len(devs) < 4:
        pytest.skip("needs 4 devices")
    cfg, params = _setup(base_params, "int8", "float8_e4m3fn")
    try:
        solo = Engine(cfg, params, max_len=64, sampling_cfg=GREEDY)
        want = [solo.generate(p, max_new_tokens=6, seed=0) for p in PROMPTS]

        mesh = meshlib.make_mesh(meshlib.MeshPlan(pp=2, tp=2), devs[:4])
        eng = PipelinedEngine(
            cfg, params, mesh, num_microbatches=2, batch=1, max_len=64,
            sampling_cfg=GREEDY,
        )
        got = eng.generate(PROMPTS, max_new_tokens=6)
        assert got == want, "pp x tp x int8 x fp8kv diverged"
    finally:
        quant.QDOT_MODE = "dequant"


@pytest.mark.parametrize("name,quant_flag,kv_dtype", VARIANTS,
                         ids=[v[0] for v in VARIANTS])
def test_lane_spec_agrees_under_variant(base_params, name, quant_flag, kv_dtype):
    """Round 5: the LANE-batched speculative engine joins the grid — its
    greedy stream must equal the solo engine under every weight/KV storage
    variant (the verify chunk writes through the same compressed cache the
    regular steps use)."""
    from inferd_tpu.core.spec_batch import (
        LaneSpecRunner, generate_lanes, make_draft_cache,
    )

    cfg, params = _setup(base_params, quant_flag, kv_dtype)
    try:
        solo = Engine(cfg, params, max_len=64, sampling_cfg=GREEDY)
        want = [solo.generate(p, max_new_tokens=6, seed=0) for p in PROMPTS]

        engine = BatchedEngine(cfg, params, lanes=2, max_len=64,
                               sampling_cfg=GREEDY)
        runner = LaneSpecRunner(cfg, cfg, k=3)
        dcache = make_draft_cache(cfg, 2, 64)
        got, _, _ = generate_lanes(
            engine, runner, params, params, dcache, PROMPTS,
            max_new_tokens=6,
        )
        assert got == want, f"lane spec diverged under {name}"
    finally:
        quant.QDOT_MODE = "dequant"
