# jaxlint: file-disable=J003 -- test code: loops here sync per-iteration to ASSERT on values; they are verification loops, not serving hot paths
"""Generation-path tests: sampling filters, cache growth, engine decode
consistency, scan-path equivalence, text round-trip."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from inferd_tpu.config import TINY, SamplingConfig
from inferd_tpu.core import sampling as samplib
from inferd_tpu.core.cache import KVCache, grow
from inferd_tpu.core.generate import Engine, bucket_len, generate_text
from inferd_tpu.core.tokenizer import ByteTokenizer, Tokenizer
from inferd_tpu.models import qwen3


def test_top_k_filter():
    logits = jnp.array([[1.0, 5.0, 3.0, 2.0]])
    out = samplib.top_k_filter(logits, 2)
    assert out[0, 1] == 5.0 and out[0, 2] == 3.0
    assert out[0, 0] < -1e29 and out[0, 3] < -1e29


def test_top_p_filter_keeps_nucleus():
    # probs ~ [0.62, 0.23, 0.08, 0.03, ...]: p=0.7 keeps exactly two tokens
    logits = jnp.log(jnp.array([[0.62, 0.23, 0.08, 0.05, 0.02]]))
    out = samplib.top_p_filter(logits, 0.7)
    kept = np.asarray(out[0] > -1e29)
    assert kept.tolist() == [True, True, False, False, False]


def test_top_p_always_keeps_one():
    logits = jnp.log(jnp.array([[0.99, 0.01]]))
    out = samplib.top_p_filter(logits, 0.001)
    assert np.asarray(out[0] > -1e29).tolist() == [True, False]


def test_min_p_filter_matches_hf():
    """min_p_filter's kept-token set == HF MinPLogitsWarper on random rows,
    both on full rows and composed after top-k (ratio invariance)."""
    torch = pytest.importorskip("torch")
    from transformers import MinPLogitsWarper

    from inferd_tpu.core import sampling as samplib

    rng = np.random.RandomState(0)
    logits = rng.normal(0, 3, size=(4, 64)).astype(np.float32)
    for min_p in (0.05, 0.2, 0.5):
        warper = MinPLogitsWarper(min_p=min_p)
        want = warper(torch.zeros(4, 1, dtype=torch.long), torch.from_numpy(logits))
        want_kept = np.isfinite(want.numpy())
        got = samplib.min_p_filter(jnp.asarray(logits), min_p)
        got_kept = np.asarray(got) > -1e29
        np.testing.assert_array_equal(got_kept, want_kept, err_msg=f"min_p={min_p}")

    # composed after top-k on the candidate row == full-row semantics
    full = samplib.warped_logits(jnp.asarray(logits), 1.0, 8, 1.0, 0.2)
    kept_full = np.asarray(full) > -1e29
    fast = samplib.warped_logits(jnp.asarray(logits), 1.0, 0, 1.0, 0.2)
    kept_topk_only = np.asarray(samplib.top_k_filter(jnp.asarray(logits), 8)) > -1e29
    np.testing.assert_array_equal(
        kept_full, kept_topk_only & (np.asarray(fast) > -1e29)
    )


def test_greedy_sampling():
    logits = jnp.array([[0.0, 10.0, 2.0]])
    tok = samplib.sample(logits, jax.random.PRNGKey(0), temperature=0.0)
    assert int(tok[0]) == 1


def test_sample_respects_top_k1():
    logits = jax.random.normal(jax.random.PRNGKey(0), (4, 64))
    tok = samplib.sample(logits, jax.random.PRNGKey(1), temperature=1.0, top_k=1, top_p=1.0)
    np.testing.assert_array_equal(np.asarray(tok), np.argmax(np.asarray(logits), -1))


def test_cache_overflow_guard():
    cache = KVCache.create(TINY, TINY.num_layers, 1, 8)
    cache.ensure_room(8)
    with pytest.raises(BufferError):
        cache.ensure_room(9)


def test_cache_grow_preserves():
    cache = KVCache.create(TINY, TINY.num_layers, 1, 8)
    k = cache.k.at[:, :, :3].set(1.0)
    cache = KVCache(k=k, v=cache.v, length=jnp.int32(3))
    g = grow(cache, 16)
    assert g.max_len == 16 and int(g.length) == 3
    np.testing.assert_array_equal(np.asarray(g.k[:, :, :3]), np.asarray(cache.k[:, :, :3]))


def test_bucket_len():
    assert bucket_len(1) == 16
    assert bucket_len(16) == 16
    assert bucket_len(17) == 32
    assert bucket_len(100) == 128


@pytest.fixture(scope="module")
def engine():
    params = qwen3.init_params(TINY, jax.random.PRNGKey(0))
    return Engine(TINY, params, max_len=128, sampling_cfg=SamplingConfig(temperature=0.0))


def test_engine_greedy_matches_uncached(engine):
    prompt = [5, 9, 13]
    out = engine.generate(prompt, max_new_tokens=6)
    # re-derive greedily with full recompute
    seq = list(prompt)
    ref = []
    for _ in range(6):
        logits, _, _ = qwen3.forward(engine.params, TINY, jnp.asarray([seq]))
        t = int(jnp.argmax(logits[0, -1]))
        ref.append(t)
        seq.append(t)
    assert out == ref


def test_engine_eos_stop(engine):
    prompt = [5, 9, 13]
    full = engine.generate(prompt, max_new_tokens=6)
    # eos == the first sampled token -> stop immediately after it
    stopped = engine.generate(prompt, max_new_tokens=6, eos_token_id=full[0])
    assert stopped == full[:1]
    # eos never sampled -> full-length generation
    unused_eos = (max(full) + 1) % TINY.vocab_size
    assert unused_eos not in full
    assert engine.generate(prompt, max_new_tokens=6, eos_token_id=unused_eos) == full


@pytest.mark.parametrize("temperature", [0.0, 0.8], ids=["greedy", "sampled"])
def test_scan_matches_host_loop(engine, temperature):
    eng = Engine(
        TINY, engine.params, max_len=128,
        sampling_cfg=SamplingConfig(temperature=temperature),
    )
    prompt = [5, 9, 13]
    host = eng.generate(prompt, max_new_tokens=6, seed=7)
    b = bucket_len(len(prompt))
    tokens = jnp.asarray([prompt + [0] * (b - len(prompt))], dtype=jnp.int32)
    scan = eng.generate_scan(tokens, len(prompt), steps=6, seed=7)
    assert np.asarray(scan)[0].tolist() == host


def test_empty_prompt_rejected(engine):
    with pytest.raises(ValueError):
        engine.generate([], 4)


def test_generate_text_roundtrip(engine):
    tok = Tokenizer()  # falls back to ByteTokenizer offline
    text = generate_text(engine, tok, "hi", max_new_tokens=5)
    assert isinstance(text, str)


def test_byte_tokenizer_roundtrip():
    bt = ByteTokenizer()
    ids = bt.encode("hello, мир")
    assert bt.decode(ids) == "hello, мир"
    chat = bt.apply_chat_template([{"role": "user", "content": "x"}])
    assert chat[0] == bt.bos_token_id


def test_generate_chunked_matches_per_step():
    """generate(chunk=N) fuses N decode steps per dispatch (the solo
    analogue of BatchedEngine's fused decode) and stays bit-identical to
    the per-step loop — greedy, sampled, EOS-mid-chunk, and with sinks."""
    import numpy as np

    params = qwen3.init_params(TINY, jax.random.PRNGKey(0))
    prompt = [3, 7, 11, 19, 5]
    for sc in (SamplingConfig(temperature=0.0), SamplingConfig(temperature=0.9, top_k=10)):
        eng = Engine(TINY, params, max_len=64, sampling_cfg=sc)
        a = eng.generate(prompt, max_new_tokens=17, seed=4)
        for ch in (2, 8):
            assert eng.generate(prompt, max_new_tokens=17, seed=4, chunk=ch) == a
    g = Engine(TINY, params, max_len=64, sampling_cfg=SamplingConfig(temperature=0.0))
    full = g.generate(prompt, max_new_tokens=17)
    eos = full[5]
    assert g.generate(prompt, max_new_tokens=17, eos_token_id=eos, chunk=8) == \
        g.generate(prompt, max_new_tokens=17, eos_token_id=eos)
    lps, lpc = [], []
    x = g.generate(prompt, max_new_tokens=10, logprob_sink=lps)
    y = g.generate(prompt, max_new_tokens=10, chunk=4, logprob_sink=lpc)
    assert x == y and np.allclose(lps, lpc, atol=1e-5)
