# jaxlint: file-disable=J003 -- test code: loops here sync per-iteration to ASSERT on values; they are verification loops, not serving hot paths
"""Mesh-backed node serving path (north-star BASELINE config 2): a node
whose executor pipelines the WHOLE model over an in-mesh pp axis, behind
the stock /forward surface — SwarmClient generation must match the
single-process engine token for token, sessions must map to cache slots
with eviction, and the protocol guards must hold."""

import asyncio

import jax
import numpy as np
import pytest

from inferd_tpu.client.swarm_client import SwarmClient
from inferd_tpu.config import TINY, SamplingConfig
from inferd_tpu.control.dht import SwarmDHT
from inferd_tpu.core.generate import Engine
from inferd_tpu.models import qwen3
from inferd_tpu.parallel import mesh as meshlib
from inferd_tpu.parallel.mesh import MeshPlan
from inferd_tpu.parallel.stages import Manifest, split_and_save
from inferd_tpu.runtime.node import Node, NodeInfo


from conftest import requires_native_shard_map

pytestmark = requires_native_shard_map

BASE = 18600
GREEDY = SamplingConfig(temperature=0.0)


@pytest.fixture(scope="module")
def mesh_parts(tmp_path_factory):
    """1-stage checkpoint: mesh mode hosts the whole model."""
    parts = tmp_path_factory.mktemp("mesh_parts")
    params = qwen3.init_params(TINY, jax.random.PRNGKey(0))
    split_and_save(params, TINY, Manifest.even_split("tiny", 1), str(parts))
    return str(parts), params


def _mk_mesh_node(idx, parts, pp=2, slots=3, max_len=64, tp=1):
    info = NodeInfo(
        name=f"m{idx}", host="127.0.0.1", port=BASE + idx,
        stage=0, num_stages=1, model_name="tiny",
    )
    dht = SwarmDHT(
        info.node_id, BASE + 100 + idx, bootstrap=[],
        host="127.0.0.1", gossip_period_s=0.05, ttl_s=1.5,
    )
    return Node(
        info, TINY, parts, dht, backend="qwen3", max_len=max_len,
        rebalance_period_s=600.0, mesh_plan=MeshPlan(pp=pp, tp=tp),
        mesh_slots=slots,
    )


@pytest.mark.asyncio
async def test_mesh_node_generation_matches_engine(mesh_parts, devices8):
    """SwarmClient -> mesh-backed node (pp=2 over the virtual CPU mesh)
    == single-process Engine, token for token (greedy)."""
    parts, params = mesh_parts
    node = _mk_mesh_node(0, parts)
    await node.start()
    try:
        engine = Engine(TINY, params, max_len=64, sampling_cfg=GREEDY)
        prompt = [3, 7, 11, 19, 23]
        expected = engine.generate(prompt, max_new_tokens=6)
        async with SwarmClient([("127.0.0.1", BASE + 0)], sampling=GREEDY) as c:
            got = await c.generate_ids(prompt, max_new_tokens=6)
        assert got == expected
    finally:
        await node.stop()


@pytest.mark.asyncio
async def test_tp_mesh_node_generation_matches_engine(mesh_parts, devices8):
    """run_node --mesh pp=2,tp=2 serving: the cached decoder blocks run
    tensor-parallel (Megatron psums) inside the pipelined pass — same
    tokens as the single-process engine."""
    parts, params = mesh_parts
    node = _mk_mesh_node(5, parts, pp=2, tp=2)
    await node.start()
    try:
        engine = Engine(TINY, params, max_len=64, sampling_cfg=GREEDY)
        prompt = [3, 7, 11, 19, 23]
        expected = engine.generate(prompt, max_new_tokens=6)
        async with SwarmClient([("127.0.0.1", BASE + 5)], sampling=GREEDY) as c:
            got = await c.generate_ids(prompt, max_new_tokens=6)
        assert got == expected
    finally:
        await node.stop()


@pytest.mark.asyncio
async def test_mesh_node_fork_e2e(mesh_parts, devices8):
    """Pinned client against a mesh-backed node: the fork lands in a cache
    slot (PipelinedEngine.fork_slot, shard-local per pp rank) and
    generations match the engine."""
    parts, params = mesh_parts
    node = _mk_mesh_node(7, parts)
    await node.start()
    try:
        engine = Engine(TINY, params, max_len=64, sampling_cfg=GREEDY)
        prefix = [3, 7, 11, 19, 5, 2]
        prompt = prefix + [4, 9]
        expected = engine.generate(prompt, 5)
        from inferd_tpu.client.swarm_client import SwarmClient

        async with SwarmClient([("127.0.0.1", BASE + 7)], sampling=GREEDY) as c:
            await c.pin_prefix(prefix)
            got = [await c.generate_ids(prompt, 5) for _ in range(2)]
        assert got == [expected, expected]
        assert node.metrics.snapshot()["counters"].get("fork.ok", 0) >= 2
    finally:
        await node.stop()


@pytest.mark.asyncio
async def test_mesh_node_concurrent_sessions(mesh_parts, devices8):
    """Multiple interleaved sessions occupy distinct cache slots and each
    matches its own single-process generation."""
    parts, params = mesh_parts
    node = _mk_mesh_node(1, parts)
    await node.start()
    try:
        engine = Engine(TINY, params, max_len=64, sampling_cfg=GREEDY)
        prompts = [[3, 7, 11], [5, 2, 9, 13], [1, 4]]
        expected = [engine.generate(p, max_new_tokens=5) for p in prompts]

        async def gen(p):
            async with SwarmClient([("127.0.0.1", BASE + 1)], sampling=GREEDY) as c:
                return await c.generate_ids(p, max_new_tokens=5)

        got = await asyncio.gather(*(gen(p) for p in prompts))
        assert list(got) == expected
    finally:
        await node.stop()


@pytest.mark.asyncio
async def test_mesh_node_slot_eviction_and_refill(mesh_parts, devices8):
    """More sessions than slots: LRU session is evicted; its slot serves the
    newcomer; the evicted session can no longer resume mid-stream."""
    parts, params = mesh_parts
    node = _mk_mesh_node(2, parts, slots=2)
    await node.start()
    try:
        ex = node.executor
        # three sessions through 2 slots
        for sid in ("a", "b", "c"):
            ex.process(sid, {"tokens": [[3, 7, 11, 19]], "start_pos": 0, "real_len": 4})
        assert len(ex.sessions) == 2 and "a" not in ex.sessions
        # evicted session resuming mid-stream is refused (its cache is gone)
        with pytest.raises(ValueError, match="unknown session"):
            ex.process("a", {"tokens": [[1]], "start_pos": 4, "real_len": 1})
        # live session continues fine
        r1 = ex.process("b", {"tokens": [[1]], "start_pos": 4, "real_len": 1})
        # a REPLAY of the last chunk (client re-sent after a lost response)
        # rolls the slot back and recomputes identically
        r2 = ex.process("b", {"tokens": [[1]], "start_pos": 4, "real_len": 1})
        np.testing.assert_allclose(
            np.asarray(r1["logits"]), np.asarray(r2["logits"]),
            rtol=1e-6, atol=1e-6,
        )
        # a FUTURE chunk is still refused
        with pytest.raises(ValueError, match="out-of-order"):
            ex.process("b", {"tokens": [[1]], "start_pos": 9, "real_len": 1})
        # end_session frees the slot
        ex.end_session("b")
        assert len(ex.sessions) == 1
        # overflow guard
        with pytest.raises(BufferError, match="KV overflow"):
            ex.process("c", {"tokens": [[0] * 61], "start_pos": 4, "real_len": 61})
    finally:
        await node.stop()


def test_mesh_requires_single_stage(mesh_parts, devices8):
    parts, _ = mesh_parts
    info = NodeInfo(
        name="bad", host="127.0.0.1", port=BASE + 50, stage=0, num_stages=2
    )
    dht = SwarmDHT(info.node_id, BASE + 150, bootstrap=[], host="127.0.0.1")
    with pytest.raises(ValueError, match="single-stage"):
        Node(info, TINY, parts, dht, mesh_plan=MeshPlan(pp=2))


def test_parse_mesh_cli():
    from inferd_tpu.tools.run_node import parse_mesh

    assert parse_mesh("") is None
    assert parse_mesh("pp=4").pp == 4
    plan = parse_mesh("pp=2,tp=1")
    assert (plan.pp, plan.tp) == (2, 1)
    plan = parse_mesh("pp=2,tp=2")  # pp x tp serving (round-2 tail)
    assert (plan.pp, plan.tp) == (2, 2)
    plan = parse_mesh("tp=2")  # tp-only serving
    assert (plan.pp, plan.tp) == (1, 2)
    with pytest.raises(ValueError, match="bad mesh spec"):
        parse_mesh("zz=4")
    with pytest.raises(ValueError, match=">=2 devices"):
        parse_mesh("pp=1")


def test_mesh_rejects_dp_axis(devices8):
    """The serving mesh is pp x tp x ep x sp (sp legalized in round 5 for
    sequence-parallel prefill; decode replicates over it): dp is the one
    axis left that would shard params with no serving collective."""
    from inferd_tpu.parallel.infer import PipelinedEngine

    mesh = meshlib.make_mesh(MeshPlan(pp=2, dp=2), jax.devices()[:4])
    params = qwen3.init_params(TINY, jax.random.PRNGKey(0))
    with pytest.raises(ValueError, match="pp\\(x tp x ep x sp\\) mesh"):
        PipelinedEngine(TINY, params, mesh, num_microbatches=1)

    from inferd_tpu.tools.run_node import parse_mesh

    with pytest.raises(ValueError, match="pp, tp, ep, and sp axes"):
        parse_mesh("pp=2,dp=2")
    assert parse_mesh("pp=2,sp=2").sp == 2  # round 5: sp serves prefill


def test_boundary_chunk_fills_cache_exactly(mesh_parts, devices8):
    """A chunk whose PADDED bucket would spill past max_len must not clamp
    the cache write (code-review r2: 4 + 60 tokens into max_len=64). The
    two-chunk session's final logits must match a one-shot prefill."""
    import numpy as np

    from inferd_tpu.runtime.mesh_executor import MeshExecutor

    parts, params = mesh_parts
    ex = MeshExecutor(TINY, params, MeshPlan(pp=2), num_slots=2, max_len=64)
    rng = np.random.RandomState(11)
    seq = rng.randint(0, TINY.vocab_size, size=64).astype(np.int32)

    out_a = ex.process("s", {"tokens": seq[None, :4], "start_pos": 0, "real_len": 4})
    out_b = ex.process("s", {"tokens": seq[None, 4:], "start_pos": 4, "real_len": 60})

    ex2 = MeshExecutor(TINY, params, MeshPlan(pp=2), num_slots=2, max_len=64)
    ref = ex2.process("r", {"tokens": seq[None, :], "start_pos": 0, "real_len": 64})
    np.testing.assert_allclose(out_b["logits"], ref["logits"], rtol=2e-5, atol=2e-5)


def test_mesh_decode_steps_coalesce(mesh_parts):
    """Co-arriving sessions' decode steps must share ONE pipeline pass
    (engine.step_slots) — driven directly with threads + barrier so
    co-arrival is guaranteed, and results must match solo slot steps."""
    import threading

    import numpy as np

    from inferd_tpu.runtime.mesh_executor import MeshExecutor

    parts, params = mesh_parts
    ex = MeshExecutor(
        TINY, params, MeshPlan(pp=2), num_slots=4, max_len=64,
        devices=jax.devices()[:2],
    )
    ex._batcher.window_s = 0.1  # plenty for barrier-released peers

    sessions = [f"ms{i}" for i in range(3)]
    last = {}
    for i, s in enumerate(sessions):
        r = ex.process(s, {"tokens": [[3 + i, 7, 11]], "start_pos": 0, "real_len": 3})
        last[s] = int(np.asarray(r["logits"])[0].argmax())

    hwm = {"n": 0}

    class TrackingList(list):
        def append(self, item):
            super().append(item)
            hwm["n"] = max(hwm["n"], len(self))

    ex._batcher._pending = TrackingList(ex._batcher._pending)

    barrier = threading.Barrier(len(sessions))
    results = {}

    def step(s):
        barrier.wait()
        results[s] = ex.process(
            s, {"tokens": [[last[s]]], "start_pos": 3, "real_len": 1}
        )

    threads = [threading.Thread(target=step, args=(s,)) for s in sessions]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    assert len(results) == 3
    assert hwm["n"] >= 2, "no decode step ever coalesced >1 session"
    assert ex.stats()["batched_tokens"] >= 3


def test_mesh_executor_handoff_roundtrip(mesh_parts, devices8):
    """--mesh replicas hand sessions off: export a slot from one mesh
    executor (layer axis reassembled across pp ranks), import into a peer
    running a DIFFERENT pp split, identical continuation logits."""
    from inferd_tpu.parallel.mesh import MeshPlan
    from inferd_tpu.runtime.mesh_executor import MeshExecutor

    parts, params = mesh_parts
    a = MeshExecutor(TINY, params, MeshPlan(pp=2), num_slots=2, max_len=64,
                     devices=devices8[:2])
    b = MeshExecutor(TINY, params, MeshPlan(pp=4), num_slots=2, max_len=64,
                     devices=devices8[:4])
    prompt = [3, 7, 11, 19, 5]
    a.process("s", {"tokens": [prompt], "start_pos": 0, "real_len": len(prompt)})
    exported = dict(a.export_sessions())["s"]
    assert exported["length"] == len(prompt)
    assert b.import_session("s", exported)
    step = {"tokens": [[4]], "start_pos": len(prompt), "real_len": 1}
    la = a.process("s", dict(step))["logits"]
    lb = b.process("s", dict(step))["logits"]
    np.testing.assert_allclose(np.asarray(la), np.asarray(lb), rtol=2e-5, atol=2e-5)
    # wrong layer count rejected; duplicate session rejected
    bad = dict(exported)
    bad["k"] = bad["k"][:-1]
    bad["v"] = bad["v"][:-1]
    assert not b.import_session("s2", bad)
    assert not b.import_session("s", exported)


# ---------------------------------------------------------------------------
# O(window) ring KV on the in-mesh path (VERDICT r03 item 3): sliding-window
# models served via --mesh store sliding layers as rings — parity with the
# uniform layout and the solo engine, handoff/replay/fork under the ring
# margin, and the odd-split fallback staying observable.
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def gemma_tiny():
    from inferd_tpu.config import get_config

    cfg = get_config("tiny-gemma2")
    return cfg, qwen3.init_params(cfg, jax.random.PRNGKey(0))


def test_mesh_ring_parity_sliding_models(devices8):
    """PipelinedEngine ring layout == uniform layout == solo Engine for
    both sliding-window families on a pp=2 mesh; the ring layout stores
    measurably less KV (the memory win the design pays for)."""
    from inferd_tpu.config import get_config
    from inferd_tpu.parallel.infer import PipelinedEngine, ring_split_ok

    for name in ("tiny-gemma2", "tiny-gptoss"):
        cfg = get_config(name)
        params = qwen3.init_params(cfg, jax.random.PRNGKey(0))
        solo = Engine(cfg, params, max_len=512, sampling_cfg=GREEDY)
        prompt = [3, 7, 11, 19, 5]
        want = solo.generate(prompt, max_new_tokens=8)
        mesh = meshlib.make_mesh(meshlib.MeshPlan(pp=2), jax.devices()[:2])
        assert ring_split_ok(cfg, 2)
        sizes = {}
        for ring in (None, False):
            eng = PipelinedEngine(
                cfg, params, mesh, num_microbatches=2, batch=1,
                max_len=512, sampling_cfg=GREEDY, ring=ring,
            )
            assert eng.ring_active == (ring is None)
            got = eng.generate([prompt], 8)[0]
            assert got == want, (name, ring, got, want)
            total = eng.caches.k.size + eng.caches.v.size
            if eng.caches.k_loc is not None:
                total += eng.caches.k_loc.size + eng.caches.v_loc.size
            sizes[bool(eng.ring_active)] = total
        # half the layers store O(window)+margin instead of max_len=512
        assert sizes[True] < 0.65 * sizes[False], sizes


def test_mesh_ring_tp_parity(gemma_tiny, devices8):
    """Ring storage composes with tensor parallelism: pp=2 x tp=2 serving
    of a sliding-window model stays token-exact (rings hold each rank's
    local kv heads)."""
    from inferd_tpu.parallel.infer import PipelinedEngine

    cfg, params = gemma_tiny
    solo = Engine(cfg, params, max_len=64, sampling_cfg=GREEDY)
    prompt = [5, 2, 9, 13]
    want = solo.generate(prompt, max_new_tokens=6)
    mesh = meshlib.make_mesh(meshlib.MeshPlan(pp=2, tp=2), devices8[:4])
    eng = PipelinedEngine(
        cfg, params, mesh, num_microbatches=2, batch=1, max_len=64,
        sampling_cfg=GREEDY,
    )
    assert eng.ring_active
    assert eng.generate([prompt], 6)[0] == want


def test_mesh_ring_executor_handoff_and_fallback(gemma_tiny, devices8):
    """Mesh executors hand RING sessions off between different (ring-
    capable) pp splits token-exact; an odd layers-per-rank split falls
    back to uniform KV, says so in stats(), and fails the ring handoff
    CLOSED (layout mismatch -> clean miss, no corruption)."""
    import dataclasses as dc

    from inferd_tpu.config import get_config
    from inferd_tpu.parallel.mesh import MeshPlan
    from inferd_tpu.runtime.mesh_executor import MeshExecutor

    cfg, params = gemma_tiny
    a = MeshExecutor(cfg, params, MeshPlan(pp=2), num_slots=2, max_len=64,
                     devices=devices8[:2])
    b = MeshExecutor(cfg, params, MeshPlan(pp=1), num_slots=2, max_len=64,
                     devices=devices8[:1])
    assert a.engine.ring_active and b.engine.ring_active
    assert not a.stats()["kv_window_fallback"]
    prompt = [3, 7, 11, 19, 5]
    a.process("s", {"tokens": [prompt], "start_pos": 0, "real_len": len(prompt)})
    exported = dict(a.export_sessions())["s"]
    assert "k_loc" in exported  # rings ship whole
    assert b.import_session("s", exported)
    step = {"tokens": [[4]], "start_pos": len(prompt), "real_len": 1}
    la = a.process("s", dict(step))["logits"]
    lb = b.process("s", dict(step))["logits"]
    np.testing.assert_allclose(np.asarray(la), np.asarray(lb), rtol=2e-5, atol=2e-5)

    # odd layers-per-rank: 6-layer variant at pp=2 -> 3 per rank
    cfg_odd = dc.replace(cfg, name="tiny-gemma2-l6", num_layers=6)
    params_odd = qwen3.init_params(cfg_odd, jax.random.PRNGKey(1))
    c = MeshExecutor(cfg_odd, params_odd, MeshPlan(pp=2), num_slots=2,
                     max_len=64, devices=devices8[:2])
    assert not c.engine.ring_active
    assert c.stats()["kv_window_fallback"]
    # uniform still serves correctly
    solo = Engine(cfg_odd, params_odd, max_len=64, sampling_cfg=GREEDY)
    want = solo.generate(prompt, max_new_tokens=4)
    got = [int(np.argmax(c.process(
        "u", {"tokens": [prompt], "start_pos": 0, "real_len": len(prompt)}
    )["logits"][0]))]
    pos = len(prompt)
    for _ in range(3):
        got.append(int(np.argmax(c.process(
            "u", {"tokens": [[got[-1]]], "start_pos": pos, "real_len": 1}
        )["logits"][0])))
        pos += 1
    assert got == want
    # a ring payload into a uniform-layout executor fails closed
    assert not c.import_session("sx", exported)


def test_mesh_ring_replay_margin(gemma_tiny, devices8):
    """Deterministic chunk replay on the ring mesh path: rollback within
    the ring margin recomputes token-exact; rollback past the high-water
    margin is REFUSED (the rings have already overwritten those slots —
    accepting would corrupt silently)."""
    from inferd_tpu.core.cache import RING_MARGIN
    from inferd_tpu.parallel.mesh import MeshPlan
    from inferd_tpu.runtime.mesh_executor import MeshExecutor

    cfg, params = gemma_tiny
    ex = MeshExecutor(cfg, params, MeshPlan(pp=2), num_slots=2, max_len=256,
                      devices=devices8[:2])
    assert ex.engine.ring_active
    rng = np.random.RandomState(0)
    chunks = [list(rng.randint(0, cfg.vocab_size, size=32)) for _ in range(3)]
    pos = 0
    outs = []
    for ch in chunks:  # stream 96 positions in (> RING_MARGIN + window)
        outs.append(ex.process(
            "r", {"tokens": [ch], "start_pos": pos, "real_len": len(ch)}
        )["logits"])
        pos += len(ch)
    # replay the LAST chunk (depth 32 < margin): identical logits
    replay = ex.process(
        "r", {"tokens": [chunks[-1]], "start_pos": 64, "real_len": 32}
    )["logits"]
    np.testing.assert_allclose(
        np.asarray(replay), np.asarray(outs[-1]), rtol=2e-5, atol=2e-5
    )
    # replay reaching past the margin (high-water 96, target 16 -> depth 80)
    assert 96 - 16 > RING_MARGIN
    with pytest.raises(ValueError, match="ring margin"):
        ex.process(
            "r", {"tokens": [chunks[0]], "start_pos": 16, "real_len": 32}
        )
