# jaxlint: file-disable=J003 -- test code: loops here sync per-iteration to ASSERT on values; they are verification loops, not serving hot paths
"""O(window) ring-buffer KV storage for sliding-window layers.

The reference's KV story is a growing DynamicCache (O(context) per layer,
qwen3_server_module.py:220); round 2 narrowed sliding layers' per-token KV
READ to O(window) (`_windowed_slice`), and this suite pins the round-3
STORAGE win: sliding layers live in fixed ring buffers of
round16(window) + RING_MARGIN slots (core/cache.py), exact against the
uniform full-length layout everywhere it ships:

  * engine parity (greedy + sampled + pinned-prefix fork + generate_scan),
  * stage executors at EVEN and ODD layer boundaries (the round-2 fast
    path silently degraded on odd cuts; rings cover any static offset),
  * export/import handoff round trip (bf16 and fp8 rings on the wire),
  * fork-margin safety (a parent that ran past the ring margin refuses the
    fork instead of serving aliased windows),
  * the memory assertion: ring caches are a fraction of uniform ones.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from inferd_tpu.config import TINY_GEMMA2, TINY_GPT_OSS, SamplingConfig
from inferd_tpu.core.cache import RING_MARGIN, KVCache, ring_slots
from inferd_tpu.core.generate import Engine
from inferd_tpu.models import qwen3
from inferd_tpu.parallel.stages import StageSpec
from inferd_tpu.runtime.executor import Qwen3StageExecutor

GREEDY = SamplingConfig(temperature=0.0)


@pytest.fixture(scope="module", params=["tiny-gemma2", "tiny-gptoss"])
def family(request):
    cfg = {"tiny-gemma2": TINY_GEMMA2, "tiny-gptoss": TINY_GPT_OSS}[request.param]
    params = qwen3.init_params(cfg, jax.random.PRNGKey(7))
    return cfg, params


def _prompt(cfg, n=23, seed=0):
    return list(np.random.RandomState(seed).randint(0, cfg.vocab_size, size=n))


def test_engine_ring_matches_uniform(family):
    """Greedy AND sampled decode token-identical between ring and uniform
    storage, with the generation walking well past the window."""
    cfg, params = family
    prompt = _prompt(cfg)
    ring = Engine(cfg, params, max_len=128, sampling_cfg=GREEDY)
    flat = Engine(cfg, params, max_len=128, sampling_cfg=GREEDY, ring_kv=False)
    assert ring.new_cache(1).k_loc is not None  # rings actually in play
    assert flat.new_cache(1).k_loc is None
    assert ring.generate(prompt, max_new_tokens=30) == flat.generate(
        prompt, max_new_tokens=30
    )
    samp = SamplingConfig(temperature=0.8, top_k=20)
    ring_s = Engine(cfg, params, max_len=128, sampling_cfg=samp)
    flat_s = Engine(cfg, params, max_len=128, sampling_cfg=samp, ring_kv=False)
    assert ring_s.generate(prompt, max_new_tokens=25, seed=3) == flat_s.generate(
        prompt, max_new_tokens=25, seed=3
    )


def test_engine_ring_pin_fork_and_scan(family):
    cfg, params = family
    prefix = _prompt(cfg, n=12, seed=1)
    tail = [5, 9, 33]
    ring = Engine(cfg, params, max_len=128, sampling_cfg=GREEDY)
    flat = Engine(cfg, params, max_len=128, sampling_cfg=GREEDY, ring_kv=False)
    ring.pin_prefix(prefix)
    flat.pin_prefix(prefix)
    assert ring.generate(prefix + tail, max_new_tokens=20) == flat.generate(
        prefix + tail, max_new_tokens=20
    )
    # prompt == pin exactly (stored-logits reuse path)
    assert ring.generate(prefix, max_new_tokens=8) == flat.generate(
        prefix, max_new_tokens=8
    )
    # fully-jitted scan path == host loop
    toks = np.zeros((1, 32), np.int32)
    pl = 14
    toks[0, :pl] = _prompt(cfg, n=pl, seed=2)
    s = ring.generate_scan(jnp.asarray(toks), pl, steps=12, seed=4)
    assert list(np.asarray(s)[0]) == ring.generate(
        list(toks[0, :pl]), max_new_tokens=12, seed=4
    )


# ------------------------------------------------------------- executors


def _pipeline_logits(cfg, params, boundaries, toks, chunks):
    """Drive a chain of stage executors chunk by chunk; returns per-chunk
    last-token logits. boundaries: [(start_layer, end_layer_incl)]."""
    execs = []
    for stage, (a, b) in enumerate(boundaries):
        spec = StageSpec(stage, len(boundaries), a, b)
        sp = dict(params)
        sp["layers"] = qwen3.slice_layers(params["layers"], a, b + 1)
        execs.append(
            Qwen3StageExecutor(cfg, spec, sp, max_len=96, initial_kv_len=32)
        )
    outs = []
    pos = 0
    for chunk in chunks:
        payload = {"tokens": np.asarray([chunk]), "start_pos": pos,
                   "real_len": len(chunk)}
        for ex in execs:
            out = ex.process("s", payload)
            if "logits" in out:
                outs.append(np.asarray(out["logits"])[0])
            else:
                payload = {"hidden": out["hidden"], "start_pos": pos,
                           "real_len": len(chunk)}
        pos += len(chunk)
    return execs, outs


@pytest.mark.parametrize("boundaries", [
    [(0, 1), (2, 3)],          # even cuts (round-2 fast-path territory)
    [(0, 0), (1, 3)],          # ODD boundary: stage 1 starts on layer 1
    [(0, 2), (3, 3)],          # odd tail stage
])
def test_stage_executors_ring_any_boundary(family, boundaries):
    """Stage pipelines produce the engine's logits with ring storage at
    even AND odd layer cuts — the verdict's fast-path-generality ask."""
    cfg, params = family
    prompt = _prompt(cfg, n=17, seed=3)
    chunks = [prompt[:9], prompt[9:]] + [[t] for t in _prompt(cfg, 4, 4)]
    execs, outs = _pipeline_logits(cfg, params, boundaries, prompt, chunks)
    # rings actually present on every stage holding a sliding layer
    for ex in execs:
        c = ex.sessions.get("s")
        has_sliding = any(
            (ex.spec.start_layer + i) % 2 == 0 for i in range(ex.spec.num_layers)
        )
        assert (c.k_loc is not None) == has_sliding

    eng = Engine(cfg, params, max_len=96, sampling_cfg=GREEDY, ring_kv=False)
    cache = eng.new_cache(1)
    pos = 0
    want = []
    for chunk in chunks:
        logits, cache = eng._prefill_at(
            eng.params, jnp.asarray([chunk + [0] * (16 - len(chunk))], jnp.int32)
            if len(chunk) > 1 else jnp.asarray([chunk], jnp.int32),
            jnp.int32(pos), jnp.int32(len(chunk)), cache,
        )
        want.append(np.asarray(logits)[0])
        pos += len(chunk)
    for got, exp in zip(outs, want):
        np.testing.assert_allclose(got, exp, rtol=2e-4, atol=2e-4)


def test_export_import_ring_roundtrip(family):
    """Handoff: a ring session exported from one executor and imported by a
    peer continues the generation with identical logits (bf16 and fp8)."""
    cfg, params = family
    for kv_dtype in (None, "float8_e4m3fn"):
        c = cfg if kv_dtype is None else dataclasses.replace(cfg, kv_dtype=kv_dtype)
        spec = StageSpec(0, 1, 0, c.num_layers - 1)
        a = Qwen3StageExecutor(c, spec, params, max_len=96, initial_kv_len=32)
        b = Qwen3StageExecutor(c, spec, params, max_len=96, initial_kv_len=32)
        prompt = _prompt(c, n=14, seed=5)
        out_a = a.process("s", {"tokens": np.asarray([prompt]), "start_pos": 0,
                                "real_len": len(prompt)})
        exported = dict(a.export_sessions())["s"]
        assert "k_loc" in exported  # rings ride the handoff payload
        assert b.import_session("s", exported)
        # both continue identically
        step = {"tokens": np.asarray([[3]]), "start_pos": len(prompt),
                "real_len": 1}
        la = a.process("s", dict(step))["logits"]
        lb = b.process("s", dict(step))["logits"]
        np.testing.assert_allclose(np.asarray(la), np.asarray(lb), rtol=1e-5, atol=1e-5)
        # malformed ring shape is rejected, not adopted
        bad = dict(exported)
        bad["k_loc"] = bad["k_loc"][:, :, :-1]
        assert not b.import_session("s2", bad)


def test_fork_margin_guard(family):
    """Fork from a ring parent succeeds at the pin point (parent parked at
    the prefix) and REFUSES once the parent ran past RING_MARGIN — the
    aliasing bound (stale ring slots would enter the child's windows)."""
    cfg, params = family
    spec = StageSpec(0, 1, 0, cfg.num_layers - 1)
    ex = Qwen3StageExecutor(cfg, spec, params, max_len=256, initial_kv_len=32)
    prompt = _prompt(cfg, n=10, seed=6)
    ex.process("p", {"tokens": np.asarray([prompt]), "start_pos": 0,
                     "real_len": len(prompt)})
    assert ex.fork_session("child", "p", len(prompt))
    # child == fresh prefill continuation
    step = {"tokens": np.asarray([[7]]), "start_pos": len(prompt), "real_len": 1}
    lc = ex.process("child", dict(step))["logits"]
    ex.process("fresh", {"tokens": np.asarray([prompt]), "start_pos": 0,
                         "real_len": len(prompt)})
    lf = ex.process("fresh", dict(step))["logits"]
    np.testing.assert_allclose(np.asarray(lc), np.asarray(lf), rtol=2e-4, atol=2e-4)
    # advance the parent far past the margin, then fork at the old prefix
    pos = len(prompt)
    for t in _prompt(cfg, RING_MARGIN + 8, seed=7):
        ex.process("p", {"tokens": np.asarray([[t]]), "start_pos": pos,
                         "real_len": 1})
        pos += 1
    assert not ex.fork_session("late", "p", len(prompt))


def test_ring_memory_fraction():
    """The point: a long-context sliding-model cache is a FRACTION of the
    uniform one. Gemma-2 shape at 8K context / window 8 (tiny widths):
    sliding layers store ring_slots(cfg) instead of 8192 slots."""
    cfg = TINY_GEMMA2
    ctx = 8192
    ring = KVCache.create(cfg, cfg.num_layers, 1, ctx)
    flat = KVCache.create(cfg, cfg.num_layers, 1, ctx, ring=False)

    def nbytes(c):
        return sum(
            x.nbytes for x in (c.k, c.v, c.k_loc, c.v_loc) if x is not None
        )

    r = ring_slots(cfg)
    assert ring.k_loc.shape[2] == r
    # exact accounting: half the layers collapse T=8192 -> R=ring_slots
    expect = nbytes(flat) * (cfg.num_layers // 2) // cfg.num_layers * (
        1 + r / ctx
    )
    assert nbytes(ring) <= expect * 1.01
    assert nbytes(ring) < 0.52 * nbytes(flat)


def test_batched_engine_ring_parity(family):
    """Continuous batching over ring storage: ragged lanes at different
    fill levels, lane REUSE over stale rings (refill without zeroing — the
    slot-attribution formula masks or overwrites stale data), and the
    fused chunk scan — all token-exact vs the solo uniform engine."""
    from inferd_tpu.core.batch import BatchedEngine

    cfg, params = family
    solo = Engine(cfg, params, max_len=128, sampling_cfg=GREEDY, ring_kv=False)
    prompts = [_prompt(cfg, 9 + i, seed=i) for i in range(5)]
    want = [solo.generate(p, max_new_tokens=20, seed=i)
            for i, p in enumerate(prompts)]
    eng = BatchedEngine(cfg, params, lanes=3, max_len=128, sampling_cfg=GREEDY)
    assert eng.cache.k_loc is not None  # rings actually in play
    assert eng.generate_all(prompts, 20) == want
    eng2 = BatchedEngine(cfg, params, lanes=3, max_len=128, sampling_cfg=GREEDY)
    assert eng2.generate_all(prompts, 20, chunk=4) == want


def test_batched_replay_rolls_back(family):
    """Batched-path deterministic replay: a re-sent chunk rolls the lane
    back and recomputes identically (ring margin honored); a future chunk
    still 409s."""
    from inferd_tpu.runtime.batch_executor import BatchedExecutor

    cfg, params = family
    ex = BatchedExecutor(cfg, params, lanes=2, max_len=128)
    prompt = _prompt(cfg, 10, seed=12)
    ex.process("s", {"tokens": np.asarray([prompt]), "start_pos": 0,
                     "real_len": len(prompt)})
    step = {"tokens": np.asarray([[5]]), "start_pos": len(prompt), "real_len": 1}
    a = ex.process("s", dict(step))
    b = ex.process("s", dict(step))  # replay
    np.testing.assert_allclose(a["logits"], b["logits"], rtol=1e-6, atol=1e-6)
    with pytest.raises(ValueError, match="out-of-order"):
        ex.process("s", {"tokens": np.asarray([[5]]),
                         "start_pos": len(prompt) + 5, "real_len": 1})


def test_batched_fork_margin_guard(family):
    """Batched-path prefix fork refuses once the parent lane ran past the
    ring margin (the executor-level alias guard)."""
    from inferd_tpu.runtime.batch_executor import BatchedExecutor

    cfg, params = family
    ex = BatchedExecutor(cfg, params, lanes=2, max_len=256)
    prompt = _prompt(cfg, 10, seed=9)
    ex.process("p", {"tokens": np.asarray([prompt]), "start_pos": 0,
                     "real_len": len(prompt)})
    assert ex.fork_session("child", "p", len(prompt))
    pos = len(prompt)
    for t in _prompt(cfg, RING_MARGIN + 8, seed=10):
        ex.process("p", {"tokens": np.asarray([[t]]), "start_pos": pos,
                         "real_len": 1})
        pos += 1
    assert not ex.fork_session("late", "p", len(prompt))


def test_ring_fuzz_random_chunks_and_rollbacks():
    """Property fuzz of the ring substrate: random chunk-size sequences
    (including chunks longer than the ring) interleaved with random
    rollbacks bounded by the margin, checked step-for-step against the
    uniform full-length layout. This pins the aliasing invariant the
    specific-path tests above rely on."""
    import dataclasses as _dc

    cfg = _dc.replace(TINY_GEMMA2, num_layers=2)  # 1 sliding + 1 global
    params = qwen3.init_params(cfg, jax.random.PRNGKey(21))
    rng = np.random.RandomState(42)
    for trial in range(4):
        max_len = 192
        ring = KVCache.create(cfg, cfg.num_layers, 1, max_len)
        flat = KVCache.create(cfg, cfg.num_layers, 1, max_len, ring=False)
        assert ring.k_loc is not None and ring.ring == ring_slots(cfg)
        pos = 0
        hi = 0  # high-water mark of positions ever written
        toks_total = 0
        while pos < max_len - 1 and toks_total < 6:
            # random chunk, sometimes longer than the ring (80 slots)
            s = int(rng.choice([1, 3, 16, 90]))
            s = min(s, max_len - pos)
            chunk = rng.randint(0, cfg.vocab_size, size=(1, s)).astype(np.int32)
            pos_arr = pos + jnp.arange(s)[None, :]
            lr, ring = qwen3.forward_cached(
                params, cfg, jnp.asarray(chunk), pos_arr, ring,
                jnp.int32(pos), real_end=jnp.int32(pos + s),
            )
            lf, flat = qwen3.forward_cached(
                params, cfg, jnp.asarray(chunk), pos_arr, flat,
                jnp.int32(pos), real_end=jnp.int32(pos + s),
            )
            np.testing.assert_allclose(
                np.asarray(lr[:, s - 1]), np.asarray(lf[:, s - 1]),
                rtol=2e-4, atol=2e-4,
                err_msg=f"trial {trial} pos {pos} chunk {s}",
            )
            ring = dataclasses.replace(ring, length=jnp.int32(pos + s))
            flat = dataclasses.replace(flat, length=jnp.int32(pos + s))
            pos += s
            hi = max(hi, pos)
            toks_total += 1
            # occasional rollback within the ALIASING INVARIANT: the
            # high-water mark of ever-written positions must stay within
            # RING_MARGIN of the current frontier (exactly what the
            # speculative engine and the executor replay path guarantee —
            # compound rollbacks past that bound are out of contract and
            # DO corrupt, by design)
            back_max = pos - max(0, hi - (RING_MARGIN - 1))
            if back_max >= 1 and rng.rand() < 0.5:
                back = int(rng.randint(1, back_max + 1))
                pos -= back
                ring = dataclasses.replace(ring, length=jnp.int32(pos))
                flat = dataclasses.replace(flat, length=jnp.int32(pos))


def test_speculative_ring_guard():
    """Spec k past the ring margin is refused for sliding models (rollback
    depth must stay under the margin)."""
    from inferd_tpu.core.speculative import SpeculativeEngine, self_draft

    cfg = TINY_GEMMA2
    params = qwen3.init_params(cfg, jax.random.PRNGKey(0))
    dcfg, dparams = self_draft(cfg, params, 2)
    with pytest.raises(ValueError, match="ring margin"):
        SpeculativeEngine(cfg, params, dcfg, dparams, k=RING_MARGIN, max_len=64)


def test_batched_executor_handoff_roundtrip(family):
    """--batch-lanes replicas hand sessions off: export from one batched
    executor, import into a peer, identical continuation logits (rings +
    hi mark ride the payload)."""
    from inferd_tpu.runtime.batch_executor import BatchedExecutor

    cfg, params = family
    a = BatchedExecutor(cfg, params, lanes=2, max_len=128)
    b = BatchedExecutor(cfg, params, lanes=2, max_len=128)
    prompt = _prompt(cfg, 12, seed=14)
    a.process("s", {"tokens": np.asarray([prompt]), "start_pos": 0,
                    "real_len": len(prompt)})
    exported = dict(a.export_sessions())["s"]
    assert "k_loc" in exported and "hi" in exported
    assert b.import_session("s", exported)
    step = {"tokens": np.asarray([[3]]), "start_pos": len(prompt), "real_len": 1}
    la = a.process("s", dict(step))["logits"]
    lb = b.process("s", dict(step))["logits"]
    np.testing.assert_allclose(np.asarray(la), np.asarray(lb), rtol=1e-5, atol=1e-5)
    # malformed ring shape rejected
    bad = dict(exported)
    bad["k_loc"] = bad["k_loc"][:, :, :-1]
    assert not b.import_session("s2", bad)
