"""Continuous profiling plane tests (PR 11): live step anatomy against
real executor weights, the perf-regression sentinel (burn-rate-style
drift vs committed per-token priors), the /profile capture-vs-tick lock
discipline, the gate budget extension, collector/dashboard surfacing,
the offline `obs prof --check` fixture — plus the e2e acceptance (a live
2-stage chain publishes anatomy/roofline series; a slowed stage-1
replica fires the sentinel alone, visible in gossip, dashboard, CSV, and
the offline check over flushed artifacts)."""

import asyncio
import json
import os
import threading
import time

import pytest

from inferd_tpu.config import TINY, get_config
from inferd_tpu.obs import prof as proflib
from inferd_tpu.obs import tsdb as tsdblib
from inferd_tpu.obs.__main__ import main as obs_main
from inferd_tpu.utils.metrics import Metrics

from test_node_e2e import BASE, _start_all, _stop_all, tiny_parts  # noqa: F401

PROF_FIXTURE = os.path.join(os.path.dirname(__file__), "data", "prof")


def _clocked_tsdb(metrics, **kw):
    clock = [1000.0]
    t = tsdblib.Tsdb(metrics, clock=lambda: clock[0], **kw)
    return t, clock


def _drive_traffic(metrics, tsdb, clock, seconds=120, tok_per_s=5,
                   tok_ms=10.0):
    for _ in range(seconds):
        clock[0] += 1.0
        metrics.inc("stage.tokens", tok_per_s)
        for _ in range(tok_per_s):
            metrics.observe("stage.compute_ms", tok_ms)
        tsdb.sample()


# ---------------------------------------------------------------- priors


def test_prior_key_and_load_priors(tmp_path):
    assert proflib.prior_key("v5e", "tiny", "int8", 2) == "v5e|tiny|int8|s2"
    p = tmp_path / "priors.json"
    p.write_text(json.dumps({
        "v": 1,
        "priors": {
            "cpu|tiny|none|s0": {"tok_ms": 12.5},
            "bad-row": {"tok_ms": -1},
            "not-a-dict": 3,
        },
    }))
    priors = proflib.load_priors(str(p))
    # garbage rows are dropped, valid ones survive
    assert priors == {"cpu|tiny|none|s0": {"tok_ms": 12.5}}
    p.write_text(json.dumps({"v": 99, "priors": {}}))
    with pytest.raises(ValueError, match="version"):
        proflib.load_priors(str(p))
    p.write_text("[]")
    with pytest.raises(ValueError):
        proflib.load_priors(str(p))


def test_prior_from_anatomy():
    assert proflib.prior_from_anatomy(
        {"step_ms": 24.0, "batch": 2}
    ) == {"tok_ms": 12.0}
    # no fused step (with_step=False live scan): the phase sum stands in
    assert proflib.prior_from_anatomy(
        {"step_ms": None, "phase_sum_ms": 8.0, "batch": 1}
    ) == {"tok_ms": 8.0}
    assert proflib.prior_from_anatomy({"step_ms": None}) is None


# ------------------------------------------------------ trailing queries


def test_live_tok_ms_and_live_frac():
    m = Metrics()
    t, clock = _clocked_tsdb(m)
    t.sample()
    assert proflib.live_tok_ms(t.history()) is None  # no traffic yet
    _drive_traffic(m, t, clock, seconds=30, tok_per_s=4, tok_ms=7.0)
    got = proflib.live_tok_ms(t.history(), 60.0)
    assert got is not None
    tok_ms, tokens = got
    assert tok_ms == pytest.approx(7.0, rel=0.01)
    assert tokens >= 100
    # achieved 4 tok/s against a 40 tok/s ceiling: ~10% of roofline
    lf = proflib.live_frac(t.history(), ceiling_tok_s=40.0)
    assert lf == pytest.approx(0.1, rel=0.2)
    assert proflib.live_frac(t.history(), ceiling_tok_s=0.0) is None


def test_sentinel_fires_only_when_both_windows_degrade():
    m = Metrics()
    t, clock = _clocked_tsdb(m)
    t.sample()
    # 5 minutes at the prior cost, then a short burst of degradation:
    # the short window reads degraded, the long window still healthy —
    # burn-rate style, the sentinel must NOT fire on one bad burst
    _drive_traffic(m, t, clock, seconds=300, tok_ms=10.0)
    _drive_traffic(m, t, clock, seconds=20, tok_ms=30.0)
    v = proflib.sentinel_eval(t.history(), prior_tok_ms=10.0)
    assert v is not None and not v["fired"]
    assert v["windows"][0]["ratio"] > 1.2  # short window IS degraded
    # the degradation persists past the long window: now it fires
    _drive_traffic(m, t, clock, seconds=300, tok_ms=30.0)
    v = proflib.sentinel_eval(t.history(), prior_tok_ms=10.0)
    assert v is not None and v["fired"] and v["ratio"] > 1.2
    # no prior / no traffic => skip, never a verdict
    assert proflib.sentinel_eval(t.history(), prior_tok_ms=None) is None
    m2 = Metrics()
    t2, _ = _clocked_tsdb(m2)
    t2.sample()
    assert proflib.sentinel_eval(t2.history(), prior_tok_ms=10.0) is None


# ------------------------------------------------------- live anatomy tick


def _tiny_target(phases=("attention", "kv_write")):
    import jax

    from inferd_tpu.models import qwen3

    cfg = get_config("tiny")
    return proflib.AnatomyTarget(
        cfg=cfg,
        params=qwen3.init_params(cfg, jax.random.PRNGKey(0)),
        phases=tuple(phases),
        ctx=32,
    )


def test_live_anatomy_tick_cycles_phases_and_budget_wiring():
    """Measured-N-ticks budget test (the satellite): the tick publishes
    anatomy.* gauges and an aggregate roofline.frac once every phase was
    visited, accumulates its real cost in prof.overhead_ms, and that
    gauge is budgeted by perf.gate.check_span_overhead exactly like its
    trace/events/tsdb/canary siblings."""
    from inferd_tpu.perf.gate import check_span_overhead

    m = Metrics()
    t, clock = _clocked_tsdb(m)
    t.sample()
    _drive_traffic(m, t, clock, seconds=60, tok_per_s=3, tok_ms=12.0)
    target = _tiny_target()
    la = proflib.LiveAnatomy(
        m, lambda: target, history_fn=t.history,
        priors={"k": {"tok_ms": 12.0}}, key_fn=lambda: "k",
    )
    out1 = la.tick_once()
    out2 = la.tick_once()
    assert {out1["phase"], out2["phase"]} == {"attention", "kv_write"}
    snap = m.snapshot()
    assert snap["gauges"]["anatomy.attention_ms"] > 0
    assert snap["gauges"]["anatomy.kv_write_ms"] > 0
    assert 0 < snap["gauges"]["anatomy.attention_frac"] <= 1.02
    # full cycle done: the phase-weighted aggregate fraction published
    assert 0 < snap["gauges"]["roofline.frac"] <= 1.02
    # live tok/s vs ceiling gauge + quiet sentinel (cost == prior)
    assert snap["gauges"]["roofline.live_frac"] > 0
    assert snap["gauges"]["perf.regression"] == 0.0
    assert not out1.get("sentinel_changed")
    # measured N-tick cost is real and budgeted: clean at a realistic
    # duty cycle (compute >> 100x scan cost), flagged when the scans eat
    # more than 1% of compute
    overhead = la.overhead_ms
    assert overhead > 0
    assert snap["gauges"]["prof.overhead_ms"] == pytest.approx(
        overhead, abs=0.01
    )

    def stats(compute_ms):
        return {
            "gauges": {"prof.overhead_ms": overhead},
            "histograms": {
                "stage.compute_ms": {"count": 1, "mean_ms": compute_ms}
            },
        }

    assert check_span_overhead(stats(overhead * 200)) == []
    flagged = check_span_overhead(stats(overhead * 10))
    assert len(flagged) == 1 and "live-anatomy" in flagged[0].message


def test_live_anatomy_sentinel_transition_journals(monkeypatch):
    """A cost regression vs the prior journals perf.regression on the
    transition (and perf.regression_cleared on recovery), sets the gauge
    the `perf.regression == 0` default rule reads, and reports the
    change so the node re-announces."""
    from inferd_tpu.obs import events as eventslib
    from inferd_tpu.obs import health as healthlib

    m = Metrics()
    t, clock = _clocked_tsdb(m)
    t.sample()
    _drive_traffic(m, t, clock, seconds=400, tok_ms=30.0)
    journal = eventslib.EventJournal("n0", metrics=m)
    la = proflib.LiveAnatomy(
        m, lambda: None, history_fn=t.history, journal=journal,
        priors={"k": {"tok_ms": 10.0}}, key_fn=lambda: "k",
    )
    out = la.tick_once()
    assert out["sentinel_changed"] and la.sentinel_fired
    evs = [e for e in journal.events() if e["type"] == "perf.regression"]
    assert len(evs) == 1 and evs[0]["attrs"]["ratio"] > 1.2
    assert m.snapshot()["gauges"]["perf.regression"] == 1.0
    # the default SLO rule fires on the gauge
    verdict = healthlib.evaluate(
        healthlib.DEFAULT_RULES, m.snapshot(),
    )
    assert any(
        f["rule"].startswith("perf.regression") for f in verdict["firing"]
    )
    # recovery: prior raised (same effect as the live cost dropping)
    la.priors["k"] = {"tok_ms": 30.0}
    out = la.tick_once()
    assert out["sentinel_changed"] and not la.sentinel_fired
    assert any(
        e["type"] == "perf.regression_cleared" for e in journal.events()
    )


def test_sentinel_skip_never_publishes_the_gauge():
    """No matching prior (or no traffic) = the sentinel SKIPS — the
    perf.regression gauge must not exist, or the `perf.regression == 0`
    default rule would evaluate green against an unjudged replica
    (no-data-is-not-green). Once a verdict exists the gauge appears."""
    m = Metrics()
    t, clock = _clocked_tsdb(m)
    t.sample()
    _drive_traffic(m, t, clock, seconds=60, tok_ms=10.0)
    la = proflib.LiveAnatomy(m, lambda: None, history_fn=t.history)
    la.tick_once()  # no priors at all: skip
    assert "perf.regression" not in m.snapshot()["gauges"]
    la.priors, la.key_fn = {"k": {"tok_ms": 10.0}}, lambda: "k"
    la.tick_once()  # judged: the gauge exists (quiet)
    assert m.snapshot()["gauges"]["perf.regression"] == 0.0


def test_live_anatomy_skips_busy_disabled_and_locked(monkeypatch):
    m = Metrics()
    calls = []
    la = proflib.LiveAnatomy(
        m, lambda: calls.append(1),  # would explode if reached
        busy_fn=lambda: True,
    )
    assert la.tick_once() == {"skipped": "busy"} and not calls
    monkeypatch.setenv("INFERD_EVENTS", "0")
    la.busy_fn = None
    assert la.tick_once() == {"skipped": "events-disabled"}
    monkeypatch.setenv("INFERD_EVENTS", "1")
    lock = threading.Lock()
    la2 = proflib.LiveAnatomy(m, lambda: None, device_lock=lock)
    with lock:
        assert la2.tick_once() == {"skipped": "capture-active"}
    ex_lock = threading.Lock()
    la3 = proflib.LiveAnatomy(
        m, lambda: None, executor_lock_fn=lambda: ex_lock
    )
    with ex_lock:
        assert la3.tick_once() == {"skipped": "device-busy"}
    # all clear: an empty target still completes a (no-op) tick
    assert "skipped" not in la3.tick_once()


def test_profiler_capture_serializes_with_tick(tmp_path):
    """The race fix: a manual /profile capture holds the shared capture
    lock from start to stop, so live-anatomy ticks SKIP for the whole
    window instead of interleaving micro-scans into the device trace —
    and the tick resumes the moment the capture closes."""
    from inferd_tpu.utils.profiling import Profiler

    lock = threading.Lock()
    prof = Profiler(base_dir=str(tmp_path / "profiles"), device_lock=lock)
    m = Metrics()
    la = proflib.LiveAnatomy(m, lambda: None, device_lock=lock)
    d = prof.start("cap1")
    try:
        # concurrent tick during the capture: skipped, never blocked
        assert la.tick_once() == {"skipped": "capture-active"}
        assert lock.locked()
    finally:
        assert prof.stop() == d
    assert not lock.locked()
    assert "skipped" not in la.tick_once()
    # a second start while one runs still 409s (and must not deadlock on
    # the device lock it already holds)
    prof.start("cap2")
    with pytest.raises(RuntimeError, match="already running"):
        prof.start("cap3")
    prof.stop()
    assert not lock.locked()


# ----------------------------------------------------- health rule family


def test_health_prof_rule_family():
    from inferd_tpu.obs import health as healthlib

    snap = {
        "gauges": {
            "roofline.frac": 0.03,
            "roofline.live_frac": 0.4,
            "anatomy.attention_frac": 0.6,
            "anatomy.mlp_ms": 4.0,
        }
    }
    r = healthlib.Rule.parse("roofline:frac >= 0.05", severity="failing")
    fired, val, _ = healthlib.evaluate_rule(r, snap)
    assert fired and val == 0.03
    # phase alias + field: attn/frac -> anatomy.attention_frac
    r2 = healthlib.Rule.parse("phase:attn/frac < 0.5")
    fired, val, _ = healthlib.evaluate_rule(r2, snap)
    assert fired and val == 0.6
    # field defaults to ms
    r3 = healthlib.Rule.parse("phase:mlp < 10")
    fired, val, _ = healthlib.evaluate_rule(r3, snap)
    assert not fired and val == 4.0
    # head alias -> lm_head; absent gauge => SKIP, not green
    r4 = healthlib.Rule.parse("phase:head/frac < 0.5")
    assert healthlib.evaluate_rule(r4, snap) == (None, None, None)
    assert healthlib.evaluate_rule(
        healthlib.Rule.parse("roofline:live_frac >= 0.1"), snap
    )[0] is False
    # the drift sentinel's default rule skips without the gauge
    r5 = healthlib.Rule.parse("perf.regression == 0")
    assert healthlib.evaluate_rule(r5, snap) == (None, None, None)


# -------------------------------------- exposition + kill-switch parity


def test_exposition_validates_prof_series():
    from inferd_tpu.obs import export

    m = Metrics()
    m.set_gauge("anatomy.attention_ms", 3.25)
    m.set_gauge("anatomy.attention_frac", 0.41)
    m.set_gauge("anatomy.lm_head_ms", 1.5)
    m.set_gauge("roofline.frac", 0.2)
    m.set_gauge("roofline.live_frac", 0.07)
    m.set_gauge("perf.regression", 1.0)
    m.set_gauge("prof.overhead_ms", 42.0)
    m.inc("prof.captures", 2)
    text = export.prometheus_text(m, labels={"node": "10.0.0.2:6050"})
    assert export.validate_exposition(text) == []
    assert "inferd_anatomy_attention_ms" in text
    assert "inferd_roofline_live_frac" in text
    assert "inferd_prof_captures_total" in text


def test_metrics_byte_parity_with_events_disabled(monkeypatch):
    """INFERD_EVENTS=0: a tick is a full no-op — no anatomy/roofline/
    sentinel gauges reach the registry, so /metrics stays byte-identical
    to a registry the prof plane never touched (the kill-switch
    contract)."""
    from inferd_tpu.obs import export

    def drive(m):
        m.inc("forward.requests")
        m.observe("stage.compute_ms", 5.0)
        la = proflib.LiveAnatomy(
            m, _tiny_target,
            priors={"k": {"tok_ms": 1.0}}, key_fn=lambda: "k",
        )
        la.tick_once()
        return m

    monkeypatch.setenv("INFERD_EVENTS", "0")
    disabled = export.prometheus_text(drive(Metrics()))
    baseline = Metrics()
    baseline.inc("forward.requests")
    baseline.observe("stage.compute_ms", 5.0)
    assert disabled == export.prometheus_text(baseline)


# ------------------------------------------------ fixture + offline check


def test_prof_golden_fixture_and_check(capsys):
    """The committed fresh-vs-regressed fixture: both histories pass the
    schema validator, the trailing anatomy/roofline series read
    deterministically, the sentinel clears fresh and fires regressed,
    and the CLI check exits 0 (run.sh step 0f)."""
    fresh = tsdblib.load_history_file(
        os.path.join(PROF_FIXTURE, "fresh.history.json")
    )
    assert tsdblib.validate_history(fresh) == []
    assert fresh["meta"]["chip"] == "cpu"
    assert tsdblib.trailing_gauge(fresh, "anatomy.attention_ms") == 5.0
    assert tsdblib.trailing_gauge(fresh, "roofline.live_frac") == 0.001
    got = proflib.live_tok_ms(fresh, 60.0)
    assert got is not None and got[0] == pytest.approx(10.0, rel=0.01)

    rc = obs_main(["prof", "--check", PROF_FIXTURE])
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "REGRESSED x1.50" in out
    assert "1 firing" in out

    rc = obs_main(["prof", "--json", PROF_FIXTURE])
    report = json.loads(capsys.readouterr().out)
    by_service = {
        r["service"]: r for r in report["histories"]
    }
    assert by_service["10.0.0.1:6050"]["verdict"]["fired"] is False
    assert by_service["10.0.0.2:6050"]["verdict"]["fired"] is True
    assert "anatomy.attention_ms" in (
        by_service["10.0.0.1:6050"]["anatomy_series"]
    )


def test_prof_check_fails_without_priors_or_histories(tmp_path, capsys):
    rc = obs_main(["prof", "--check", str(tmp_path)])
    assert rc == 1
    assert "FAIL" in capsys.readouterr().out
    # histories but no matching prior: valid files, zero evaluated
    import shutil

    shutil.copy(
        os.path.join(PROF_FIXTURE, "fresh.history.json"),
        tmp_path / "n.history.json",
    )
    rc = obs_main(["prof", "--check", str(tmp_path)])
    assert rc == 1
    assert "zero histories evaluated" in capsys.readouterr().out


# -------------------------------------------- collector + dashboard cells


def _stage_map(victim_firing=True):
    return {
        1: {
            "10.0.0.2:6050": {
                "name": "healthy", "load": 0, "cap": 4,
                "host": "10.0.0.2", "port": 6050,
                "roofline": 0.21, "health": "ok",
            },
            "10.0.0.3:6050": {
                "name": "victim", "load": 1, "cap": 4,
                "host": "10.0.0.3", "port": 6050,
                "roofline": 0.05,
                **({"perf": 1} if victim_firing else {}),
                "health": "degraded",
                # an UNKNOWN future key: every consumer must pass it
                # through / ignore it (mixed-version contract)
                "future_key": {"x": 1},
            },
            # an OLD peer: gossips neither roofline nor perf
            "10.0.0.4:6050": {
                "name": "old", "load": 0, "cap": 4,
                "host": "10.0.0.4", "port": 6050,
            },
        },
    }


def test_collector_roofline_and_perf_columns():
    from inferd_tpu.tools.collector import FIELDS, stage_rows

    assert "roofline_worst" in FIELDS and "perf" in FIELDS
    (row,) = stage_rows(_stage_map(), ts=1.0)
    # worst replica = LOWEST live roofline fraction
    assert row["roofline_worst"] == 0.05
    assert row["perf"] == "10.0.0.3:6050"
    # mixed-version: a stage of only old peers renders blank cells
    old_only = {1: {"10.0.0.4:6050": _stage_map()[1]["10.0.0.4:6050"]}}
    (row,) = stage_rows(old_only, ts=1.0)
    assert row["roofline_worst"] == "" and row["perf"] == ""


def test_dashboard_roofline_and_perf_cells():
    from inferd_tpu.tools.dashboard import render_table

    text = render_table(_stage_map())
    assert "roof%" in text and "perf" in text
    assert "21.0%" in text and "5.0%" in text
    assert "!perf" in text
    # the old peer's row renders with blank markers, not a crash
    lines = [ln for ln in text.splitlines() if "10.0.0.4" in ln]
    assert lines and "!perf" not in lines[0]
    # sentinel quiet: no marker anywhere
    assert "!perf" not in render_table(_stage_map(victim_firing=False))


# ------------------------------------------------------ executor targets


def test_batched_executor_anatomy_target(tiny_engine_params=None):
    import jax

    from inferd_tpu.models import qwen3
    from inferd_tpu.runtime.batch_executor import BatchedExecutor

    params = qwen3.init_params(TINY, jax.random.PRNGKey(0))
    ex = BatchedExecutor(TINY, params, lanes=2, max_len=64)
    t = ex.anatomy_target()
    assert t["cfg"] is TINY and t["params"] is ex.engine.params
    assert set(t["phases"]) == {
        "embed", "attention", "mlp", "lm_head", "sampling", "kv_write"
    }
    assert t["paged_block_size"] == 0
    assert 0 < t["ctx"] <= 64
    ex_paged = BatchedExecutor(
        TINY, params, lanes=2, max_len=64, block_size=8
    )
    assert ex_paged.anatomy_target()["paged_block_size"] == 8


def test_stage_executor_anatomy_target_slices_phases():
    import jax

    from inferd_tpu.models import qwen3
    from inferd_tpu.parallel.stages import Manifest, extract_stage_params
    from inferd_tpu.runtime.stage_batch import BatchedStageExecutor

    params = qwen3.init_params(TINY, jax.random.PRNGKey(0))
    manifest = Manifest.even_split("tiny", 2)
    targets = {}
    for stage in (0, 1):
        spec = manifest.stage_spec(stage)
        ex = BatchedStageExecutor(
            TINY, spec, extract_stage_params(params, TINY, spec), lanes=2,
            max_len=64,
        )
        targets[stage] = ex.anatomy_target()
    t0, t1 = targets[0], targets[1]
    # first stage embeds, last stage unembeds + samples; both attend
    assert "embed" in t0["phases"] and "lm_head" not in t0["phases"]
    assert "embed" not in t1["phases"]
    assert {"lm_head", "sampling"} <= set(t1["phases"])
    for t in (t0, t1):
        assert {"attention", "mlp", "kv_write"} <= set(t["phases"])
        # the cfg is re-shaped to the SLICE's layer count so the scans
        # match params["layers"]
        assert t["cfg"].num_layers == len(
            jax.tree.leaves(t["params"]["layers"])[0]
        )


@pytest.mark.asyncio
async def test_collector_capture_fleet(tiny_parts, tmp_path):  # noqa: F811
    """Fleet-coordinated capture: the collector triggers one bounded
    capture_id-tagged /profile window on every node simultaneously, then
    merges the per-node spans into a Chrome-trace bundle + manifest. A
    node without --enable-profiling degrades to a recorded error instead
    of aborting the capture (mixed-fleet contract); the capturing node's
    `capture` span (bracketing the device trace) rides the bundle."""
    from test_node_e2e import _mk_node

    from inferd_tpu.tools.collector import capture_fleet

    nodes = [
        _mk_node(170, 0, 2, bootstrap_idx=170),
        _mk_node(171, 1, 2, bootstrap_idx=170),
    ]
    cap, no_cap = nodes[0], nodes[1]
    cap.enable_profiling = True
    cap.profiler.base_dir = str(tmp_path / "profiles")
    await _start_all(nodes)
    try:
        swarm_map = cap.dht.get_all(2)
        out_dir = str(tmp_path / "bundle")
        manifest = await capture_fleet(
            swarm_map, "cap-test", seconds=0.4, out_dir=out_dir
        )
        assert manifest["capture_id"] == "cap-test"
        rec_cap = manifest["nodes"][cap.info.node_id]
        rec_no = manifest["nodes"][no_cap.info.node_id]
        assert "cap-test" in rec_cap["dir"]
        assert "disabled" in rec_no["error"]
        # the device-trace artifacts landed under the tagged dir
        assert os.path.isdir(rec_cap["dir"])
        # the bundle: chrome trace with the capture span in it
        with open(os.path.join(out_dir, "cap-test.trace.json")) as f:
            chrome = json.load(f)
        cap_events = [
            ev for ev in chrome["traceEvents"]
            if ev["name"] == "capture"
            and ev["args"].get("capture_id") == "cap-test"
        ]
        assert len(cap_events) == 1
        assert cap_events[0]["dur"] >= 0.4 * 1e6 * 0.5
        # the capture journaled open AND close on the capturing node
        types = [ev["type"] for ev in cap.journal.events()]
        assert "profile.capture" in types
        assert "profile.capture_done" in types
        # profiler closed itself after the bounded window
        assert cap.profiler.active_dir is None
        assert not cap._capture_lock.locked()
    finally:
        await _stop_all(nodes)


@pytest.mark.asyncio
async def test_capture_fleet_empty_swarm(tmp_path):
    """A capture against an empty swarm map yields an empty manifest —
    the CLI turns that into a nonzero exit (an empty bundle must not
    read as a working capture)."""
    from inferd_tpu.tools.collector import capture_fleet

    manifest = await capture_fleet({}, "none", 0.1, str(tmp_path / "b"))
    assert manifest["nodes"] == {} and manifest["spans"] == 0


def test_live_anatomy_session_reuse():
    """The tick compiles each phase's scan ONCE per target signature and
    reuses it: the second tick on the same phase must be far cheaper
    than the first (the review finding: jit keys on function objects, so
    per-tick profile_step rebuilds would recompile every time)."""
    m = Metrics()
    target = _tiny_target(phases=("attention",))
    la = proflib.LiveAnatomy(m, lambda: target)
    t0 = time.perf_counter()
    la.tick_once()
    first = time.perf_counter() - t0
    t0 = time.perf_counter()
    la.tick_once()
    second = time.perf_counter() - t0
    assert second < first / 3, (first, second)
    assert la._session is not None
    # a changed signature (migration/ctx bucket) rebuilds
    la.reset_target()
    assert la._session is None


# ----------------------------------------------------------------- e2e


@pytest.mark.asyncio
async def test_live_anatomy_and_sentinel_e2e(tiny_parts, tmp_path):  # noqa: F811
    """THE e2e acceptance: a live 2-stage stage-lanes chain under steady
    traffic publishes non-empty anatomy.* / roofline.live_frac series at
    /metrics/history; a slowed stage-1 replica (injected compute
    slowdown) fires the perf.regression sentinel on that replica ONLY —
    journaled, gossiped (dashboard `!perf`, collector CSV column), and
    reproduced OFFLINE by `obs prof --check` over the flushed per-node
    artifacts + priors."""
    import aiohttp
    import numpy as np

    from inferd_tpu.control.dht import SwarmDHT
    from inferd_tpu.runtime import wire
    from inferd_tpu.runtime.node import Node, NodeInfo
    from inferd_tpu.tools.collector import stage_rows
    from inferd_tpu.tools.dashboard import render_table

    parts, _params = tiny_parts
    obs_dir = str(tmp_path / "obs")

    def mk(idx, stage, bootstrap_idx):
        info = NodeInfo(
            name=f"p{idx}", host="127.0.0.1", port=BASE + idx,
            stage=stage, num_stages=2, capacity=4, model_name="tiny",
        )
        dht = SwarmDHT(
            info.node_id, BASE + 100 + idx,
            bootstrap=(
                [("127.0.0.1", BASE + 100 + bootstrap_idx)]
                if idx != bootstrap_idx else []
            ),
            host="127.0.0.1", gossip_period_s=0.05, ttl_s=1.5,
        )
        return Node(
            info, TINY, parts, dht, backend="qwen3", max_len=64,
            rebalance_period_s=600.0, stage_lanes=2,
            # prof plane ON; the interval is parked long so the test
            # drives ticks deterministically
            prof_interval_s=3600.0,
            trace_dir=obs_dir,
        )

    nodes = [mk(160, 0, 160), mk(161, 1, 160), mk(162, 1, 160)]
    healthy, victim = nodes[1], nodes[2]
    await _start_all(nodes)
    loop = asyncio.get_running_loop()
    try:
        assert all(n.prof is not None for n in nodes)
        # inject the chaos slowdown: every device step on the victim
        # costs +40 ms (both the solo path and the window flush path)
        for name in ("process", "process_batch"):
            orig = getattr(victim.executor, name)

            def slowed(*a, _orig=orig, **kw):
                time.sleep(0.04)
                return _orig(*a, **kw)

            setattr(victim.executor, name, slowed)

        # steady traffic: one pinned session per stage-1 replica, a
        # prefill then a decode stream (each step books stage.tokens +
        # stage.compute_ms — the sentinel's live-cost series)
        hidden_sz = TINY.hidden_size
        async with aiohttp.ClientSession() as s:

            async def post(n, payload, sid):
                body = wire.pack(
                    {"stage": 1, "session_id": sid, "payload": payload,
                     "relay": False}
                )
                async with s.post(
                    f"http://127.0.0.1:{n.info.port}/forward", data=body
                ) as r:
                    assert r.status == 200, await r.text()

            for n in (healthy, victim):
                sid = f"sess-{n.info.port}"
                await post(n, {
                    "hidden": np.zeros((1, 4, hidden_sz), np.float32),
                    "start_pos": 0, "real_len": 4,
                }, sid)
                for step in range(24):
                    await post(n, {
                        "hidden": np.zeros((1, 1, hidden_sz), np.float32),
                        "start_pos": 4 + step, "real_len": 1,
                    }, sid)
            for n in nodes:
                n.tsdb.sample()

        # first tick: anatomy gauges + live_frac. The history
        # snapshot serializes on the loop thread (as _prof_loop does) —
        # the tick thread never touches the live rings
        for n in (healthy, victim):
            out = await loop.run_in_executor(
                None, n.prof.tick_once, n.tsdb.history()
            )
            assert "phase" in out, out
            n.tsdb.sample()

        # non-empty anatomy.*/roofline.live_frac series at the endpoint
        async with aiohttp.ClientSession() as s:
            async with s.get(
                f"http://127.0.0.1:{healthy.info.port}/metrics/history"
            ) as r:
                assert r.status == 200
                h = await r.json()
        assert tsdblib.validate_history(h) == []
        anat = [g for g in h["gauges"] if g.startswith("anatomy.")]
        assert anat, sorted(h["gauges"])
        assert any(h["gauges"][g][0] for g in anat)
        assert h["gauges"]["roofline.live_frac"][0]
        assert h["meta"]["preset"] == "tiny" and h["meta"]["chip"] == "cpu"

        # the committed prior = the HEALTHY replica's live cost; the
        # victim's +40 ms/step reads far past the 20% drift bar
        prior_tok_ms, _ = proflib.live_tok_ms(healthy.tsdb.history())
        key = healthy.prof.key_fn()
        assert key == victim.prof.key_fn()  # same (chip, config, stage)
        for n in (healthy, victim):
            n.prof.priors = {key: {"tok_ms": prior_tok_ms}}
            out = await loop.run_in_executor(
                None, n.prof.tick_once, n.tsdb.history()
            )
            if out.get("sentinel_changed"):
                n._health_cache = (0.0, None)
                n.announce()

        # fires on the victim ONLY
        assert victim.prof.sentinel_fired
        assert not healthy.prof.sentinel_fired
        assert any(
            ev["type"] == "perf.regression"
            for ev in victim.journal.events()
        )
        assert not any(
            ev["type"] == "perf.regression"
            for ev in healthy.journal.events()
        )

        # visible in gossip from ANOTHER node's view...
        for _ in range(100):
            rec = nodes[0].dht.get_stage(1).get(victim.info.node_id, {})
            if rec.get("perf"):
                break
            await asyncio.sleep(0.05)
        assert rec.get("perf") == 1, rec
        assert isinstance(rec.get("roofline"), float)
        swarm_map = nodes[0].dht.get_all(2)
        # ...in the dashboard (!perf marker on the victim's row only)...
        table = render_table(swarm_map)
        victim_rows = [
            ln for ln in table.splitlines() if victim.info.node_id in ln
        ]
        assert victim_rows and "!perf" in victim_rows[0]
        healthy_rows = [
            ln for ln in table.splitlines() if healthy.info.node_id in ln
        ]
        assert healthy_rows and "!perf" not in healthy_rows[0]
        # ...and in the collector CSV row for stage 1
        row = next(r for r in stage_rows(swarm_map) if r["stage"] == 1)
        assert row["perf"] == victim.info.node_id
        assert row["roofline_worst"] != ""

        # offline: flush artifacts + priors, re-run the sentinel check
        for n in nodes:
            n._flush_obs()
        with open(os.path.join(obs_dir, "priors.json"), "w") as f:
            json.dump(
                {"v": 1, "priors": {key: {"tok_ms": prior_tok_ms}}}, f
            )
        rc = obs_main(["prof", "--check", "--json", obs_dir])
        assert rc == 0
        report = proflib.check_paths([obs_dir])
        fired = [
            r["service"] for r in report["histories"]
            if (r.get("verdict") or {}).get("fired")
        ]
        assert fired == [victim.info.node_id]
        assert report["perf_regression_events"] >= 1
    finally:
        await _stop_all(nodes)
