"""Property-based fuzz of the wire format v1: the native C++ codec and the
normative pure-Python implementation must agree BYTE-FOR-BYTE on pack and
value-for-value on unpack, for arbitrary nested payloads and tensors —
mixed swarms (some nodes with the extension, some without) depend on it.
Also: unpack must reject corrupted bytes with clean errors, never crash or
execute anything (SURVEY B8)."""

import numpy as np
import pytest
from hypothesis import given, settings
from hypothesis import strategies as st

from inferd_tpu import native
from inferd_tpu.native import pyimpl
from inferd_tpu.runtime import wire

DTYPES = ["float32", "int32", "uint8", "bool", "bfloat16", "float16", "int64"]


def np_tensor(draw_dtype, shape):
    if draw_dtype == "bfloat16":
        import ml_dtypes

        return np.zeros(shape, dtype=ml_dtypes.bfloat16)
    return (np.arange(int(np.prod(shape)) or 1)[: int(np.prod(shape))]
            .reshape(shape)
            .astype(draw_dtype))


tensors = st.builds(
    np_tensor,
    st.sampled_from(DTYPES),
    st.lists(st.integers(0, 5), min_size=0, max_size=3).map(tuple),
)

scalars = st.one_of(
    st.none(),
    st.booleans(),
    st.integers(min_value=-(2**63), max_value=2**63 - 1),
    st.floats(allow_nan=False),
    st.text(max_size=40),
    st.binary(max_size=64),
)

payloads = st.recursive(
    st.one_of(scalars, tensors),
    lambda children: st.one_of(
        st.lists(children, max_size=4),
        st.dictionaries(st.text(max_size=16), children, max_size=4),
    ),
    max_leaves=12,
)


def _norm(x):
    """Canonical form for comparison (tensors -> (dtype, shape, bytes))."""
    if isinstance(x, np.ndarray):
        return ("t", str(x.dtype), x.shape, x.tobytes())
    if isinstance(x, list):
        return [_norm(v) for v in x]
    if isinstance(x, dict):
        return {k: _norm(v) for k, v in x.items()}
    return x


@settings(max_examples=200, deadline=None)
@given(payloads)
def test_roundtrip_python_codec(payload):
    blob = pyimpl.pack(payload, native.tensor_parts)
    out = pyimpl.unpack(blob, native.tensor_build)
    assert _norm(out) == _norm(payload)


@pytest.mark.skipif(native.codec is None, reason="native codec not built")
@settings(max_examples=200, deadline=None)
@given(payloads)
def test_native_matches_python_byte_for_byte(payload):
    py_blob = pyimpl.pack(payload, native.tensor_parts)
    nat_blob = native.codec.pack(payload)
    assert nat_blob == py_blob
    # and each implementation unpacks the other's bytes identically
    assert _norm(native.codec.unpack(py_blob)) == _norm(payload)
    assert _norm(pyimpl.unpack(nat_blob, native.tensor_build)) == _norm(payload)


@settings(max_examples=200, deadline=None)
@given(payloads, st.data())
def test_corruption_never_crashes(payload, data):
    blob = bytearray(pyimpl.pack(payload, native.tensor_parts))
    if not blob:
        return
    # flip one byte anywhere (magic, tag, length, or body)
    i = data.draw(st.integers(0, len(blob) - 1))
    blob[i] ^= data.draw(st.integers(1, 255))
    for impl in ("py", "native"):
        if impl == "native" and native.codec is None:
            continue
        try:
            if impl == "py":
                pyimpl.unpack(bytes(blob), native.tensor_build)
            else:
                native.codec.unpack(bytes(blob))
        except (ValueError, KeyError, OverflowError, MemoryError):
            pass  # clean rejection is the contract
