"""Multi-step fused decode (models/qwen3.decode_k): K tokens per dispatch
with on-device sampling, wired through all three executors.

The contract under test everywhere: decoding K tokens in ONE dispatch must
NEVER change what any session decodes — greedy streams are token-exact
against the K=1 client-side-argmax loop, sampled streams are token-exact
against chained K=1 on-device steps (same per-session key schedule), and
the stop-token / budget / replay edge cases degrade exactly like the
per-token path.
"""

import threading

import numpy as np
import pytest


@pytest.fixture(scope="module")
def solo_setup():
    import jax

    from inferd_tpu.config import TINY
    from inferd_tpu.models import qwen3
    from inferd_tpu.parallel.stages import StageSpec, extract_stage_params

    params = qwen3.init_params(TINY, jax.random.PRNGKey(0))
    spec = StageSpec(0, 1, 0, TINY.num_layers - 1)
    sp = extract_stage_params(params, TINY, spec)
    return TINY, params, spec, sp


PROMPT = [3, 7, 11, 19]
SAMPLING = {"temperature": 0.8, "top_k": 8, "top_p": 0.95}


def _mk_solo(solo_setup, max_len=64):
    from inferd_tpu.runtime.executor import Qwen3StageExecutor

    cfg, _params, spec, sp = solo_setup
    return Qwen3StageExecutor(cfg, spec, sp, max_len=max_len)


def _client_loop(ex, prompt, steps, eos=None):
    """The K=1 reference: per-token dispatch, client-side argmax."""
    r = ex.process("ref", {"tokens": [prompt], "start_pos": 0,
                           "real_len": len(prompt)})
    out = [int(np.argmax(r["logits"][0]))]
    pos = len(prompt)
    while len(out) < steps and (eos is None or out[-1] != eos):
        r = ex.process("ref", {"tokens": [[out[-1]]], "start_pos": pos,
                               "real_len": 1})
        out.append(int(np.argmax(r["logits"][0])))
        pos += 1
    ex.end_session("ref")
    return out


def _kstep_loop(ex, sid, prompt, steps, k, eos=None, sampling=None, seed=0):
    """Drive the multi-step path: decode_steps=k per request, chaining the
    returned PRNG key. Returns the emitted stream."""
    r = ex.process(sid, {"tokens": [prompt], "start_pos": 0,
                         "real_len": len(prompt)})
    out = [int(np.argmax(r["logits"][0]))]
    pos = len(prompt)
    key = None
    while len(out) < steps and (eos is None or out[-1] != eos):
        pl = {"tokens": [[out[-1]]], "start_pos": pos,
              "decode_steps": min(k, steps - len(out))}
        if eos is not None:
            pl["eos"] = eos
        if sampling is not None:
            pl["sampling"] = sampling
            pl["seed"] = seed
        if key is not None:
            pl["key"] = key
        rr = ex.process(sid, pl)
        assert rr["real_len"] == len(rr["tokens"][0])
        if rr["real_len"] == 0:
            break
        out.extend(int(t) for t in rr["tokens"][0])
        pos += rr["real_len"]
        key = rr.get("key")
    ex.end_session(sid)
    return out


# ---------------------------------------------------------------------------
# solo executor (runtime/executor.Qwen3StageExecutor)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("k", [2, 5, 8])
def test_solo_kstep_greedy_token_exact(solo_setup, k):
    ex = _mk_solo(solo_setup)
    ref = _client_loop(ex, PROMPT, 12)
    assert _kstep_loop(ex, f"k{k}", PROMPT, 12, k) == ref


@pytest.mark.parametrize("k", [2, 5, 8])
def test_solo_kstep_stop_token_mid_window(solo_setup, k):
    """eos fires inside a K window: the executor deactivates in-graph,
    commits only the tokens through the stop token (real_len < K), and
    the stream equals the K=1 loop with the same eos. Uses the SAMPLED
    path so the stream varies (tiny greedy degenerates to one token) and
    the stop genuinely lands mid-window."""
    ex = _mk_solo(solo_setup)
    ref = _kstep_loop(ex, "r1", PROMPT, 12, 1, sampling=SAMPLING, seed=7)
    eos = ref[5]  # force a stop mid-stream (and mid-window for k=5/8)
    cut = ref.index(eos) + 1
    assert 1 < cut <= 6  # genuinely mid-stream
    got = _kstep_loop(ex, f"k{k}", PROMPT, 12, k, eos=eos,
                      sampling=SAMPLING, seed=7)
    assert got == ref[:cut]


@pytest.mark.parametrize("k", [4, 8])
def test_solo_kstep_sampled_parity(solo_setup, k):
    """Sampling parity for the on-device greedy/temperature path: a K-step
    window with a chained per-session key emits bit-identical tokens to K
    chained single-step dispatches."""
    ex = _mk_solo(solo_setup)
    ref = _kstep_loop(ex, "s1", PROMPT, 10, 1, sampling=SAMPLING, seed=7)
    got = _kstep_loop(ex, f"s{k}", PROMPT, 10, k, sampling=SAMPLING, seed=7)
    assert got == ref
    assert len(set(ref)) > 1  # the sampled stream actually varies


def test_solo_kstep_budget_clamp_and_overflow(solo_setup):
    """K falls back toward K=1 at the KV budget boundary; a frontier at
    max_len raises BufferError like the per-token path."""
    ex = _mk_solo(solo_setup, max_len=10)
    r = ex.process("s", {"tokens": [PROMPT], "start_pos": 0, "real_len": 4})
    tok = int(np.argmax(r["logits"][0]))
    rr = ex.process("s", {"tokens": [[tok]], "start_pos": 4,
                          "decode_steps": 16})
    assert rr["decode_steps"] == 6 and rr["real_len"] == 6
    with pytest.raises(BufferError):
        ex.process("s", {"tokens": [[1]], "start_pos": 10, "decode_steps": 4})


def test_solo_kstep_replay_rollback(solo_setup):
    """A replayed K-step chunk (client re-sent after a lost response)
    rolls the frontier back and recomputes the identical window."""
    ex = _mk_solo(solo_setup)
    ex.process("s", {"tokens": [PROMPT], "start_pos": 0, "real_len": 4})
    r1 = ex.process("s", {"tokens": [[5]], "start_pos": 4, "decode_steps": 4})
    r2 = ex.process("s", {"tokens": [[5]], "start_pos": 4, "decode_steps": 4})
    assert r1["tokens"] == r2["tokens"]
    with pytest.raises(ValueError, match="out-of-order"):
        ex.process("s", {"tokens": [[5]], "start_pos": 50, "decode_steps": 4})


def test_multistage_stage_rejects_kstep(solo_setup):
    """A pipeline stage (not whole-model) must reject decode_steps: the
    next token depends on the other stages."""
    import jax

    from inferd_tpu.config import TINY
    from inferd_tpu.models import qwen3
    from inferd_tpu.parallel.stages import Manifest, extract_stage_params
    from inferd_tpu.runtime.executor import Qwen3StageExecutor

    params = qwen3.init_params(TINY, jax.random.PRNGKey(0))
    spec0 = list(Manifest.even_split("tiny", 2).stage_specs())[0]
    ex = Qwen3StageExecutor(
        TINY, spec0, extract_stage_params(params, TINY, spec0), max_len=64
    )
    ex.process("s", {"tokens": [PROMPT], "start_pos": 0, "real_len": 4})
    with pytest.raises(ValueError, match="single-stage"):
        ex.process("s", {"tokens": [[1]], "start_pos": 4, "decode_steps": 4})


# ---------------------------------------------------------------------------
# batched executor (runtime/batch_executor.BatchedExecutor)
# ---------------------------------------------------------------------------


def test_batched_kstep_cobatch_token_exact(solo_setup):
    """Concurrent sessions' K-step windows FUSE into one K-step scan per
    flush, and every stream equals its solo-executor run (same on-device
    sampler, same key chains). Also asserts token-true stats: a K-step
    entry counts K tokens, not 1."""
    from inferd_tpu.runtime.batch_executor import BatchedExecutor

    cfg, params, _spec, _sp = solo_setup
    prompts = {"a": [3, 7, 11, 19], "b": [5, 2], "c": [9, 9, 4]}
    steps, k = 9, 4

    refs = {}
    ex = _mk_solo(solo_setup)
    for i, (sid, p) in enumerate(prompts.items()):
        refs[sid] = _kstep_loop(ex, sid, p, steps, 1, sampling=SAMPLING,
                                seed=i)

    bx = BatchedExecutor(cfg, params, lanes=4, max_len=64, window_ms=30.0)
    state = {}
    for i, (sid, p) in enumerate(prompts.items()):
        r = bx.process(sid, {"tokens": [p], "start_pos": 0,
                             "real_len": len(p)})
        state[sid] = {"pos": len(p), "out": [int(np.argmax(r["logits"][0]))],
                      "key": None, "seed": i}
    while any(len(s["out"]) < steps for s in state.values()):
        results = {}

        def go(sid):
            s = state[sid]
            pl = {"tokens": [[s["out"][-1]]], "start_pos": s["pos"],
                  "real_len": 1,
                  "decode_steps": min(k, steps - len(s["out"])),
                  "sampling": SAMPLING, "seed": s["seed"]}
            if s["key"] is not None:
                pl["key"] = s["key"]
            results[sid] = bx.process(sid, pl)

        ths = [threading.Thread(target=go, args=(sid,)) for sid in prompts]
        for t in ths:
            t.start()
        for t in ths:
            t.join()
        for sid, rr in results.items():
            s = state[sid]
            s["out"].extend(int(x) for x in rr["tokens"][0])
            s["pos"] += rr["real_len"]
            s["key"] = rr["key"]
    for sid in prompts:
        assert state[sid]["out"] == refs[sid], sid
    st = bx.stats()
    # 3 sessions x 8 decode tokens = 24 tokens; token-true accounting
    # means batched_tokens counts them all even though far fewer K-step
    # DISPATCH entries were served
    assert st["batched_tokens"] == 24
    assert st["batched_steps"] < 24


def test_batched_kstep_interop_with_legacy_window(solo_setup):
    """A window mixing a classic logits-contract decode with K-step
    entries serves both: per-path dispatches under one device-lock hold,
    neither stream corrupted."""
    from inferd_tpu.runtime.batch_executor import BatchedExecutor

    cfg, params, _spec, _sp = solo_setup
    bx = BatchedExecutor(cfg, params, lanes=4, max_len=64, window_ms=40.0)
    pa, pb = [3, 7, 11, 19], [5, 2]
    ra = bx.process("a", {"tokens": [pa], "start_pos": 0, "real_len": 4})
    rb = bx.process("b", {"tokens": [pb], "start_pos": 0, "real_len": 2})
    ta, tb = int(np.argmax(ra["logits"][0])), int(np.argmax(rb["logits"][0]))
    results = {}

    def legacy():
        results["a"] = bx.process(
            "a", {"tokens": [[ta]], "start_pos": 4, "real_len": 1}
        )

    def kstep():
        results["b"] = bx.process(
            "b", {"tokens": [[tb]], "start_pos": 2, "real_len": 1,
                  "decode_steps": 3}
        )

    ths = [threading.Thread(target=legacy), threading.Thread(target=kstep)]
    for t in ths:
        t.start()
    for t in ths:
        t.join()
    assert "logits" in results["a"] and results["a"]["real_len"] == 1
    assert len(results["b"]["tokens"][0]) == 3

    # both sessions' streams stay exact vs solo greedy
    ex = _mk_solo(solo_setup)
    ref_a = _client_loop(ex, pa, 2)
    assert [ta, int(np.argmax(results["a"]["logits"][0]))] == ref_a
    ref_b = _kstep_loop(ex, "rb", pb, 4, 3)
    assert [tb] + [int(x) for x in results["b"]["tokens"][0]] == ref_b


def test_kstep_hi_not_overstated_on_early_eos(solo_setup):
    """The ring high-water mark after an eos-stopped K window covers the
    committed tokens plus the ONE frozen-frontier garbage slot — not the
    full K, which would spuriously trip the `hi - start_pos >
    RING_MARGIN` replay guard after an early stop."""
    from inferd_tpu.runtime.batch_executor import BatchedExecutor
    from inferd_tpu.runtime.executor import kstep_hi

    assert kstep_hi(10, 16, 16) == 26  # full window: k committed writes
    assert kstep_hi(10, 3, 16) == 14  # early eos: n committed + 1 garbage
    assert kstep_hi(10, 0, 4) == 11

    cfg, params, _spec, _sp = solo_setup
    ex = _mk_solo(solo_setup)
    ref = _client_loop(ex, PROMPT, 4)
    eos = ref[1]  # fires mid-window below
    bx = BatchedExecutor(cfg, params, lanes=2, max_len=64, window_ms=5.0)
    r = bx.process("s", {"tokens": [PROMPT], "start_pos": 0, "real_len": 4})
    t0 = int(np.argmax(r["logits"][0]))
    assert t0 == ref[0]
    rr = bx.process("s", {"tokens": [[t0]], "start_pos": 4, "real_len": 1,
                          "decode_steps": 8, "eos": eos})
    n = rr["real_len"]
    assert n < 8 and rr["tokens"][0][-1] == eos
    lane = bx._sessions["s"]
    assert bx._lane_hi[lane] == 4 + n + 1


def test_batched_kstep_group_failure_is_isolated(solo_setup):
    """Per-dispatch error isolation: a window holding two K-step sampling
    groups where one group's device dispatch dies must fail ONLY that
    group's sessions. The surviving group's results commit (and stay
    token-exact), and the dead group's lane frontier does not move, so the
    client's ordinary retry from its old frontier recovers the stream."""
    from inferd_tpu.runtime.batch_executor import BatchedExecutor

    cfg, params, _spec, _sp = solo_setup
    bx = BatchedExecutor(cfg, params, lanes=4, max_len=64, window_ms=40.0)
    pa, pb = [3, 7, 11, 19], [5, 2]
    ra = bx.process("a", {"tokens": [pa], "start_pos": 0, "real_len": 4})
    rb = bx.process("b", {"tokens": [pb], "start_pos": 0, "real_len": 2})
    ta, tb = int(np.argmax(ra["logits"][0])), int(np.argmax(rb["logits"][0]))

    real = bx.engine._decode_k_serve

    def boom(params, cache, toks, lengths, active, keys, eos, k, t, tk,
             tp, mp, ads=None):
        if t > 0:  # the sampled group dies BEFORE touching the device
            raise RuntimeError("injected group failure")
        return real(params, cache, toks, lengths, active, keys, eos, k, t,
                    tk, tp, mp, ads=ads)

    bx.engine._decode_k_serve = boom
    try:
        results, errors = {}, {}

        def greedy():
            results["a"] = bx.process(
                "a", {"tokens": [[ta]], "start_pos": 4, "real_len": 1,
                      "decode_steps": 3}
            )

        def sampled():
            try:
                bx.process(
                    "b", {"tokens": [[tb]], "start_pos": 2, "real_len": 1,
                          "decode_steps": 3, "sampling": SAMPLING,
                          "seed": 1}
                )
            except Exception as e:  # noqa: BLE001 -- the assertion target
                errors["b"] = e

        ths = [threading.Thread(target=greedy),
               threading.Thread(target=sampled)]
        for t in ths:
            t.start()
        for t in ths:
            t.join()
        assert "injected group failure" in str(errors["b"])
        assert len(results["a"]["tokens"][0]) == 3
    finally:
        bx.engine._decode_k_serve = real

    # survivor stream stays token-exact vs solo
    ex = _mk_solo(solo_setup)
    ref_a = _kstep_loop(ex, "ra", pa, 4, 3)
    assert [ta] + [int(x) for x in results["a"]["tokens"][0]] == ref_a
    # the failed lane never advanced: a plain retry from the client's old
    # frontier completes and matches the solo reference
    r2 = bx.process(
        "b", {"tokens": [[tb]], "start_pos": 2, "real_len": 1,
              "decode_steps": 3}
    )
    ref_b = _kstep_loop(ex, "rb", pb, 4, 3)
    assert [tb] + [int(x) for x in r2["tokens"][0]] == ref_b
    # token-true stats survive the failure: only the 3 + 3 tokens the
    # surviving dispatches really served are counted, never the failed
    # group's entries
    assert bx.stats()["batched_tokens"] == 6


def test_batched_kstep_device_failure_poisons_window_clearly(solo_setup):
    """Per-dispatch isolation only holds for HOST-side failures. A
    dispatch that dies DEVICE-side after the jit donated the cache
    leaves the shared KV buffers deleted: the window must stop
    dispatching and fail the remaining groups with a clear 'KV cache
    invalidated' error instead of handing them dead buffers."""
    import types

    from inferd_tpu.runtime.batch_executor import BatchedExecutor

    cfg, params, _spec, _sp = solo_setup
    bx = BatchedExecutor(cfg, params, lanes=4, max_len=64, window_ms=5.0)
    pa, pb = [3, 7, 11, 19], [5, 2]
    ra = bx.process("a", {"tokens": [pa], "start_pos": 0, "real_len": 4})
    rb = bx.process("b", {"tokens": [pb], "start_pos": 0, "real_len": 2})
    ta, tb = int(np.argmax(ra["logits"][0])), int(np.argmax(rb["logits"][0]))

    def boom(params, cache, toks, lens, ads=None):
        cache.k.delete()  # what a failed donating jit leaves behind
        raise RuntimeError("injected device failure")

    la, lb = bx._sessions["a"], bx._sessions["b"]
    ea = types.SimpleNamespace(payload=(la, ta, None), result=None,
                               error=None)
    ks = {"k": 3, "sampling": (0.0, 0, 1.0, 0.0), "eos": -1,
          "key": np.zeros(2, np.uint32)}
    eb = types.SimpleNamespace(payload=(lb, tb, ks), result=None,
                               error=None)
    bx.engine._decode_logits = boom
    bx._run_decode_batch([ea, eb])
    assert "injected device failure" in str(ea.error)
    assert "KV cache invalidated" in str(eb.error)
    assert eb.result is None


# ---------------------------------------------------------------------------
# shared primitive sanity (models/qwen3.decode_k)
# ---------------------------------------------------------------------------


def test_decode_k_counts_eos_token_then_freezes(solo_setup):
    """Direct decode_k semantics: the stop token itself is emitted and
    counted; subsequent steps freeze the row (n_new stops advancing) and
    its key chain keeps the documented always-split schedule."""
    import jax
    import jax.numpy as jnp

    from inferd_tpu.config import TINY
    from inferd_tpu.core.cache import KVCache
    from inferd_tpu.models import qwen3

    cfg, params, _spec, _sp = solo_setup
    cache = KVCache.create(cfg, cfg.num_layers, 1, 32)
    # prefill via the model forward to establish a frontier
    toks = jnp.asarray([PROMPT], jnp.int32)
    _logits, nc = qwen3.forward_cached(
        params, cfg, toks, None, cache, jnp.int32(0), real_end=4
    )
    import dataclasses

    cache = dataclasses.replace(nc, length=jnp.int32(4))
    lengths = jnp.asarray([4], jnp.int32)
    k = 6
    # greedy, no eos: full window commits
    c2, seq, n_new, _keys, _l, _t, _tl = qwen3.decode_k(
        params, cfg, jnp.asarray([PROMPT[-1]], jnp.int32), cache, lengths,
        jnp.ones((1,), bool), jnp.zeros((1, 2), jnp.uint32), k,
    )
    assert int(n_new[0]) == k
    stream = [int(x) for x in np.asarray(seq)[:, 0]]
    # rerun with eos = the 3rd emitted token: n_new stops there
    eos = stream[2]
    c3, seq2, n_new2, _k2, _l2, _t2, _tl2 = qwen3.decode_k(
        params, cfg, jnp.asarray([PROMPT[-1]], jnp.int32), c2, lengths,
        jnp.ones((1,), bool), jnp.zeros((1, 2), jnp.uint32), k,
        eos=jnp.int32(eos),
    )
    expect = stream.index(eos) + 1  # first occurrence stops the row
    assert int(n_new2[0]) == expect
    assert [int(x) for x in np.asarray(seq2)[:expect, 0]] == stream[:expect]
