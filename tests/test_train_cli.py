"""Data pipeline (inferd_tpu.data) and training CLI (tools/train.py):
windowed sampling determinism, mesh-parallel CLI runs on the virtual
device mesh, and checkpoint save/resume through the CLI surface."""

import json

import numpy as np
import pytest

from inferd_tpu import data as datalib
from inferd_tpu.tools.train import main as train_main, parse_train_mesh
from conftest import requires_native_shard_map


def test_dataset_windows_and_determinism(tmp_path):
    toks = np.arange(1000, dtype=np.uint16)
    path = tmp_path / "toks.npy"
    np.save(path, toks)
    ds = datalib.TokenDataset(str(path), seq_len=8)  # mmap path
    a1, t1 = ds.sample(np.random.RandomState(3), mb=2, batch=3)
    a2, t2 = ds.sample(np.random.RandomState(3), mb=2, batch=3)
    assert a1.shape == t1.shape == (2, 3, 8)
    assert a1.dtype == np.int32
    np.testing.assert_array_equal(a1, a2)  # same seed -> same batch
    # target is the next-token shift of the input window
    np.testing.assert_array_equal(t1, a1 + 1)


def test_dataset_minimum_corpus_and_last_offset():
    """The smallest accepted corpus (seq_len+1) must sample, and the final
    token must be reachable as a target (offset len-s-1 drawn)."""
    ds = datalib.TokenDataset(np.arange(9, dtype=np.int32), seq_len=8)
    a, t = ds.sample(np.random.RandomState(0), mb=1, batch=1)
    np.testing.assert_array_equal(a[0, 0], np.arange(8))
    assert t[0, 0, -1] == 8
    ds2 = datalib.TokenDataset(np.arange(12, dtype=np.int32), seq_len=8)
    seen = {
        int(ds2.sample(np.random.RandomState(i), 1, 1)[0][0, 0, 0])
        for i in range(64)
    }
    assert 3 in seen  # the last valid offset (len - s - 1) is reachable


def test_dataset_skip_matches_uninterrupted_stream():
    """batches(skip=N) yields the same tail an uninterrupted stream from the
    same seed would — the crash-equivalent reproducibility contract a
    --resume'd training run relies on."""
    ds = datalib.TokenDataset(np.arange(500, dtype=np.int32), seq_len=8)
    full = list(ds.batches(mb=2, batch=3, steps=5, seed=7))
    tail = list(ds.batches(mb=2, batch=3, steps=3, seed=7, skip=2))
    for (a1, t1), (a2, t2) in zip(full[2:], tail):
        np.testing.assert_array_equal(a1, a2)
        np.testing.assert_array_equal(t1, t2)


def test_dataset_validation():
    with pytest.raises(ValueError, match="1-D"):
        datalib.TokenDataset(np.zeros((4, 4), np.int32), seq_len=2)
    with pytest.raises(ValueError, match="at least"):
        datalib.TokenDataset(np.zeros(4, np.int32), seq_len=8)
    with pytest.raises(ValueError, match="integer"):
        datalib.TokenDataset(np.zeros(64, np.float32), seq_len=8)


def test_parse_train_mesh():
    p = parse_train_mesh("dp=2,pp=2,tp=2")
    assert (p.dp, p.pp, p.tp) == (2, 2, 2) and p.num_devices == 8
    assert parse_train_mesh("").num_devices == 1
    with pytest.raises(ValueError):
        parse_train_mesh("zz=2")


@requires_native_shard_map
def test_train_cli_synthetic_mesh(capsys):
    """End-to-end CLI run on a dp=2,pp=2 mesh: loss finite, JSON summary."""
    rc = train_main([
        "--model", "tiny", "--random-init", "--synthetic",
        "--steps", "3", "--mb", "2", "--batch", "2", "--seq", "16",
        "--mesh", "dp=2,pp=2", "--optimizer", "adam",
        "--log-every", "0", "--device", "cpu",
    ])
    assert rc == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["steps"] == 3
    assert np.isfinite(out["final_loss"])


@requires_native_shard_map
def test_train_cli_resume(tmp_path, capsys):
    """--resume continues from the snapshot: a 2+2 run's final state equals
    the step counter having advanced past the restore point."""
    ck = str(tmp_path / "ck")
    common = [
        "--model", "tiny", "--random-init", "--synthetic",
        "--mb", "1", "--batch", "2", "--seq", "16",
        "--optimizer", "adam", "--checkpoint-dir", ck,
        "--save-every", "2", "--log-every", "0", "--device", "cpu",
    ]
    assert train_main(common + ["--steps", "2"]) == 0
    capsys.readouterr()
    assert train_main(common + ["--steps", "4", "--resume"]) == 0
    err = capsys.readouterr()
    from inferd_tpu.parallel import checkpoint as ckptlib

    assert ckptlib.latest_step(ck) == 4
    assert "resumed from step 2" in err.err
