# jaxlint: file-disable=J003 -- test code: loops here sync per-iteration to ASSERT on values; they are verification loops, not serving hot paths
"""Model-correctness tests: shapes, cache/cacheless consistency, stage
splitting, and golden-logits parity against HF transformers — the test the
reference never had (SURVEY.md §4: no model-correctness tests there)."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from inferd_tpu.config import TINY, TINY_MOE, ModelConfig
from inferd_tpu.models import qwen3
from inferd_tpu.models.loader import params_from_hf_state_dict


@pytest.fixture(scope="module")
def tiny_params():
    return qwen3.init_params(TINY, jax.random.PRNGKey(0))


def test_forward_shapes(tiny_params):
    tokens = jnp.array([[1, 2, 3, 4, 5]])
    logits, _, _ = qwen3.forward(tiny_params, TINY, tokens)
    assert logits.shape == (1, 5, TINY.vocab_size)
    assert logits.dtype == jnp.float32


def test_moe_forward_shapes():
    params = qwen3.init_params(TINY_MOE, jax.random.PRNGKey(0))
    tokens = jnp.array([[1, 2, 3]])
    logits, _, _ = qwen3.forward(params, TINY_MOE, tokens)
    assert logits.shape == (1, 3, TINY_MOE.vocab_size)
    assert np.all(np.isfinite(logits))


def test_cache_matches_cacheless(tiny_params):
    """Prefill+decode through a preallocated KV buffer must produce the same
    logits as a cache-free full-sequence forward."""
    cfg = TINY
    tokens = jax.random.randint(jax.random.PRNGKey(1), (1, 7), 0, cfg.vocab_size)
    full_logits, _, _ = qwen3.forward(tiny_params, cfg, tokens)

    max_len = 16
    k = jnp.zeros((cfg.num_layers, 1, max_len, cfg.num_kv_heads, cfg.head_dim), cfg.jnp_dtype)
    v = jnp.zeros_like(k)

    # prefill first 4 tokens
    pos = jnp.arange(4)[None, :]
    logits_p, k, v = qwen3.forward(
        tiny_params, cfg, tokens[:, :4], pos, k, v, jnp.int32(0)
    )
    np.testing.assert_allclose(logits_p, full_logits[:, :4], rtol=1e-4, atol=1e-4)

    # decode tokens 4..6 one at a time
    for t in range(4, 7):
        pos = jnp.array([[t]])
        logits_d, k, v = qwen3.forward(
            tiny_params, cfg, tokens[:, t : t + 1], pos, k, v, jnp.int32(t)
        )
        np.testing.assert_allclose(
            logits_d[:, 0], full_logits[:, t], rtol=1e-4, atol=1e-4
        )


def test_cacheless_offset_positions_stay_causal(tiny_params):
    """A cache-free forward over a chunk with offset absolute positions must
    still be causal: token i's output can't depend on tokens > i."""
    cfg = TINY
    tokens = jax.random.randint(jax.random.PRNGKey(3), (1, 6), 0, cfg.vocab_size)
    positions = 10 + jnp.arange(6)[None, :]
    hidden = qwen3.embed(tiny_params, tokens, cfg)
    out_full, _, _ = qwen3.forward_layers(tiny_params["layers"], cfg, hidden, positions)

    # perturb the last token; earlier outputs must be unchanged
    tokens2 = tokens.at[0, -1].set((int(tokens[0, -1]) + 1) % cfg.vocab_size)
    hidden2 = qwen3.embed(tiny_params, tokens2, cfg)
    out2, _, _ = qwen3.forward_layers(tiny_params["layers"], cfg, hidden2, positions)
    np.testing.assert_allclose(
        np.asarray(out_full[:, :-1]), np.asarray(out2[:, :-1]), rtol=1e-5, atol=1e-5
    )


def test_stage_split_matches_full(tiny_params):
    """Running layers as two sliced stages == running the full stack."""
    cfg = TINY
    tokens = jax.random.randint(jax.random.PRNGKey(2), (2, 5), 0, cfg.vocab_size)
    positions = jnp.broadcast_to(jnp.arange(5), tokens.shape)
    hidden = qwen3.embed(tiny_params, tokens, cfg)
    full, _, _ = qwen3.forward_layers(tiny_params["layers"], cfg, hidden, positions)

    s0 = qwen3.slice_layers(tiny_params["layers"], 0, 2)
    s1 = qwen3.slice_layers(tiny_params["layers"], 2, 4)
    h, _, _ = qwen3.forward_layers(s0, cfg, hidden, positions)
    h, _, _ = qwen3.forward_layers(s1, cfg, h, positions)
    np.testing.assert_allclose(np.asarray(h), np.asarray(full), rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("moe", [False, True], ids=["dense", "moe"])
def test_golden_parity_vs_hf(moe):
    """Logits parity vs HF transformers Qwen3 on a randomly-initialized tiny
    config (offline — no downloads). Covers RMSNorm/RoPE/GQA-with-qk-norm/
    SwiGLU(/MoE routing) numerics end to end."""
    torch = pytest.importorskip("torch")
    import transformers

    if moe:
        hf_cfg = transformers.Qwen3MoeConfig(
            vocab_size=256, hidden_size=64, intermediate_size=128,
            num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
            head_dim=16, max_position_embeddings=512, rope_theta=1e6,
            tie_word_embeddings=True, num_experts=8, num_experts_per_tok=2,
            moe_intermediate_size=32, norm_topk_prob=True, decoder_sparse_step=1,
            mlp_only_layers=[],
        )
        hf_model = transformers.Qwen3MoeForCausalLM(hf_cfg)
        cfg = ModelConfig(
            name="tiny-moe-parity", vocab_size=256, hidden_size=64,
            intermediate_size=128, num_layers=2, num_heads=4, num_kv_heads=2,
            head_dim=16, max_position_embeddings=512, dtype="float32",
            num_experts=8, num_experts_per_tok=2, moe_intermediate_size=32,
        )
    else:
        hf_cfg = transformers.Qwen3Config(
            vocab_size=256, hidden_size=64, intermediate_size=128,
            num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
            head_dim=16, max_position_embeddings=512, rope_theta=1e6,
            tie_word_embeddings=True,
        )
        hf_model = transformers.Qwen3ForCausalLM(hf_cfg)
        cfg = ModelConfig(
            name="tiny-parity", vocab_size=256, hidden_size=64,
            intermediate_size=128, num_layers=2, num_heads=4, num_kv_heads=2,
            head_dim=16, max_position_embeddings=512, dtype="float32",
        )

    hf_model.eval()
    params = params_from_hf_state_dict(cfg, hf_model.state_dict())

    tokens_np = np.array([[3, 17, 42, 99, 7, 250]], dtype=np.int64)
    with torch.no_grad():
        hf_logits = hf_model(torch.from_numpy(tokens_np)).logits.float().numpy()

    logits, _, _ = qwen3.forward(params, cfg, jnp.asarray(tokens_np))
    np.testing.assert_allclose(np.asarray(logits), hf_logits, rtol=2e-4, atol=2e-4)


def test_qwen2_golden_parity_vs_hf():
    """Logits parity vs HF transformers Qwen2 (no q/k-norm, attention bias
    — the reference swarm path's model family, petals/inferd.yaml:1)."""
    torch = pytest.importorskip("torch")
    import transformers

    hf_cfg = transformers.Qwen2Config(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=512, rope_theta=1e6, tie_word_embeddings=True,
    )
    hf_model = transformers.Qwen2ForCausalLM(hf_cfg)
    cfg = ModelConfig(
        name="tiny-qwen2-parity", vocab_size=256, hidden_size=64,
        intermediate_size=128, num_layers=2, num_heads=4, num_kv_heads=2,
        head_dim=16, max_position_embeddings=512, dtype="float32",
        qk_norm=False, attn_bias=True,
    )
    hf_model.eval()
    # biases must actually be exercised: HF inits them to zero, so nudge
    with torch.no_grad():
        for layer in hf_model.model.layers:
            for proj in (layer.self_attn.q_proj, layer.self_attn.k_proj, layer.self_attn.v_proj):
                proj.bias.normal_(0.0, 0.1)
    params = params_from_hf_state_dict(cfg, hf_model.state_dict())

    tokens_np = np.array([[3, 17, 42, 99, 7, 250]], dtype=np.int64)
    with torch.no_grad():
        hf_logits = hf_model(torch.from_numpy(tokens_np)).logits.float().numpy()
    logits, _, _ = qwen3.forward(params, cfg, jnp.asarray(tokens_np))
    np.testing.assert_allclose(np.asarray(logits), hf_logits, rtol=2e-4, atol=2e-4)


def test_qwen2_cache_matches_cacheless():
    """KV-cached decode == full recompute for the qwen2 variant."""
    from inferd_tpu.config import TINY_QWEN2
    from inferd_tpu.core.cache import KVCache

    cfg = TINY_QWEN2
    params = qwen3.init_params(cfg, jax.random.PRNGKey(1))
    toks = jax.random.randint(jax.random.PRNGKey(2), (1, 6), 0, cfg.vocab_size, dtype=jnp.int32)
    cache = KVCache.create(cfg, cfg.num_layers, 1, 16)
    logits, k, v = qwen3.forward(params, cfg, toks, k_cache=cache.k, v_cache=cache.v, cache_write_pos=cache.length)
    cache = KVCache(k=k, v=v, length=cache.length + 6)
    nxt = jnp.argmax(logits[:, -1], -1)[:, None]
    cached = []
    full = toks
    for _ in range(4):
        cached.append(int(nxt[0, 0]))
        logits, k, v = qwen3.forward(params, cfg, nxt, k_cache=cache.k, v_cache=cache.v, cache_write_pos=cache.length)
        cache = KVCache(k=k, v=v, length=cache.length + 1)
        nxt = jnp.argmax(logits[:, -1], -1)[:, None]
    uncached = []
    for _ in range(4):
        logits, _, _ = qwen3.forward(params, cfg, full)
        t = jnp.argmax(logits[:, -1], -1)[:, None]
        uncached.append(int(t[0, 0]))
        full = jnp.concatenate([full, t], axis=1)
    assert cached == uncached


def test_llama_golden_parity_vs_hf():
    """Logits parity vs HF transformers Llama (no q/k-norm, no attention
    bias, llama3 frequency-dependent RoPE scaling — the Llama-3.1+ family,
    added scope beyond the reference's Qwen2/Qwen3)."""
    torch = pytest.importorskip("torch")
    import transformers

    hf_cfg = transformers.LlamaConfig(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        head_dim=16, max_position_embeddings=512, rope_theta=5e5,
        tie_word_embeddings=True, attention_bias=False, mlp_bias=False,
        rope_scaling={
            "rope_type": "llama3", "factor": 8.0, "low_freq_factor": 1.0,
            "high_freq_factor": 4.0, "original_max_position_embeddings": 128,
        },
    )
    hf_model = transformers.LlamaForCausalLM(hf_cfg)
    cfg = ModelConfig(
        name="tiny-llama-parity", vocab_size=256, hidden_size=64,
        intermediate_size=128, num_layers=2, num_heads=4, num_kv_heads=2,
        head_dim=16, max_position_embeddings=512, rope_theta=5e5,
        dtype="float32", qk_norm=False, attn_bias=False,
        rope_scaling="llama3", rope_scaling_factor=8.0,
        rope_low_freq_factor=1.0, rope_high_freq_factor=4.0,
        rope_original_max_position=128,
    )
    hf_model.eval()
    params = params_from_hf_state_dict(cfg, hf_model.state_dict())

    # positions past rope_original_max_position exercise the scaled bands
    tokens_np = np.array([[3, 17, 42, 99, 7, 250] * 24], dtype=np.int64)  # S=144
    with torch.no_grad():
        hf_logits = hf_model(torch.from_numpy(tokens_np)).logits.float().numpy()
    logits, _, _ = qwen3.forward(params, cfg, jnp.asarray(tokens_np))
    np.testing.assert_allclose(np.asarray(logits), hf_logits, rtol=2e-4, atol=2e-4)


def test_llama_cache_matches_cacheless():
    """KV-cached decode == full recompute for the llama variant (exercises
    the scaled-rope path through the cache plumbing)."""
    from inferd_tpu.config import TINY_LLAMA
    from inferd_tpu.core.cache import KVCache

    cfg = TINY_LLAMA
    params = qwen3.init_params(cfg, jax.random.PRNGKey(3))
    toks = jax.random.randint(jax.random.PRNGKey(4), (1, 10), 0, cfg.vocab_size, jnp.int32)

    full_logits, _, _ = qwen3.forward(params, cfg, toks)

    cache = KVCache.create(cfg, cfg.num_layers, 1, 32, ring=False)
    logits_p, nk, nv = qwen3.forward(params, cfg, toks[:, :6], None, cache.k, cache.v, jnp.int32(0))
    cache = KVCache(k=nk, v=nv, length=jnp.int32(6))
    outs = [logits_p[:, -1]]
    for i in range(6, 10):
        logits_i, nk, nv = qwen3.forward(
            params, cfg, toks[:, i : i + 1], None, cache.k, cache.v, cache.length
        )
        cache = KVCache(k=nk, v=nv, length=cache.length + 1)
        outs.append(logits_i[:, 0])
    got = jnp.stack(outs, axis=1)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(full_logits[:, 5:10]), rtol=2e-4, atol=2e-4
    )


def test_mixtral_golden_parity_vs_hf():
    """Logits parity vs HF transformers Mixtral — Llama-like attention with
    the block_sparse_moe naming (w1/w3/w2) mapped by the loader; routing is
    the same softmax-all -> top-k -> renormalize as Qwen3-MoE."""
    torch = pytest.importorskip("torch")
    import transformers

    hf_cfg = transformers.MixtralConfig(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        head_dim=16, max_position_embeddings=512, rope_theta=1e6,
        tie_word_embeddings=False, num_local_experts=8, num_experts_per_tok=2,
        sliding_window=None, attn_implementation="eager",
    )
    hf_model = transformers.MixtralForCausalLM(hf_cfg)
    cfg = ModelConfig(
        name="tiny-mixtral-parity", vocab_size=256, hidden_size=64,
        intermediate_size=128, num_layers=2, num_heads=4, num_kv_heads=2,
        head_dim=16, max_position_embeddings=512, rope_theta=1e6,
        rms_norm_eps=1e-5,  # Mixtral's default (Qwen uses 1e-6)
        dtype="float32", qk_norm=False, attn_bias=False,
        tie_word_embeddings=False, num_experts=8, num_experts_per_tok=2,
        moe_intermediate_size=128, norm_topk_prob=True,
    )
    hf_model.eval()
    params = params_from_hf_state_dict(cfg, hf_model.state_dict())

    tokens_np = np.array([[3, 17, 42, 99, 7, 250]], dtype=np.int64)
    with torch.no_grad():
        hf_logits = hf_model(torch.from_numpy(tokens_np)).logits.float().numpy()
    logits, _, _ = qwen3.forward(params, cfg, jnp.asarray(tokens_np))
    np.testing.assert_allclose(np.asarray(logits), hf_logits, rtol=2e-4, atol=2e-4)


def test_gpt_oss_golden_parity_vs_hf():
    """Logits parity vs HF transformers GptOss — the full recipe: attention
    sinks, q/k/v/o biases, YaRN rope scaling, sliding window on even
    layers, topk-then-softmax routing, and biased clamped-GLU experts
    (alpha=1.702, limit=7). S=24 > window=8 so the local/global alternation
    and the sink's effect on long contexts are both exercised."""
    torch = pytest.importorskip("torch")
    import transformers

    hf_cfg = transformers.GptOssConfig(
        vocab_size=256, hidden_size=64, intermediate_size=32,
        num_hidden_layers=4, num_attention_heads=4, num_key_value_heads=2,
        head_dim=16, max_position_embeddings=512, rope_theta=150000.0,
        tie_word_embeddings=False, num_local_experts=8, num_experts_per_tok=2,
        sliding_window=8, attention_bias=True, rms_norm_eps=1e-5,
        rope_scaling={
            "rope_type": "yarn", "factor": 32.0, "beta_fast": 32.0,
            "beta_slow": 1.0, "truncate": False,
            "original_max_position_embeddings": 64,
        },
        attn_implementation="eager",
    )
    hf_model = transformers.GptOssForCausalLM(hf_cfg)
    # sinks/biases init to zero or empty: randomize so they're exercised
    with torch.no_grad():
        for layer in hf_model.model.layers:
            layer.self_attn.sinks.normal_(0.0, 1.0)
            layer.self_attn.o_proj.bias.normal_(0.0, 0.1)
            for proj in (layer.self_attn.q_proj, layer.self_attn.k_proj,
                         layer.self_attn.v_proj):
                proj.bias.normal_(0.0, 0.1)
            layer.mlp.router.bias.normal_(0.0, 0.1)
            layer.mlp.experts.gate_up_proj_bias.normal_(0.0, 0.1)
            layer.mlp.experts.down_proj_bias.normal_(0.0, 0.1)
    hf_model.eval()
    cfg = ModelConfig(
        name="tiny-gptoss-parity", vocab_size=256, hidden_size=64,
        intermediate_size=32, num_layers=4, num_heads=4, num_kv_heads=2,
        head_dim=16, max_position_embeddings=512, rope_theta=150000.0,
        rms_norm_eps=1e-5, dtype="float32", qk_norm=False,
        attn_bias=True, o_bias=True, attn_sinks=True, sliding_window=8,
        tie_word_embeddings=False,
        rope_scaling="yarn", rope_scaling_factor=32.0,
        rope_original_max_position=64, rope_beta_fast=32.0,
        rope_beta_slow=1.0, rope_truncate=False,
        num_experts=8, num_experts_per_tok=2, moe_intermediate_size=32,
        moe_router_mode="topk_softmax", router_bias=True, moe_bias=True,
        swiglu_limit=7.0,
    )
    params = params_from_hf_state_dict(cfg, hf_model.state_dict())

    tokens_np = np.array([[3, 17, 42, 99, 7, 250] * 4], dtype=np.int64)  # S=24
    with torch.no_grad():
        hf_logits = hf_model(torch.from_numpy(tokens_np)).logits.float().numpy()
    logits, _, _ = qwen3.forward(params, cfg, jnp.asarray(tokens_np))
    np.testing.assert_allclose(np.asarray(logits), hf_logits, rtol=3e-4, atol=3e-4)


def test_gpt_oss_cache_matches_cacheless():
    """KV-cached decode == full recompute for the gpt-oss variant (sinks +
    sliding window + yarn through the cache plumbing)."""
    from inferd_tpu.config import TINY_GPT_OSS
    from inferd_tpu.core.cache import KVCache

    cfg = TINY_GPT_OSS
    params = qwen3.init_params(cfg, jax.random.PRNGKey(12))
    toks = jax.random.randint(jax.random.PRNGKey(13), (1, 14), 0, cfg.vocab_size, jnp.int32)

    full_logits, _, _ = qwen3.forward(params, cfg, toks)

    cache = KVCache.create(cfg, cfg.num_layers, 1, 32, ring=False)
    logits_p, nk, nv = qwen3.forward(params, cfg, toks[:, :6], None, cache.k, cache.v, jnp.int32(0))
    cache = KVCache(k=nk, v=nv, length=jnp.int32(6))
    outs = [logits_p[:, -1]]
    for i in range(6, 14):  # decode walks past the window of 8
        logits_i, nk, nv = qwen3.forward(
            params, cfg, toks[:, i : i + 1], None, cache.k, cache.v, cache.length
        )
        cache = KVCache(k=nk, v=nv, length=cache.length + 1)
        outs.append(logits_i[:, 0])
    got = jnp.stack(outs, axis=1)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(full_logits[:, 5:14]), rtol=2e-4, atol=2e-4
    )


def test_mxfp4_dequant_matches_transformers():
    """loader.dequant_mxfp4 == transformers' convert_moe_packed_tensors on
    random packed tensors (the official GPT-OSS checkpoint storage)."""
    torch = pytest.importorskip("torch")
    from transformers.integrations.mxfp4 import convert_moe_packed_tensors

    from inferd_tpu.models.loader import dequant_mxfp4

    rng = np.random.RandomState(0)
    blocks = rng.randint(0, 256, size=(3, 8, 2, 16), dtype=np.uint8)
    scales = rng.randint(118, 136, size=(3, 8, 2), dtype=np.uint8)
    want = (
        convert_moe_packed_tensors(
            torch.from_numpy(blocks), torch.from_numpy(scales),
            dtype=torch.float32,
        )
        .float()
        .numpy()
    )
    got = dequant_mxfp4(blocks, scales)
    np.testing.assert_allclose(got, want, rtol=0, atol=0)


def test_gpt_oss_mxfp4_state_dict_loads():
    """A state dict with *_blocks/*_scales expert tensors (the official
    GPT-OSS storage) loads to the same params as its dequantized-dense
    equivalent."""
    from inferd_tpu.config import TINY_GPT_OSS
    from inferd_tpu.models.loader import dequant_mxfp4

    cfg = TINY_GPT_OSS  # H=64, D=32: gate_up rows=64 packs [G=2, B=16]
    rng = np.random.RandomState(1)
    base = qwen3.init_params(cfg, jax.random.PRNGKey(0))

    def common(i):
        sd = {}
        L = cfg.num_layers
        sd[f"model.layers.{i}.input_layernorm.weight"] = np.asarray(base["layers"]["input_norm"][i])
        sd[f"model.layers.{i}.post_attention_layernorm.weight"] = np.asarray(base["layers"]["post_norm"][i])
        for nm in ("q", "k", "v", "o"):
            sd[f"model.layers.{i}.self_attn.{nm}_proj.weight"] = np.asarray(
                base["layers"][f"{nm}_proj"][i]
            ).T
        for nm in ("q", "k", "v"):
            sd[f"model.layers.{i}.self_attn.{nm}_proj.bias"] = np.asarray(base["layers"][f"{nm}_bias"][i])
        sd[f"model.layers.{i}.self_attn.o_proj.bias"] = np.asarray(base["layers"]["o_bias"][i])
        sd[f"model.layers.{i}.self_attn.sinks"] = np.asarray(base["layers"]["sinks"][i])
        sd[f"model.layers.{i}.mlp.router.weight"] = np.asarray(base["layers"]["router"][i]).T
        sd[f"model.layers.{i}.mlp.router.bias"] = np.asarray(base["layers"]["router_bias"][i])
        sd[f"model.layers.{i}.mlp.experts.gate_up_proj_bias"] = rng.normal(
            0, 0.1, (cfg.num_experts, 2 * cfg.moe_intermediate_size)
        ).astype(np.float32)
        sd[f"model.layers.{i}.mlp.experts.down_proj_bias"] = rng.normal(
            0, 0.1, (cfg.num_experts, cfg.hidden_size)
        ).astype(np.float32)
        return sd

    sd_packed, sd_dense = {}, {}
    E, H, D = cfg.num_experts, cfg.hidden_size, cfg.moe_intermediate_size
    for i in range(cfg.num_layers):
        c = common(i)
        sd_packed.update(c)
        sd_dense.update(c)
        gu_blocks = rng.randint(0, 256, (E, 2 * D, H // 32, 16), dtype=np.uint8)
        gu_scales = rng.randint(120, 130, (E, 2 * D, H // 32), dtype=np.uint8)
        dn_blocks = rng.randint(0, 256, (E, H, D // 32, 16), dtype=np.uint8)
        dn_scales = rng.randint(120, 130, (E, H, D // 32), dtype=np.uint8)
        pre = f"model.layers.{i}.mlp.experts."
        sd_packed[pre + "gate_up_proj_blocks"] = gu_blocks
        sd_packed[pre + "gate_up_proj_scales"] = gu_scales
        sd_packed[pre + "down_proj_blocks"] = dn_blocks
        sd_packed[pre + "down_proj_scales"] = dn_scales
        sd_dense[pre + "gate_up_proj"] = dequant_mxfp4(gu_blocks, gu_scales)
        sd_dense[pre + "down_proj"] = dequant_mxfp4(dn_blocks, dn_scales)
    for sd in (sd_packed, sd_dense):
        sd["model.embed_tokens.weight"] = np.asarray(base["embed"])
        sd["model.norm.weight"] = np.asarray(base["final_norm"])
        sd["lm_head.weight"] = np.asarray(base["lm_head"]).T

    pa = params_from_hf_state_dict(cfg, sd_packed)
    pb = params_from_hf_state_dict(cfg, sd_dense)
    for path, leaf in jax.tree_util.tree_leaves_with_path(pa):
        other = dict(jax.tree_util.tree_leaves_with_path(pb))[path]
        np.testing.assert_array_equal(np.asarray(leaf), np.asarray(other))
    logits, _, _ = qwen3.forward(pa, cfg, jnp.asarray([[3, 7, 11]], jnp.int32))
    assert np.all(np.isfinite(np.asarray(logits)))


def test_gemma2_golden_parity_vs_hf():
    """Logits parity vs HF transformers Gemma2 — the architecturally most
    distinct family in the zoo: sandwich norms, (1+w) RMSNorm, GeGLU,
    scaled embeddings, attn/final logit softcapping, query_pre_attn_scalar
    score scale, and sliding-window attention on even layers. The sequence
    (S=24) exceeds the window (8) so the local/global alternation is
    actually exercised."""
    torch = pytest.importorskip("torch")
    import transformers

    hf_cfg = transformers.Gemma2Config(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=4, num_attention_heads=4, num_key_value_heads=2,
        head_dim=16, max_position_embeddings=512, rope_theta=1e4,
        tie_word_embeddings=True, query_pre_attn_scalar=32.0,
        attn_logit_softcapping=50.0, final_logit_softcapping=30.0,
        sliding_window=8, hidden_activation="gelu_pytorch_tanh",
        attn_implementation="eager",
    )
    hf_model = transformers.Gemma2ForCausalLM(hf_cfg)
    cfg = ModelConfig(
        name="tiny-gemma2-parity", vocab_size=256, hidden_size=64,
        intermediate_size=128, num_layers=4, num_heads=4, num_kv_heads=2,
        head_dim=16, max_position_embeddings=512, rope_theta=1e4,
        dtype="float32", qk_norm=False, attn_bias=False,
        sandwich_norm=True, rms_norm_plus_one=True, hidden_act="gelu_tanh",
        scale_embedding=True, attn_logit_softcap=50.0,
        final_logit_softcap=30.0, query_pre_attn_scalar=32.0,
        sliding_window=8,
    )
    hf_model.eval()
    params = params_from_hf_state_dict(cfg, hf_model.state_dict())

    tokens_np = np.array([[3, 17, 42, 99, 7, 250] * 4], dtype=np.int64)  # S=24 > window
    with torch.no_grad():
        hf_logits = hf_model(torch.from_numpy(tokens_np)).logits.float().numpy()
    logits, _, _ = qwen3.forward(params, cfg, jnp.asarray(tokens_np))
    np.testing.assert_allclose(np.asarray(logits), hf_logits, rtol=2e-4, atol=2e-4)


def test_gemma2_cache_matches_cacheless():
    """KV-cached decode == full recompute for the gemma2 variant — the
    sliding-window mask must produce identical logits whether the window
    is applied over a padded cache buffer or the exact prefix."""
    from inferd_tpu.config import TINY_GEMMA2
    from inferd_tpu.core.cache import KVCache

    cfg = TINY_GEMMA2
    params = qwen3.init_params(cfg, jax.random.PRNGKey(5))
    toks = jax.random.randint(jax.random.PRNGKey(6), (1, 14), 0, cfg.vocab_size, jnp.int32)

    full_logits, _, _ = qwen3.forward(params, cfg, toks)

    cache = KVCache.create(cfg, cfg.num_layers, 1, 32, ring=False)
    logits_p, nk, nv = qwen3.forward(params, cfg, toks[:, :6], None, cache.k, cache.v, jnp.int32(0))
    cache = KVCache(k=nk, v=nv, length=jnp.int32(6))
    outs = [logits_p[:, -1]]
    for i in range(6, 14):  # decode walks well past the window of 8
        logits_i, nk, nv = qwen3.forward(
            params, cfg, toks[:, i : i + 1], None, cache.k, cache.v, cache.length
        )
        cache = KVCache(k=nk, v=nv, length=cache.length + 1)
        outs.append(logits_i[:, 0])
    got = jnp.stack(outs, axis=1)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(full_logits[:, 5:14]), rtol=2e-4, atol=2e-4
    )


def test_gemma2_stage_split_matches_full():
    """Stage slices of a sliding-window model must pass layer_offset so the
    even/odd local-global pattern follows GLOBAL layer indices; a wrong
    offset flips window assignment on stage 1 and diverges."""
    from inferd_tpu.config import TINY_GEMMA2

    cfg = TINY_GEMMA2
    params = qwen3.init_params(cfg, jax.random.PRNGKey(7))
    tokens = jax.random.randint(jax.random.PRNGKey(8), (2, 12), 0, cfg.vocab_size)
    positions = jnp.broadcast_to(jnp.arange(12), tokens.shape)
    hidden = qwen3.embed(params, tokens, cfg)
    full, _, _ = qwen3.forward_layers(params["layers"], cfg, hidden, positions)

    s0 = qwen3.slice_layers(params["layers"], 0, 3)
    s1 = qwen3.slice_layers(params["layers"], 3, 4)
    h, _, _ = qwen3.forward_layers(s0, cfg, hidden, positions, layer_offset=0)
    h, _, _ = qwen3.forward_layers(s1, cfg, h, positions, layer_offset=3)
    np.testing.assert_allclose(np.asarray(h), np.asarray(full), rtol=1e-5, atol=1e-5)

    # sanity: the WRONG offset must not match (odd split => patterns differ)
    h_bad, _, _ = qwen3.forward_layers(s1, cfg, h * 0 + hidden, positions, layer_offset=0)
    h_good, _, _ = qwen3.forward_layers(s1, cfg, h * 0 + hidden, positions, layer_offset=3)
    assert not np.allclose(np.asarray(h_bad), np.asarray(h_good))


@pytest.mark.parametrize("family", ["gemma2", "gptoss"])
def test_windowed_read_fast_path_matches_uniform(family):
    """The sliding-window pair-scan fast path (static window -> KV read
    narrowed to a window-covering slice) must produce bit-comparable
    logits AND identical cache writes to the uniform scan (traced window,
    full-buffer mask-only read) — prefill chunk and decode steps."""
    from inferd_tpu.config import TINY_GEMMA2, TINY_GPT_OSS
    from inferd_tpu.core.cache import KVCache

    cfg = TINY_GEMMA2 if family == "gemma2" else TINY_GPT_OSS
    params = qwen3.init_params(cfg, jax.random.PRNGKey(17))
    toks = jax.random.randint(jax.random.PRNGKey(18), (2, 6), 0, cfg.vocab_size, jnp.int32)

    def run(layer_offset):
        # static int offset 0 -> pair fast path; traced offset -> uniform
        # (ring=False: this test pins the UNIFORM-layout windowed-READ fast
        # path; ring STORAGE has its own suite, tests/test_ringkv.py)
        cache = KVCache.create(cfg, cfg.num_layers, 2, 32, ring=False)
        pos = jnp.broadcast_to(jnp.arange(6), (2, 6))
        hidden = qwen3.embed(params, toks, cfg)
        h, nk, nv = qwen3.forward_layers(
            params["layers"], cfg, hidden, pos, cache.k, cache.v,
            jnp.int32(0), layer_offset=layer_offset,
        )
        outs = [qwen3.unembed(params, cfg, h)]
        length = jnp.int32(6)
        tok = jnp.argmax(outs[0][:, -1], -1)[:, None]
        for i in range(6, 14):  # decode walks past the window of 8
            pos = jnp.full((2, 1), i, jnp.int32)
            hidden = qwen3.embed(params, tok, cfg)
            h, nk, nv = qwen3.forward_layers(
                params["layers"], cfg, hidden, pos, nk, nv, length,
                layer_offset=layer_offset,
            )
            length = length + 1
            outs.append(qwen3.unembed(params, cfg, h))
            tok = jnp.argmax(outs[-1][:, -1], -1)[:, None]
        return jnp.concatenate(outs, axis=1), nk, nv

    # both jitted: layer_offset a static closure int (pair fast path) vs a
    # traced argument (uniform scan) — same compilation regime otherwise
    fast_logits, fast_k, fast_v = jax.jit(lambda: run(0))()
    uni_logits, uni_k, uni_v = jax.jit(run)(jnp.int32(0))
    np.testing.assert_allclose(
        np.asarray(fast_logits), np.asarray(uni_logits), rtol=2e-5, atol=2e-5
    )
    np.testing.assert_allclose(
        np.asarray(fast_k), np.asarray(uni_k), rtol=1e-6, atol=1e-6
    )
    np.testing.assert_allclose(
        np.asarray(fast_v), np.asarray(uni_v), rtol=1e-6, atol=1e-6
    )


def test_windowed_slice_fuzz():
    """Randomized shapes/fills: attention over the window-covering slice ==
    attention over the full buffer with the window mask, for scalar and
    per-row ends, prefill chunks and decode steps, tiny and buffer-sized
    windows (the invariant the pair-scan fast path rests on)."""
    from inferd_tpu.models.qwen3 import _windowed_slice, gqa_attention

    rng = np.random.RandomState(41)
    for trial in range(12):
        b = int(rng.randint(1, 3))
        t = int(rng.choice([16, 24, 48]))
        s = int(rng.choice([1, 1, 4]))
        window = int(rng.choice([2, 8, t]))
        nq, nkv, d = 4, 2, 8
        kq = jax.random.PRNGKey(trial)
        q = jax.random.normal(kq, (b, s, nq, d))
        kbuf = jax.random.normal(jax.random.fold_in(kq, 1), (b, t, nkv, d))
        vbuf = jax.random.normal(jax.random.fold_in(kq, 2), (b, t, nkv, d))
        per_row = bool(rng.randint(0, 2))
        if per_row:
            end_np = rng.randint(s, t + 1, size=b)
            end = jnp.asarray(end_np, jnp.int32)
            qpos = end[:, None] - s + jnp.arange(s)[None, :]
        else:
            end_np = int(rng.randint(s, t + 1))
            end = jnp.int32(end_np)
            qpos = end - s + jnp.broadcast_to(jnp.arange(s), (b, s))

        ref = gqa_attention(
            q, kbuf, vbuf, qpos, end, window=jnp.int32(window)
        )
        k_att, v_att, kvpos, valid = _windowed_slice(kbuf, vbuf, end, window, s)
        got = gqa_attention(
            q, k_att, v_att, qpos, valid,
            kv_positions=kvpos, window=jnp.int32(window),
        )
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(ref), rtol=2e-5, atol=2e-5,
            err_msg=f"trial {trial}: b={b} t={t} s={s} w={window} "
                    f"per_row={per_row} end={end_np}",
        )


def test_fp8_kv_cache_close_to_full_recompute():
    """cfg.kv_dtype=float8_e4m3fn: cached decode logits must track the
    cache-free forward within fp8 storage noise (the narrow dtype only
    touches KV storage — weights/activations stay in cfg.dtype)."""
    from inferd_tpu.config import TINY
    from inferd_tpu.core.cache import KVCache

    cfg = dataclasses.replace(TINY, kv_dtype="float8_e4m3fn")
    assert str(cfg.kv_jnp_dtype) == "float8_e4m3fn"
    params = qwen3.init_params(cfg, jax.random.PRNGKey(6))
    toks = jax.random.randint(jax.random.PRNGKey(7), (1, 10), 0, cfg.vocab_size, jnp.int32)

    full_logits, _, _ = qwen3.forward(params, cfg, toks)

    cache = KVCache.create(cfg, cfg.num_layers, 1, 32, ring=False)
    assert cache.k.dtype == jnp.float8_e4m3fn
    logits_p, nk, nv = qwen3.forward(
        params, cfg, toks[:, :6], None, cache.k, cache.v, jnp.int32(0)
    )
    cache = KVCache(k=nk, v=nv, length=jnp.int32(6))
    outs = [logits_p[:, -1]]
    for i in range(6, 10):
        logits_i, nk, nv = qwen3.forward(
            params, cfg, toks[:, i : i + 1], None, cache.k, cache.v, cache.length
        )
        cache = KVCache(k=nk, v=nv, length=cache.length + 1)
        outs.append(logits_i[:, 0])
    got = np.asarray(jnp.stack(outs, axis=1), np.float32)
    want = np.asarray(full_logits[:, 5:10], np.float32)
    # fp8 (e4m3 ~ 2 decimal digits) perturbs but must stay well correlated
    cos = (got * want).sum() / (np.linalg.norm(got) * np.linalg.norm(want) + 1e-9)
    assert cos > 0.99, cos


def test_fp8_kv_engine_generates():
    from inferd_tpu.config import TINY
    from inferd_tpu.core.generate import Engine

    cfg = dataclasses.replace(TINY, kv_dtype="float8_e4m3fn")
    params = qwen3.init_params(cfg, jax.random.PRNGKey(6))
    eng = Engine(cfg, params, max_len=64)
    out = eng.generate([3, 5, 7], max_new_tokens=8, seed=0)
    assert len(out) == 8 and all(0 <= t < cfg.vocab_size for t in out)


def test_fp8_kv_write_saturates_no_nan():
    """An out-of-e4m3-range V value must saturate on cache write, not
    become NaN (e4m3fn maps overflow to NaN, which would permanently
    poison the session's cache)."""
    from inferd_tpu.models.qwen3 import _to_cache_dtype

    big = jnp.asarray([[1e4, -1e4, 0.5]], jnp.float32)
    out = _to_cache_dtype(big, jnp.float8_e4m3fn)
    f = np.asarray(out, np.float32)
    assert not np.isnan(f).any()
    assert f[0, 0] > 400 and f[0, 1] < -400
