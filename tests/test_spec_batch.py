# jaxlint: file-disable=J003 -- test code: loops here sync per-iteration to ASSERT on values; they are verification loops, not serving hot paths
"""Lane-batched speculative decoding (core.spec_batch): greedy exactness
per lane under concurrency, non-interference with regular batched lanes,
full-acceptance catch-up, ring-KV families, and the sampled rejection
scheme's distribution-exactness. Round-5 scope: speculation composing with
continuous batching instead of shedding to the regular loop (the
reference's decode is strictly one token per pass, client.py:244-266)."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from inferd_tpu.config import TINY, SamplingConfig
from inferd_tpu.core.batch import BatchedEngine
from inferd_tpu.core.generate import Engine
from inferd_tpu.core.spec_batch import (
    LaneSpecRunner, generate_lanes, make_draft_cache,
)
from inferd_tpu.core.speculative import self_draft
from inferd_tpu.models import qwen3


@pytest.fixture(scope="module")
def target():
    params = qwen3.init_params(TINY, jax.random.PRNGKey(0))
    return TINY, params


@pytest.fixture(scope="module")
def draft(target):
    cfg, params = target
    return self_draft(cfg, params, 2)


def test_concurrent_lanes_greedy_exactness(target, draft):
    """Three lanes speculating in the same rounds each emit EXACTLY their
    solo greedy stream — acceptance frontiers diverge per lane and never
    bleed across lanes."""
    cfg, params = target
    dcfg, dparams = draft
    engine = BatchedEngine(cfg, params, lanes=4, max_len=128)
    runner = LaneSpecRunner(cfg, dcfg, k=3)
    dcache = make_draft_cache(dcfg, 4, 128)

    prompts = [[3, 17, 42, 9], [5, 11, 2], [7, 1, 13, 25, 4]]
    solo = Engine(cfg, params, max_len=128,
                  sampling_cfg=SamplingConfig(temperature=0.0))
    want = [solo.generate(p, max_new_tokens=20) for p in prompts]

    got, _, acc = generate_lanes(
        engine, runner, params, dparams, dcache, prompts, max_new_tokens=20
    )
    assert got == want
    assert 0.0 <= acc <= 1.0


def test_spec_lanes_do_not_corrupt_regular_lanes(target, draft):
    """A regular continuous-batching session decoding on one lane while two
    other lanes run speculative rounds must keep its exact token stream:
    spec rounds write garbage at inactive lanes' frontiers, which is never
    attributed (the static-shape trick's aliasing contract)."""
    cfg, params = target
    dcfg, dparams = draft
    engine = BatchedEngine(
        cfg, params, lanes=4, max_len=128,
        sampling_cfg=SamplingConfig(temperature=0.0),
    )
    runner = LaneSpecRunner(cfg, dcfg, k=3)
    dcache = make_draft_cache(dcfg, 4, 128)

    reg_prompt = [9, 8, 7, 6]
    solo = Engine(cfg, params, max_len=128,
                  sampling_cfg=SamplingConfig(temperature=0.0))
    want_reg = solo.generate(reg_prompt, max_new_tokens=12)

    lane, tok = engine.admit(reg_prompt)
    reg_out = [tok]

    # interleave: a few regular decode steps, a spec generation, more steps
    def step_reg():
        toks = [0] * engine.lanes
        active = [False] * engine.lanes
        toks[lane], active[lane] = reg_out[-1], True
        nt = engine.decode(toks, active)
        reg_out.append(int(nt[lane]))

    for _ in range(4):
        step_reg()
    spec_got, _, _ = generate_lanes(
        engine, runner, params, dparams, dcache,
        [[3, 17, 42, 9], [5, 11, 2]], max_new_tokens=10,
    )
    want_spec = [solo.generate([3, 17, 42, 9], max_new_tokens=10),
                 solo.generate([5, 11, 2], max_new_tokens=10)]
    assert spec_got == want_spec
    while len(reg_out) < 12:
        step_reg()
    assert reg_out == want_reg


def test_full_acceptance_catchup(target):
    """Draft == target accepts every draft every round (rate 1.0), which
    exercises the per-lane catch-up path continuously; tokens stay exact."""
    cfg, params = target
    engine = BatchedEngine(cfg, params, lanes=2, max_len=128)
    runner = LaneSpecRunner(cfg, cfg, k=4)
    dcache = make_draft_cache(cfg, 2, 128)
    solo = Engine(cfg, params, max_len=128,
                  sampling_cfg=SamplingConfig(temperature=0.0))
    prompts = [[5, 11, 2], [3, 1, 4, 1, 5]]
    want = [solo.generate(p, max_new_tokens=20) for p in prompts]
    got, _, acc = generate_lanes(
        engine, runner, params, params, dcache, prompts, max_new_tokens=20
    )
    assert got == want
    assert acc == 1.0


def test_eos_stops_mid_chunk(target, draft):
    cfg, params = target
    solo = Engine(cfg, params, max_len=128,
                  sampling_cfg=SamplingConfig(temperature=0.0))
    prompt = [7, 1, 13]
    ref = solo.generate(prompt, max_new_tokens=30)
    eos = ref[5]
    want = solo.generate(prompt, max_new_tokens=30, eos_token_id=eos)

    engine = BatchedEngine(cfg, params, lanes=2, max_len=128)
    runner = LaneSpecRunner(cfg, cfg, k=4)
    dcache = make_draft_cache(cfg, 2, 128)
    got, _, _ = generate_lanes(
        engine, runner, params, params, dcache, [prompt],
        max_new_tokens=30, eos_token_id=eos,
    )
    assert got == [want]


def test_ring_family_greedy_exactness():
    """Sliding-window (ring-KV) model: lane-batched speculation stays
    token-exact — verify-chunk rollback depth is inside the ring margin."""
    from inferd_tpu.config import TINY_GEMMA2

    cfg = TINY_GEMMA2
    params = qwen3.init_params(cfg, jax.random.PRNGKey(31))
    solo = Engine(cfg, params, max_len=128,
                  sampling_cfg=SamplingConfig(temperature=0.0))
    prompt = [3, 17, 42, 9, 8, 1, 5, 12, 2]
    want = solo.generate(prompt, max_new_tokens=16)  # walks past window 8

    dcfg, dparams = self_draft(cfg, params, 2)
    engine = BatchedEngine(cfg, params, lanes=2, max_len=128)
    runner = LaneSpecRunner(cfg, dcfg, k=3)
    dcache = make_draft_cache(dcfg, 2, 128)
    got, _, _ = generate_lanes(
        engine, runner, params, dparams, dcache, [prompt], max_new_tokens=16
    )
    assert got == [want]


def test_ring_margin_guard():
    from inferd_tpu.config import TINY_GEMMA2
    from inferd_tpu.core.cache import RING_MARGIN

    with pytest.raises(ValueError, match="ring margin"):
        LaneSpecRunner(TINY_GEMMA2, TINY_GEMMA2, k=RING_MARGIN)


def test_sampled_distribution_matches_target(target):
    """Per-lane rejection sampling must emit tokens distributed exactly as
    target-only warped sampling, independent of the co-batched lane:
    empirical first-emitted-token distribution over many rounds vs the
    target's warped probabilities, in total-variation distance. Runs TWO
    lanes per round (the second with a different prefix) so any cross-lane
    key/probability bleed would show up as TV drift."""
    from inferd_tpu.core import sampling as samplib

    cfg, params = target
    draft_cfg = dataclasses.replace(TINY, name="tiny-draft2", num_layers=2)
    draft_params = qwen3.init_params(draft_cfg, jax.random.PRNGKey(77))
    sc = SamplingConfig(temperature=1.2, top_k=5, top_p=0.9)
    runner = LaneSpecRunner(cfg, draft_cfg, k=3, sampling=sc)

    prompt = [3, 17, 42, 9]
    other = [8, 2, 6]
    n = len(prompt)
    toks16 = jnp.asarray([prompt + [0] * (16 - n)], jnp.int32)
    logits_p, _, _ = qwen3.forward(params, cfg, toks16)
    x_n = int(jnp.argmax(logits_p[0, n - 1]))
    logits_full, _, _ = qwen3.forward(
        params, cfg,
        jnp.asarray([prompt + [x_n] + [0] * (15 - n)], jnp.int32),
    )
    want = np.asarray(
        jax.nn.softmax(
            samplib.warped_logits(
                logits_full[:, n], sc.temperature, sc.top_k, sc.top_p
            )
        )
    )[0]

    # prefill ONCE; per trial only the lane lengths reset (speculative
    # rollback is free: frontier slots rewritten next round, prefix KV
    # untouched) — rebuilding the engine per trial would retrace every jit
    engine = BatchedEngine(cfg, params, lanes=2, max_len=64)
    dcache = make_draft_cache(draft_cfg, 2, 64)
    outs = []
    for i, p in enumerate([prompt, other]):
        lane = engine.free.pop()
        b = 16
        padded = np.zeros((1, b), np.int32)
        padded[0, : len(p)] = p
        engine.cache, lg = engine._prefill_lane_logits(
            engine.params, engine.cache, jnp.asarray(padded),
            jnp.int32(lane), jnp.int32(0), jnp.int32(len(p)),
        )
        engine.lengths[lane] = len(p)
        dcache = runner.draft_prefill(
            draft_params, dcache, padded, lane, 0, len(p)
        )
        outs.append(lane)

    counts = np.zeros(cfg.vocab_size)
    trials = 500
    for s in range(trials):
        engine.lengths[outs[0]] = len(prompt)
        engine.lengths[outs[1]] = len(other)
        last = np.zeros((2,), np.int32)
        last[outs[0]] = x_n
        last[outs[1]] = int(np.argmax(np.asarray(lg)))
        dlens = np.zeros((2,), np.int32)
        dlens[outs[0]] = len(prompt)
        dlens[outs[1]] = len(other)
        keys = np.zeros((2, 2), np.uint32)
        keys[outs[0]] = np.asarray(jax.random.PRNGKey(10_000 + s))
        keys[outs[1]] = np.asarray(jax.random.PRNGKey(20_000 + s))
        # keep the RETURNED draft cache: the round donates its input (the
        # prefix KV is intact — rounds only write at/beyond the frontier)
        toks, n_new, dcache = runner.run_round(
            params, draft_params, engine, dcache, last,
            np.zeros((2,), np.int32), np.zeros((2,), bool),
            dlens, np.ones((2,), bool), keys,
        )
        counts[int(toks[outs[0], 0])] += 1
    emp = counts / trials
    tv = 0.5 * np.abs(emp - want).sum()
    assert tv < 0.10, f"TV distance {tv}"


def test_quantized_lane_spec_exactness():
    """Speculation composes with int8 serving quantization: the lane
    runner's draft AND verify contract quantized leaves via qdot, staying
    token-exact with the solo engine over the SAME quantized params."""
    from inferd_tpu.ops import quant

    cfg = TINY
    params = qwen3.init_params(cfg, jax.random.PRNGKey(0))
    qparams = quant.apply_quant_mode(
        "int8", params, tie_word_embeddings=cfg.tie_word_embeddings
    )
    dcfg, dparams = self_draft(cfg, qparams, 2)
    engine = BatchedEngine(cfg, qparams, lanes=2, max_len=128)
    runner = LaneSpecRunner(cfg, dcfg, k=3)
    dcache = make_draft_cache(dcfg, 2, 128)
    solo = Engine(cfg, qparams, max_len=128,
                  sampling_cfg=SamplingConfig(temperature=0.0))
    prompt = [3, 17, 42, 9]
    want = [solo.generate(prompt, max_new_tokens=12)]
    got, _, _ = generate_lanes(
        engine, runner, qparams, dparams, dcache, [prompt], max_new_tokens=12
    )
    assert got == want


def test_chunk_logprob_trail_matches_per_row():
    """The flattened verify-chunk logprob trail equals per-row
    logprob_topn (the wire shape both executors' flushes pack)."""
    from inferd_tpu.core import sampling as samplib
    from inferd_tpu.core.spec_batch import SPEC_TOP_N, chunk_logprob_trail

    L, K, V = 3, 2, 32
    tl = jax.random.normal(jax.random.PRNGKey(0), (L, K + 1, V), jnp.float32)
    greedy = jnp.argmax(tl, axis=-1).astype(jnp.int32)
    lp, ti, tls = chunk_logprob_trail(tl, greedy, K, SPEC_TOP_N, True)
    assert lp.shape == (L, K + 1)
    assert ti.shape == (L, K + 1, SPEC_TOP_N)
    for l in range(L):
        for j in range(K + 1):
            wlp, wti, wtls = samplib.logprob_topn(
                tl[l, j][None], greedy[l, j][None], SPEC_TOP_N
            )
            np.testing.assert_allclose(float(lp[l, j]), float(wlp[0]), rtol=1e-6)
            assert ti[l, j].tolist() == wti[0].tolist()
    # want_lp=False: zero-width placeholders (the fast path's shape)
    lp0, ti0, _ = chunk_logprob_trail(tl, greedy, K, SPEC_TOP_N, False)
    assert ti0.shape == (L, K + 1, 0)


def test_spec_entry_result_wire_shape():
    """One definition of the flush result tuple both executors pack."""
    from inferd_tpu.runtime.spec_serving import SpecServing

    toks = np.asarray([5, 6, 7, 8])
    lps = np.asarray([-0.1, -0.2, -0.3, -0.4])
    tis = np.asarray([[1, 2]] * 4)
    tls = np.asarray([[-0.5, -0.9]] * 4)
    plain = SpecServing._spec_entry_result(False, toks, 2)
    assert plain == ([5, 6], 2)
    rich = SpecServing._spec_entry_result(True, toks, 3, lps, tis, tls)
    assert rich[0] == [5, 6, 7] and rich[1] == 3
    assert rich[2] == [-0.1, -0.2, -0.3]
    assert rich[3][0] == ([1, 2], [-0.5, -0.9]) and len(rich[3]) == 3
