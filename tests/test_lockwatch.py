"""utils.lockwatch: the dynamic half of the concurrency plane.

Covers the order-recording lock proxies (strict raise vs journal mode),
the ticketed FairDeviceLock's starvation bound, the event-loop stall
detector (J009's runtime twin, seeded via the chaos `block_ms` fault),
and the <=1%-of-compute overhead budget perf.gate holds the sanitizer
to. tests/conftest.py instruments strict mode suite-wide; the fixture
here isolates each test's state and restores the suite's.
"""

import asyncio
import threading
import time

import pytest

from inferd_tpu.utils import lockwatch
from inferd_tpu.utils.chaos import Chaos
from inferd_tpu.utils.lockwatch import (
    LOCK_ORDER,
    FairDeviceLock,
    LockOrderError,
    LoopStallDetector,
    WatchedLock,
)


@pytest.fixture
def lw(monkeypatch):
    """Pristine lockwatch state; restores the suite's strict instrument
    (conftest) afterwards."""
    monkeypatch.delenv("INFERD_LOCKWATCH", raising=False)
    prev = (
        lockwatch._state.enabled,
        lockwatch._state.strict,
        lockwatch._state.on_event,
    )
    lockwatch.reset()
    yield lockwatch
    lockwatch.reset()
    (
        lockwatch._state.enabled,
        lockwatch._state.strict,
        lockwatch._state.on_event,
    ) = prev


# ------------------------------------------------------ construction seam


def test_make_lock_plain_when_disabled(lw):
    lock = lw.make_lock("dev")
    assert not isinstance(lock, WatchedLock)
    lock.acquire()
    lock.release()
    assert lw.stats()["checks"] == 0  # disabled = zero bookkeeping


def test_make_lock_watched_when_instrumented(lw):
    lw.instrument()
    assert isinstance(lw.make_lock("dev"), WatchedLock)
    # an unranked name cannot be order-checked: plain lock, no guessing
    assert not isinstance(lw.make_lock("not_a_ranked_lock"), WatchedLock)


def test_env_kill_switch_beats_instrument(lw, monkeypatch):
    lw.instrument(strict=True)
    monkeypatch.setenv("INFERD_LOCKWATCH", "0")
    assert not lw.watching()
    assert not isinstance(lw.make_lock("dev"), WatchedLock)


# ----------------------------------------------------- inversion checking


def test_canonical_order_passes_strict(lw):
    lw.instrument(strict=True)
    locks = [lw.make_lock(n) for n in LOCK_ORDER]
    for lock in locks:
        lock.acquire()
    assert lw.held_stack() == list(LOCK_ORDER)
    for lock in reversed(locks):
        lock.release()
    assert lw.held_stack() == []
    assert lw.stats()["inversions"] == 0


def test_inversion_raises_in_strict_mode(lw):
    # the seeded inversion's DYNAMIC catch (its static twin is
    # test_analysis.test_j007_inversion_fires)
    lw.instrument(strict=True)
    dev, mu = lw.make_lock("dev"), lw.make_lock("mu")
    with mu:
        with pytest.raises(LockOrderError, match="canonical order"):
            dev.acquire()
    # the refused acquire left no phantom entry behind
    assert lw.held_stack() == []
    with dev:
        with mu:
            pass  # same pair, canonical direction: fine


def test_inversion_journals_once_per_pair(lw):
    events = []
    lw.instrument(journal=lambda et, **kw: events.append((et, kw)))
    dev, mu = lw.make_lock("dev"), lw.make_lock("mu")
    for _ in range(3):
        with mu:
            with dev:
                pass
    assert lw.stats()["inversions"] == 3
    assert len(events) == 1  # deduped per (held, acquiring) pair
    et, kw = events[0]
    assert et == "lock.inversion"
    assert kw["held"] == "mu" and kw["acquiring"] == "dev"


def test_try_acquire_is_exempt(lw):
    lw.instrument(strict=True)
    dev, mu = lw.make_lock("dev"), lw.make_lock("mu")
    with mu:
        # a try-acquire cannot participate in a deadlock cycle
        assert dev.acquire(blocking=False)
        dev.release()
    assert lw.stats()["inversions"] == 0


def test_journal_hook_failure_is_swallowed(lw):
    def bad_hook(et, **kw):
        raise RuntimeError("observability must not add failure modes")

    lw.instrument(journal=bad_hook)
    dev, mu = lw.make_lock("dev"), lw.make_lock("mu")
    with mu:
        with dev:
            pass  # no raise: the hook error is contained


# ------------------------------------------------------- FairDeviceLock


def test_fair_lock_release_cannot_barge_past_waiter(lw):
    """The chunked-prefill starvation shape, deterministically: once a
    flusher is queued, the releasing chunk loop CANNOT re-acquire ahead
    of it (threading.Lock makes no such promise — that race is why the
    executors' inter-chunk sleep existed)."""
    lock = FairDeviceLock()
    assert lock.acquire()
    got = threading.Event()

    def flusher():
        lock.acquire()
        got.set()
        lock.release()

    t = threading.Thread(target=flusher)
    t.start()
    while lock._next < 2:  # flusher's ticket is queued
        time.sleep(0.001)
    lock.release()
    # the ticket at the head of the queue is the flusher's, not ours
    assert lock.acquire(blocking=False) is False
    assert got.wait(2.0)
    t.join()
    assert lock.acquire(blocking=False)  # queue drained: ours again
    lock.release()


def test_fair_lock_flusher_not_starved_under_chunk_loop(lw):
    """Contention test: a decode flusher arriving mid-prefill is served
    within ONE further chunk — the FIFO bound the yield-based
    workaround could only approximate."""
    lock = FairDeviceLock()
    chunks_done = 0
    flusher_done = threading.Event()
    granted_after = None

    def chunk_loop():
        nonlocal chunks_done
        for _ in range(2000):
            with lock:
                time.sleep(0.0002)  # one chunk dispatch
            chunks_done += 1
            if flusher_done.is_set():
                return

    def flusher():
        nonlocal granted_after
        queued_at = chunks_done
        with lock:
            granted_after = chunks_done - queued_at
        flusher_done.set()

    ct = threading.Thread(target=chunk_loop)
    ct.start()
    while chunks_done < 3:
        time.sleep(0.001)
    ft = threading.Thread(target=flusher)
    ft.start()
    assert flusher_done.wait(5.0), "flusher starved behind the chunk loop"
    ct.join()
    ft.join()
    # at most the in-flight chunk plus the one that queued ahead of us
    assert granted_after is not None and granted_after <= 2


def test_fair_lock_timeout_abandons_ticket(lw):
    lock = FairDeviceLock()
    lock.acquire()
    t0 = time.perf_counter()
    assert lock.acquire(timeout=0.05) is False
    assert time.perf_counter() - t0 < 1.0
    lock.release()
    # the abandoned ticket must not wedge the grant chain
    assert lock.acquire(blocking=False)
    lock.release()
    assert not lock.locked()


def test_fair_devlock_composes_with_watching(lw, monkeypatch):
    lw.instrument(strict=True)
    lock = lw.make_lock("dev", fair=True)
    assert isinstance(lock, WatchedLock)
    assert lw.is_fair(lock)  # the chunk-yield site sees through the proxy
    assert not lw.is_fair(lw.make_lock("dev"))
    with lock:
        assert lw.held_stack() == ["dev"]
    monkeypatch.setenv("INFERD_FAIR_DEVLOCK", "1")
    assert lw.fair_devlock_enabled()
    monkeypatch.delenv("INFERD_FAIR_DEVLOCK")
    assert not lw.fair_devlock_enabled()


# --------------------------------------------------- loop-stall detector


async def test_stall_detector_catches_blocking_sleep(lw):
    # the seeded blocking-async handler's DYNAMIC catch (static twin:
    # test_analysis.test_j009_sync_lock_in_async_handler)
    events = []
    det = LoopStallDetector(
        stall_ms=50.0, interval_ms=10.0,
        on_event=lambda et, **kw: events.append((et, kw)),
    ).start()
    await asyncio.sleep(0.03)
    time.sleep(0.12)  # jaxlint: disable=J005 -- the seeded loop stall this test exists to catch
    await asyncio.sleep(0.05)
    det.stop()
    assert det.stalls and max(det.stalls) >= 50.0
    et, kw = events[0]
    assert et == "loop.stall" and kw["blocked_ms"] >= 50.0


async def test_stall_detector_quiet_loop_stays_silent(lw):
    det = LoopStallDetector(stall_ms=50.0, interval_ms=10.0).start()
    for _ in range(5):
        await asyncio.sleep(0.02)  # yielding work never stalls the loop
    det.stop()
    assert det.stalls == []


async def test_chaos_block_ms_is_detectable(lw):
    """utils.chaos `block_ms` holds the event loop synchronously — the
    injectable J009 violation — and the detector sees it."""
    chaos = Chaos.parse("block_ms=120")
    assert chaos.block_ms == 120.0
    det = LoopStallDetector(stall_ms=50.0, interval_ms=10.0).start()
    await asyncio.sleep(0.03)
    await chaos.before_forward()
    await asyncio.sleep(0.05)
    det.stop()
    assert det.stalls and max(det.stalls) >= 50.0


async def test_chaos_delay_ms_yields_no_stall(lw):
    # the async twin fault must NOT trip the detector: it awaits
    chaos = Chaos.parse("delay_ms=120")
    det = LoopStallDetector(stall_ms=50.0, interval_ms=10.0).start()
    await asyncio.sleep(0.03)
    await chaos.before_forward()
    await asyncio.sleep(0.05)
    det.stop()
    assert det.stalls == []


# ------------------------------------------------------- overhead budget


def test_overhead_within_gate_budget(lw):
    from inferd_tpu.perf import gate as gatelib

    lw.instrument()
    lock = lw.make_lock("dev")
    n = 20000
    for _ in range(n):
        lock.acquire()
        lock.release()
    ov = lw.stats()["overhead_ms"]
    assert lw.stats()["checks"] == n
    # perf.gate's bar: sanitizer cost <= 1% of compute. One check per
    # device step against a conservative 1 ms step means the per-check
    # cost must stay under 10 us.
    per_check_ms = ov / n
    assert per_check_ms < 0.01, f"{per_check_ms * 1e3:.2f}us per check"
    stats = {
        "gauges": {"lockwatch.overhead_ms": ov},
        "counters": {},
        "histograms": {"stage.compute_ms": {"count": n, "mean_ms": 1.0}},
    }
    assert gatelib.check_span_overhead(stats) == []
    # and the gate actually watches the gauge: blow the budget, it fires
    stats["gauges"]["lockwatch.overhead_ms"] = 0.02 * n * 1.0
    found = gatelib.check_span_overhead(stats)
    assert any("lock-order-sanitizer" in f.message for f in found)


def test_suite_runs_instrumented_with_zero_inversions():
    """tier-1's standing invariant: conftest instruments strict mode
    suite-wide (unless INFERD_LOCKWATCH=0), so by the time this test
    runs, every executor/node lock constructed by earlier tests was
    order-checked — and nothing raised or journaled an inversion."""
    import os

    if os.environ.get("INFERD_LOCKWATCH", "").strip().lower() in (
        "0", "off", "false", "no"
    ):
        pytest.skip("lockwatch killed via INFERD_LOCKWATCH")
    assert lockwatch.watching() and lockwatch.strict()
    assert lockwatch.stats()["inversions"] == 0
