"""In-mesh speculative SERVING (--mesh pp=N --spec-draft-layers): the mesh
node's /generate speculates inside the SPMD program — concurrent requests
coalesce rounds, greedy stays token-exact with the solo engine, and
regular /forward sessions on sibling slots are untouched. Round-5 scope
(VERDICT r04 #1b: the north-star pipelined topology can finally
speculate)."""

import asyncio

import jax
import pytest

from inferd_tpu.client.swarm_client import SwarmClient
from inferd_tpu.config import TINY, SamplingConfig
from inferd_tpu.control.dht import SwarmDHT
from inferd_tpu.core.generate import Engine
from inferd_tpu.models import qwen3
from inferd_tpu.parallel.mesh import MeshPlan
from inferd_tpu.parallel.stages import Manifest, split_and_save
from inferd_tpu.runtime.node import Node, NodeInfo


from conftest import requires_native_shard_map

pytestmark = requires_native_shard_map

BASE = 18800
GREEDY = SamplingConfig(temperature=0.0)


@pytest.fixture(scope="module")
def mesh_parts(tmp_path_factory):
    parts = tmp_path_factory.mktemp("specmesh_parts")
    params = qwen3.init_params(TINY, jax.random.PRNGKey(0))
    split_and_save(params, TINY, Manifest.even_split("tiny", 1), str(parts))
    return str(parts), params


def _mk_node(idx, parts, pp=2, slots=3, max_len=64, draft_layers=2, k=3):
    info = NodeInfo(
        name=f"sm{idx}", host="127.0.0.1", port=BASE + idx,
        stage=0, num_stages=1, model_name="tiny",
    )
    dht = SwarmDHT(
        info.node_id, BASE + 100 + idx, bootstrap=[],
        host="127.0.0.1", gossip_period_s=0.05, ttl_s=5.0,
    )
    return Node(
        info, TINY, parts, dht, backend="qwen3", max_len=max_len,
        rebalance_period_s=600.0, mesh_plan=MeshPlan(pp=pp),
        mesh_slots=slots, spec_draft_layers=draft_layers, spec_k=k,
    )


async def _start(node):
    await node.start()
    t = getattr(node, "_spec_prebuild_task", None)
    if t is not None:
        await t
    return node


@pytest.mark.asyncio
async def test_mesh_concurrent_generate_speculative_exact(
    mesh_parts, devices8
):
    """Two concurrent greedy /generate requests on a pp=2 mesh node BOTH
    speculate and match the solo engine exactly."""
    parts, params = mesh_parts
    node = _mk_node(0, parts)
    await _start(node)
    try:
        prompts = [[3, 7, 11], [2, 5, 13, 17]]
        engine = Engine(TINY, params, max_len=64, sampling_cfg=GREEDY)
        want = [engine.generate(p, max_new_tokens=10) for p in prompts]

        async def one(p):
            async with SwarmClient(
                [("127.0.0.1", BASE)], sampling=GREEDY
            ) as c:
                return await c.generate_server_side(
                    p, max_new_tokens=10, return_payload=True
                )

        payloads = await asyncio.gather(*(one(p) for p in prompts))
        assert [p["ids"] for p in payloads] == want
        assert all(p.get("speculative") for p in payloads), payloads
        st = node.executor.stats()
        assert st["spec_rounds"] > 0
        assert st["spec_sessions"] == 0
    finally:
        await node.stop()


@pytest.mark.asyncio
async def test_mesh_spec_and_regular_sessions_interleave(
    mesh_parts, devices8
):
    """A regular /forward session decoding while a sibling slot
    speculates keeps its exact stream (verify-chunk garbage writes on
    inactive slots are never attributed)."""
    parts, params = mesh_parts
    node = _mk_node(1, parts)
    await _start(node)
    try:
        engine = Engine(TINY, params, max_len=64, sampling_cfg=GREEDY)
        reg_prompt = [9, 8, 7, 6]
        want_reg = engine.generate(reg_prompt, max_new_tokens=10)
        want_spec = engine.generate([3, 7, 11], max_new_tokens=10)

        async def regular():
            async with SwarmClient(
                [("127.0.0.1", BASE + 1)], sampling=GREEDY
            ) as c:
                return await c.generate_ids(reg_prompt, max_new_tokens=10)

        async def spec():
            async with SwarmClient(
                [("127.0.0.1", BASE + 1)], sampling=GREEDY
            ) as c:
                return await c.generate_server_side(
                    [3, 7, 11], max_new_tokens=10
                )

        got_reg, got_spec = await asyncio.gather(regular(), spec())
        assert got_reg == want_reg
        assert got_spec == want_spec
    finally:
        await node.stop()


@pytest.mark.asyncio
async def test_mesh_sampled_spec_deterministic(mesh_parts, devices8):
    parts, params = mesh_parts
    node = _mk_node(2, parts)
    await _start(node)
    try:
        sc = SamplingConfig(temperature=0.9, top_k=10, top_p=0.95)

        async def one():
            async with SwarmClient(
                [("127.0.0.1", BASE + 2)], sampling=sc
            ) as c:
                return await c.generate_server_side(
                    [3, 7, 11], max_new_tokens=10, seed=5,
                    return_payload=True,
                )

        p1 = await one()
        p2 = await one()
        assert p1["speculative"] and len(p1["ids"]) == 10
        assert p1["ids"] == p2["ids"]
    finally:
        await node.stop()


@pytest.mark.asyncio
async def test_mesh_pinned_prefix_composes_with_spec(mesh_parts, devices8):
    """The mesh executor's spec path forks pinned prefixes too (slot-level
    fork, shard-local on every pp rank) — greedy-exact with the solo
    engine, fast path taken."""
    parts, params = mesh_parts
    node = _mk_node(3, parts)
    await _start(node)
    try:
        engine = Engine(TINY, params, max_len=64, sampling_cfg=GREEDY)
        prefix = [3, 7, 11, 13]
        full = prefix + [2, 5]
        want = engine.generate(full, max_new_tokens=8)
        async with SwarmClient(
            [("127.0.0.1", BASE + 3)], sampling=GREEDY
        ) as c:
            p = await c.generate_server_side(
                full, max_new_tokens=8, pin_prefix_len=len(prefix),
                return_payload=True,
            )
        assert p["ids"] == want
        assert p.get("speculative") is True
        assert node.metrics.snapshot()["counters"][
            "generate.speculative_pinned"
        ] == 1
    finally:
        await node.stop()
