# jaxlint: file-disable=J003 -- test code: loops here sync per-iteration to ASSERT on values; they are verification loops, not serving hot paths
"""Stage partitioning + executor tests: manifest validation, checkpoint
round-trip, and the golden pipeline test — a chain of stage executors must
reproduce the single-process engine token-for-token."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from inferd_tpu.config import TINY, SamplingConfig
from inferd_tpu.core.generate import Engine
from inferd_tpu.models import qwen3
from inferd_tpu.parallel.stages import (
    Manifest,
    StageSpec,
    extract_stage_params,
    load_stage_checkpoint,
    split_and_save,
    stage_checkpoint_path,
)
from inferd_tpu.runtime.executor import CounterStageExecutor, Qwen3StageExecutor


MANIFEST_YAML = """
model_name: tiny
stages_count: 3
nodes:
  - {name: node0, stage: 0, start_layer: 0, end_layer: 0}
  - {name: node1, stage: 1, start_layer: 1, end_layer: 2}
  - {name: node2, stage: 2, start_layer: 3, end_layer: 3}
  - {name: node2b, stage: 2, start_layer: 3, end_layer: 3}
"""


def test_manifest_parse_validate():
    m = Manifest.from_yaml(MANIFEST_YAML)
    m.validate()
    assert m.num_stages == 3
    assert m.stage_spec(1).num_layers == 2
    assert m.stage_spec(2).is_last
    # replicated stage: two nodes, same range
    assert sum(1 for n in m.nodes if n.stage == 2) == 2


def test_manifest_rejects_gap():
    bad = MANIFEST_YAML.replace("start_layer: 1", "start_layer: 2")
    with pytest.raises(ValueError):
        Manifest.from_yaml(bad).validate()


def test_manifest_even_split():
    m = Manifest.even_split("tiny", 3)
    m.validate()
    sizes = [m.stage_spec(s).num_layers for s in range(3)]
    assert sum(sizes) == TINY.num_layers and max(sizes) - min(sizes) <= 1


def test_checkpoint_roundtrip(tmp_path):
    params = qwen3.init_params(TINY, jax.random.PRNGKey(0))
    m = Manifest.from_yaml(MANIFEST_YAML)
    paths = split_and_save(params, TINY, m, str(tmp_path))
    assert len(paths) == 3  # per-stage, replicas share
    sp, spec, model_name = load_stage_checkpoint(stage_checkpoint_path(str(tmp_path), 1))
    assert model_name == "tiny" and spec.stage == 1 and spec.num_layers == 2
    np.testing.assert_array_equal(
        np.asarray(sp["layers"]["q_proj"]),
        np.asarray(params["layers"]["q_proj"][1:3]),
    )
    assert "embed" not in sp  # inner stage carries no embedding


def _pipeline_decode(executors, session, tokens, start_pos):
    payload = {"tokens": tokens, "start_pos": start_pos}
    for ex in executors:
        out = ex.process(session, payload)
        if "hidden" in out:
            payload = {"hidden": out["hidden"], "start_pos": start_pos, "real_len": out["real_len"]}
    return out["logits"]


def _assert_pipeline_matches_engine(cfg, specs, seed, prompt, steps, session):
    """Golden chain test shared by every family: prefill through the stage
    executors, decode greedily token by token, compare with the engine."""
    params = qwen3.init_params(cfg, jax.random.PRNGKey(seed))
    execs = [
        Qwen3StageExecutor(cfg, spec, extract_stage_params(params, cfg, spec), max_len=64)
        for spec in specs
    ]
    engine = Engine(cfg, params, max_len=64, sampling_cfg=SamplingConfig(temperature=0.0))
    expected = engine.generate(prompt, max_new_tokens=steps)

    logits = _pipeline_decode(execs, session, np.asarray([prompt]), 0)
    tok = int(np.argmax(logits[0]))
    got = [tok]
    pos = len(prompt)
    for _ in range(steps - 1):
        logits = _pipeline_decode(execs, session, np.asarray([[tok]]), pos)
        tok = int(np.argmax(logits[0]))
        got.append(tok)
        pos += 1
    assert got == expected


def test_pipeline_matches_engine():
    """3-stage executor chain == single-process engine (greedy)."""
    m = Manifest.from_yaml(MANIFEST_YAML)
    _assert_pipeline_matches_engine(
        TINY, m.stage_specs(), seed=0, prompt=[7, 3, 11, 2], steps=5, session="s1"
    )


def test_moe_pipeline_matches_engine():
    """Stage-split MoE serving: expert params slice per stage like dense
    layers, and a 2-stage executor chain reproduces the single-process
    engine token-for-token (MoE was otherwise only covered by model-level
    and mesh-parallel tests, never through the serving executors)."""
    from inferd_tpu.config import TINY_MOE

    m = Manifest.even_split(TINY_MOE.name, 2)
    _assert_pipeline_matches_engine(
        TINY_MOE, m.stage_specs(), seed=0, prompt=[5, 2, 9], steps=5, session="moe1"
    )


def test_gemma2_pipeline_matches_engine():
    """Stage-split Gemma-2 serving: the executor must thread its stage's
    start_layer into forward_layers so sliding-window assignment follows
    GLOBAL layer indices (a 3/1 split puts stage 1's only layer at global
    index 3 — odd, so global attention; offset 0 would make it local and
    diverge). Prompt+decode walk past the window of 8."""
    from inferd_tpu.config import TINY_GEMMA2

    _assert_pipeline_matches_engine(
        TINY_GEMMA2, [StageSpec(0, 2, 0, 2), StageSpec(1, 2, 3, 3)],
        seed=1, prompt=[5, 2, 9, 11, 4, 8, 1], steps=6, session="g2",
    )


def test_gpt_oss_pipeline_matches_engine():
    """Stage-split GPT-OSS serving: sinks/biases/clamped experts flow
    through the stage executors' jitted per-session KV path; a 3/1 split
    puts stage 1's only layer at global index 3 (odd = global attention).
    Decode walks past the window of 8."""
    from inferd_tpu.config import TINY_GPT_OSS

    _assert_pipeline_matches_engine(
        TINY_GPT_OSS, [StageSpec(0, 2, 0, 2), StageSpec(1, 2, 3, 3)],
        seed=2, prompt=[5, 2, 9, 11, 4, 8, 1], steps=6, session="go",
    )


def test_executor_rejects_out_of_order():
    cfg = TINY
    params = qwen3.init_params(cfg, jax.random.PRNGKey(0))
    spec = StageSpec(0, 1, 0, cfg.num_layers - 1)
    ex = Qwen3StageExecutor(cfg, spec, extract_stage_params(params, cfg, spec), max_len=64)
    ex.process("s", {"tokens": np.asarray([[1, 2, 3]]), "start_pos": 0})
    with pytest.raises(ValueError, match="out-of-order"):
        ex.process("s", {"tokens": np.asarray([[4]]), "start_pos": 7})


def test_executor_replay_rolls_back_deterministically():
    """A chunk starting BEFORE the frontier is a deterministic replay (a
    client re-sent after a lost response): the executor rolls the cache
    back and recomputes — identical output, session continues, no 409."""
    cfg = TINY
    params = qwen3.init_params(cfg, jax.random.PRNGKey(0))
    spec = StageSpec(0, 1, 0, cfg.num_layers - 1)
    ex = Qwen3StageExecutor(cfg, spec, extract_stage_params(params, cfg, spec), max_len=64)
    first = ex.process(
        "s", {"tokens": np.asarray([[1, 2, 3]]), "start_pos": 0, "real_len": 3}
    )
    step = {"tokens": np.asarray([[4]]), "start_pos": 3, "real_len": 1}
    a = ex.process("s", dict(step))
    a2 = ex.process("s", dict(step))  # replay of the SAME chunk
    np.testing.assert_allclose(a["logits"], a2["logits"], rtol=1e-6, atol=1e-6)
    # whole-prefill replay too (start_pos 0 over an advanced session)
    rep = ex.process(
        "s", {"tokens": np.asarray([[1, 2, 3]]), "start_pos": 0, "real_len": 3}
    )
    np.testing.assert_allclose(rep["logits"], first["logits"], rtol=1e-6, atol=1e-6)
    # and the session continues from the replayed frontier
    b = ex.process("s", dict(step))
    np.testing.assert_allclose(b["logits"], a["logits"], rtol=1e-6, atol=1e-6)


def test_executor_session_isolation():
    cfg = TINY
    params = qwen3.init_params(cfg, jax.random.PRNGKey(0))
    spec = StageSpec(0, 1, 0, cfg.num_layers - 1)
    ex = Qwen3StageExecutor(cfg, spec, extract_stage_params(params, cfg, spec), max_len=64)
    a = ex.process("a", {"tokens": np.asarray([[1, 2, 3]]), "start_pos": 0})
    b = ex.process("b", {"tokens": np.asarray([[9, 8]]), "start_pos": 0})
    a2 = ex.process("a", {"tokens": np.asarray([[4]]), "start_pos": 3})
    assert a["logits"].shape == b["logits"].shape == a2["logits"].shape
    assert len(ex.sessions) == 2
    ex.end_session("a")
    assert len(ex.sessions) == 1


def test_counter_executor_chain():
    specs = [StageSpec(s, 3, s, s) for s in range(3)]
    execs = [CounterStageExecutor(sp) for sp in specs]
    payload = {}
    for ex in execs:
        payload = ex.process("sess", payload)
    assert payload["result_for_user"]["state"] == 3
    assert payload["result_for_user"]["trace"] == [0, 1, 2]


def test_split_tool_cli(tmp_path):
    from inferd_tpu.tools.split_model import main

    main(["--model", "tiny", "--stages", "2", "--out", str(tmp_path), "--random-init"])
    p, spec, name = load_stage_checkpoint(stage_checkpoint_path(str(tmp_path), 0))
    assert name == "tiny" and spec.is_first and "embed" in p
