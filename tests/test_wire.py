"""Wire codec tests: round-trips, bf16, and malformed-payload rejection
(the reference shipped pickle on the wire — SURVEY B8; this codec must
never execute anything)."""

import numpy as np
import pytest

import ml_dtypes

from inferd_tpu.runtime import wire


def test_roundtrip_nested():
    payload = {
        "task_id": "t1",
        "stage": 2,
        "payload": {
            "hidden": np.random.randn(1, 4, 8).astype(np.float32),
            "start_pos": 7,
            "flags": [True, None, "x"],
        },
    }
    out = wire.unpack(wire.pack(payload))
    assert out["task_id"] == "t1" and out["stage"] == 2
    np.testing.assert_array_equal(out["payload"]["hidden"], payload["payload"]["hidden"])
    assert out["payload"]["flags"] == [True, None, "x"]


def test_roundtrip_bf16():
    a = np.arange(12, dtype=np.float32).reshape(3, 4).astype(ml_dtypes.bfloat16)
    out = wire.unpack(wire.pack({"x": a}))
    assert out["x"].dtype == np.dtype(ml_dtypes.bfloat16)
    np.testing.assert_array_equal(out["x"].astype(np.float32), a.astype(np.float32))


def test_roundtrip_int_dtypes():
    for dt in (np.int32, np.int64, np.uint8, np.bool_):
        a = np.array([[1, 0], [0, 1]], dtype=dt)
        out = wire.unpack(wire.pack({"x": a}))
        assert out["x"].dtype == a.dtype
        np.testing.assert_array_equal(out["x"], a)


def test_rejects_bad_shape():
    import struct

    blob = wire.pack({"x": np.zeros(4, dtype=np.float32)})
    # tamper: claim dim 5 where the payload holds 4 elements (wire v1
    # encodes dims as little-endian u64 after the dtype name)
    dim4, dim5 = struct.pack("<Q", 4), struct.pack("<Q", 5)
    assert dim4 in blob
    tampered = blob.replace(dim4, dim5, 1)
    with pytest.raises(ValueError):
        wire.unpack(tampered)


def test_rejects_disallowed_dtype():
    import msgpack

    evil = msgpack.packb(
        {"x": {"__nd__": 1, "dtype": "object", "shape": [1], "data": b"x"}},
        use_bin_type=True,
    )
    with pytest.raises(ValueError, match="disallowed"):
        wire.unpack(evil)


def test_scalar_array():
    out = wire.unpack(wire.pack({"s": np.float32(3.5)}))
    assert out["s"].shape == () and float(out["s"]) == 3.5


def _mk_decode_env(i, h=None):
    return {
        "task_id": f"t{i}",
        "session_id": f"s{i}",
        "stage": 1,
        "payload": {
            "hidden": (
                h if h is not None
                else np.random.randn(1, 1, 8).astype(np.float32)
            ),
            "start_pos": 4 + i,
            "real_len": 1,
        },
        **({"route": {"1": "10.0.0.9:6050"}} if i % 2 else {}),
    }


def _assert_env_equal(a, b):
    np.testing.assert_array_equal(a["payload"]["hidden"], b["payload"]["hidden"])
    for k in ("task_id", "session_id", "stage"):
        assert a[k] == b[k]
    assert a["payload"]["start_pos"] == b["payload"]["start_pos"]
    assert a.get("route") == b.get("route")


def test_multi_envelope_roundtrip_both_generations(monkeypatch):
    """coalesce_forward -> pack -> unpack -> split_forward must be exact
    through BOTH wire generations (v1 and legacy msgpack): the coalesced
    relay envelope is plain dicts/lists/tensors, no new wire tags."""
    envs = [_mk_decode_env(i) for i in range(3)]
    menv = wire.coalesce_forward(envs)
    assert np.asarray(menv["hidden"]).shape == (3, 1, 8)
    for legacy in (False, True):
        if legacy:
            monkeypatch.setenv("INFERD_WIRE", "legacy")
        else:
            monkeypatch.delenv("INFERD_WIRE", raising=False)
        blob = wire.pack(menv)
        back = wire.split_forward(wire.unpack(blob))
        assert len(back) == 3
        for orig, got in zip(envs, back):
            _assert_env_equal(orig, got)


def test_multi_envelope_v1_native_pyimpl_byte_identical():
    """The v1 frame for a multi envelope must be byte-identical between
    the native codec and the pure-Python fallback (mixed builds
    interoperate)."""
    from inferd_tpu import native as _native
    from inferd_tpu.native import pyimpl

    envs = [_mk_decode_env(i) for i in range(2)]
    menv = wire.coalesce_forward(envs)
    py = pyimpl.pack(menv, _native.tensor_parts)
    if _native.codec is not None:
        assert _native.codec.pack(menv) == py
    # and the pyimpl frame decodes to the same envelopes either way
    back = wire.split_forward(
        pyimpl.unpack(py, _native.tensor_build)
    )
    for orig, got in zip(envs, back):
        _assert_env_equal(orig, got)


def test_multi_reply_roundtrip():
    """The multi REPLY ({"multi": [{"status", "body"(bytes)}]}) carries
    raw pre-packed per-session reply bodies through both generations."""
    inner = wire.pack({"result_for_user": {"logits": np.zeros((1, 4), np.float32)}})
    reply = {"multi": [{"status": 200, "body": inner}, {"status": 409, "body": b"x"}]}
    for packer in (wire.pack, wire.pack_legacy):
        out = wire.unpack(packer(reply))
        assert out["multi"][0]["status"] == 200
        assert bytes(out["multi"][0]["body"]) == inner
        assert out["multi"][1]["status"] == 409
    nested = wire.unpack(bytes(wire.unpack(wire.pack(reply))["multi"][0]["body"]))
    assert "result_for_user" in nested


def test_single_session_traffic_unchanged_by_multi_support():
    """Mixed-version guarantee: a NEW node that never coalesces emits
    byte-identical single-session envelopes — an old node (modeled by the
    codec alone, which predates the multi keys) decodes them exactly as
    before."""
    env = _mk_decode_env(0)
    blob = wire.pack(env)
    out = wire.unpack(blob)
    assert wire.MULTI_KEY not in out
    _assert_env_equal(env, out)


def test_coalesce_rejects_mixed_and_malformed():
    a, b = _mk_decode_env(0), _mk_decode_env(1)
    b["stage"] = 2
    with pytest.raises(ValueError, match="mixed stages"):
        wire.coalesce_forward([a, b])
    with pytest.raises(ValueError, match=">= 2"):
        wire.coalesce_forward([a])
    c = _mk_decode_env(2)
    c["payload"]["hidden"] = np.zeros((1, 3, 8), np.float32)  # not a decode row
    with pytest.raises(ValueError, match="decode row"):
        wire.coalesce_forward([a, c])
    menv = wire.coalesce_forward([_mk_decode_env(0), _mk_decode_env(1)])
    menv["multi"] = menv["multi"][:1]  # frame/row misalignment
    with pytest.raises(ValueError, match="frames vs hidden"):
        wire.split_forward(menv)


def test_stage_output_rides_wire_unpadded():
    """A 17-token prompt chunk is bucket-padded to 32 for jit, but only the
    17 real rows may ride the wire (VERDICT r1 weak #7); the downstream
    stage re-pads locally and produces identical hidden states."""
    import jax

    from inferd_tpu.config import TINY
    from inferd_tpu.models import qwen3
    from inferd_tpu.parallel.stages import Manifest, extract_stage_params
    from inferd_tpu.runtime.executor import Qwen3StageExecutor

    params = qwen3.init_params(TINY, jax.random.PRNGKey(0))
    manifest = Manifest.even_split("tiny", 2)
    ex0, ex1 = [
        Qwen3StageExecutor(
            TINY, spec, extract_stage_params(params, TINY, spec), max_len=64
        )
        for spec in manifest.stage_specs()
    ]
    toks = np.arange(17, dtype=np.int32)[None] % TINY.vocab_size
    out0 = ex0.process("s", {"tokens": toks, "start_pos": 0, "real_len": 17})
    assert out0["hidden"].shape[1] == 17  # sliced, not the 32-row bucket
    # the next hop's envelope is correspondingly small
    blob = wire.pack({"payload": out0})
    padded_rows = 32 * TINY.hidden_size * 4
    real_rows = 17 * TINY.hidden_size * 4
    assert real_rows <= len(blob) < padded_rows
    # downstream stage accepts the trimmed chunk and yields last-token logits
    out1 = ex1.process("s", dict(out0))
    assert out1["logits"].shape == (1, TINY.vocab_size)
