"""Wire codec tests: round-trips, bf16, and malformed-payload rejection
(the reference shipped pickle on the wire — SURVEY B8; this codec must
never execute anything)."""

import numpy as np
import pytest

import ml_dtypes

from inferd_tpu.runtime import wire


def test_roundtrip_nested():
    payload = {
        "task_id": "t1",
        "stage": 2,
        "payload": {
            "hidden": np.random.randn(1, 4, 8).astype(np.float32),
            "start_pos": 7,
            "flags": [True, None, "x"],
        },
    }
    out = wire.unpack(wire.pack(payload))
    assert out["task_id"] == "t1" and out["stage"] == 2
    np.testing.assert_array_equal(out["payload"]["hidden"], payload["payload"]["hidden"])
    assert out["payload"]["flags"] == [True, None, "x"]


def test_roundtrip_bf16():
    a = np.arange(12, dtype=np.float32).reshape(3, 4).astype(ml_dtypes.bfloat16)
    out = wire.unpack(wire.pack({"x": a}))
    assert out["x"].dtype == np.dtype(ml_dtypes.bfloat16)
    np.testing.assert_array_equal(out["x"].astype(np.float32), a.astype(np.float32))


def test_roundtrip_int_dtypes():
    for dt in (np.int32, np.int64, np.uint8, np.bool_):
        a = np.array([[1, 0], [0, 1]], dtype=dt)
        out = wire.unpack(wire.pack({"x": a}))
        assert out["x"].dtype == a.dtype
        np.testing.assert_array_equal(out["x"], a)


def test_rejects_bad_shape():
    import struct

    blob = wire.pack({"x": np.zeros(4, dtype=np.float32)})
    # tamper: claim dim 5 where the payload holds 4 elements (wire v1
    # encodes dims as little-endian u64 after the dtype name)
    dim4, dim5 = struct.pack("<Q", 4), struct.pack("<Q", 5)
    assert dim4 in blob
    tampered = blob.replace(dim4, dim5, 1)
    with pytest.raises(ValueError):
        wire.unpack(tampered)


def test_rejects_disallowed_dtype():
    import msgpack

    evil = msgpack.packb(
        {"x": {"__nd__": 1, "dtype": "object", "shape": [1], "data": b"x"}},
        use_bin_type=True,
    )
    with pytest.raises(ValueError, match="disallowed"):
        wire.unpack(evil)


def test_scalar_array():
    out = wire.unpack(wire.pack({"s": np.float32(3.5)}))
    assert out["s"].shape == () and float(out["s"]) == 3.5


def test_stage_output_rides_wire_unpadded():
    """A 17-token prompt chunk is bucket-padded to 32 for jit, but only the
    17 real rows may ride the wire (VERDICT r1 weak #7); the downstream
    stage re-pads locally and produces identical hidden states."""
    import jax

    from inferd_tpu.config import TINY
    from inferd_tpu.models import qwen3
    from inferd_tpu.parallel.stages import Manifest, extract_stage_params
    from inferd_tpu.runtime.executor import Qwen3StageExecutor

    params = qwen3.init_params(TINY, jax.random.PRNGKey(0))
    manifest = Manifest.even_split("tiny", 2)
    ex0, ex1 = [
        Qwen3StageExecutor(
            TINY, spec, extract_stage_params(params, TINY, spec), max_len=64
        )
        for spec in manifest.stage_specs()
    ]
    toks = np.arange(17, dtype=np.int32)[None] % TINY.vocab_size
    out0 = ex0.process("s", {"tokens": toks, "start_pos": 0, "real_len": 17})
    assert out0["hidden"].shape[1] == 17  # sliced, not the 32-row bucket
    # the next hop's envelope is correspondingly small
    blob = wire.pack({"payload": out0})
    padded_rows = 32 * TINY.hidden_size * 4
    real_rows = 17 * TINY.hidden_size * 4
    assert real_rows <= len(blob) < padded_rows
    # downstream stage accepts the trimmed chunk and yields last-token logits
    out1 = ex1.process("s", dict(out0))
    assert out1["logits"].shape == (1, TINY.vocab_size)
