"""Dashboard / collector / plot tests (the reference's dashboard +
metrics-CSV + notebook trio, SURVEY §2 'Console dashboard' / 'Multi-node
rebalance sim' / 'Metrics plots', as asserted units)."""

import asyncio
import csv
import io
import os

import pytest

from inferd_tpu.tools.collector import FIELDS, Collector, stage_rows
from inferd_tpu.tools.dashboard import Dashboard, gossip_source, render_table

SAMPLE = {
    0: {"10.0.0.2:6050": {"name": "node0", "load": 1, "cap": 4, "model": "qwen3-0.6b"}},
    1: {
        "10.0.0.3:6050": {"name": "node1", "load": 3, "cap": 4, "model": "qwen3-0.6b"},
        "10.0.0.4:6050": {"name": "node2", "load": 0, "cap": 4, "model": "qwen3-0.6b"},
    },
    2: {},
}


def test_render_table_contents():
    text = render_table(SAMPLE, ts=0.0)
    assert "node0" in text and "10.0.0.3:6050" in text
    assert "<no servers>" in text  # empty stage shown, not hidden
    assert "3 node(s), 3 stage(s)" in text
    # one line per node + header/rules/footer
    assert len(text.splitlines()) == 3 + 4 + 1


def test_stage_rows_aggregation():
    rows = stage_rows(SAMPLE, ts=100.0)
    assert [r["stage"] for r in rows] == [0, 1, 2]
    r1 = rows[1]
    assert r1["servers"] == 2
    assert r1["tasks_running"] == 3
    assert r1["total_cap"] == 8
    assert r1["min_load"] == 0 and r1["max_load"] == 3
    r2 = rows[2]
    assert r2["servers"] == 0 and r2["tasks_running"] == 0


@pytest.mark.asyncio
async def test_dashboard_renders_from_source():
    calls = []

    async def source():
        calls.append(1)
        return SAMPLE

    out = io.StringIO()
    dash = Dashboard(source, period_s=0.01, out=out, clear_screen=False)
    text = await dash.render_once()
    assert "node0" in text
    assert calls == [1]


@pytest.mark.asyncio
async def test_collector_writes_csv():
    async def source():
        return SAMPLE

    buf = io.StringIO()
    c = Collector(source, buf, period_s=0.01)
    await c.sample_once()
    await c.sample_once()
    rows = list(csv.DictReader(io.StringIO(buf.getvalue())))
    assert len(rows) == 6  # 3 stages x 2 samples
    assert rows[0]["stage"] == "0"
    assert rows[1]["tasks_running"] == "3"


@pytest.mark.asyncio
async def test_gossip_observer_sees_swarm():
    """A silent gossip observer converges on the nodes' records without
    announcing anything itself."""
    from inferd_tpu.control.dht import SwarmDHT

    base = 19300
    a = SwarmDHT("a", base, host="127.0.0.1", gossip_period_s=0.05, ttl_s=5.0)
    b = SwarmDHT(
        "b", base + 1, bootstrap=[("127.0.0.1", base)], host="127.0.0.1",
        gossip_period_s=0.05, ttl_s=5.0,
    )
    await a.start()
    await b.start()
    a.announce({"stage": 0, "load": 0, "cap": 4, "name": "a"})
    b.announce({"stage": 1, "load": 1, "cap": 4, "name": "b"})
    source, start, stop = gossip_source([("127.0.0.1", base)], num_stages=2, listen_port=base + 2)
    await start()
    try:
        for _ in range(100):
            m = await source()
            if m[0] and m[1]:
                break
            await asyncio.sleep(0.05)
        assert m[0] and m[1], m
        # the observer never announced: nodes must not see a third record
        assert len(a.alive_records()) == 2
    finally:
        await stop()
        await a.stop()
        await b.stop()


def test_plot_metrics_renders_png(tmp_path):
    from inferd_tpu.tools import plot_metrics

    csv_path = tmp_path / "m.csv"
    with open(csv_path, "w", newline="") as f:
        w = csv.DictWriter(f, fieldnames=FIELDS)
        w.writeheader()
        for t in range(5):
            for s in range(2):
                w.writerow(
                    {
                        "ts": 100 + t, "stage": s, "servers": 1 + s,
                        "tasks_running": t % 3, "total_cap": 4,
                        "min_load": 0, "max_load": t % 3,
                    }
                )
    out = tmp_path / "m.png"
    plot_metrics.main([str(csv_path), "--out", str(out)])
    assert os.path.getsize(out) > 1000


def test_collector_explicit_hop_columns_and_aliases():
    """The PR 3 conflation fix: hop_p50_ms (median replica) and
    hop_p99_ms (WORST replica) keep their values as one-release aliases,
    while the explicit hop_p50_med_ms / hop_p99_worst_ms columns name
    the aggregation — and outlier-flagged replicas land in `outliers`."""
    from inferd_tpu.tools.collector import FIELDS, stage_rows

    sample = {
        0: {
            "a": {"load": 1, "cap": 4, "hop_p50_ms": 10.0, "hop_p99_ms": 50.0},
            "b": {"load": 0, "cap": 4, "hop_p50_ms": 20.0, "hop_p99_ms": 90.0,
                  "outlier": 1},
            "c": {"load": 0, "cap": 4, "hop_p50_ms": 30.0, "hop_p99_ms": 70.0},
        },
    }
    assert {"hop_p50_med_ms", "hop_p99_worst_ms", "outliers"} <= set(FIELDS)
    row = stage_rows(sample, ts=1.0)[0]
    assert row["hop_p50_med_ms"] == 20.0  # median replica's p50
    assert row["hop_p99_worst_ms"] == 90.0  # worst replica's p99
    # aliases carry the SAME values for one release
    assert row["hop_p50_ms"] == row["hop_p50_med_ms"]
    assert row["hop_p99_ms"] == row["hop_p99_worst_ms"]
    assert row["outliers"] == "b"


def test_collector_renders_rows_from_old_peers():
    """Mixed-version fleets: records from pre-PR-7 peers lack the
    windowed-quantile and outlier keys entirely — the collector must
    still emit their stage rows with blank cells, never crash or invent
    defaults."""
    from inferd_tpu.tools.collector import stage_rows

    sample = {
        0: {"old": {"load": 2, "cap": 4}},  # nothing but the PR-1 schema
        1: {
            "old2": {"load": 0, "cap": 4},
            "new": {"load": 0, "cap": 4, "hop_p50_ms": 5.0,
                    "hop_p99_ms": 9.0, "svc_p99_ms": 7.0},
        },
    }
    rows = stage_rows(sample, ts=1.0)
    assert rows[0]["hop_p50_med_ms"] == "" and rows[0]["outliers"] == ""
    # the single new replica's numbers still aggregate
    assert rows[1]["hop_p50_med_ms"] == 5.0
    assert rows[1]["hop_p99_worst_ms"] == 9.0


def test_dashboard_independent_hop_cells_and_outlier_marker():
    """The dashboard renders hop p50 and p99 as SEPARATE columns with
    independent '-' fallbacks (the old single cell blanked both when
    either was missing) plus the outlier marker."""
    from inferd_tpu.tools.dashboard import render_table

    table = render_table({
        0: {
            "10.0.0.2:6050": {"name": "full", "load": 0, "cap": 4,
                              "hop_p50_ms": 4.0, "hop_p99_ms": 40.0},
            "10.0.0.3:6050": {"name": "p50only", "load": 0, "cap": 4,
                              "hop_p50_ms": 6.0},
            "10.0.0.4:6050": {"name": "oldpeer", "load": 0, "cap": 4},
            "10.0.0.5:6050": {"name": "flagged", "load": 0, "cap": 4,
                              "hop_p50_ms": 5.0, "hop_p99_ms": 400.0,
                              "outlier": 1},
        },
    })
    assert "hop p50" in table and "hop p99" in table and "out" in table
    rows = {
        ln.split()[2]: ln.split()
        for ln in table.splitlines() if "10.0.0." in ln
    }
    # tokens: [stage, node, name, load/cap, hop_p50, hop_p99, out?/...]
    assert rows["full"][4] == "4" and rows["full"][5] == "40"
    # a peer carrying only p50 renders it, with "-" only for p99
    assert rows["p50only"][4] == "6" and rows["p50only"][5] == "-"
    assert rows["oldpeer"][4] == "-" and rows["oldpeer"][5] == "-"
    assert rows["flagged"][5] == "400" and rows["flagged"][6] == "!"
    # non-flagged rows collapse the empty out cell (next token is the
    # cobatch "-"), never a stray marker
    assert "!" not in rows["oldpeer"]
