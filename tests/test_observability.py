"""Dashboard / collector / plot tests (the reference's dashboard +
metrics-CSV + notebook trio, SURVEY §2 'Console dashboard' / 'Multi-node
rebalance sim' / 'Metrics plots', as asserted units)."""

import asyncio
import csv
import io
import os

import pytest

from inferd_tpu.tools.collector import FIELDS, Collector, stage_rows
from inferd_tpu.tools.dashboard import Dashboard, gossip_source, render_table

SAMPLE = {
    0: {"10.0.0.2:6050": {"name": "node0", "load": 1, "cap": 4, "model": "qwen3-0.6b"}},
    1: {
        "10.0.0.3:6050": {"name": "node1", "load": 3, "cap": 4, "model": "qwen3-0.6b"},
        "10.0.0.4:6050": {"name": "node2", "load": 0, "cap": 4, "model": "qwen3-0.6b"},
    },
    2: {},
}


def test_render_table_contents():
    text = render_table(SAMPLE, ts=0.0)
    assert "node0" in text and "10.0.0.3:6050" in text
    assert "<no servers>" in text  # empty stage shown, not hidden
    assert "3 node(s), 3 stage(s)" in text
    # one line per node + header/rules/footer
    assert len(text.splitlines()) == 3 + 4 + 1


def test_stage_rows_aggregation():
    rows = stage_rows(SAMPLE, ts=100.0)
    assert [r["stage"] for r in rows] == [0, 1, 2]
    r1 = rows[1]
    assert r1["servers"] == 2
    assert r1["tasks_running"] == 3
    assert r1["total_cap"] == 8
    assert r1["min_load"] == 0 and r1["max_load"] == 3
    r2 = rows[2]
    assert r2["servers"] == 0 and r2["tasks_running"] == 0


@pytest.mark.asyncio
async def test_dashboard_renders_from_source():
    calls = []

    async def source():
        calls.append(1)
        return SAMPLE

    out = io.StringIO()
    dash = Dashboard(source, period_s=0.01, out=out, clear_screen=False)
    text = await dash.render_once()
    assert "node0" in text
    assert calls == [1]


@pytest.mark.asyncio
async def test_collector_writes_csv():
    async def source():
        return SAMPLE

    buf = io.StringIO()
    c = Collector(source, buf, period_s=0.01)
    await c.sample_once()
    await c.sample_once()
    rows = list(csv.DictReader(io.StringIO(buf.getvalue())))
    assert len(rows) == 6  # 3 stages x 2 samples
    assert rows[0]["stage"] == "0"
    assert rows[1]["tasks_running"] == "3"


@pytest.mark.asyncio
async def test_gossip_observer_sees_swarm():
    """A silent gossip observer converges on the nodes' records without
    announcing anything itself."""
    from inferd_tpu.control.dht import SwarmDHT

    base = 19300
    a = SwarmDHT("a", base, host="127.0.0.1", gossip_period_s=0.05, ttl_s=5.0)
    b = SwarmDHT(
        "b", base + 1, bootstrap=[("127.0.0.1", base)], host="127.0.0.1",
        gossip_period_s=0.05, ttl_s=5.0,
    )
    await a.start()
    await b.start()
    a.announce({"stage": 0, "load": 0, "cap": 4, "name": "a"})
    b.announce({"stage": 1, "load": 1, "cap": 4, "name": "b"})
    source, start, stop = gossip_source([("127.0.0.1", base)], num_stages=2, listen_port=base + 2)
    await start()
    try:
        for _ in range(100):
            m = await source()
            if m[0] and m[1]:
                break
            await asyncio.sleep(0.05)
        assert m[0] and m[1], m
        # the observer never announced: nodes must not see a third record
        assert len(a.alive_records()) == 2
    finally:
        await stop()
        await a.stop()
        await b.stop()


def test_plot_metrics_renders_png(tmp_path):
    from inferd_tpu.tools import plot_metrics

    csv_path = tmp_path / "m.csv"
    with open(csv_path, "w", newline="") as f:
        w = csv.DictWriter(f, fieldnames=FIELDS)
        w.writeheader()
        for t in range(5):
            for s in range(2):
                w.writerow(
                    {
                        "ts": 100 + t, "stage": s, "servers": 1 + s,
                        "tasks_running": t % 3, "total_cap": 4,
                        "min_load": 0, "max_load": t % 3,
                    }
                )
    out = tmp_path / "m.png"
    plot_metrics.main([str(csv_path), "--out", str(out)])
    assert os.path.getsize(out) > 1000
