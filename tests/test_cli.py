"""CLI-surface tests: run_node bootstrap parsing/config precedence, seed
node, and an end-to-end counter-backend swarm started purely through the
run_node entrypoint (the reference's run_node.py:40-86 flow)."""

import asyncio
import os

import pytest

from inferd_tpu.parallel.stages import Manifest
from inferd_tpu.tools.run_node import build_parser, get_own_ip, parse_bootstrap

EXAMPLE = os.path.join(os.path.dirname(__file__), "..", "examples", "cluster.yaml")


def test_parse_bootstrap():
    assert parse_bootstrap(None) == []
    assert parse_bootstrap("") == []
    assert parse_bootstrap("10.0.0.2:7050") == [("10.0.0.2", 7050)]
    assert parse_bootstrap("a:1, b:2 ,") == [("a", 1), ("b", 2)]
    with pytest.raises(ValueError):
        parse_bootstrap("no-port")


def test_get_own_ip_returns_address():
    ip = get_own_ip()
    assert ip.count(".") == 3


def test_example_manifest_valid():
    m = Manifest.from_yaml(EXAMPLE)
    m.validate()
    assert m.num_stages == 3
    assert len(m.nodes) == 4  # stage 2 replicated
    assert m.stage_spec(2).start_layer == 20


def test_parser_env_precedence(monkeypatch):
    monkeypatch.setenv("NODE_NAME", "node1")
    monkeypatch.setenv("BOOTSTRAP_NODES", "127.0.0.1:7051")
    monkeypatch.setenv("NODE_PORT", "6123")
    args = build_parser().parse_args(["--manifest", EXAMPLE])
    assert args.name == "node1"
    assert args.bootstrap == "127.0.0.1:7051"
    assert args.port == 6123
    # CLI flag wins over env
    args = build_parser().parse_args(["--manifest", EXAMPLE, "--name", "node2"])
    assert args.name == "node2"


@pytest.mark.asyncio
async def test_run_node_entrypoint_counter_swarm(tmp_path, unused_tcp_port_base=18600):
    """Start a 2-stage counter swarm via the run_node module's wiring (not
    raw Node construction) and drive one task through it."""
    from inferd_tpu.client.swarm_client import SwarmClient
    from inferd_tpu.tools import run_node as rn

    manifest_text = """
model_name: tiny
stages_count: 2
nodes:
  - {name: node0, stage: 0, start_layer: 0, end_layer: 1}
  - {name: node1, stage: 1, start_layer: 2, end_layer: 3}
"""
    mpath = tmp_path / "cluster.yaml"
    mpath.write_text(manifest_text)

    base = unused_tcp_port_base
    tasks = []
    stop_events = []

    async def start_one(name, stage, idx):
        argv = [
            "--manifest", str(mpath), "--name", name, "--backend", "counter",
            "--host", "127.0.0.1", "--port", str(base + idx),
            "--gossip-port", str(base + 100 + idx),
            "--bootstrap", f"127.0.0.1:{base + 100}" if idx else "",
            "--rebalance-period", "600",
        ]
        args = rn.build_parser().parse_args(argv)
        # run the node's coroutine but swap the blocking wait for our event
        stop = asyncio.Event()
        stop_events.append(stop)

        async def runner():
            from inferd_tpu.control.dht import SwarmDHT
            from inferd_tpu.runtime.node import Node, NodeInfo

            m = Manifest.from_yaml(args.manifest)
            spec = m.node(args.name)
            info = NodeInfo(
                name=args.name, host=args.host, port=args.port,
                stage=spec.stage, num_stages=m.num_stages,
                capacity=args.capacity, model_name=m.model_name,
            )
            dht = SwarmDHT(
                info.node_id, args.gossip_port,
                bootstrap=rn.parse_bootstrap(args.bootstrap),
                host="127.0.0.1", gossip_period_s=0.05, ttl_s=2.0,
            )
            node = Node(
                info, m.config, args.parts, dht, backend=args.backend,
                rebalance_period_s=args.rebalance_period,
            )
            await node.start()
            await stop.wait()
            await node.stop()

        t = asyncio.create_task(runner())
        tasks.append(t)

    await start_one("node0", 0, 0)
    await start_one("node1", 1, 1)
    try:
        # wait for convergence then run a counter task end to end
        async with SwarmClient([("127.0.0.1", base)]) as client:
            for _ in range(100):
                try:
                    resp = await client._post(
                        "/forward",
                        {"stage": 0, "session_id": "s1", "payload": {"state": 0}},
                    )
                    break
                except Exception:
                    await asyncio.sleep(0.1)
            else:
                raise TimeoutError("swarm never served the task")
            r = resp["result_for_user"]["result_for_user"]
            assert r["state"] == 2  # one increment per stage
            assert r["trace"] == [0, 1]
    finally:
        for e in stop_events:
            e.set()
        await asyncio.gather(*tasks, return_exceptions=True)


def test_multihost_flags_parse(monkeypatch):
    """--coordinator/--num-processes/--process-id (and their env forms)
    parse; jax.distributed is only initialized when a coordinator is set."""
    from inferd_tpu.tools.run_node import build_parser

    args = build_parser().parse_args(
        ["--coordinator", "10.0.0.1:1234", "--num-processes", "4", "--process-id", "2"]
    )
    assert args.coordinator == "10.0.0.1:1234"
    assert args.num_processes == 4 and args.process_id == 2

    monkeypatch.setenv("INFERD_COORDINATOR", "h:1")
    monkeypatch.setenv("INFERD_NUM_PROCESSES", "8")
    monkeypatch.setenv("INFERD_PROCESS_ID", "7")
    args = build_parser().parse_args([])
    assert (args.coordinator, args.num_processes, args.process_id) == ("h:1", 8, 7)


def test_generate_cli_engines(capsys):
    """tools/generate drives every engine in-process (tokenizer-free)."""
    from inferd_tpu.tools.generate import main as gen_main

    base = ["--model", "tiny", "--random-init", "--prompt-ids", "3,7,11",
            "--max-new-tokens", "4", "--device", "cpu"]
    assert gen_main(base) == 0
    assert gen_main(base + ["--engine", "batched", "--lanes", "2"]) == 0
    assert gen_main(base + ["--engine", "speculative", "--temperature", "0"]) == 0
    assert gen_main(base + ["--quant", "int8", "--kv-dtype", "float8_e4m3fn"]) == 0
    outs = capsys.readouterr().out
    assert outs.count("generated ids:") == 4


def test_generate_cli_needs_prompt():
    from inferd_tpu.tools.generate import main as gen_main

    assert gen_main(["--model", "tiny", "--random-init", "--device", "cpu"]) == 2


@pytest.mark.asyncio
async def test_send_cli_against_live_swarm(tmp_path):
    """tools/send drives a live 2-node counter... qwen3 swarm end to end."""
    import jax

    from inferd_tpu.config import TINY
    from inferd_tpu.control.dht import SwarmDHT
    from inferd_tpu.models import qwen3 as qw
    from inferd_tpu.parallel.stages import Manifest, split_and_save
    from inferd_tpu.runtime.node import Node, NodeInfo
    from inferd_tpu.tools.send import _run, build_parser

    base = 18900
    params = qw.init_params(TINY, jax.random.PRNGKey(0))
    split_and_save(params, TINY, Manifest.even_split("tiny", 2), str(tmp_path))
    nodes = []
    for i in range(2):
        info = NodeInfo(
            name=f"sc{i}", host="127.0.0.1", port=base + i,
            stage=i, num_stages=2, capacity=4, model_name="tiny",
        )
        dht = SwarmDHT(
            info.node_id, base + 100 + i,
            bootstrap=[] if i == 0 else [("127.0.0.1", base + 100)],
            host="127.0.0.1", gossip_period_s=0.05, ttl_s=1.5,
        )
        nodes.append(Node(info, TINY, str(tmp_path), dht, backend="qwen3",
                          max_len=64, rebalance_period_s=600.0))
    for n in nodes:
        await n.start()
    try:
        args = build_parser().parse_args([
            "--entry", f"127.0.0.1:{base}", "--prompt-ids", "3,7,11",
            "--max-new-tokens", "5", "--temperature", "0",
            "--session-retries", "5",
        ])
        assert await _run(args) == 0
        # --routed: the chain is planned by D*-Lite over the gossip view
        # (bootstraps off node 0's gossip port as a records-less observer)
        args = build_parser().parse_args([
            "--routed", f"127.0.0.1:{base + 100}", "--num-stages", "2",
            "--prompt-ids", "3,7,11", "--max-new-tokens", "5",
            "--temperature", "0", "--session-retries", "5",
        ])
        assert await _run(args) == 0
        # --routed without --num-stages is a usage error
        args = build_parser().parse_args([
            "--routed", f"127.0.0.1:{base + 100}", "--prompt-ids", "3",
        ])
        assert await _run(args) == 2
    finally:
        for n in nodes:
            await n.stop()


def test_bench_battery_arg_validation(tmp_path):
    """Battery leg-name validation + smoke-leg listing (the machinery that
    turns hardware windows into committed bench_artifacts/ JSONL)."""
    from inferd_tpu.tools.bench_battery import DEFAULT_LEGS, SMOKE_LEGS, main

    assert main(["--legs", "nonexistent", "--smoke"]) == 2
    names = {n for n, _, _ in DEFAULT_LEGS}
    # the verdict's requested legs are all present
    for want in ("decode", "decode_ctx8k", "decode_ctx8k_fp8kv", "decode_int8",
                 "decode_int8_kernel", "prefill", "batched_lanes8",
                 "gemma2_ctx8k", "decode_8b_int8", "anatomy"):
        assert want in names
    assert all(len(l) == 3 for l in SMOKE_LEGS)


def test_package_import_initializes_no_jax_backend():
    """Importing the package (models, engines, parallel, runtime, tools)
    must allocate NOTHING on a device: a module-level jnp constant would
    initialize a jax backend at import time — on tunneled-TPU hosts whose
    sitecustomize overrides jax_platforms, that dials remote hardware
    before any CLI's --device pin can run (a real hang this test pins)."""
    import subprocess
    import sys

    code = (
        "import importlib, pkgutil\n"
        "import inferd_tpu\n"
        "for m in pkgutil.walk_packages(inferd_tpu.__path__, 'inferd_tpu.'):\n"
        "    importlib.import_module(m.name)  # EVERY module, no hand list\n"
        "from jax._src import xla_bridge\n"
        "assert not xla_bridge.backends_are_initialized(), "
        "'package import initialized a jax backend'\n"
        "print('clean')\n"
    )
    out = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        timeout=180, cwd=os.path.join(os.path.dirname(__file__), ".."),
    )
    assert out.returncode == 0, (out.stdout, out.stderr)
    assert "clean" in out.stdout
