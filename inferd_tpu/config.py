"""Model hyperparameter configs for the Qwen3 family.

Capability parity with the reference's static constants class
(/root/reference/models/qwen3/qwen3_config.py:1-25) — redesigned as a frozen
dataclass so configs are hashable (usable as jit static args) and so the
framework supports multiple model sizes, not one hardcoded set.
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class ModelConfig:
    """Architecture hyperparameters for a Qwen3-family causal LM."""

    name: str = "qwen3-0.6b"
    vocab_size: int = 151936
    hidden_size: int = 1024
    intermediate_size: int = 3072
    num_layers: int = 28
    num_heads: int = 16
    num_kv_heads: int = 8
    head_dim: int = 128
    rms_norm_eps: float = 1e-6
    rope_theta: float = 1_000_000.0
    max_position_embeddings: int = 40960
    tie_word_embeddings: bool = True
    dtype: str = "bfloat16"

    # KV cache storage dtype: "model" stores cache entries in `dtype`;
    # "float8_e4m3fn" halves long-context decode's dominant HBM read (the
    # KV buffer: 28 layers x T x 8 heads x 128 dims x 2 for Qwen3-0.6B
    # already outweighs the weights past ~8K tokens). Writes SATURATE to
    # the dtype's range (e4m3 has no inf — an unclamped V outlier would
    # poison the cache with NaN); reads upcast on the XLA attention path,
    # where the convert fuses into the score einsum.
    kv_dtype: str = "model"

    # Attention implementation: "auto" (Pallas flash kernel on TPU, XLA
    # elsewhere), "flash", "flash_interpret" (kernel in the Pallas
    # interpreter — CPU-testable), or "xla".
    attn_impl: str = "auto"

    # Family knobs: Qwen3 uses per-head q/k RMSNorm and no attention bias;
    # Qwen2 (the reference's swarm-path model, Qwen2-0.5B —
    # /root/reference/petals/inferd.yaml:1) is the reverse. Llama-3 uses
    # neither knob and (3.1+) frequency-dependent "llama3" RoPE scaling.
    qk_norm: bool = True
    attn_bias: bool = False

    # RoPE scaling: "none", "llama3" (Llama-3.1+ long-context scheme:
    # low-frequency bands divided by `rope_scaling_factor`, high-frequency
    # bands untouched, smooth ramp between), or "yarn" (NTK-by-parts
    # interpolation with an attention-temperature factor on cos/sin —
    # GPT-OSS) — both matching HF rope_utils exactly.
    rope_scaling: str = "none"
    rope_scaling_factor: float = 8.0
    rope_low_freq_factor: float = 1.0
    rope_high_freq_factor: float = 4.0
    rope_original_max_position: int = 8192
    # yarn-only: ramp boundaries in rotations, correction-range truncation,
    # and the cos/sin attention factor (0 = derive 0.1*ln(factor)+1)
    rope_beta_fast: float = 32.0
    rope_beta_slow: float = 1.0
    rope_truncate: bool = True
    rope_attention_factor: float = 0.0

    # MoE (Qwen3-MoE family); num_experts == 0 means dense MLP.
    num_experts: int = 0
    num_experts_per_tok: int = 0
    moe_intermediate_size: int = 0
    norm_topk_prob: bool = True

    # GPT-OSS family knobs (all off elsewhere):
    #   moe_router_mode — "softmax_topk" (Qwen/Mixtral: probs over ALL
    #                     experts, then top-k) or "topk_softmax" (GPT-OSS:
    #                     top-k over LOGITS, softmax over the k values)
    #   router_bias / moe_bias — biases on the router / expert projections
    #   swiglu_limit  — >0: clamped GLU experts (gate<=limit, |up|<=limit,
    #                   glu = gate*sigmoid(1.702*gate), out = (up+1)*glu)
    #   attn_sinks    — per-head learned sink logit joining the softmax
    #                   denominator (an always-attendable virtual slot)
    #   o_bias        — bias on the attention output projection too
    moe_router_mode: str = "softmax_topk"
    router_bias: bool = False
    moe_bias: bool = False
    swiglu_limit: float = 0.0
    attn_sinks: bool = False
    o_bias: bool = False

    # Gemma-2 family knobs (all off for Qwen/Llama):
    #   sandwich_norm  — norms BOTH before and after each sublayer (the
    #                    post-norms apply to the sublayer output pre-residual)
    #   rms_norm_plus_one — RMSNorm scales by (1 + w); weights init to zero
    #   hidden_act     — MLP gate activation: "silu" or "gelu_tanh"
    #   scale_embedding — multiply embeddings by sqrt(hidden_size)
    #   attn_logit_softcap / final_logit_softcap — cap*tanh(x/cap), 0 = off
    #   query_pre_attn_scalar — attention scores scale by this**-0.5
    #                    instead of head_dim**-0.5 (0 = use head_dim)
    #   sliding_window — local attention window on EVEN layer indices
    #                    (odd layers stay global); 0 = all layers global
    sandwich_norm: bool = False
    rms_norm_plus_one: bool = False
    hidden_act: str = "silu"
    scale_embedding: bool = False
    attn_logit_softcap: float = 0.0
    final_logit_softcap: float = 0.0
    query_pre_attn_scalar: float = 0.0
    sliding_window: int = 0

    @property
    def is_moe(self) -> bool:
        return self.num_experts > 0

    @property
    def q_dim(self) -> int:
        return self.num_heads * self.head_dim

    @property
    def kv_dim(self) -> int:
        return self.num_kv_heads * self.head_dim

    @property
    def jnp_dtype(self):
        return jnp.dtype(self.dtype)

    @property
    def kv_jnp_dtype(self):
        return jnp.dtype(self.dtype if self.kv_dtype == "model" else self.kv_dtype)

    @property
    def attn_scale(self) -> float:
        base = self.query_pre_attn_scalar or self.head_dim
        return float(base) ** -0.5

    def with_layers(self, num_layers: int) -> "ModelConfig":
        return dataclasses.replace(self, num_layers=num_layers)


@dataclasses.dataclass(frozen=True)
class SamplingConfig:
    """Generation-time sampling knobs (reference: qwen3_config.py:5-7)."""

    temperature: float = 0.6
    top_k: int = 20
    top_p: float = 0.95
    # min-p filtering (HF MinPLogitsWarper, applied after top-p): drop
    # tokens whose probability is below min_p * max-prob; 0 = off
    min_p: float = 0.0
    max_new_tokens: int = 512


# ---------------------------------------------------------------------------
# Presets. Sizes cross-checked against the HF model cards for the Qwen3
# family; 0.6B matches the reference's constants (qwen3_config.py:10-25).
# ---------------------------------------------------------------------------

QWEN3_0_6B = ModelConfig(
    name="qwen3-0.6b",
    hidden_size=1024,
    intermediate_size=3072,
    num_layers=28,
    num_heads=16,
    num_kv_heads=8,
)

QWEN3_1_7B = ModelConfig(
    name="qwen3-1.7b",
    hidden_size=2048,
    intermediate_size=6144,
    num_layers=28,
    num_heads=16,
    num_kv_heads=8,
    tie_word_embeddings=True,
)

QWEN3_4B = ModelConfig(
    name="qwen3-4b",
    hidden_size=2560,
    intermediate_size=9728,
    num_layers=36,
    num_heads=32,
    num_kv_heads=8,
    tie_word_embeddings=True,
)

QWEN3_8B = ModelConfig(
    name="qwen3-8b",
    hidden_size=4096,
    intermediate_size=12288,
    num_layers=36,
    num_heads=32,
    num_kv_heads=8,
    tie_word_embeddings=False,
)

QWEN3_14B = ModelConfig(
    name="qwen3-14b",
    hidden_size=5120,
    intermediate_size=17408,
    num_layers=40,
    num_heads=40,
    num_kv_heads=8,
    tie_word_embeddings=False,
)

QWEN3_32B = ModelConfig(
    name="qwen3-32b",
    hidden_size=5120,
    intermediate_size=25600,
    num_layers=64,
    num_heads=64,
    num_kv_heads=8,
    tie_word_embeddings=False,
)

# Qwen2 family (the reference swarm path serves Qwen2-0.5B,
# /root/reference/petals/inferd.yaml:1-2; sizes from the HF model cards).
QWEN2_0_5B = ModelConfig(
    name="qwen2-0.5b",
    hidden_size=896,
    intermediate_size=4864,
    num_layers=24,
    num_heads=14,
    num_kv_heads=2,
    head_dim=64,
    max_position_embeddings=32768,
    tie_word_embeddings=True,
    qk_norm=False,
    attn_bias=True,
)

QWEN2_1_5B = ModelConfig(
    name="qwen2-1.5b",
    hidden_size=1536,
    intermediate_size=8960,
    num_layers=28,
    num_heads=12,
    num_kv_heads=2,
    head_dim=128,
    max_position_embeddings=32768,
    tie_word_embeddings=True,
    qk_norm=False,
    attn_bias=True,
)

QWEN2_7B = ModelConfig(
    name="qwen2-7b",
    vocab_size=152064,  # 7B uses the larger vocab (0.5B/1.5B: 151936)
    hidden_size=3584,
    intermediate_size=18944,
    num_layers=28,
    num_heads=28,
    num_kv_heads=4,
    head_dim=128,
    max_position_embeddings=32768,
    tie_word_embeddings=False,
    qk_norm=False,
    attn_bias=True,
)

# Llama family (added TPU-first scope beyond the reference's Qwen2/Qwen3:
# the decoder is fully config-driven, so Llama = knob settings + presets).
# Sizes per the HF model cards.

LLAMA32_1B = ModelConfig(
    name="llama3.2-1b",
    vocab_size=128256,
    hidden_size=2048,
    intermediate_size=8192,
    num_layers=16,
    num_heads=32,
    num_kv_heads=8,
    head_dim=64,
    rope_theta=500_000.0,
    max_position_embeddings=131072,
    tie_word_embeddings=True,
    qk_norm=False,
    attn_bias=False,
    rope_scaling="llama3",
    rope_scaling_factor=32.0,
    rope_low_freq_factor=1.0,
    rope_high_freq_factor=4.0,
    rope_original_max_position=8192,
)

LLAMA31_8B = ModelConfig(
    name="llama3.1-8b",
    vocab_size=128256,
    hidden_size=4096,
    intermediate_size=14336,
    num_layers=32,
    num_heads=32,
    num_kv_heads=8,
    head_dim=128,
    rope_theta=500_000.0,
    max_position_embeddings=131072,
    tie_word_embeddings=False,
    qk_norm=False,
    attn_bias=False,
    rope_scaling="llama3",
    rope_scaling_factor=8.0,
    rope_low_freq_factor=1.0,
    rope_high_freq_factor=4.0,
    rope_original_max_position=8192,
)

# Gemma-2 family (Google; sizes per the HF model cards). Architecturally
# the most distinct family in the zoo: sandwich norms, (1+w) RMSNorm,
# GeGLU, scaled embeddings, attention/final logit softcapping, and sliding-
# window attention on alternating layers — all config-driven in the shared
# decoder (models/qwen3.py).

GEMMA2_2B = ModelConfig(
    name="gemma2-2b",
    vocab_size=256000,
    hidden_size=2304,
    intermediate_size=9216,
    num_layers=26,
    num_heads=8,
    num_kv_heads=4,
    head_dim=256,
    rope_theta=10_000.0,
    max_position_embeddings=8192,
    tie_word_embeddings=True,
    qk_norm=False,
    attn_bias=False,
    sandwich_norm=True,
    rms_norm_plus_one=True,
    hidden_act="gelu_tanh",
    scale_embedding=True,
    attn_logit_softcap=50.0,
    final_logit_softcap=30.0,
    query_pre_attn_scalar=256.0,
    sliding_window=4096,
)

GEMMA2_9B = dataclasses.replace(
    GEMMA2_2B,
    name="gemma2-9b",
    hidden_size=3584,
    intermediate_size=14336,
    num_layers=42,
    num_heads=16,
    num_kv_heads=8,
)

GEMMA2_27B = dataclasses.replace(
    GEMMA2_2B,
    name="gemma2-27b",
    hidden_size=4608,
    intermediate_size=36864,
    num_layers=46,
    num_heads=32,
    num_kv_heads=16,
    head_dim=128,
    query_pre_attn_scalar=144.0,
)

# Mixtral (Mistral's MoE family; sizes per the HF model card). Routing is
# the same softmax-all → top-k → renormalize our moe_mlp implements for
# Qwen3-MoE (norm_topk_prob=True); arch is Llama-like (no q/k-norm, no
# attention bias, untied head).
MIXTRAL_8X7B = ModelConfig(
    name="mixtral-8x7b",
    vocab_size=32000,
    hidden_size=4096,
    intermediate_size=14336,
    num_layers=32,
    num_heads=32,
    num_kv_heads=8,
    head_dim=128,
    rope_theta=1_000_000.0,
    max_position_embeddings=32768,
    rms_norm_eps=1e-5,
    tie_word_embeddings=False,
    qk_norm=False,
    attn_bias=False,
    num_experts=8,
    num_experts_per_tok=2,
    moe_intermediate_size=14336,
    norm_topk_prob=True,
)

# GPT-OSS (OpenAI's open-weights MoE family; sizes per the HF configs).
# Every layer is MoE (top-4 of 32/128 clamped-GLU experts with biases,
# top-k-then-softmax routing), attention has per-head sink logits and
# biases on all four projections, sliding window 128 on even layers, and
# YaRN rope scaling (factor 32 over a 4096 pretraining window).
GPT_OSS_20B = ModelConfig(
    name="gpt-oss-20b",
    vocab_size=201088,
    hidden_size=2880,
    intermediate_size=2880,
    num_layers=24,
    num_heads=64,
    num_kv_heads=8,
    head_dim=64,
    rope_theta=150_000.0,
    max_position_embeddings=131072,
    rms_norm_eps=1e-5,
    tie_word_embeddings=False,
    qk_norm=False,
    attn_bias=True,
    o_bias=True,
    attn_sinks=True,
    sliding_window=128,
    rope_scaling="yarn",
    rope_scaling_factor=32.0,
    rope_original_max_position=4096,
    rope_beta_fast=32.0,
    rope_beta_slow=1.0,
    rope_truncate=False,
    num_experts=32,
    num_experts_per_tok=4,
    moe_intermediate_size=2880,
    moe_router_mode="topk_softmax",
    router_bias=True,
    moe_bias=True,
    swiglu_limit=7.0,
)

GPT_OSS_120B = dataclasses.replace(
    GPT_OSS_20B,
    name="gpt-oss-120b",
    num_layers=36,
    num_experts=128,
)

QWEN3_MOE_30B_A3B = ModelConfig(
    name="qwen3-moe-30b-a3b",
    hidden_size=2048,
    intermediate_size=6144,
    num_layers=48,
    num_heads=32,
    num_kv_heads=4,
    tie_word_embeddings=False,
    num_experts=128,
    num_experts_per_tok=8,
    moe_intermediate_size=768,
)

# Synthetic mid-size config for the default bench's paired pipeline leg
# (bench.py): big enough that a decode step's compute dominates the
# inter-stage hop (the regime the north-star ratio grades), small enough
# that interleaved paired trials finish in seconds on a 1-core CPU host.
# Qwen3 topology at reduced width — NOT a real checkpoint shape.
BENCH_PIPE = ModelConfig(
    name="bench-pipe",
    vocab_size=8192,
    hidden_size=512,
    intermediate_size=1536,
    num_layers=8,
    num_heads=8,
    num_kv_heads=4,
    head_dim=64,
    max_position_embeddings=2048,
    dtype="float32",
)

# Tiny configs for tests — same topology, toy widths.
TINY = ModelConfig(
    name="tiny",
    vocab_size=256,
    hidden_size=64,
    intermediate_size=128,
    num_layers=4,
    num_heads=4,
    num_kv_heads=2,
    head_dim=16,
    max_position_embeddings=512,
    dtype="float32",
)

TINY_MOE = dataclasses.replace(
    TINY,
    name="tiny-moe",
    num_experts=8,
    num_experts_per_tok=2,
    moe_intermediate_size=32,
)

TINY_QWEN2 = dataclasses.replace(
    TINY, name="tiny-qwen2", qk_norm=False, attn_bias=True
)

TINY_LLAMA = dataclasses.replace(
    TINY, name="tiny-llama", qk_norm=False, attn_bias=False,
    rope_scaling="llama3", rope_scaling_factor=8.0,
    rope_original_max_position=128, rope_theta=500_000.0,
)

TINY_GPT_OSS = dataclasses.replace(
    TINY, name="tiny-gptoss", qk_norm=False, attn_bias=True, o_bias=True,
    tie_word_embeddings=False,
    attn_sinks=True, sliding_window=8, rope_theta=150_000.0, rms_norm_eps=1e-5,
    rope_scaling="yarn", rope_scaling_factor=32.0,
    rope_original_max_position=64, rope_truncate=False,
    num_experts=8, num_experts_per_tok=2, moe_intermediate_size=32,
    moe_router_mode="topk_softmax", router_bias=True, moe_bias=True,
    swiglu_limit=7.0,
)

TINY_GEMMA2 = dataclasses.replace(
    TINY, name="tiny-gemma2", qk_norm=False, attn_bias=False,
    rope_theta=10_000.0,
    sandwich_norm=True, rms_norm_plus_one=True, hidden_act="gelu_tanh",
    scale_embedding=True, attn_logit_softcap=50.0, final_logit_softcap=30.0,
    query_pre_attn_scalar=32.0, sliding_window=8,
)

PRESETS = {
    c.name: c
    for c in [
        QWEN3_0_6B,
        QWEN3_1_7B,
        QWEN3_4B,
        QWEN3_8B,
        QWEN3_14B,
        QWEN3_32B,
        QWEN2_0_5B,
        QWEN2_1_5B,
        QWEN2_7B,
        LLAMA32_1B,
        LLAMA31_8B,
        GEMMA2_2B,
        GEMMA2_9B,
        GEMMA2_27B,
        MIXTRAL_8X7B,
        GPT_OSS_20B,
        GPT_OSS_120B,
        QWEN3_MOE_30B_A3B,
        BENCH_PIPE,
        TINY,
        TINY_MOE,
        TINY_QWEN2,
        TINY_LLAMA,
        TINY_GEMMA2,
        TINY_GPT_OSS,
    ]
}

# HF hub repos for weight loading (inferd_tpu.models.loader).
HF_REPOS = {
    "qwen3-0.6b": "Qwen/Qwen3-0.6B",
    "qwen3-1.7b": "Qwen/Qwen3-1.7B",
    "qwen3-4b": "Qwen/Qwen3-4B",
    "qwen3-8b": "Qwen/Qwen3-8B",
    "qwen3-14b": "Qwen/Qwen3-14B",
    "qwen3-32b": "Qwen/Qwen3-32B",
    "qwen3-moe-30b-a3b": "Qwen/Qwen3-30B-A3B",
    "qwen2-0.5b": "Qwen/Qwen2-0.5B",
    "qwen2-1.5b": "Qwen/Qwen2-1.5B",
    "qwen2-7b": "Qwen/Qwen2-7B",
    "llama3.2-1b": "meta-llama/Llama-3.2-1B",
    "llama3.1-8b": "meta-llama/Llama-3.1-8B",
    "gemma2-2b": "google/gemma-2-2b",
    "gemma2-9b": "google/gemma-2-9b",
    "gemma2-27b": "google/gemma-2-27b",
    "mixtral-8x7b": "mistralai/Mixtral-8x7B-v0.1",
    "gpt-oss-20b": "openai/gpt-oss-20b",
    "gpt-oss-120b": "openai/gpt-oss-120b",
}


def get_config(name: str) -> ModelConfig:
    try:
        return PRESETS[name.lower()]
    except KeyError:
        raise KeyError(f"unknown model preset {name!r}; have {sorted(PRESETS)}")
