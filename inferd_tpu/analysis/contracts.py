"""Contract-drift lint: code's observability vocabulary vs the docs.

The journal event types, /metrics series names, and gossip field keys ARE
the node's wire contract with dashboards, SLO rules, and mixed-version
peers (docs/OBSERVABILITY.md documents them; obs.export validates the
exposition format; test_dht pins gossip compat). Nothing previously
checked that the three vocabularies and the docs stay in sync — a new
event type silently ships undocumented, a renamed metric leaves a dead
doc row. This lint extracts every emitted vocabulary entry from the AST
(no imports, no backend) and diffs it against the documented tables:

  C001  event type emitted in code but absent from the event table
  C002  event table row whose type is never emitted
  C003  gossip key announced but absent from the gossip vocabulary table
  C004  documented gossip key never announced
  C005  /metrics series emitted but not documented
  C006  documented /metrics series never emitted

Deliberate gaps live in a committed allowlist (analysis-contracts.json):
`{"version": 1, "allow": [{"code", "name", "reason"}]}` — fnmatch
wildcards allowed in `name`, and an entry without a non-empty reason does
not suppress (same contract as the jaxlint baseline). Names extracted
from non-constant expressions (f-strings, variables) can't be diffed
statically; they are counted and reported, never silently dropped.

Run: `python -m inferd_tpu.analysis contracts [--root DIR] [--json]`.
"""

from __future__ import annotations

import ast
import fnmatch
import json
import os
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

# ----------------------------------------------------------- extraction

#: emit-shaped calls -> index of the event-type argument. `_emit` covers
#: wrappers like control/balance.py's journal helper; `emit_safely(hook,
#: etype, ...)` takes the hook first.
_EMIT_FUNCS = {"emit": 0, "_emit": 0, "emit_safely": 1}
#: metric-registry calls -> series kind (decides the exposition suffix)
_METRIC_FUNCS = {
    "inc": "counter",
    "set_counter": "counter",
    "set_gauge": "gauge",
    "observe": "histogram",
}


@dataclass
class CodeVocab:
    """Vocabulary extracted from the code tree. Maps name -> first
    (path, line) sighting; `dynamic_*` counts sites whose name is not a
    string literal (reported, not diffed)."""

    events: Dict[str, Tuple[str, int]] = field(default_factory=dict)
    metrics: Dict[str, Tuple[str, str, int]] = field(default_factory=dict)
    gossip: Dict[str, Tuple[str, int]] = field(default_factory=dict)
    dynamic_events: int = 0
    dynamic_metrics: int = 0
    dynamic_gossip: int = 0


def _const_str(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _attr_leaf(call: ast.Call) -> Optional[str]:
    if isinstance(call.func, ast.Attribute):
        return call.func.attr
    if isinstance(call.func, ast.Name):
        return call.func.id
    return None


def _dotted(node: ast.AST) -> Optional[str]:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


class _GossipResolver:
    """Resolve the key set of the dict argument to `self.dht.announce`.

    The announce payload is built from literal keys, inline conditional
    spreads (`**({...} if x else {})`), and `**var` spreads whose vars
    come from helper methods (`self._windowed_gossip()`,
    `self._health_state()["gossip"]`). This follows those shapes — dict
    literals, IfExp branches, Name assignments, helper-return dicts,
    `d[k] = v` stores, `d.update({...})` — to a bounded depth. Anything
    it can't prove is counted as dynamic, not guessed."""

    def __init__(self, tree: ast.AST):
        self.methods: Dict[str, ast.AST] = {}
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.methods.setdefault(node.name, node)
        self.dynamic = 0

    def dict_keys(self, expr: ast.AST, fn: ast.AST, depth: int = 0) -> Set[str]:
        if depth > 5 or expr is None:
            return set()
        out: Set[str] = set()
        if isinstance(expr, ast.Dict):
            for k, v in zip(expr.keys, expr.values):
                if k is None:  # ** spread
                    out |= self.dict_keys(v, fn, depth + 1)
                else:
                    s = _const_str(k)
                    if s is not None:
                        out.add(s)
                    else:
                        self.dynamic += 1
            return out
        if isinstance(expr, ast.IfExp):
            return self.dict_keys(expr.body, fn, depth + 1) | self.dict_keys(
                expr.orelse, fn, depth + 1
            )
        if isinstance(expr, ast.Call):
            leaf = _attr_leaf(expr)
            if leaf in self.methods:
                return self.return_keys(self.methods[leaf], depth + 1)
            self.dynamic += 1
            return out
        if isinstance(expr, ast.Subscript):
            # e.g. self._health_state()["gossip"]
            key = _const_str(expr.slice)
            base = expr.value
            if key is not None and isinstance(base, ast.Call):
                leaf = _attr_leaf(base)
                if leaf in self.methods:
                    return self.subkey_keys(self.methods[leaf], key, depth + 1)
            self.dynamic += 1
            return out
        if isinstance(expr, ast.Name):
            return self.var_keys(fn, expr.id, depth + 1)
        self.dynamic += 1
        return out

    def var_keys(self, fn: ast.AST, var: str, depth: int) -> Set[str]:
        if depth > 5:
            return set()
        out: Set[str] = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name) and tgt.id == var:
                        out |= self.dict_keys(node.value, fn, depth + 1)
                    elif (
                        isinstance(tgt, ast.Subscript)
                        and isinstance(tgt.value, ast.Name)
                        and tgt.value.id == var
                    ):
                        s = _const_str(tgt.slice)
                        if s is not None:
                            out.add(s)
                        else:
                            self.dynamic += 1
            elif (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "update"
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id == var
                and node.args
            ):
                out |= self.dict_keys(node.args[0], fn, depth + 1)
        return out

    def return_keys(self, meth: ast.AST, depth: int) -> Set[str]:
        if depth > 5:
            return set()
        out: Set[str] = set()
        for node in ast.walk(meth):
            if isinstance(node, ast.Return) and node.value is not None:
                out |= self.dict_keys(node.value, meth, depth + 1)
        return out

    def subkey_keys(self, meth: ast.AST, key: str, depth: int) -> Set[str]:
        """Keys of the dict that method `meth` stores under `key` in any
        dict literal (e.g. _health_state's `{"gossip": gossip}`)."""
        if depth > 5:
            return set()
        out: Set[str] = set()
        for node in ast.walk(meth):
            if isinstance(node, ast.Dict):
                for k, v in zip(node.keys, node.values):
                    if _const_str(k) == key:
                        out |= self.dict_keys(v, meth, depth + 1)
            elif (
                isinstance(node, ast.Assign)
                and any(
                    isinstance(t, ast.Subscript)
                    and _const_str(t.slice) == key
                    for t in node.targets
                )
            ):
                out |= self.dict_keys(node.value, meth, depth + 1)
        return out


def extract_code_vocab(code_root: str) -> CodeVocab:
    """Walk every .py under `code_root` and pull the three vocabularies
    out of the AST (no imports, no JAX)."""
    vocab = CodeVocab()
    for dirpath, dirnames, filenames in os.walk(code_root):
        dirnames[:] = [
            d for d in dirnames if not d.startswith(".") and d != "__pycache__"
        ]
        for name in sorted(filenames):
            if not name.endswith(".py"):
                continue
            fpath = os.path.join(dirpath, name)
            try:
                with open(fpath, "r", encoding="utf-8") as fh:
                    tree = ast.parse(fh.read())
            except (OSError, UnicodeDecodeError, SyntaxError):
                continue
            rel = os.path.relpath(fpath, code_root).replace(os.sep, "/")
            _extract_file(tree, rel, vocab)
    return vocab


def _extract_file(tree: ast.AST, rel: str, vocab: CodeVocab) -> None:
    resolver: Optional[_GossipResolver] = None
    fn_of: Dict[ast.AST, ast.AST] = {}

    def map_fns(node: ast.AST, fn: Optional[ast.AST]) -> None:
        for child in ast.iter_child_nodes(node):
            cur = (
                child
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef))
                else fn
            )
            if cur is not None:
                fn_of[child] = cur
            map_fns(child, cur)

    map_fns(tree, None)

    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        leaf = _attr_leaf(node)
        if leaf in _EMIT_FUNCS:
            idx = _EMIT_FUNCS[leaf]
            if len(node.args) > idx:
                s = _const_str(node.args[idx])
                if s is not None:
                    vocab.events.setdefault(s, (rel, node.lineno))
                else:
                    vocab.dynamic_events += 1
        elif leaf in _METRIC_FUNCS and node.args:
            s = _const_str(node.args[0])
            if s is not None:
                vocab.metrics.setdefault(
                    s, (_METRIC_FUNCS[leaf], rel, node.lineno)
                )
            else:
                vocab.dynamic_metrics += 1
        if _dotted(node.func) == "self.dht.announce":
            if resolver is None:
                resolver = _GossipResolver(tree)
            fn = fn_of.get(node, tree)
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                if isinstance(arg, ast.Dict):
                    for key in resolver.dict_keys(arg, fn):
                        vocab.gossip.setdefault(key, (rel, node.lineno))
            vocab.dynamic_gossip += resolver.dynamic
            resolver.dynamic = 0


# ---------------------------------------------------------- doc parsing

_BACKTICK_RE = re.compile(r"`([^`]+)`")


@dataclass
class DocVocab:
    events: Dict[str, int] = field(default_factory=dict)  # name -> line
    gossip: Dict[str, int] = field(default_factory=dict)
    tokens: Set[str] = field(default_factory=set)  # every backticked token


def _table_rows(lines: List[str], header_cell: str) -> List[Tuple[int, str]]:
    """(lineno, first-cell text) of every row of markdown tables whose
    header row contains `header_cell` as a cell."""
    out: List[Tuple[int, str]] = []
    i = 0
    while i < len(lines):
        line = lines[i].strip()
        if line.startswith("|"):
            cells = [c.strip().lower() for c in line.strip("|").split("|")]
            if header_cell in cells:
                i += 2  # skip header + separator row
                while i < len(lines) and lines[i].strip().startswith("|"):
                    first = lines[i].strip().strip("|").split("|")[0]
                    out.append((i + 1, first))
                    i += 1
                continue
        i += 1
    return out


def _expand_slashes(token: str) -> List[str]:
    """`hedge.fired/won/cancelled` -> the three dotted names. A token
    without a slash (or without a dotted first part) passes through."""
    if "/" not in token:
        return [token]
    parts = [p.strip() for p in token.split("/") if p.strip()]
    if not parts or "." not in parts[0]:
        return [token]
    prefix = parts[0].rsplit(".", 1)[0] + "."
    return [p if "." in p else prefix + p for p in parts]


def parse_doc_vocab(doc_path: str) -> DocVocab:
    with open(doc_path, "r", encoding="utf-8") as fh:
        text = fh.read()
    lines = text.splitlines()
    # fenced code blocks carry EXAMPLES (curl output, exposition
    # samples), not vocabulary declarations — and their ``` markers
    # desync the inline-backtick pairing for the whole rest of the file
    text = re.sub(r"```.*?```", "", text, flags=re.S)
    vocab = DocVocab()
    for lineno, cell in _table_rows(lines, "event"):
        for tok in _BACKTICK_RE.findall(cell):
            for name in _expand_slashes("".join(tok.split())):
                vocab.events.setdefault(name, lineno)
    for lineno, cell in _table_rows(lines, "key"):
        for tok in _BACKTICK_RE.findall(cell):
            vocab.gossip.setdefault("".join(tok.split()), lineno)
    for tok in _BACKTICK_RE.findall(text):
        clean = "".join(tok.split())
        for name in _expand_slashes(clean):
            vocab.tokens.add(name)
    return vocab


# ---------------------------------------------------------- diff + gate


@dataclass
class ContractFinding:
    code: str  # "C001"
    name: str  # the drifted vocabulary entry
    where: str  # "path:line" in code or doc
    message: str

    def render(self) -> str:
        return f"{self.where}: {self.code} {self.message}"


_MESSAGES = {
    "C001": "event `{name}` is emitted but missing from the event table "
    "in docs/OBSERVABILITY.md",
    "C002": "documented event `{name}` is never emitted — dead doc row "
    "(or the emit went dynamic; allowlist it with a reason)",
    "C003": "gossip key `{name}` is announced but missing from the "
    "gossip vocabulary table in docs/OBSERVABILITY.md",
    "C004": "documented gossip key `{name}` is never announced — dead "
    "doc row",
    "C005": "/metrics series `{name}` is emitted but not documented in "
    "docs/OBSERVABILITY.md",
    "C006": "documented /metrics series `{name}` is never emitted — "
    "dead doc entry",
}


def _sanitize(name: str) -> str:
    return re.sub(r"[^a-zA-Z0-9_]", "_", name)


def _full_names(name: str, kind: str) -> List[str]:
    base = "inferd_" + _sanitize(name)
    return [base + "_total"] if kind == "counter" else [base]


def _doc_has_metric(name: str, kind: str, tokens: Set[str]) -> bool:
    if name in tokens:
        return True
    for full in _full_names(name, kind):
        for tok in tokens:
            if not tok.startswith(("inferd_", "_")) and "*" not in tok:
                continue
            pat = re.sub(r"<[^>]*>", "*", tok)
            if tok.startswith("inferd_") or "*" in pat:
                if full == tok or (
                    "*" in pat and fnmatch.fnmatchcase(full, pat)
                ):
                    return True
            if tok.startswith("_") and full.endswith(tok):
                # continuation shorthand: `inferd_hbm_bytes_in_use` /
                # `_bytes_limit` — valid if a sibling token shares the head
                head = full[: -len(tok)]
                if any(
                    t.startswith(head) and t != tok
                    for t in tokens
                    if t.startswith("inferd_")
                ):
                    return True
    return False


def _emitted_matches_token(tok: str, fulls: Set[str]) -> bool:
    pat = re.sub(r"<[^>]*>", "*", tok)
    if "*" in pat:
        return any(fnmatch.fnmatchcase(f, pat) for f in fulls)
    if tok.startswith("_"):
        return any(f.endswith(tok) for f in fulls)
    return tok in fulls


class Allowlist:
    """analysis-contracts.json: deliberate contract gaps, reason required."""

    def __init__(self, entries: List[dict]):
        self.entries = entries
        self.hits: Set[int] = set()

    @classmethod
    def load(cls, path: str) -> "Allowlist":
        if not os.path.isfile(path):
            return cls([])
        with open(path, "r", encoding="utf-8") as fh:
            data = json.load(fh)
        return cls(list(data.get("allow", [])))

    def covers(self, code: str, name: str) -> bool:
        for i, e in enumerate(self.entries):
            if e.get("code") != code:
                continue
            if not str(e.get("reason", "")).strip():
                continue  # reasonless entries never suppress
            if fnmatch.fnmatchcase(name, str(e.get("name", ""))):
                self.hits.add(i)
                return True
        return False

    def unused(self) -> List[dict]:
        return [
            e for i, e in enumerate(self.entries) if i not in self.hits
        ]


def run_contracts(
    root: str,
    code_root: Optional[str] = None,
    doc_path: Optional[str] = None,
    allow_path: Optional[str] = None,
) -> Tuple[List[ContractFinding], CodeVocab, Allowlist]:
    """-> (unallowlisted findings, extracted code vocab, allowlist)."""
    code_root = code_root or os.path.join(root, "inferd_tpu")
    doc_path = doc_path or os.path.join(root, "docs", "OBSERVABILITY.md")
    allow_path = allow_path or os.path.join(root, "analysis-contracts.json")
    if not os.path.isdir(code_root):
        raise FileNotFoundError(f"contracts: no code root at {code_root!r}")
    if not os.path.isfile(doc_path):
        raise FileNotFoundError(f"contracts: no doc at {doc_path!r}")
    code = extract_code_vocab(code_root)
    doc = parse_doc_vocab(doc_path)
    allow = Allowlist.load(allow_path)
    doc_rel = os.path.relpath(doc_path, root).replace(os.sep, "/")

    findings: List[ContractFinding] = []

    def add(code_id: str, name: str, where: str) -> None:
        if allow.covers(code_id, name):
            return
        findings.append(
            ContractFinding(
                code=code_id,
                name=name,
                where=where,
                message=_MESSAGES[code_id].format(name=name),
            )
        )

    for name, (path, line) in sorted(code.events.items()):
        if name not in doc.events:
            add("C001", name, f"{path}:{line}")
    for name, line in sorted(doc.events.items()):
        if name not in code.events:
            add("C002", name, f"{doc_rel}:{line}")
    for name, (path, line) in sorted(code.gossip.items()):
        if name not in doc.gossip:
            add("C003", name, f"{path}:{line}")
    for name, line in sorted(doc.gossip.items()):
        if name not in code.gossip:
            add("C004", name, f"{doc_rel}:{line}")

    for name, (kind, path, line) in sorted(code.metrics.items()):
        if not _doc_has_metric(name, kind, doc.tokens):
            add("C005", name, f"{path}:{line}")
    # C006 runs only over exposition-shaped tokens (inferd_* families):
    # prose backticks name plenty of non-metric identifiers, and failing
    # on those would make the doc unwritable
    fulls: Set[str] = set()
    for name, (kind, _p, _l) in code.metrics.items():
        fulls.update(_full_names(name, kind))
        fulls.add("inferd_" + _sanitize(name))  # kind-agnostic fallback
    for tok in sorted(doc.tokens):
        if not tok.startswith("inferd_"):
            continue
        if "/" in tok or tok == "inferd_tpu" or tok.startswith("inferd_tpu."):
            continue  # a path or module reference, not an exposition name
        if not _emitted_matches_token(tok, fulls):
            add("C006", tok, doc_rel)
    return findings, code, allow
