"""jaxlint — JAX-aware static analysis + runtime sanitizers for inferd_tpu.

Static side (`python -m inferd_tpu.analysis check <paths>`): six AST rules
that catch the bug classes the round-5 ADVICE found by hand — retrace
hazards (J001), buffer-donation misuse (J002), host-device sync inside
decode loops (J003), impurity under jit/scan (J004), blocking calls inside
async code (J005), and fragile `jax.default_backend()` string probes
(J006). Every finding carries a rule ID and a fix hint; known-deliberate
sites live in `analysis-baseline.json` with a reason string, or behind an
inline `# jaxlint: disable=J0xx -- reason` comment. See docs/ANALYSIS.md.

Runtime side: `retrace_guard()` (fail a test when a registered jitted fn
re-traces in a hot loop) and `nan_guard()` (wrap a step fn with post-hoc
NaN/Inf checks, no jax.debug insertion into the graph).
"""

from inferd_tpu.analysis.baseline import Baseline
from inferd_tpu.analysis.engine import (
    Finding,
    check_paths,
    check_source,
    iter_py_files,
)
from inferd_tpu.analysis.rules import ALL_RULES, rule_catalog
from inferd_tpu.analysis.sanitizers import (
    NanError,
    RetraceError,
    RetraceGuard,
    nan_guard,
    retrace_guard,
)

__all__ = [
    "ALL_RULES",
    "Baseline",
    "Finding",
    "NanError",
    "RetraceError",
    "RetraceGuard",
    "check_paths",
    "check_source",
    "iter_py_files",
    "nan_guard",
    "retrace_guard",
    "rule_catalog",
]
