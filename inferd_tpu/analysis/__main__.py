"""jaxlint CLI.

    python -m inferd_tpu.analysis check inferd_tpu/ tests/ \
        [--baseline analysis-baseline.json] [--rules J003,J006] [--json] \
        [--write-baseline] [--jobs N]
    python -m inferd_tpu.analysis contracts [--root DIR] [--json]
    python -m inferd_tpu.analysis rules

`check` exits 0 iff every finding is covered by an inline
`# jaxlint: disable=J0xx -- reason` directive or a baseline entry with a
non-empty reason; anything else is a build failure. `contracts` diffs the
emitted observability vocabulary (journal events, /metrics series, gossip
keys) against docs/OBSERVABILITY.md, gated by analysis-contracts.json.
Pure stdlib — safe to run in CPU-only CI without initializing any JAX
backend.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional

from inferd_tpu.analysis.baseline import DEFAULT_BASELINE, Baseline
from inferd_tpu.analysis.engine import check_paths, iter_py_files, relpath
from inferd_tpu.analysis.rules import ALL_RULES, rule_catalog


def _select_rules(spec: Optional[str]):
    if not spec:
        return None
    wanted = {s.strip().upper() for s in spec.split(",") if s.strip()}
    unknown = wanted - {r.id for r in ALL_RULES}
    if unknown:
        raise SystemExit(f"unknown rule ids: {sorted(unknown)}")
    return [r for r in ALL_RULES if r.id in wanted]


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(prog="python -m inferd_tpu.analysis")
    sub = ap.add_subparsers(dest="cmd", required=True)

    chk = sub.add_parser("check", help="scan paths, gate on findings")
    chk.add_argument("paths", nargs="+")
    chk.add_argument(
        "--baseline",
        default=None,
        help=f"suppression file (default: nearest {DEFAULT_BASELINE} "
        "walking up from cwd; 'none' disables)",
    )
    chk.add_argument("--rules", default=None, help="comma-separated rule ids")
    chk.add_argument("--json", action="store_true", help="machine output")
    chk.add_argument(
        "--write-baseline",
        metavar="FILE",
        default=None,
        help="write current findings to FILE with empty reasons (each must "
        "be hand-justified before it suppresses) and exit 0",
    )
    chk.add_argument(
        "--warn-unused-baseline",
        action="store_true",
        help="also fail when baseline entries no longer match anything",
    )
    chk.add_argument(
        "--jobs",
        type=int,
        default=1,
        help="parallelize the per-file scan over N processes (0 = one "
        "per CPU); project-wide finalize always runs in this process",
    )

    con = sub.add_parser(
        "contracts",
        help="diff emitted events/metrics/gossip vs docs/OBSERVABILITY.md",
    )
    con.add_argument(
        "--root",
        default=".",
        help="repo root (holds inferd_tpu/, docs/OBSERVABILITY.md, "
        "analysis-contracts.json); default cwd",
    )
    con.add_argument("--json", action="store_true", help="machine output")

    sub.add_parser("rules", help="print the rule catalog")

    args = ap.parse_args(argv)

    if args.cmd == "rules":
        for rid, title, hint in rule_catalog():
            print(f"{rid}  {title}\n      fix: {hint}")
        return 0

    if args.cmd == "contracts":
        return _contracts_main(args)

    # resolve the baseline FIRST: finding paths (and so fingerprints) are
    # made relative to the baseline file's directory, so the gate matches
    # no matter which directory it is invoked from
    if args.write_baseline:
        baseline = Baseline(path=args.write_baseline)
    elif args.baseline == "none":
        baseline = Baseline()
    elif args.baseline:
        baseline = Baseline.load(args.baseline)
    else:
        baseline = Baseline.load_default()
    rel_to = (
        os.path.dirname(os.path.abspath(baseline.path)) or None
        if baseline.path
        else None
    )

    jobs = args.jobs if args.jobs > 0 else (os.cpu_count() or 1)
    try:
        findings = check_paths(
            args.paths,
            rules=_select_rules(args.rules),
            rel_to=rel_to,
            jobs=jobs,
        )
    except FileNotFoundError as e:
        print(str(e), file=sys.stderr)
        return 2

    if args.write_baseline:
        # carry hand-written reasons over from the previous baseline (the
        # --baseline file if given, else the write target itself), and
        # keep previous entries that were OUT OF SCOPE this run (rules
        # not selected, files not scanned) verbatim: a partial refresh
        # must never destroy the justifications it maintains
        reasons = {}
        keep = []
        prev_path = args.baseline if args.baseline not in (None, "none") \
            else args.write_baseline
        selected_ids = {
            r.id for r in (_select_rules(args.rules) or ALL_RULES)
        }
        scanned = {relpath(f, rel_to) for f in iter_py_files(args.paths)}
        if os.path.isfile(prev_path):
            # re-key the old entries into the NEW file's path frame: both
            # files anchor fingerprints to their own directory
            prev = Baseline.load(prev_path)
            prev_dir = os.path.dirname(os.path.abspath(prev_path))
            new_dir = os.path.dirname(
                os.path.abspath(args.write_baseline)
            )
            for key, reason in prev.entries.items():
                rid, file, ctx, snip = key
                new_file = relpath(os.path.join(prev_dir, file), new_dir)
                if rid in selected_ids and new_file in scanned:
                    reasons[(rid, new_file, ctx, snip)] = reason
                else:
                    keep.append(
                        {
                            "rule": rid,
                            "file": new_file,
                            "context": ctx,
                            "snippet": snip,
                            "count": prev.counts.get(key, 1),
                            "reason": reason,
                        }
                    )
        Baseline.write(
            args.write_baseline, findings, reasons=reasons,
            extra_entries=keep,
        )
        kept = sum(
            1 for f in findings if reasons.get(f.fingerprint(), "").strip()
        )
        print(
            f"jaxlint: wrote {len(findings)} finding(s) to "
            f"{args.write_baseline} ({kept} with carried-over reasons, "
            f"{len(keep)} out-of-scope entr"
            f"{'y' if len(keep) == 1 else 'ies'} kept); fill in every "
            "empty `reason` before it suppresses anything"
        )
        return 0

    remaining = baseline.filter(findings)

    # entries outside this run's scope (non-selected rules, files not in
    # the scanned paths) never got a chance to match — they are not stale
    selected = _select_rules(args.rules)
    selected_ids = {r.id for r in (selected or ALL_RULES)}
    scanned = {
        relpath(f, rel_to) for f in iter_py_files(args.paths)
    }
    unused = [
        k
        for k in baseline.unused()
        if k[0] in selected_ids and k[1] in scanned
    ]
    if args.json:
        print(
            json.dumps(
                {
                    "findings": [f.__dict__ for f in remaining],
                    "baselined": len(findings) - len(remaining),
                    "unused_baseline_entries": [list(k) for k in unused],
                }
            )
        )
    else:
        for f in remaining:
            print(f.render())
        if unused:
            print(
                f"jaxlint: {len(unused)} stale baseline entr"
                f"{'y' if len(unused) == 1 else 'ies'} no longer match "
                "anything (code fixed? prune them):",
                file=sys.stderr,
            )
            for k in unused:
                print(f"  {k[0]} {k[1]} [{k[2]}] {k[3]!r}", file=sys.stderr)
        summary = (
            f"jaxlint: {len(remaining)} finding(s), "
            f"{len(findings) - len(remaining)} baselined"
        )
        print(summary, file=sys.stderr)

    if remaining:
        return 1
    if unused and args.warn_unused_baseline:
        return 1
    return 0


def _contracts_main(args) -> int:
    from inferd_tpu.analysis.contracts import run_contracts

    try:
        findings, code, allow = run_contracts(args.root)
    except FileNotFoundError as e:
        print(str(e), file=sys.stderr)
        return 2
    dynamic = {
        "events": code.dynamic_events,
        "metrics": code.dynamic_metrics,
        "gossip": code.dynamic_gossip,
    }
    unused = allow.unused()
    if args.json:
        print(
            json.dumps(
                {
                    "findings": [f.__dict__ for f in findings],
                    "dynamic_skipped": dynamic,
                    "unused_allowlist_entries": unused,
                    "counts": {
                        "events": len(code.events),
                        "metrics": len(code.metrics),
                        "gossip": len(code.gossip),
                    },
                }
            )
        )
    else:
        for f in findings:
            print(f.render())
        if unused:
            print(
                f"contracts: {len(unused)} stale allowlist entr"
                f"{'y' if len(unused) == 1 else 'ies'} no longer match "
                "anything (prune them):",
                file=sys.stderr,
            )
            for e in unused:
                print(
                    f"  {e.get('code')} {e.get('name')!r}: "
                    f"{e.get('reason', '')}",
                    file=sys.stderr,
                )
        print(
            f"contracts: {len(findings)} finding(s) over "
            f"{len(code.events)} events / {len(code.metrics)} metrics / "
            f"{len(code.gossip)} gossip keys "
            f"(dynamic sites skipped: {dynamic['events']} event, "
            f"{dynamic['metrics']} metric, {dynamic['gossip']} gossip)",
            file=sys.stderr,
        )
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
